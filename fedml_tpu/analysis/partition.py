"""PartitionSpec rule table + coverage rule.

The `match_partition_rules` pattern (regex over a 'path/to/param' string ->
PartitionSpec, scalars auto-replicated, first match wins, unmatched leaf is
an error) is how model-parallel shardings stay total as models grow: a new
layer whose params match no rule fails the lint instead of silently
defaulting to replicated on a TPU pod.

The default table below covers every flax leaf name the zoo produces
(kernel / bias / scale / mean / var / embedding, plus opt-state counts).
It is deliberately coarse — the repo's data-parallel engine never consumes
these specs today; the table is the *coverage contract* that a future
tensor-parallel pass starts from (ROADMAP: multi-chip scaling).
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as PS

from fedml_tpu.analysis.core import Finding

# (path regex, spec). Specs may be shorter than the leaf rank — trailing
# dims replicate. First match wins.
DEFAULT_PARTITION_RULES: List[Tuple[str, PS]] = [
    (r"embedding$", PS("model", None)),      # embed tables: shard the vocab dim
    (r"kernel$", PS(None, "model")),         # dense/conv: shard the out-features dim
    (r"(bias|scale)$", PS()),                # norms + biases replicate
    (r"(mean|var|count)$", PS()),            # batch_stats / opt-state scalars-ish
]


def _flat_paths(tree):
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        keys = []
        for p in path:
            if hasattr(p, "key"):
                keys.append(str(p.key))
            elif hasattr(p, "idx"):
                keys.append(str(p.idx))
            else:
                keys.append(str(p))
        out.append(("/".join(keys), leaf))
    return out


def match_partition_rules(rules: Sequence[Tuple[str, PS]], tree):
    """Map every leaf to a PartitionSpec. Scalars get PS(); a leaf matching
    no rule raises ValueError naming its path (the lint-rule form of the
    same check returns Findings instead — see check_partition_coverage)."""

    def match_one(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return PS()
        for pattern, spec in rules:
            if re.search(pattern, path):
                return spec
        raise ValueError(f"partition rule not found for param: {path}")

    flat = _flat_paths(tree)
    specs = {path: match_one(path, leaf) for path, leaf in flat}
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(tree),
        [specs[path] for path, _ in flat])


def check_partition_coverage(tree, target: str,
                             rules: Optional[Sequence[Tuple[str, PS]]] = None,
                             ) -> List[Finding]:
    """Lint form of match_partition_rules: one Finding per unmatched
    non-scalar leaf, plus a rank check (a spec longer than the leaf's rank
    could never be applied)."""
    rules = DEFAULT_PARTITION_RULES if rules is None else rules
    out: List[Finding] = []
    for path, leaf in _flat_paths(tree):
        if getattr(leaf, "ndim", 0) == 0:
            continue
        for pattern, spec in rules:
            if re.search(pattern, path):
                if len(spec) > leaf.ndim:
                    out.append(Finding(
                        "partition-coverage", target,
                        f"{path}: rule {pattern!r} spec {spec} is longer "
                        f"than the leaf's rank {leaf.ndim}"))
                break
        else:
            out.append(Finding(
                "partition-coverage", target,
                f"{path} (shape {tuple(leaf.shape)}) matches no "
                f"PartitionSpec rule — add one to DEFAULT_PARTITION_RULES"))
    return out


def model_variable_shapes(module, shape, in_dtype=jnp.float32):
    """abstract variables tree for a flax module (eval_shape — no FLOPs)."""
    rng = jax.random.PRNGKey(0)
    return jax.eval_shape(
        lambda: module.init({"params": rng, "dropout": rng},
                            jnp.zeros(shape, in_dtype), train=False))
