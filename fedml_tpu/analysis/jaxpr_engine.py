"""jaxpr-level lint rules.

All rules operate on a traced `Jaxpr`/`ClosedJaxpr` (or, for donation and
retrace, on the jitted callable itself) and return `core.Finding` lists —
nothing here raises on a violation; callers (CLI, tests) decide severity.

The recursive walker descends into scan/while/cond/pjit/custom_vmap
sub-jaxprs but NOT into pallas kernels: flash attention accumulates in f32
*inside* the kernel by design (bf16 in/out, f32 accumulate is the
numerically-correct flash formulation), and Mosaic-facing compare casts in
ops/fused_sgd.py are likewise deliberate. The dtype knob governs what the
kernel is *fed*, which the surrounding dots cover.
"""

from __future__ import annotations

import re
import warnings
from collections import Counter
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.extend import core as jex_core

from fedml_tpu.analysis.core import Finding

MATMUL_PRIMS = ("dot_general", "conv_general_dilated")
# Host-callback primitives: any of these inside a round body forces a
# device->host round-trip per invocation — the dispatch-bound failure mode
# the chunked runner exists to avoid.
CALLBACK_PRIMS = ("pure_callback", "debug_callback", "io_callback")

_ALIASING_RE = re.compile(r"tf\.aliasing_output")


def _subjaxprs(eqn) -> Iterable[jex_core.Jaxpr]:
    for v in eqn.params.values():
        for sub in jax.tree.leaves(v, is_leaf=lambda l: isinstance(
                l, (jex_core.Jaxpr, jex_core.ClosedJaxpr))):
            if isinstance(sub, jex_core.ClosedJaxpr):
                yield sub.jaxpr
            elif isinstance(sub, jex_core.Jaxpr):
                yield sub


def _as_jaxpr(jaxpr) -> jex_core.Jaxpr:
    return jaxpr.jaxpr if isinstance(jaxpr, jex_core.ClosedJaxpr) else jaxpr


def walk_eqns(jaxpr):
    """All eqns, recursing into scan/cond/pjit/... sub-jaxprs — but NOT into
    pallas kernels (see module docstring)."""
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        if "pallas" in eqn.primitive.name:
            continue
        for sub in _subjaxprs(eqn):
            yield from walk_eqns(sub)


def _walk_levels(jaxpr):
    """Each (sub-)jaxpr as its own level — dead-cast needs per-level
    producer/use maps, since vars don't cross jaxpr boundaries."""
    jaxpr = _as_jaxpr(jaxpr)
    yield jaxpr
    for eqn in jaxpr.eqns:
        if "pallas" in eqn.primitive.name:
            continue
        for sub in _subjaxprs(eqn):
            yield from _walk_levels(sub)


def check_dtype_policy(jaxpr, target: str,
                       policy=jnp.bfloat16) -> List[Finding]:
    """No floating matmul/conv may produce a dtype other than `policy`.
    Integer dots (e.g. turboaggregate's field arithmetic) pass."""
    out: List[Finding] = []
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name not in MATMUL_PRIMS:
            continue
        dt = eqn.outvars[0].aval.dtype
        if jnp.issubdtype(dt, jnp.floating) and dt != policy:
            out.append(Finding(
                "dtype-policy", target,
                f"{eqn.primitive.name} lowers to {dt} under "
                f"policy={jnp.dtype(policy).name} (MXU half-rate)"))
    return out


def check_host_sync(jaxpr, target: str) -> List[Finding]:
    out: List[Finding] = []
    for eqn in walk_eqns(jaxpr):
        for prim in CALLBACK_PRIMS:
            if prim in eqn.primitive.name:
                out.append(Finding(
                    "host-sync", target,
                    f"{eqn.primitive.name} inside the traced body forces a "
                    f"device->host round-trip per step"))
    return out


def check_dead_cast(jaxpr, target: str) -> List[Finding]:
    """A->B->A convert_element_type round-trips where the intermediate is
    used exactly once. These burn VPU cycles and memory bandwidth for a
    no-op (modulo bf16 rounding, which makes them a *numerics* hazard too:
    the value silently lost mantissa bits on the way through)."""
    out: List[Finding] = []
    for level in _walk_levels(jaxpr):
        producer = {}
        uses: Counter = Counter()
        for eqn in level.eqns:
            for ov in eqn.outvars:
                producer[ov] = eqn
            for iv in eqn.invars:
                if isinstance(iv, jex_core.Var):
                    uses[iv] += 1
        for ov in level.outvars:
            if isinstance(ov, jex_core.Var):
                uses[ov] += 1
        for eqn in level.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            iv = eqn.invars[0]
            if not isinstance(iv, jex_core.Var):
                continue
            prev = producer.get(iv)
            if prev is None or prev.primitive.name != "convert_element_type":
                continue
            a = prev.invars[0].aval.dtype
            b = prev.outvars[0].aval.dtype
            c = eqn.outvars[0].aval.dtype
            if a == c and a != b and uses[iv] == 1:
                out.append(Finding(
                    "dead-cast", target,
                    f"{a}->{b}->{a} convert round-trip (intermediate used "
                    f"once) — drop both casts or keep the narrow dtype"))
    return out


def check_unconstrained_intermediate(jaxpr, target: str,
                                     tensor_axis_size: int) -> List[Finding]:
    """A tensor-sharded client step (GSPMD, mesh tensor axis > 1) whose
    matmul/einsum intermediates carry NO sharding constraint. Without the
    `constrain` hooks the partitioner is free to (and in practice does)
    re-gather every activation replicated between layers — the program
    still runs, still converges, and silently loses the entire per-device
    peak-memory win the tensor axis exists for. One finding per program:
    the fix is model-level (thread `parallel.activations.constrain` through
    the intermediates), not per-dot."""
    if tensor_axis_size <= 1:
        # a 1-shard tensor axis is trivially replicated; constraints are
        # structurally off there by design (bit-identity at shards=1)
        return []
    n_dots = 0
    n_constraints = 0
    for eqn in walk_eqns(jaxpr):
        name = eqn.primitive.name
        if name in MATMUL_PRIMS:
            n_dots += 1
        elif name == "sharding_constraint":
            n_constraints += 1
    if n_dots and not n_constraints:
        return [Finding(
            "unconstrained-intermediate", target,
            f"{n_dots} matmul intermediate(s), 0 sharding constraints on a "
            f"{tensor_axis_size}-way tensor axis — GSPMD re-gathers the "
            f"activations replicated between layers; mark the model's "
            f"attention/MLP/logits intermediates with "
            f"parallel.activations.constrain (or build the step with its "
            f"activation rule table)")]
    return []


def lint_jaxpr(jaxpr, target: str, policy=None,
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the pure-jaxpr rules on one traced program. `policy=None` skips
    dtype-policy (f32-policy programs legitimately lower f32 dots)."""
    out: List[Finding] = []
    if policy is not None and (rules is None or "dtype-policy" in rules):
        out += check_dtype_policy(jaxpr, target, policy)
    if rules is None or "host-sync" in rules:
        out += check_host_sync(jaxpr, target)
    if rules is None or "dead-cast" in rules:
        out += check_dead_cast(jaxpr, target)
    return out


def check_donation(jitted, args, target: str,
                   argnums: Optional[Sequence[int]] = None,
                   expected_leaves: Optional[int] = None) -> List[Finding]:
    """Verify declared `donate_argnums` actually lower as donated buffers.

    Mechanism: a successfully-donated leaf shows up in the lowered MLIR as a
    `tf.aliasing_output = N` arg attribute; a declared-but-unusable donation
    (dtype/shape mismatch with every output) emits ZERO aliasing attrs plus
    a "Some donated buffers were not usable" UserWarning. Both signals are
    checked — the aliasing count is the ground truth, the warning gives the
    compiler's own reason when available. Pass the same `argnums` the jit
    declares (to size the expectation), or an explicit `expected_leaves`.
    """
    if expected_leaves is None:
        if argnums:
            expected_leaves = sum(
                len(jax.tree.leaves(args[i])) for i in argnums if i < len(args))
        else:
            expected_leaves = 1  # caller said "this should donate something"
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        txt = jitted.lower(*args).as_text()
    found = len(_ALIASING_RE.findall(txt))
    out: List[Finding] = []
    if found < expected_leaves:
        why = "; ".join(
            str(w.message) for w in caught
            if "donated" in str(w.message).lower()) or "no compiler diagnostic"
        out.append(Finding(
            "donation", target,
            f"declared donations lower as {found}/{expected_leaves} aliased "
            f"buffer(s) — the carry is being copied, not reused ({why})"))
    return out


def check_retrace(jitted, make_args, target: str, rounds: int = 3,
                  expected_signatures: int = 1) -> List[Finding]:
    """Drive `jitted` for `rounds` calls (args from `make_args(i)`) and
    assert one compile per shape signature. A cache that grows past
    `expected_signatures` means something non-hashable-stable (weak types,
    python scalars, shifting shapes) retraces every round — the
    compile-once contract every bench and the chunked runner assume."""
    for i in range(rounds):
        a = make_args(i)
        jax.block_until_ready(jitted(*a))
    size = jitted._cache_size()
    if size > expected_signatures:
        return [Finding(
            "retrace", target,
            f"{size} compiles across {rounds} same-signature rounds "
            f"(expected {expected_signatures}) — per-round retracing")]
    return []
