"""The HLO-layer lintable surface + the COMMS_BUDGET.json gate.

`PROGRAMS` names the repo's parallel round programs — the shard_map
rounds (sharded.py's round per aggregator, hierarchical.py's two-axis
round, the 2x4 tensor-sharded rounds of parallel/tensor.py with their
codec and federated-LoRA twins, the GSPMD `tensor.step` activation-sharded
client step and its replicated budget twin, gossip.py's ring mix, both
sequence.py attention variants) plus two single-chip extras (the engine
round and the chunked chunk_fn) whose budget entries pin their collective
count at ZERO: a collective ever appearing in the single-chip path is
itself the regression. `--fast` skips the extras.

Every program lowers on the forced 8-virtual-device host mesh
(``--xla_force_host_platform_device_count=8``, set by the CLI before
backend init; tests get it from conftest.py). `run_comms` feeds each
program through `hlo_engine.analyze_program` and then gates the measured
(collective_count, collective_bytes, peak_bytes) against the checked-in
COMMS_BUDGET.json — exact ceilings for count/bytes (they are deterministic
functions of the traced program), a 1.5x-headroom ceiling for peak memory
(an XLA scheduling artifact that wobbles across releases). A program with
no budget entry is itself a `comms-budget` finding: new parallel code must
declare its traffic, `--update-budgets` writes the entry.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from fedml_tpu.analysis.core import Finding, Report
from fedml_tpu.analysis.hlo_engine import ProgramComms, analyze_program

BUDGET_FILE = "COMMS_BUDGET.json"

# peak memory is an XLA scheduling artifact — exact pinning would break on
# every toolchain bump; 1.5x catches the "suddenly materializes the client
# stack" class of regression while riding out scheduler noise
PEAK_HEADROOM = 1.5

N_DEV = 8  # the forced host mesh every program lowers on


def _tree_bytes(tree) -> int:
    import jax

    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree.leaves(tree))


def _lr_trainer():
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.models.registry import create_model

    return ClassificationTrainer(
        create_model("lr", output_dim=10, dtype="float32"))


def _abstract_gv(trainer, shape, in_dtype):
    import jax
    import jax.numpy as jnp

    rng = jax.random.PRNGKey(0)
    var_shapes = jax.eval_shape(
        lambda: trainer.init(rng, jnp.zeros(shape, in_dtype)))
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes), rng


def _sharded_round(agg_name: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.parallel.sharded import build_sharded_round_fn

    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("clients",))
    trainer = _lr_trainer()
    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32")
    agg = make_aggregator(agg_name, cfg)
    round_fn = build_sharded_round_fn(trainer, cfg, agg, mesh)
    gv, rng = _abstract_gv(trainer, (2, 32), jnp.float32)
    agg_state = jax.eval_shape(agg.init_state, gv)
    c, n = N_DEV, 4  # one client per device
    args = (gv, agg_state,
            jax.ShapeDtypeStruct((c, n, 32), jnp.float32),
            jax.ShapeDtypeStruct((c, n), jnp.int32),
            jax.ShapeDtypeStruct((c,), jnp.int32), rng)
    return round_fn, args, _tree_bytes(gv)


def _hier_round():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.parallel.hierarchical import (
        build_sharded_hierarchical_round_fn)

    mesh = Mesh(np.array(jax.devices()[:N_DEV]).reshape(2, 4),
                ("groups", "clients"))
    trainer = _lr_trainer()
    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32")
    round_fn = build_sharded_hierarchical_round_fn(
        trainer, cfg, mesh, group_comm_round=2)
    gv, rng = _abstract_gv(trainer, (2, 32), jnp.float32)
    g, c, n = 2, 4, 4
    args = (gv,
            jax.ShapeDtypeStruct((g, c, n, 32), jnp.float32),
            jax.ShapeDtypeStruct((g, c, n), jnp.int32),
            jax.ShapeDtypeStruct((g, c), jnp.int32), rng)
    return round_fn, args, _tree_bytes(gv)


def _gossip_mix():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.parallel.gossip import build_sharded_mix

    n = N_DEV
    # ring: self 0.5, each neighbor 0.25 — 3 nonzero shifts (0, 1, n-1),
    # so 2 ppermutes per pytree leaf
    W = np.zeros((n, n), np.float32)
    for i in range(n):
        W[i, i] = 0.5
        W[i, (i + 1) % n] = 0.25
        W[i, (i - 1) % n] = 0.25
    mesh = Mesh(np.array(jax.devices()[:n]), ("nodes",))
    mix = build_sharded_mix(W, mesh)
    stacked = {
        "w": jax.ShapeDtypeStruct((n, 16, 4), jnp.float32),
        "b": jax.ShapeDtypeStruct((n, 4), jnp.float32),
    }
    return mix, (stacked,), _tree_bytes(stacked)


def _ring_attention():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.parallel.sequence import ring_attention

    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("sp",))
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    s = jax.ShapeDtypeStruct((1, 64, 8, 16), jnp.float32)
    return fn, (s, s, s), None


def _ulysses_attention():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.parallel.sequence import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("sp",))
    fn = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))
    s = jax.ShapeDtypeStruct((1, 64, 8, 16), jnp.float32)
    return fn, (s, s, s), None


def _tensor_round(model_name: str, agg_name: str,
                  codec_name: Optional[str] = None, codec_k: int = 64,
                  lora_rank: int = 0):
    """A 2x4 ('clients', 'tensor') tensor-sharded round
    (parallel/tensor.py): params + aggregator state enter sharded, the
    round gathers per leaf at entry and slices before the client psums —
    so the budget pins BOTH the all_gather cost of the gathered client
    step and the 1/|tensor| aggregation traffic.

    `codec_name` builds the codec-on twin (graft-codec): the entry gather
    moves int8 payloads + per-shard scales, the clients-axis reduction
    moves the codec's encoded partial sums (shared-scale s8 psums, or
    top-k (values, idx) all_gathers). Its COMMS entry is the headline
    wire-shrink gate — the top-k variant must show >=4x fewer collective
    bytes than the codec-off twin (tests/test_codecs.py pins the ratio
    from the committed budgets).

    `lora_rank` builds the federated-LoRA twin (models/lora.py): the
    trainer is LoRA-wrapped, so the federated tree is adapters-only and
    the entry's exact `param_bytes` pin is the >=50x wire-shrink gate vs
    the full-model twin (tests/test_lora.py reads both from the committed
    budgets). Codecs then compress the adapter deltas — the lora+topk
    entry must move strictly fewer collective bytes than either lever
    alone (gated in run_comms on the measured programs)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.codecs import make_codec
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.models.lora import LoRATrainer, strip_lora_base
    from fedml_tpu.parallel.tensor import (TensorSharding,
                                           build_tensor_round_fn)

    mesh = Mesh(np.array(jax.devices()[:N_DEV]).reshape(2, 4),
                ("clients", "tensor"))
    cfg = FedConfig(model=model_name, batch_size=2, epochs=1,
                    dtype="float32", server_optimizer="adam", server_lr=0.01,
                    update_codec=codec_name or "none", codec_k=codec_k,
                    lora_rank=lora_rank)
    if model_name == "lr":
        trainer = _lr_trainer()
        in_shape, in_dtype = (2, 32), jnp.float32
        data = (jax.ShapeDtypeStruct((2, 4, 32), jnp.float32),
                jax.ShapeDtypeStruct((2, 4), jnp.int32))
    else:
        from fedml_tpu.core.trainer import NWPTrainer
        from fedml_tpu.models.registry import create_model

        # realistic NWP vocab (the registry default): the embedding + LM
        # head dominate the param tree exactly as they do in the deployed
        # stackoverflow-scale models, so the LoRA twins' >=50x param_bytes
        # shrink is measured against an honest full-model baseline
        trainer = NWPTrainer(create_model(model_name, output_dim=10004))
        in_shape, in_dtype = (2, 16), jnp.int32
        data = (jax.ShapeDtypeStruct((2, 4, 16), jnp.int32),
                jax.ShapeDtypeStruct((2, 4, 16), jnp.int32))
    if lora_rank:
        trainer = LoRATrainer(trainer, rank=lora_rank)
    gv, rng = _abstract_gv(trainer, in_shape, in_dtype)
    agg = make_aggregator(agg_name, cfg)
    codec = make_codec(cfg.update_codec, cfg)
    round_fn = build_tensor_round_fn(
        trainer, cfg, agg, TensorSharding.for_model(mesh, model_name),
        donate_state=True, codec=codec)
    if codec is None:
        agg_state = jax.eval_shape(agg.init_state, gv)
    else:
        def init_st(g):
            # the residual mirrors the WIRE tree — adapters-only under LoRA
            fed = strip_lora_base(g)
            resid = jax.tree.map(
                lambda l: jnp.zeros(
                    (2,) + (l.shape
                            if jnp.issubdtype(l.dtype, jnp.inexact)
                            else ()), l.dtype), fed)
            return {"agg": agg.init_state(g), "codec": resid}

        agg_state = jax.eval_shape(init_st, gv)
    args = (gv, agg_state) + data + (
        jax.ShapeDtypeStruct((2,), jnp.int32), rng)
    # 4th element: the federated (wire) tree's bytes — the exact
    # `param_bytes` pin. Equal to the full tree when LoRA is off.
    return round_fn, args, _tree_bytes(gv), _tree_bytes(strip_lora_base(gv))


def _tensor_step(replicated: bool = False):
    """The activation-sharded client step (parallel/tensor.py
    build_tensor_step_fn) on the 2x4 ('clients', 'tensor') mesh — the
    program whose per-device peak bytes IS the tentpole win. Params enter
    under the transformer rule table and the matmul/attention
    intermediates carry sharding constraints, so neither the weights nor
    the activations ever materialize whole on one device.

    `replicated=True` builds the budget twin: same step, same mesh, same
    data sharding, but params replicated and the activation-constraint
    scope off — the baseline the <=0.5x per-device peak ratio is measured
    against (gated in run_comms; tests/test_lora.py re-derives it from
    memory_analysis directly). Both entries pin collective traffic at
    ZERO: the step is client-parallel + tensor-sharded compute with no
    cross-device reduction until aggregation, so any collective appearing
    here is itself the regression."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import NWPTrainer
    from fedml_tpu.models.registry import create_model
    from fedml_tpu.parallel.tensor import (REPLICATED_RULES, TensorSharding,
                                           build_tensor_step_fn)

    mesh = Mesh(np.array(jax.devices()[:N_DEV]).reshape(2, 4),
                ("clients", "tensor"))
    cfg = FedConfig(model="transformer_nwp", batch_size=2, epochs=1,
                    dtype="float32", tensor_shards=4)
    trainer = NWPTrainer(create_model("transformer_nwp", output_dim=10004))
    if replicated:
        sharding = TensorSharding(mesh, tuple(REPLICATED_RULES))
        step_fn = build_tensor_step_fn(trainer, cfg, sharding,
                                       activation_rules=None)
    else:
        sharding = TensorSharding.for_model(mesh, "transformer_nwp")
        step_fn = build_tensor_step_fn(trainer, cfg, sharding)
    gv, rng = _abstract_gv(trainer, (2, 16), jnp.int32)
    args = (gv,
            jax.ShapeDtypeStruct((2, 4, 16), jnp.int32),
            jax.ShapeDtypeStruct((2, 4, 16), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.int32), rng)
    return step_fn, args, _tree_bytes(gv), _tree_bytes(gv)


def _buffered_program(which: str, agg_name: str,
                      codec_name: Optional[str] = None, codec_k: int = 16):
    """The buffered-aggregation admit/commit shard_map programs
    (parallel/sharded.py build_sharded_buffer_fns) on the 8-device clients
    mesh: buffer rows AND the stacked client-step result sharded over
    'clients'. Admit's budget pins the one param-sized masked psum that
    moves the source row to the buffer's owner; commit's pins the
    aggregator's psum-reduction traffic (the synchronous round's
    aggregation half, no client-step collectives)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from fedml_tpu.algorithms.aggregators import (make_aggregator,
                                                  make_staleness_discount)
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.parallel.sharded import build_sharded_buffer_fns

    from fedml_tpu.codecs import make_codec

    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("clients",))
    trainer = _lr_trainer()
    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32",
                    update_codec=codec_name or "none", codec_k=codec_k)
    agg = make_aggregator(agg_name, cfg)
    codec = make_codec(cfg.update_codec, cfg)
    admit_fn, commit_fn = build_sharded_buffer_fns(
        agg, make_staleness_discount(0.5), mesh, codec=codec)
    gv, rng = _abstract_gv(trainer, (2, 32), jnp.float32)
    c = k = N_DEV  # one stacked-result row and one buffer row per device
    i32 = lambda shape=(): jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
    row = lambda l: jax.ShapeDtypeStruct((k,) + l.shape, l.dtype)  # noqa: E731
    buf = {
        "vars": jax.tree.map(row, gv),
        "steps": i32((k,)),
        "weights": jax.ShapeDtypeStruct((k,), jnp.float32),
        "metrics": {"loss_sum": jax.ShapeDtypeStruct((k,), jnp.float32),
                    "total": jax.ShapeDtypeStruct((k,), jnp.float32)},
        "birth": i32((k,)),
    }
    if which == "admit":
        stacked = jax.tree.map(lambda l: jax.ShapeDtypeStruct(
            (c,) + l.shape[1:], l.dtype), buf)
        args = (buf, i32(), stacked["vars"], stacked["steps"],
                stacked["metrics"], i32((c,)), i32(), i32())
        if codec is not None:
            # codec-on admit takes the trailing replicated delta base
            args = args + (gv,)
        return admit_fn, args, _tree_bytes(gv)
    agg_state = jax.eval_shape(agg.init_state, gv)
    args = (gv, agg_state, buf, i32(), i32(), rng)
    return commit_fn, args, _tree_bytes(gv)


def _engine_round():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_round_fn
    from fedml_tpu.core.config import FedConfig

    trainer = _lr_trainer()
    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32")
    agg = make_aggregator("fedavg", cfg)
    round_fn = build_round_fn(trainer, cfg, agg)
    gv, rng = _abstract_gv(trainer, (2, 32), jnp.float32)
    agg_state = jax.eval_shape(agg.init_state, gv)
    c, n = 2, 4
    args = (gv, agg_state,
            jax.ShapeDtypeStruct((c, n, 32), jnp.float32),
            jax.ShapeDtypeStruct((c, n), jnp.int32),
            jax.ShapeDtypeStruct((c,), jnp.int32), rng)
    return round_fn, args, _tree_bytes(gv)


def _engine_lora_round(pfl: bool = False):
    """The single-chip federated-LoRA engine round and (pfl=True) its
    personalized twin (graft-pfl): same trainer, same aggregator, same
    cohort geometry — the pfl twin adds the trailing [C, ...] personal
    adapter rows in and out. BOTH pin zero collectives (1-device vmap
    programs), and the pair backs the 'wire bytes unchanged' contract:
    run_comms gates the pfl twin's collective bytes EQUAL to the shared
    twin's (the personal rows ride outputs, never a psum)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import (build_personal_round_fn,
                                             build_round_fn)
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.models.lora import LoRATrainer, strip_lora_base

    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32",
                    lora_rank=8, personalize=pfl)
    trainer = LoRATrainer(_lr_trainer(), rank=8)
    agg = make_aggregator("fedavg", cfg)
    gv, rng = _abstract_gv(trainer, (2, 32), jnp.float32)
    agg_state = jax.eval_shape(agg.init_state, gv)
    c, n = 2, 4
    data = (jax.ShapeDtypeStruct((c, n, 32), jnp.float32),
            jax.ShapeDtypeStruct((c, n), jnp.int32),
            jax.ShapeDtypeStruct((c,), jnp.int32), rng)
    if pfl:
        round_fn = build_personal_round_fn(trainer, cfg, agg)
        personal = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((c,) + l.shape, l.dtype),
            gv["params"])
        args = (gv, agg_state) + data + (personal,)
    else:
        round_fn = build_round_fn(trainer, cfg, agg)
        args = (gv, agg_state) + data
    # 4th element: the federated (wire) tree is adapters-only under LoRA
    # — IDENTICAL for both twins, personal rows are not wire traffic
    return round_fn, args, _tree_bytes(gv), _tree_bytes(strip_lora_base(gv))


def _chunked_chunk_fn():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_chunked_round_runner
    from fedml_tpu.core.config import FedConfig

    trainer = _lr_trainer()
    cfg = FedConfig(model="lr", batch_size=2, epochs=2, dtype="float32")
    runner = build_chunked_round_runner(
        trainer, cfg, make_aggregator("fedavg", cfg), epoch_chunk=1)
    rng = jax.random.PRNGKey(0)
    gv = trainer.init(rng, jnp.zeros((2, 32), jnp.float32))
    c, n = 2, 4
    counts = jnp.full((c,), n, jnp.int32)
    stacked, opt_state, steps, erngs = runner.init_fn(gv, counts, rng)
    x = jnp.zeros((c, n, 32), jnp.float32)
    y = jnp.zeros((c, n), jnp.int32)
    args = (stacked, opt_state, steps, gv["params"], x, y, counts,
            erngs[:, 0:1])
    return runner.chunk_fn, args, _tree_bytes(gv)


# target name -> (builder, num_devices the program spans); the two engine
# extras carry zero-collective budget entries and are skipped by --fast.
PROGRAMS: Dict[str, Tuple[Callable, int]] = {
    "sharded.round[lr,f32,fedavg]": (lambda: _sharded_round("fedavg"), N_DEV),
    "sharded.round[lr,f32,fedopt]": (lambda: _sharded_round("fedopt"), N_DEV),
    "sharded.round[lr,f32,robust]": (lambda: _sharded_round("robust"), N_DEV),
    "sharded.round[lr,f32,fednova]": (lambda: _sharded_round("fednova"),
                                      N_DEV),
    "hier.round[lr,f32,2x4]": (_hier_round, N_DEV),
    "tensor.round[tformer,f32,fedavg,2x4]": (
        lambda: _tensor_round("transformer_nwp", "fedavg"), N_DEV),
    "tensor.round[tformer,f32,fedopt,2x4]": (
        lambda: _tensor_round("transformer_nwp", "fedopt"), N_DEV),
    "tensor.round[lr,f32,robust,2x4]": (
        lambda: _tensor_round("lr", "robust"), N_DEV),
    "tensor.round[lr,f32,fednova,2x4]": (
        lambda: _tensor_round("lr", "fednova"), N_DEV),
    # graft-codec twins: same programs with the update codec on the wire.
    # topk entries are the headline >=4x-fewer-bytes gates (vs their
    # codec-off twins above/below); int8 twins are pinned too — they land
    # just under 4x (payload/4 alone nearly exhausts the quota; the scale
    # sidecars tip it) and PERF.md documents the honest numbers.
    "tensor.round[tformer,f32,fedavg,2x4,int8]": (
        lambda: _tensor_round("transformer_nwp", "fedavg", "int8"), N_DEV),
    "tensor.round[tformer,f32,fedavg,2x4,topk64]": (
        lambda: _tensor_round("transformer_nwp", "fedavg", "topk", 64),
        N_DEV),
    # federated-LoRA twins (models/lora.py): the federated tree is the
    # adapters-only view, so the exact param_bytes pin is the >=50x
    # wire-shrink gate vs the full-model twin; lora+topk stacks both
    # levers and must move strictly fewer bytes than either alone
    "tensor.round[tformer,f32,fedavg,2x4,lora8]": (
        lambda: _tensor_round("transformer_nwp", "fedavg", lora_rank=8),
        N_DEV),
    "tensor.round[tformer,f32,fedavg,2x4,lora8,topk64]": (
        lambda: _tensor_round("transformer_nwp", "fedavg", "topk", 64,
                              lora_rank=8), N_DEV),
    # the activation-sharded client step + its replicated budget twin —
    # the pair behind the <=0.5x per-device peak-bytes gate below
    "tensor.step[tformer,f32,2x4]": (
        lambda: _tensor_step(replicated=False), N_DEV),
    "tensor.step[tformer,f32,2x4,replicated]": (
        lambda: _tensor_step(replicated=True), N_DEV),
    "buffered.admit[lr,f32]": (
        lambda: _buffered_program("admit", "fedavg"), N_DEV),
    "buffered.admit[lr,f32,int8]": (
        lambda: _buffered_program("admit", "fedavg", "int8"), N_DEV),
    "buffered.admit[lr,f32,topk16]": (
        lambda: _buffered_program("admit", "fedavg", "topk", 16), N_DEV),
    "buffered.commit[lr,f32,fedavg]": (
        lambda: _buffered_program("commit", "fedavg"), N_DEV),
    "buffered.commit[lr,f32,fedopt]": (
        lambda: _buffered_program("commit", "fedopt"), N_DEV),
    "gossip.mix[ring8]": (_gossip_mix, N_DEV),
    "sequence.ring[b1,t64,h8,d16]": (_ring_attention, N_DEV),
    "sequence.ulysses[b1,t64,h8,d16]": (_ulysses_attention, N_DEV),
    "engine.round[lr,f32,fedavg]": (_engine_round, 1),
    "engine.round[lr,f32,fedavg,lora8]": (
        lambda: _engine_lora_round(pfl=False), 1),
    "engine.round[lr,f32,fedavg,lora8,pfl]": (
        lambda: _engine_lora_round(pfl=True), 1),
    "engine.chunked.chunk_fn[lr]": (_chunked_chunk_fn, 1),
}

EXTRA_PROGRAMS = ("engine.round[lr,f32,fedavg]",
                  "engine.round[lr,f32,fedavg,lora8]",
                  "engine.round[lr,f32,fedavg,lora8,pfl]",
                  "engine.chunked.chunk_fn[lr]")

_BUDGET_KEYS = ("collective_count", "collective_bytes", "peak_bytes",
                "param_bytes")

# measured-ratio gates applied in run_comms whenever both programs of a
# pair were analyzed in the same run (targets filtering may select one):
# the sharded tensor.step must keep per-device peak at <=0.5x its
# replicated twin — the activation-sharding win IS the program's reason
# to exist, so losing it is a finding, not a budget bump.
_STEP_PEAK_GATE = ("tensor.step[tformer,f32,2x4]",
                   "tensor.step[tformer,f32,2x4,replicated]", 0.5)

# lora+topk must move strictly fewer collective bytes than either lever
# alone — the codecs compress adapter deltas, so the wire shrinks stack
_LORA_STACK_GATE = ("tensor.round[tformer,f32,fedavg,2x4,lora8,topk64]",
                    ("tensor.round[tformer,f32,fedavg,2x4,lora8]",
                     "tensor.round[tformer,f32,fedavg,2x4,topk64]"))

# personalization is wire-free by construction (graft-pfl): the pfl twin
# must move EXACTLY the collective bytes of its shared-LoRA twin (both
# zero on the single chip) — any delta means personal rows leaked into a
# collective
_PFL_WIRE_GATE = ("engine.round[lr,f32,fedavg,lora8,pfl]",
                  "engine.round[lr,f32,fedavg,lora8]")


def load_budgets(repo_root: str) -> Dict[str, Dict[str, int]]:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def make_budgets(programs: Dict[str, ProgramComms],
                 existing: Optional[Dict] = None,
                 param_bytes: Optional[Dict[str, int]] = None
                 ) -> Dict[str, Dict]:
    """Budget entries for measured programs, merged over `existing` so a
    filtered --update-budgets run does not drop the rest of the table.
    `param_bytes` (per program, from the builders that report it) is
    pinned EXACTLY — the federated tree's size is a deterministic function
    of the model + LoRA rank, and the pin is what the >=50x adapter-only
    wire-shrink test reads."""
    out = dict(existing or {})
    for name, pc in programs.items():
        entry = {
            "collective_count": pc.collective_count,
            "collective_bytes": pc.collective_bytes,
        }
        if pc.peak_bytes is not None:
            entry["peak_bytes"] = int(pc.peak_bytes * PEAK_HEADROOM)
        pb = (param_bytes or {}).get(name)
        if pb is not None:
            entry["param_bytes"] = int(pb)
        out[name] = entry
    return dict(sorted(out.items()))


def check_budgets(programs: Dict[str, ProgramComms],
                  budgets: Dict[str, Dict],
                  param_bytes: Optional[Dict[str, int]] = None
                  ) -> List[Finding]:
    """Gate measured comms against the checked-in ceilings. The message is
    the diff a human needs: key, measured, ceiling, overshoot."""
    findings: List[Finding] = []
    for name, pc in programs.items():
        budget = budgets.get(name)
        if budget is None:
            findings.append(Finding(
                "comms-budget", name,
                f"no {BUDGET_FILE} entry — new parallel programs must "
                f"declare their collective traffic; run `python -m "
                f"fedml_tpu.analysis --comms --update-budgets`"))
            continue
        measured = {"collective_count": pc.collective_count,
                    "collective_bytes": pc.collective_bytes,
                    "peak_bytes": pc.peak_bytes,
                    "param_bytes": (param_bytes or {}).get(name)}
        for key in _BUDGET_KEYS:
            ceiling = budget.get(key)
            got = measured[key]
            if ceiling is None or got is None:
                continue
            if got > ceiling:
                findings.append(Finding(
                    "comms-budget", name,
                    f"{key} regressed: measured {got} > budget {ceiling} "
                    f"(+{got - ceiling}, {got / ceiling:.2f}x) — if the "
                    f"extra traffic is intended, re-run with "
                    f"--update-budgets and justify the bump in the PR"))
    return findings


def run_comms(repo_root: str, fast: bool = False,
              targets: Optional[List[str]] = None,
              update_budgets: bool = False,
              compile_programs: bool = True
              ) -> Tuple[Report, Dict]:
    """Lower + analyze every selected program, then apply the budget gate
    (or rewrite it under --update-budgets). Returns (Report, COMMS dict)."""
    import jax

    ndev = len(jax.devices())
    if ndev < N_DEV:
        raise RuntimeError(
            f"HLO layer needs {N_DEV} devices, found {ndev} — set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={N_DEV} "
            f"before jax initializes (the CLI does this itself)")

    report = Report()
    programs: Dict[str, ProgramComms] = {}
    param_bytes: Dict[str, int] = {}
    for name, (builder, num_devices) in PROGRAMS.items():
        if fast and name in EXTRA_PROGRAMS:
            continue
        if targets and not any(t in name for t in targets):
            continue
        built = builder()
        fn, args, params_bytes = built[:3]
        if len(built) > 3 and built[3] is not None:
            # federated-tree bytes (builders that report them) — the
            # exact param_bytes pin
            param_bytes[name] = int(built[3])
        comms, findings = analyze_program(
            fn, args, name, num_devices=num_devices,
            params_bytes=params_bytes, compile=compile_programs,
            # tensor.step runs under GSPMD automatic partitioning — the
            # partitioner's resharding collectives are by design there
            expect_resharding=name.startswith("tensor.step"))
        report.extend(findings)
        report.mark(name)
        if comms is not None:
            programs[name] = comms

    # measured-ratio gates (independent of the budget file — these hold
    # whenever both programs of a pair were analyzed in this run)
    sh_name, rep_name, ratio = _STEP_PEAK_GATE
    sh, rep = programs.get(sh_name), programs.get(rep_name)
    if (sh is not None and rep is not None
            and sh.peak_bytes and rep.peak_bytes
            and sh.peak_bytes > ratio * rep.peak_bytes):
        report.extend([Finding(
            "comms-budget", sh_name,
            f"activation-sharded step peak {sh.peak_bytes}B exceeds "
            f"{ratio}x its replicated twin ({rep.peak_bytes}B, ratio "
            f"{sh.peak_bytes / rep.peak_bytes:.2f}) — the per-device "
            f"memory shrink is the program's contract; a lost sharding "
            f"constraint or a gather of the full params re-materializes "
            f"the replicated footprint")])
    stack_name, singles = _LORA_STACK_GATE
    stacked = programs.get(stack_name)
    for single_name in singles:
        single = programs.get(single_name)
        if (stacked is not None and single is not None
                and stacked.collective_bytes >= single.collective_bytes):
            report.extend([Finding(
                "comms-budget", stack_name,
                f"lora+topk moved {stacked.collective_bytes}B on the wire "
                f"— not strictly fewer than {single_name} "
                f"({single.collective_bytes}B); the codec must compress "
                f"the adapter deltas, not the full tree (the shrinks are "
                f"multiplicative by construction)")])
    pfl_name, shared_name = _PFL_WIRE_GATE
    pfl, shared = programs.get(pfl_name), programs.get(shared_name)
    if (pfl is not None and shared is not None
            and pfl.collective_bytes != shared.collective_bytes):
        report.extend([Finding(
            "comms-budget", pfl_name,
            f"personalized round moved {pfl.collective_bytes}B of "
            f"collectives vs {shared.collective_bytes}B for its shared "
            f"twin — personal adapter rows must ride program OUTPUTS "
            f"(models/adapter_bank.py scatter), never a psum; wire bytes "
            f"are contractually unchanged by --personalize")])

    if update_budgets:
        budgets = make_budgets(programs, existing=load_budgets(repo_root),
                               param_bytes=param_bytes)
        with open(os.path.join(repo_root, BUDGET_FILE), "w") as f:
            json.dump(budgets, f, indent=2)
            f.write("\n")
    else:
        report.extend(check_budgets(programs, load_budgets(repo_root),
                                    param_bytes=param_bytes))

    comms_dict = {
        "ok": report.ok,
        "num_findings": len(report.findings),
        "programs": {n: pc.to_dict() for n, pc in programs.items()},
        "findings": [
            {"rule": f.rule, "target": f.target, "message": f.message,
             "severity": f.severity} for f in report.findings],
    }
    return report, comms_dict


def format_comms_table(programs: Dict[str, Dict]) -> str:
    """Human-readable per-program traffic table for the CLI."""
    lines = []
    for name, pc in programs.items():
        ops = ", ".join(f"{k}x{v}" for k, v in sorted(pc["per_op"].items()))
        peak = (f"{pc['peak_bytes']}B peak"
                if pc.get("peak_bytes") is not None else "peak n/a")
        lines.append(f"  {name}: {pc['collective_count']} collective(s) "
                     f"[{ops or 'none'}], {pc['collective_bytes']}B on the "
                     f"wire, {peak}")
    return "\n".join(lines)
