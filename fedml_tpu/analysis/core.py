"""Findings contract + report plumbing shared by both lint engines."""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

# Every rule the analyzer knows. Keep in sync with docs/INVENTORY.md's table.
RULES = {
    "dtype-policy": "f32 dot_general/conv in a bfloat16-policy program",
    "donation": "donate_argnums arg did not lower as a donated buffer",
    "host-sync": "pure/debug/io callback primitive inside a jitted round body",
    "dead-cast": "A->B->A convert_element_type round-trip",
    "retrace": "more than one compile per shape signature across a drive",
    "host-transfer": "host sync (float/np.asarray/device_get/...) in traced code",
    "traced-loop": "Python for-loop over a traced array",
    "sync-idiom": "float(np.asarray(...)) double-transfer idiom",
    "blocking-fetch-in-drive-loop": "per-item float()/np.asarray()/.item() "
                                    "host sync inside an algorithms/ driver "
                                    "round loop",
    "naked-timer-in-drive-loop": "raw time.time()/perf_counter() timing in "
                                 "an algorithms/ drive loop (measures async "
                                 "dispatch, not compute — use telemetry "
                                 "spans or block_until_ready-bracketed "
                                 "timers)",
    "full-store-materialize": "np.asarray/np.stack/.x[:] whole-store read "
                              "over a packed/streaming client store outside "
                              "the blessed materialize() helper (stores are "
                              "O(cohort) by contract — select() the cohort)",
    "partition-coverage": "param tree leaf matches no PartitionSpec rule",
    "unconstrained-intermediate": "matmul/einsum intermediates in a "
                                  "tensor-sharded step carry no sharding "
                                  "constraint — GSPMD will gather the "
                                  "activations replicated between layers "
                                  "and the per-device peak-memory win "
                                  "silently evaporates",
    # HLO-layer rules (hlo_engine / comms): lowered-program collectives
    "collective-in-loop": "loop-invariant collective inside a while/scan body",
    "accidental-replication": "partitioner all-gather rematerializes the "
                              "full param tree on every device",
    "ppermute-coverage": "collective-permute source/target pairs are not a "
                         "permutation of the full axis group",
    "unweighted-psum-mean": "psum(x)/axis_size mean where the repo's "
                            "weighted-mean aggregation was intended",
    "axis-name-mismatch": "collective names a mesh axis the program's mesh "
                          "does not bind",
    "comms-budget": "program exceeds its COMMS_BUDGET.json collective/memory "
                    "ceiling (or has no budget entry)",
    # Compile-layer rules (compile_engine): program-count and thread/liveness
    # discipline around the jitted drive loops.
    "compile-budget": "drive config compiles more distinct programs than its "
                      "COMPILE_BUDGET.json pin (or has no budget entry)",
    "retrace-risk": "call site feeds a Python scalar, weak-typed literal, or "
                    "shape-varying operand into a jitted function (every "
                    "distinct value/shape is a fresh compile)",
    "use-after-donate": "value passed at a donated argnum is read again "
                        "after the donating call (the buffer is dead — "
                        "XLA may have already reused it)",
    "lock-discipline": "stager-thread function touches shared mutable state "
                       "outside a `with self._lock` block",
    "rng-key-reuse": "PRNG key consumed by two jitted calls without an "
                     "intervening fold_in/split (identical randomness)",
    "unregistered-codec": "Int8Codec/TopKCodec constructed directly in "
                          "algorithms//parallel//serving/ instead of via "
                          "fedml_tpu.codecs.make_codec (call-site literals "
                          "desync the codec from FedConfig and its budget "
                          "program twins)",
    "personal-state-in-federated-tree": "personal adapter state passed to "
                                        "an aggregator/codec/checkpoint "
                                        "surface (psum/aggregate/encode/"
                                        "save_checkpoint...) — personal rows "
                                        "are client-private and persist only "
                                        "through models/adapter_bank.py",
    "bare-suppression": "graft-lint: disable comment without a '-- reason'",
    # Matrix-layer rules (matrix_engine / --matrix): the declarative
    # RoundProgramSpec (core/spec.py) vs the repo.
    "matrix-coverage": "feature-matrix drift: a legal axis combination "
                       "fails to build, an illegal one passes config "
                       "validation, or a spec-reachable program is missing "
                       "from (or stale in) COMPILE/COMMS budget pins",
    "axis-drift": "round assembler signature diverges from its "
                  "spec.ASSEMBLERS declaration — a feature-axis kwarg "
                  "siblings thread through is missing, or a new one is "
                  "undeclared",
    "unschema-event": "tracer.event()/telemetry.emit() with a literal kind "
                      "that is not in EVENT_SCHEMAS (the call raises "
                      "ValueError the first time it fires at runtime — "
                      "often in a rarely-hit error path)",
    # Equivalence-layer rules (equiv_engine / --equiv): canonical-jaxpr
    # identity proofs over core/builder.py's composed round programs.
    "equiv-contract": "a spec.EQUIV_PAIRS structurally-off contract broke: "
                      "the two sides trace to canonically different jaxprs "
                      "(first divergence reported eqn-by-eqn)",
    "equiv-divergence": "core/builder.build_round_program emits a "
                        "canonically different jaxpr than the preserved "
                        "legacy hand assembly for a matrix cover point",
}

# Suppression grammar: `# graft-lint: disable=rule1,rule2 -- reason`.
# The rule list is comma-separated rule names only; the ` -- ` separator
# (spaces required) starts the mandatory human reason. The char class
# deliberately excludes spaces so a reason can never be swallowed into a
# rule name.
_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint:\s*disable="
    r"([\w\-]+(?:\s*,\s*[\w\-]+)*)"
    r"(?:\s+--\s+(\S.*))?")


@dataclass
class Finding:
    rule: str
    target: str          # "module.fn", "file.py:LINE", "model:resnet56", ...
    message: str
    severity: str = "error"

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown rule {self.rule!r}; known: {sorted(RULES)}")

    def __str__(self):
        return f"{self.target}: [{self.rule}] {self.message}"


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    checked: List[str] = field(default_factory=list)   # targets examined

    def extend(self, findings: List[Finding]) -> None:
        self.findings.extend(findings)

    def mark(self, target: str) -> None:
        self.checked.append(target)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict:
        return {
            "ok": self.ok,
            "num_findings": len(self.findings),
            "num_targets": len(self.checked),
            "findings": [asdict(f) for f in self.findings],
            "targets": self.checked,
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
            f.write("\n")

    def summary(self) -> str:
        lines = [str(f) for f in self.findings]
        lines.append(
            f"graft-lint: {len(self.findings)} finding(s) across "
            f"{len(self.checked)} target(s)")
        return "\n".join(lines)


def suppressed_rules(source_line: str) -> Optional[set]:
    """Rules disabled by a `# graft-lint: disable=rule1,rule2 -- reason`
    comment on this line; None when there is no suppression comment."""
    m = _SUPPRESS_RE.search(source_line)
    if not m:
        return None
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def suppression_reason(source_line: str) -> Optional[str]:
    """The `-- reason` text of a suppression comment on this line; None when
    there is no suppression comment OR the suppression is bare (no reason) —
    callers distinguish the two via suppressed_rules()."""
    m = _SUPPRESS_RE.search(source_line)
    if not m:
        return None
    return m.group(2)


def iter_suppressions(source: str):
    """(1-based lineno, rules set, reason-or-None) for every graft-lint
    suppression comment in `source` — the bare-suppression rule's walk."""
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            yield i, rules, m.group(2)


def is_suppressed(source_lines: List[str], lineno: int, rule: str) -> bool:
    """True if `rule` is suppressed on 1-based `lineno` (same line or the
    line directly above it)."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(source_lines):
            rules = suppressed_rules(source_lines[ln - 1])
            if rules and rule in rules:
                return True
    return False
