"""graft-lint engine #4: the compile layer.

Three rule families around the jitted drive loops, plus the CI-pinned
compile budget:

* **retrace budget** — `enumerate_drive_programs()` (targets.py) counts the
  distinct XLA programs each registered drive config reaches;
  `COMPILE_BUDGET.json` pins those counts exactly (two-way: an un-budgeted
  program and a stale pin are both findings) and, for the runtime drive
  configs, a `max_compiles` ceiling that `telemetry.report.run_compile_gate`
  checks against graft-trace's `compile_cache` events. The AST-side
  `retrace-risk` rule flags call sites that feed Python scalars, weak-typed
  literals, or shape-varying operands into jitted callables — each distinct
  value/shape is a fresh compile.
* **use-after-donate** — linear dataflow over each function body tracking
  the expressions passed at donated argnums (through `jax.jit(...,
  donate_argnums=...)` bindings and the repo's `build_*` factory
  conventions); any later read/len/indexing of the donated binding is a
  finding. Re-binding the donated value from the call's own result (the
  `stacked, ... = chunk_fn(stacked, ...)` idiom) blesses it.
* **lock-discipline** — for every class that owns a `threading.Lock`/`RLock`,
  attributes are *guarded* if any method touches them under `with
  self._lock` and *shared* if written outside ``__init__``; touching a
  guarded+shared attribute outside the lock (including from a nested
  function handed to the stager thread, which never inherits the caller's
  lock) is a finding. A method whose every in-class call site holds the
  lock is lock-held by propagation, like the AST engine's traced-ness.
* **rng-key-reuse** — a PRNG key variable (assigned from
  `PRNGKey/fold_in/split`, or an `rng`/`key` parameter) consumed by two
  jitted calls without an intervening `fold_in`/`split` reuses identical
  randomness; consumption inside a loop whose key was minted outside is
  flagged immediately.

The budget half mirrors analysis/comms.py: `load_budgets` / `make_budgets` /
`check_budgets` / `run_compile`, JSON written deterministically so
`--update-budgets` round-trips byte-stable.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from fedml_tpu.analysis.core import Finding, Report, is_suppressed

BUDGET_FILE = "COMPILE_BUDGET.json"

# Drive configs whose compile counts are measured at runtime (10-round CLI
# drives under graft-trace) in addition to the static enumeration. The CLI
# fragments double as documentation of what each budget entry pins.
RUNTIME_DRIVE_CLI = {
    "eager": "--comm_round 10",
    "pipelined": ("--comm_round 10 --pipeline_depth 2 --chaos 1 "
                  "--chaos_seed 7 --chaos_drop_rate 0.3 --chaos_nan_rate 0.4 "
                  "--guard 1"),
    "buffered": ("--comm_round 10 --pipeline_depth 2 --buffer_size 5 "
                 "--staleness_alpha 0.5 --chaos_straggler_rate 0.5 "
                 "--chaos_straggler_rounds 2"),
    "tensor": "--comm_round 10 --tensor_shards 4",
}

# ---------------------------------------------------------------------------
# jit-binding collection (shared by retrace-risk / use-after-donate /
# rng-key-reuse)
# ---------------------------------------------------------------------------

# Factories following the repo's build_* convention whose results donate
# input buffers. Values are the donated argnums of the *returned* callable;
# donation is active only when the donate_* keyword is passed and is not a
# literal False (a non-literal toggle is treated as donating — conservative).
_DONATING_FACTORIES = {
    "build_round_fn": ("donate_data", (2, 3, 4)),
    "build_round_fn_from_update": ("donate_data", (2, 3, 4)),
    "build_tensor_round_fn": ("donate_data", (2, 3, 4)),
    "build_client_step_fn": ("donate_data", (1, 2)),
    "build_buffer_admit": ("donate_buffer", (0,)),
}

_KEY_SOURCES = {"PRNGKey", "fold_in", "split", "key", "wrap_key_data",
                # the in-graph Feistel sampler's host-side key schedule
                # (algorithms/sampling.py): a derived per-round block is
                # itself a key — deriving is blessed, replaying one fires
                "feistel_keys_block", "feistel_round_keys", "split_keys"}


def _dotted(node) -> Optional[str]:
    """'jax.jit' for Attribute chains, 'jit' for Names (ast_engine's)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _JitBindings:
    """Names/attrs in a module bound to jitted callables, with donation info.

    `names` / `attrs`: bare names and attribute tails (``self.round_fn`` ->
    ``round_fn``) whose RHS was `jax.jit(...)`, `pjit(...)`, or a call to a
    `build_*` factory (the repo convention: factories return jitted
    callables).  `donating` / `donating_attrs` map the subset with known
    donated argnums.
    """

    def __init__(self, tree: ast.AST):
        self.names: set = set()
        self.attrs: set = set()
        self.donating: Dict[str, Tuple[int, ...]] = {}
        self.donating_attrs: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if isinstance(target, ast.Name):
                key, attr = target.id, False
            elif isinstance(target, ast.Attribute):
                key, attr = target.attr, True
            else:
                continue
            argnums = self._jit_rhs(node.value)
            if argnums is None:
                continue
            (self.attrs if attr else self.names).add(key)
            if argnums:
                (self.donating_attrs if attr else self.donating)[key] = argnums

    @staticmethod
    def _jit_rhs(value) -> Optional[Tuple[int, ...]]:
        """None: not a jit binding. (): jitted, no known donation.
        (i, ...): jitted with those donated argnums."""
        if not isinstance(value, ast.Call):
            return None
        tail = (_dotted(value.func) or "").rsplit(".", 1)[-1]
        if tail in ("jit", "pjit"):
            for kw in value.keywords:
                if kw.arg == "donate_argnums":
                    lits = _literal_int_tuple(kw.value)
                    return lits if lits else ()
            return ()
        if tail in _DONATING_FACTORIES:
            toggle, argnums = _DONATING_FACTORIES[tail]
            for kw in value.keywords:
                if kw.arg == toggle and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is False):
                    return argnums
            return ()
        if tail.startswith("build_"):
            return ()
        return None

    def callee(self, func) -> Optional[str]:
        """Dotted callee string if `func` refers to a jit binding."""
        d = _dotted(func)
        if d is None:
            return None
        tail = d.rsplit(".", 1)[-1]
        if (isinstance(func, ast.Name) and d in self.names) or (
                isinstance(func, ast.Attribute) and tail in self.attrs):
            return d
        return None

    def donated_argnums(self, func) -> Optional[Tuple[int, ...]]:
        d = _dotted(func)
        if d is None:
            return None
        if isinstance(func, ast.Name):
            return self.donating.get(d)
        return self.donating_attrs.get(d.rsplit(".", 1)[-1])


def _literal_int_tuple(node) -> Optional[Tuple[int, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


# ---------------------------------------------------------------------------
# ordered statement/expression event stream (use-after-donate, rng-key-reuse)
# ---------------------------------------------------------------------------


def _iter_events(body: Sequence[ast.stmt], depth: int = 0):
    """Yield ('stmt'|'expr', node, loop_depth) in source order. Compound
    statements contribute their header expressions then recurse; nested
    def/class scopes are skipped (they run at call time, not here)."""
    for stmt in body:
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            yield ("expr", stmt.iter, depth)
            yield from _iter_events(stmt.body + stmt.orelse, depth + 1)
        elif isinstance(stmt, ast.While):
            yield ("expr", stmt.test, depth + 1)
            yield from _iter_events(stmt.body + stmt.orelse, depth + 1)
        elif isinstance(stmt, ast.If):
            yield ("expr", stmt.test, depth)
            yield from _iter_events(stmt.body, depth)
            yield from _iter_events(stmt.orelse, depth)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                yield ("expr", item.context_expr, depth)
            yield from _iter_events(stmt.body, depth)
        elif isinstance(stmt, ast.Try):
            yield from _iter_events(stmt.body, depth)
            for h in stmt.handlers:
                yield from _iter_events(h.body, depth)
            yield from _iter_events(stmt.orelse, depth)
            yield from _iter_events(stmt.finalbody, depth)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            continue
        else:
            yield ("stmt", stmt, depth)


def _assign_targets(stmt) -> List[str]:
    """Dotted strings bound by this statement (tuple targets flattened)."""
    out = []
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                d = _dotted(e)
                if d:
                    out.append(d)
        else:
            d = _dotted(t)
            if d:
                out.append(d)
    return out


def _calls_in(node) -> List[ast.Call]:
    return [n for n in ast.walk(node) if isinstance(n, ast.Call)]


# ---------------------------------------------------------------------------
# retrace-risk
# ---------------------------------------------------------------------------


def _const_expr(node) -> bool:
    if node is None or isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return isinstance(node.operand, ast.Constant)
    return False


def _retrace_risk_arg(arg) -> Optional[str]:
    """Reason string if `arg` is a retrace hazard when fed to a jitted fn."""
    if isinstance(arg, ast.Constant) and type(arg.value) in (bool, int, float):
        return (f"Python scalar literal {arg.value!r} is weak-typed — a "
                "second call site passing an array (or a different literal) "
                "retraces; wrap with np.int32/jnp.asarray or close over it")
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) \
            and arg.func.id in ("float", "int", "bool"):
        return (f"{arg.func.id}(...) feeds a weak-typed Python scalar into "
                "a jitted call — every distinct value is a fresh compile")
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Subscript):
            slices = sub.slice.elts if isinstance(sub.slice, ast.Tuple) \
                else [sub.slice]
            for s in slices:
                if isinstance(s, ast.Slice) and not all(
                        _const_expr(b) for b in (s.lower, s.upper, s.step)):
                    return ("shape-varying operand: slice bounds are not "
                            "constant, so every distinct extent is a fresh "
                            "compile — pad to a static shape")
    return None


def _lint_retrace_risk(fn_body, bindings: _JitBindings, path: str,
                       lines: List[str]) -> List[Finding]:
    findings = []
    for kind, node, _ in _iter_events(fn_body):
        for call in _calls_in(node):
            callee = bindings.callee(call.func)
            if callee is None:
                continue
            exprs = [a for a in call.args
                     if not isinstance(a, ast.Starred)]
            exprs += [kw.value for kw in call.keywords if kw.arg]
            for arg in exprs:
                reason = _retrace_risk_arg(arg)
                if reason is None:
                    continue
                lineno = getattr(arg, "lineno", call.lineno)
                if is_suppressed(lines, lineno, "retrace-risk"):
                    continue
                findings.append(Finding(
                    rule="retrace-risk",
                    target=f"{path}:{lineno}",
                    message=f"call to jitted `{callee}`: {reason}"))
    return findings


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------


def _lint_use_after_donate(fn_body, bindings: _JitBindings, path: str,
                           lines: List[str]) -> List[Finding]:
    findings = []
    live: Dict[str, Tuple[str, int]] = {}   # donated dotted -> (callee, line)
    list_values: Dict[str, List[ast.expr]] = {}

    def reads_of(node):
        for sub in ast.walk(node):
            d = _dotted(sub) if isinstance(sub, (ast.Name, ast.Attribute)) \
                else None
            if d is None:
                continue
            if not isinstance(getattr(sub, "ctx", None), ast.Load):
                continue
            for b in list(live):
                if d == b or d.startswith(b + "."):
                    yield b, sub

    def check_reads(node):
        for b, sub in reads_of(node):
            callee, dline = live.pop(b)
            if is_suppressed(lines, sub.lineno, "use-after-donate"):
                continue
            findings.append(Finding(
                rule="use-after-donate",
                target=f"{path}:{sub.lineno}",
                message=(f"`{b}` was donated to `{callee}` at line {dline} "
                         "— the buffer is dead (XLA may already have reused "
                         "it); re-bind the result or drop the read")))

    for kind, node, _ in _iter_events(fn_body):
        check_reads(node)

        if kind != "stmt":
            continue
        targets = _assign_targets(node)
        # assignment to (or through the root of) a donated binding kills it
        for t in targets:
            for b in list(live):
                if b == t or b.startswith(t + ".") or t.startswith(b + "."):
                    del live[b]

        # model `args = [...]` / `args.append(x)` so `fn(*args)` resolves
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.List):
            list_values[node.targets[0].id] = list(node.value.elts)
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr == "append" \
                and isinstance(node.value.func.value, ast.Name) \
                and node.value.func.value.id in list_values \
                and node.value.args:
            list_values[node.value.func.value.id].append(node.value.args[0])

        for call in _calls_in(node):
            argnums = bindings.donated_argnums(call.func)
            if not argnums:
                continue
            pos_args = call.args
            if len(pos_args) == 1 and isinstance(pos_args[0], ast.Starred) \
                    and isinstance(pos_args[0].value, ast.Name):
                pos_args = list_values.get(pos_args[0].value.id, [])
            for i in argnums:
                if i >= len(pos_args):
                    continue
                d = _dotted(pos_args[i])
                if d is None or d in targets:   # re-binding idiom: blessed
                    continue
                live[d] = (bindings.callee(call.func) or _dotted(call.func)
                           or "?", call.lineno)
    return findings


# ---------------------------------------------------------------------------
# rng-key-reuse
# ---------------------------------------------------------------------------


def _is_key_name(name: str) -> bool:
    parts = name.lower().split("_")
    return "rng" in parts or "key" in parts


def _is_key_rhs(value) -> bool:
    if not isinstance(value, ast.Call):
        return False
    tail = (_dotted(value.func) or "").rsplit(".", 1)[-1]
    return tail in _KEY_SOURCES


def _lint_rng_key_reuse(fn_node, bindings: _JitBindings, path: str,
                        lines: List[str]) -> List[Finding]:
    findings = []
    keys: Dict[str, Tuple[int, int]] = {}   # name -> (uses, bound_depth)
    for a in list(fn_node.args.args) + list(fn_node.args.kwonlyargs):
        if _is_key_name(a.arg):
            keys[a.arg] = (0, 0)

    def consume(node, depth):
        for call in _calls_in(node):
            if bindings.callee(call.func) is None:
                continue
            exprs = [a for a in call.args] + \
                    [kw.value for kw in call.keywords]
            seen = set()

            def scan(n):
                # a key inside fold_in(key, i)/split(key) is being DERIVED,
                # not consumed raw — that is the blessed idiom
                if isinstance(n, ast.Call) and (
                        _dotted(n.func) or "").rsplit(
                            ".", 1)[-1] in _KEY_SOURCES:
                    return
                if isinstance(n, ast.Name) and n.id in keys:
                    seen.add(n.id)
                for c in ast.iter_child_nodes(n):
                    scan(c)

            for e in exprs:
                scan(e)
            for name in seen:
                uses, bound_depth = keys[name]
                looped = depth > bound_depth
                if uses >= 1 or looped:
                    del keys[name]
                    if is_suppressed(lines, call.lineno, "rng-key-reuse"):
                        continue
                    how = ("inside a loop without a per-iteration "
                           "fold_in/split" if looped and uses == 0
                           else "by a second jitted call without an "
                                "intervening fold_in/split")
                    findings.append(Finding(
                        rule="rng-key-reuse",
                        target=f"{path}:{call.lineno}",
                        message=(f"PRNG key `{name}` is consumed {how} — "
                                 "identical randomness on every use")))
                else:
                    keys[name] = (uses + 1, bound_depth)

    for kind, node, depth in _iter_events(fn_node.body):
        consume(node, depth)
        if kind != "stmt" or not isinstance(node, ast.Assign):
            continue
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if _is_key_rhs(node.value):
                keys[name] = (0, depth)     # fresh/refolded key
            elif name in keys:
                del keys[name]              # rebound to something else
    return findings


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class _Access:
    __slots__ = ("attr", "lineno", "write", "locked", "nested", "method")

    def __init__(self, attr, lineno, write, locked, nested, method):
        self.attr, self.lineno = attr, lineno
        self.write, self.locked = write, locked
        self.nested, self.method = nested, method


_MUTATORS = {"append", "extend", "pop", "popleft", "appendleft", "clear",
             "update", "setdefault", "insert", "remove", "discard", "add",
             "sort"}


def _lock_attr_of(cls: ast.ClassDef) -> Optional[str]:
    """Attr name assigned threading.Lock()/RLock() in __init__, if any."""
    for stmt in cls.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__":
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and (_dotted(node.value.func) or "").rsplit(
                            ".", 1)[-1] in ("Lock", "RLock") \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute) \
                        and isinstance(node.targets[0].value, ast.Name) \
                        and node.targets[0].value.id == "self":
                    return node.targets[0].attr
    return None


def _collect_accesses(method: ast.FunctionDef, lock_attr: str,
                      self_name: str = "self"):
    """(accesses, calls): attribute touches on `self` with lock context, and
    (callee_method, locked) pairs for in-class calls."""
    accesses: List[_Access] = []
    calls: List[Tuple[str, bool]] = []

    def walk(node, locked, nested, parent_store=False):
        if isinstance(node, ast.With):
            item_locked = locked
            for item in node.items:
                walk(item.context_expr, locked, nested)
                if _dotted(item.context_expr) == f"{self_name}.{lock_attr}":
                    item_locked = True
            for stmt in node.body:
                walk(stmt, item_locked, nested)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not method:
                # nested def: runs later (e.g. on the stager thread) —
                # the enclosing lock is NOT held when it executes.
                for stmt in node.body:
                    walk(stmt, False, True)
                return
            for stmt in node.body:
                walk(stmt, locked, nested)
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Name) and base.id == self_name:
                calls.append((node.func.attr, locked))
                for a in node.args + [kw.value for kw in node.keywords]:
                    walk(a, locked, nested)
                return
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == self_name:
                # self.X.append(...) — mutation through the attribute
                accesses.append(_Access(
                    base.attr, base.lineno,
                    node.func.attr in _MUTATORS, locked, nested, method.name))
                for a in node.args + [kw.value for kw in node.keywords]:
                    walk(a, locked, nested)
                return
        if isinstance(node, ast.Subscript):
            store = isinstance(node.ctx, (ast.Store, ast.Del))
            walk(node.value, locked, nested, parent_store=store)
            walk(node.slice, locked, nested)
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == self_name:
            write = isinstance(node.ctx, (ast.Store, ast.Del)) or parent_store
            accesses.append(_Access(
                node.attr, node.lineno, write, locked, nested, method.name))
            return
        for child in ast.iter_child_nodes(node):
            walk(child, locked, nested)

    walk(method, False, False)
    return accesses, calls


def _lint_lock_discipline(tree: ast.AST, path: str,
                          lines: List[str]) -> List[Finding]:
    findings = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        lock_attr = _lock_attr_of(cls)
        if lock_attr is None:
            continue
        methods = [s for s in cls.body if isinstance(s, ast.FunctionDef)]
        method_names = {m.name for m in methods}
        per_method = {m.name: _collect_accesses(m, lock_attr)
                      for m in methods}

        # lock-held propagation: a method is lock-held when every in-class
        # call site holds the lock (directly or via a lock-held caller).
        call_sites: Dict[str, List[Tuple[str, bool]]] = {}
        for name, (_, calls) in per_method.items():
            for callee, locked in calls:
                call_sites.setdefault(callee, []).append((name, locked))
        lock_held: set = set()
        changed = True
        while changed:
            changed = False
            for name in method_names - lock_held - {"__init__"}:
                sites = call_sites.get(name)
                if sites and all(locked or caller in lock_held
                                 for caller, locked in sites):
                    lock_held.add(name)
                    changed = True

        def effective_locked(a: _Access) -> bool:
            if a.nested:
                return a.locked
            return a.locked or a.method in lock_held

        guarded, shared = set(), set()
        for name, (accesses, _) in per_method.items():
            for a in accesses:
                if name != "__init__" and effective_locked(a):
                    guarded.add(a.attr)
                if a.write and name != "__init__":
                    shared.add(a.attr)
        hot = (guarded & shared) - method_names - {lock_attr}

        seen = set()
        for name, (accesses, _) in per_method.items():
            if name == "__init__":
                continue
            for a in accesses:
                if a.attr not in hot or effective_locked(a):
                    continue
                if (a.lineno, a.attr) in seen:
                    continue
                seen.add((a.lineno, a.attr))
                if is_suppressed(lines, a.lineno, "lock-discipline"):
                    continue
                findings.append(Finding(
                    rule="lock-discipline",
                    target=f"{path}:{a.lineno}",
                    message=(f"`self.{a.attr}` is guarded by "
                             f"`self.{lock_attr}` elsewhere in "
                             f"`{cls.name}` but touched here without it"
                             + (" (nested function: the enclosing lock is "
                                "not held when this runs)" if a.nested
                                else ""))))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _function_nodes(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def lint_compile_tree(tree: ast.AST, path: str,
                      lines: List[str]) -> List[Finding]:
    """Run the four compile-layer AST rules over a parsed module."""
    bindings = _JitBindings(tree)
    findings: List[Finding] = []
    for fn in _function_nodes(tree):
        findings.extend(_lint_retrace_risk(fn.body, bindings, path, lines))
        findings.extend(_lint_use_after_donate(fn.body, bindings, path, lines))
        findings.extend(_lint_rng_key_reuse(fn, bindings, path, lines))
    findings.extend(_lint_lock_discipline(tree, path, lines))
    return findings


def lint_compile_source(source: str, path: str = "<string>") -> List[Finding]:
    """Standalone parse + compile-layer rules (fixture tests use this)."""
    tree = ast.parse(source)
    return lint_compile_tree(tree, path, source.splitlines())


def lint_compile_file(path: str) -> List[Finding]:
    with open(path) as f:
        source = f.read()
    try:
        return lint_compile_source(source, path)
    except SyntaxError as e:
        return [Finding(rule="retrace-risk", target=f"{path}:{e.lineno}",
                        message=f"could not parse: {e.msg}",
                        severity="warning")]


def lint_compile_dir(root: str,
                     subdirs: Sequence[str] = ("fedml_tpu", "tools")
                     ) -> List[Finding]:
    findings = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, _, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    findings.extend(
                        lint_compile_file(os.path.join(dirpath, fn)))
    return findings


# ---------------------------------------------------------------------------
# compile budgets (mirrors analysis/comms.py's budget plumbing)
# ---------------------------------------------------------------------------


def load_budgets(repo_root: str) -> Dict:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def make_budgets(measured: Dict[str, Dict[str, int]],
                 existing: Optional[Dict] = None,
                 max_compiles: Optional[Dict[str, int]] = None) -> Dict:
    """Budget dict from measured program counts. Existing `max_compiles`
    ceilings survive unless re-measured; keys are sorted so the JSON is
    byte-stable across runs."""
    existing = existing or {}
    out = dict(existing)
    for drive, programs in measured.items():
        entry = {
            "programs": dict(sorted(programs.items())),
            "static_total": sum(programs.values()),
        }
        prev = existing.get(drive, {})
        if max_compiles and drive in max_compiles:
            entry["max_compiles"] = max_compiles[drive]
        elif "max_compiles" in prev:
            entry["max_compiles"] = prev["max_compiles"]
        if drive in RUNTIME_DRIVE_CLI:
            entry["cli"] = RUNTIME_DRIVE_CLI[drive]
        out[drive] = entry
    return dict(sorted(out.items()))


def check_budgets(measured: Dict[str, Dict[str, int]],
                  budgets: Dict) -> List[Finding]:
    """Exact two-way check: every enumerated program must be pinned with the
    same signature count, and every pin must still be reachable."""
    findings = []
    hint = "re-run `python -m fedml_tpu.analysis --compile --update-budgets`"
    for drive, programs in sorted(measured.items()):
        entry = budgets.get(drive)
        if entry is None:
            findings.append(Finding(
                rule="compile-budget", target=f"drive:{drive}",
                message=(f"no {BUDGET_FILE} entry for drive config "
                         f"`{drive}` ({sum(programs.values())} program(s) "
                         f"enumerated) — {hint}")))
            continue
        pinned = entry.get("programs", {})
        for name, n in sorted(programs.items()):
            if name not in pinned:
                findings.append(Finding(
                    rule="compile-budget", target=f"drive:{drive}",
                    message=(f"program `{name}` is reachable but not "
                             f"budgeted ({n} signature(s)) — {hint}")))
            elif pinned[name] != n:
                diff = n - pinned[name]
                findings.append(Finding(
                    rule="compile-budget", target=f"drive:{drive}",
                    message=(f"program `{name}`: enumerated {n} "
                             f"signature(s) != pinned {pinned[name]} "
                             f"({diff:+d}) — {hint}")))
        for name in sorted(set(pinned) - set(programs)):
            findings.append(Finding(
                rule="compile-budget", target=f"drive:{drive}",
                message=(f"stale budget pin `{name}` — program is no "
                         f"longer reachable from this drive config; "
                         f"{hint}")))
    return findings


def format_compile_table(measured: Dict[str, Dict[str, int]],
                         budgets: Dict) -> str:
    lines = [f"{'drive':<14} {'programs':>8} {'signatures':>10} "
             f"{'max_compiles':>12}"]
    for drive, programs in sorted(measured.items()):
        entry = budgets.get(drive, {})
        mc = entry.get("max_compiles", "-")
        lines.append(f"{drive:<14} {len(programs):>8} "
                     f"{sum(programs.values()):>10} {str(mc):>12}")
    return "\n".join(lines)


def measure_drive_compiles(drive: str, repo_root: str,
                           rounds: int = 10) -> int:
    """Ground-truth compile count for a runtime drive config: run the CLI
    drive in a fresh subprocess (jit caches are process-global, so in-process
    back-to-back drives under-count) with graft-trace on, and count the
    trace's compile-request events."""
    import tempfile
    cli = RUNTIME_DRIVE_CLI[drive].replace("--comm_round 10",
                                           f"--comm_round {rounds}")
    with tempfile.TemporaryDirectory() as td:
        # main_fedavg's tracer always writes <run_dir>/TRACE.jsonl, and
        # setup_run() turns the jax.monitoring -> compile_cache forwarding on
        trace = os.path.join(td, "TRACE.jsonl")
        cmd = [sys.executable, "-m", "fedml_tpu.experiments.main_fedavg",
               "--run_dir", td, "--seed", "0",
               "--dataset", "mnist", "--data_dir", "./data",
               "--model", "lr", "--client_num_in_total", "8",
               "--client_num_per_round", "8", "--epochs", "1",
               "--batch_size", "4", "--frequency_of_the_test", "5",
               ] + cli.split()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
        subprocess.run(cmd, cwd=repo_root, env=env, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        from fedml_tpu.telemetry.report import load_trace
        records = load_trace(trace)
        return sum(1 for r in records
                   if r.get("kind") == "compile_cache"
                   and str(r.get("name", "")).endswith(
                       "compile_requests_use_cache"))


def run_compile(repo_root: str, fast: bool = False,
                targets: Optional[Sequence[str]] = None,
                update_budgets: bool = False,
                measure: bool = False) -> Tuple[Report, Dict]:
    """The --compile engine: AST compile rules over the tree + static
    program enumeration vs COMPILE_BUDGET.json. With `measure`, also re-runs
    the four runtime drive configs in subprocesses to refresh their
    `max_compiles` ceilings (slow — minutes)."""
    from fedml_tpu.analysis.targets import (DRIVE_CONFIGS,
                                            enumerate_drive_programs)
    report = Report()

    report.extend(lint_compile_dir(repo_root))
    report.mark("ast:compile-rules")

    drives = list(targets) if targets else list(DRIVE_CONFIGS)
    if fast:
        drives = [d for d in drives if d in RUNTIME_DRIVE_CLI]
    measured = {}
    for drive in drives:
        measured[drive] = enumerate_drive_programs(drive)
        report.mark(f"drive:{drive}")

    budgets = load_budgets(repo_root)
    if update_budgets:
        ceilings = None
        if measure:
            ceilings = {d: measure_drive_compiles(d, repo_root)
                        for d in drives if d in RUNTIME_DRIVE_CLI}
        budgets = make_budgets(measured, existing=budgets,
                               max_compiles=ceilings)
        with open(os.path.join(repo_root, BUDGET_FILE), "w") as f:
            json.dump(budgets, f, indent=2)
            f.write("\n")
    report.extend(check_budgets(measured, budgets))
    return report, measured
