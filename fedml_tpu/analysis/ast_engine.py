"""Source-level lint rules over fedml_tpu/ and tools/.

Traced-root detection: a function is "traced" when it is jit-decorated
(`@jax.jit`, `@partial(jax.jit, ...)`, `@nn.jit`) or its NAME is passed to
a tracing combinator (`jax.jit(f)`, `jax.vmap`, `jax.grad`,
`jax.value_and_grad`, `jax.lax.scan/map/fori_loop/while_loop/cond`,
`jax.checkpoint`, `shard_map`). Tracedness propagates through the
intra-module call graph: a helper called (by name) from a traced function
is traced too. Nested `def`s inherit their enclosing function's
tracedness.

Rules (all suppressible with `# graft-lint: disable=<rule> -- <reason>` on
the line or the line above; the reason is mandatory — a bare disable is
itself the `bare-suppression` finding):

- `host-transfer`: `.block_until_ready()`, `jax.device_get`, `.item()`,
  `np.asarray`/`np.array`/`onp.asarray`, and `float()`/`int()` applied to
  a parameter of the traced function — each forces a host sync (or a
  ConcretizationError) inside code that is supposed to stay on device.
- `traced-loop`: `for _ in <param>` inside a traced function — unrolls at
  trace time into O(n) HLO and retraces when n changes; use lax.scan.
- `sync-idiom`: `float(np.asarray(x))` ANYWHERE (traced or not) — a
  double host transfer; `jax.block_until_ready(x)` (no copy) or a single
  `jax.device_get` is always what's meant.
- `bare-suppression`: a `# graft-lint: disable=<rule>` comment without a
  `-- <reason>` tail — every suppression must say WHY the rule is wrong
  here, or the next reader deletes the comment and reintroduces the bug.
- `blocking-fetch-in-drive-loop` (algorithms/ drivers only): per-item
  `float()`/`int()`/`np.asarray()`/`.item()` host syncs inside `for`/
  comprehension iteration, or `float(jnp...)` anywhere inside a loop — the
  UNTRACED drive-loop half of the host-sync story (the jaxpr host-sync rule
  only sees traced code). Each such call is one blocking device round trip
  per item through the driver tunnel; the blessed idiom is ONE
  `jax.device_get` of the whole tree with host-side iteration —
  `{k: float(v) for k, v in jax.device_get(m).items()}` is clean because
  the iterable resolves everything in a single transfer.
- `naked-timer-in-drive-loop` (algorithms/ drivers only): raw
  `time.time()`/`time.perf_counter()` reads inside a drive loop — async
  dispatch makes them measure the tunnel, not the device. Blessed: the
  telemetry Span API and `jax.block_until_ready`-bracketed timers.
- `unschema-event`: a `tracer.event(...)` / `telemetry.emit(...)` call whose
  literal kind string is not registered in EVENT_SCHEMAS — the emit raises
  ValueError the FIRST time it fires at runtime, which for error-path events
  (reconnects, rollbacks) is exactly when you can least afford a crash.
  Non-literal kinds (the seam's own `tracer.event(kind, ...)` forward) are
  skipped: the rule is a static spelling check, not a dataflow analysis.
- `unregistered-codec` (algorithms/, parallel/, serving/ only): a direct
  `Int8Codec(...)` / `TopKCodec(...)` constructor call outside
  `fedml_tpu/codecs/` — codecs must come from `fedml_tpu.codecs.make_codec`
  so the CLI/config name, the COMMS/COMPILE budget program names, and the
  codec-off bit-identity contract stay in sync; a hand-built codec with
  ad-hoc parameters would run under a budget pin measured for different
  wire bytes.
- `personal-state-in-federated-tree`: a personal-adapter collection (any
  argument whose name mentions "personal") handed to a federated-tree
  surface — the aggregator/collective tail (`psum`, `pmean`, `all_reduce`,
  `aggregate`, `masked_psum_tail`), the update-codec encode path (`encode`,
  `wrap_codec`), or checkpointing (`save_checkpoint`). Personal rows are
  client-private BY CONTRACT (graft-pfl): the aggregator sees only trained
  effective params, the wire carries zero extra bytes, and persistence is
  the mmap adapter bank — a personal tree reaching any of those surfaces
  either leaks private state into the global model/checkpoint or breaks
  the pinned COMMS twin equality. Blessed path: `models/adapter_bank.py`
  (the bank IS the sanctioned persistence for personal rows).
- `full-store-materialize`: `np.asarray(store.x)` / `np.stack(...)` /
  `store.x[:]` whole-store reads over a packed/streaming client store —
  the data plane's O(cohort) contract (data/packed_store.py) dies the
  moment someone materializes `.x` wholesale. Blessed, call-graph-aware:
  code inside a function named `materialize` or `__array__` (and the
  closure of local helpers those call) is the one sanctioned whole-store
  path. Bounded reads (`store.x[idx]`, `.x[:1, 0]`) are clean.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from fedml_tpu.analysis.core import Finding, is_suppressed, iter_suppressions

_TRACING_CALLS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "scan", "map", "fori_loop", "while_loop", "cond", "switch", "shard_map",
    "custom_vmap", "associated_scan", "associative_scan",
}
_NP_ALIASES = {"np", "onp", "numpy"}
_HOST_ATTR_CALLS = {"block_until_ready", "item"}  # x.block_until_ready(), x.item()


def _dotted(node) -> str:
    """'jax.lax.scan' for an Attribute/Name chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_tracing_call(call: ast.Call) -> bool:
    name = _dotted(call.func)
    return bool(name) and name.split(".")[-1] in _TRACING_CALLS


def _is_jit_decorator(dec) -> bool:
    if isinstance(dec, ast.Call):
        name = _dotted(dec.func)
        tail = name.split(".")[-1] if name else ""
        if tail in {"jit", "pmap", "checkpoint", "remat"}:
            return True
        if tail == "partial" and dec.args:
            inner = _dotted(dec.args[0])
            return bool(inner) and inner.split(".")[-1] in _TRACING_CALLS
        return False
    name = _dotted(dec)
    return bool(name) and name.split(".")[-1] in {"jit", "pmap", "checkpoint",
                                                  "remat"}


class _FnInfo:
    def __init__(self, node: ast.FunctionDef, parent: Optional["_FnInfo"]):
        self.node = node
        self.parent = parent
        self.traced = any(_is_jit_decorator(d) for d in node.decorator_list)
        self.calls: Set[str] = set()  # local function names this fn calls
        self.params: Set[str] = {
            a.arg for a in (node.args.args + node.args.posonlyargs
                            + node.args.kwonlyargs)}


class _Collector(ast.NodeVisitor):
    """Pass 1: find every function, its decorators, its local calls, and
    which names get handed to tracing combinators anywhere in the module."""

    def __init__(self):
        self.fns: Dict[str, _FnInfo] = {}   # qualified-by-nesting name
        self.by_name: Dict[str, List[_FnInfo]] = {}
        self.traced_names: Set[str] = set()
        self._stack: List[_FnInfo] = []

    def visit_FunctionDef(self, node: ast.FunctionDef):
        info = _FnInfo(node, self._stack[-1] if self._stack else None)
        self.fns[node.name + f"@{node.lineno}"] = info
        self.by_name.setdefault(node.name, []).append(info)
        self._stack.append(info)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        if self._stack:
            callee = _dotted(node.func)
            if callee and "." not in callee:
                self._stack[-1].calls.add(callee)
        if _is_tracing_call(node):
            # every plain-name argument to jit/vmap/scan/... is traced
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    self.traced_names.add(a.id)
                elif isinstance(a, ast.Call):  # jit(partial(f, ...)) etc.
                    inner = _dotted(a.func)
                    if inner and inner.split(".")[-1] == "partial" and a.args:
                        if isinstance(a.args[0], ast.Name):
                            self.traced_names.add(a.args[0].id)
        self.generic_visit(node)


def _propagate(col: _Collector) -> None:
    for name in col.traced_names:
        for info in col.by_name.get(name, []):
            info.traced = True
    # nested defs inherit; call-graph closure over local names
    changed = True
    while changed:
        changed = False
        for info in col.fns.values():
            if not info.traced and info.parent is not None and info.parent.traced:
                info.traced = changed = True
            if info.traced:
                for callee in info.calls:
                    for ci in col.by_name.get(callee, []):
                        if not ci.traced:
                            ci.traced = changed = True


def _is_np_asarray(call: ast.Call) -> bool:
    name = _dotted(call.func)
    if not name or "." not in name:
        return False
    head, tail = name.split(".", 1)
    return head in _NP_ALIASES and tail in {"asarray", "array"}


class _RuleRunner(ast.NodeVisitor):
    """Pass 2: emit findings inside one traced function body (not into
    nested defs — they're visited as their own _FnInfo)."""

    def __init__(self, info: _FnInfo, path: str, lines: List[str],
                 findings: List[Finding]):
        self.info = info
        self.path = path
        self.lines = lines
        self.findings = findings

    def _emit(self, rule: str, node, msg: str):
        if not is_suppressed(self.lines, node.lineno, rule):
            self.findings.append(
                Finding(rule, f"{self.path}:{node.lineno}", msg))

    def visit_FunctionDef(self, node):
        if node is not self.info.node:
            return  # nested def handled by its own runner
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if (isinstance(node.func, ast.Attribute) and tail in _HOST_ATTR_CALLS
                and not name.startswith("jax.")):
            self._emit("host-transfer", node,
                       f".{tail}() in traced code forces a host sync")
        elif name == "jax.device_get":
            self._emit("host-transfer", node,
                       "jax.device_get in traced code forces a host sync")
        elif _is_np_asarray(node):
            self._emit("host-transfer", node,
                       f"{name}() in traced code pulls the array to host "
                       f"(and breaks the trace)")
        elif isinstance(node.func, ast.Name) and node.func.id in {"float", "int"}:
            if node.args and self._mentions_param(node.args[0]):
                self._emit("host-transfer", node,
                           f"{node.func.id}() on a traced argument "
                           f"concretizes it — keep it a 0-d array")
        self.generic_visit(node)

    def _mentions_param(self, expr) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.info.params:
                return True
        return False

    def visit_For(self, node: ast.For):
        it = node.iter
        if isinstance(it, ast.Name) and it.id in self.info.params:
            self._emit("traced-loop", node,
                       f"Python for-loop over traced argument {it.id!r} "
                       f"unrolls at trace time — use jax.lax.scan")
        self.generic_visit(node)


class _SyncIdiom(ast.NodeVisitor):
    """float(np.asarray(x)) anywhere in the module — traced or not."""

    def __init__(self, path: str, lines: List[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings

    def visit_Call(self, node: ast.Call):
        if (isinstance(node.func, ast.Name)
                and node.func.id in {"float", "int"} and node.args):
            inner = node.args[0]
            # unwrap trailing .ravel()[0] / indexing around the asarray
            while True:
                if isinstance(inner, ast.Subscript):
                    inner = inner.value
                elif (isinstance(inner, ast.Call)
                      and isinstance(inner.func, ast.Attribute)
                      and not _is_np_asarray(inner)):
                    inner = inner.func.value
                else:
                    break
            if isinstance(inner, ast.Call) and _is_np_asarray(inner):
                if not is_suppressed(self.lines, node.lineno, "sync-idiom"):
                    self.findings.append(Finding(
                        "sync-idiom", f"{self.path}:{node.lineno}",
                        "float(np.asarray(...)) double-transfers; use "
                        "jax.block_until_ready (no copy) or one device_get"))
        self.generic_visit(node)


class _DriveLoopFetch(ast.NodeVisitor):
    """blocking-fetch-in-drive-loop: per-item host syncs in the untraced
    drive loops of algorithms/ drivers.

    Two triggers, one rule:
    - a `float()`/`int()`/`np.asarray()`/`np.array()`/`.item()` whose
      argument mentions the target variable of an enclosing `for` statement
      or comprehension generator — the per-item fetch shape
      (`{k: float(v) for k, v in metrics.items()}` syncs once per key);
    - any `float(jnp...)`/`int(jnp...)`/`np.asarray(jnp...)` inside a loop
      (for/while/comprehension) — a device value resolved per iteration
      regardless of what drives the loop.

    A loop/generator whose iterable expression contains a `device_get` call
    blesses its targets: the transfer already happened in one batch, so
    host-side `float()` over the fetched tree is free. Shape/size
    arithmetic (`int(np.prod(l.shape))` and friends) never touches device
    data and is skipped.
    """

    def __init__(self, path: str, lines: List[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings
        self._frames: List[tuple] = []  # (target_names, blessed)
        self._loops = 0

    @staticmethod
    def _names(node) -> Set[str]:
        return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

    @staticmethod
    def _blessed(iter_node) -> bool:
        for sub in ast.walk(iter_node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func)
                if name and name.split(".")[-1] == "device_get":
                    return True
        return False

    @staticmethod
    def _shape_math(expr) -> bool:
        # int(np.prod(l.shape[1:])) etc. — static metadata, no device data
        return any(isinstance(sub, ast.Attribute)
                   and sub.attr in {"shape", "ndim", "size", "nbytes"}
                   for sub in ast.walk(expr))

    @staticmethod
    def _has_jnp_call(expr) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func)
                if name.startswith("jnp.") or name.startswith("jax.numpy."):
                    return True
        return False

    def _emit(self, node, what: str):
        if not is_suppressed(self.lines, node.lineno,
                             "blocking-fetch-in-drive-loop"):
            self.findings.append(Finding(
                "blocking-fetch-in-drive-loop", f"{self.path}:{node.lineno}",
                f"{what} inside a drive loop is one blocking device->host "
                "round trip per item; fetch once with jax.device_get(tree) "
                "and iterate the host copy"))

    # ---- loop frames ------------------------------------------------------
    def visit_For(self, node: ast.For):
        self.visit(node.iter)  # the iterable belongs to the OUTER scope
        self._frames.append((self._names(node.target),
                             self._blessed(node.iter)))
        self._loops += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._loops -= 1
        self._frames.pop()

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While):
        self.visit(node.test)
        self._loops += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._loops -= 1

    def _visit_comprehension(self, node, bodies):
        for gen in node.generators:
            self.visit(gen.iter)
        for gen in node.generators:
            self._frames.append((self._names(gen.target),
                                 self._blessed(gen.iter)))
        self._loops += 1
        for body in bodies:
            self.visit(body)
        for gen in node.generators:
            for cond in gen.ifs:
                self.visit(cond)
        self._loops -= 1
        for _ in node.generators:
            self._frames.pop()

    def visit_ListComp(self, node):
        self._visit_comprehension(node, [node.elt])

    visit_SetComp = visit_ListComp
    visit_GeneratorExp = visit_ListComp

    def visit_DictComp(self, node):
        self._visit_comprehension(node, [node.key, node.value])

    # ---- the fetch calls --------------------------------------------------
    def visit_Call(self, node: ast.Call):
        arg = None
        what = None
        if (isinstance(node.func, ast.Name)
                and node.func.id in {"float", "int"} and node.args):
            arg, what = node.args[0], f"{node.func.id}()"
        elif _is_np_asarray(node) and node.args:
            arg, what = node.args[0], f"{_dotted(node.func)}()"
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            arg, what = node.func.value, ".item()"
        if arg is not None and not self._shape_math(arg):
            mentioned = self._names(arg)
            per_item = any(targets & mentioned
                           for targets, blessed in self._frames
                           if not blessed)
            in_any_blessed = any(targets & mentioned
                                 for targets, blessed in self._frames
                                 if blessed)
            if per_item and not in_any_blessed:
                self._emit(node, f"per-item {what}")
            elif self._loops and self._has_jnp_call(arg):
                self._emit(node, f"{what} on a jnp expression")
        self.generic_visit(node)


class _NakedTimer(ast.NodeVisitor):
    """naked-timer-in-drive-loop: raw wall-clock reads inside algorithms/
    drive loops.

    `time.time()` / `time.perf_counter()` / `time.monotonic()` /
    `time.process_time()` bracketing a jitted call measures DISPATCH
    latency, not compute — jax returns futures, so the timer closes before
    the device finishes. That is exactly how the r01–r05 throughput
    trajectory went flat without anyone noticing (PERF.md): the numbers
    timed the tunnel, and a regression in the round program hid behind
    async dispatch. Two blessed idioms:

    - the telemetry Span API (`tracer.span(...)` context managers,
      `tracer.now()` — spans are what the perf gate audits); a loop whose
      body opens a `.span(...)` / `.round(...)` context is considered
      instrumented and its remaining timer reads are measurement plumbing;
    - a loop body that calls `jax.block_until_ready(...)` — the timer pair
      then measures completed device work (tools/bench_* style).
    """

    _TIMER_TAILS = {"time", "perf_counter", "monotonic", "process_time"}
    _BLESSING_ATTRS = {"block_until_ready", "span", "round"}

    def __init__(self, path: str, lines: List[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings
        self._blessed_loops = 0
        self._loops = 0

    @classmethod
    def _loop_blessed(cls, node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func)
                if name and name.split(".")[-1] in cls._BLESSING_ATTRS:
                    return True
        return False

    def _visit_loop(self, node, parts):
        blessed = self._loop_blessed(node)
        self._loops += 1
        self._blessed_loops += blessed
        for stmt in parts:
            self.visit(stmt)
        self._blessed_loops -= blessed
        self._loops -= 1

    def visit_For(self, node: ast.For):
        self.visit(node.iter)
        self._visit_loop(node, node.body + node.orelse)

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While):
        self.visit(node.test)
        self._visit_loop(node, node.body + node.orelse)

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        if (name.startswith("time.")
                and name.split(".")[-1] in self._TIMER_TAILS
                and self._loops and not self._blessed_loops
                and not is_suppressed(self.lines, node.lineno,
                                      "naked-timer-in-drive-loop")):
            self.findings.append(Finding(
                "naked-timer-in-drive-loop", f"{self.path}:{node.lineno}",
                f"{name}() in a drive loop times async dispatch, not "
                "compute — record a telemetry span (tracer.span/round) or "
                "bracket the timed region with jax.block_until_ready"))
        self.generic_visit(node)


def _first_index(sub: ast.Subscript):
    """The leading index expression of `a[i, j, ...]` (or `a[i]`)."""
    sl = sub.slice
    if isinstance(sl, ast.Tuple):
        return sl.elts[0] if sl.elts else None
    return sl


def _is_full_slice(node) -> bool:
    """True for a bare `:` — the whole-first-axis read."""
    return (isinstance(node, ast.Slice)
            and node.lower is None and node.upper is None)


def _blessed_store_ranges(col: _Collector) -> List[tuple]:
    """(lineno, end_lineno) spans of the blessed whole-store readers:
    functions named `materialize` or `__array__` plus the call-graph
    closure of the local helpers they invoke (same propagation idea as
    tracedness — a helper that materialize() delegates to is blessed
    too)."""
    frontier = [info for name in ("materialize", "__array__")
                for info in col.by_name.get(name, [])]
    blessed = set()
    while frontier:
        info = frontier.pop()
        if id(info) in {id(b) for b in blessed}:
            continue
        blessed.add(info)
        for callee in info.calls:
            frontier.extend(col.by_name.get(callee, []))
    return [(i.node.lineno, i.node.end_lineno or i.node.lineno)
            for i in blessed]


class _FullStoreMaterialize(ast.NodeVisitor):
    """full-store-materialize: whole-store reads outside materialize().

    Two triggers, one rule:
    - a gather call (`np`/`onp`/`numpy`/`jnp` × `asarray`/`array`/`stack`)
      whose argument contains a `.x` attribute that is bare or first-indexed
      with a full `:` slice — `np.asarray(store.x)` copies EVERY client row
      through the facade;
    - any `<expr>.x[...]` subscript whose leading index is a full `:` —
      `.x[:]` and `.x[:, :cap]` read the whole first axis no matter how the
      rest is bounded.

    Bounded first indices (`store.x[idx]`, `.x[k]`, `.x[:64]`) are the
    select()-shaped access pattern and stay clean. Findings inside the
    blessed ranges (functions named `materialize`/`__array__` and their
    local-callee closure) are skipped — that is the ONE place a full read
    is the point, and it enforces its own byte budget.
    """

    _GATHER_HEADS = _NP_ALIASES | {"jnp"}
    _GATHER_TAILS = {"asarray", "array", "stack"}

    def __init__(self, path: str, lines: List[str], findings: List[Finding],
                 blessed_ranges: List[tuple]):
        self.path = path
        self.lines = lines
        self.findings = findings
        self.blessed_ranges = blessed_ranges
        self._flagged_lines: Set[int] = set()  # call-level finding emitted

    def _blessed(self, lineno: int) -> bool:
        return any(lo <= lineno <= hi for lo, hi in self.blessed_ranges)

    def _emit(self, node, msg: str):
        if self._blessed(node.lineno):
            return
        if node.lineno in self._flagged_lines:
            return
        if not is_suppressed(self.lines, node.lineno,
                             "full-store-materialize"):
            self._flagged_lines.add(node.lineno)
            self.findings.append(Finding(
                "full-store-materialize", f"{self.path}:{node.lineno}", msg))

    def _is_gather(self, call: ast.Call) -> bool:
        name = _dotted(call.func)
        if not name or "." not in name:
            return False
        head, tail = name.split(".", 1)
        return head in self._GATHER_HEADS and tail in self._GATHER_TAILS

    @classmethod
    def _whole_x_reads(cls, expr) -> List[ast.Attribute]:
        """`.x` attributes in `expr` read without a bounding first index:
        bare (`p.x`) or full-sliced (`p.x[:, ...]`)."""
        bounded = set()
        for sub in ast.walk(expr):
            if (isinstance(sub, ast.Subscript)
                    and isinstance(sub.value, ast.Attribute)
                    and sub.value.attr == "x"
                    and not _is_full_slice(_first_index(sub))):
                bounded.add(id(sub.value))
        return [a for a in ast.walk(expr)
                if isinstance(a, ast.Attribute) and a.attr == "x"
                and id(a) not in bounded]

    def visit_Call(self, node: ast.Call):
        if self._is_gather(node):
            exprs = list(node.args) + [k.value for k in node.keywords]
            if any(self._whole_x_reads(e) for e in exprs):
                self._emit(node,
                           f"{_dotted(node.func)}() over a store's .x "
                           "materializes every client row — select() the "
                           "cohort, or route through the blessed "
                           "materialize() helper")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if (isinstance(node.value, ast.Attribute) and node.value.attr == "x"
                and _is_full_slice(_first_index(node))):
            self._emit(node,
                       ".x[:] reads the whole first axis of a store — "
                       "index with the sampled cohort (store.x[idx]) or "
                       "use materialize()")
        self.generic_visit(node)


class _UnschemaEvent(ast.NodeVisitor):
    """unschema-event: literal event kinds must exist in EVENT_SCHEMAS.

    Matches the two emit surfaces — the seam (`telemetry.emit(...)` or a
    bare `emit(...)` from `from fedml_tpu.telemetry import emit`) and tracer
    methods (`<anything>.event(...)`, e.g. `tracer.event`,
    `self.tracer.event`). The kind is the first positional string literal,
    or the `kind=` keyword; calls passing a variable are skipped (the
    tracer's own runtime check owns those)."""

    def __init__(self, path: str, lines: List[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings
        # late import keeps analysis importable even if telemetry grows
        # heavier deps; tracer.py is stdlib-only today
        from fedml_tpu.telemetry.tracer import EVENT_SCHEMAS
        self.schemas = EVENT_SCHEMAS

    @staticmethod
    def _is_emit_call(name: str) -> bool:
        if name == "emit":
            return True
        parts = name.split(".")
        if parts[-1] == "emit" and parts[-2:-1] == ["telemetry"]:
            return True
        # tracer.event / self.tracer.event — but not a bare event() name
        return parts[-1] == "event" and len(parts) > 1

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        if name and self._is_emit_call(name):
            kind = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                kind = node.args[0].value
            else:
                for kw in node.keywords:
                    if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        kind = kw.value.value
            if kind is not None and kind not in self.schemas \
                    and not is_suppressed(self.lines, node.lineno,
                                          "unschema-event"):
                self.findings.append(Finding(
                    "unschema-event", f"{self.path}:{node.lineno}",
                    f"event kind {kind!r} is not in EVENT_SCHEMAS — this "
                    f"call raises ValueError the first time it fires; "
                    f"register the kind (with its required fields) in "
                    f"telemetry/tracer.py"))
        self.generic_visit(node)


class _UnregisteredCodec(ast.NodeVisitor):
    """unregistered-codec: update codecs are built ONLY by make_codec.

    Scope: the codec-armed data-plane packages (algorithms/, parallel/,
    serving/). A direct `Int8Codec(...)` / `TopKCodec(...)` call there
    bypasses the registry — its bits/k come from call-site literals instead
    of FedConfig, so the `--update_codec` CLI, the budget program names
    (`...,int8]` / `...,topk64]`), and the codec-off bit-identity tests all
    describe a codec the round isn't actually running. Dotted spellings
    (`int8.Int8Codec`, `codecs.topk.TopKCodec`) match too; the
    `CodecAggregator` wrapper is exempt — round builders construct it
    around a make_codec-produced codec by design."""

    _CODEC_CTORS = {"Int8Codec", "TopKCodec"}

    def __init__(self, path: str, lines: List[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        if name and name.split(".")[-1] in self._CODEC_CTORS \
                and not is_suppressed(self.lines, node.lineno,
                                      "unregistered-codec"):
            self.findings.append(Finding(
                "unregistered-codec", f"{self.path}:{node.lineno}",
                f"`{name}(...)` constructs an update codec directly — build "
                f"it with `fedml_tpu.codecs.make_codec(cfg.update_codec, "
                f"cfg)` so the codec's parameters come from FedConfig and "
                f"match the COMMS/COMPILE budget program twins"))
        self.generic_visit(node)


class _PersonalStateInFederatedTree(ast.NodeVisitor):
    """personal-state-in-federated-tree: personal rows never federate.

    The graft-pfl privacy/bit-identity contract has three walls: personal
    adapter rows are never summed into the global tree (the aggregator
    input is the TRAINED effective params, the delta returns unaggregated),
    never encoded onto the wire (the COMMS twin gate pins pfl collective
    bytes == non-pfl), and never ride the global checkpoint (the mmap bank
    owns persistence, byte-stably). This rule is the static tripwire for
    all three: a call whose dotted tail is one of the federated-tree
    surfaces with an argument that names personal state is a contract
    breach no matter what the runtime gates happen to measure that day.
    Matching is by identifier substring ("personal" in a Name or attribute
    chain inside the argument), so `new_personal`, `staged.personal`,
    `personal_rows` all trip; calls inside models/adapter_bank.py are
    blessed (lint_source path-scopes the visitor away from it)."""

    _SURFACE_TAILS = {"psum", "pmean", "all_reduce", "aggregate",
                      "masked_psum_tail", "encode", "wrap_codec",
                      "save_checkpoint"}

    def __init__(self, path: str, lines: List[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings

    @staticmethod
    def _personal_names(expr) -> List[str]:
        names = []
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and "personal" in sub.id:
                names.append(sub.id)
            elif isinstance(sub, ast.Attribute) and "personal" in sub.attr:
                names.append(sub.attr)
        return names

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if tail in self._SURFACE_TAILS:
            exprs = list(node.args) + [k.value for k in node.keywords
                                       if k.value is not None]
            hits = [n for e in exprs for n in self._personal_names(e)]
            if hits and not is_suppressed(self.lines, node.lineno,
                                          "personal-state-in-federated-tree"):
                self.findings.append(Finding(
                    "personal-state-in-federated-tree",
                    f"{self.path}:{node.lineno}",
                    f"personal adapter state ({hits[0]!r}) reaches the "
                    f"federated-tree surface `{name}(...)` — personal rows "
                    f"are client-private: they never aggregate, never hit "
                    f"the update codec, and persist only through "
                    f"models/adapter_bank.py"))
        self.generic_visit(node)


def lint_source(source: str, path: str) -> List[Finding]:
    """Run all AST rules on one module's source text."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("host-transfer", f"{path}:{e.lineno or 0}",
                        f"unparseable module: {e.msg}", severity="warning")]
    lines = source.splitlines()
    col = _Collector()
    col.visit(tree)
    _propagate(col)
    findings: List[Finding] = []
    for info in col.fns.values():
        if info.traced:
            _RuleRunner(info, path, lines, findings).visit(info.node)
    _SyncIdiom(path, lines, findings).visit(tree)
    _UnschemaEvent(path, lines, findings).visit(tree)
    # the bank is the ONE sanctioned persistence path for personal rows —
    # everywhere else, personal state reaching a federated surface is a
    # privacy/bit-identity breach (see _PersonalStateInFederatedTree)
    norm = path.replace(os.sep, "/")
    if not norm.endswith("models/adapter_bank.py"):
        _PersonalStateInFederatedTree(path, lines, findings).visit(tree)
    _FullStoreMaterialize(path, lines, findings,
                          _blessed_store_ranges(col)).visit(tree)
    # drive-loop fetch hygiene is an algorithms/-driver contract: that is
    # where the untraced round loops live (lint_tree hands us repo-relative
    # paths, so the scope survives any checkout location)
    parts = path.replace(os.sep, "/").split("/")
    if "algorithms" in parts:
        _DriveLoopFetch(path, lines, findings).visit(tree)
        _NakedTimer(path, lines, findings).visit(tree)
    # codec registry discipline is a data-plane contract: these are the
    # packages whose rounds the codec budget twins pin (codecs/ itself is
    # out of scope — it's where the constructors legitimately live)
    if {"algorithms", "parallel", "serving"} & set(parts):
        _UnregisteredCodec(path, lines, findings).visit(tree)
    # compile-layer rules (engine #4) ride the same sweep so LINT.json and
    # the repo-clean pins cover them; late import avoids a module cycle
    from fedml_tpu.analysis.compile_engine import lint_compile_tree
    findings.extend(lint_compile_tree(tree, path, lines))
    for lineno, rules, reason in iter_suppressions(source):
        if reason is None and not is_suppressed(lines, lineno,
                                                "bare-suppression"):
            findings.append(Finding(
                "bare-suppression", f"{path}:{lineno}",
                f"suppression of {', '.join(sorted(rules))} has no reason — "
                "write `# graft-lint: disable=<rule> -- <why it is safe "
                "here>`"))
    return findings


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    with open(path) as f:
        src = f.read()
    return lint_source(src, rel or path)


def lint_tree(root: str, subdirs: Optional[List[str]] = None) -> List[Finding]:
    """Lint every .py under `root` (optionally restricted to `subdirs`),
    reporting repo-relative paths."""
    findings: List[Finding] = []
    tops = subdirs or [""]
    for top in tops:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in {"__pycache__", ".git", ".pytest_cache"}]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    findings += lint_file(full, os.path.relpath(full, root))
    return findings
