"""The repo's lintable surface — what `python -m fedml_tpu.analysis` checks.

One table (MODEL_EXAMPLES, moved here from tests/test_dtype_registry.py so
the test and the CLI share it) plus builders that trace the repo's actual
jitted programs: engine round runners, the silo-grouped round, every
aggregator's round, the chunked runner's donated chunk dispatch, the DARTS
supernet, and a 3-round retrace drive.

Everything traces abstractly (eval_shape / make_jaxpr on
ShapeDtypeStructs) except the donation and retrace checks, which need the
real jit machinery — those use the tiniest model in the registry.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.analysis.core import Finding, Report
from fedml_tpu.analysis.jaxpr_engine import (
    check_donation,
    check_retrace,
    lint_jaxpr,
)
from fedml_tpu.analysis.partition import (
    check_partition_coverage,
    model_variable_shapes,
)
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.models.registry import available_models, create_model

# model name -> (example input shape, input dtype, extra factory kwargs).
# Every registered model MUST have a row (enforced by tests/test_lint.py and
# tests/test_dtype_registry.py) — a new factory that drops the dtype knob
# fails the lint, not a bench three rounds later.
MODEL_EXAMPLES = {
    "lr": ((2, 32), jnp.float32, {}),
    "mlp": ((2, 32), jnp.float32, {}),
    "purchasemlp": ((2, 600), jnp.float32, {}),
    "texasmlp": ((2, 6169), jnp.float32, {}),
    "cnn_fedavg": ((2, 28, 28, 1), jnp.float32, {}),
    "cnn": ((2, 28, 28, 1), jnp.float32, {}),
    "cnn_cifar": ((2, 32, 32, 3), jnp.float32, {}),
    "har_cnn": ((2, 128, 9), jnp.float32, {}),
    "resnet20": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet32": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet44": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet56": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet56_s2d": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet110": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet18": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet34": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet50": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet18_gn": ((2, 24, 24, 3), jnp.float32, {}),
    "mobilenet": ((2, 32, 32, 3), jnp.float32, {}),
    "mobilenet_v3": ((2, 32, 32, 3), jnp.float32, {"mode": "SMALL"}),
    "efficientnet": ((2, 32, 32, 3), jnp.float32,
                     {"variant": "efficientnet-b0"}),
    "vgg11": ((2, 32, 32, 3), jnp.float32, {}),
    "vgg16": ((2, 32, 32, 3), jnp.float32, {}),
    "deeplab": ((2, 32, 32, 3), jnp.float32, {}),
    "fcn": ((2, 16, 16, 3), jnp.float32, {}),
    "rnn": ((2, 16), jnp.int32, {"vocab_size": 90}),
    "rnn_stackoverflow": ((2, 12), jnp.int32, {}),
    "transformer_nwp": ((2, 16), jnp.int32, {}),
}


def models_missing_examples() -> List[str]:
    return sorted(set(available_models()) - set(MODEL_EXAMPLES))


def forward_jaxpr(module, shape, in_dtype):
    """Abstract forward trace of a flax module (eval_shape init -> make_jaxpr
    of apply) — zero FLOPs, works for any registry model."""
    rng = jax.random.PRNGKey(0)
    x = jax.ShapeDtypeStruct(shape, in_dtype)
    var_shapes = jax.eval_shape(
        lambda: module.init({"params": rng, "dropout": rng},
                            jnp.zeros(shape, in_dtype), train=False))
    variables = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes)
    return jax.make_jaxpr(
        lambda v, xx: module.apply(v, xx, train=False))(variables, x).jaxpr


def model_jaxpr(name: str, dtype: str = "bfloat16"):
    shape, in_dtype, kw = MODEL_EXAMPLES[name]
    module = create_model(name, output_dim=10, dtype=dtype, **kw)
    return forward_jaxpr(module, shape, in_dtype)


def darts_jaxpr():
    """The DARTS supernet is built directly by FedNASAPI (not via the
    registry) — its mixed-op tensordot path gets its own target."""
    from fedml_tpu.models.darts import DARTSNetwork, init_alphas

    net = DARTSNetwork(output_dim=10, channels=4, layers=2,
                       dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    an, ar = init_alphas(rng)
    x = jnp.zeros((2, 16, 16, 3))
    var_shapes = jax.eval_shape(
        lambda: net.init({"params": rng}, x, an, ar, train=False))
    variables = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes)
    return jax.make_jaxpr(
        lambda v, xx, a, b: net.apply(v, xx, a, b, train=False))(
        variables, jax.ShapeDtypeStruct(x.shape, x.dtype), an, ar).jaxpr


def _tiny_trainer(model: str, dtype: str, **kw):
    shape, in_dtype, extra = MODEL_EXAMPLES[model]
    extra = dict(extra, **kw)
    module = create_model(model, output_dim=10, dtype=dtype, **extra)
    return ClassificationTrainer(module), shape, in_dtype


def _abstract_round_args(trainer, shape, in_dtype, clients: int = 2,
                         n_max: int = 4):
    rng = jax.random.PRNGKey(0)
    var_shapes = jax.eval_shape(
        lambda: trainer.init(rng, jnp.zeros(shape, in_dtype)))
    gv = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes)
    x = jax.ShapeDtypeStruct((clients, n_max) + shape[1:], in_dtype)
    y = jax.ShapeDtypeStruct((clients, n_max), jnp.int32)
    counts = jax.ShapeDtypeStruct((clients,), jnp.int32)
    return gv, x, y, counts, rng


def round_jaxpr(model: str = "cnn", dtype: str = "bfloat16",
                aggregator_name: str = "fedavg",
                silo_threshold: int = 0):
    """Traced jaxpr of one full engine round (vmap(local_update) +
    aggregate) — or the silo-grouped round when silo_threshold > 0."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_round_fn

    trainer, shape, in_dtype = _tiny_trainer(model, dtype)
    cfg = FedConfig(model=model, batch_size=2, epochs=1, dtype=dtype)
    agg = make_aggregator(aggregator_name, cfg)
    if silo_threshold > 0:
        from fedml_tpu.algorithms.silo_grouped import (
            build_silo_round_fn, silo_trainer)

        round_fn = build_silo_round_fn(
            silo_trainer(trainer, silo_threshold), cfg, agg)
    else:
        round_fn = build_round_fn(trainer, cfg, agg)
    gv, x, y, counts, rng = _abstract_round_args(trainer, shape, in_dtype)
    agg_state = agg.init_state(gv)
    return jax.make_jaxpr(round_fn)(gv, agg_state, x, y, counts, rng).jaxpr


_POLICY = {"bfloat16": jnp.bfloat16, "float32": None}

# Aggregators all run on f32 params (the mixed-precision contract keeps
# aggregation full-precision), so their rounds lint without a dtype policy.
AGGREGATOR_NAMES = ("fedavg", "fedopt", "robust", "fednova")


def iter_jaxpr_targets(include_models: bool = True,
                       ) -> Iterator[Tuple[str, object, Optional[object]]]:
    """(target name, jaxpr, dtype policy or None) for every pure-jaxpr
    target. Order: cheap engine targets first, the 29-model sweep last."""
    yield ("engine.round[cnn,bf16,fedavg]",
           round_jaxpr("cnn", "bfloat16", "fedavg"), jnp.bfloat16)
    for agg in AGGREGATOR_NAMES:
        yield (f"engine.round[lr,f32,{agg}]",
               round_jaxpr("lr", "float32", agg), None)
    yield ("silo.round[resnet20,bf16,fedavg]",
           round_jaxpr("resnet20", "bfloat16", "fedavg", silo_threshold=32),
           jnp.bfloat16)
    yield ("darts.supernet[bf16]", darts_jaxpr(), jnp.bfloat16)
    if include_models:
        for name in sorted(MODEL_EXAMPLES):
            if name in available_models():
                yield (f"model:{name}[bf16]", model_jaxpr(name),
                       jnp.bfloat16)


def tensor_step_jaxpr(model: str = "transformer_nwp",
                      constrained: bool = True):
    """Traced jaxpr of the activation-sharded client step (tensor.step,
    parallel/tensor.py build_tensor_step_fn) on the 2x4 mesh, plus the
    tensor-axis size — the unconstrained-intermediate repo-clean pin.
    `constrained=False` builds the step WITHOUT its activation rule table
    (the lint fixture arm: same program, constraint hooks dark)."""
    from jax.sharding import Mesh

    from fedml_tpu.core.trainer import NWPTrainer
    from fedml_tpu.parallel.tensor import (TensorSharding,
                                           build_tensor_step_fn)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("clients", "tensor"))
    cfg = FedConfig(model=model, batch_size=2, epochs=1, dtype="float32",
                    tensor_shards=4)
    trainer = NWPTrainer(create_model(model, output_dim=10))
    step_fn = build_tensor_step_fn(
        trainer, cfg, TensorSharding.for_model(mesh, model),
        activation_rules="auto" if constrained else None)
    rng = jax.random.PRNGKey(0)
    var_shapes = jax.eval_shape(
        lambda: trainer.init(rng, jnp.zeros((2, 16), jnp.int32)))
    gv = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes)
    args = (gv, jax.ShapeDtypeStruct((2, 4, 16), jnp.int32),
            jax.ShapeDtypeStruct((2, 4, 16), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.int32), rng)
    return jax.make_jaxpr(step_fn)(*args).jaxpr, 4


def check_chunked_donation() -> List[Finding]:
    """The chunked runner's (variables, opt_state, steps) carry must lower
    as donated buffers — otherwise every chunk boundary pays a full-carry
    HBM copy and the 'zero device copies' contract in its docstring lies."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_chunked_round_runner

    trainer, shape, in_dtype = _tiny_trainer("lr", "float32")
    cfg = FedConfig(model="lr", batch_size=2, epochs=2, dtype="float32")
    runner = build_chunked_round_runner(
        trainer, cfg, make_aggregator("fedavg", cfg), epoch_chunk=1)
    rng = jax.random.PRNGKey(0)
    gv = trainer.init(rng, jnp.zeros(shape, in_dtype))
    c, n = 2, 4
    counts = jnp.full((c,), n, jnp.int32)
    stacked, opt_state, steps, erngs = runner.init_fn(gv, counts, rng)
    x = jnp.zeros((c, n) + shape[1:], in_dtype)
    y = jnp.zeros((c, n), jnp.int32)
    args = (stacked, opt_state, steps, gv["params"], x, y, counts,
            erngs[:, 0:1])
    return check_donation(
        runner.chunk_fn, args, "engine.chunked.chunk_fn[lr]",
        argnums=runner.chunk_donate_argnums)


def check_round_retrace(rounds: int = 3) -> List[Finding]:
    """Drive 3 same-shape rounds through build_round_fn and assert ONE
    compile — the compile-once-per-shape contract every bench assumes."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_round_fn

    trainer, shape, in_dtype = _tiny_trainer("lr", "float32")
    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32")
    round_fn = build_round_fn(trainer, cfg, make_aggregator("fedavg", cfg))
    rng = jax.random.PRNGKey(0)
    gv = trainer.init(rng, jnp.zeros(shape, in_dtype))
    c, n = 2, 4
    x = np.zeros((c, n) + shape[1:], np.float32)
    y = np.zeros((c, n), np.int32)
    counts = np.full((c,), n, np.int32)

    state = {"gv": gv, "agg": ()}

    def make_args(i):
        # fresh host arrays each round — exactly how the benches feed it;
        # only the rng VALUE changes, never a shape or dtype
        return (state["gv"], state["agg"], jnp.asarray(x), jnp.asarray(y),
                jnp.asarray(counts), jax.random.PRNGKey(i))

    return check_retrace(round_fn, make_args,
                         "engine.round[lr,f32,fedavg]", rounds=rounds)


def check_model_partitions() -> List[Finding]:
    """Every registry model's full variables tree must match a
    PartitionSpec rule (the match_partition_rules coverage contract)."""
    out: List[Finding] = []
    for name in sorted(MODEL_EXAMPLES):
        if name not in available_models():
            continue
        shape, in_dtype, kw = MODEL_EXAMPLES[name]
        module = create_model(name, output_dim=10, **kw)
        tree = model_variable_shapes(module, shape, in_dtype)
        out += check_partition_coverage(tree, f"model:{name}")
    return out


def check_tensor_rule_coverage(rule_tables=None,
                               family_models=None) -> List[Finding]:
    """100% coverage over the RUNTIME partition-rule tables
    (parallel/tensor.py RULE_TABLES) — the lint-only contract above,
    extended to the tables that actually shard rounds.

    Two directions: every non-scalar leaf of every family model must match
    its family's table (an unmatched leaf would raise inside
    `resolve_param_specs` at round-build time — catch it in lint instead),
    and every rule must match at least one leaf across the family's models
    (a dead rule means the table and the model zoo drifted apart).
    `rule_tables`/`family_models` default to the runtime tables; tests
    inject fixtures."""
    import re

    from fedml_tpu.analysis.partition import _flat_paths
    from fedml_tpu.models.lora import init_lora_adapters
    from fedml_tpu.parallel.tensor import FAMILY_MODELS, RULE_TABLES

    tables = RULE_TABLES if rule_tables is None else rule_tables
    models = FAMILY_MODELS if family_models is None else family_models
    out: List[Finding] = []
    for family in sorted(tables):
        rules = list(tables[family])
        used = [False] * len(rules)

        def mark_used(tree):
            for path, leaf in _flat_paths(tree):
                if getattr(leaf, "ndim", 0) == 0:
                    continue
                for i, (pattern, _) in enumerate(rules):
                    if re.search(pattern, path):
                        used[i] = True
                        break

        for name in models.get(family, ()):
            if name not in available_models():
                continue
            shape, in_dtype, kw = MODEL_EXAMPLES[name]
            module = create_model(name, output_dim=10, **kw)
            tree = model_variable_shapes(module, shape, in_dtype)
            out += check_partition_coverage(
                tree, f"tensor-rules:{family}:{name}", rules=rules)
            mark_used(tree)
            # the LoRA composition these tables explicitly carry rules for
            # (models/lora.py wraps any family model: replicated lora_A/B
            # adapters over the tensor-sharded frozen base) — the adapter
            # leaves must be covered too, and covering them is what keeps
            # the lora_[AB] rule live in the dead-rule direction below
            try:
                adapters = jax.eval_shape(
                    lambda p: init_lora_adapters(p, 8, jax.random.PRNGKey(0)),
                    tree.get("params", tree))
            except ValueError:
                adapters = None  # no 2D kernel eligible in this family model
            if adapters:
                out += check_partition_coverage(
                    adapters, f"tensor-rules:{family}:{name}+lora8",
                    rules=rules)
                mark_used(adapters)
        for hit, (pattern, spec) in zip(used, rules):
            if not hit:
                out.append(Finding(
                    "partition-coverage", f"tensor-rules:{family}",
                    f"rule {pattern!r} ({spec}) matches no leaf of any "
                    f"family model — dead rule; prune it or fix the "
                    f"pattern"))
    return out


# ------------------------------------------------------------------ drives
# The registered drive configs whose XLA program sets COMPILE_BUDGET.json
# pins (compile_engine). The per-drive program LISTS live in the
# declarative spec (core/spec.py DRIVE_SPECS, graft-matrix) — codec twins
# are expanded from the codec axis there, not hand-listed here. This
# module's job is to TRACE every declared point through the real builders,
# so the enumeration still crashes the moment a signature arm drifts.
DRIVE_CONFIGS = ("eager", "pipelined", "buffered", "tensor", "sharded",
                 "hierarchical", "silo", "serving", "finetune")


def _drive_eval_programs(trainer, shape, in_dtype, gv, rng):
    """The three eval programs every FedAvgAPI drive shares: packed global
    eval, chunked per-client eval, and the resident federation eval (two
    signatures — the Train and Test splits pack to different n_max)."""
    from fedml_tpu.algorithms.engine import (build_client_eval_fn,
                                             build_eval_fn,
                                             build_federation_eval_fn)

    feat = shape[1:]
    i32 = lambda s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    xs = lambda s: jax.ShapeDtypeStruct(s + feat, in_dtype)  # noqa: E731
    jax.eval_shape(build_eval_fn(trainer), gv,
                   xs((3, 2)), i32((3, 2)), f32((3, 2)))
    jax.eval_shape(build_client_eval_fn(trainer), gv,
                   xs((2, 4)), i32((2, 4)), i32((2,)))
    fed_eval = build_federation_eval_fn(trainer)
    for n_max in (4, 6):
        jax.eval_shape(fed_eval, gv,
                       xs((1, 2, n_max)), i32((1, 2, n_max)), i32((1, 2)))
    return {"engine.eval[lr,f32]": 1, "engine.client_eval[lr,f32]": 1,
            "engine.federation_eval[lr,f32]": 2}


def _point_codec(point, cfg):
    """The codec a spec ProgramPoint's name tag declares (int8 at the
    config's bit width, topk at the point's pinned k), or None."""
    from fedml_tpu.codecs import make_codec

    level = point.opt("codec")
    if level is None:
        return None
    if level == "int8":
        return make_codec("int8", cfg)
    return make_codec("topk", {"codec_k": point.opt("codec_k")})


def _trace_buffered_programs(trainer, cfg, agg, gv, agg_state, x, y, counts,
                             rng, codecs=()) -> dict:
    """Abstractly trace the buffered drive's three jit programs (client
    step, admit, commit) — shared by the buffered and serving enumerations.
    `codecs` adds the codec-on admit variants (graft-codec): each codec's
    admit takes the trailing replicated delta base, a distinct jit
    signature the budget pins as its own program."""
    from fedml_tpu.algorithms.aggregators import (build_buffer_admit,
                                                  build_buffer_commit,
                                                  make_staleness_discount)
    from fedml_tpu.algorithms.buffered import build_client_step_fn

    programs = {}
    step = build_client_step_fn(trainer, cfg)
    result = jax.eval_shape(step, gv, x, y, counts, rng)
    programs["buffered.client_step[lr,f32]"] = 1
    k = 5
    row = lambda l: jax.ShapeDtypeStruct(  # noqa: E731
        (k,) + l.shape[1:], l.dtype)
    i32 = lambda s=(): jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    buf = {"vars": jax.tree.map(row, result.variables),
           "steps": i32((k,)),
           "weights": jax.ShapeDtypeStruct((k,), jnp.float32),
           "metrics": {name: row(v)
                       for name, v in result.metrics.items()},
           "birth": i32((k,)), "fill": i32()}
    jax.eval_shape(build_buffer_admit(), buf, result.variables,
                   result.num_steps, result.metrics, counts,
                   i32(), i32())
    programs["buffered.admit[lr,f32]"] = 1
    for codec in codecs:
        # the codec delta base mirrors the WIRE tree — adapters-only under
        # LoRA, same strip the drive applies (algorithms/buffered.py)
        from fedml_tpu.models.lora import strip_lora_base

        jax.eval_shape(build_buffer_admit(codec=codec), buf,
                       result.variables, result.num_steps, result.metrics,
                       counts, i32(), i32(), strip_lora_base(gv))
        programs[f"buffered.admit[lr,f32,{codec.name}]"] = 1
    jax.eval_shape(build_buffer_commit(agg, make_staleness_discount(0.5)),
                   gv, agg_state, buf, i32(), rng)
    programs["buffered.commit[lr,f32,fedavg]"] = 1
    return programs


def _trace_engine_round(point, ctx) -> None:
    """Trace one declared engine.round point: the base vmap round, or its
    masked / federated-LoRA / fused-kernel / codec-wrapped twin, per the
    point's spec opts."""
    from fedml_tpu.algorithms.engine import build_round_fn

    trainer, cfg, agg = ctx["trainer"], ctx["cfg"], ctx["agg"]
    gv, x, y = ctx["gv"], ctx["x"], ctx["y"]
    counts, rng, agg_state = ctx["counts"], ctx["rng"], ctx["agg_state"]
    if point.opt("fused"):
        # fused-kernel twin (a --fused_kernel run reaches it): the
        # CNN_DropOut epoch kernel replacing the vmap round wholesale
        model = point.opt("model")
        ftrainer, fshape, f_dtype = _tiny_trainer(model, "float32")
        fcfg = FedConfig(model=model, batch_size=2, epochs=1,
                         dtype="float32", fused_kernel=True, grad_clip=10.0)
        fgv, fx, fy, fcounts, frng = _abstract_round_args(
            ftrainer, fshape, f_dtype)
        round_f = build_round_fn(ftrainer, fcfg, agg)
        jax.eval_shape(round_f, fgv, agg_state, fx, fy, fcounts, frng)
        return
    if point.opt("pfl"):
        # personalized twin (a --personalize run reaches it): the
        # federated-LoRA round plus trailing [C, ...] personal adapter
        # rows in and out — a distinct jit signature the budget pins as
        # its own program (graft-pfl, models/adapter_bank.py)
        from fedml_tpu.algorithms.engine import build_personal_round_fn
        from fedml_tpu.models.lora import LoRATrainer

        ptrainer = LoRATrainer(trainer, rank=point.opt("lora_rank"))
        pgv, px, py, pcounts, prng = _abstract_round_args(
            ptrainer, ctx["shape"], ctx["in_dtype"])
        round_p = build_personal_round_fn(ptrainer, cfg, agg)
        personal = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((2,) + l.shape, l.dtype),
            pgv["params"])
        jax.eval_shape(round_p, pgv, jax.eval_shape(agg.init_state, pgv),
                       px, py, pcounts, prng, personal)
        return
    if point.opt("lora_rank"):
        # federated-LoRA round (a --lora_rank run reaches it): adapters
        # under "params", frozen base riding as the lora_base collection —
        # a distinct jit signature the budget pins as its own program
        from fedml_tpu.models.lora import LoRATrainer

        ltrainer = LoRATrainer(trainer, rank=point.opt("lora_rank"))
        lgv, lx, ly, lcounts, lrng = _abstract_round_args(
            ltrainer, ctx["shape"], ctx["in_dtype"])
        round_l = build_round_fn(ltrainer, cfg, agg)
        jax.eval_shape(round_l, lgv, jax.eval_shape(agg.init_state, lgv),
                       lx, ly, lcounts, lrng)
        return
    codec = _point_codec(point, cfg)
    if codec is not None:
        # codec-wrapped sync round (a codec-on serving tenant reaches it):
        # the CodecAggregator state is a distinct jit signature
        from fedml_tpu.codecs.transport import CodecAggregator

        wrapped = CodecAggregator(codec, agg, slots=2)
        round_c = build_round_fn(trainer, cfg, wrapped)
        jax.eval_shape(round_c, gv, jax.eval_shape(wrapped.init_state, gv),
                       x, y, counts, rng)
        return
    round_fn = build_round_fn(trainer, cfg, agg)
    args = (gv, agg_state, x, y, counts, rng)
    if point.opt("masked"):
        # chaos is on for this config, so every round carries a
        # participation mask — only the masked arm ever compiles
        args = args + (jax.ShapeDtypeStruct((2,), jnp.bool_),)
    jax.eval_shape(round_fn, *args)


def _trace_superstep(point, ctx) -> None:
    """K rounds scanned in ONE program, chaos-armed + stats-collecting as
    the drive builds it (collect_stats always on in FedAvgAPI)."""
    from fedml_tpu.algorithms.engine import build_superstep_fn

    k = point.opt("rounds")
    scfg = FedConfig(model="lr", batch_size=2, epochs=1,
                     dtype="float32", client_num_per_round=2,
                     rounds_per_dispatch=k)
    super_fn = build_superstep_fn(
        ctx["trainer"], scfg, ctx["agg"], k, client_num_in_total=2,
        collect_stats=True, chaos_armed=True)

    def i32(shape=()):
        return jax.ShapeDtypeStruct(shape, jnp.int32)

    per_round = {"round_idx": i32((k,)), "idx": i32((k, 2)),
                 "nan": jax.ShapeDtypeStruct((k, 2), jnp.bool_),
                 "corrupt": jax.ShapeDtypeStruct((k, 2), jnp.bool_),
                 "participation": jax.ShapeDtypeStruct((k, 2), jnp.bool_)}
    jax.eval_shape(super_fn, ctx["gv"], ctx["agg_state"], ctx["x"],
                   ctx["y"], ctx["counts"], ctx["rng"], per_round)


def _trace_tensor_point(point, ctx) -> None:
    """tensor.round (plus its codec twins carrying the wrapped
    {"agg","codec"} state) and the --shard_step tensor.step round."""
    from jax.sharding import Mesh

    from fedml_tpu.parallel.tensor import (TensorSharding,
                                           build_tensor_round_fn,
                                           build_tensor_step_round_fn)

    trainer, cfg, agg = ctx["trainer"], ctx["cfg"], ctx["agg"]
    gv, x, y = ctx["gv"], ctx["x"], ctx["y"]
    counts, rng, agg_state = ctx["counts"], ctx["rng"], ctx["agg_state"]
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(point.opt("mesh")),
                ("clients", "tensor"))
    sharding = TensorSharding.for_model(mesh, "lr")
    if point.family == "tensor.step":
        # --shard_step twin: the GSPMD activation-sharded round
        # (build_tensor_step_round_fn) replacing the shard_map round
        cfg_ss = FedConfig(model="lr", batch_size=2, epochs=1,
                           dtype="float32", tensor_shards=4,
                           shard_step=True)
        round_ss = build_tensor_step_round_fn(
            trainer, cfg_ss, agg, sharding, donate_state=False)
        jax.eval_shape(round_ss, gv, agg_state, x, y, counts, rng)
        return
    codec = _point_codec(point, cfg)
    if codec is None:
        round_fn = build_tensor_round_fn(
            trainer, cfg, agg, sharding, donate_state=True)
        jax.eval_shape(round_fn, gv, agg_state, x, y, counts, rng)
        return
    # graft-codec twins: the codec-on round carries the wrapped
    # {"agg", "codec"} state (per-clients-device residual rows), a
    # distinct signature per codec; k matches the COMMS-budget twin
    round_c = build_tensor_round_fn(
        trainer, cfg, agg, sharding, donate_state=True, codec=codec)

    def init_st(g):
        resid = jax.tree.map(
            lambda l: jnp.zeros(
                (2,) + (l.shape
                        if jnp.issubdtype(l.dtype, jnp.inexact)
                        else ()), l.dtype), g)
        return {"agg": agg.init_state(g), "codec": resid}

    jax.eval_shape(round_c, gv, jax.eval_shape(init_st, gv),
                   x, y, counts, rng)


def _trace_sharded_point(point, ctx) -> None:
    """The shard_map round and its codec twins (CodecAggregator state, one
    residual row per cohort slot, sharded over 'clients'). EVERY codec
    level the spec arms traces here — the hand enumeration's [:1] slice
    was exactly how the topk twin stayed ungated."""
    from jax.sharding import Mesh

    from fedml_tpu.parallel.sharded import build_sharded_round_fn

    trainer, cfg, agg = ctx["trainer"], ctx["cfg"], ctx["agg"]
    gv, rng = ctx["gv"], ctx["rng"]
    c = point.opt("mesh")[0]
    mesh = Mesh(np.array(jax.devices()[:c]), ("clients",))
    sharded_args = (
        jax.ShapeDtypeStruct((c, 4) + ctx["shape"][1:], ctx["in_dtype"]),
        jax.ShapeDtypeStruct((c, 4), jnp.int32),
        jax.ShapeDtypeStruct((c,), jnp.int32), rng)
    codec = _point_codec(point, cfg)
    if codec is None:
        round_fn = build_sharded_round_fn(trainer, cfg, agg, mesh)
        jax.eval_shape(round_fn, gv, ctx["agg_state"], *sharded_args)
        return
    from fedml_tpu.codecs.transport import CodecAggregator

    wrapped = CodecAggregator(codec, agg, slots=c)
    round_c = build_sharded_round_fn(trainer, cfg, wrapped, mesh)
    jax.eval_shape(round_c, gv, jax.eval_shape(wrapped.init_state, gv),
                   *sharded_args)


def _trace_hier_point(point, ctx) -> None:
    from jax.sharding import Mesh

    from fedml_tpu.parallel.hierarchical import (
        build_sharded_hierarchical_round_fn)

    g, c = point.opt("mesh")
    mesh = Mesh(np.array(jax.devices()[:g * c]).reshape(g, c),
                ("groups", "clients"))
    round_fn = build_sharded_hierarchical_round_fn(
        ctx["trainer"], ctx["cfg"], mesh, group_comm_round=2)
    n = 4
    jax.eval_shape(round_fn, ctx["gv"],
                   jax.ShapeDtypeStruct((g, c, n) + ctx["shape"][1:],
                                        ctx["in_dtype"]),
                   jax.ShapeDtypeStruct((g, c, n), jnp.int32),
                   jax.ShapeDtypeStruct((g, c), jnp.int32), ctx["rng"])


def _trace_silo_point(point, ctx) -> None:
    # silo grouping needs convs to group — mirror the jaxpr target
    jaxpr = round_jaxpr(point.opt("model"), point.opt("dtype"), "fedavg",
                        silo_threshold=32)
    del jaxpr


def enumerate_drive_programs(drive: str) -> dict:
    """{program name: distinct signature count} for one registered drive
    config — the static half of the compile budget, DERIVED from the
    declarative spec (core/spec.py DRIVE_SPECS): every declared
    ProgramPoint is traced through the real builders, so the enumeration
    crashes the moment a signature arm drifts, and the budget names are
    the spec's names. All programs trace on the lr/f32/fedavg example
    (signature COUNT does not depend on the model), except silo which
    needs a conv model to group."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.core.spec import DRIVE_SPECS, EVAL_POINTS, drive_points

    if drive not in DRIVE_SPECS:
        raise ValueError(f"unknown drive config {drive!r}; "
                         f"known: {sorted(DRIVE_SPECS)}")
    trainer, shape, in_dtype = _tiny_trainer("lr", "float32")
    cfg = FedConfig(model="lr", batch_size=2, epochs=1, dtype="float32")
    agg = make_aggregator("fedavg", cfg)
    gv, x, y, counts, rng = _abstract_round_args(trainer, shape, in_dtype)
    ctx = {"trainer": trainer, "shape": shape, "in_dtype": in_dtype,
           "cfg": cfg, "agg": agg, "gv": gv, "x": x, "y": y,
           "counts": counts, "rng": rng,
           "agg_state": jax.eval_shape(agg.init_state, gv)}

    tracers = {"engine.round": _trace_engine_round,
               "engine.superstep": _trace_superstep,
               "tensor.round": _trace_tensor_point,
               "tensor.step": _trace_tensor_point,
               "sharded.round": _trace_sharded_point,
               "hier.round": _trace_hier_point,
               "silo.round": _trace_silo_point}
    eval_families = {p.family for p in EVAL_POINTS}

    programs = {}
    buffered_points = []
    for point in drive_points(drive):
        if point.family in eval_families:
            continue  # the shared evals trace once, below
        if point.family.startswith("buffered."):
            buffered_points.append(point)
            continue
        tracers[point.family](point, ctx)
        programs[point.name] = point.signatures
    if buffered_points:
        # the buffered family traces as one group (admit needs the client
        # step's result shapes); codec-on admit twins ride the declared
        # codec levels — k matches the COMMS-budget twin
        codecs = [_point_codec(p, cfg) for p in buffered_points
                  if p.family == "buffered.admit" and p.opt("codec")]
        traced = _trace_buffered_programs(
            trainer, cfg, agg, gv, ctx["agg_state"], x, y, counts, rng,
            codecs=codecs)
        declared = {p.name: p.signatures for p in buffered_points}
        if set(traced) != set(declared):
            raise RuntimeError(
                f"buffered tracer/spec drift for drive {drive!r}: traced "
                f"{sorted(traced)} != declared {sorted(declared)}")
        programs.update(traced)
    if DRIVE_SPECS[drive].evals:
        programs.update(_drive_eval_programs(trainer, shape, in_dtype,
                                             gv, rng))
    return dict(sorted(programs.items()))


def run_all(repo_root: str, include_models: bool = True,
            include_ast: bool = True) -> Report:
    """The full lint pass the CLI and tests/test_lint.py run."""
    from fedml_tpu.analysis.ast_engine import lint_tree

    report = Report()
    missing = models_missing_examples()
    for m in missing:
        report.extend([Finding(
            "dtype-policy", f"model:{m}",
            "registered without a MODEL_EXAMPLES row — the dtype sweep "
            "cannot see it; add one in fedml_tpu/analysis/targets.py")])
    for target, jaxpr, policy in iter_jaxpr_targets(include_models):
        report.extend(lint_jaxpr(jaxpr, target, policy=policy))
        report.mark(target)
    report.extend(check_chunked_donation())
    report.mark("engine.chunked.chunk_fn[lr]")
    report.extend(check_round_retrace())
    report.mark("engine.round.retrace[lr]")
    report.extend(check_model_partitions())
    report.mark("partition-coverage[registry]")
    report.extend(check_tensor_rule_coverage())
    report.mark("partition-coverage[tensor-rules]")
    from fedml_tpu.analysis.jaxpr_engine import (
        check_unconstrained_intermediate)

    step_jaxpr, t_sz = tensor_step_jaxpr()
    report.extend(check_unconstrained_intermediate(
        step_jaxpr, "tensor.step[tformer,f32,2x4]", tensor_axis_size=t_sz))
    report.mark("tensor.step[tformer,f32,2x4]")
    if include_ast:
        report.extend(lint_tree(repo_root, ["fedml_tpu", "tools"]))
        report.mark("ast[fedml_tpu,tools]")
    return report
