"""Sharded decentralized gossip: node-per-device neighbor exchange.

The dense path (algorithms/decentralized.py) mixes all node models with one
einsum `W @ x` on a single chip — fine until the stacked node models exceed
one chip's HBM. This module is the multi-chip variant (SURVEY §2.9
"decentralized/gossip ... or ppermute"): node i's model lives on device i of
a `nodes` mesh axis and a gossip round moves ONLY actual edges over the ICI
via `lax.ppermute`.

Any mixing matrix decomposes into cyclic shifts:

    W = sum_s  diag(c_s) . P_s        c_s[i] = W[i, (i - s) mod N]

where P_s is the cyclic node shift by s. For a ring + Watts-Strogatz
topology (reference symmetric_topology_manager.py:21-52) only a handful of
shifts carry nonzero weight, so the exchange is a few ppermutes — each a
pure neighbor hop on a ring-wired ICI — instead of an all-to-all.

Equality with the dense einsum path is asserted on the virtual 8-device
mesh by tests/test_parallel.py and in __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.utils.jax_compat import shard_map


def shift_decomposition(W: np.ndarray) -> tuple[list[int], np.ndarray]:
    """Nonzero cyclic shifts of W and their per-node coefficients.

    Returns (shifts, coefs[len(shifts), N]) with
    coefs[k, i] = W[i, (i - shifts[k]) % N].
    """
    W = np.asarray(W)
    N = W.shape[0]
    shifts, rows = [], []
    for s in range(N):
        c = np.array([W[i, (i - s) % N] for i in range(N)], W.dtype)
        if np.any(c != 0):
            shifts.append(s)
            rows.append(c)
    return shifts, np.stack(rows) if rows else np.zeros((0, N), W.dtype)


def build_sharded_mix(W: np.ndarray, mesh: Mesh,
                      axis_name: str = "nodes") -> Callable:
    """One-node-per-device gossip mix: stacked [N, ...] pytree -> W @ x,
    computed with one `ppermute` per nonzero cyclic shift of W.

    Requires mesh.shape[axis_name] == N (the node axis is fully sharded —
    that is the point of the multi-chip variant; use the dense einsum path
    below that scale)."""
    W = np.asarray(W, np.float32)
    N = W.shape[0]
    if mesh.shape[axis_name] != N:
        raise ValueError(
            f"sharded gossip wants one node per device: N={N} nodes vs "
            f"mesh axis {axis_name!r}={mesh.shape[axis_name]} devices")
    shifts, coefs = shift_decomposition(W)
    coefs_arr = jnp.asarray(coefs)  # [S, N]

    def mix_leaf(x, c):
        # x: local [1, ...] node block; c: local [S, 1] coefficients
        acc = jnp.zeros_like(x)
        for k, s in enumerate(shifts):
            if s == 0:
                shifted = x
            else:
                # receiver i gets node (i - s) % N: send j -> (j + s) % N
                perm = [(j, (j + s) % N) for j in range(N)]
                shifted = jax.lax.ppermute(x, axis_name, perm)
            acc = acc + c[k].reshape((1,) * x.ndim) * shifted
        return acc

    mix_sharded = shard_map(
        mix_leaf, mesh=mesh,
        in_specs=(P(axis_name), P(None, axis_name)),
        out_specs=P(axis_name),
    )

    def mix(stacked_tree):
        return jax.tree.map(lambda leaf: mix_sharded(leaf, coefs_arr),
                            stacked_tree)

    return jax.jit(mix)
