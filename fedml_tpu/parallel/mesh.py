"""Device-mesh construction helpers.

The reference maps MPI ranks to GPUs via a YAML hostfile
(reference fedml_api/distributed/utils/gpu_mapping.py:8-37). Here placement is
a `jax.sharding.Mesh`; axis names give the FL-parallelism taxonomy:

  clients — client/data parallelism (one client shard per device group)
  groups  — hierarchical FL outer axis (cloud -> group -> client)
  stages  — model-split axis (SplitNN pipeline analog)
  tensor  — tensor/model parallelism (per-param partition rules,
            parallel/tensor.py rule tables)
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def make_mesh(shape: tuple[int, ...] | None = None, axis_names: tuple[str, ...] = ("clients",)) -> Mesh:
    """Create a mesh over the available devices.

    With `shape=None`, all devices form a 1-D mesh over `axis_names[0]`.
    """
    devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    dev_mesh = mesh_utils.create_device_mesh(shape, devices=devices[:n])
    return Mesh(dev_mesh, axis_names)


def make_tensor_mesh(tensor_shards: int) -> Mesh:
    """2D ('clients', 'tensor') mesh: tensor-parallel groups nested in cohorts.

    Uses every available device; the client axis absorbs whatever is left
    after the tensor axis takes `tensor_shards` devices per group.
    """
    n_dev = len(jax.devices())
    if tensor_shards < 1 or n_dev % tensor_shards != 0:
        raise ValueError(
            f"tensor_shards={tensor_shards} must divide device count {n_dev}"
        )
    return make_mesh((n_dev // tensor_shards, tensor_shards), ("clients", "tensor"))
