"""Multi-chip parallelism: device meshes + shard_map federated rounds.

This package is the TPU-native replacement for the reference's entire
`fedml_core/distributed` transport stack (MPI send/recv threads + pickled
state_dicts, reference com_manager.py:13-101): the "cluster" is a
`jax.sharding.Mesh`, clients are sharded over the `clients` axis, and the
server's weighted average is an XLA collective over ICI.
"""

from fedml_tpu.parallel.hierarchical import (  # noqa: F401
    build_sharded_hierarchical_round_fn,
)
from fedml_tpu.parallel.mesh import make_mesh, make_tensor_mesh  # noqa: F401
from fedml_tpu.parallel.sharded import (  # noqa: F401
    build_sharded_buffer_fns,
    build_sharded_round_fn,
)
from fedml_tpu.parallel.tensor import (  # noqa: F401
    RULE_TABLES,
    TensorSharding,
    build_tensor_round_fn,
    rules_for_model,
)
