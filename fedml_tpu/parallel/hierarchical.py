"""Two-level (groups, clients) mesh for hierarchical FL.

SURVEY §2.9 maps the reference's cloud→group→client nesting
(standalone/hierarchical_fl/trainer.py:43-71, group.py:24-46) onto a
two-level device mesh: ICI within a slice hosts a group's clients, the
cross-slice (DCN-reaching) axis is the cloud. Concretely:

  - clients are sharded over BOTH mesh axes: x is [G, C, n_max, ...] with G
    split over the `groups` axis and C over the `clients` axis;
  - each inner group round ends in a weighted `psum` over the `clients`
    axis only — the group-local all-reduce that rides ICI;
  - after `group_comm_round` inner rounds, the cloud average is a weighted
    `psum` over the `groups` axis — the only traffic that crosses slices,
    once per global round instead of once per inner round (the whole point
    of hierarchical FL's communication hierarchy).

Per-group/per-client RNG keys are assigned from the same nested split tables
as the single-chip `build_hierarchical_round_fn`, so the sharded round
reproduces the vmap round to float tolerance (asserted in
tests/test_parallel.py and in the driver dryrun).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.algorithms.aggregators import (
    client_finite_mask,
    tree_weighted_mean_psum,
    tree_weighted_sum_psum,
)
from fedml_tpu.algorithms.engine import build_local_update
from fedml_tpu.core.builder import shard_key_slice
from fedml_tpu.core.config import FedConfig
from fedml_tpu.utils.jax_compat import pcast, shard_map
from fedml_tpu.utils.pytree import tree_where


def build_sharded_hierarchical_round_fn(
    trainer,
    cfg: FedConfig,
    mesh: Mesh,
    group_comm_round: int,
    group_axis: str = "groups",
    client_axis: str = "clients",
) -> Callable:
    """Jitted two-level round over a (groups, clients) mesh.

    Inputs mirror build_hierarchical_round_fn: x/y/counts are group-major
    [G, C, n_max, ...]; G must divide by mesh.shape[group_axis] and C by
    mesh.shape[client_axis] (pad with zero-count clients / empty groups —
    weight-0 no-ops at both averaging levels).

    Fault tolerance (optional trailing `participation`, [G, C] bool sharded
    like counts) is two-level, matching the communication hierarchy: dropped
    clients are `where`-zeroed zero-weight rows inside every inner round
    (elementwise only — the group weight normalization psum stays hoisted
    outside the inner scan, so no collective enters the loop), while the
    non-finite quarantine runs at GROUP granularity at the cloud step: a
    group whose final variables carry NaN/Inf — one poisoned client inside
    an inner round contaminates its whole group's running mean, there is no
    finer-grained recovery point — is excluded from the cloud average with
    zero weight. All groups quarantined degrades to a no-op (global passes
    through). `participation=None` traces the exact legacy program
    (COMMS_BUDGET.json gates it); metrics of the masked specialization gain
    `participated_count` (participating clients in surviving groups) and
    `quarantined_count` (participating clients in quarantined groups).
    """
    # clients-axis pcast: each client's scan carries become varying over the
    # clients axis; the groups axis is handled at the inner-round scan below
    local_update = build_local_update(trainer, cfg, pvary_axes=(client_axis,))
    g_dev = mesh.shape[group_axis]
    c_dev = mesh.shape[client_axis]

    def shard_body(global_variables, x, y, counts, rng, participation=None):
        masked = participation is not None
        g_loc, c_loc = x.shape[0], x.shape[1]
        g_total, c_total = g_loc * g_dev, c_loc * c_dev
        gidx = jax.lax.axis_index(group_axis)
        cidx = jax.lax.axis_index(client_axis)
        # same group-key table as the vmap engine: split(rng, G)[g]
        grngs = shard_key_slice(rng, g_total, gidx, g_loc)

        def group_train(gv, xg, yg, cg, grng, pg):
            # pg: this group's [c_loc] participation row (unused — and
            # dead-code-eliminated — on the unmasked trace)
            # inner-scan carry: starts as the invariant global broadcast,
            # exits varying over the groups axis (each group trains its own
            # line) — pcast so the carry types match under check_vma
            gv = pcast(gv, (group_axis,), to="varying")
            # the group's total client weight is round-invariant, so its
            # psum is hoisted OUT of the inner-round scan: one scalar
            # all-reduce per global round instead of one per inner round
            # (graft-lint collective-in-loop); the guarded denominator makes
            # an empty padded group zeros (weight-0 at the cloud), not NaN
            cw = cg.astype(jnp.float32)
            if masked:
                # dropped clients: zero weight before the hoisted
                # normalization, so the mask costs no loop-carried collective
                cw = jnp.where(pg, cw, 0.0)
            cw_norm = cw / jnp.maximum(
                jax.lax.psum(jnp.sum(cw), client_axis), 1e-12)

            def inner_round(gv, r_rng):
                # same client-key table: split(r_rng, C)[c]
                crngs = shard_key_slice(r_rng, c_total, cidx, c_loc)
                result = jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
                    gv, xg, yg, cg, crngs
                )
                variables, mets = result.variables, result.metrics
                if masked:
                    # `where`-zero dropped rows (elementwise, no collective):
                    # a zero weight alone cannot save the sum from a NaN row
                    # (NaN * 0 == NaN)
                    def zero_dropped(leaf):
                        keep = pg.reshape((-1,) + (1,) * (leaf.ndim - 1))
                        return jnp.where(keep, leaf, jnp.zeros((), leaf.dtype))

                    variables = jax.tree.map(zero_dropped, variables)
                    mets = {k: jnp.where(pg, v, jnp.zeros((), v.dtype))
                            for k, v in mets.items()}
                # group-local weighted mean == psum over the clients axis
                # (ICI), with the pre-normalized weights from above
                new_gv = tree_weighted_sum_psum(variables, cw_norm, client_axis)
                metrics = {
                    k: jax.lax.psum(v.sum(), client_axis)
                    for k, v in mets.items()
                }
                return new_gv, metrics

            gv, ms = jax.lax.scan(
                inner_round, gv, jax.random.split(grng, group_comm_round)
            )
            return gv, {k: v[-1] for k, v in ms.items()}

        # the trailing operand is the participation block when masked and a
        # dummy (counts — unused, DCE'd) otherwise, keeping one group_train
        part = participation if masked else counts
        group_vars, metrics = jax.vmap(group_train, in_axes=(None, 0, 0, 0, 0, 0))(
            global_variables, x, y, counts, grngs, part
        )
        if not masked:
            # cloud level: weighted mean over groups — the once-per-global-
            # round cross-slice reduction
            gw = jax.lax.psum(counts.sum(axis=1).astype(jnp.float32), client_axis)
            new_global = tree_weighted_mean_psum(group_vars, gw, group_axis)
            out_metrics = {
                k: jax.lax.psum(v.sum(), group_axis) for k, v in metrics.items()
            }
            return new_global, out_metrics
        pb = participation.astype(bool)
        cw_all = jnp.where(pb, counts.astype(jnp.float32), 0.0)
        gw = jax.lax.psum(cw_all.sum(axis=1), client_axis)
        # group-level quarantine: one poisoned client contaminates its whole
        # group's inner-round running mean, so the recovery granularity at
        # the cloud is the group — non-finite groups get zero weight and
        # `where`-zeroed variables
        fin_g = client_finite_mask(group_vars)
        gw_eff = jnp.where(fin_g, gw, 0.0)

        def zero_bad_group(leaf):
            keep = fin_g.reshape((-1,) + (1,) * (leaf.ndim - 1))
            return jnp.where(keep, leaf, jnp.zeros((), leaf.dtype))

        new_global = tree_weighted_mean_psum(
            jax.tree.map(zero_bad_group, group_vars), gw_eff, group_axis)
        any_alive = jax.lax.psum(gw_eff.sum(), group_axis) > 0
        new_global = tree_where(any_alive, new_global, global_variables)
        # participating clients per local group, cloud-summed by survival
        p_g = jax.lax.psum(pb.astype(jnp.float32).sum(axis=1), client_axis)
        out_metrics = {
            k: jax.lax.psum(jnp.where(fin_g, v, jnp.zeros((), v.dtype)).sum(),
                            group_axis)
            for k, v in metrics.items()
        }
        out_metrics["participated_count"] = jax.lax.psum(
            jnp.where(fin_g, p_g, 0.0).sum(), group_axis)
        out_metrics["quarantined_count"] = jax.lax.psum(
            jnp.where(fin_g, 0.0, p_g).sum(), group_axis)
        return new_global, out_metrics

    def round_fn(global_variables, x, y, counts, rng, participation=None):
        data_spec = P(group_axis, client_axis)
        if participation is None:
            sharded = shard_map(
                shard_body,
                mesh=mesh,
                in_specs=(P(), data_spec, data_spec, data_spec, P()),
                out_specs=(P(), P()),
            )
            return sharded(global_variables, x, y, counts, rng)
        sharded = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), data_spec, data_spec, data_spec, P(), data_spec),
            out_specs=(P(), P()),
        )
        return sharded(global_variables, x, y, counts, rng, participation)

    return jax.jit(round_fn)
