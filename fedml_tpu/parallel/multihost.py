"""Cross-silo multi-process deployment — the mpirun replacement.

The reference deploys with `mpirun -np N+1` + a hostfile and moves pickled
state_dicts point-to-point (SURVEY §3.1). The TPU-native deployment is a JAX
multi-process run: one process per silo/host, all devices form one global
mesh, and every exchange is an XLA collective over ICI/DCN
(`jax.distributed.initialize` + `multihost_utils`, per SURVEY §2.9's
"distributed communication backend" row).

Control-plane messages (sampling indices, eval stats) ride
`broadcast_one_to_all` / `process_allgather` on DCN; the model average rides
the in-graph psum/all_gather of the sharded round. There are no send/recv
threads, no 0.3 s poll loops, no MPI.Abort shutdown (SURVEY §7 defects).

Single-process runs (process_count == 1) degrade to no-ops so the same
training script works from a laptop to a multi-host pod.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

log = logging.getLogger(__name__)

# bounded by default: an unconfigured peer-wait must surface as an error in
# minutes, not hang the pod forever (the reference's mpirun deployment hangs)
DEFAULT_INIT_TIMEOUT = 300


def _distributed_initialized() -> bool:
    """jax.distributed.is_initialized arrived in newer jax; fall back to the
    runtime's global client handle on versions without it."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None,
                   initialization_timeout: int | None = None) -> dict[str, int]:
    """Initialize the JAX distributed runtime (idempotent; no-op when
    unconfigured single-process). Returns topology info.

    ``initialization_timeout`` (seconds, default ``DEFAULT_INIT_TIMEOUT``)
    bounds how long a process waits for missing peers at startup — a dead
    silo then surfaces as a clean RuntimeError naming the coordinator and
    this process's slot instead of an indefinite hang (the reference's
    mpirun deployment just hangs; tests/test_multihost.py asserts the
    error)."""
    if coordinator_address is not None:
        if _distributed_initialized():
            log.info("jax.distributed already initialized — skipping")
        else:
            timeout = (DEFAULT_INIT_TIMEOUT if initialization_timeout is None
                       else initialization_timeout)
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=timeout,
                )
            except Exception as e:
                # rewrap with the topology facts the operator needs to act
                # (which silo is missing is almost always answerable from
                # "who am I, who was I waiting for"); the original traceback
                # rides along via __cause__
                raise RuntimeError(
                    f"jax.distributed.initialize timed out or failed after "
                    f"{timeout}s (coordinator={coordinator_address}, "
                    f"process_id={process_id}, num_processes={num_processes})"
                    f": {e}. Check that every process slot is up and can "
                    f"reach the coordinator address, then relaunch."
                ) from e
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def broadcast_from_server(value: Any) -> Any:
    """Process-0 value -> every process (the reference's send_init_msg /
    sync broadcast, FedAvgServerManager.py:31-37, as one DCN collective)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def allgather_metrics(local_metrics: dict[str, float]) -> dict[str, float]:
    """Sum scalar metrics across processes (replaces per-client MPI metric
    messages feeding server-side eval, FedAVGAggregator.py:109-163)."""
    if jax.process_count() == 1:
        return dict(local_metrics)
    from jax.experimental import multihost_utils

    keys = sorted(local_metrics)
    vec = np.asarray([local_metrics[k] for k in keys], np.float64)
    gathered = multihost_utils.process_allgather(vec)
    summed = np.asarray(gathered).sum(axis=0)
    return {k: float(v) for k, v in zip(keys, summed)}


def assert_same_across_processes(value: np.ndarray, name: str = "value"):
    """Cross-host agreement check (debugging aid for silo drift; the
    reference has no equivalent — SURVEY §5 race/failure detection gaps)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.assert_equal(value, f"{name} differs across processes")


def round_barrier(name: str, round_idx: int):
    """Named sync point between rounds (replaces the implicit MPI ordering)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"{name}_{round_idx}")


# --------------------------------------------------- sharded cohort sampling
#
# The million-client data plane (data/packed_store.py) makes per-host
# staging O(cohort); this section makes it O(cohort / process_count).
# Client sampling is a pure function of the round seed
# (algorithms.fedavg.client_sampling), so every host can derive the FULL
# cohort with zero communication — no broadcast, no leader — and then
# gather/stage only its own contiguous block. The padded cohort partitions
# exactly across hosts (tests/test_multihost.py "cohort" mode pins both
# properties at 2 processes).


@dataclass(frozen=True)
class ShardedCohort:
    """One round's cohort partitioned across hosts.

    `full_idx` is the seed-derived global cohort — identical on every
    host. `padded_idx` appends `-1` sentinel rows until the length is
    `block * process_count` (block itself rounded up to `multiple`, the
    per-host mesh size, so each host's slice feeds its local devices
    evenly); sentinels stage as zero-count no-op clients, the same
    weight-0 convention as data.packing.pad_clients."""

    round_idx: int
    full_idx: np.ndarray
    padded_idx: np.ndarray
    block: int
    process_index: int
    process_count: int

    @property
    def local_slice(self) -> slice:
        return slice(self.process_index * self.block,
                     (self.process_index + 1) * self.block)

    @property
    def local_idx(self) -> np.ndarray:
        """This host's contiguous block of the padded cohort (-1 = pad)."""
        return self.padded_idx[self.local_slice]


def sample_sharded_cohort(round_idx: int, client_num_in_total: int,
                          client_num_per_round: int, multiple: int = 1,
                          process_index: int | None = None,
                          process_count: int | None = None,
                          sampler=None) -> ShardedCohort:
    """Derive the round's cohort from the round seed and partition it
    across hosts — deterministically, with no communication.

    Every host runs the canonical `client_sampling` (same
    `np.random.RandomState(round_idx)` stream as the single-host drive
    loops, so a sharded deployment samples bit-identical cohorts), pads to
    `block * process_count` where `block = ceil(n / P)` rounded up to
    `multiple`, and owns the contiguous slice
    `[process_index * block, (process_index + 1) * block)`. Topology
    defaults to the live `jax.process_*` values; tests pass them
    explicitly. `sampler` swaps the cohort-derivation function (e.g.
    `fast_client_sampling` for the O(cohort) path) — any pure function of
    (round_idx, N, num) keeps the no-communication property."""
    # function-level import: algorithms.fedavg imports the parallel package
    # for the shard_map backend, so the modules must not need each other at
    # import time
    from fedml_tpu.algorithms.fedavg import client_sampling

    if sampler is None:
        sampler = client_sampling
    if multiple < 1:
        raise ValueError(f"multiple must be >= 1, got {multiple}")
    pc = jax.process_count() if process_count is None else int(process_count)
    pi = jax.process_index() if process_index is None else int(process_index)
    if not 0 <= pi < pc:
        raise ValueError(f"process_index {pi} out of range [0, {pc})")
    full_idx = np.asarray(
        sampler(round_idx, client_num_in_total, client_num_per_round),
        np.int64)
    block = -(-len(full_idx) // pc)          # ceil(n / P)
    block = -(-block // multiple) * multiple  # ... up to the mesh multiple
    padded_idx = np.full(block * pc, -1, np.int64)
    padded_idx[: len(full_idx)] = full_idx
    return ShardedCohort(round_idx=round_idx, full_idx=full_idx,
                         padded_idx=padded_idx, block=block,
                         process_index=pi, process_count=pc)


def stage_local_cohort(store, cohort: ShardedCohort):
    """Gather ONLY this host's slice of the cohort from a PackedClients
    duck-typed store (in-RAM, streaming, or data.packed_store mmap):
    `select()` touches just the local real clients; `-1` sentinel rows
    become zero-count padding. Returns host (x, y, counts) ready for
    `engine.stage_to_device` / `make_array_from_process_local_data`."""
    ids = cohort.local_idx
    real = ids[ids >= 0]
    x, y, counts = store.select(real)
    pad = len(ids) - len(real)
    if pad:
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        counts = np.concatenate([counts, np.zeros(pad, counts.dtype)])
    return x, y, counts
