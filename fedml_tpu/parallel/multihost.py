"""Cross-silo multi-process deployment — the mpirun replacement.

The reference deploys with `mpirun -np N+1` + a hostfile and moves pickled
state_dicts point-to-point (SURVEY §3.1). The TPU-native deployment is a JAX
multi-process run: one process per silo/host, all devices form one global
mesh, and every exchange is an XLA collective over ICI/DCN
(`jax.distributed.initialize` + `multihost_utils`, per SURVEY §2.9's
"distributed communication backend" row).

Control-plane messages (sampling indices, eval stats) ride
`broadcast_one_to_all` / `process_allgather` on DCN; the model average rides
the in-graph psum/all_gather of the sharded round. There are no send/recv
threads, no 0.3 s poll loops, no MPI.Abort shutdown (SURVEY §7 defects).

Single-process runs (process_count == 1) degrade to no-ops so the same
training script works from a laptop to a multi-host pod.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import numpy as np

log = logging.getLogger(__name__)

# bounded by default: an unconfigured peer-wait must surface as an error in
# minutes, not hang the pod forever (the reference's mpirun deployment hangs)
DEFAULT_INIT_TIMEOUT = 300


def _distributed_initialized() -> bool:
    """jax.distributed.is_initialized arrived in newer jax; fall back to the
    runtime's global client handle on versions without it."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None,
                   initialization_timeout: int | None = None) -> dict[str, int]:
    """Initialize the JAX distributed runtime (idempotent; no-op when
    unconfigured single-process). Returns topology info.

    ``initialization_timeout`` (seconds, default ``DEFAULT_INIT_TIMEOUT``)
    bounds how long a process waits for missing peers at startup — a dead
    silo then surfaces as a clean RuntimeError naming the coordinator and
    this process's slot instead of an indefinite hang (the reference's
    mpirun deployment just hangs; tests/test_multihost.py asserts the
    error)."""
    if coordinator_address is not None:
        if _distributed_initialized():
            log.info("jax.distributed already initialized — skipping")
        else:
            timeout = (DEFAULT_INIT_TIMEOUT if initialization_timeout is None
                       else initialization_timeout)
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=timeout,
                )
            except Exception as e:
                # rewrap with the topology facts the operator needs to act
                # (which silo is missing is almost always answerable from
                # "who am I, who was I waiting for"); the original traceback
                # rides along via __cause__
                raise RuntimeError(
                    f"jax.distributed.initialize timed out or failed after "
                    f"{timeout}s (coordinator={coordinator_address}, "
                    f"process_id={process_id}, num_processes={num_processes})"
                    f": {e}. Check that every process slot is up and can "
                    f"reach the coordinator address, then relaunch."
                ) from e
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def broadcast_from_server(value: Any) -> Any:
    """Process-0 value -> every process (the reference's send_init_msg /
    sync broadcast, FedAvgServerManager.py:31-37, as one DCN collective)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def allgather_metrics(local_metrics: dict[str, float]) -> dict[str, float]:
    """Sum scalar metrics across processes (replaces per-client MPI metric
    messages feeding server-side eval, FedAVGAggregator.py:109-163)."""
    if jax.process_count() == 1:
        return dict(local_metrics)
    from jax.experimental import multihost_utils

    keys = sorted(local_metrics)
    vec = np.asarray([local_metrics[k] for k in keys], np.float64)
    gathered = multihost_utils.process_allgather(vec)
    summed = np.asarray(gathered).sum(axis=0)
    return {k: float(v) for k, v in zip(keys, summed)}


def assert_same_across_processes(value: np.ndarray, name: str = "value"):
    """Cross-host agreement check (debugging aid for silo drift; the
    reference has no equivalent — SURVEY §5 race/failure detection gaps)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.assert_equal(value, f"{name} differs across processes")


def round_barrier(name: str, round_idx: int):
    """Named sync point between rounds (replaces the implicit MPI ordering)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"{name}_{round_idx}")
