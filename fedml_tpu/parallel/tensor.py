"""Tensor-parallel federated rounds: regex partition rules on a 2D
('clients', 'tensor') mesh.

Promotes `analysis/partition.py::match_partition_rules` from the lint-only
coverage contract (PR 3) to a runtime sharding subsystem: per-model-family
rule tables below resolve a variables/opt-state tree into a PartitionSpec
tree over `make_tensor_mesh`'s ('clients', 'tensor') mesh, and
`build_tensor_round_fn` runs the federated round under pjit with the
persistent state tensor-sharded and DONATED (old shards alias the new).
Cohort sharding and the optional trailing participation mask are exactly
the PR 4/5 contract — same key table, same quarantine staging, same
all-dead no-op guard.

What is sharded (v1):

- the persistent state: global variables AND aggregator state (the FedOpt
  server momenta are param-sized x2) live tensor-sharded between rounds —
  per-device resident param bytes shrink by ~|tensor| (tools/
  bench_tensor_shard.py -> BENCH_SHARD_r01.json);
- the aggregation data path: client update stacks are sliced to the
  device's tensor shard BEFORE the client-axis reductions, so the
  weighted-mean partial sums, the psums that carry them, the FedOpt server
  step and the FedNova recombine all move/compute 1/|tensor| of the bytes;
- the client vmap step computes on gathered (full) params: the explicit
  per-leaf `all_gather` at the round's entry and the `dynamic_slice` at
  the aggregation boundary are the two layer-boundary resharding points —
  the shard_map-manual analog of a `with_sharding_constraint` pair in
  GSPMD-automatic pjit. Splitting the client-step matmuls themselves
  (Megatron-style — the qkv/proj column/row rules below already encode
  that layout) reassociates float contractions and is deliberately left
  to a tolerance-gated follow-up: this path keeps bit-identity.

Bit-identity contract: `all_gather`/`dynamic_slice` are pure data
movement and slicing commutes exactly with every elementwise aggregation
rule, so a tensor-sharded round is BIT-IDENTICAL in f32 to the replicated
round on the same mesh (REPLICATED_RULES; pinned by
tests/test_tensor_shard.py, fedavg/fedopt/robust/fednova, masked and
unmasked). The same holds in bf16 on this path — no reduction is
reassociated; only a future compute-split would introduce a documented
tolerance. Versus the single-chip vmap engine the usual client-psum
reassociation applies (<=1e-6, same as parallel/sharded.py).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from fedml_tpu.analysis.partition import _flat_paths, match_partition_rules
from fedml_tpu.core.builder import (build_round_core, donation_argnums,
                                    masked_psum_tail, shard_key_slice)
from fedml_tpu.core.config import FedConfig
from fedml_tpu.utils.jax_compat import shard_map

CLIENT_AXIS = "clients"
TENSOR_AXIS = "tensor"

# --------------------------------------------------------------- rule tables
#
# (path regex, spec) per model family; first match wins, scalars
# auto-replicate, an UNMATCHED leaf raises — that is the coverage contract,
# held at 100% over these tables by graft-lint's
# partition-coverage[tensor-rules] rule (analysis/targets.py). Rules are
# matched against opt-state trees too (optax paths embed the param path, so
# `kernel$` covers `0/mu/block0/qkv/kernel`).

# Megatron layout for the transformer blocks: qkv/mlp_up are
# column-parallel (shard out-features = heads / ffn dim), proj/mlp_down are
# row-parallel (shard in-features — the same heads / ffn dim), embeddings
# and lm_head shard d_model. Norms and biases replicate.
TRANSFORMER_PARTITION_RULES: List[Tuple[str, PS]] = [
    # LoRA adapters (models/lora.py) replicate: rank-r factors are tiny and
    # every device needs both to fold base + A @ B. The frozen base keeps
    # matching the kernel rules below through its lora_base/... paths, so a
    # LoRA-wrapped model tensor-shards the big frozen matrices while the
    # federated (trainable) tree stays replicated. First match wins.
    (r"lora_[AB]$", PS()),
    (r"(tok_emb|pos_emb)/embedding$", PS(None, TENSOR_AXIS)),
    (r"qkv/kernel$", PS(None, TENSOR_AXIS)),
    (r"proj/kernel$", PS(TENSOR_AXIS, None)),
    (r"mlp_up/kernel$", PS(None, TENSOR_AXIS)),
    (r"mlp_down/kernel$", PS(TENSOR_AXIS, None)),
    (r"lm_head/kernel$", PS(TENSOR_AXIS, None)),
    (r"(bias|scale)$", PS()),
]

# LSTM gate kernels shard their out-features (the hidden dim), embeddings
# shard the embedding dim, the vocab-sized output projections shard
# out-features. 670-unit stackoverflow kernels are not divisible by small
# tensor axes — resolve_param_specs demotes those leaves to replicated.
RNN_PARTITION_RULES: List[Tuple[str, PS]] = [
    (r"lora_[AB]$", PS()),  # adapters replicate (see transformer table)
    (r"embeddings/embedding$", PS(None, TENSOR_AXIS)),
    (r"OptimizedLSTMCell_\d+/[ih][ifgo]/kernel$", PS(None, TENSOR_AXIS)),
    (r"fc\d?/kernel$", PS(None, TENSOR_AXIS)),
    (r"(bias|scale)$", PS()),
]

# Fallback for the rest of the zoo (lr / mlp / cnn...): shard dense
# in-features (dim 0 — always the large dim for classifier heads), keep
# everything else replicated. Conv kernels ([kh, kw, cin, cout]) hit the
# kernel rule on their tiny kh dim and get demoted to replicated — safe,
# just not sharded.
DEFAULT_TENSOR_RULES: List[Tuple[str, PS]] = [
    (r"lora_[AB]$", PS()),  # adapters replicate (see transformer table)
    (r"embedding$", PS(None, TENSOR_AXIS)),
    (r"kernel$", PS(TENSOR_AXIS, None)),
    (r"(bias|scale)$", PS()),
    (r"(mean|var|count)$", PS()),
]

# every leaf replicated — the baseline arm of the bit-identity tests and
# bench (same program, gathers and slices fold to no-ops)
REPLICATED_RULES: List[Tuple[str, PS]] = [(r".", PS())]

RULE_TABLES = {
    "transformer": TRANSFORMER_PARTITION_RULES,
    "rnn": RNN_PARTITION_RULES,
}

# registry models each family's table must cover at 100% (the lint pin)
FAMILY_MODELS = {
    "transformer": ("transformer_nwp",),
    "rnn": ("rnn", "rnn_stackoverflow"),
}


def rules_for_model(model_name: str) -> List[Tuple[str, PS]]:
    """Family rule table for a registry model name (prefix dispatch);
    unknown families fall back to the generic dense table."""
    if model_name.startswith("transformer"):
        return TRANSFORMER_PARTITION_RULES
    if model_name.startswith("rnn"):
        return RNN_PARTITION_RULES
    return DEFAULT_TENSOR_RULES


# ---------------------------------------------------------- spec resolution

def _tensor_dim(spec) -> Optional[int]:
    """Index of the dim a spec shards over the tensor axis (None if the
    leaf is replicated over it)."""
    if not isinstance(spec, PS):
        return None
    for d, ax in enumerate(spec):
        if ax == TENSOR_AXIS or (isinstance(ax, (tuple, list))
                                 and TENSOR_AXIS in ax):
            return d
    return None


def resolve_param_specs(rules: Sequence[Tuple[str, PS]], tree,
                        tensor_shards: int):
    """match_partition_rules + per-leaf divisibility demotion.

    Returns (spec_tree, demoted) where `demoted` lists the paths whose
    matched rule shards a dim not divisible by `tensor_shards` — those
    leaves fall back to replicated (explicitly, here, instead of deep in a
    device_put error). Raises ValueError on an unmatched leaf, same as the
    lint contract."""
    specs = match_partition_rules(rules, tree)
    flat_leaves = _flat_paths(tree)
    flat_specs = [s for _, s in _flat_paths(specs)]
    demoted: List[str] = []
    resolved = []
    for (path, leaf), spec in zip(flat_leaves, flat_specs):
        d = _tensor_dim(spec)
        if d is not None and (d >= getattr(leaf, "ndim", 0)
                              or leaf.shape[d] % tensor_shards):
            demoted.append(path)
            spec = PS()
        resolved.append(spec)
    spec_tree = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(tree), resolved)
    return spec_tree, demoted


@dataclasses.dataclass(frozen=True)
class TensorSharding:
    """The `param_sharding` seam: a ('clients', 'tensor') mesh plus the
    rule table that places every persistent-state leaf on it. Passed to
    `algorithms/engine.py::build_round_fn` to swap the single-chip vmap
    round for the tensor-sharded one."""

    mesh: Mesh
    rules: Tuple[Tuple[str, PS], ...]

    @classmethod
    def for_model(cls, mesh: Mesh, model_name: str) -> "TensorSharding":
        return cls(mesh, tuple(rules_for_model(model_name)))

    @property
    def tensor_shards(self) -> int:
        return self.mesh.shape[TENSOR_AXIS]

    def specs(self, tree):
        return resolve_param_specs(self.rules, tree, self.tensor_shards)[0]

    def shardings(self, tree):
        specs = self.specs(tree)
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs,
                            is_leaf=lambda s: isinstance(s, PS))

    def place(self, tree):
        """Commit a host/replicated state tree to its tensor-sharded
        layout (one device_put per leaf). The round donates these buffers
        and returns identically-sharded ones."""
        return jax.device_put(tree, self.shardings(tree))

    def per_device_bytes(self, tree) -> Tuple[int, int]:
        """(replicated_bytes, sharded_bytes) a single device holds for
        `tree` — the BENCH_SHARD accounting, computable from specs alone."""
        specs, _ = resolve_param_specs(self.rules, tree, self.tensor_shards)
        flat = _flat_paths(tree)
        flat_specs = [s for _, s in _flat_paths(specs)]
        repl = shard = 0
        for (_, leaf), spec in zip(flat, flat_specs):
            nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            repl += nbytes
            shard += nbytes // (self.tensor_shards
                                if _tensor_dim(spec) is not None else 1)
        return repl, shard


# -------------------------------------------------- shard-local tree movers

def _gather_tree(tree, specs):
    """Reassemble full leaves from tensor shards (tiled all_gather on each
    sharded leaf's dim) — the round-entry layer boundary."""
    def gather(leaf, spec):
        d = _tensor_dim(spec)
        if d is None:
            return leaf
        return jax.lax.all_gather(leaf, TENSOR_AXIS, axis=d, tiled=True)

    return jax.tree.map(gather, tree, specs,
                        is_leaf=lambda x: isinstance(x, PS))


def _slice_tree(tree, specs, tensor_shards: int, lead: int = 0):
    """This device's tensor shard of full leaves (`lead` skips stacked
    client axes) — the aggregation-boundary reshard. Pure dynamic_slice:
    together with _gather_tree it is exact data movement, the root of the
    bit-identity contract."""
    tidx = jax.lax.axis_index(TENSOR_AXIS)

    def one(leaf, spec):
        d = _tensor_dim(spec)
        if d is None:
            return leaf
        size = leaf.shape[d + lead] // tensor_shards
        return jax.lax.dynamic_slice_in_dim(leaf, tidx * size, size,
                                            axis=d + lead)

    return jax.tree.map(one, tree, specs,
                        is_leaf=lambda x: isinstance(x, PS))


# ------------------------------------------------- codec transport (uplink
# + downlink). The tensor round is the one program whose collectives ARE
# the federation's wire traffic in both directions: the entry all_gather
# broadcasts the model to the client-hosting devices, the clients-axis
# reductions carry the updates back. A codec therefore compresses BOTH
# legs — measured split on the tformer budget program: 1.85 MB of gather
# (downlink) vs 0.47 MB of psum (uplink), so an uplink-only codec could
# never reach the 4x wire shrink the COMMS budget pins.

def _quantized_gather_tree(tree, specs, tensor_shards: int, levels: int):
    """Codec downlink: each device int8-quantizes its local shard slice
    (per-shard scale, deterministic rounding), the all_gather moves int8
    payloads + a (tensor_shards,) f32 scale vector per leaf, and every
    device dequantizes tile-wise. Replicated leaves move no gather bytes
    and pass through exact."""
    def gather(leaf, spec):
        d = _tensor_dim(spec)
        if d is None:
            return leaf
        amax = jnp.max(jnp.abs(leaf))
        scale = jnp.where(amax > 0, amax / levels, jnp.ones((), leaf.dtype))
        q = jnp.clip(jnp.round(leaf / scale), -levels, levels).astype(jnp.int8)
        qg = jax.lax.all_gather(q, TENSOR_AXIS, axis=d, tiled=True)
        sg = jax.lax.all_gather(scale, TENSOR_AXIS)  # (t_sz,) f32
        size = leaf.shape[d]
        shp = qg.shape
        qt = qg.reshape(shp[:d] + (tensor_shards, size) + shp[d + 1:])
        sshape = (1,) * d + (tensor_shards, 1) + (1,) * (len(shp) - d - 1)
        dec = qt.astype(leaf.dtype) * sg.reshape(sshape)
        return dec.reshape(shp)

    return jax.tree.map(gather, tree, specs,
                        is_leaf=lambda x: isinstance(x, PS))


def _shifted_spec(spec, inexact: bool):
    """Residual-leaf spec: leading per-device slot dim over CLIENT_AXIS,
    trailing dims tensor-sharded like the gv leaf (passthrough leaves keep
    only the slot dim)."""
    d = _tensor_dim(spec)
    if d is None or not inexact:
        return PS(CLIENT_AXIS)
    return PS(*((CLIENT_AXIS,) + (None,) * d + (TENSOR_AXIS,)))


def codec_residual_specs(specs_gv, global_variables):
    """PartitionSpecs for the tensor round's uplink residual tree."""
    return jax.tree.map(
        lambda s, l: _shifted_spec(s, jnp.issubdtype(l.dtype, jnp.inexact)),
        specs_gv, global_variables, is_leaf=lambda x: isinstance(x, PS))


def init_codec_agg_state(sharding: "TensorSharding", global_variables,
                         inner_state):
    """Placed {"agg", "codec"} state for a codec-on tensor round: the inner
    aggregator state tensor-sharded as usual, plus the per-device
    error-feedback residual (zeros, one slot per clients-axis device,
    trailing dims sharded like gv). Donated with the rest of the state."""
    from fedml_tpu.models.lora import strip_lora_base

    # the residual mirrors the WIRE tree — adapters-only under LoRA (the
    # frozen base never crosses the uplink, so it carries no error feedback)
    fed_gv = strip_lora_base(global_variables)
    n_cl = sharding.mesh.shape[CLIENT_AXIS]
    resid = jax.tree.map(
        lambda l: jnp.zeros(
            (n_cl,) + (l.shape if jnp.issubdtype(l.dtype, jnp.inexact)
                       else ()), l.dtype),
        fed_gv)
    specs_gv = sharding.specs(fed_gv)
    rspecs = codec_residual_specs(specs_gv, fed_gv)
    shardings = jax.tree.map(
        lambda s: NamedSharding(sharding.mesh, s), rspecs,
        is_leaf=lambda s: isinstance(s, PS))
    return {
        "agg": sharding.place(inner_state),
        "codec": jax.device_put(resid, shardings),
    }


def _add_noise_sharded(aggregator, avg_shard, rng, full_params, specs_params,
                       tensor_shards: int):
    """RobustAggregator._add_noise with the SAME full-shape normal draws as
    the replicated path, sliced to this device's shard — key-per-leaf and
    draw shape unchanged, so sharded noise == replicated noise[shard]."""
    noise_rng = jax.random.fold_in(rng, 7)
    leaves, treedef = jax.tree.flatten(avg_shard["params"])
    full_leaves = jax.tree.leaves(full_params)
    spec_leaves = [s for _, s in _flat_paths(specs_params)]
    keys = jax.random.split(noise_rng, len(leaves))
    tidx = jax.lax.axis_index(TENSOR_AXIS)
    noisy = []
    for leaf, key, full, spec in zip(leaves, keys, full_leaves, spec_leaves):
        noise = aggregator.cfg.stddev * jax.random.normal(
            key, full.shape, leaf.dtype)
        d = _tensor_dim(spec)
        if d is not None:
            size = full.shape[d] // tensor_shards
            noise = jax.lax.dynamic_slice_in_dim(noise, tidx * size, size,
                                                 axis=d)
        noisy.append(leaf + noise)
    out = dict(avg_shard)
    out["params"] = jax.tree.unflatten(treedef, noisy)
    return out


def _aggregate_sharded(aggregator, gv_shard, gv_full, result, result_shard,
                       weights, rng, agg_state, specs_gv, tensor_shards):
    """Dispatch one aggregator over tensor-sharded client stacks.

    fedavg/fedopt/fednova are elementwise over param dims, so their
    existing `sharded` (clients-psum) rules run unchanged on shard-sized
    trees — slicing commutes exactly. RobustAggregator's clip norm is a
    reduction over the WHOLE tree, so the clip runs on the full stacks
    (replicated over tensor — deterministic) and only the clipped result
    is sliced into the mean; the DP noise slices the replicated full-shape
    draw (see _add_noise_sharded)."""
    from fedml_tpu.algorithms.aggregators import (RobustAggregator,
                                                  tree_weighted_mean_psum)

    if isinstance(aggregator, RobustAggregator):
        clipped = aggregator._clipped(gv_full, result)
        clipped_shard = _slice_tree(clipped, specs_gv, tensor_shards, lead=1)
        avg = tree_weighted_mean_psum(clipped_shard, weights, CLIENT_AXIS)
        avg = _add_noise_sharded(aggregator, avg, rng, gv_full["params"],
                                 specs_gv["params"], tensor_shards)
        return avg, agg_state
    return aggregator.sharded(gv_shard, result_shard, weights, rng,
                              agg_state, CLIENT_AXIS)


# ------------------------------------------------------------ round builder

def build_tensor_round_fn(trainer, cfg: FedConfig, aggregator,
                          sharding: TensorSharding,
                          donate_state: bool = True,
                          donate_data: bool = False,
                          collect_stats: bool = False,
                          codec=None) -> Callable:
    """Jitted tensor-sharded round over sharding.mesh — the runtime the
    rule tables exist for.

    Same signature and semantics as engine.build_round_fn /
    parallel.sharded.build_sharded_round_fn:
    (gv, agg_state, x, y, counts, rng[, participation]) ->
    (new_gv, new_agg_state, metrics), where gv/agg_state live
    tensor-sharded (place them once with `sharding.place`; outputs come
    back identically sharded). C must divide by mesh.shape['clients'];
    the participation mask arms PR-4 fault tolerance bit-identically to
    the replicated round (quarantine runs on the FULL stacks — a NaN in
    any tensor shard quarantines the client everywhere).

    `donate_state` (default ON — pjit donation of argnums (0, 1)) aliases
    the old state shards into the new: between-round state costs ONE
    sharded copy of params + opt state. Callers that snapshot live state
    refs (the guard's rollback) must turn it off. `donate_data` matches
    the engine's opt-in cohort-buffer donation for the pipelined loop.
    """
    from fedml_tpu.algorithms.aggregators import quarantine_stage
    from fedml_tpu.algorithms.engine import build_local_update, cohort_stats
    from fedml_tpu.models.lora import attach_lora_base, strip_lora_base

    mesh = sharding.mesh
    n_cl = mesh.shape[CLIENT_AXIS]
    t_sz = mesh.shape[TENSOR_AXIS]
    local_update = build_local_update(trainer, cfg, pvary_axes=(CLIENT_AXIS,))

    if codec is not None:
        from fedml_tpu.algorithms.aggregators import (FedAvgAggregator,
                                                      FedOptAggregator)
        if not isinstance(aggregator, (FedAvgAggregator, FedOptAggregator)):
            raise ValueError(
                "update codecs on the tensor path support fedavg/fedopt "
                "only: robust clips whole-tree norms of raw client deltas "
                "and fednova recombines per-client taus — both would "
                "silently run on already-decoded values. Got %r"
                % type(aggregator).__name__)
        # downlink grid: reuse the int8 codec's level count; top-k has no
        # scalar grid of its own, so its downlink rides the full int8 one
        down_levels = codec.levels if codec.kind == "int8" else 127
        is_fedopt = isinstance(aggregator, FedOptAggregator)

    def specialize(specs_gv, specs_st, masked: bool):
        # federated LoRA: client results are adapters-only (the base leaves
        # local_update inside the vmap), so every aggregation-side tree.map
        # must run over the base-stripped "federated view" of gv/specs —
        # identical to the full trees when the trainer isn't wrapped
        specs_fed = strip_lora_base(specs_gv) if isinstance(specs_gv, dict) \
            else specs_gv

        def shard_body(gv_shard, st_shard, x, y, counts, rng,
                       participation=None):
            c_local = x.shape[0]
            didx = jax.lax.axis_index(CLIENT_AXIS)
            # same key table as the vmap engine / 1-D sharded round:
            # split(rng, C)[d*c_local:(d+1)*c_local]
            crngs = shard_key_slice(rng, c_local * n_cl, didx, c_local)
            gv_full = _gather_tree(gv_shard, specs_gv)
            result = jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
                gv_full, x, y, counts, crngs)
            # ledger stats: per-client rows from the FULL (gathered) result,
            # so they are invariant over the tensor axis by the same
            # argument as result.metrics — check_vma accepts the
            # PS(CLIENT_AXIS) out-spec with zero new collectives
            stats = cohort_stats(gv_full, result) if collect_stats else None
            weights = counts.astype(jnp.float32)
            if participation is not None:
                result, weights, alive, quarantined = quarantine_stage(
                    result, weights, participation)
            result_shard = result._replace(variables=_slice_tree(
                result.variables, specs_fed, t_sz, lead=1))
            new_gshard, new_st = _aggregate_sharded(
                aggregator, strip_lora_base(gv_shard),
                strip_lora_base(gv_full), result, result_shard,
                weights, rng, st_shard, specs_fed, t_sz)
            # the server's frozen base shards re-attach untouched (no-op
            # when the trainer isn't LoRA-wrapped)
            new_gshard = attach_lora_base(new_gshard, gv_shard)
            metrics = {k: jax.lax.psum(v.sum(), CLIENT_AXIS)
                       for k, v in result.metrics.items()}
            if participation is None:
                if collect_stats:
                    return new_gshard, new_st, metrics, stats
                return new_gshard, new_st, metrics
            new_gshard, new_st, metrics = masked_psum_tail(
                new_gshard, new_st, metrics, alive, quarantined,
                gv_shard, st_shard, CLIENT_AXIS)
            if collect_stats:
                return new_gshard, new_st, metrics, stats
            return new_gshard, new_st, metrics

        def shard_body_codec(gv_shard, st_shard, x, y, counts, rng,
                             participation=None):
            """Codec-on twin of shard_body: int8 downlink on the entry
            gather, codec uplink (transport_wsum) on the clients-axis
            reduction of locally-weighted delta partial sums, device-
            resident error-feedback residual in st_shard["codec"]."""
            from fedml_tpu.codecs.transport import transport_wsum

            inner_st = st_shard["agg"]
            resid = st_shard["codec"]
            c_local = x.shape[0]
            didx = jax.lax.axis_index(CLIENT_AXIS)
            crngs = shard_key_slice(rng, c_local * n_cl, didx, c_local)
            gv_full = _quantized_gather_tree(gv_shard, specs_gv, t_sz,
                                             down_levels)
            result = jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
                gv_full, x, y, counts, crngs)
            stats = cohort_stats(gv_full, result) if collect_stats else None
            weights = counts.astype(jnp.float32)
            if participation is not None:
                result, weights, alive, quarantined = quarantine_stage(
                    result, weights, participation)
            vars_shard = _slice_tree(result.variables, specs_fed, t_sz,
                                     lead=1)
            fed_gshard = strip_lora_base(gv_shard)

            # local numerator partials: sum_i w_i * (vars_i - gv) for
            # inexact leaves (deltas are what the codec encodes — small,
            # zero-centered), plain weighted sums for passthrough leaves
            def local_partial(l, g):
                wb = weights.reshape((-1,) + (1,) * (l.ndim - 1))
                if jnp.issubdtype(l.dtype, jnp.inexact):
                    return jnp.sum((l - g[None]) * wb.astype(l.dtype),
                                   axis=0)
                return jnp.sum(l * wb.astype(l.dtype), axis=0)

            wsum = jax.tree.map(local_partial, vars_shard, fed_gshard)
            r0 = jax.tree.map(lambda r: r[0], resid)
            num, r_new = transport_wsum(codec, wsum, r0, CLIENT_AXIS, n_cl)
            den = jax.lax.psum(weights.sum(), CLIENT_AXIS)
            inv = 1.0 / jnp.maximum(den, 1e-12)
            avg = jax.tree.map(
                lambda g, s: (g + s * jnp.asarray(inv, s.dtype)).astype(
                    g.dtype)
                if jnp.issubdtype(g.dtype, jnp.inexact)
                else (s * inv).astype(g.dtype),
                fed_gshard, num)
            if is_fedopt:
                new_gshard, new_inner = aggregator._server_step(
                    fed_gshard, avg, inner_st)
            else:
                new_gshard, new_inner = avg, inner_st
            new_gshard = attach_lora_base(new_gshard, gv_shard)
            new_st = {"agg": new_inner,
                      "codec": jax.tree.map(lambda r: r[None], r_new)}
            metrics = {k: jax.lax.psum(v.sum(), CLIENT_AXIS)
                       for k, v in result.metrics.items()}
            if participation is None:
                if collect_stats:
                    return new_gshard, new_st, metrics, stats
                return new_gshard, new_st, metrics
            new_gshard, new_st, metrics = masked_psum_tail(
                new_gshard, new_st, metrics, alive, quarantined,
                gv_shard, st_shard, CLIENT_AXIS)
            if collect_stats:
                return new_gshard, new_st, metrics, stats
            return new_gshard, new_st, metrics

        body = shard_body if codec is None else shard_body_codec
        data_specs = (PS(CLIENT_AXIS), PS(CLIENT_AXIS), PS(CLIENT_AXIS))
        in_specs = (specs_gv, specs_st) + data_specs + (PS(),)
        if masked:
            in_specs = in_specs + (PS(CLIENT_AXIS),)
        out_specs = (specs_gv, specs_st, PS())
        if collect_stats:
            out_specs = out_specs + (PS(CLIENT_AXIS),)
        fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs)
        donate = donation_argnums(donate_state, donate_data)
        return jax.jit(fn, donate_argnums=donate) if donate else jax.jit(fn)

    cache: dict = {}

    def _specialized(global_variables, agg_state, masked: bool):
        key = (jax.tree.structure(global_variables),
               tuple(l.shape for l in jax.tree.leaves(global_variables)),
               jax.tree.structure(agg_state),
               tuple(l.shape for l in jax.tree.leaves(agg_state)),
               masked)
        jitted = cache.get(key)
        if jitted is None:
            specs_gv = sharding.specs(global_variables)
            if codec is not None:
                # wrapped {"agg", "codec"} state (init_codec_agg_state):
                # inner state sharded as usual, residual rows on the
                # shifted (CLIENT_AXIS, ..., TENSOR_AXIS) layout
                from fedml_tpu.models.lora import strip_lora_base as _strip
                fed_gv = _strip(global_variables)
                specs_st = {
                    "agg": sharding.specs(agg_state["agg"]),
                    "codec": codec_residual_specs(_strip(specs_gv)
                                                  if isinstance(specs_gv,
                                                                dict)
                                                  else specs_gv, fed_gv),
                }
            else:
                specs_st = sharding.specs(agg_state)
            jitted = specialize(specs_gv, specs_st, masked)
            cache[key] = jitted
        return jitted

    def round_fn(global_variables, agg_state, x, y, counts, rng,
                 participation=None):
        jitted = _specialized(global_variables, agg_state,
                              participation is not None)
        round_fn.jitted = jitted  # graft-lint donation introspection
        args = (global_variables, agg_state, x, y, counts, rng)
        if participation is not None:
            args += (participation,)
        # CPU can't alias some donated shapes — the fallback is a plain
        # copy, so the per-compile warning is noise (engine.py idiom)
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=".*onat")
            return jitted(*args)

    def lower(*args):
        """jax.jit-compatible lower — the HLO engine (analysis/comms.py)
        lowers round programs from ShapeDtypeStructs without executing."""
        return _specialized(args[0], args[1], len(args) > 6).lower(*args)

    round_fn.lower = lower
    round_fn.sharding = sharding
    round_fn.donate_state = donate_state

    from fedml_tpu import telemetry
    telemetry.emit("round_fn_built", program="tensor.round",
                   donate=donate_state,
                   mesh=f"{n_cl}x{t_sz}",
                   codec=(codec.name if codec is not None else "none"))
    return round_fn


# ----------------------------------------- activation-sharded step (GSPMD)
#
# The shard_map round above gathers FULL params to every device before the
# client vmap step — per-device peak bytes during the step scale with the
# whole model. `--shard_step` swaps that for GSPMD automatic partitioning:
# the round jits with params tensor-sharded per the rule table as
# `in_shardings` and the model zoo's `constrain()` hooks
# (parallel/activations.py) pin attention/MLP/logits intermediates to the
# tensor axis, so the step's matmuls split Megatron-style and the big
# activations never materialize whole on one device. Measured on the forced
# 8-device CPU mesh: 0.24x per-device peak temp bytes for the transformer
# step at 4 shards (COMMS_BUDGET.json `tensor.step` twins pin the <=0.5x
# ratio in CI). The trade, documented in ROADMAP/PERF: GSPMD reassociates
# float contractions, so `shard_step` carries an allclose contract
# (tests/test_lora.py pins the tolerance) instead of the shard_map path's
# f32 bit-identity; at tensor_shards <= 1 the constraint scope is
# structurally off and the program is the plain jitted round.

def build_tensor_step_fn(trainer, cfg: FedConfig, sharding: TensorSharding,
                         activation_rules="auto"):
    """The client step ALONE — vmap(local_update) jitted under GSPMD with
    rule-table `in_shardings` and the activation-constraint scope. This is
    the `tensor.step` program analysis/comms.py lowers for the per-device
    peak-bytes budgets; the full drive uses build_tensor_step_round_fn.

    `activation_rules`: "auto" looks the model family's table up
    (parallel/activations.py); None disables the constraint scope — the
    replicated budget twin the <=0.5x peak ratio is measured against."""
    from fedml_tpu.algorithms.engine import build_local_update
    from fedml_tpu.parallel.activations import (activation_rules_for_model,
                                                activation_sharding)

    mesh = sharding.mesh
    act_rules = (activation_rules_for_model(cfg.model)
                 if activation_rules == "auto" else activation_rules)
    local_update = build_local_update(trainer, cfg)

    def step(global_variables, x, y, counts, rng):
        crngs = jax.random.split(rng, x.shape[0])
        return jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
            global_variables, x, y, counts, crngs)

    data_sh = NamedSharding(mesh, PS(CLIENT_AXIS))
    cache: dict = {}

    def _specialized(gv):
        key = (jax.tree.structure(gv),
               tuple((l.shape, str(l.dtype)) for l in jax.tree.leaves(gv)))
        jitted = cache.get(key)
        if jitted is None:
            jitted = jax.jit(step, in_shardings=(
                sharding.shardings(gv), data_sh, data_sh, data_sh, None))
            cache[key] = jitted
        return jitted

    def step_fn(global_variables, x, y, counts, rng):
        # the constraint hooks read the scope at TRACE time; entering it
        # around every call keeps cached traces consistent (the scope is a
        # constant of this builder)
        with activation_sharding(mesh, act_rules):
            return _specialized(global_variables)(
                global_variables, x, y, counts, rng)

    def lower(*args):
        with activation_sharding(mesh, act_rules):
            return _specialized(args[0]).lower(*args)

    step_fn.lower = lower
    step_fn.sharding = sharding
    return step_fn


def build_tensor_step_round_fn(trainer, cfg: FedConfig, aggregator,
                               sharding: TensorSharding,
                               donate_state: bool = True,
                               donate_data: bool = False,
                               collect_stats: bool = False,
                               codec=None) -> Callable:
    """The `--shard_step` round: engine.round_fn semantics (same rng table,
    same quarantine staging, same all-dead no-op guard, same LoRA
    strip/attach) jitted under GSPMD on sharding.mesh — params, opt state
    AND the step's intermediates tensor-sharded; aggregation math is plain
    jnp that GSPMD partitions. State lives sharded between rounds exactly
    like the shard_map tensor round (`sharding.place` once, outputs come
    back identically sharded), so FedAvgAPI's tensor plumbing works
    unchanged."""
    if codec is not None:
        raise ValueError(
            "--shard_step runs under GSPMD automatic partitioning — the "
            "codec transports are manual shard_map collectives and do not "
            "compose with it. Drop --shard_step (the storage-sharded "
            "tensor round supports codecs) or --update_codec.")
    from fedml_tpu.algorithms.engine import _vmapped_update
    from fedml_tpu.parallel.activations import (activation_rules_for_model,
                                                activation_sharding)

    mesh = sharding.mesh
    n_cl = mesh.shape[CLIENT_AXIS]
    t_sz = mesh.shape[TENSOR_AXIS]
    act_rules = activation_rules_for_model(cfg.model)
    # the round body IS the engine's round: the shared core from
    # core/builder.py (same rng table, quarantine staging, all-dead guard,
    # LoRA strip/attach), jitted under GSPMD instead of plain jit — the
    # --equiv engine proves the two programs identical up to sharding
    # annotations (the tensor-shards-1 contract)
    core = build_round_core(_vmapped_update(trainer, cfg), aggregator,
                            collect_stats)

    def round_body(global_variables, agg_state, x, y, counts, rng,
                   participation=None):
        new_global, new_state, metrics, stats = core(
            global_variables, agg_state, x, y, counts, rng, participation)
        if collect_stats:
            return new_global, new_state, metrics, stats
        return new_global, new_state, metrics

    data_sh = NamedSharding(mesh, PS(CLIENT_AXIS))
    repl_sh = NamedSharding(mesh, PS())
    cache: dict = {}

    def _specialized(global_variables, agg_state, masked: bool):
        key = (jax.tree.structure(global_variables),
               tuple(l.shape for l in jax.tree.leaves(global_variables)),
               jax.tree.structure(agg_state),
               tuple(l.shape for l in jax.tree.leaves(agg_state)),
               masked)
        jitted = cache.get(key)
        if jitted is None:
            gv_sh = sharding.shardings(global_variables)
            st_sh = sharding.shardings(agg_state)
            in_sh = (gv_sh, st_sh, data_sh, data_sh, data_sh, None)
            if masked:
                in_sh = in_sh + (data_sh,)
            out_sh = (gv_sh, st_sh, repl_sh)
            if collect_stats:
                out_sh = out_sh + (data_sh,)
            donate = donation_argnums(donate_state, donate_data)
            jitted = jax.jit(round_body, in_shardings=in_sh,
                             out_shardings=out_sh, donate_argnums=donate)
            cache[key] = jitted
        return jitted

    def round_fn(global_variables, agg_state, x, y, counts, rng,
                 participation=None):
        jitted = _specialized(global_variables, agg_state,
                              participation is not None)
        round_fn.jitted = jitted  # graft-lint donation introspection
        args = (global_variables, agg_state, x, y, counts, rng)
        if participation is not None:
            args += (participation,)
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=".*onat")
            with activation_sharding(mesh, act_rules):
                return jitted(*args)

    def lower(*args):
        with activation_sharding(mesh, act_rules):
            return _specialized(args[0], args[1],
                                len(args) > 6).lower(*args)

    round_fn.lower = lower
    round_fn.sharding = sharding
    round_fn.donate_state = donate_state

    from fedml_tpu import telemetry
    telemetry.emit("round_fn_built", program="tensor.step",
                   donate=donate_state, mesh=f"{n_cl}x{t_sz}")
    return round_fn
