"""Sequence/context parallelism — long-context attention over a device mesh.

The reference has no attention models (SURVEY §2.9 lists SP/CP as absent),
but long-context support is a first-class capability here. Two standard
TPU-native schemes over a `sp` mesh axis:

- `ring_attention`: sequence sharded over devices; K/V blocks rotate around
  the ICI ring via `ppermute` while each device keeps flash-style online
  softmax statistics (running max / denominator / numerator) for its local
  queries. Peak memory per device is O(T/n) — the long-context scheme.
- `ulysses_attention` (DeepSpeed-Ulysses style): two `all_to_all`s reshard
  [B, T/n, H, D] -> [B, T, H/n, D], run full attention locally per head
  shard, and reshard back. Cheaper collectives when H >= n_devices.

Both are bit-close to `fedml_tpu.ops.attention_reference` on a virtual CPU
mesh (tested) and compose with the rest of the framework's shard_map world
(the `sp` axis can live alongside the `clients` axis in one mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.utils.jax_compat import pcast, shard_map


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False):
    """q/k/v: [B, T, H, D] GLOBAL arrays, sequence dim sharded over
    mesh[axis]. Returns attention output with the same sharding."""
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by "
                         f"{axis} axis size {n}")
    t_local = q.shape[1] // n
    scale = 1.0 / np.sqrt(q.shape[-1])

    def body(q, k, v):
        # local shards: [B, T/n, H, D]
        d_idx = jax.lax.axis_index(axis)
        qf = q.astype(jnp.float32) * scale
        q_pos = d_idx * t_local + jnp.arange(t_local)

        def block_update(o, m, l, kb, vb, t):
            src = (d_idx - t) % n  # which device's block we hold at step t
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
            if causal:
                k_pos = src * t_local + jnp.arange(t_local)
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask[None, None], s, -jnp.inf)
            m_new = jnp.maximum(m, s.max(axis=-1))
            alpha = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m - m_new))
            p = jnp.exp(s - m_new[..., None])
            l = l * alpha + p.sum(axis=-1)
            o = o * alpha[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
            return o, m_new, l

        def step(carry, t):
            o, m, l, kb, vb = carry
            o, m, l = block_update(o, m, l, kb, vb, t)
            # rotate K/V blocks one hop around the ring
            perm = [(i, (i + 1) % n) for i in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return (o, m, l, kb, vb), None

        b, _, h, dd = q.shape
        # pcast to varying: the online-softmax stats become device-varying
        # inside the scan (each device sees different K/V blocks); marking
        # the init values keeps jax's check_vma carry typing satisfied
        var = lambda a: pcast(a, (axis,), to="varying")
        o0 = var(jnp.zeros((b, h, t_local, dd), jnp.float32))
        m0 = var(jnp.full((b, h, t_local), -jnp.inf, jnp.float32))
        l0 = var(jnp.zeros((b, h, t_local), jnp.float32))
        # n-1 compute+rotate hops in the scan, final block computed outside —
        # no wasted last rotation on the ICI ring
        (o, m, l, kb, vb), _ = jax.lax.scan(
            step, (o0, m0, l0, k, v), jnp.arange(n - 1))
        o, m, l = block_update(o, m, l, kb, vb, n - 1)
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, T/n, H, D]

    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
    )
    return sharded(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = False):
    """All-to-all sequence parallelism: reshard sequence-sharded Q/K/V to
    head-sharded, attend over the FULL sequence per head shard, reshard
    back. Requires H divisible by the axis size."""
    n = mesh.shape[axis]
    h = q.shape[2]
    if h % n:
        raise ValueError(f"head count {h} not divisible by {axis} size {n}")
    if q.shape[1] % n:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by "
                         f"{axis} size {n}")

    from fedml_tpu.ops.attention import attention_reference

    def body(q, k, v):
        # [B, T/n, H, D] -> all_to_all -> [B, T, H/n, D]
        a2a = partial(jax.lax.all_to_all, axis_name=axis,
                      split_axis=2, concat_axis=1, tiled=True)
        qh, kh, vh = a2a(q), a2a(k), a2a(v)
        out = attention_reference(qh, kh, vh, causal=causal)
        # back: [B, T, H/n, D] -> [B, T/n, H, D]
        return jax.lax.all_to_all(out, axis_name=axis,
                                  split_axis=1, concat_axis=2, tiled=True)

    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
    )
    return sharded(q, k, v)
