"""Activation-sharding constraint hooks — Megatron compute splitting as a
scoped side-channel into the model zoo.

`parallel/tensor.py`'s rule tables shard *storage*: the shard_map round
gathers full params per device before the forward, so the client step's
activations (and the gathered params) still materialize replicated. The
activation-sharded client step (`build_tensor_step_fn`) instead jits the
step under GSPMD with `NamedSharding` in_shardings from the same rule
tables — and the models mark their matmul intermediates with `constrain`
so the partitioner keeps attention/MLP/logits activations split over the
mesh's 'tensor' axis instead of re-gathering them between layers (the
`with_sharding_constraint` pattern, Shoeybi et al. 2019).

The hook is a ContextVar scope: OUTSIDE `activation_sharding(...)` every
`constrain` call is the identity, so the legacy paths (vmap engine,
shard_map tensor.round, buffered client_step) trace byte-identical
programs — activation sharding is structurally off unless a builder opts
in. Inside the scope, a constraint is applied only when the active rule
table names the site AND the mesh's tensor axis is >1 (a 1-shard mesh is
trivially replicated — bit-identity at tensor_shards=1 is preserved).

Specs are written at the rank the model code sees — NOT the client-batched
rank. The client step vmaps the model over the cohort, and vmap's batching
rule prepends the batch dim to every constraint automatically; a spec
written at the batched rank would raise "only valid for values of rank at
least N" at trace time (pinned in tests/test_lora.py).
"""

from __future__ import annotations

from contextlib import contextmanager
from contextvars import ContextVar
from typing import Dict, Optional, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as PS

TENSOR_AXIS = "tensor"

# site name -> PartitionSpec at the model-code rank (batch dims the model
# itself sees are part of the rank; the client vmap dim is NOT).
# Transformer activations are (b, t, channels): shard the channel dim.
TRANSFORMER_ACTIVATION_RULES: Dict[str, PS] = {
    "attn_qkv": PS(None, None, TENSOR_AXIS),    # (b, t, 3*d_model)
    "attn_ctx": PS(None, None, TENSOR_AXIS),    # (b, t, d_model) pre-proj
    "mlp_hidden": PS(None, None, TENSOR_AXIS),  # (b, t, mlp_ratio*d_model)
    "logits": PS(None, None, TENSOR_AXIS),      # (b, t, vocab)
}

# RNN activations are (b, t, channels) too (post-embed / post-LSTM / fc).
RNN_ACTIVATION_RULES: Dict[str, PS] = {
    "embed": PS(None, None, TENSOR_AXIS),       # (b, t, embed_dim)
    "rnn_hidden": PS(None, None, TENSOR_AXIS),  # (b, t, hidden)
    "fc_hidden": PS(None, None, TENSOR_AXIS),   # (b, t, fc width)
    "logits": PS(None, None, TENSOR_AXIS),      # (b, t, vocab)
}

ACTIVATION_RULE_TABLES: Dict[str, Dict[str, PS]] = {
    "transformer": TRANSFORMER_ACTIVATION_RULES,
    "rnn": RNN_ACTIVATION_RULES,
}


def activation_rules_for_model(model_name: str) -> Optional[Dict[str, PS]]:
    """Prefix dispatch mirroring tensor.rules_for_model: transformer* and
    rnn* get their family table; every other model has no constrained
    intermediates (its step shards params only)."""
    for family, rules in ACTIVATION_RULE_TABLES.items():
        if model_name.startswith(family):
            return rules
    return None


_SCOPE: ContextVar[Optional[Tuple]] = ContextVar(
    "activation_sharding_scope", default=None)


@contextmanager
def activation_sharding(mesh, rules: Optional[Dict[str, PS]]):
    """Arm `constrain` for the duration of a trace. `rules=None` (model
    families without a table) leaves every hook as the identity."""
    if rules is None or mesh.shape.get(TENSOR_AXIS, 1) <= 1:
        yield
        return
    token = _SCOPE.set((mesh, rules))
    try:
        yield
    finally:
        _SCOPE.reset(token)


def constrain(x, site: str):
    """Pin intermediate `x`'s sharding when a scope is active; identity
    otherwise. Called from inside the model zoo, so it must stay free on
    every legacy path (no scope -> no-op, not even a reshape)."""
    scope = _SCOPE.get()
    if scope is None:
        return x
    mesh, rules = scope
    spec = rules.get(site)
    if spec is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
