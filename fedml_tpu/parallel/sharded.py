"""shard_map federated round — clients sharded over the mesh, aggregation in-XLA.

Replaces the reference's distributed FedAvg path (SURVEY §3.1): where the
reference runs 1 MPI process per worker and the server does a per-key numpy
average of gathered state_dicts (reference FedAVGAggregator.py:58-87), here
each device trains its shard of the round's clients (vmap over the local
shard), client-stacked results are `all_gather`ed over ICI, and the aggregator
runs replicated on every device — one jitted XLA program, no transport layer.

Exact-equivalence property: per-client RNG keys are assigned from the same
`jax.random.split(rng, C)` table as the single-chip vmap engine, and the tiled
all_gather preserves client order, so the sharded round computes bit-identical
results to `fedml_tpu.algorithms.engine.build_round_fn` (tested in
tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.algorithms.engine import LocalResult, build_local_update
from fedml_tpu.core.config import FedConfig


def build_sharded_round_fn(
    trainer,
    cfg: FedConfig,
    aggregator,
    mesh: Mesh,
    axis: str = "clients",
) -> Callable:
    """Jitted multi-chip round: shard_map(local train) + all_gather + aggregate.

    Inputs mirror build_round_fn: x/y/counts have a leading client axis C which
    must be divisible by mesh.shape[axis] (pad with zero-count clients — they
    are weight-0 no-ops in every aggregator).
    """
    local_update = build_local_update(trainer, cfg)
    n_dev = mesh.shape[axis]

    def shard_body(global_variables, agg_state, x, y, counts, rng):
        c_local = x.shape[0]
        didx = jax.lax.axis_index(axis)
        # same key table as the vmap engine: split(rng, C)[d*c_local:(d+1)*c_local]
        all_keys = jax.random.split(rng, c_local * n_dev)
        crngs = jax.lax.dynamic_slice_in_dim(all_keys, didx * c_local, c_local)
        result = jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
            global_variables, x, y, counts, crngs
        )
        # client-stacked pytrees -> full [C, ...] on every device (ICI collective)
        gather = partial(jax.lax.all_gather, axis_name=axis, tiled=True)
        full = LocalResult(
            jax.tree.map(gather, result.variables),
            gather(result.num_steps),
            jax.tree.map(gather, result.metrics),
        )
        all_counts = gather(counts)
        new_global, new_state = aggregator(
            global_variables, full, all_counts.astype(jnp.float32), rng, agg_state
        )
        metrics = {k: v.sum() for k, v in full.metrics.items()}
        return new_global, new_state, metrics

    def round_fn(global_variables, agg_state, x, y, counts, rng):
        # check_vma=False is deliberate and NARROW in scope: the outputs are
        # derived from `all_gather`ed per-client results, which this jax
        # version's varying-manual-axes system cannot mark as replicated on
        # an Auto-mode mesh (all_gather(to="reduced") demands Explicit axis
        # types; probed 2026-07). The replication this flag would verify is
        # instead asserted STRONGER by tests/test_parallel.py: the sharded
        # round is bit-identical to the single-chip vmap round.
        sharded = jax.shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        return sharded(global_variables, agg_state, x, y, counts, rng)

    return jax.jit(round_fn)
