"""shard_map federated round — clients sharded over the mesh, aggregation in-XLA.

Replaces the reference's distributed FedAvg path (SURVEY §3.1): where the
reference runs 1 MPI process per worker and the server does a per-key numpy
average of gathered state_dicts (reference FedAVGAggregator.py:58-87), here
each device trains its shard of the round's clients (vmap over the local
shard) and aggregation is the aggregator's `sharded` rule: locally weighted
partial sums + param-sized `psum`s over ICI — one jitted XLA program, no
transport layer, no client gather, and machine-checked output replication
(shard_map check_vma stays on; psum outputs are invariant-typed).

Equivalence property: per-client RNG keys are assigned from the same
`jax.random.split(rng, C)` table as the single-chip vmap engine, so local
training is bit-identical per client; aggregation reassociates the weighted
sum across devices (partials-then-psum), equal to the single-chip round up
to float summation order (<=1e-6, tested in tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.algorithms.aggregators import quarantine_stage
from fedml_tpu.algorithms.engine import build_local_update, cohort_stats
from fedml_tpu.core.builder import masked_psum_tail, shard_key_slice
from fedml_tpu.core.config import FedConfig
from fedml_tpu.utils.jax_compat import shard_map


def build_sharded_round_fn(
    trainer,
    cfg: FedConfig,
    aggregator,
    mesh: Mesh,
    axis: str = "clients",
    collect_stats: bool = False,
) -> Callable:
    """Jitted multi-chip round: shard_map(local train) + psum-aggregation.

    Inputs mirror build_round_fn: x/y/counts have a leading client axis C which
    must be divisible by mesh.shape[axis] (pad with zero-count clients — they
    are weight-0 no-ops in every aggregator).

    The optional trailing `participation` ([C] bool, sharded like counts)
    arms in-round fault tolerance: dropped clients and non-finite
    (quarantined) updates become `where`-zeroed zero-weight rows before the
    psum partial sums, so a masked round is bit-identical to the unmasked
    round over the zero-count-padded surviving cohort on the same geometry
    and rng table (tests/test_robustness.py). All-dead rounds pass global
    variables and aggregator state through unchanged. The default
    `participation=None` traces the exact legacy program — COMMS_BUDGET.json
    gates that program's collective counts/bytes, and the masked
    specialization adds only two scalar psums (the participated/quarantined
    counts).
    """
    local_update = build_local_update(trainer, cfg, pvary_axes=(axis,))
    n_dev = mesh.shape[axis]

    # codec-wrapped aggregators carry per-slot error-feedback residual rows
    # in state["codec"] — those rows align with the cohort axis, so they
    # shard like the data while the inner state stays replicated. An
    # unwrapped aggregator keeps the exact legacy P() spec (bit-identity).
    from fedml_tpu.codecs.transport import CodecAggregator
    st_spec = ({"agg": P(), "codec": P(axis)}
               if isinstance(aggregator, CodecAggregator) else P())

    def shard_body(global_variables, agg_state, x, y, counts, rng,
                   participation=None):
        c_local = x.shape[0]
        didx = jax.lax.axis_index(axis)
        # same key table as the vmap engine: split(rng, C)[d*c_local:(d+1)*c_local]
        crngs = shard_key_slice(rng, c_local * n_dev, didx, c_local)
        result = jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
            global_variables, x, y, counts, crngs
        )
        # ledger stats are plain per-client rows of the LOCAL shard (no
        # cross-client reductions in cohort_stats), returned under P(axis):
        # zero new collectives, so the legacy COMMS budget is untouched
        stats = cohort_stats(global_variables, result) if collect_stats \
            else None
        weights = counts.astype(jnp.float32)
        if participation is not None:
            result, weights, alive, quarantined = quarantine_stage(
                result, weights, participation)
        # no client gather: the aggregator's sharded rule reduces locally
        # weighted partial sums with param-sized psums over ICI (at most half
        # the collective bytes of an all_gather of client stacks — asserted
        # against the lowered HLO inventory by tests/test_comms.py::
        # test_psum_aggregation_halves_all_gather_bytes), and psum outputs
        # are invariant-typed — shard_map's check_vma replication
        # verification stays ON (VERDICT r4 weak #3)
        new_global, new_state = aggregator.sharded(
            global_variables, result, weights, rng, agg_state, axis
        )
        metrics = {k: jax.lax.psum(v.sum(), axis) for k, v in result.metrics.items()}
        if participation is None:
            if collect_stats:
                return new_global, new_state, metrics, stats
            return new_global, new_state, metrics
        new_global, new_state, metrics = masked_psum_tail(
            new_global, new_state, metrics, alive, quarantined,
            global_variables, agg_state, axis)
        if collect_stats:
            return new_global, new_state, metrics, stats
        return new_global, new_state, metrics

    # stats rows stay client-sharded end to end: concatenating the device
    # shards under P(axis) reproduces the staged cohort order exactly
    out_specs = (P(), st_spec, P()) + ((P(axis),) if collect_stats else ())

    def round_fn(global_variables, agg_state, x, y, counts, rng,
                 participation=None):
        if participation is None:
            sharded = shard_map(
                shard_body,
                mesh=mesh,
                in_specs=(P(), st_spec, P(axis), P(axis), P(axis), P()),
                out_specs=out_specs,
            )
            return sharded(global_variables, agg_state, x, y, counts, rng)
        sharded = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), st_spec, P(axis), P(axis), P(axis), P(), P(axis)),
            out_specs=out_specs,
        )
        return sharded(global_variables, agg_state, x, y, counts, rng,
                       participation)

    return jax.jit(round_fn)


def build_sharded_buffer_fns(
    aggregator,
    discount_fn,
    mesh: Mesh,
    axis: str = "clients",
    codec=None,
) -> tuple:
    """The buffered-aggregation admit/commit programs with the K-row update
    buffer (and the stacked client-step result) sharded over mesh `axis` —
    the shard_map twin of aggregators.build_buffer_admit/build_buffer_commit.

    `admit(buf, fill, stacked_vars, stacked_steps, stacked_metrics, counts,
    src, birth_round)` moves ONE client row (global index `src` in the
    client-sharded stacked result) into buffer row `fill`: the owning device
    contributes the row to a masked param-sized psum (the twin's only
    admit-time collective — C-invariant, vs. an all_gather's C-fold bytes)
    and the device owning buffer row `fill` where-writes it. `fill` travels
    as a separate replicated scalar — the host mirrors it exactly as in the
    vmap drive loop — so the buffer dict's in_specs stay uniformly P(axis).

    `commit(gv, agg_state, buf, fill, commit_round, rng)` mirrors the vmap
    commit: staleness discount and quarantine run shard-local, then the
    aggregator's `sharded` rule reduces with param-sized psums. Equal to the
    vmap commit up to float summation order, same bar as
    build_sharded_round_fn (tests/test_buffered.py).

    `codec` arms the compressed admit transport: the admit program gains a
    trailing replicated `gv` argument (the delta base), and the owner's row
    crosses the mesh as the codec's encoded payload — masked int8 psums or
    top-k (values, idx) psums instead of the full-width f32 row. The buffer
    stores DECODED f32 rows (storage is device-local; only the wire is
    compressed), so the commit program is unchanged. The codec-on admit is
    a different program with its own COMMS_BUDGET.json entry; `codec=None`
    traces the exact legacy admit."""
    from fedml_tpu.algorithms.engine import LocalResult

    n_dev = mesh.shape[axis]

    def admit_body(buf, fill, stacked_vars, stacked_steps, stacked_metrics,
                   counts, src, birth_round, gv=None):
        c_local = stacked_steps.shape[0]
        k_local = buf["steps"].shape[0]
        didx = jax.lax.axis_index(axis)

        # fetch: the owner's row, everywhere (one param-sized masked psum —
        # or, codec-on, the encoded payload's masked psums)
        src_local = jnp.clip(src - didx * c_local, 0, c_local - 1)
        has_src = (src >= didx * c_local) & (src < (didx + 1) * c_local)

        def fetch(stacked):
            row = jax.lax.dynamic_index_in_dim(stacked, src_local, 0,
                                               keepdims=False)
            return jax.lax.psum(
                jnp.where(has_src, row, jnp.zeros((), row.dtype)), axis)

        if codec is None:
            row_vars = jax.tree.map(fetch, stacked_vars)
        else:
            from fedml_tpu.codecs.transport import masked_row_transport

            def _inexact(l):
                return jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)

            row_local = jax.tree.map(
                lambda s: jax.lax.dynamic_index_in_dim(
                    s, src_local, 0, keepdims=False), stacked_vars)
            delta = jax.tree.map(
                lambda r, g: r - g if _inexact(r) else r, row_local, gv)
            dec = masked_row_transport(codec, delta, axis, has_src)
            row_vars = jax.tree.map(
                lambda g, d, r: (g + d).astype(r.dtype)
                if _inexact(r) else d, gv, dec, row_local)
        row_steps = fetch(stacked_steps)
        row_weight = fetch(counts).astype(jnp.float32)
        row_metrics = {k: fetch(v) for k, v in stacked_metrics.items()}

        # write: only the device owning global buffer row `fill` lands it
        dst_local = jnp.clip(fill - didx * k_local, 0, k_local - 1)
        has_dst = (fill >= didx * k_local) & (fill < (didx + 1) * k_local)

        def put(row_buf, row):
            updated = jax.lax.dynamic_update_index_in_dim(
                row_buf, row.astype(row_buf.dtype), dst_local, 0)
            return jnp.where(has_dst, updated, row_buf)

        return {
            "vars": jax.tree.map(put, buf["vars"], row_vars),
            "steps": put(buf["steps"], row_steps),
            "weights": put(buf["weights"], row_weight),
            "metrics": {k: put(buf["metrics"][k], v)
                        for k, v in row_metrics.items()},
            "birth": put(buf["birth"],
                         jnp.asarray(birth_round, jnp.int32)),
        }

    def commit_body(global_variables, agg_state, buf, fill, commit_round,
                    rng):
        k_local = buf["steps"].shape[0]
        didx = jax.lax.axis_index(axis)
        global_idx = didx * k_local + jnp.arange(k_local, dtype=jnp.int32)
        staleness = (jnp.asarray(commit_round, jnp.int32)
                     - buf["birth"]).astype(jnp.float32)
        weights = buf["weights"] * discount_fn(staleness)
        participation = global_idx < fill
        result = LocalResult(buf["vars"], buf["steps"], buf["metrics"])
        result, weights, alive, quarantined = quarantine_stage(
            result, weights, participation)
        new_global, new_state = aggregator.sharded(
            global_variables, result, weights, rng, agg_state, axis)
        metrics = {k: jax.lax.psum(v.sum(), axis)
                   for k, v in result.metrics.items()}
        new_global, new_state, metrics = masked_psum_tail(
            new_global, new_state, metrics, alive, quarantined,
            global_variables, agg_state, axis)
        alive_f = alive.astype(jnp.float32)
        metrics["staleness_sum"] = jax.lax.psum(
            jnp.sum(staleness * alive_f), axis)
        metrics["staleness_max"] = jax.lax.pmax(
            jnp.max(jnp.where(alive, staleness,
                              jnp.zeros((), jnp.float32))), axis)
        return new_global, new_state, metrics

    buf_spec = {"vars": P(axis), "steps": P(axis), "weights": P(axis),
                "metrics": P(axis), "birth": P(axis)}

    def admit_fn(buf, fill, stacked_vars, stacked_steps, stacked_metrics,
                 counts, src, birth_round, *gv):
        # codec-on admits take a trailing replicated gv (the delta base)
        sharded = shard_map(
            admit_body,
            mesh=mesh,
            in_specs=(buf_spec, P(), P(axis), P(axis), P(axis), P(axis),
                      P(), P()) + ((P(),) if gv else ()),
            out_specs=buf_spec,
        )
        return sharded(buf, fill, stacked_vars, stacked_steps,
                       stacked_metrics, counts, src, birth_round, *gv)

    def commit_fn(global_variables, agg_state, buf, fill, commit_round, rng):
        sharded = shard_map(
            commit_body,
            mesh=mesh,
            in_specs=(P(), P(), buf_spec, P(), P(), P()),
            out_specs=(P(), P(), P()),
        )
        return sharded(global_variables, agg_state, buf, fill, commit_round,
                       rng)

    from fedml_tpu import telemetry
    telemetry.emit("round_fn_built", program="buffered.admit.sharded",
                   donate=False)
    telemetry.emit("round_fn_built", program="buffered.commit.sharded",
                   donate=False)
    return jax.jit(admit_fn), jax.jit(commit_fn)
