"""shard_map federated round — clients sharded over the mesh, aggregation in-XLA.

Replaces the reference's distributed FedAvg path (SURVEY §3.1): where the
reference runs 1 MPI process per worker and the server does a per-key numpy
average of gathered state_dicts (reference FedAVGAggregator.py:58-87), here
each device trains its shard of the round's clients (vmap over the local
shard) and aggregation is the aggregator's `sharded` rule: locally weighted
partial sums + param-sized `psum`s over ICI — one jitted XLA program, no
transport layer, no client gather, and machine-checked output replication
(shard_map check_vma stays on; psum outputs are invariant-typed).

Equivalence property: per-client RNG keys are assigned from the same
`jax.random.split(rng, C)` table as the single-chip vmap engine, so local
training is bit-identical per client; aggregation reassociates the weighted
sum across devices (partials-then-psum), equal to the single-chip round up
to float summation order (<=1e-6, tested in tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.algorithms.aggregators import quarantine_stage
from fedml_tpu.algorithms.engine import build_local_update
from fedml_tpu.core.config import FedConfig
from fedml_tpu.utils.jax_compat import shard_map
from fedml_tpu.utils.pytree import tree_where


def build_sharded_round_fn(
    trainer,
    cfg: FedConfig,
    aggregator,
    mesh: Mesh,
    axis: str = "clients",
) -> Callable:
    """Jitted multi-chip round: shard_map(local train) + psum-aggregation.

    Inputs mirror build_round_fn: x/y/counts have a leading client axis C which
    must be divisible by mesh.shape[axis] (pad with zero-count clients — they
    are weight-0 no-ops in every aggregator).

    The optional trailing `participation` ([C] bool, sharded like counts)
    arms in-round fault tolerance: dropped clients and non-finite
    (quarantined) updates become `where`-zeroed zero-weight rows before the
    psum partial sums, so a masked round is bit-identical to the unmasked
    round over the zero-count-padded surviving cohort on the same geometry
    and rng table (tests/test_robustness.py). All-dead rounds pass global
    variables and aggregator state through unchanged. The default
    `participation=None` traces the exact legacy program — COMMS_BUDGET.json
    gates that program's collective counts/bytes, and the masked
    specialization adds only two scalar psums (the participated/quarantined
    counts).
    """
    local_update = build_local_update(trainer, cfg, pvary_axes=(axis,))
    n_dev = mesh.shape[axis]

    def shard_body(global_variables, agg_state, x, y, counts, rng,
                   participation=None):
        c_local = x.shape[0]
        didx = jax.lax.axis_index(axis)
        # same key table as the vmap engine: split(rng, C)[d*c_local:(d+1)*c_local]
        all_keys = jax.random.split(rng, c_local * n_dev)
        crngs = jax.lax.dynamic_slice_in_dim(all_keys, didx * c_local, c_local)
        result = jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
            global_variables, x, y, counts, crngs
        )
        weights = counts.astype(jnp.float32)
        if participation is not None:
            result, weights, alive, quarantined = quarantine_stage(
                result, weights, participation)
        # no client gather: the aggregator's sharded rule reduces locally
        # weighted partial sums with param-sized psums over ICI (at most half
        # the collective bytes of an all_gather of client stacks — asserted
        # against the lowered HLO inventory by tests/test_comms.py::
        # test_psum_aggregation_halves_all_gather_bytes), and psum outputs
        # are invariant-typed — shard_map's check_vma replication
        # verification stays ON (VERDICT r4 weak #3)
        new_global, new_state = aggregator.sharded(
            global_variables, result, weights, rng, agg_state, axis
        )
        metrics = {k: jax.lax.psum(v.sum(), axis) for k, v in result.metrics.items()}
        if participation is None:
            return new_global, new_state, metrics
        alive_total = jax.lax.psum(alive.sum(), axis)
        # psum outputs are invariant-typed, so the no-op guard's select is
        # invariant too and check_vma accepts the P() out_specs unchanged
        any_alive = alive_total > 0
        new_global = tree_where(any_alive, new_global, global_variables)
        new_state = tree_where(any_alive, new_state, agg_state)
        metrics["participated_count"] = alive_total.astype(jnp.float32)
        metrics["quarantined_count"] = jax.lax.psum(
            quarantined.sum(), axis).astype(jnp.float32)
        return new_global, new_state, metrics

    def round_fn(global_variables, agg_state, x, y, counts, rng,
                 participation=None):
        if participation is None:
            sharded = shard_map(
                shard_body,
                mesh=mesh,
                in_specs=(P(), P(), P(axis), P(axis), P(axis), P()),
                out_specs=(P(), P(), P()),
            )
            return sharded(global_variables, agg_state, x, y, counts, rng)
        sharded = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(axis), P(), P(axis)),
            out_specs=(P(), P(), P()),
        )
        return sharded(global_variables, agg_state, x, y, counts, rng,
                       participation)

    return jax.jit(round_fn)
