"""Seeded deterministic fault-schedule injector (the chaos harness).

Faults are decided per round from `np.random.default_rng([seed, round_idx])`
— a pure function of (plan, round_idx, n_clients), independent of execution
history, so a crashed-and-resumed run or a guard-triggered re-run sees the
identical schedule, and two runs with the same seed produce identical fault
schedules and identical final metrics (ISSUE 4 acceptance criterion).

Injection happens at the host boundary, before dispatch: dropped clients
become zeros in the `participation` mask (the round program gives them zero
aggregation weight — see engine.build_round_fn_from_update), NaN-poisoned
clients get NaN written into their input rows (their grads go non-finite and
the in-round quarantine stage excludes them), corrupted clients get a large
multiplicative perturbation (finite garbage — exercises the guard's
loss-spike detector rather than the quarantine).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, NamedTuple, Optional

import numpy as np

from fedml_tpu import telemetry

# Distinct third seed word for the straggler-latency rng stream: keeps
# `latencies` draws independent of the `events` stream at the same
# (seed, round_idx) without disturbing its byte-stable draw order.
_STRAGGLER_STREAM = 0x5BA6


class FaultEvents(NamedTuple):
    """Host-side fault decisions for one round (numpy, length n_clients)."""

    participation: np.ndarray  # bool — False = client dropped this round
    nan_mask: np.ndarray  # bool — True = client's update poisoned with NaN
    corrupt_mask: np.ndarray  # bool — True = client data corrupted (finite)

    @property
    def dropped(self) -> int:
        return int((~self.participation).sum())


@dataclass(frozen=True)
class FaultPlan:
    """Per-round fault rates plus optional per-round overrides.

    overrides maps round_idx -> {"drop_rate": ..., "nan_rate": ...,
    "corrupt_rate": ...} (missing keys inherit the plan-level rate), so a
    test can script e.g. "round 3 loses everyone".
    """

    seed: int = 0
    drop_rate: float = 0.0
    nan_rate: float = 0.0
    corrupt_rate: float = 0.0
    # straggler plan (buffered aggregation): each straggling client's update
    # arrives `latency` dispatch rounds late (1..straggler_rounds, uniform)
    # instead of at its birth round. Drawn from a SEPARATE rng stream
    # (seed, round_idx, _STRAGGLER_STREAM) so enabling stragglers never
    # perturbs the drop/nan/corrupt draws above — seeded chaos trajectories
    # from earlier PRs stay bit-identical.
    straggler_rate: float = 0.0
    straggler_rounds: int = 0
    overrides: Dict[int, Dict[str, float]] = field(default_factory=dict)

    def rates_for(self, round_idx: int) -> Dict[str, float]:
        base = {"drop_rate": self.drop_rate, "nan_rate": self.nan_rate,
                "corrupt_rate": self.corrupt_rate}
        base.update(self.overrides.get(round_idx, {}))
        return base

    def events(self, round_idx: int, n_clients: int) -> FaultEvents:
        """Deterministic fault decisions for this round's sampled cohort."""
        rates = self.rates_for(round_idx)
        rng = np.random.default_rng([self.seed, round_idx])
        drop = rng.random(n_clients) < rates["drop_rate"]
        nan = rng.random(n_clients) < rates["nan_rate"]
        corrupt = rng.random(n_clients) < rates["corrupt_rate"]
        # a dropped client never reaches the round program — its other
        # faults are moot; keep the masks disjoint so counts add up
        nan &= ~drop
        corrupt &= ~drop & ~nan
        events = FaultEvents(participation=~drop, nan_mask=nan,
                             corrupt_mask=corrupt)
        telemetry.emit("chaos_inject", round=round_idx,
                       dropped=int(drop.sum()), nan=int(nan.sum()),
                       corrupt=int(corrupt.sum()))
        return events

    def events_block(self, round_start: int, num_rounds: int,
                     n_clients: int) -> tuple:
        """Fault decisions for rounds [round_start, round_start+num_rounds)
        in one call — the superstep drive's [K, C] mask precompute.

        Returns (events, masks): `events` is the per-round FaultEvents list
        (each drawn through `events()`, so per-round purity, overrides AND
        the per-round chaos_inject telemetry are identical to K eager
        calls), `masks` a dict of stacked [K, C] bool arrays keyed
        "participation" / "nan" / "corrupt" — the traced per-round inputs
        of engine.build_superstep_fn."""
        evs = [self.events(round_start + j, n_clients)
               for j in range(num_rounds)]
        masks = {
            "participation": np.stack([e.participation for e in evs]),
            "nan": np.stack([e.nan_mask for e in evs]),
            "corrupt": np.stack([e.corrupt_mask for e in evs]),
        }
        return evs, masks

    def latencies(self, round_idx: int, n_clients: int) -> np.ndarray:
        """Per-client arrival latency (int32 dispatch rounds, 0 = on time)
        for the cohort dispatched at `round_idx` — the seeded straggler
        plan. Pure in (plan, round_idx, n_clients), like `events`, so a
        resumed or guard-retried run replays the identical arrival
        schedule."""
        lat = np.zeros(n_clients, np.int32)
        if self.straggler_rate <= 0.0 or self.straggler_rounds <= 0:
            return lat
        rng = np.random.default_rng([self.seed, round_idx,
                                     _STRAGGLER_STREAM])
        straggle = rng.random(n_clients) < self.straggler_rate
        draws = rng.integers(1, self.straggler_rounds + 1, n_clients,
                             dtype=np.int32)
        lat[straggle] = draws[straggle]
        return lat


def apply_faults(events: FaultEvents, x: np.ndarray) -> np.ndarray:
    """Perturb the cohort's packed input rows [C, n_max, ...] per `events`.

    Only float inputs can carry NaN; for integer/token inputs the NaN fault
    degrades to corruption (max-value fill) which still derails the client's
    update without violating the dtype. Returns a copy; `x` is untouched.
    """
    x = np.asarray(x)
    if not (events.nan_mask.any() or events.corrupt_mask.any()):
        return x
    out = np.array(x, copy=True)
    is_float = np.issubdtype(out.dtype, np.floating)
    for c in np.nonzero(events.nan_mask)[0]:
        if is_float:
            out[c] = np.nan
        else:
            out[c] = np.iinfo(out.dtype).max
    for c in np.nonzero(events.corrupt_mask)[0]:
        if is_float:
            out[c] = out[c] * 1e3 + 7.0
        else:
            out[c] = (out[c] + 13) % max(int(out.max()) + 1, 2)
    return out


def summarize(events: Optional[FaultEvents]) -> Dict[str, int]:
    """Host-side event counts for logging (all zeros when chaos is off)."""
    if events is None:
        return {"chaos_dropped": 0, "chaos_nan": 0, "chaos_corrupt": 0}
    return {
        "chaos_dropped": int((~events.participation).sum()),
        "chaos_nan": int(events.nan_mask.sum()),
        "chaos_corrupt": int(events.corrupt_mask.sum()),
    }
