"""Capped exponential backoff with full jitter — the one retry loop.

The reference stack retried ad hoc (MQTT reconnect had its own hand-rolled
backoff loop, data/acquire.py special-cased exactly one Drive interstitial
refetch); this module is the single policy both now share. Full jitter
(delay = uniform(0, min(cap, base * mult^attempt))) is the AWS-architecture
variant: under correlated failures it spreads the retry herd across the whole
window instead of synchronizing it at the cap.

Everything time-like is injectable (`sleep`, `clock`, `rng`) so the backoff
sequence is unit-testable deterministically — tests inject a fake clock and a
recorded rng and assert the exact delay sequence, no real sleeping.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class RetryPolicy:
    """How to retry: attempts, backoff shape, deadline, what is retryable.

    max_attempts counts total calls (first try included). base_delay is the
    pre-jitter delay after attempt 0; each subsequent failure multiplies it
    by `multiplier`, capped at `max_delay`. With jitter on, the actual sleep
    is uniform in [0, capped_delay]. `deadline` (seconds, measured on the
    injected clock from the first attempt) bounds the whole loop: each sleep
    is clamped to the remaining budget (the loop never sleeps past the
    deadline), and once no budget remains the loop stops retrying and raises
    RetryError.
    """

    max_attempts: int = 5
    base_delay: float = 0.2
    max_delay: float = 30.0
    multiplier: float = 2.0
    jitter: bool = True
    deadline: Optional[float] = None
    retryable: Tuple[Type[BaseException], ...] = (
        ConnectionError, TimeoutError, OSError)

    def delay_for(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Pre-sleep delay after failed attempt `attempt` (0-based)."""
        capped = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        if not self.jitter:
            return capped
        return ((rng or random).random()) * capped


class RetryError(Exception):
    """All attempts exhausted (or deadline passed). `.last` is the final
    underlying exception, `.attempts` how many calls were made."""

    def __init__(self, message: str, last: BaseException, attempts: int):
        super().__init__(message)
        self.last = last
        self.attempts = attempts


def call_with_retry(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    abort: Optional[Callable[[], bool]] = None,
    **kwargs,
):
    """Call fn(*args, **kwargs), retrying per `policy`.

    on_retry(attempt, exc, delay) fires before each sleep — callers log or
    count there. `abort()` is polled before every attempt and before every
    sleep; returning True stops the loop immediately (re-raising the last
    exception, or RetryError("aborted") before any attempt) — MQTT clients
    pass their shutdown Event here so a closing client never sits out a
    30 s backoff.
    """
    policy = policy or RetryPolicy()
    if policy.max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {policy.max_attempts}")
    start = clock()
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        if abort is not None and abort():
            if last is not None:
                raise last
            raise RetryError("aborted before first attempt",
                             RuntimeError("aborted"), 0)
        try:
            return fn(*args, **kwargs)
        except policy.retryable as e:
            last = e
            final = attempt == policy.max_attempts - 1
            delay = 0.0 if final else policy.delay_for(attempt, rng)
            if not final and policy.deadline is not None:
                remaining = policy.deadline - (clock() - start)
                if remaining <= 0.0:
                    final = True
                else:
                    # clamp, don't give up: a jittered draw that would
                    # overshoot sleeps exactly the remaining budget, so the
                    # deadline buys every attempt it can afford
                    delay = min(delay, remaining)
            if final:
                raise RetryError(
                    f"{fn!r} failed after {attempt + 1} attempt(s): {e}",
                    e, attempt + 1) from e
            if on_retry is not None:
                on_retry(attempt, e, delay)
            if abort is not None and abort():
                raise last
            if delay > 0:
                sleep(delay)
    raise AssertionError("unreachable")  # loop always returns or raises
