"""Fault-tolerance subsystem: retry/backoff, chaos injection, round guard.

Production federated rounds are defined by partial participation — clients
straggle, drop, and ship non-finite updates (SURVEY §5, §7). This package
holds the host-side half of the fault story:

- `retry`  — capped-exponential-backoff-with-full-jitter retry loop shared by
  `comm/mqtt.py` (socket reconnects) and `data/acquire.py` (download retry).
- `chaos`  — seeded, deterministic fault-schedule injector (drops, NaN
  poisoning, value corruption) applied at the host boundary before dispatch.
- `guard`  — driver-side loss-spike / non-finite-global detector that rolls
  the run back to the last good state and re-runs the round with fresh rng.

The device-side half (the static-shape `participation` mask and the
non-finite update quarantine) lives in `algorithms/aggregators.py` and the
round builders (`algorithms/engine.py`, `parallel/sharded.py`,
`parallel/hierarchical.py`) so it compiles into the round programs.
"""

from fedml_tpu.robustness.chaos import FaultEvents, FaultPlan, apply_faults
from fedml_tpu.robustness.guard import GuardVerdict, RoundGuard
from fedml_tpu.robustness.retry import RetryError, RetryPolicy, call_with_retry

__all__ = [
    "FaultEvents",
    "FaultPlan",
    "apply_faults",
    "GuardVerdict",
    "RoundGuard",
    "RetryError",
    "RetryPolicy",
    "call_with_retry",
]
