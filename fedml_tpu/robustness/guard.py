"""Driver-side round guard: loss-spike / non-finite-global detection.

The in-round quarantine (algorithms/aggregators.py) stops per-client NaN from
entering the aggregate; the guard is the outer line of defense for what
quarantine cannot see — finite-but-garbage updates (corrupted data, poisoned
clients below the attack-detection threshold) that send the global loss off a
cliff, and any non-finite value that reaches the global model through a path
without quarantine. The drive loop (algorithms/fedavg.py FedAvgAPI.train)
consults the guard after every round; on a bad verdict it rolls back to the
last good state (checkpoint via the existing Checkpointable machinery when
available, otherwise the in-memory pre-round snapshot) and re-runs the round
with a fresh rng salt.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class GuardVerdict(NamedTuple):
    ok: bool
    reason: str  # "" when ok


@jax.jit
def _all_finite(tree: Any) -> jnp.ndarray:
    """Scalar bool: every inexact leaf of the pytree is fully finite."""
    leaves = [l for l in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack([jnp.all(jnp.isfinite(l)) for l in leaves]).all()


@dataclass
class RoundGuard:
    """Flags a round when the train loss goes non-finite, the global model
    picks up a non-finite leaf, or the loss spikes past `spike_factor` x the
    median of the last `window` accepted losses (needs >= `min_history`
    accepted rounds before the spike test arms — early training is noisy).

    `max_retries` bounds how many times the drive loop re-runs one round on
    a bad verdict before accepting it and moving on (a permanently-poisoned
    cohort must not livelock the run).
    """

    spike_factor: float = 4.0
    window: int = 8
    min_history: int = 3
    max_retries: int = 2

    def __post_init__(self):
        self._losses: deque = deque(maxlen=self.window)

    def inspect(self, round_idx: int, loss: float,
                global_variables: Optional[Any] = None) -> GuardVerdict:
        """Judge one completed round. Accepted losses enter the history;
        rejected rounds leave it untouched (a spike must not poison the
        baseline it is judged against). The drive loop ledgers every
        verdict as a `guard_verdict` telemetry event — emitted there, not
        here, so user-supplied guard objects are ledgered identically."""
        loss = float(loss)
        if not np.isfinite(loss):
            return GuardVerdict(False, f"round {round_idx}: non-finite train "
                                       f"loss ({loss})")
        if global_variables is not None and not bool(
                _all_finite(global_variables)):
            return GuardVerdict(False, f"round {round_idx}: non-finite leaf "
                                       f"in global variables")
        if len(self._losses) >= self.min_history:
            baseline = float(np.median(self._losses))
            if baseline > 0 and loss > self.spike_factor * baseline:
                return GuardVerdict(
                    False, f"round {round_idx}: loss {loss:.4g} spiked past "
                           f"{self.spike_factor}x median {baseline:.4g}")
        self._losses.append(loss)
        return GuardVerdict(True, "")

    def reset(self):
        self._losses.clear()
