"""Native (C++) host-runtime components, loaded via ctypes.

Compiles `packing.cpp` to `libfedpack.so` on first import (g++, no deps) and
exposes `pack_rows` — the fast path under
`fedml_tpu.data.packing.pack_client_data`. Falls back silently to the numpy
implementation when no compiler is available, so the package never hard-fails.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_HERE = os.path.dirname(__file__)
_SO_PATH = os.path.join(_HERE, "libfedpack.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        src = os.path.join(_HERE, "packing.cpp")
        try:
            if (not os.path.exists(_SO_PATH)
                    or os.path.getmtime(_SO_PATH) < os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", src, "-o", _SO_PATH],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_SO_PATH)
            lib.pack_rows.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
            ]
            lib.pack_rows.restype = None
            _lib = lib
        except Exception as e:
            log.info("native packing unavailable (%s); numpy fallback in use", e)
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def pack_rows(src: np.ndarray, idx_lists: list[np.ndarray], n_max: int) -> np.ndarray:
    """Gather per-client row indices of `src` into a zero-padded
    [n_clients, n_max, ...] array using the C++ kernel.

    Raises RuntimeError when the native library is unavailable — callers
    (fedml_tpu.data.packing) catch and fall back to numpy.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native packing unavailable")
    src = np.ascontiguousarray(src)
    n_clients = len(idx_lists)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    idx = np.ascontiguousarray(np.concatenate([np.asarray(i, np.int64) for i in idx_lists])
                               if idx_lists else np.zeros(0, np.int64), dtype=np.int64)
    offsets = np.zeros(n_clients + 1, np.int64)
    np.cumsum([len(i) for i in idx_lists], out=offsets[1:])
    out = np.zeros((n_clients, n_max) + src.shape[1:], src.dtype)
    lib.pack_rows(
        src.ctypes.data_as(ctypes.c_char_p), row_bytes,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n_clients, n_max, out.ctypes.data_as(ctypes.c_char_p),
    )
    return out
