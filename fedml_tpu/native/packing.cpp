// Native client-packing kernels — the host-side data-plane hot path.
//
// The reference framework is pure Python (SURVEY §2.9: no native components;
// its "native layer" is MPI/torch). Here the TPU compute path is XLA; this
// extension is the native runtime piece for the *host* side of the pipeline:
// packing thousands of variable-size client shards into the fixed-shape
// [clients, n_max, ...] arrays the jitted rounds consume
// (fedml_tpu/data/packing.py falls back to numpy loops when this .so is
// unavailable).
//
// Build: g++ -O3 -march=native -shared -fPIC packing.cpp -o libfedpack.so
// (done automatically by fedml_tpu.native on first import).

#include <cstdint>
#include <cstring>

extern "C" {

// Gather rows of `src` (n_rows x row_bytes, contiguous) into the padded
// [n_clients, n_max, row_bytes] buffer `dst` (pre-zeroed by the caller).
// idx: concatenated per-client row indices; offsets: [n_clients + 1] bounds
// into idx. Rows beyond n_max per client are dropped (caller clamps counts).
void pack_rows(const char* src, int64_t row_bytes, const int64_t* idx,
               const int64_t* offsets, int64_t n_clients, int64_t n_max,
               char* dst) {
  for (int64_t c = 0; c < n_clients; ++c) {
    const int64_t start = offsets[c];
    int64_t count = offsets[c + 1] - start;
    if (count > n_max) count = n_max;
    char* client_dst = dst + c * n_max * row_bytes;
    for (int64_t i = 0; i < count; ++i) {
      std::memcpy(client_dst + i * row_bytes, src + idx[start + i] * row_bytes,
                  row_bytes);
    }
  }
}

// Same gather for naturally-split clients already stored back to back:
// starts[c] is the row offset of client c in src, counts[c] its row count.
void pack_ranges(const char* src, int64_t row_bytes, const int64_t* starts,
                 const int64_t* counts, int64_t n_clients, int64_t n_max,
                 char* dst) {
  for (int64_t c = 0; c < n_clients; ++c) {
    int64_t count = counts[c];
    if (count > n_max) count = n_max;
    std::memcpy(dst + c * n_max * row_bytes, src + starts[c] * row_bytes,
                count * row_bytes);
  }
}

}  // extern "C"
