"""graft-serve: the multi-tenant federated serving plane.

One device mesh, N concurrent tenant jobs — different models, algorithms,
aggregators, buffer configs — multiplexed by a deterministic scheduler.
A job is declared (`JobDescriptor`), built into a runtime (`Job`) whose
round program is a schedulable unit, and dispatched by a `Scheduler` whose
policies (round-robin / deficit-weighted fair share) are seeded and
bit-reproducible: each tenant's final params are byte-identical to running
its job solo, no matter how the tenants interleave.
"""

from fedml_tpu.serving.evict_store import EvictionStore  # noqa: F401
from fedml_tpu.serving.job import Job, JobDescriptor  # noqa: F401
from fedml_tpu.serving.scheduler import JobQueue, Scheduler  # noqa: F401
