"""Declarative federated jobs: a round program as a schedulable unit.

The drive loops (`fedml_tpu/algorithms`) own the whole process — one job,
one `train()` call to completion. `JobDescriptor` lifts the inputs of such
a run (model, algorithm, FedConfig, client-store handle, rng seed, round
budget) into a declarative value, and `Job` wraps the runtime state so ONE
round is a `step()` call the scheduler can interleave with other tenants.

Bit-reproducibility argument: everything a round consumes is a pure
function of `(cfg.seed, round_idx)` — sampling, staging, the round rng,
chaos faults and straggler latencies — and each Job owns its own
`FedAvgAPI` (params, aggregator state, jit wrappers) plus its own round
counter. Interleaving tenants therefore cannot perturb any tenant's
stream: a job stepped under the scheduler trains byte-identical params to
the same job run solo through `FedAvgAPI.train` (tests/test_serving.py).

Synchronous jobs reuse `FedAvgAPI.train_one_round` verbatim; buffered jobs
(`cfg.buffer_size > 0`) reuse `algorithms.buffered.BufferedRunner` — the
same step/drain code path as the classic buffered loop — optionally in
`partial_dispatch` mode, where each dispatch round stages only as many
replacement clients as arrivals have freed buffer capacity
(`FedAvgAPI.stage_partial_cohort`) instead of re-running the full cohort.

Overload robustness (graft-slo): `evict()` snapshots the job's FULL
Checkpointable surface to host — params/adapters + aggregator (and codec
residual) state via `_ckpt_tree`, the history via `_ckpt_meta`, the
buffered runner's device buffer + birth tags + pending-arrival schedule
via `BufferedRunner.snapshot()` (the same surface guard rollback rewinds),
and the round guard's loss window — then drops every device reference, so
the tenant's mesh slot is free. `resume()` rebuilds the api/runner from
the descriptor (the persistent XLA compile cache makes the rebuild a
warm start — traced again, compiled never) and restores the snapshot;
an evicted-then-resumed tenant trains byte-identical final params to its
uninterrupted solo run, for sync AND buffered (straggler-armed) tenants
(tests/test_serving.py). Snapshots optionally spill to the mmap-backed
`serving.evict_store.EvictionStore` so parked tenants cost file pages,
not RSS. Under LoRA the snapshot is adapters-only (`_ckpt_tree` strips
the deterministic frozen base), so eviction is O(adapter bytes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import numpy as np

from fedml_tpu.algorithms.buffered import BufferedRunner
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.robustness.chaos import summarize as chaos_summary
from fedml_tpu.telemetry.records import RoundRecordLog

#: SLO classes a tenant may declare: latency-bound tenants form a strict
#: priority tier in the scheduler's pick and may preempt throughput-bound
#: residents via evict(); throughput-bound tenants absorb the slack.
SLO_CLASSES = ("throughput", "latency")


@dataclass(frozen=True)
class JobDescriptor:
    """Everything needed to (re)build one tenant's federated run.

    `weight` feeds the scheduler's deficit-weighted fair-share policy;
    `partial_dispatch` opts a buffered job into replacement-client
    dispatch. `trainer_factory` defaults to the standard classification
    trainer over `create_model(cfg.model, output_dim=dataset.class_num)`.

    graft-slo fields: `slo` declares the tenant's class (see SLO_CLASSES);
    `deadline_s` arms the scheduler's per-tenant deadline-miss ledger
    (completion - submission > deadline_s -> a `deadline_miss` event —
    measured telemetry, never a pick input); `guard` attaches a round
    guard (robustness.guard.RoundGuard) to the served job, mirroring the
    solo drive's rollback-and-retry semantics exactly.
    """

    name: str
    config: FedConfig
    dataset: Any  # data.registry.FederatedDataset (any backing store)
    aggregator_name: str = "fedavg"
    trainer_factory: Optional[Callable[[], Any]] = None
    chaos: Any = None  # robustness.chaos.FaultPlan
    weight: float = 1.0
    partial_dispatch: bool = False
    slo: str = "throughput"
    deadline_s: Optional[float] = None
    guard: Any = None  # robustness.guard.RoundGuard
    #: models.adapter_bank.AdapterBank — required when config.personalize.
    #: The bank is HOST state (mmap-backed), owned by the caller and shared
    #: across evict/resume: eviction flushes its dirty rows to disk but
    #: never closes it, so a resumed tenant gathers exactly the rows its
    #: evicted self scattered.
    bank: Any = None
    extra: dict = field(default_factory=dict, hash=False, compare=False)

    def __post_init__(self):
        if self.slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown slo class {self.slo!r}; choose from {SLO_CLASSES}")

    @property
    def kind(self) -> str:
        return "buffered" if self.config.buffer_size > 0 else "sync"

    @property
    def codec(self) -> str:
        """This tenant's update-codec name ("none" when transport is raw).

        Per-tenant compression rides `config.update_codec` into the job's
        own FedAvgAPI, so one scheduler can interleave a codec-on tenant
        with codec-off ones — each tenant's admit/round programs (and their
        COMPILE/COMMS budget accounting) stay per-job, and a codec-on
        tenant served next to raw tenants trains byte-identical to the same
        job solo (the serving bit-reproducibility argument is per-job
        state, which the codec residual is part of)."""
        return self.config.update_codec or "none"

    @property
    def drive(self) -> str:
        """Which COMPILE_BUDGET.json drive this tenant's jit programs are
        accounted against (per-tenant compile-budget gate)."""
        return "buffered" if self.config.buffer_size > 0 else "eager"

    @property
    def rounds(self) -> int:
        return int(self.config.comm_round)

    def build_trainer(self):
        from fedml_tpu.models.lora import maybe_wrap_lora

        if self.trainer_factory is not None:
            # factory-built trainers get the same LoRA seam the stock path
            # has — a tenant descriptor with lora_rank > 0 federates
            # adapters no matter how its trainer was constructed
            return maybe_wrap_lora(self.trainer_factory(), self.config)
        from fedml_tpu.core.trainer import ClassificationTrainer
        from fedml_tpu.models.registry import create_model

        return maybe_wrap_lora(
            ClassificationTrainer(
                create_model(self.config.model,
                             output_dim=self.dataset.class_num)),
            self.config)

    def build_api(self) -> FedAvgAPI:
        """A fresh FedAvgAPI for this descriptor — the SAME construction a
        solo `train()` run uses, so served and solo runs share programs."""
        return FedAvgAPI(self.dataset, self.config, self.build_trainer(),
                         aggregator_name=self.aggregator_name)

    def build(self) -> "Job":
        return Job(self)


class Job:
    """One tenant's runtime: (queued ->) pending -> running -> committed,
    with evicted as a parkable detour and cancelled as the other terminal.

    `step(tracer)` executes exactly one dispatch round (buffered jobs also
    drain after their final round) and returns True once the job has
    consumed its whole round budget. The scheduler owns WHEN steps happen;
    the job owns WHAT a step does — and what it does is independent of the
    interleaving by construction (see module docstring).

    `build=False` defers `desc.build_api()` until `materialize()` — the
    admission-controlled scheduler admits hundreds of tenants without
    paying device state for any that never reach the mesh."""

    def __init__(self, desc: JobDescriptor, build: bool = True):
        self.desc = desc
        self.name = desc.name
        self.api: Optional[FedAvgAPI] = None
        self.runner: Optional[BufferedRunner] = None
        self.records: Optional[RoundRecordLog] = None
        self.round_idx = 0
        self.state = "queued"
        # eviction snapshot (host pytree, or an EvictionStore holding it)
        self._snapshot = None
        self._spill_store = None
        # scheduler bookkeeping (deficit-weighted fair share + bench timing)
        self.deficit = 0.0
        self.dispatched_ticks = 0
        self.submit_t: Optional[float] = None
        self.start_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self._submit_seq = 0  # scheduler-stamped submission index
        self.warm_start = False  # scheduler warm-pool signature hit
        # one-shot staged-cohort handoff from the scheduler's shared
        # prefetcher into the api's stage seam (sync path)
        self._staged_override = None
        if build:
            self.materialize()

    def materialize(self) -> None:
        """Build (or rebuild, on resume) the device-facing runtime: the
        FedAvgAPI, the buffered runner, and the stage-override seam.
        Idempotent while an api is live."""
        if self.api is not None:
            return
        self.api = self.desc.build_api()
        if self.desc.bank is not None:
            # the drive loops attach via train(bank=...); served jobs step
            # through train_one_round directly, so the seam is here
            self.api.bank = self.desc.bank
        if self.desc.kind == "buffered":
            # the guard rides into the runner so donation gating matches
            # the solo buffered drive (a guard snapshot holds the buffer's
            # arrays — donating them would deallocate the snapshot)
            self.runner = BufferedRunner(
                self.api, chaos=self.desc.chaos, guard=self.desc.guard,
                partial_dispatch=self.desc.partial_dispatch)
        self._orig_stage_fn = self.api.stage_fn
        self.api.stage_fn = self._stage_or_override
        if self.state == "queued":
            self.state = "pending"

    # ------------------------------------------------------------- plumbing
    @property
    def done(self) -> bool:
        return self.state == "committed"

    @property
    def closed(self) -> bool:
        """Terminal either way: committed or cancelled — the job will
        never be scheduled again."""
        return self.state in ("committed", "cancelled")

    @property
    def resident(self) -> bool:
        """Whether this job currently holds device state (a mesh slot)."""
        return self.api is not None

    @property
    def history(self):
        return self.api.history

    @property
    def prefetchable(self) -> bool:
        """Whether this job's cohorts can be staged ahead by round index:
        staging must be pure in round_idx, which partial dispatch is not
        (its width depends on in-flight capacity at dispatch time)."""
        return not (self.desc.kind == "buffered"
                    and self.desc.partial_dispatch)

    def _stage_or_override(self, round_idx, **kw):
        staged = self._staged_override
        if staged is not None and staged.round_idx == round_idx:
            self._staged_override = None
            return staged
        return self._orig_stage_fn(round_idx, **kw)

    def stage(self, round_idx: int):
        """Stage one cohort for this job — the shared prefetcher's staging
        callback (pure in round_idx; chaos faults derived per round)."""
        return self._orig_stage_fn(round_idx, chaos=self.desc.chaos)

    # ------------------------------------------------------ evict / resume
    def evict(self, tracer, reason: str = "preempted", store=None) -> bool:
        """Checkpointed preemption: fetch the job's full state surface to
        host, drop every device reference (the mesh slot is free), park
        the snapshot (optionally spilled into `store`, an EvictionStore).
        Only called at step boundaries, where the record log is flushed
        and no staged cohort is in flight. Returns False when there is
        nothing resident to evict."""
        if self.api is None or self.closed:
            return False
        if self.records is not None:
            self.records.flush(self.round_idx)
        if self.desc.bank is not None:
            # flush AFTER the record flush above scattered any pending
            # _bank blocks: the parked tenant's personal rows are on disk
            # before the slot frees, so resume gathers the exact bytes
            self.desc.bank.flush()
        buf = None
        host_snap = None
        in_flight = 0
        if self.runner is not None:
            if self.api._buffer is not None:
                buf = jax.device_get(self.api._buffer)
            # the pending dict holds the client-step programs' stacked
            # device results — device_get folds them (and nothing else;
            # host ints/lists pass through) into plain numpy
            host_snap = jax.device_get(self.runner.host.snapshot())
            in_flight = self.runner.in_flight
        guard = self.desc.guard
        snap = {
            "tree": jax.device_get(self.api._ckpt_tree()),
            "meta": self.api._ckpt_meta(),
            "buffer": buf,
            "host": host_snap,
            "in_flight": in_flight,
            "round_idx": self.round_idx,
            "state": self.state,
            "guard_losses": (list(guard._losses)
                             if guard is not None else None),
        }
        if store is not None:
            store.save(self.name, snap)
            self._snapshot = None
            self._spill_store = store
        else:
            self._snapshot = snap
            self._spill_store = None
        # free the mesh slot: every device reference goes
        self.api = None
        self.runner = None
        self.records = None
        self._staged_override = None
        self.state = "evicted"
        tracer.event("job_evicted", job=self.name, round=self.round_idx,
                     reason=reason)
        return True

    def resume(self, tracer) -> bool:
        """Rebuild the runtime from the descriptor and restore the parked
        snapshot. The rebuild re-traces the same programs a fresh build
        would — with the persistent compile cache enabled XLA serves them
        warm (cache_hits > 0, no new compiles: tests/test_serving.py) —
        and the restored bytes make the resumed run a bitwise continuation
        of the evicted one."""
        if self.state != "evicted":
            return False
        snap = (self._spill_store.load(self.name)
                if self._spill_store is not None else self._snapshot)
        self._snapshot = None
        self._spill_store = None
        self.materialize()
        api = self.api
        api._ckpt_load(snap["tree"], snap["meta"])
        if self.runner is not None:
            if snap["buffer"] is not None:
                api._buffer = jax.device_put(snap["buffer"])
            self.runner.host.restore(snap["host"])
            self.runner.in_flight = snap["in_flight"]
        guard = self.desc.guard
        if guard is not None and snap["guard_losses"] is not None:
            guard._losses.clear()
            guard._losses.extend(snap["guard_losses"])
        self.round_idx = snap["round_idx"]
        self.state = snap["state"]
        if self.state == "running":
            # _ckpt_load restored the history INTO api.history in place;
            # the fresh record log binds to that same list
            self.records = RoundRecordLog(tracer, api.history, None,
                                          bank=self.desc.bank)
        tracer.event("job_resumed", job=self.name, round=self.round_idx)
        return True

    def cancel(self) -> None:
        """Terminal removal (admission shed / caller cancel): device refs
        and any parked snapshot are dropped; the job never runs again."""
        self.api = None
        self.runner = None
        self.records = None
        self._snapshot = None
        self._spill_store = None
        self._staged_override = None
        self.state = "cancelled"

    # ----------------------------------------------------------------- step
    def step(self, tracer, staged=None) -> bool:
        """One schedulable unit of this job. `staged` (optional) is a
        prefetched cohort for `self.round_idx`. Returns True when the job
        just finished (drain included)."""
        if self.closed:
            return True
        if self.api is None:
            self.materialize()
        if self.state == "pending":
            self.state = "running"
            self.records = RoundRecordLog(tracer, self.api.history, None,
                                          bank=self.desc.bank)
        if self.desc.kind == "sync":
            self._step_sync(tracer, staged)
        else:
            self._step_buffered(tracer, staged)
        if self.round_idx >= self.desc.rounds:
            self.state = "committed"
        return self.done

    def _step_sync(self, tracer, staged) -> None:
        """One sync round — guard retry attempts included, mirroring
        `FedAvgAPI._eager_round` exactly (snapshot refs, salted rng,
        verdict/rollback/exhausted events), so a guard-armed served tenant
        stays byte-identical to its solo run."""
        cfg = self.api.cfg
        guard = self.desc.guard
        r = self.round_idx
        retries = 0
        while True:
            rejected = False
            with tracer.round(r) as rspan:
                faults = None
                if self.desc.chaos is not None and staged is None:
                    n_cohort = min(cfg.client_num_per_round,
                                   self.api.dataset.client_num)
                    faults = self.desc.chaos.events(r, n_cohort)
                snapshot = None
                if guard is not None:
                    # jax pytrees are immutable: the refs ARE the snapshot
                    snapshot = (self.api._ckpt_tree(), self.api._ckpt_meta())
                self._staged_override = staged
                train_metrics = self.api.train_one_round(r, faults=faults,
                                                         rng_salt=retries,
                                                         tracer=tracer)
                with tracer.span("device_wait", r):
                    jax.block_until_ready(self.api.global_variables)
                if guard is not None:
                    total = max(train_metrics.get("total", 1.0), 1.0)
                    loss = train_metrics.get("loss_sum", 0.0) / total
                    with tracer.span("guard_verdict", r):
                        verdict = guard.inspect(r, loss,
                                                self.api.global_variables)
                    tracer.event("guard_verdict", round=r, ok=verdict.ok,
                                 reason=verdict.reason)
                    if not verdict.ok and retries < guard.max_retries:
                        retries += 1
                        tracer.event("guard_rollback", round=r,
                                     retry=retries)
                        self.api._ckpt_load(*snapshot)
                        rejected = True  # new attempt, new round span
                    elif not verdict.ok:
                        tracer.event("guard_exhausted", round=r)
                if not rejected:
                    record = {"round": r, "round_time": rspan.elapsed()}
                    staged_used, stats = self.api._last_dispatch
                    block = FedAvgAPI._ledger_block(r, staged_used, stats)
                    if block is not None:
                        record["_ledger"] = [block]
                    bank_block = self.api._bank_block(r)
                    if bank_block is not None:
                        record["_bank"] = [bank_block]
                    if staged_used.faults is not None:
                        record.update(chaos_summary(staged_used.faults))
                        for k in ("participated_count", "quarantined_count"):
                            if k in train_metrics:
                                record[k] = train_metrics[k]
                    if guard is not None and retries:
                        record["guard_retries"] = retries
                    if (r % cfg.frequency_of_the_test == 0
                            or r == cfg.comm_round - 1):
                        with tracer.span("eval", r):
                            record.update(
                                self.api.local_test_on_all_clients(r))
                            record.update(self.api.test_global(r))
                    self.records.add(record)
                    self.records.flush(r)
            if not rejected:
                break
            staged = None  # restage the retry (attempt buffers were donated)
        self.round_idx += 1

    def _step_buffered(self, tracer, staged) -> None:
        """One buffered dispatch round — guard retry attempts included,
        mirroring `train_buffered` (runner.snapshot/restore over globals +
        buffer + arrival schedule, salted rng, restage on retry)."""
        cfg = self.api.cfg
        runner = self.runner
        host = runner.host
        guard = self.desc.guard
        r = self.round_idx
        retries = 0
        while True:
            rejected = False
            with tracer.round(r) as rspan:
                if staged is None:
                    staged = self._stage_buffered(r, tracer)
                snapshot = runner.snapshot() if guard is not None else None
                rng_round = runner.base_rng(r, retries)
                out = runner.step(r, staged, rng_round, tracer)
                train_metrics: dict = {}
                if out["commit_metrics"]:
                    with tracer.span("metrics_fetch", r):
                        for m in jax.device_get(out["commit_metrics"]):
                            for key in m:
                                train_metrics[key] = (
                                    train_metrics.get(key, 0.0)
                                    + float(m[key]))
                if guard is not None and out["commit_metrics"]:
                    total = max(train_metrics.get("total", 1.0), 1.0)
                    loss = train_metrics.get("loss_sum", 0.0) / total
                    with tracer.span("guard_verdict", r):
                        verdict = guard.inspect(r, loss,
                                                self.api.global_variables)
                    tracer.event("guard_verdict", round=r, ok=verdict.ok,
                                 reason=verdict.reason)
                    if not verdict.ok and retries < guard.max_retries:
                        retries += 1
                        tracer.event("guard_rollback", round=r,
                                     retry=retries)
                        runner.restore(snapshot)
                        rejected = True
                    elif not verdict.ok:
                        tracer.event("guard_exhausted", round=r)
                if not rejected:
                    record = {"round": r, "round_time": rspan.elapsed(),
                              "buffer_commits": out["n_commits"],
                              "committed_updates": host.committed_updates,
                              "buffer_fill": host.fill,
                              "_ledger": out["ledger_blocks"]}
                    for key in ("loss_sum", "total", "participated_count",
                                "quarantined_count", "staleness_sum",
                                "staleness_max"):
                        if key in train_metrics:
                            record[key] = train_metrics[key]
                    if staged is not None and staged.faults is not None:
                        record.update(chaos_summary(staged.faults))
                    if guard is not None and retries:
                        record["guard_retries"] = retries
                    if (r % cfg.frequency_of_the_test == 0
                            or r == cfg.comm_round - 1):
                        with tracer.span("eval", r):
                            record.update(
                                self.api.local_test_on_all_clients(r))
                            record.update(self.api.test_global(r))
                    self.records.add(record)
                    self.records.flush(r)
            if not rejected:
                break
            staged = None  # restage the retry against the restored timeline
        self.round_idx += 1
        if self.round_idx >= cfg.comm_round:
            self._drain_buffered(tracer)

    def _stage_buffered(self, round_idx: int, tracer):
        """Stage this dispatch round's cohort — the full seeded sample in
        classic mode, the freed-capacity prefix (padded to static width)
        in partial mode, or None when there is no capacity at all (the
        dispatch program is skipped; the round only processes arrivals)."""
        cfg = self.api.cfg
        cohort = min(cfg.client_num_per_round, self.api.dataset.client_num)
        width = self.runner.capacity(cohort)
        if width <= 0:
            return None
        if width >= cohort:
            return self.api.stage_fn(round_idx, chaos=self.desc.chaos,
                                     tracer=tracer)
        return self.api.stage_partial_cohort(round_idx, width, cohort,
                                             chaos=self.desc.chaos,
                                             tracer=tracer)

    def _drain_buffered(self, tracer) -> None:
        out = self.runner.drain(tracer)
        if not out["n_commits"]:
            return
        host = self.runner.host
        cfg = self.api.cfg
        record = {"round": cfg.comm_round, "round_time": 0.0,
                  "buffer_commits": out["n_commits"],
                  "committed_updates": host.committed_updates,
                  "buffer_fill": host.fill,
                  "_ledger": out["ledger_blocks"]}
        with tracer.span("metrics_fetch", out["drain_round"]):
            for m in jax.device_get(out["commit_metrics"]):
                for key in m:
                    record[key] = record.get(key, 0.0) + float(m[key])
        self.records.add(record)
        self.records.flush(cfg.comm_round)

    def final_params(self):
        """Host copy of the final global variables (bitwise-comparable)."""
        return jax.device_get(self.api.global_variables)

    def __repr__(self) -> str:  # pragma: no cover - debug nicety
        return (f"Job({self.name!r}, kind={self.desc.kind}, "
                f"round={self.round_idx}/{self.desc.rounds}, "
                f"state={self.state})")


def params_equal(a, b) -> bool:
    """Bitwise equality over two fetched variable pytrees."""
    la = jax.tree.leaves(a)
    lb = jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))
