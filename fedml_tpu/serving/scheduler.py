"""graft-serve scheduler: deterministic multi-tenant dispatch over one mesh.

`JobQueue` holds tenant jobs in submission order; `Scheduler` owns WHICH
job steps next. Two policies, both seeded by nothing but submission order
and tick count — no wall clock, no thread races — so a schedule is
bit-reproducible across reruns:

- ``round_robin``: cycle submission order, skipping finished jobs.
- ``fair_share``: deficit round-robin. Every tick each active job accrues
  its `weight`; the max-deficit job (submission order breaks ties) runs
  and pays the total active weight. A weight-2 tenant gets 2 of every 3
  ticks next to a weight-1 tenant, deterministically.

Per-tenant compile accounting: around every step (and descriptor build)
the scheduler snapshots the tracer's `compile_cache` event ledger and
attributes the delta (requests / cache hits / cache misses) to the tenant
that ran. `check_compile_budgets()` gates each tenant's compile requests
against its drive's `max_compiles` ceiling in COMPILE_BUDGET.json — one
tenant blowing the jit cache fails ITS budget, not its neighbors'.

Cross-tenant prefetch: one shared `CohortPrefetcher` stages cohorts ahead
for jobs that want it (`cfg.pipeline_depth > 0` and round-pure staging),
keyed by `(job, round_idx)` so one tenant's rollback/commit can never
evict another tenant's staged rounds (data/prefetch.py).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Union

from fedml_tpu import telemetry
from fedml_tpu.data.prefetch import CohortPrefetcher
from fedml_tpu.serving.job import Job, JobDescriptor

#: compile_cache event-name tails -> ledger keys (utils/cache.py forwards
#: jax.monitoring events whose full names end in these segments)
_COMPILE_TAILS = {
    "compile_requests_use_cache": "requests",
    "cache_hits": "cache_hits",
    "cache_misses": "cache_misses",
}


def _zero_counts() -> Dict[str, int]:
    return {"requests": 0, "cache_hits": 0, "cache_misses": 0}


def load_compile_budgets(path: Optional[str] = None) -> dict:
    """COMPILE_BUDGET.json as a dict (drive -> budget entry)."""
    if path is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(repo_root, "COMPILE_BUDGET.json")
    with open(path) as f:
        return json.load(f)


class JobQueue:
    """Submission-ordered tenant jobs, addressable by unique name."""

    def __init__(self):
        self._jobs: List[Job] = []
        self._by_name: Dict[str, Job] = {}

    def submit(self, job: Job) -> Job:
        if job.name in self._by_name:
            raise ValueError(f"duplicate job name {job.name!r}")
        self._jobs.append(job)
        self._by_name[job.name] = job
        return job

    def get(self, name: str) -> Job:
        return self._by_name[name]

    def active(self) -> List[Job]:
        return [j for j in self._jobs if not j.done]

    def all_done(self) -> bool:
        return all(j.done for j in self._jobs)

    def __iter__(self):
        return iter(self._jobs)

    def __len__(self) -> int:
        return len(self._jobs)

    def __getitem__(self, i: int) -> Job:
        return self._jobs[i]


class Scheduler:
    """Dispatch loop over a JobQueue. `tick()` steps exactly one job (the
    policy's pick) under its `telemetry.job_scope`; `run()` ticks until the
    queue drains. `prefetch_depth` bounds staged-ahead cohorts across ALL
    tenants (0 disables the shared prefetcher)."""

    POLICIES = ("round_robin", "fair_share")

    def __init__(self, policy: str = "round_robin", tracer=None,
                 budgets: Optional[dict] = None, prefetch_depth: int = 4):
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; choose from {self.POLICIES}")
        self.policy = policy
        self.tracer = tracer if tracer is not None else telemetry.NULL_TRACER
        self.budgets = budgets
        self.queue = JobQueue()
        self.compile_ledger: Dict[str, Dict[str, int]] = {}
        self.ticks = 0
        self._rr_cursor = 0
        self._prefetch_depth = int(prefetch_depth)
        self._prefetcher: Optional[CohortPrefetcher] = None

    # ------------------------------------------------------------- submit
    def submit(self, job: Union[Job, JobDescriptor],
               submit_t: Optional[float] = None) -> Job:
        """Enqueue a tenant. A descriptor is built here, under the
        tenant's job scope, so its construction compiles (model init) land
        in the tenant's compile ledger."""
        if isinstance(job, JobDescriptor):
            before = self._compile_counts()
            with telemetry.job_scope(job.name):
                job = job.build()
            self._account(job, before)
        else:
            self.compile_ledger.setdefault(job.name, _zero_counts())
        job.submit_t = submit_t if submit_t is not None else self.tracer.now()
        return self.queue.submit(job)

    # ------------------------------------------------------------ policies
    def _pick(self) -> Optional[Job]:
        active = self.queue.active()
        if not active:
            return None
        if self.policy == "round_robin":
            n = len(self.queue)
            for _ in range(n):
                job = self.queue[self._rr_cursor % n]
                self._rr_cursor += 1
                if not job.done:
                    return job
            return None
        # fair_share: deficit round-robin over the active set
        total = 0.0
        for job in active:
            job.deficit += job.desc.weight
            total += job.desc.weight
        picked = active[0]
        for job in active[1:]:
            if job.deficit > picked.deficit:
                picked = job
        picked.deficit -= total
        return picked

    # ---------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """Step the policy's pick one round. Returns the stepped job's
        name, or None when every job has committed."""
        job = self._pick()
        if job is None:
            return None
        self.ticks += 1
        job.dispatched_ticks += 1
        if job.start_t is None:
            job.start_t = self.tracer.now()
        before = self._compile_counts()
        with telemetry.job_scope(job.name):
            staged = self._take_prefetched(job)
            done = job.step(self.tracer, staged=staged)
        self._account(job, before)
        if done:
            job.finish_t = self.tracer.now()
            wall = job.finish_t - (job.start_t or job.finish_t)
            self.tracer.event("job_committed", job=job.name,
                              rounds=job.round_idx, wall_s=round(wall, 6))
            if self._prefetcher is not None:
                self._prefetcher.invalidate(job=job.name)
        else:
            self._prefetch_ahead(job)
        return job.name

    def run(self) -> int:
        """Tick until the queue drains; returns the tick count. Installs
        the tracer for the duration so module-level telemetry (chaos,
        prefetch gauges, compile-cache events) lands in it."""
        install = hasattr(self.tracer, "find_events")
        if install:
            telemetry.install(self.tracer)
        try:
            while self.tick() is not None:
                pass
        finally:
            if install:
                telemetry.uninstall(self.tracer)
            self.close()
        return self.ticks

    def close(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None

    # ------------------------------------------------------- prefetch seam
    def _wants_prefetch(self, job: Job) -> bool:
        return (self._prefetch_depth > 0 and job.prefetchable
                and job.api.cfg.pipeline_depth > 0)

    def _ensure_prefetcher(self) -> CohortPrefetcher:
        if self._prefetcher is None:
            self._prefetcher = CohortPrefetcher(
                lambda r, jobname: self.queue.get(jobname).stage(r),
                depth=self._prefetch_depth)
        return self._prefetcher

    def _take_prefetched(self, job: Job):
        if not self._wants_prefetch(job):
            return None
        return self._ensure_prefetcher().get(job.round_idx, job=job.name)

    def _prefetch_ahead(self, job: Job) -> None:
        if not self._wants_prefetch(job):
            return
        pf = self._ensure_prefetcher()
        for k in range(job.api.cfg.pipeline_depth):
            r = job.round_idx + k
            if r >= job.desc.rounds:
                break
            pf.prefetch(r, job=job.name)

    # --------------------------------------------------- compile accounting
    def _compile_counts(self) -> Optional[Dict[str, int]]:
        """Fold the tracer's compile_cache event ledger into cumulative
        {requests, cache_hits, cache_misses}; None when the tracer keeps no
        event ledger (NullTracer)."""
        if not hasattr(self.tracer, "find_events"):
            return None
        totals = _zero_counts()
        for e in self.tracer.find_events("compile_cache"):
            key = _COMPILE_TAILS.get(str(e.get("name", "")).rsplit("/", 1)[-1])
            if key is not None:
                totals[key] += 1
        return totals

    def _account(self, job: Job, before: Optional[Dict[str, int]]) -> None:
        ledger = self.compile_ledger.setdefault(job.name, _zero_counts())
        if before is None:
            return
        after = self._compile_counts()
        for key in ledger:
            ledger[key] += after[key] - before[key]

    def check_compile_budgets(self, budgets: Optional[dict] = None):
        """Gate every tenant's compile requests against its drive's
        `max_compiles` ceiling in COMPILE_BUDGET.json. Returns
        (ok, report) — ok is False if ANY tenant exceeded its ceiling;
        tenants whose drive pins no ceiling are SKIP lines."""
        if budgets is None:
            budgets = self.budgets if self.budgets is not None \
                else load_compile_budgets()
        lines = []
        ok = True
        for job in self.queue:
            counts = self.compile_ledger.get(job.name, _zero_counts())
            drive = job.desc.drive
            ceiling = (budgets.get(drive) or {}).get("max_compiles")
            if ceiling is None:
                lines.append(f"SKIP tenant={job.name} drive={drive} "
                             f"requests={counts['requests']} "
                             f"(no ceiling pinned)")
                continue
            verdict = "OK" if counts["requests"] <= ceiling else "FAIL"
            if verdict == "FAIL":
                ok = False
            lines.append(
                f"{verdict} tenant={job.name} drive={drive} "
                f"requests={counts['requests']} <= max {ceiling} "
                f"(hits={counts['cache_hits']} "
                f"misses={counts['cache_misses']})")
        return ok, "\n".join(lines)
