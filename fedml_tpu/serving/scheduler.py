"""graft-serve scheduler: deterministic multi-tenant dispatch over one mesh.

`JobQueue` holds tenant jobs in submission order; `Scheduler` owns WHICH
job steps next. Two policies, both seeded by nothing but submission order,
tick count, and the scheduler's `seed` — no wall clock, no thread races —
so a schedule is bit-reproducible across reruns:

- ``round_robin``: cycle submission order, skipping finished jobs.
- ``fair_share``: deficit round-robin. Every tick each active job accrues
  its `weight`; the max-deficit job runs and pays the total active weight
  (ties break by a seeded blake2s hash of the job name, then submission
  order). A weight-2 tenant gets 2 of every 3 ticks next to a weight-1
  tenant, deterministically.

Overload robustness (graft-slo):

- **SLO tiers**: tenants declaring `slo="latency"` form a strictly-prior
  pick tier — while any latency-bound tenant is active, throughput-bound
  tenants neither run nor accrue deficit. With no latency tenants the
  pick is byte-identical to the legacy policies.
- **Checkpointed preemption**: `max_resident=N` bounds how many tenants
  hold device state at once. A picked non-resident tenant evicts a
  deterministic victim (throughput-bound residents first, latest
  submission first) via `Job.evict()` — snapshots optionally spill to the
  mmap `EvictionStore` (`spill_dir`) — and resumes it bitwise later.
  `max_resident=None` keeps the legacy build-at-submit behavior.
- **Admission control**: `admission="reject"` bounces submissions past
  `max_queued` active tenants (`job_rejected` event, submit returns
  None); `"shed"` lets a latency-bound arrival cancel the youngest
  never-dispatched throughput-bound tenant instead; `"queue"` (default)
  admits unboundedly. `cancel(name)` removes a tenant deterministically:
  it simply leaves the active set, taking its accrued deficit with it —
  nobody else's deficit changes, so the remaining schedule replays
  bit-identically.
- **SLO ledger**: tenants with `deadline_s` get per-tenant deadline-miss
  counters (`slo_ledger`) and `deadline_miss` events — measured from the
  tracer clock as telemetry only, never consulted by the pick, so the
  dispatch schedule stays replayable. `check_slo()` gates miss counts the
  way `check_compile_budgets()` gates compile requests.
- **Warm-start pools**: tenants are fingerprinted by their program-shape
  config; a submission matching an evicted/completed tenant's signature
  is flagged `warm_start` and — with the persistent compile cache on —
  materializes against cached programs (cache_hits in its ledger, no new
  compiles), so tenant N+1 starts in milliseconds.

Per-tenant compile accounting: around every step (and descriptor build)
the scheduler snapshots the tracer's `compile_cache` event ledger and
attributes the delta (requests / cache hits / cache misses) to the tenant
that ran. `check_compile_budgets()` gates each tenant's compile requests
against its drive's `max_compiles` ceiling in COMPILE_BUDGET.json — one
tenant blowing the jit cache fails ITS budget, not its neighbors'.

Cross-tenant prefetch: one shared `CohortPrefetcher` stages cohorts ahead
for jobs that want it (`cfg.pipeline_depth > 0` and round-pure staging),
keyed by `(job, round_idx)` so one tenant's rollback/commit can never
evict another tenant's staged rounds (data/prefetch.py).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Union

from fedml_tpu import telemetry
from fedml_tpu.data.prefetch import CohortPrefetcher
from fedml_tpu.serving.evict_store import EvictionStore
from fedml_tpu.serving.job import Job, JobDescriptor

#: compile_cache event-name tails -> ledger keys (utils/cache.py forwards
#: jax.monitoring events whose full names end in these segments)
_COMPILE_TAILS = {
    "compile_requests_use_cache": "requests",
    "cache_hits": "cache_hits",
    "cache_misses": "cache_misses",
}


def _zero_counts() -> Dict[str, int]:
    return {"requests": 0, "cache_hits": 0, "cache_misses": 0}


def load_compile_budgets(path: Optional[str] = None) -> dict:
    """COMPILE_BUDGET.json as a dict (drive -> budget entry)."""
    if path is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(repo_root, "COMPILE_BUDGET.json")
    with open(path) as f:
        return json.load(f)


def _warm_signature(desc: JobDescriptor) -> str:
    """Program-shape fingerprint for the warm-start pool: everything that
    shapes this tenant's jit programs, nothing that only shapes its data
    stream (seed) or its schedule (comm_round, weight, slo, deadline)."""
    cfg = desc.config.replace(seed=0, comm_round=1)
    return (f"{desc.aggregator_name}|{desc.partial_dispatch}|"
            f"{desc.trainer_factory is not None}|{cfg!r}")


class JobQueue:
    """Submission-ordered tenant jobs, addressable by unique name."""

    def __init__(self):
        self._jobs: List[Job] = []
        self._by_name: Dict[str, Job] = {}

    def submit(self, job: Job) -> Job:
        if job.name in self._by_name:
            raise ValueError(f"duplicate job name {job.name!r}")
        self._jobs.append(job)
        self._by_name[job.name] = job
        return job

    def get(self, name: str) -> Job:
        return self._by_name[name]

    def active(self) -> List[Job]:
        return [j for j in self._jobs if not j.closed]

    def all_done(self) -> bool:
        return all(j.closed for j in self._jobs)

    def cancel(self, name: str) -> bool:
        """Terminal removal with deterministic deficit-ledger cleanup: the
        job leaves the active set carrying its accrued deficit with it
        (deficits are per-job state, so nothing else changes), and its
        device refs / parked snapshot are dropped. Returns False when the
        job is already terminal."""
        job = self._by_name[name]
        if job.closed:
            return False
        job.cancel()
        return True

    def __iter__(self):
        return iter(self._jobs)

    def __len__(self) -> int:
        return len(self._jobs)

    def __getitem__(self, i: int) -> Job:
        return self._jobs[i]


class Scheduler:
    """Dispatch loop over a JobQueue. `tick()` steps exactly one job (the
    policy's pick) under its `telemetry.job_scope`; `run()` ticks until the
    queue drains. `prefetch_depth` bounds staged-ahead cohorts across ALL
    tenants (0 disables the shared prefetcher). See the module docstring
    for the graft-slo knobs (max_resident / admission / max_queued / seed /
    spill_dir)."""

    POLICIES = ("round_robin", "fair_share")
    ADMISSIONS = ("queue", "reject", "shed")

    def __init__(self, policy: str = "round_robin", tracer=None,
                 budgets: Optional[dict] = None, prefetch_depth: int = 4,
                 max_resident: Optional[int] = None,
                 admission: str = "queue",
                 max_queued: Optional[int] = None,
                 seed: int = 0,
                 spill_dir: Optional[str] = None):
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; choose from {self.POLICIES}")
        if admission not in self.ADMISSIONS:
            raise ValueError(f"unknown admission policy {admission!r}; "
                             f"choose from {self.ADMISSIONS}")
        if max_resident is not None and max_resident < 1:
            raise ValueError(f"max_resident must be >= 1, got {max_resident}")
        self.policy = policy
        self.tracer = tracer if tracer is not None else telemetry.NULL_TRACER
        self.budgets = budgets
        self.queue = JobQueue()
        self.compile_ledger: Dict[str, Dict[str, int]] = {}
        self.ticks = 0
        self._rr_cursor = 0
        self._prefetch_depth = int(prefetch_depth)
        self._prefetcher: Optional[CohortPrefetcher] = None
        # graft-slo state
        self.max_resident = max_resident
        self.admission = admission
        self.max_queued = max_queued
        self.seed = int(seed)
        self.spill_store = EvictionStore(spill_dir) if spill_dir else None
        self.evictions = 0
        self.rejections = 0
        self.slo_ledger: Dict[str, Dict[str, object]] = {}
        self.warm_pool: Dict[str, str] = {}  # signature -> first tenant
        self._submit_seq = 0

    # ------------------------------------------------------------- submit
    def submit(self, job: Union[Job, JobDescriptor],
               submit_t: Optional[float] = None) -> Optional[Job]:
        """Enqueue a tenant, subject to the admission policy (a bounced
        submission emits `job_rejected` and returns None). With
        `max_resident` set, descriptor builds are deferred to first
        dispatch; otherwise a descriptor is built here, under the tenant's
        job scope, so its construction compiles land in its ledger."""
        desc = job.desc if isinstance(job, Job) else job
        if not self._admit(desc):
            return None
        if isinstance(job, JobDescriptor):
            sig = _warm_signature(job)
            warm = sig in self.warm_pool
            if not warm:
                self.warm_pool[sig] = job.name
            if self.max_resident is not None:
                # deferred build: admitted tenants cost no device state
                # until the pick actually reaches them (materialize/resume
                # under _ensure_resident pays — and attributes — compiles)
                job = Job(job, build=False)
                self.compile_ledger.setdefault(job.name, _zero_counts())
            else:
                before = self._compile_counts()
                with telemetry.job_scope(job.name):
                    job = job.build()
                self._account(job, before)
            job.warm_start = warm
        else:
            self.compile_ledger.setdefault(job.name, _zero_counts())
        job.submit_t = submit_t if submit_t is not None else self.tracer.now()
        job._submit_seq = self._submit_seq
        self._submit_seq += 1
        out = self.queue.submit(job)
        self.tracer.gauge("queue_depth", depth=len(self.queue.active()))
        return out

    def _admit(self, desc: JobDescriptor) -> bool:
        """Admission control: True admits. `queue` always admits; past
        `max_queued` active tenants, `reject` bounces the arrival and
        `shed` sacrifices the youngest never-dispatched throughput-bound
        tenant to a latency-bound arrival (bouncing the arrival when no
        such victim exists)."""
        if self.admission == "queue" or self.max_queued is None:
            return True
        depth = len(self.queue.active())
        if depth < self.max_queued:
            return True
        if self.admission == "shed" and desc.slo == "latency":
            victims = [j for j in self.queue.active()
                       if j.desc.slo == "throughput"
                       and j.dispatched_ticks == 0]
            if victims:
                self.cancel(victims[-1].name, reason="shed")
                return True
        self.rejections += 1
        self.tracer.event("job_rejected", job=desc.name, reason="queue_full",
                          slo=desc.slo)
        self.tracer.gauge("queue_depth", depth=depth, rejected=1)
        return False

    def cancel(self, name: str, reason: str = "cancelled") -> bool:
        """Cancel an admitted tenant (deterministic deficit cleanup — see
        JobQueue.cancel). Surfaced in the ledger as a `job_rejected` event
        with this reason."""
        job = self.queue.get(name)
        if not self.queue.cancel(name):
            return False
        self.tracer.event("job_rejected", job=name, reason=reason,
                          slo=job.desc.slo)
        if self._prefetcher is not None:
            self._prefetcher.invalidate(job=name)
        return True

    # ------------------------------------------------------------ policies
    def _tiebreak(self, job: Job) -> int:
        """Seeded, name-stable tiebreak key: reruns replay it exactly,
        and no wall clock or id() leaks in."""
        h = hashlib.blake2s(f"{self.seed}:{job.name}".encode(),
                            digest_size=8)
        return int.from_bytes(h.digest(), "big")

    def _pick(self) -> Optional[Job]:
        active = self.queue.active()
        if not active:
            return None
        # SLO tier: latency-bound tenants are strictly prior — while any
        # is active, throughput-bound tenants neither run nor accrue
        # deficit. Empty tier == the legacy pick, byte-identical.
        lat = [j for j in active if j.desc.slo == "latency"]
        pool = lat if lat else active
        if self.policy == "round_robin":
            n = len(self.queue)
            for _ in range(n):
                job = self.queue[self._rr_cursor % n]
                self._rr_cursor += 1
                if not job.closed and (not lat
                                       or job.desc.slo == "latency"):
                    return job
            return None
        # fair_share: deficit round-robin over the pick pool
        total = 0.0
        for job in pool:
            job.deficit += job.desc.weight
            total += job.desc.weight
        picked = pool[0]
        for job in pool[1:]:
            if job.deficit > picked.deficit or (
                    job.deficit == picked.deficit
                    and self._tiebreak(job) < self._tiebreak(picked)):
                picked = job
        picked.deficit -= total
        return picked

    # ------------------------------------------------- residency / eviction
    def _resident_jobs(self) -> List[Job]:
        return [j for j in self.queue if j.resident and not j.closed]

    def _evict_victim(self, exclude: Job) -> Optional[Job]:
        """Deterministic preemption victim: throughput-bound residents
        before latency-bound ones, latest submission first."""
        cands = [j for j in self._resident_jobs() if j is not exclude]
        if not cands:
            return None
        cands.sort(key=lambda j: (
            0 if j.desc.slo == "throughput" else 1, -j._submit_seq))
        return cands[0]

    def _evict(self, job: Job, reason: str = "preempted") -> None:
        if job.evict(self.tracer, reason=reason, store=self.spill_store):
            self.evictions += 1
            self.tracer.gauge("evicted_jobs", count=self.evictions,
                              job=job.name)
            if self._prefetcher is not None:
                self._prefetcher.invalidate(job=job.name)

    def _ensure_resident(self, job: Job) -> None:
        """Give the picked job a mesh slot: evict deterministic victims
        while over `max_resident`, then materialize (first dispatch) or
        resume (evicted) under the tenant's job scope so the rebuild's
        compile activity lands in ITS ledger."""
        if job.resident:
            return
        if self.max_resident is not None:
            while len(self._resident_jobs()) >= self.max_resident:
                victim = self._evict_victim(exclude=job)
                if victim is None:
                    break  # nothing evictable: oversubscribe, don't stall
                self._evict(victim)
        before = self._compile_counts()
        with telemetry.job_scope(job.name):
            if job.state == "evicted":
                job.resume(self.tracer)
            else:
                job.materialize()
        self._account(job, before)

    # ---------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """Step the policy's pick one round (evicting / resuming around it
        as residency demands). Returns the stepped job's name, or None
        when every job has committed."""
        job = self._pick()
        if job is None:
            return None
        self.ticks += 1
        self._ensure_resident(job)
        job.dispatched_ticks += 1
        if job.start_t is None:
            job.start_t = self.tracer.now()
        before = self._compile_counts()
        with telemetry.job_scope(job.name):
            staged = self._take_prefetched(job)
            done = job.step(self.tracer, staged=staged)
        self._account(job, before)
        self.tracer.gauge("queue_depth", depth=len(self.queue.active()))
        if done:
            job.finish_t = self.tracer.now()
            wall = job.finish_t - (job.start_t or job.finish_t)
            self.tracer.event("job_committed", job=job.name,
                              rounds=job.round_idx, wall_s=round(wall, 6))
            self._ledger_deadline(job)
            if self._prefetcher is not None:
                self._prefetcher.invalidate(job=job.name)
        else:
            self._prefetch_ahead(job)
        return job.name

    def _ledger_deadline(self, job: Job) -> None:
        """Deadline bookkeeping at completion — measured telemetry from
        the tracer clock, never an input to `_pick`, so replays stay
        bit-identical under an injected deterministic clock."""
        ddl = job.desc.deadline_s
        if ddl is None or job.submit_t is None:
            return
        latency = job.finish_t - job.submit_t
        entry = self.slo_ledger.setdefault(
            job.name, {"slo": job.desc.slo, "deadline_s": ddl,
                       "latency_s": None, "misses": 0})
        entry["latency_s"] = round(latency, 6)
        if latency > ddl:
            entry["misses"] += 1
            self.tracer.event("deadline_miss", job=job.name, deadline_s=ddl,
                              latency_s=round(latency, 6))

    def run(self) -> int:
        """Tick until the queue drains; returns the tick count. Installs
        the tracer for the duration so module-level telemetry (chaos,
        prefetch gauges, compile-cache events) lands in it."""
        install = hasattr(self.tracer, "find_events")
        if install:
            telemetry.install(self.tracer)
        try:
            while self.tick() is not None:
                pass
        finally:
            if install:
                telemetry.uninstall(self.tracer)
            self.close()
        return self.ticks

    def close(self) -> None:
        """Shut the dispatch plane down WITHOUT abandoning device state:
        any still-active resident tenant (an interrupted run) is evicted —
        its buffers snapshotted to host and freed — so a later scheduler
        can resume it; then the shared prefetcher drains."""
        for job in self.queue:
            if job.resident and not job.closed:
                self._evict(job, reason="close")
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None

    # ------------------------------------------------------- prefetch seam
    def _wants_prefetch(self, job: Job) -> bool:
        return (self._prefetch_depth > 0 and job.prefetchable
                and job.api.cfg.pipeline_depth > 0)

    def _ensure_prefetcher(self) -> CohortPrefetcher:
        if self._prefetcher is None:
            self._prefetcher = CohortPrefetcher(
                lambda r, jobname: self.queue.get(jobname).stage(r),
                depth=self._prefetch_depth)
        return self._prefetcher

    def _take_prefetched(self, job: Job):
        if not self._wants_prefetch(job):
            return None
        return self._ensure_prefetcher().get(job.round_idx, job=job.name)

    def _prefetch_ahead(self, job: Job) -> None:
        if not self._wants_prefetch(job):
            return
        pf = self._ensure_prefetcher()
        for k in range(job.api.cfg.pipeline_depth):
            r = job.round_idx + k
            if r >= job.desc.rounds:
                break
            pf.prefetch(r, job=job.name)

    # --------------------------------------------------- compile accounting
    def _compile_counts(self) -> Optional[Dict[str, int]]:
        """Fold the tracer's compile_cache event ledger into cumulative
        {requests, cache_hits, cache_misses}; None when the tracer keeps no
        event ledger (NullTracer)."""
        if not hasattr(self.tracer, "find_events"):
            return None
        totals = _zero_counts()
        for e in self.tracer.find_events("compile_cache"):
            key = _COMPILE_TAILS.get(str(e.get("name", "")).rsplit("/", 1)[-1])
            if key is not None:
                totals[key] += 1
        return totals

    def _account(self, job: Job, before: Optional[Dict[str, int]]) -> None:
        ledger = self.compile_ledger.setdefault(job.name, _zero_counts())
        if before is None:
            return
        after = self._compile_counts()
        for key in ledger:
            ledger[key] += after[key] - before[key]

    def check_compile_budgets(self, budgets: Optional[dict] = None):
        """Gate every tenant's compile requests against its drive's
        `max_compiles` ceiling in COMPILE_BUDGET.json. Returns
        (ok, report) — ok is False if ANY tenant exceeded its ceiling;
        tenants whose drive pins no ceiling are SKIP lines."""
        if budgets is None:
            budgets = self.budgets if self.budgets is not None \
                else load_compile_budgets()
        lines = []
        ok = True
        for job in self.queue:
            counts = self.compile_ledger.get(job.name, _zero_counts())
            drive = job.desc.drive
            ceiling = (budgets.get(drive) or {}).get("max_compiles")
            if ceiling is None:
                lines.append(f"SKIP tenant={job.name} drive={drive} "
                             f"requests={counts['requests']} "
                             f"(no ceiling pinned)")
                continue
            verdict = "OK" if counts["requests"] <= ceiling else "FAIL"
            if verdict == "FAIL":
                ok = False
            lines.append(
                f"{verdict} tenant={job.name} drive={drive} "
                f"requests={counts['requests']} <= max {ceiling} "
                f"(hits={counts['cache_hits']} "
                f"misses={counts['cache_misses']})")
        return ok, "\n".join(lines)

    def check_slo(self, miss_ceiling: int = 0):
        """Gate every deadline-armed tenant's miss count against
        `miss_ceiling`, mirroring check_compile_budgets' (ok, report)
        shape. Tenants without a pinned deadline are SKIP lines; cancelled
        tenants never count (they have no completion to miss)."""
        lines = []
        ok = True
        for job in self.queue:
            ddl = job.desc.deadline_s
            if ddl is None:
                lines.append(f"SKIP tenant={job.name} slo={job.desc.slo} "
                             f"(no deadline pinned)")
                continue
            entry = self.slo_ledger.get(
                job.name, {"misses": 0, "latency_s": None})
            verdict = "OK" if entry["misses"] <= miss_ceiling else "FAIL"
            if verdict == "FAIL":
                ok = False
            lines.append(
                f"{verdict} tenant={job.name} slo={job.desc.slo} "
                f"misses={entry['misses']} <= max {miss_ceiling} "
                f"(deadline_s={ddl} latency_s={entry['latency_s']})")
        return ok, "\n".join(lines)
