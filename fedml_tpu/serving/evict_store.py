"""Mmap-backed spill store for evicted tenant snapshots (graft-slo).

`Job.evict()` fetches a tenant's full Checkpointable surface to host —
params/adapters, aggregator (and codec residual) state, the buffered
runner's K-row buffer + birth tags + pending-arrival results, the guard's
loss history. Holding 100+ evicted tenants' snapshots as live numpy in the
scheduler process is exactly the RSS failure mode the packed-store layout
was built to avoid, so the store spills every array leaf of the snapshot
into ONE packed binary per tenant (`<name>.bin`) with a JSON manifest of
(offset, dtype, shape) entries, and `load()` hands the leaves back as
`np.memmap` views — the OS pages them in lazily when `Job.resume()`
re-uploads them, and a resumed tenant's bytes are identical to an
in-memory round trip (tests/test_serving.py pins evict→resume parity
through this store).

Only array leaves go out-of-line; the snapshot's small host structure
(arrival schedules, birth tags, counters, the pytree skeleton itself)
stays in memory — it is O(cohort), not O(model), and the treedef cannot
be serialized portably anyway. The store is in-process by design: eviction
frees *device* memory (the mesh slot), not the scheduler's address space.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import jax

from fedml_tpu.utils.packed_leaves import load_leaves, spill_leaves


class EvictionStore:
    """One spill directory; tenants addressed by job name (re-evicting a
    name overwrites its previous spill). The on-disk bytes are the shared
    packed-leaf format (utils/packed_leaves.py) the adapter bank also
    writes, so a spilled tenant and its bank rows stay byte-comparable."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # name -> (treedef, inline leaves with None placeholders, manifest)
        self._index: Dict[str, Tuple[Any, list, dict]] = {}

    def save(self, name: str, snapshot: Any) -> dict:
        """Spill `snapshot`'s array leaves to `<name>.bin`; returns the
        manifest (also written as `<name>.json` for inspection)."""
        leaves, treedef = jax.tree.flatten(snapshot)
        bin_path = os.path.join(self.root, f"{name}.bin")
        entries, inline, offset = spill_leaves(bin_path, leaves)
        manifest = {"bin": bin_path, "bytes": offset, "arrays": entries}
        with open(os.path.join(self.root, f"{name}.json"), "w") as f:
            json.dump(manifest, f)
        self._index[name] = (treedef, inline, manifest)
        return manifest

    def load(self, name: str) -> Any:
        """Rehydrate `name`'s snapshot; array leaves come back as read-only
        `np.memmap` views over the packed binary."""
        treedef, inline, manifest = self._index.pop(name)
        leaves = load_leaves(manifest["bin"], manifest["arrays"], inline)
        return jax.tree.unflatten(treedef, leaves)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __len__(self) -> int:
        return len(self._index)
