"""TurboAggregate experiment main (reference fedml_experiments distributed
turboaggregate launch: FedAvg training under secure multi-group circular
aggregation — the server reconstructs only the group-ring share sum).

Usage:
  python -m fedml_tpu.experiments.main_turboaggregate --dataset mnist \
      --model lr --client_num_in_total 8 --client_num_per_round 8 \
      --num_groups 2 --comm_round 3
"""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.turboaggregate import TurboAggregateAPI
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    parser.add_argument("--num_groups", type=int, default=2)
    parser.add_argument("--privacy_threshold", type=int, default=None)
    parser.add_argument("--frac_bits", type=int, default=16)
    args = parser.parse_args(argv)
    cfg, ds, trainer = setup_run(args)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = TurboAggregateAPI(ds, cfg, trainer, num_groups=args.num_groups,
                            threshold=args.privacy_threshold,
                            frac_bits=args.frac_bits)
    history = api.train(metrics_logger=logger)
    logger.finish()
    return history


if __name__ == "__main__":
    main()
