"""YAML-driven experiment launcher (reference fedml_experiments/distributed/
fed_launch/: run_fedavg.sh + main.py dispatch over a hostfile + gpu_util
YAML). The TPU-native launch has no mpirun/hostfiles — one process drives
the device mesh — so the YAML describes the *experiment*: algorithm,
model, dataset, hyperparameters and mesh shape; multi-host deployments add
a `multihost:` block (coordinator address + process grid) consumed by
`fedml_tpu.parallel.multihost.init_multihost`.

Config example (see also configs/ in this directory):

    algorithm: fedavg            # any main in fedml_tpu.experiments
    args:
      dataset: femnist
      model: cnn
      client_num_in_total: 3400
      client_num_per_round: 10
      comm_round: 100
      batch_size: 20
      lr: 0.1
      backend: shard_map
      mesh_shape: [8]
    # multihost:                 # optional cross-silo deployment
    #   coordinator: "10.0.0.1:1234"
    #   num_processes: 4
    #   process_id: 0            # or taken from $FEDML_PROCESS_ID

Usage:
  python -m fedml_tpu.experiments.fed_launch --config exp.yaml
  python -m fedml_tpu.experiments.fed_launch --config exp.yaml --override comm_round=2
"""

from __future__ import annotations

import argparse
import importlib
import os

ALGORITHMS = {
    # algorithm name -> experiments module with a main(argv) entry
    name: f"fedml_tpu.experiments.main_{name}"
    for name in ("fedavg", "fedopt", "fednova", "fedavg_robust", "hierarchical",
                 "decentralized", "fednas", "base", "fedgkt", "split_nn", "vfl",
                 "turboaggregate", "fedseg", "privacy")
}


def _load_yaml(path: str) -> dict:
    try:
        import yaml

        with open(path) as f:
            return yaml.safe_load(f)
    except ImportError:
        # yaml is optional in this image — accept the JSON subset
        import json

        with open(path) as f:
            return json.load(f)


def config_to_argv(args_map: dict) -> list[str]:
    argv: list[str] = []
    for k, v in args_map.items():
        if isinstance(v, bool):
            if v:
                argv.append(f"--{k}")  # bare store_true flag; False -> omit
        elif isinstance(v, (list, tuple)):
            argv += [f"--{k}"] + [str(x) for x in v]
        else:
            argv += [f"--{k}", str(v)]
    return argv


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, required=True)
    parser.add_argument("--override", type=str, nargs="*", default=[],
                        action="extend",
                        help="key=value overrides applied on top of the YAML; "
                             "repeatable (occurrences accumulate)")
    args = parser.parse_args(argv)
    cfg = _load_yaml(args.config)
    algo = cfg.get("algorithm", "fedavg")
    if algo not in ALGORITHMS:
        raise SystemExit(f"unknown algorithm {algo!r}; one of {sorted(ALGORITHMS)}")
    exp_args = dict(cfg.get("args", {}))
    for ov in args.override:
        k, _, v = ov.partition("=")
        exp_args[k] = v

    mh = cfg.get("multihost")
    if mh:
        from fedml_tpu.parallel.multihost import init_multihost

        pid = mh.get("process_id")
        if pid is None:
            pid = int(os.environ.get("FEDML_PROCESS_ID", "0"))
        info = init_multihost(mh["coordinator"], int(mh["num_processes"]), int(pid))
        print(f"multihost topology: {info}")

    module = importlib.import_module(ALGORITHMS[algo])
    return module.main(config_to_argv(exp_args))


if __name__ == "__main__":
    main()
