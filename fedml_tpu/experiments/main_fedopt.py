"""FedOpt experiment main (reference fedml_experiments/distributed/fedopt/
main_fedopt.py — adds --server_optimizer/--server_lr, main_fedopt.py:54-60)."""

from __future__ import annotations

from fedml_tpu.experiments.main_fedavg import main as fedavg_main


def _extra(parser):
    parser.add_argument("--server_optimizer", type=str, default="adam")
    parser.add_argument("--server_lr", type=float, default=0.001)
    parser.add_argument("--server_momentum", type=float, default=0.0)


def main(argv=None):
    return fedavg_main(argv, aggregator_name="fedopt", extra_args=_extra)


if __name__ == "__main__":
    main()
