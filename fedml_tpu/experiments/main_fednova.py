"""FedNova experiment main (reference fedml_experiments/standalone/fednova/).
FedProx is its --fedprox_mu flag (reference fednova.py:124-126 mu term)."""

from __future__ import annotations

from fedml_tpu.experiments.main_fedavg import main as fedavg_main


def main(argv=None):
    return fedavg_main(argv, aggregator_name="fednova")


if __name__ == "__main__":
    main()
