"""Base-framework smoke main (reference fedml_experiments/distributed/base/
— the CI framework smoke test target, CI-script-framework.sh:16-23)."""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.base_framework import FedML_Base_simulated


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--client_num", type=int, default=4)
    parser.add_argument("--comm_round", type=int, default=3)
    args = parser.parse_args(argv)
    out = FedML_Base_simulated(args.client_num,
                               lambda i, r: float(i + r), args.comm_round)
    print("aggregated:", out)
    return out


if __name__ == "__main__":
    main()
