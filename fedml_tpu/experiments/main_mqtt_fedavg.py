"""FedAvg over MQTT — the mobile deployment mode as a CLI.

Single-host simulation of the reference's is_mobile path (reference
FedAvgClientManager.py:148-156 + mqtt_comm_manager.py:14-125): an in-process
broker, a server actor and one worker actor per sampled client exchange real
MQTT frames with list-encoded model payloads; each worker's local SGD is the
jitted engine step. Point --broker_host/--broker_port at an external broker
to span processes/machines instead.

Usage:
  python -m fedml_tpu.experiments.main_mqtt_fedavg --dataset mnist --model lr \
      --client_num_in_total 4 --client_num_per_round 2 --comm_round 3
"""

from __future__ import annotations

import argparse

from fedml_tpu.comm.mqtt_fedavg import run_mqtt_fedavg
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    parser.add_argument("--broker_host", type=str, default=None,
                        help="external MQTT broker (default: in-process)")
    parser.add_argument("--broker_port", type=int, default=1883)
    args = parser.parse_args(argv)
    cfg, ds, trainer = setup_run(args)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    _, history = run_mqtt_fedavg(
        ds, trainer, cfg, host=args.broker_host,
        port=args.broker_port if args.broker_host else None,
    )
    for rec in history:
        out = {"round": rec["round"]}
        if "test_acc" in rec:
            out["Test/Acc"] = rec["test_acc"]
            out["Test/Loss"] = rec["test_loss"]
        logger.log(out, step=rec["round"])
    logger.finish()
    return history


if __name__ == "__main__":
    main()
