"""Hierarchical FL experiment main (reference fedml_experiments/standalone/
hierarchical_fl/ — --group_num / --group_comm_round)."""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.hierarchical import HierarchicalFLAPI
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    parser.add_argument("--group_num", type=int, default=2)
    parser.add_argument("--group_comm_round", type=int, default=1)
    args = parser.parse_args(argv)
    cfg, ds, trainer = setup_run(args)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = HierarchicalFLAPI(ds, cfg, trainer, group_num=args.group_num,
                            group_comm_round=args.group_comm_round)
    history = api.train()
    for rec in history:
        logger.log({k: v for k, v in rec.items() if k != "round"}, step=rec["round"])
    logger.finish()
    return history


if __name__ == "__main__":
    main()
