"""Robust FedAvg experiment main (reference fedml_experiments/distributed/
fedavg_robust/ + FedAvgRobustAggregator.py:14-112): norm-clipping + weak-DP
defense aggregation under an active backdoor attacker, with poisoned-task
evaluation alongside the main task.

Attackers (the first `--attacker_num` clients) poison `--poison_frac` of
their local samples: with the reference's edge-case pickles present under
--data_dir (southwest airplanes labeled as truck) those images are used;
otherwise the pixel-trigger substitute. After training the final model is
scored on main-task accuracy AND backdoor success rate, written to
wandb-summary.json.
"""

from __future__ import annotations

import argparse

import numpy as np

from fedml_tpu.algorithms.backdoor import (
    backdoor_metrics,
    load_edge_case_sets,
    poison_client_data,
)
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.utils.logging import MetricsLogger


def _extra(parser: argparse.ArgumentParser):
    parser.add_argument("--norm_bound", type=float, default=5.0)
    parser.add_argument("--stddev", type=float, default=0.025)
    parser.add_argument("--attacker_num", type=int, default=1)
    parser.add_argument("--poison_frac", type=float, default=0.5)
    parser.add_argument("--target_label", type=int, default=9)
    parser.add_argument("--trigger_size", type=int, default=3)


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    _extra(parser)
    args = parser.parse_args(argv)
    cfg, ds, trainer = setup_run(args)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))

    # ---- poison the attackers' packed rows (reference load_poisoned_dataset)
    # Normalize the edge-case images with the SAME channel stats the target
    # dataset's loader applied (keyed by dataset name, not image shape —
    # cinic10 is 32x32x3 but uses CINIC stats, data/readers.py:146-148)
    from fedml_tpu.data.readers import CINIC10_MEAN, CINIC10_STD

    _edge_stats = {
        "cifar10": True,  # load_edge_case_sets' default CIFAR-10 stats
        "cifar100": True,  # load_cifar_arrays normalizes cifar100 identically
        "cinic10": (CINIC10_MEAN, CINIC10_STD),
    }
    edge = None
    if args.dataset in _edge_stats and tuple(ds.train.x.shape[2:]) == (32, 32, 3):
        edge = load_edge_case_sets(args.data_dir,
                                   normalize=_edge_stats[args.dataset])
    if args.attacker_num > 0 and not isinstance(ds.train.x, np.ndarray):
        # streaming datasets (ILSVRC2012/gld*) expose a lazy x facade with no
        # item assignment — poisoning mutates rows, so materialize (bounded
        # by the stream byte budget; errors clearly past it)
        from dataclasses import replace as _dc_replace

        from fedml_tpu.data.streaming import materialize

        ds = _dc_replace(ds, train=materialize(ds.train))
    rng = np.random.RandomState(cfg.seed)
    for k in range(min(args.attacker_num, ds.train.num_clients)):
        count = int(ds.train.counts[k])
        if edge is not None:
            x_poison, _, target = edge
            n_p = min(int(count * args.poison_frac), len(x_poison))
            idx = rng.choice(count, n_p, replace=False)
            ds.train.x[k][idx] = x_poison[:n_p]
            ds.train.y[k][idx] = target
        else:
            x_new, y_new = poison_client_data(
                ds.train.x[k], ds.train.y[k], count, args.target_label,
                args.poison_frac, args.trigger_size, rng)
            ds.train.x[k] = x_new
            ds.train.y[k] = y_new

    api = FedAvgAPI(ds, cfg, trainer, aggregator_name="robust")
    history = api.train(ckpt_dir=args.ckpt_dir, metrics_logger=logger)

    # ---- poisoned-task eval (reference test(..., mode="targetted-task"))
    import jax.numpy as jnp

    def predict(x):
        logits, _ = trainer.apply(api.global_variables, x, train=False)
        return logits

    xte, yte = ds.test_global
    n = min(len(yte), 2048)
    bm = backdoor_metrics(
        predict, jnp.asarray(xte[:n]), np.asarray(yte[:n]),
        target_label=(edge[2] if edge is not None else args.target_label),
        trigger_size=args.trigger_size,
        x_edge_case=(edge[1] if edge is not None else None))
    logger.log(bm, step=cfg.comm_round)
    logger.finish()
    return history


if __name__ == "__main__":
    main()
