"""Robust FedAvg experiment main (reference fedml_experiments/distributed/
fedavg_robust/ — norm-clipping + weak-DP defense aggregation)."""

from __future__ import annotations

from fedml_tpu.experiments.main_fedavg import main as fedavg_main


def _extra(parser):
    parser.add_argument("--norm_bound", type=float, default=5.0)
    parser.add_argument("--stddev", type=float, default=0.025)


def main(argv=None):
    return fedavg_main(argv, aggregator_name="robust", extra_args=_extra)


if __name__ == "__main__":
    main()
