"""Privacy experiment main (reference privacy_fedml/main_fedavg.py:1-552 —
the fork's raison d'etre: branch/ensemble FedAvg + membership-inference
attack evaluation). Flags mirror the reference surface (:100-135):
--branch_num, --ensemble_method, --server_data_ratio, --feat_lmda,
--no_mi_attack.

Ensemble methods: predavg / predvote / predweight / blockavg / hetero via
BranchFedAvgAPI (privacy/branch_fedavg.py); blockensemble via the true
block-mixing BlockEnsembleAPI (privacy/blockensemble.py) whose clients run
TwoModelTrainer/ThreeModelTrainer joint training (--num_paths 2|3).

Usage:
  python -m fedml_tpu.experiments.main_privacy --dataset mnist \
      --branch_num 4 --ensemble_method blockensemble --comm_round 3
"""

from __future__ import annotations

import argparse

import jax.numpy as jnp

from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.experiments.common import add_args, config_from_args
from fedml_tpu.utils.logging import MetricsLogger


def run_mi_attacks(predict_fn, trainer, variables, member, nonmember):
    """Shadow-NN + loss + gradient-norm membership attacks on the final
    model (reference privacy_fedml/MI_attack/*; privacy/mi_attack.py)."""
    from fedml_tpu.privacy.mi_attack import (
        GradientVectorAttack,
        MixGradientAttack,
        NNAttack,
        gradient_norm_attack,
        loss_attack,
        make_penultimate_grad_fn,
        make_per_sample_grad_norm,
        make_per_sample_loss,
    )

    (mx, my), (nx, ny) = member, nonmember
    out = {}
    nn_attack = NNAttack(top_k=3)
    nn_attack.fit(predict_fn, mx, nx)
    out.update({f"MI/NN_{k}": v for k, v in
                nn_attack.score(predict_fn, mx, nx).items()})
    if trainer is not None and variables is not None:
        loss_fn = make_per_sample_loss(trainer, variables)
        out.update({f"MI/Loss_{k}": v for k, v in
                    loss_attack(loss_fn, (mx, my), (nx, ny)).items()})
        gn_fn = make_per_sample_grad_norm(trainer, variables)
        out.update({f"MI/GradNorm_{k}": v for k, v in
                    gradient_norm_attack(gn_fn, (mx, my), (nx, ny)).items()})
        pg_fn = make_penultimate_grad_fn(trainer, variables)

        def local_predict(x):
            logits, _ = trainer.apply(variables, x, train=False)
            return logits

        # gradient-vector attack: the LOCAL model's own preds + grads
        gv = GradientVectorAttack().fit(local_predict, pg_fn, (mx, my), (nx, ny))
        out.update({f"MI/GradVec_{k}": v for k, v in
                    gv.score(local_predict, pg_fn, (mx, my), (nx, ny)).items()})
        # mix-gradient attack: TARGET (ensemble) preds + LOCAL grads — the
        # reference's feature mix (MixGradient_attack.py:104-114). Only
        # meaningful when the target prediction differs from the local one.
        mg = MixGradientAttack(seed=1).fit(predict_fn, pg_fn, (mx, my), (nx, ny))
        out.update({f"MI/MixGrad_{k}": v for k, v in
                    mg.score(predict_fn, pg_fn, (mx, my), (nx, ny)).items()})
    return out


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    # reference privacy_fedml/main_fedavg.py:122-134
    parser.add_argument("--branch_num", type=int, default=4)
    parser.add_argument("--ensemble_method", type=str, default="predavg",
                        choices=["predavg", "predvote", "predweight",
                                 "blockavg", "hetero", "blockensemble"])
    parser.add_argument("--server_data_ratio", type=float, default=0.1)
    parser.add_argument("--feat_lmda", type=float, default=0.0)
    parser.add_argument("--num_paths", type=int, default=2,
                        help="2 = TwoModelTrainer, 3 = ThreeModelTrainer "
                             "(blockensemble client joint training)")
    parser.add_argument("--no_mi_attack", action="store_true")
    parser.add_argument("--shared_blocks", type=str, nargs="*", default=None)
    args = parser.parse_args(argv)
    cfg = config_from_args(args)

    from fedml_tpu.data.registry import load_dataset

    # AdaptiveCNN branches operate on images — keep mnist/fmnist unflattened
    ds = load_dataset(args.dataset, data_dir=args.data_dir,
                      client_num_in_total=args.client_num_in_total,
                      partition_method=args.partition_method,
                      partition_alpha=args.partition_alpha, seed=args.seed,
                      flatten=False)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))

    trainer_for_mi = None
    vars_for_mi = None
    if args.ensemble_method == "blockensemble":
        from fedml_tpu.privacy.blockensemble import BlockEnsembleAPI

        api = BlockEnsembleAPI(ds, cfg, branch_num=args.branch_num,
                               num_paths=args.num_paths,
                               feat_lmda=args.feat_lmda)
        api.train(metrics_logger=logger)
        predict_fn = lambda x: jnp.log(api.branch_probs(x).mean(axis=0) + 1e-9)
    else:
        from fedml_tpu.models.ensemble import AdaptiveCNN, ArchSpec, build_hetero_archs
        from fedml_tpu.privacy.branch_fedavg import BranchFedAvgAPI

        if args.ensemble_method == "hetero":
            archs = build_hetero_archs(args.branch_num)
        else:
            archs = [ArchSpec()] * args.branch_num
        _dt = jnp.bfloat16 if cfg.dtype == "bfloat16" else None
        trainers = [ClassificationTrainer(
            AdaptiveCNN(output_dim=ds.class_num, arch=a, dtype=_dt))
            for a in archs]
        shared = (tuple(args.shared_blocks) if args.shared_blocks
                  else (("conv1_out", "conv2_out")
                        if args.ensemble_method == "blockavg" else ()))
        api = BranchFedAvgAPI(ds, cfg, trainers,
                              ensemble_method=args.ensemble_method,
                              shared_blocks=shared,
                              server_data_ratio=args.server_data_ratio)
        history = api.train()
        for rec in history:
            logger.log({k: v for k, v in rec.items() if k != "round"},
                       step=rec["round"])
        trainer_for_mi = trainers[0]
        vars_for_mi = api.branches[0]
        predict_fn = lambda x: jnp.log(api.branch_probs(x).mean(axis=0) + 1e-9)

    final = api.evaluate()
    logger.log(final, step=cfg.comm_round)

    if not args.no_mi_attack:
        # members = training samples seen by the federation; nonmembers =
        # held-out test samples (reference MI eval split)
        xtr, ytr = ds.train_global
        xte, yte = ds.test_global
        k = min(len(ytr), len(yte), 512)
        member = (jnp.asarray(xtr[:k]), jnp.asarray(ytr[:k]))
        nonmember = (jnp.asarray(xte[:k]), jnp.asarray(yte[:k]))
        mi = run_mi_attacks(predict_fn, trainer_for_mi, vars_for_mi,
                            member, nonmember)
        logger.log(mi, step=cfg.comm_round)
        final.update(mi)

    logger.finish()
    return api.history, final


if __name__ == "__main__":
    main()
