"""Shared experiment plumbing: the reference's argparse surface + run setup.

Flag names follow reference fedml_experiments/distributed/fedavg/
main_fedavg.py:46-112 verbatim so launch scripts transfer; GPU-mapping flags
are replaced by mesh flags (SURVEY §2.2 gpu_mapping -> jax.sharding.Mesh).
"""

from __future__ import annotations

import argparse
import logging
import random

import numpy as np

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import (
    ClassificationTrainer,
    NWPTrainer,
    TagPredictionTrainer,
)
from fedml_tpu.data.registry import FederatedDataset, load_dataset
from fedml_tpu.models.registry import create_model


def add_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """Reference add_args (main_fedavg.py:46-112), TPU-adapted."""
    parser.add_argument("--model", type=str, default="lr")
    parser.add_argument("--dataset", type=str, default="mnist")
    parser.add_argument("--data_dir", type=str, default="./data")
    parser.add_argument("--partition_method", type=str, default="hetero")
    parser.add_argument("--partition_alpha", type=float, default=0.5)
    parser.add_argument("--client_num_in_total", type=int, default=10)
    parser.add_argument("--client_num_per_round", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=10)
    parser.add_argument("--client_optimizer", type=str, default="sgd")
    parser.add_argument("--lr", type=float, default=0.03)
    parser.add_argument("--wd", type=float, default=0.0)
    parser.add_argument("--momentum", type=float, default=0.0)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--comm_round", type=int, default=10)
    parser.add_argument("--frequency_of_the_test", type=int, default=1)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ci", type=int, default=0)
    # TPU-native replacements for gpu_server_num / gpu_mapping_file
    parser.add_argument("--backend", type=str, default="vmap",
                        choices=["vmap", "shard_map"])
    parser.add_argument("--mesh_shape", type=int, nargs="*", default=None)
    parser.add_argument("--ckpt_dir", type=str, default=None)
    parser.add_argument("--run_dir", type=str, default="./wandb/latest-run/files")
    parser.add_argument("--fedprox_mu", type=float, default=0.0)
    parser.add_argument("--dtype", type=str, default="float32",
                        choices=["float32", "bfloat16"])
    # fault-tolerance drive-loop knobs (fedml_tpu.robustness)
    parser.add_argument("--chaos", type=int, default=0,
                        help="1 = inject a seeded deterministic fault "
                             "schedule (drops/NaN/corruption) per round")
    parser.add_argument("--chaos_seed", type=int, default=0)
    parser.add_argument("--chaos_drop_rate", type=float, default=0.0)
    parser.add_argument("--chaos_nan_rate", type=float, default=0.0)
    parser.add_argument("--chaos_corrupt_rate", type=float, default=0.0)
    # seeded straggler plan (buffered aggregation): straggling clients'
    # updates arrive 1..straggler_rounds dispatch rounds late
    parser.add_argument("--chaos_straggler_rate", type=float, default=0.0)
    parser.add_argument("--chaos_straggler_rounds", type=int, default=0)
    parser.add_argument("--guard", type=int, default=0,
                        help="1 = roll back + re-run rounds whose loss goes "
                             "non-finite or spikes")
    parser.add_argument("--guard_spike_factor", type=float, default=4.0)
    parser.add_argument("--guard_max_retries", type=int, default=2)
    # asynchronous round pipeline (fedml_tpu.data.prefetch): stage cohort
    # t+k while round t executes + deferred metric sync; bit-identical to
    # the eager loop at any depth, so it is on by default for CLI runs.
    # 0 restores the eager driver.
    parser.add_argument("--pipeline_depth", type=int, default=2,
                        help="cohort prefetch depth for the FedAvg-family "
                             "drive loop (0 = eager)")
    # tensor-parallel rounds (fedml_tpu.parallel.tensor): params +
    # aggregator state sharded per the model family's partition-rule table
    # over a 2D ('clients', 'tensor') mesh; bit-identical in f32 to the
    # replicated round
    parser.add_argument("--tensor_shards", type=int, default=0,
                        help="tensor-axis size of the 2D (clients, tensor) "
                             "mesh (0 = replicated params)")
    parser.add_argument("--shard_step", type=int, default=0,
                        help="1 = activation-shard the client step itself "
                             "(GSPMD + with_sharding_constraint on model "
                             "intermediates; allclose contract, needs "
                             "--tensor_shards > 1)")
    # federated LoRA (models/lora.py): frozen base + rank-r adapters;
    # only adapters cross the wire / hit the aggregator / get checkpointed
    parser.add_argument("--lora_rank", type=int, default=0,
                        help="LoRA adapter rank; 0 = full fine-tuning "
                             "(trainer never wrapped, legacy programs)")
    # fused pallas SGD epoch kernel (ops/fused_sgd.py, ROADMAP item 1a)
    parser.add_argument("--fused_kernel", type=int, default=0,
                        help="1 = run the local epoch as ONE fused pallas "
                             "kernel (femnist-CNN shapes; interpret mode "
                             "on CPU)")
    # multi-round fused dispatch (engine.build_superstep_fn): K rounds per
    # jitted lax.scan program — in-graph cohort gather from a device-resident
    # store, one deferred metrics fetch per chunk. Bit-identical to K eager
    # rounds; eval/checkpoint cadence clamps K per chunk. 1 = eager loop.
    parser.add_argument("--rounds_per_dispatch", type=int, default=1,
                        help="federated rounds fused into one device "
                             "program (1 = eager; needs pipeline_depth 0)")
    parser.add_argument("--fast_sampling", type=int, default=0,
                        help="1 = O(cohort) Feistel-permutation cohort "
                             "sampler (different seeded trajectory than the "
                             "default O(N) sampler)")
    # staleness-aware buffered aggregation (fedml_tpu.algorithms.buffered):
    # admit updates into a K-row device buffer, commit when it fills — no
    # global round barrier; deterministic under the seeded straggler plan
    parser.add_argument("--buffer_size", type=int, default=0,
                        help="update-buffer size K for FedBuff-style "
                             "buffered aggregation (0 = synchronous)")
    parser.add_argument("--staleness_alpha", type=float, default=0.5,
                        help="staleness-discount exponent: committed weight "
                             "= count * (1 + staleness) ** -alpha")
    # compressed update transport (fedml_tpu.codecs): codec stage between
    # the client step and the aggregator; "none" keeps the exact legacy
    # (bit-identical) round program
    parser.add_argument("--update_codec", type=str, default="none",
                        choices=["none", "int8", "topk"],
                        help="update transport codec: int8 quantization "
                             "with error feedback, or top-k sparsification "
                             "with static-shape payloads")
    parser.add_argument("--codec_k", type=int, default=64,
                        help="top-k codec: entries kept per leaf (clamped "
                             "to the leaf size)")
    parser.add_argument("--codec_bits", type=int, default=8,
                        help="int8 codec: quantization width in bits (2-8; "
                             "wire dtype stays int8)")
    # graft-trace observability (fedml_tpu.telemetry): TRACE.jsonl is
    # always written to <run_dir>/TRACE.jsonl; these knobs add sinks
    parser.add_argument("--trace_summary", type=int, default=0,
                        help="1 = print an end-of-run per-phase p50/p95 "
                             "span table")
    parser.add_argument("--trace_wandb", type=int, default=0,
                        help="1 = mirror per-round phase durations into the "
                             "metrics logger as trace/<phase>_s")
    parser.add_argument("--profile_rounds", type=str, default=None,
                        help="A:B = capture a jax.profiler trace window "
                             "covering rounds [A, B) into --profile_dir")
    parser.add_argument("--profile_dir", type=str, default=None,
                        help="TensorBoard trace dir for --profile_rounds "
                             "(default <run_dir>/trace)")
    parser.add_argument("--trace_max_mb", type=float, default=0,
                        help="rotate TRACE.jsonl when it exceeds this many "
                             "MB (archived as TRACE.jsonl.NNN; 0 = never)")
    # graft-ledger client-health observability (telemetry/client_ledger.py):
    # out-of-core per-client counters fed by the round programs' stats
    # vector; read back with tools/client_report.py
    parser.add_argument("--client_ledger_dir", type=str, default=None,
                        help="directory for the mmap-backed per-client "
                             "health ledger (None = ledger off)")
    # graft-pfl million-client personalization (models/adapter_bank.py):
    # per-client rank-r adapter rows in a packed sparse mmap bank —
    # O(cohort) gather/scatter per round, O(touched rows) disk
    parser.add_argument("--adapter_bank_dir", type=str, default=None,
                        help="directory for the personal adapter bank; "
                             "setting it turns personalization ON "
                             "(requires --lora_rank > 0); resumable — "
                             "reopening validates rows and layout")
    parser.add_argument("--adapter_clusters", type=int, default=0,
                        help="share K cluster rows instead of one row per "
                             "client (assignment: static EMA-loss bucket "
                             "from the client ledger; 0 = per-client rows)")
    return parser


def robustness_from_args(args):
    """(FaultPlan | None, RoundGuard | None) from the --chaos/--guard flags."""
    chaos = guard = None
    if getattr(args, "chaos", 0):
        from fedml_tpu.robustness.chaos import FaultPlan

        chaos = FaultPlan(
            seed=args.chaos_seed,
            drop_rate=args.chaos_drop_rate,
            nan_rate=args.chaos_nan_rate,
            corrupt_rate=args.chaos_corrupt_rate,
            straggler_rate=getattr(args, "chaos_straggler_rate", 0.0),
            straggler_rounds=getattr(args, "chaos_straggler_rounds", 0))
    if getattr(args, "guard", 0):
        from fedml_tpu.robustness.guard import RoundGuard

        guard = RoundGuard(spike_factor=args.guard_spike_factor,
                           max_retries=args.guard_max_retries)
    return chaos, guard


def tracer_from_args(args, metrics_logger=None):
    """The run's graft-trace Tracer: TRACE.jsonl manifest in run_dir
    (always on — it is the run's flight recorder), optional wandb mirror
    (--trace_wandb) and jax.profiler window (--profile_rounds A:B)."""
    import os

    from fedml_tpu import telemetry

    run_dir = getattr(args, "run_dir", None)
    jsonl = os.path.join(run_dir, "TRACE.jsonl") if run_dir else None
    if jsonl:
        os.makedirs(run_dir, exist_ok=True)
    profile_dir = getattr(args, "profile_dir", None)
    if profile_dir is None and run_dir:
        profile_dir = os.path.join(run_dir, "trace")
    max_mb = getattr(args, "trace_max_mb", 0) or 0
    return telemetry.Tracer(
        jsonl_path=jsonl,
        metrics_logger=metrics_logger if getattr(args, "trace_wandb", 0)
        else None,
        profile_rounds=getattr(args, "profile_rounds", None),
        profile_dir=profile_dir,
        max_bytes=int(max_mb * 2 ** 20) or None,
        run_meta={"model": args.model, "dataset": args.dataset,
                  "clients": args.client_num_in_total,
                  "clients_per_round": args.client_num_per_round,
                  "batch_size": args.batch_size,
                  "pipeline_depth": args.pipeline_depth})


def ledger_from_args(args, num_clients: int):
    """The run's ClientLedger (--client_ledger_dir), or None. The ledger is
    opened against the dataset's FULL client population — its disk footprint
    is O(num_clients), its per-round write is O(cohort)."""
    ledger_dir = getattr(args, "client_ledger_dir", None)
    if not ledger_dir:
        return None
    from fedml_tpu.telemetry.client_ledger import open_or_create

    return open_or_create(ledger_dir, num_clients)


def bank_from_args(args, num_clients: int, api):
    """The run's AdapterBank (--adapter_bank_dir), or None. Row count is
    the full client population (or --adapter_clusters K in cluster mode);
    disk stays O(touched rows) — sparse files, lazy zero rows. The row
    template is the api's live adapter tree, so resume validates layout
    against THIS run's model/rank."""
    bank_dir = getattr(args, "adapter_bank_dir", None)
    if not bank_dir:
        return None
    import jax

    from fedml_tpu.models.adapter_bank import open_or_create

    template = jax.tree.map(
        lambda l: np.zeros(l.shape, l.dtype),
        jax.device_get(api.global_variables["params"]))
    clusters = int(getattr(args, "adapter_clusters", 0) or 0)
    rows = clusters if clusters > 0 else num_clients
    return open_or_create(bank_dir, rows, template)


def config_from_args(args) -> FedConfig:
    d = {k: v for k, v in vars(args).items() if v is not None}
    d.pop("data_dir", None)
    d.pop("ckpt_dir", None)
    d.pop("run_dir", None)
    # observability knobs configure the tracer/ledger, not the round program
    for k in ("trace_summary", "trace_wandb", "profile_rounds",
              "profile_dir", "trace_max_mb", "client_ledger_dir"):
        d.pop(k, None)
    # --adapter_bank_dir IS the personalization switch: the bank location
    # is a drive-side concern, the personalize bit is the config axis
    if d.pop("adapter_bank_dir", None):
        d["personalize"] = True
    if d.get("mesh_shape"):
        d["mesh_shape"] = tuple(d["mesh_shape"])
    else:
        d.pop("mesh_shape", None)
    d["fast_sampling"] = bool(d.get("fast_sampling", 0))
    d["shard_step"] = bool(d.get("shard_step", 0))
    d["fused_kernel"] = bool(d.get("fused_kernel", 0))
    # the superstep subsumes the pipeline (there is no per-round host gap
    # left to overlap) — a fused CLI run drops the pipeline default rather
    # than tripping the library's mutual-exclusion check
    if int(d.get("rounds_per_dispatch", 1)) > 1:
        d["pipeline_depth"] = 0
    return FedConfig.from_dict(d)


def setup_run(args) -> tuple[FedConfig, FederatedDataset, object]:
    """Seeds + logging + data + model + task trainer (reference main
    preamble, main_fedavg.py:262-320: trainer chosen by dataset)."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s [%(levelname)s] %(name)s: %(message)s",
    )
    # persistent XLA compile cache (repo-local, gitignored): repeat CLI runs
    # of compile-heavy mains (DARTS/GDAS especially) skip recompilation
    from fedml_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    random.seed(args.seed)
    np.random.seed(args.seed)
    cfg = config_from_args(args)
    extra_load = {}
    if args.dataset == "mnist":
        # reference mnist feeds lr a flat 784 vector and CNN_DropOut 28x28
        # images (standalone main_fedavg.py:318-325) — flatten by model
        extra_load["flatten"] = args.model in ("lr", "mlp")
    ds = load_dataset(
        args.dataset,
        data_dir=args.data_dir,
        client_num_in_total=args.client_num_in_total,
        partition_method=args.partition_method,
        partition_alpha=args.partition_alpha,
        seed=args.seed,
        **extra_load,
    )
    model_kwargs = {"dtype": cfg.dtype}
    if args.dataset in ("shakespeare", "fed_shakespeare"):
        model_kwargs["vocab_size"] = 90
        model_kwargs["per_position"] = args.dataset == "fed_shakespeare"
    # dataset-contextual "cnn" dispatch, exactly the reference's
    # (standalone main_fedavg.py:315-340: cnn+har -> HAR_CNN,
    # cnn+cifar10 -> CNNCifar, cnn+mnist-family/femnist -> CNN_DropOut) —
    # the examples/baseline scripts rely on it
    model_name = args.model
    if model_name == "cnn":
        if args.dataset in ("har", "har_subject"):
            model_name = "har_cnn"
        elif args.dataset == "cifar10":
            model_name = "cnn_cifar"
    module = create_model(model_name, output_dim=ds.class_num, **model_kwargs)
    # task trainer by dataset (reference FedAvgAPI.py:33-39)
    if ds.meta.get("task") == "nwp" or args.dataset in ("fed_shakespeare", "stackoverflow_nwp"):
        trainer = NWPTrainer(module, pad_id=0)
    elif ds.meta.get("task") == "tag_prediction" or args.dataset == "stackoverflow_lr":
        trainer = TagPredictionTrainer(module)
    else:
        trainer = ClassificationTrainer(module)
    # federated LoRA: wrap AFTER task-trainer construction so the adapter
    # seam is task-agnostic; --lora_rank 0 returns the trainer unchanged
    from fedml_tpu.models.lora import maybe_wrap_lora

    trainer = maybe_wrap_lora(trainer, cfg)
    return cfg, ds, trainer
