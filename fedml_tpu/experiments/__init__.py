"""Experiment launchers (L4) — argparse mains mirroring the reference's
fedml_experiments/ entry points, driving the TPU-native algorithm APIs."""
