"""SplitNN experiment main (reference fedml_experiments/distributed/split_nn/
main_split_nn.py: round-robin split learning over a client pool).

Usage:
  python -m fedml_tpu.experiments.main_split_nn --dataset cifar10 \
      --client_num_in_total 4 --comm_round 5 --epochs 1 --batch_size 32
"""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.splitnn import SplitLowerCNN, SplitNNAPI, SplitUpperCNN
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    parser.add_argument("--split_width", type=int, default=16)
    args = parser.parse_args(argv)
    cfg, ds, _trainer = setup_run(args)
    lower = SplitLowerCNN(width=args.split_width)
    upper = SplitUpperCNN(output_dim=ds.class_num)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = SplitNNAPI(ds, cfg, lower, upper)
    history = api.train()
    final = api.evaluate()
    for r, rec in enumerate(history):
        logger.log({k: v for k, v in rec.items() if k != "round"}, step=r)
    logger.log(final, step=len(history))
    logger.finish()
    return history


if __name__ == "__main__":
    main()
