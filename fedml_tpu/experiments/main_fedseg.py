"""FedSeg experiment main (reference fedml_api/distributed/fedseg consumed
via its API; the fork ships no launcher — this one mirrors the FedAvg main
flags plus the segmentation extras from fedseg/utils.py).

Usage:
  python -m fedml_tpu.experiments.main_fedseg --dataset pascal_voc \
      --model deeplab --client_num_in_total 4 --comm_round 3 --loss_type ce
"""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.fedseg import FedSegAPI, SegmentationTrainer
from fedml_tpu.experiments.common import add_args, config_from_args
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    parser.add_argument("--loss_type", type=str, default="ce",
                        choices=["ce", "focal"])
    parser.add_argument("--image_size", type=int, default=32)
    parser.add_argument("--model_width", type=int, default=16)
    parser.set_defaults(dataset="pascal_voc", model="deeplab",
                        partition_method="homo", client_num_in_total=4,
                        client_num_per_round=4)
    args = parser.parse_args(argv)

    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    cfg = config_from_args(args)
    ds = load_dataset(args.dataset, data_dir=args.data_dir,
                      client_num_in_total=args.client_num_in_total,
                      partition_method=args.partition_method,
                      partition_alpha=args.partition_alpha,
                      image_size=args.image_size, seed=args.seed)
    module = create_model(args.model, output_dim=ds.class_num,
                          width=args.model_width)
    trainer = SegmentationTrainer(module, loss_type=args.loss_type)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = FedSegAPI(ds, cfg, trainer)
    history = api.train(metrics_logger=logger)
    logger.finish()
    return history


if __name__ == "__main__":
    main()
