"""FedAvg experiment main (reference fedml_experiments/distributed/fedavg/
main_fedavg.py:262-328 — the north-star entry). Subsumes the standalone main
(standalone/fedavg/main_fedavg.py:216-366): backend=vmap is the standalone
simulator, backend=shard_map is the distributed deployment on a mesh.

Usage:
  python -m fedml_tpu.experiments.main_fedavg --dataset mnist --model lr \
      --client_num_in_total 1000 --client_num_per_round 10 --comm_round 100
"""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.experiments.common import (
    add_args,
    bank_from_args,
    ledger_from_args,
    robustness_from_args,
    setup_run,
    tracer_from_args,
)
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None, aggregator_name: str = "fedavg", extra_args=None):
    parser = add_args(argparse.ArgumentParser())
    if extra_args:
        extra_args(parser)
    args = parser.parse_args(argv)
    cfg, ds, trainer = setup_run(args)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = FedAvgAPI(ds, cfg, trainer, aggregator_name=aggregator_name)
    chaos, guard = robustness_from_args(args)
    tracer = tracer_from_args(args, metrics_logger=logger)
    ledger = ledger_from_args(args, ds.client_num)
    bank = bank_from_args(args, ds.client_num, api)
    try:
        history = api.train(ckpt_dir=args.ckpt_dir, metrics_logger=logger,
                            chaos=chaos, guard=guard, tracer=tracer,
                            ledger=ledger, bank=bank)
    finally:
        tracer.close()
        if ledger is not None:
            ledger.close()
        if bank is not None:
            bank.close()
    logger.finish()
    if getattr(args, "trace_summary", 0):
        print(tracer.summary_table(), flush=True)
    return history


if __name__ == "__main__":
    main()
