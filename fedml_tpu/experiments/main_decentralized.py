"""Decentralized online-learning experiment main (reference
fedml_experiments/standalone/decentralized/ — DSGD / push-sum over ring
topologies on streaming data)."""

from __future__ import annotations

import argparse

import numpy as np

from fedml_tpu.algorithms.decentralized import DecentralizedFLAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.topology import (
    AsymmetricTopologyManager,
    SymmetricTopologyManager,
)
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.models.registry import create_model
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--client_number", type=int, default=8)
    parser.add_argument("--iterations", type=int, default=100)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--neighbor_num", type=int, default=4)
    parser.add_argument("--mode", type=str, default="dsgd", choices=["dsgd", "pushsum"])
    parser.add_argument("--b_symmetric", type=int, default=1)
    parser.add_argument("--dim", type=int, default=20)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run_dir", type=str, default="./wandb/latest-run/files")
    args = parser.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    w = rng.normal(size=(args.dim, 2)).astype(np.float32)
    x = rng.normal(size=(args.client_number, args.iterations, args.dim)).astype(np.float32)
    y = np.argmax(x @ w, axis=-1).astype(np.int32)

    cfg = FedConfig(lr=args.lr, seed=args.seed)
    if args.b_symmetric:
        topo = SymmetricTopologyManager(args.client_number, args.neighbor_num)
    else:
        topo = AsymmetricTopologyManager(args.client_number, args.neighbor_num,
                                         args.neighbor_num, np.random.RandomState(args.seed))
    trainer = ClassificationTrainer(create_model("lr", output_dim=2))
    api = DecentralizedFLAPI(trainer, cfg, topo, push_sum=(args.mode == "pushsum"))
    api.run(x, y)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    logger.log({"regret": api.regret(), "final_loss": api.loss_history[-1]})
    logger.finish()
    return api.loss_history


if __name__ == "__main__":
    main()
