"""FedGKT experiment main (reference fedml_experiments/distributed/fedgkt/
main_fedgkt.py: edge CNN + server ResNet group knowledge transfer).

Usage:
  python -m fedml_tpu.experiments.main_fedgkt --dataset cifar10 \
      --client_num_in_total 8 --comm_round 10 --epochs 1 --epochs_server 2
"""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.fedgkt import FedGKTAPI
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.models.resnet_gkt import GKTClientResNet, GKTServerResNet
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    # reference main_fedgkt flags (--epochs_server, --temperature, --alpha)
    parser.add_argument("--epochs_server", type=int, default=2)
    parser.add_argument("--temperature", type=float, default=3.0)
    parser.add_argument("--alpha", type=float, default=1.0)
    parser.add_argument("--client_blocks", type=int, default=1)
    parser.add_argument("--server_blocks", type=int, nargs=3, default=None)
    parser.add_argument("--client_sample_cap", type=int, default=None,
                        help="truncate each client's local data to N samples "
                             "(quick experiments / CI; GKT trains the FULL "
                             "federation every round, so work scales with "
                             "total samples, not clients-per-round)")
    args = parser.parse_args(argv)
    cfg, ds, _trainer = setup_run(args)
    if args.client_sample_cap:
        import dataclasses

        import numpy as np

        from fedml_tpu.data.packing import PackedClients

        cap = args.client_sample_cap
        ds = dataclasses.replace(
            ds,
            # graft-lint: disable=full-store-materialize -- GKT runs on eager CIFAR-scale PackedClients (all clients train every cycle); the cap re-pack is an intended one-shot whole-array copy
            train=PackedClients(ds.train.x[:, :cap], ds.train.y[:, :cap],
                                np.minimum(ds.train.counts, cap)),
            test_global=(ds.test_global[0][:512], ds.test_global[1][:512]),
        )
    client = GKTClientResNet(output_dim=ds.class_num, num_blocks=args.client_blocks)
    server_kw = {"output_dim": ds.class_num}
    if args.server_blocks:
        server_kw["layers"] = tuple(args.server_blocks)
    server = GKTServerResNet(**server_kw)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = FedGKTAPI(ds, cfg, client, server, alpha=args.alpha,
                    temperature=args.temperature, server_epochs=args.epochs_server)
    history = api.train(ckpt_dir=args.ckpt_dir)
    final = api.evaluate()
    for r, rec in enumerate(history):
        logger.log({k: v for k, v in rec.items() if k != "round"}, step=r)
    logger.log(final, step=len(history))
    logger.finish()
    return history


if __name__ == "__main__":
    main()
