"""Classical vertical FL experiment main (reference fedml_experiments/
distributed/classical_vertical_fl/main_vfl.py: guest + hosts hold disjoint
feature columns of the same rows; lending_club / NUS-WIDE style data).

Usage:
  python -m fedml_tpu.experiments.main_vfl --dataset adult --party_num 3 \
      --epochs 5 --batch_size 64 --lr 0.05
"""

from __future__ import annotations

import argparse

import numpy as np

from fedml_tpu.algorithms.vfl import VerticalFederatedLearningAPI
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset", type=str, default="adult")
    parser.add_argument("--data_dir", type=str, default="./data")
    parser.add_argument("--party_num", type=int, default=3)
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run_dir", type=str, default="./wandb/latest-run/files")
    args = parser.parse_args(argv)

    ds = load_dataset(args.dataset, data_dir=args.data_dir,
                      client_num_in_total=2, seed=args.seed)
    Xtr, ytr = ds.train_global
    Xte, yte = ds.test_global
    Xtr = Xtr.reshape(len(Xtr), -1)
    Xte = Xte.reshape(len(Xte), -1)
    ytr = (np.asarray(ytr) > 0).astype(np.int32)  # binary guest label
    yte = (np.asarray(yte) > 0).astype(np.int32)
    # vertical split: party k owns a contiguous feature slice (reference
    # vfl_fixture splits the design matrix across guest + hosts)
    splits = [np.asarray(c) for c in np.array_split(np.arange(Xtr.shape[1]),
                                                    args.party_num)]
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = VerticalFederatedLearningAPI(splits, lr=args.lr, seed=args.seed)
    api.fit(Xtr, ytr, epochs=args.epochs, batch_size=args.batch_size,
            seed=args.seed)
    out = {"Train/Acc": api.score(Xtr, ytr), "Test/Acc": api.score(Xte, yte),
           "Train/Loss": api.loss_history[-1] if api.loss_history else float("nan")}
    logger.log(out, step=args.epochs)
    logger.finish()
    print(out)
    return out


if __name__ == "__main__":
    main()
