"""Classical vertical FL experiment main (reference fedml_experiments/
distributed/classical_vertical_fl/main_vfl.py: guest + hosts hold disjoint
feature columns of the same rows; lending_club / NUS-WIDE style data).

Usage:
  python -m fedml_tpu.experiments.main_vfl --dataset adult --party_num 3 \
      --epochs 5 --batch_size 64 --lr 0.05
"""

from __future__ import annotations

import argparse

import numpy as np

from fedml_tpu.algorithms.vfl import VerticalFederatedLearningAPI
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset", type=str, default="adult",
                        help="9-tuple datasets are column-split across "
                             "--party_num parties; 'nus_wide' / "
                             "'lending_club' are natively party-split")
    parser.add_argument("--data_dir", type=str, default="./data")
    parser.add_argument("--party_num", type=int, default=3)
    parser.add_argument("--model", type=str, default="lr",
                        choices=["lr", "dense"],
                        help="lr = classical linear parties; dense = the "
                             "reference's LocalModel+DenseModel neural stack")
    parser.add_argument("--hidden_dim", type=int, default=32)
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run_dir", type=str, default="./wandb/latest-run/files")
    args = parser.parse_args(argv)

    if args.dataset in ("nus_wide", "lending_club"):
        from fedml_tpu.data.loaders import load_vfl_parties

        ptr, ytr, pte, yte = load_vfl_parties(
            args.dataset, data_dir=args.data_dir, seed=args.seed,
            three_party=args.party_num >= 3)
        parties_tr, parties_te = list(ptr), list(pte)
        if len(parties_tr) != args.party_num:
            # these datasets fix the party structure (nus_wide: 2 or 3,
            # lending_club: 2) — record what actually ran
            import logging

            logging.getLogger(__name__).warning(
                "%s provides %d parties; requested --party_num %d ignored",
                args.dataset, len(parties_tr), args.party_num)
            args.party_num = len(parties_tr)
    else:
        ds = load_dataset(args.dataset, data_dir=args.data_dir,
                          client_num_in_total=2, seed=args.seed)
        Xtr, ytr = ds.train_global
        Xte, yte = ds.test_global
        Xtr = Xtr.reshape(len(Xtr), -1)
        Xte = Xte.reshape(len(Xte), -1)
        ytr = (np.asarray(ytr) > 0).astype(np.int32)  # binary guest label
        yte = (np.asarray(yte) > 0).astype(np.int32)
        # vertical split: party k owns a contiguous feature slice (reference
        # vfl_fixture splits the design matrix across guest + hosts)
        cols = np.array_split(np.arange(Xtr.shape[1]), args.party_num)
        parties_tr = [Xtr[:, c] for c in cols]
        parties_te = [Xte[:, c] for c in cols]

    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    if args.model == "dense":
        from fedml_tpu.algorithms.vfl import NeuralVFLAPI

        api = NeuralVFLAPI([x.shape[1] for x in parties_tr],
                           hidden_dim=args.hidden_dim, lr=args.lr,
                           seed=args.seed)
        api.fit(parties_tr, ytr, epochs=args.epochs,
                batch_size=args.batch_size, seed=args.seed)
        out = {"Train/Acc": api.score(parties_tr, ytr),
               "Test/Acc": api.score(parties_te, yte)}
    else:
        Xtr = np.concatenate(parties_tr, axis=1)
        Xte = np.concatenate(parties_te, axis=1)
        offs = np.cumsum([0] + [x.shape[1] for x in parties_tr])
        splits = [np.arange(offs[i], offs[i + 1]) for i in range(len(parties_tr))]
        api = VerticalFederatedLearningAPI(splits, lr=args.lr, seed=args.seed)
        api.fit(Xtr, ytr, epochs=args.epochs, batch_size=args.batch_size,
                seed=args.seed)
        out = {"Train/Acc": api.score(Xtr, ytr), "Test/Acc": api.score(Xte, yte)}
    out["Train/Loss"] = api.loss_history[-1] if api.loss_history else float("nan")
    logger.log(out, step=args.epochs)
    logger.finish()
    print(out)
    return out


if __name__ == "__main__":
    main()
