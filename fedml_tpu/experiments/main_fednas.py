"""FedNAS experiment main (reference fedml_experiments/distributed/fednas/)."""

from __future__ import annotations

import argparse

from fedml_tpu.algorithms.fednas import FedNASAPI
from fedml_tpu.experiments.common import add_args, setup_run
from fedml_tpu.utils.logging import MetricsLogger


def main(argv=None):
    parser = add_args(argparse.ArgumentParser())
    parser.add_argument("--init_channels", type=int, default=8)
    parser.add_argument("--layers", type=int, default=4)
    # cell size (reference model_search.py Network(steps, multiplier));
    # steps 2 / multiplier 2 gives a genuinely tiny CI-smokeable search net
    parser.add_argument("--steps", type=int, default=4)
    parser.add_argument("--multiplier", type=int, default=4)
    parser.add_argument("--arch_lr", type=float, default=3e-4)
    parser.add_argument("--unrolled", type=int, default=0)
    # GDAS variant (reference model_search_gdas.py): hard gumbel-softmax
    # architecture sampling with temperature tau
    parser.add_argument("--gdas", type=int, default=0)
    parser.add_argument("--tau", type=float, default=5.0)
    args = parser.parse_args(argv)
    cfg, ds, _ = setup_run(args)
    logger = MetricsLogger(run_dir=args.run_dir, config=vars(args))
    api = FedNASAPI(ds, cfg, channels=args.init_channels, layers=args.layers,
                    arch_lr=args.arch_lr, unrolled=bool(args.unrolled),
                    gdas=bool(args.gdas), tau=args.tau, steps=args.steps,
                    multiplier=args.multiplier)
    history = api.train(ckpt_dir=args.ckpt_dir)
    for rec in history:
        logger.log({"search_loss": rec["search_loss"],
                    "search_acc": rec["search_acc"]}, step=rec["round"])
    # reference records the genotype each round (FedNASAggregator.py:173)
    logger.log({"genotype": str(api.genotype_history[-1])})
    logger.finish()
    return history


if __name__ == "__main__":
    main()
