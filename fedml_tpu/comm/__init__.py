"""Mobile/IoT control-plane transport (the reference's MQTT path)."""

from fedml_tpu.comm.message import Message  # noqa: F401
from fedml_tpu.comm.mqtt import MiniBroker, MqttClient, MqttCommManager  # noqa: F401
from fedml_tpu.comm.mqtt_fedavg import (  # noqa: F401
    MqttFedAvgClientManager,
    MqttFedAvgServerManager,
    MyMessage,
    run_mqtt_fedavg,
)
