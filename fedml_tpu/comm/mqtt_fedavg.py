"""FedAvg riding the MQTT mobile transport, end to end.

The reference's mobile deployment runs the full algorithm through the broker:
FedAvgServerManager broadcasts init/sync messages, FedAvgClientManager
trains on each sync and publishes its model back, with tensors list-encoded
in JSON when is_mobile (reference FedAvgServerManager.py:63-127,
FedAvgClientManager.py:127-167, mqtt_comm_manager.py:14-125). This module is
that deployment mode for the TPU rebuild: message-driven actor shells around
the jitted local-SGD step — the wire protocol is the reference's, the compute
inside each actor is the engine's.

Worker-pool semantics are preserved: `worker_num` actor processes impersonate
`client_num_per_round` logical clients; each round the server samples logical
indices with np.random.seed(round_idx) + choice (reference
FedAVGAggregator.client_sampling:89-97) and tells worker i which client to be
(MSG_ARG_KEY_CLIENT_INDEX, string-encoded like the reference).
"""

from __future__ import annotations

import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.engine import build_local_update
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.mqtt import MiniBroker, MqttCommManager
from fedml_tpu.core.config import FedConfig
from fedml_tpu.data.registry import FederatedDataset

log = logging.getLogger(__name__)


class MyMessage:
    """Reference message_define.py values, verbatim."""

    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
    MSG_TYPE_C2S_SEND_STATS_TO_SERVER = 4

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    # fault-tolerance extension (absent from the reference's message_define;
    # messages without it are handled with the legacy counters, so the
    # reference wire-format interop is unchanged): stamping the round makes
    # sync/reply handling idempotent under resends — a duplicated sync
    # retrains deterministically (rng is derived from the round index, not
    # from how many messages the worker has seen) and a stale reply from a
    # finished round is dropped instead of polluting the current aggregate
    MSG_ARG_KEY_ROUND_IDX = "round_idx"


def _client_sampling(round_idx: int, total: int, per_round: int) -> list[int]:
    """Reference client_sampling (FedAVGAggregator.py:89-97) exactly."""
    if total == per_round:
        return list(range(total))
    np.random.seed(round_idx)
    return list(np.random.choice(range(total), min(per_round, total), replace=False))


class MqttFedAvgServerManager:
    """Rank-0 actor: receive models -> aggregate -> eval -> resample -> sync.

    Mirrors FedAvgServerManager.handle_message_receive_model_from_client
    (FedAvgServerManager.py:74-112); aggregation is the sample-weighted
    state-dict mean of FedAVGAggregator.aggregate:58-87 over decoded pytrees.
    """

    def __init__(self, host: str, port: int, worker_num: int,
                 global_variables, cfg: FedConfig, trainer=None,
                 test_global=None, topic: str = "fedml",
                 resend_interval: float | None = None):
        self.cfg = cfg
        self.worker_num = worker_num
        self.global_variables = global_variables
        self.round_idx = 0
        self.history: list[dict] = []
        self.done = threading.Event()
        self._lock = threading.Lock()
        self._model_dict: dict[int, object] = {}
        self._sample_num_dict: dict[int, float] = {}
        # self-healing: the current round's worker->client assignment so the
        # resend loop can re-sync stragglers whose sync/reply got lost when
        # the broker died mid-exchange (round_idx stamping makes it safe)
        self._assignment: dict[int, int] = {}
        self._resend_type: int | None = None
        self._resend_interval = resend_interval
        if resend_interval is not None:
            threading.Thread(target=self._resend_loop, daemon=True).start()
        if trainer is not None and test_global is not None:
            x, y = test_global
            self._test = (jnp.asarray(x), jnp.asarray(y))
            self._eval = jax.jit(
                lambda v, x, y: trainer.eval_fn(
                    v, {"x": x, "y": y, "mask": jnp.ones(x.shape[0])}
                )
            )
        else:
            self._eval = None
        self.comm = MqttCommManager(host, port, topic=topic, client_id=0,
                                    client_num=worker_num)
        self.comm.add_observer(self._dispatch)

    def _dispatch(self, msg_type, msg: Message):
        if msg_type == MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER:
            self._handle_model(msg)

    def send_init_msg(self):
        with self._lock:
            ridx = self.round_idx
        idx = _client_sampling(
            ridx, self.cfg.client_num_in_total, self.worker_num
        )
        with self._lock:
            self._assignment = {w: idx[w - 1]
                                for w in range(1, self.worker_num + 1)}
            self._resend_type = MyMessage.MSG_TYPE_S2C_INIT_CONFIG
        for worker in range(1, self.worker_num + 1):
            self._send_model(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, worker,
                             idx[worker - 1])

    def _send_model(self, msg_type: int, worker: int, client_index: int,
                    round_idx: int | None = None):
        if round_idx is None:
            with self._lock:
                round_idx = self.round_idx
        m = Message(msg_type, 0, worker)
        m.add_model_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self.global_variables)
        m.add(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, str(client_index))
        m.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, str(round_idx))
        self.comm.send_message(m)

    def _resend_loop(self):
        """Periodically re-sync workers the current round is still waiting on.

        Lost frames are the failure mode of a broker kill/restart: the comm
        layer reconnects and resubscribes, but anything in flight during the
        outage is gone and the round wedges. Duplicates are harmless — the
        worker retrains deterministically from the stamped round_idx and the
        server keys replies by sender, so a re-reply just overwrites with the
        identical model.
        """
        while not self.done.wait(self._resend_interval):
            with self._lock:
                if self._resend_type is None:
                    continue
                pending = [(w, c) for w, c in self._assignment.items()
                           if w not in self._model_dict]
                msg_type = self._resend_type
                # capture the round under the lock: if the round advances
                # after release, these frames carry the old stamp and the
                # workers' re-replies get dropped as stale, not aggregated
                ridx = self.round_idx
            for worker, client_index in pending:
                try:
                    self._send_model(msg_type, worker, client_index,
                                     round_idx=ridx)
                except OSError:  # broker mid-restart; next tick retries
                    break

    def _handle_model(self, msg: Message):
        sender = msg.get_sender_id()
        raw_ridx = msg.get_params().get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
        # this dispatch thread is the only round_idx WRITER, so the locked
        # snapshot stays current for the whole handler
        with self._lock:
            current_round = self.round_idx
        if raw_ridx is not None and int(raw_ridx) != current_round:
            log.info("dropping stale round-%s reply from worker %d "
                     "(current round %d)", raw_ridx, sender, current_round)
            return
        variables = Message.decode_model_params(
            msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS), self.global_variables
        )
        with self._lock:
            self._model_dict[sender] = variables
            self._sample_num_dict[sender] = float(
                msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
            )
            if len(self._model_dict) < self.worker_num:
                return
            models = [self._model_dict[i] for i in sorted(self._model_dict)]
            nums = np.array(
                [self._sample_num_dict[i] for i in sorted(self._model_dict)]
            )
            self._model_dict.clear()
            self._sample_num_dict.clear()
            self._resend_type = None  # round complete; pause resends
        w = nums / nums.sum()
        self.global_variables = jax.tree.map(
            lambda *leaves: sum(
                wi * np.asarray(l) for wi, l in zip(w, leaves)
            ).astype(np.asarray(leaves[0]).dtype),
            *models,
        )
        record = {"round": current_round}
        if self._eval is not None:
            m = self._eval(self.global_variables, *self._test)
            total = float(m["test_total"])
            record["test_loss"] = float(m["test_loss"]) / max(total, 1.0)
            record["test_acc"] = float(m["test_correct"]) / max(total, 1.0)
        self.history.append(record)
        log.info("mqtt round %d done: %s", current_round, record)

        # advance under the lock: the resend loop snapshots round_idx there,
        # and an unlocked increment could let it stamp a half-advanced round
        with self._lock:
            self.round_idx += 1
            current_round = self.round_idx
        if current_round == self.cfg.comm_round:
            self.done.set()
            return
        idx = _client_sampling(
            current_round, self.cfg.client_num_in_total, self.worker_num
        )
        with self._lock:
            self._assignment = {w: idx[w - 1]
                                for w in range(1, self.worker_num + 1)}
            self._resend_type = MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT
        for worker in range(1, self.worker_num + 1):
            self._send_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                             worker, idx[worker - 1])

    def stop(self):
        self.comm.stop()


class MqttFedAvgClientManager:
    """Worker actor: on init/sync decode the global model, impersonate the
    assigned logical client, run the jitted local-SGD update, publish the
    trained model + sample count (FedAvgClientManager.py:127-167; the
    is_mobile list encoding is Message.add_model_params)."""

    def __init__(self, host: str, port: int, worker_id: int,
                 dataset: FederatedDataset, trainer, cfg: FedConfig,
                 example_variables, topic: str = "fedml",
                 local_update=None):
        self.worker_id = worker_id
        self.cfg = cfg
        self.dataset = dataset
        self.example_variables = example_variables
        self.rounds_trained = 0
        self.finished = threading.Event()
        # workers in one process share a jitted local_update (pass it in) so
        # the XLA program compiles once, not once per worker
        self._local_update = (
            jax.jit(build_local_update(trainer, cfg))
            if local_update is None else local_update
        )
        self.comm = MqttCommManager(host, port, topic=topic, client_id=worker_id)
        self.comm.add_observer(self._dispatch)

    def _dispatch(self, msg_type, msg: Message):
        if msg_type in (MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
                        MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
            self._train_and_reply(msg)

    def _train_and_reply(self, msg: Message):
        variables = Message.decode_model_params(
            msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS), self.example_variables
        )
        client_index = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        # round_idx stamp (absent from reference-format messages -> fall back
        # to the legacy local counter, which equals the stamp when no frames
        # were lost, so the rng stream is bit-identical): deriving the rng
        # from the ROUND rather than from how many syncs this worker has seen
        # makes a resent sync retrain to the exact same model
        raw_ridx = msg.get_params().get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
        ridx = self.rounds_trained if raw_ridx is None else int(raw_ridx)
        x = jnp.asarray(self.dataset.train.x[client_index])
        y = jnp.asarray(self.dataset.train.y[client_index])
        count = jnp.int32(self.dataset.train.counts[client_index])
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.cfg.seed), ridx * 1000 + self.worker_id
        )
        result = self._local_update(variables, x, y, count, rng)
        reply = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                        self.worker_id, 0)
        reply.add_model_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                               jax.device_get(result.variables))
        reply.add(MyMessage.MSG_ARG_KEY_NUM_SAMPLES,
                  int(self.dataset.train.counts[client_index]))
        reply.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, str(ridx))
        self.comm.send_message(reply)
        self.rounds_trained = max(self.rounds_trained, ridx + 1)
        if self.rounds_trained >= self.cfg.comm_round:
            self.finished.set()

    def stop(self):
        self.comm.stop()


def run_mqtt_fedavg(dataset: FederatedDataset, trainer, cfg: FedConfig,
                    host: str | None = None, port: int | None = None,
                    timeout: float = 300.0):
    """Single-host mobile simulation: broker + server + worker actors in one
    process (the analog of the reference CI's mpirun-on-localhost), full
    FedAvg over real MQTT frames. Returns (final_variables, history)."""
    worker_num = min(cfg.client_num_per_round, cfg.client_num_in_total)
    broker = MiniBroker() if host is None else None
    if broker is not None:
        host, port = broker.host, broker.port
    gv = trainer.init(jax.random.PRNGKey(cfg.seed),
                      jnp.asarray(dataset.train.x[0][:1]))
    server = MqttFedAvgServerManager(
        host, port, worker_num, jax.device_get(gv), cfg,
        trainer=trainer, test_global=dataset.test_global,
        resend_interval=2.0,
    )
    shared_update = jax.jit(build_local_update(trainer, cfg))
    clients = [
        MqttFedAvgClientManager(host, port, k, dataset, trainer, cfg, gv,
                                local_update=shared_update)
        for k in range(1, worker_num + 1)
    ]
    try:
        server.send_init_msg()
        if not server.done.wait(timeout):
            raise TimeoutError("mqtt fedavg did not finish in time")
        for c in clients:
            c.finished.wait(10.0)
    finally:
        for c in clients:
            c.stop()
        server.stop()
        if broker is not None:
            broker.close()
    return server.global_variables, server.history
