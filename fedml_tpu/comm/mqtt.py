"""Dependency-free MQTT 3.1.1 transport for the mobile/IoT deployment mode.

Behavior-parity rebuild of reference fedml_core/distributed/communication/
mqtt/mqtt_comm_manager.py:14-125 (paho-based): the server subscribes to one
topic per client and publishes to `<topic><server>_<client>`; each client
subscribes to its `<topic><server>_<client>` inbox and publishes to
`<topic><client>`; payloads are JSON Message envelopes. Improvements kept
from SURVEY §7's defect list: no hard-coded broker IP, clean disconnect
instead of thread-kill shutdown.

paho-mqtt is not in this image, so the codec is implemented directly:
MQTT 3.1.1 CONNECT/CONNACK/PUBLISH/SUBSCRIBE/SUBACK/PINGREQ/PINGRESP/
DISCONNECT at QoS 0 over a TCP socket. `MiniBroker` is an in-process
broker (thread per connection, topic -> subscriber routing) so the whole
path is testable with no external services — the analog of the reference
CI's mpirun-on-localhost trick.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Callable

from fedml_tpu import telemetry
from fedml_tpu.comm.message import Message
from fedml_tpu.robustness.retry import RetryError, RetryPolicy, call_with_retry

log = logging.getLogger(__name__)

# MQTT 3.1.1 control packet types
CONNECT, CONNACK = 0x10, 0x20
PUBLISH = 0x30
SUBSCRIBE, SUBACK = 0x82, 0x90
PINGREQ, PINGRESP = 0xC0, 0xD0
DISCONNECT = 0xE0


def _encode_len(n: int) -> bytes:
    out = b""
    while True:
        d, n = n % 128, n // 128
        out += bytes([d | (0x80 if n else 0)])
        if not n:
            return out


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> tuple[int, bytes]:
    head = _read_exact(sock, 1)[0]
    mult, length = 1, 0
    while True:
        b = _read_exact(sock, 1)[0]
        length += (b & 0x7F) * mult
        if not (b & 0x80):
            break
        mult *= 128
    return head, _read_exact(sock, length) if length else b""


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _connect_packet(client_id: str) -> bytes:
    var = _mqtt_str("MQTT") + bytes([4, 0x02]) + struct.pack(">H", 60)
    payload = _mqtt_str(client_id)
    body = var + payload
    return bytes([CONNECT]) + _encode_len(len(body)) + body


def _publish_packet(topic: str, payload: bytes) -> bytes:
    body = _mqtt_str(topic) + payload
    return bytes([PUBLISH]) + _encode_len(len(body)) + body


def _subscribe_packet(pid: int, topic: str) -> bytes:
    body = struct.pack(">H", pid) + _mqtt_str(topic) + bytes([0])
    return bytes([SUBSCRIBE]) + _encode_len(len(body)) + body


class MiniBroker:
    """In-process MQTT broker (QoS 0, exact-topic routing) for tests and
    single-host mobile simulations."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self.host, self.port = self._srv.getsockname()
        self._subs: dict[str, list[socket.socket]] = {}
        self._send_locks: dict[socket.socket, threading.Lock] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket):
        with self._lock:
            self._send_locks[conn] = threading.Lock()

        def send(sock: socket.socket, data: bytes):
            # serialize writers per socket: a multi-send PUBLISH fan-out from
            # another connection's thread must not interleave with this
            # connection's own SUBACK/PINGRESP bytes
            with self._lock:
                lock = self._send_locks.get(sock)
            if lock is None:
                raise OSError("peer gone")
            with lock:
                sock.sendall(data)

        try:
            head, _body = _read_packet(conn)
            if head & 0xF0 != CONNECT:
                conn.close()
                return
            send(conn, bytes([CONNACK, 2, 0, 0]))
            while True:
                head, body = _read_packet(conn)
                ptype = head & 0xF0
                if ptype == SUBSCRIBE & 0xF0:
                    pid = struct.unpack(">H", body[:2])[0]
                    tlen = struct.unpack(">H", body[2:4])[0]
                    topic = body[4:4 + tlen].decode()
                    with self._lock:
                        self._subs.setdefault(topic, []).append(conn)
                    send(conn, bytes([SUBACK, 3]) + struct.pack(">H", pid) + b"\x00")
                elif ptype == PUBLISH:
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode()
                    payload = body[2 + tlen:]
                    pkt = _publish_packet(topic, payload)
                    with self._lock:
                        targets = list(self._subs.get(topic, ()))
                    for t in targets:
                        try:
                            send(t, pkt)
                        except OSError:
                            pass
                elif ptype == PINGREQ:
                    send(conn, bytes([PINGRESP, 0]))
                elif ptype == DISCONNECT:
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                for subs in self._subs.values():
                    if conn in subs:
                        subs.remove(conn)
                self._send_locks.pop(conn, None)
            conn.close()

    def close(self):
        self._stop.set()
        self._srv.close()


class MqttClient:
    """Minimal MQTT 3.1.1 client: connect, subscribe(topic, cb), publish.

    paho-parity semantics the reference gets from its client library:
    a keepalive PINGREQ loop, and automatic reconnect + re-subscribe after
    a dropped connection (QoS-0: messages published while disconnected are
    lost, exactly as with paho at QoS 0)."""

    def __init__(self, host: str, port: int, client_id: str,
                 keepalive: float = 60.0, reconnect: bool = True,
                 reconnect_backoff: float = 0.2, reconnect_tries: int = 12,
                 reconnect_policy: RetryPolicy | None = None):
        self._addr = (host, port)
        self._client_id = client_id
        self._keepalive = keepalive
        self._reconnect = reconnect
        # robustness.retry owns the backoff; the legacy knobs map onto it.
        # No jitter here: with jitter every sleep can land near zero, so all
        # attempts may burn in under a second while the broker is still
        # restarting — and an exhausted reconnect kills the receive loop for
        # good. Deterministic backoff makes the give-up horizon a guarantee
        # (~2 min of patience at these defaults), and a per-process handful
        # of clients has no retry herd worth spreading.
        self._reconnect_policy = reconnect_policy or RetryPolicy(
            max_attempts=reconnect_tries, base_delay=reconnect_backoff,
            max_delay=30.0, jitter=False, retryable=(OSError,))
        self._cbs: dict[str, Callable[[str, bytes], None]] = {}
        self._pid = 0
        self._send_lock = threading.Lock()  # publish/subscribe from any thread
        # SUBACKs are matched to their SUBSCRIBE by packet id so concurrent
        # subscribers never return on each other's ack
        self._pending_subacks: dict[int, threading.Event] = {}
        self._stop = threading.Event()
        self._sock = self._connect()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self._ping_thread = threading.Thread(target=self._ping_loop, daemon=True)
        self._ping_thread.start()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr, timeout=30)
        sock.sendall(_connect_packet(self._client_id))
        head, body = _read_packet(sock)
        if head & 0xF0 != CONNACK or body[1] != 0:
            raise ConnectionError(f"MQTT CONNACK refused: {body!r}")
        return sock

    def _try_reconnect(self) -> bool:
        """Rebuild the connection and re-subscribe every topic (paho's
        on_connect-resubscribe pattern), with capped-exponential-backoff +
        full-jitter retries (robustness.retry — the shared policy also used
        by data downloads). Returns False when shut down or out of retries."""

        attempts = [1]  # first try + one per on_retry callback

        def reconnect_once():
            sock = self._connect()
            with self._send_lock:
                self._sock = sock
                for topic in list(self._cbs):
                    self._pid = (self._pid % 0xFFFF) + 1
                    sock.sendall(_subscribe_packet(self._pid, topic))

        def on_retry(attempt, exc, delay):
            attempts[0] = attempt + 2
            log.info("mqtt %s: reconnect attempt %d failed (%s), next in "
                     "%.2fs", self._client_id, attempt + 1, exc, delay)

        try:
            call_with_retry(
                reconnect_once,
                policy=self._reconnect_policy,
                abort=self._stop.is_set,
                on_retry=on_retry,
            )
        except (RetryError, OSError):
            telemetry.emit("mqtt_reconnect", client_id=self._client_id,
                           ok=False, attempts=attempts[0])
            return False
        with self._send_lock:
            n_topics = len(self._cbs)
        log.info("mqtt %s: reconnected and resubscribed %d topic(s)",
                 self._client_id, n_topics)
        telemetry.emit("mqtt_reconnect", client_id=self._client_id,
                       ok=True, attempts=attempts[0])
        return True

    def _loop(self):
        while not self._stop.is_set():
            try:
                # snapshot the socket ref under the lock (reconnect rebinds
                # it there) but read packets with the lock RELEASED — a
                # blocking read under the send lock would starve publishers
                with self._send_lock:
                    sock = self._sock
                head, body = _read_packet(sock)
            except (ConnectionError, OSError):
                if self._stop.is_set() or not self._reconnect:
                    return
                if not self._try_reconnect():
                    return
                continue
            ptype = head & 0xF0
            if ptype == PUBLISH:
                tlen = struct.unpack(">H", body[:2])[0]
                topic = body[2:2 + tlen].decode()
                with self._send_lock:
                    cb = self._cbs.get(topic)
                if cb is not None:
                    try:
                        cb(topic, body[2 + tlen:])
                    except Exception:
                        # a handler that publishes onto a just-severed socket
                        # raises OSError here; letting it kill the receive
                        # loop would permanently deafen the client — log and
                        # keep receiving (reconnect + the server's resend
                        # loop recover the lost exchange)
                        log.exception("mqtt %s: subscriber callback failed "
                                      "for topic %s", self._client_id, topic)
            elif ptype == SUBACK & 0xF0:
                pid = struct.unpack(">H", body[:2])[0]
                with self._send_lock:
                    ev = self._pending_subacks.pop(pid, None)
                if ev is not None:
                    ev.set()

    def _ping_loop(self):
        """PINGREQ every keepalive/2 so the broker (and any NAT between)
        keeps the connection alive — paho's keepalive loop."""
        while not self._stop.wait(self._keepalive / 2):
            try:
                with self._send_lock:
                    self._sock.sendall(bytes([PINGREQ, 0]))
            except OSError:
                pass  # the receive loop owns reconnection

    def subscribe(self, topic: str, callback: Callable[[str, bytes], None],
                  timeout: float = 10.0):
        ev = threading.Event()
        with self._send_lock:
            self._cbs[topic] = callback
            self._pid = (self._pid % 0xFFFF) + 1
            pid = self._pid
            self._pending_subacks[pid] = ev
            self._sock.sendall(_subscribe_packet(pid, topic))
        if not ev.wait(timeout):
            with self._send_lock:
                self._pending_subacks.pop(pid, None)
            raise TimeoutError(f"no SUBACK for {topic!r}")

    def publish(self, topic: str, payload: bytes):
        with self._send_lock:
            self._sock.sendall(_publish_packet(topic, payload))

    def disconnect(self):
        self._stop.set()
        try:
            with self._send_lock:
                self._sock.sendall(bytes([DISCONNECT, 0]))
                self._sock.close()
        except OSError:
            pass


class MqttCommManager:
    """Reference MqttCommManager surface (mqtt_comm_manager.py:14-125):
    server (client_id 0) subscribes to every client's topic and sends to
    `<topic><server>_<client>`; clients subscribe to their inbox and send
    to `<topic><client>`. Observers receive decoded Message envelopes."""

    def __init__(self, host: str, port: int, topic: str = "fedml",
                 client_id: int = 0, client_num: int = 0):
        self._topic = topic
        self.client_id = client_id
        self.client_num = client_num
        self._observers: list[Callable[[int, Message], None]] = []
        self._client = MqttClient(host, port, f"{topic}_{client_id}")
        if client_id == 0:  # server: one inbox per client
            for cid in range(1, client_num + 1):
                self._client.subscribe(f"{topic}{cid}", self._on_payload)
        else:
            self._client.subscribe(f"{topic}0_{client_id}", self._on_payload)

    def add_observer(self, fn: Callable[[int, Message], None]):
        self._observers.append(fn)

    def _on_payload(self, _topic: str, payload: bytes):
        msg = Message.from_json(payload)
        for fn in self._observers:
            fn(msg.get_type(), msg)

    def send_message(self, msg: Message):
        receiver = msg.get_receiver_id()
        if self.client_id == 0:
            topic = f"{self._topic}0_{receiver}"
        else:
            topic = f"{self._topic}{self.client_id}"
        self._client.publish(topic, msg.to_json().encode())

    def stop(self):
        self._client.disconnect()
