"""Typed message envelope for the mobile transport.

The core TPU path has no message envelopes — rounds are jitted functions
(algorithms/engine.py) — but the mobile/IoT deployment mode keeps the
reference's wire contract (reference fedml_core/distributed/communication/
message.py:5-74): a msg_type + sender + receiver header with arbitrary
JSON-serializable params, arrays encoded as nested lists exactly like the
reference's `transform_tensor_to_list` (fedavg/utils.py:118) for
`is_mobile` payloads.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"


class Message:
    def __init__(self, msg_type: int | str = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender_id,
            MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # reference surface (message.py:23-58)
    def add_params(self, key: str, value: Any):
        self.msg_params[key] = value

    def get_params(self) -> dict[str, Any]:
        return self.msg_params

    def add(self, key: str, value: Any):
        self.msg_params[key] = value

    def get(self, key: str) -> Any:
        return self.msg_params[key]

    def get_type(self):
        return self.msg_params[MSG_ARG_KEY_TYPE]

    def get_sender_id(self):
        return self.msg_params[MSG_ARG_KEY_SENDER]

    def get_receiver_id(self):
        return self.msg_params[MSG_ARG_KEY_RECEIVER]

    def add_model_params(self, key: str, tree: Any):
        """Arrays -> nested lists (the reference's mobile JSON encoding)."""
        import jax

        leaves, treedef = jax.tree.flatten(tree)
        self.msg_params[key] = {
            "leaves": [np.asarray(l).tolist() for l in leaves],
            "treedef": str(treedef),
        }

    @staticmethod
    def decode_model_params(payload: dict, example_tree: Any) -> Any:
        """Nested lists -> pytree with example_tree's structure/dtypes."""
        import jax

        leaves = [np.asarray(l, dtype=np.asarray(e).dtype)
                  for l, e in zip(payload["leaves"], jax.tree.leaves(example_tree))]
        return jax.tree.unflatten(jax.tree.structure(example_tree), leaves)

    def to_json(self) -> str:
        return json.dumps(self.msg_params)

    @classmethod
    def from_json(cls, s: str | bytes) -> "Message":
        m = cls()
        m.msg_params = json.loads(s)
        return m
