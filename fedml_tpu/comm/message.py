"""Typed message envelope for the mobile transport.

The core TPU path has no message envelopes — rounds are jitted functions
(algorithms/engine.py) — but the mobile/IoT deployment mode keeps the
reference's wire contract (reference fedml_core/distributed/communication/
message.py:5-74): a msg_type + sender + receiver header with arbitrary
JSON-serializable params, model params as a flat {name: nested lists} dict
exactly like the reference's `transform_tensor_to_list` (fedavg/utils.py:
11-14) for `is_mobile` payloads.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"


def _named_leaves(tree: Any) -> list[tuple[str, Any]]:
    """Deterministic (dotted-path-name, leaf) pairs — the pytree analog of
    torch state_dict keys ('params.linear.kernel' ≙ 'linear.weight')."""
    import jax

    def name(path):
        parts = []
        for p in path:
            for attr in ("key", "idx", "name"):
                if hasattr(p, attr):
                    parts.append(str(getattr(p, attr)))
                    break
            else:
                parts.append(str(p))
        return ".".join(parts)

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    named = [(name(path), leaf) for path, leaf in flat]
    if len({n for n, _ in named}) != len(named):
        # e.g. a dict key containing '.' colliding with a nested path — the
        # flat wire dict would silently drop a leaf and decode would
        # duplicate another; fail loudly instead
        dupes = sorted({n for i, (n, _) in enumerate(named)
                        if any(m == n for m, _ in named[:i])})
        raise ValueError(
            f"pytree paths collide under dotted naming: {dupes}; rename the "
            "colliding keys to use the mobile wire format")
    return named


class Message:
    def __init__(self, msg_type: int | str = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender_id,
            MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # reference surface (message.py:23-58)
    def add_params(self, key: str, value: Any):
        self.msg_params[key] = value

    def get_params(self) -> dict[str, Any]:
        return self.msg_params

    def add(self, key: str, value: Any):
        self.msg_params[key] = value

    def get(self, key: str) -> Any:
        return self.msg_params[key]

    def get_type(self):
        return self.msg_params[MSG_ARG_KEY_TYPE]

    def get_sender_id(self):
        return self.msg_params[MSG_ARG_KEY_SENDER]

    def get_receiver_id(self):
        return self.msg_params[MSG_ARG_KEY_RECEIVER]

    def add_model_params(self, key: str, tree: Any):
        """pytree -> flat {dotted-name: nested lists} dict — the EXACT mobile
        wire FORMAT of the reference's transform_tensor_to_list
        (fedavg/utils.py:11-14: a state-dict-style dict whose values are
        .tolist() arrays). Format-level interop is asserted both directions
        by tests/test_mqtt.py; note the names themselves are framework
        leaf names ('params.linear.kernel' here vs 'linear.weight' in a
        torch peer), so cross-FRAMEWORK peers additionally need a name map
        for their model."""
        self.msg_params[key] = {
            name: np.asarray(leaf).tolist()
            for name, leaf in _named_leaves(tree)
        }

    @staticmethod
    def decode_model_params(payload: dict, example_tree: Any) -> Any:
        """Flat named-lists dict -> pytree with example_tree's structure and
        dtypes (the reference decodes with transform_list_to_tensor,
        fedavg/utils.py:5-8 — same contract, torch-free)."""
        import jax

        flat = _named_leaves(example_tree)
        leaves = [np.asarray(payload[name], dtype=np.asarray(e).dtype)
                  for name, e in flat]
        return jax.tree.unflatten(jax.tree.structure(example_tree), leaves)

    def to_json(self) -> str:
        return json.dumps(self.msg_params)

    @classmethod
    def from_json(cls, s: str | bytes) -> "Message":
        m = cls()
        m.msg_params = json.loads(s)
        return m
