#!/usr/bin/env bash
# CI smoke matrix: every algorithm runnable from its CLI on tiny configs, with
# wandb-summary.json asserts — the reference's CI strategy (SURVEY §4,
# command_line/CI-script-fedavg.sh:32-62) rebuilt for the TPU framework.
#
# Runs on the virtual CPU mesh (same trick as tests/conftest.py) so it needs
# no TPU. Usage: bash command_line/ci_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

RUN_DIR="${RUN_DIR:-./wandb/ci-smoke/files}"
rm -rf "$RUN_DIR"

assert_summary () {  # assert_summary <key> <min> <max>
  python - "$RUN_DIR" "$1" "$2" "$3" <<'EOF'
import json, sys
run_dir, key, lo, hi = sys.argv[1], sys.argv[2], float(sys.argv[3]), float(sys.argv[4])
with open(f"{run_dir}/wandb-summary.json") as f:
    s = json.load(f)
v = s[key]
assert lo <= v <= hi, f"{key}={v} not in [{lo}, {hi}]"
print(f"OK {key}={v}")
EOF
}

assert_summary_str () {  # assert_summary_str <key> <required-substring>
  python - "$RUN_DIR" "$1" "$2" <<'EOF'
import json, sys
run_dir, key, sub = sys.argv[1], sys.argv[2], sys.argv[3]
with open(f"{run_dir}/wandb-summary.json") as f:
    s = json.load(f)
v = s[key]
assert isinstance(v, str) and sub in v, f"{key}={v!r} lacks {sub!r}"
print(f"OK {key} contains {sub!r}")
EOF
}

COMMON="--run_dir $RUN_DIR --data_dir ./data --seed 0"

echo "== graft-lint --all (six engines, one summary table, one exit code)"
# ONE invocation replaces the five sequential engine runs this script used
# to chain: the jaxpr+AST lint (with the full 29-model dtype sweep), the
# HLO comms layer vs COMMS_BUDGET.json, the compile layer vs
# COMPILE_BUDGET.json, the feature-matrix prover vs core/spec.py, and the
# jaxpr equivalence prover (EQUIV_PAIRS contracts + builder-vs-legacy over
# the full matrix cover). Any finding in any layer is the single nonzero
# exit; --json-dir drops every machine report (LINT/COMMS/COMPILE/MATRIX/
# EQUIV.json) next to the committed copies
python -m fedml_tpu.analysis --all --json-dir .

echo "== comms budget self-test: a halved tensor-round ceiling must trip"
# run one tensor program against a doctored budget table (real table with
# the fednova bytes ceiling cut in half) — the gate must produce a
# comms-budget finding, proving the new tensor.round entries are live
python - <<'EOF'
import json, tempfile, os
from fedml_tpu.analysis.comms import run_comms
name = "tensor.round[lr,f32,fednova,2x4]"
budgets = json.load(open("COMMS_BUDGET.json"))
budgets[name]["collective_bytes"] //= 2
with tempfile.TemporaryDirectory() as d:
    with open(os.path.join(d, "COMMS_BUDGET.json"), "w") as f:
        json.dump(budgets, f)
    report, _ = run_comms(d, targets=[name])
assert not report.ok, "halved tensor budget failed to trip the comms gate"
assert any(f.rule == "comms-budget" and f.target == name
           for f in report.findings), report.findings
print("OK comms budget trips on tensor.round regression")
EOF

echo "== codec comms self-test: a tightened topk16 ceiling must trip"
# the --comms run above already gated the codec-on program twins against
# their committed entries (they are regular PROGRAMS, not --fast-skipped
# extras); here the topk16 admit budget is doctored 2x tighter and the
# gate must fire with the measured-vs-ceiling diff, proving the codec
# entries are live gates and not dead pins
python - <<'EOF'
import json, tempfile, os
from fedml_tpu.analysis.comms import run_comms
name = "buffered.admit[lr,f32,topk16]"
budgets = json.load(open("COMMS_BUDGET.json"))
budgets[name]["collective_bytes"] //= 2
with tempfile.TemporaryDirectory() as d:
    with open(os.path.join(d, "COMMS_BUDGET.json"), "w") as f:
        json.dump(budgets, f)
    report, _ = run_comms(d, targets=[name])
assert not report.ok, "tightened topk16 budget failed to trip the comms gate"
finding = next(f for f in report.findings
               if f.rule == "comms-budget" and f.target == name)
assert "bytes" in finding.message, finding
print("OK comms budget trips on codec-on admit regression:", finding.message)
EOF

echo "== step-peak comms self-test: a tightened tensor.step ceiling must trip"
# the sharded step's peak_bytes budget (1.5x headroom over the measured
# per-device peak) is doctored to a third of the committed ceiling — below
# the measured peak — and the gate must fire on peak_bytes, proving the
# activation-sharding memory contract is a live gate; the targeted run
# also re-lowers the replicated twin, so the <=0.5x measured-ratio gate
# runs (and must stay quiet) in the same pass
python - <<'EOF'
import json, tempfile, os
from fedml_tpu.analysis.comms import run_comms
name = "tensor.step[tformer,f32,2x4]"
budgets = json.load(open("COMMS_BUDGET.json"))
budgets[name]["peak_bytes"] //= 3
with tempfile.TemporaryDirectory() as d:
    with open(os.path.join(d, "COMMS_BUDGET.json"), "w") as f:
        json.dump(budgets, f)
    report, _ = run_comms(d, targets=[name])
assert not report.ok, "tightened step peak budget failed to trip the gate"
finding = next(f for f in report.findings
               if f.rule == "comms-budget" and f.target == name)
assert "peak_bytes" in finding.message, finding
print("OK comms budget trips on tensor.step peak regression:",
      finding.message)
EOF

echo "== compile budget self-test: an extra compile over the ceiling must trip"
# fold a synthetic trace with one more compile request than the pipelined
# drive's measured max_compiles — run_compile_gate must FAIL, proving the
# runtime half of the budget gate is live
python - <<'EOF'
import json
from fedml_tpu.telemetry.report import fold, run_compile_gate
budgets = json.load(open("COMPILE_BUDGET.json"))
n = budgets["pipelined"]["max_compiles"] + 1
records = [{"type": "event", "kind": "compile_cache",
            "name": "/jax/compilation_cache/compile_requests_use_cache"}] * n
ok, skipped, msg = run_compile_gate(fold(records), budgets, "pipelined")
assert not ok and not skipped, msg
print("OK compile gate trips on one compile over the pipelined ceiling")
EOF

echo "== compile budget self-test: the superstep pin must be live"
# enumerate the finetune drive (which reaches the K=4 superstep program)
# against a doctored budget table with the superstep pin removed —
# check_budgets must produce a reachable-but-not-budgeted finding, proving
# the new engine.superstep entry is a live gate, not dead JSON
python - <<'EOF'
import json
from fedml_tpu.analysis.compile_engine import check_budgets
from fedml_tpu.analysis.targets import enumerate_drive_programs
budgets = json.load(open("COMPILE_BUDGET.json"))
pin = "engine.superstep[lr,f32,fedavg,k4]"
measured = {"finetune": enumerate_drive_programs("finetune")}
assert pin in measured["finetune"], "superstep program not enumerated"
assert not check_budgets(measured, budgets), "committed budgets out of date"
del budgets["finetune"]["programs"][pin]
findings = check_budgets(measured, budgets)
assert any(pin in f.message and "not budgeted" in f.message
           for f in findings), findings
print("OK compile budget trips when the superstep pin is removed")
EOF

echo "== matrix coverage self-test: an unpinned reachable program must trip"
# remove the sharded topk64 codec-twin pin (the program this layer first
# proved reachable) from an in-memory copy of COMPILE_BUDGET.json — the
# spec<->budget diff must produce a matrix-coverage finding with a
# readable reachable-but-not-gated message, proving the coverage gate is
# a live diff and not dead JSON
python - <<'EOF'
import json
from fedml_tpu.analysis.matrix_engine import check_budget_coverage
pin = "sharded.round[lr,f32,fedavg,8,topk64]"
budgets = json.load(open("COMPILE_BUDGET.json"))
assert pin in budgets["sharded"]["programs"], "topk64 pin missing from repo"
assert not check_budget_coverage(".", compile_budgets=budgets), \
    "committed budgets out of coverage"
del budgets["sharded"]["programs"][pin]
findings = check_budget_coverage(".", compile_budgets=budgets)
hit = [f for f in findings
       if f.rule == "matrix-coverage" and pin in f.message]
assert hit and "not budget-gated" in hit[0].message, findings
print("OK matrix coverage trips when the sharded topk64 pin is removed:")
print("  ", hit[0].message)
EOF

echo "== equiv self-test: a mutated structurally-off contract must trip"
# flip ONE EQUIV_PAIRS knob in memory — the lora-rank-0 contract's builder
# side gets lora_rank=2, so it emits a REAL LoRA round against the plain
# legacy engine round — and the prover must FAIL that contract with a
# readable divergence (eqn index / signature, primitive, operand
# provenance), proving the equivalence gate catches real drift and isn't
# a tautology over shared code paths
python - <<'EOF'
import fedml_tpu.core.spec as spec
from fedml_tpu.analysis.equiv_engine import run_equiv
spec.EQUIV_PAIRS = tuple(
    spec.EquivPair(p.name,
                   spec.EquivSide(p.lhs.kind, p.lhs.levels,
                                  (("lora_rank", 2),)),
                   p.rhs, p.doc)
    if p.name == "lora-rank-0" else p
    for p in spec.EQUIV_PAIRS)
report, payload = run_equiv(".", fast=True, targets=["lora-rank-0"])
assert not report.ok, "mutated lora-rank-0 contract failed to trip"
[row] = [r for r in payload["pairs"] if r["name"] == "lora-rank-0"]
assert row["ok"] is False, row
msg = report.findings[0].message
assert "divergence" in msg and ("eqn[" in msg or "signature" in msg), msg
print("OK equiv gate trips on a mutated contract:")
print("  ", msg.splitlines()[0])
EOF

echo "== base framework (scalar-sum smoke, CI-script-framework.sh analog)"
python -m fedml_tpu.experiments.main_base --client_num 4 --comm_round 2

echo "== fedavg standalone smoke (2 clients, 1 round, batch 4, eager loop)"
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 \
  --epochs 1 --batch_size 4 --pipeline_depth 0
assert_summary "Test/Acc" 0.0 1.0
cp "$RUN_DIR/wandb-summary.json" /tmp/ci_smoke_eager_summary.json

echo "== fedavg pipelined smoke (depth-2 async drive loop == eager, CLI level)"
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 \
  --epochs 1 --batch_size 4 --pipeline_depth 2
python - "$RUN_DIR" <<'EOF'
import json, sys
with open("/tmp/ci_smoke_eager_summary.json") as f:
    eager = json.load(f)
with open(f"{sys.argv[1]}/wandb-summary.json") as f:
    piped = json.load(f)
for k in ("Test/Acc", "Test/Loss", "Train/Acc", "Train/Loss"):
    assert piped.get(k) == eager.get(k), (k, eager.get(k), piped.get(k))
print("OK pipelined == eager:", {k: piped[k] for k in ("Test/Acc", "Test/Loss") if k in piped})
EOF

echo "== fedavg tensor-sharded smoke (2x4 clients x tensor mesh, CLI level)"
# same workload as the eager smoke but with params tensor-sharded 4-way on
# the forced 8-virtual-device mesh; tensor rounds are bit-identical to their
# replicated twin (tests/test_tensor_shard.py) and match the vmap engine up
# to client-psum reassociation, so the summary must agree to ~1e-5
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 \
  --epochs 1 --batch_size 4 --pipeline_depth 0 --tensor_shards 4
python - "$RUN_DIR" <<'EOF'
import json, sys
with open("/tmp/ci_smoke_eager_summary.json") as f:
    eager = json.load(f)
with open(f"{sys.argv[1]}/wandb-summary.json") as f:
    sharded = json.load(f)
for k in ("Test/Acc", "Test/Loss", "Train/Acc", "Train/Loss"):
    d = abs(sharded.get(k, 1e9) - eager.get(k, -1e9))
    assert d < 1e-5, (k, eager.get(k), sharded.get(k))
print("OK tensor-sharded ~= eager:",
      {k: sharded[k] for k in ("Test/Acc", "Test/Loss") if k in sharded})
EOF

echo "== fedavg chaos smoke (seeded drops + NaN faults, quarantine + guard)"
# seed 7 deterministically drops clients and poisons others with NaN every
# round; the masked round must quarantine the poisoned clients (nonzero
# quarantined_count), still make progress on the survivors, and the guard
# must accept every round (finite final loss)
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 8 --comm_round 2 \
  --epochs 1 --batch_size 4 \
  --chaos 1 --chaos_seed 7 --chaos_drop_rate 0.3 --chaos_nan_rate 0.4 --guard 1
assert_summary "chaos_dropped" 1 7
assert_summary "quarantined_count" 1 7
assert_summary "participated_count" 1 7
assert_summary "Test/Loss" 0 10
assert_summary "Test/Acc" 0.0 1.0

echo "== codec smoke (depth-2 chaos drive with --update_codec int8)"
# the compressed-transport drive must survive the same chaos: int8-encoded
# updates with error-feedback residuals through the depth-2 async loop,
# quarantine and guard active — finite loss proves decode+EF keeps the
# trajectory sane end to end at the CLI level
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 8 --comm_round 2 \
  --epochs 1 --batch_size 4 --pipeline_depth 2 \
  --chaos 1 --chaos_seed 7 --chaos_drop_rate 0.3 --chaos_nan_rate 0.4 --guard 1 \
  --update_codec int8
assert_summary "Test/Loss" 0 10
assert_summary "Test/Acc" 0.0 1.0
assert_summary "quarantined_count" 1 7

echo "== superstep smoke (--rounds_per_dispatch 4: K fused rounds, chaos on)"
# K=4 depth-0 chaos drive at the CLI level: round 0 is the eval boundary
# (eager), rounds 1-3 run as ONE fused dispatch with the [K, C] chaos
# masks applied in-graph; the drive must survive and report sane metrics
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 8 --comm_round 4 \
  --epochs 1 --batch_size 4 --frequency_of_the_test 100 \
  --chaos 1 --chaos_seed 7 --chaos_drop_rate 0.3 --chaos_nan_rate 0.4 \
  --rounds_per_dispatch 4
assert_summary "Test/Loss" 0 10
assert_summary "Test/Acc" 0.0 1.0
assert_summary "chaos_dropped" 0 7

echo "== superstep byte-equality check: K=4 fused == K=1 eager, bitwise"
python - <<'EOF'
# API-level twin of the CLI smoke: the fused drive must commit final params
# BYTE-equal to the eager drive under the same seeded chaos, and the trace
# must carry superstep_committed events covering the fused chunks
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import numpy as np
from fedml_tpu import telemetry
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan

ds = load_dataset("mnist", client_num_in_total=8, partition_method="homo")

def run(k):
    cfg = FedConfig(comm_round=5, epochs=1, batch_size=4, lr=0.05,
                    client_num_in_total=8, client_num_per_round=8,
                    frequency_of_the_test=100, rounds_per_dispatch=k)
    api = FedAvgAPI(ds, cfg, ClassificationTrainer(
        create_model("lr", output_dim=10)))
    tracer = telemetry.Tracer()
    api.train(chaos=FaultPlan(seed=7, drop_rate=0.3, nan_rate=0.4),
              tracer=tracer)
    return api, tracer

eager, _ = run(1)
fused, tracer = run(4)
for a, b in zip(jax.tree.leaves(eager.global_variables),
                jax.tree.leaves(fused.global_variables)):
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), \
        "superstep params diverged from the eager drive"
committed = tracer.find_events("superstep_committed")
assert committed and sum(e["rounds"] for e in committed) == 4, committed
print("OK superstep K=4 byte-equal to eager;",
      len(committed), "superstep_committed event(s)")
EOF

echo "== federated LoRA smoke (--lora_rank 8: adapter-only rounds, CLI level)"
# two rounds with rank-8 adapters on the lr base — the CLI seam wraps the
# trainer via maybe_wrap_lora, the drive trains (A,B) only, and the loss
# must stay finite and the accuracy sane
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 8 --comm_round 2 \
  --epochs 1 --batch_size 4 --lora_rank 8
assert_summary "Test/Loss" 0 10
assert_summary "Test/Acc" 0.0 1.0

echo "== LoRA frozen-base check: the same drive must never move the base"
python - <<'EOF'
# API-level twin of the CLI smoke: the base params live in the lora_base
# collection and must be byte-identical after training, while the adapters
# (the only federated state) must have moved
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import numpy as np
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.lora import LORA_COLLECTION, maybe_wrap_lora
from fedml_tpu.models.registry import create_model

ds = load_dataset("mnist", client_num_in_total=8, partition_method="homo")
cfg = FedConfig(comm_round=2, epochs=1, batch_size=4, lr=0.05,
                client_num_in_total=8, client_num_per_round=8, lora_rank=8)
trainer = maybe_wrap_lora(
    ClassificationTrainer(create_model("lr", output_dim=10)), cfg)
api = FedAvgAPI(ds, cfg, trainer)
base0 = jax.tree.map(np.copy, api.global_variables[LORA_COLLECTION])
adap0 = jax.tree.map(np.copy, api.global_variables["params"])
api.train()
for x, y in zip(jax.tree.leaves(base0),
                jax.tree.leaves(api.global_variables[LORA_COLLECTION])):
    assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), \
        "frozen LoRA base moved during federated rounds"
moved = any(not np.array_equal(x, np.asarray(y)) for x, y in
            zip(jax.tree.leaves(adap0),
                jax.tree.leaves(api.global_variables["params"])))
assert moved, "adapters never moved — the drive trained nothing"
print("OK LoRA drive: base byte-frozen, adapters trained")
EOF

echo "== graft-trace smoke (depth-2 chaos drive: --trace_summary + span coverage)"
# same chaos workload, pipelined, with the tracer's p50/p95 table on stdout;
# TRACE.jsonl lands next to the run files and must cover >=95% of round
# wall-clock with phase spans and carry the chaos/commit event ledger
rm -rf /tmp/ci_smoke_trace_ckpt   # a stale ckpt would resume past the rounds
rm -rf /tmp/ci_smoke_ledger       # open_or_create ACCUMULATES across runs
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 8 --comm_round 2 \
  --epochs 1 --batch_size 4 --pipeline_depth 2 \
  --chaos 1 --chaos_seed 7 --chaos_drop_rate 0.3 --chaos_nan_rate 0.4 --guard 1 \
  --ckpt_dir /tmp/ci_smoke_trace_ckpt \
  --client_ledger_dir /tmp/ci_smoke_ledger \
  --trace_summary 1 | tee /tmp/ci_smoke_trace_stdout.txt
grep -Eq '^phase +count +total_s +p50_ms +p95_ms' /tmp/ci_smoke_trace_stdout.txt
grep -Eq '^dispatch ' /tmp/ci_smoke_trace_stdout.txt
python - "$RUN_DIR" <<'EOF'
import sys
from fedml_tpu.telemetry.report import fold, load_trace, run_compile_gate
report = fold(load_trace(f"{sys.argv[1]}/TRACE.jsonl"))
assert report["coverage"] >= 0.95, f"span coverage {report['coverage']} < 0.95"
assert report["rounds"] == 2, report["rounds"]
ev = report["events"]
assert ev.get("chaos_inject", 0) >= 2, ev
assert ev.get("guard_verdict", 0) >= 2, ev
assert ev.get("round_committed", 0) == 2, ev
assert ev.get("checkpoint_save", 0) >= 1, ev
print(f"OK trace: coverage={report['coverage']} events={ev}")

# compile gate: this drive IS the budgeted "pipelined" config (2 rounds of
# it), so its traced compile count must fit under the measured 10-round
# ceiling in COMPILE_BUDGET.json — any excess is a retracing call site
import json
budgets = json.load(open("COMPILE_BUDGET.json"))
ok, skipped, msg = run_compile_gate(report, budgets, "pipelined")
print(msg)
assert ok and not skipped, msg
EOF

echo "== client-health ledger smoke (graft-ledger fleet view + gate)"
# the depth-2 chaos drive above also wrote the per-client ledger; the fleet
# report must gate PASS — full coverage (every client sampled both rounds),
# and the ledger's dispatch-time quarantine accounting must agree with the
# trace's commit-time round_committed counters (two independent paths)
python tools/client_report.py /tmp/ci_smoke_ledger \
  --trace "$RUN_DIR/TRACE.jsonl" --gate --coverage_floor 0.9 \
  | tee /tmp/ci_smoke_ledger_report.txt
python - <<'EOF'
import json
line = [l for l in open("/tmp/ci_smoke_ledger_report.txt")
        if l.startswith("{")][-1]
r = json.loads(line)
assert r["num_clients"] == 8 and r["coverage"] == 1.0, r
assert r["quarantine_total"] >= 1, r          # nan chaos must quarantine
assert r["quarantine_total"] == r["trace_quarantined_total"], r
assert r["drop_total"] >= 1, r                # drop chaos must drop
print(f"OK ledger report: quarantined={r['quarantine_total']} "
      f"dropped={r['drop_total']} gini={r['participation_gini']}")
EOF
echo "== ledger gate self-test: a zero flagged-ceiling must trip (exit 1)"
# recidivist_min=1 guarantees a non-empty flagged set (the chaos smoke
# asserted quarantined_count >= 1), so ceiling 0 must fail the gate
if python tools/client_report.py /tmp/ci_smoke_ledger --gate \
     --recidivist_min 1 --flagged_ceiling 0 >/tmp/ci_smoke_ledger_trip.txt 2>&1; then
  echo "client-health gate FAILED TO TRIP on a zero flagged ceiling:"
  cat /tmp/ci_smoke_ledger_trip.txt
  exit 1
fi
grep -q 'client-health gate: FAIL' /tmp/ci_smoke_ledger_trip.txt
echo "OK client-health gate trips on zero flagged ceiling"

echo "== buffered straggler smoke (FedBuff drive: no round barrier, depth-2)"
# seeded straggler plan: half the cohort arrives 1-2 dispatch rounds late,
# updates land in the K=5 buffer staleness-discounted, outstanding arrivals
# drain after the last dispatch round — every one of the 8*3 updates must
# commit and some must carry staleness > 0
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 8 --comm_round 3 \
  --epochs 1 --batch_size 4 --pipeline_depth 2 \
  --buffer_size 5 --staleness_alpha 0.5 \
  --chaos 1 --chaos_seed 7 --chaos_straggler_rate 0.5 --chaos_straggler_rounds 2
assert_summary "committed_updates" 24 24
assert_summary "staleness_sum" 1 1000
assert_summary "Test/Acc" 0.0 1.0
python - "$RUN_DIR" <<'EOF'
import sys
from fedml_tpu.telemetry.report import fold, load_trace
report = fold(load_trace(f"{sys.argv[1]}/TRACE.jsonl"))
ev = report["events"]
assert ev.get("update_admitted", 0) == 24, ev
assert ev.get("buffer_committed", 0) >= 4, ev  # 24 updates / K=5 -> >=4 fills
print(f"OK buffered trace: events={ev}")
EOF

echo "== buffered determinism: same seed + stragglers => byte-identical params"
python - <<'EOF'
# the async schedule is a pure function of the seed: two buffered runs with
# the same straggler plan must produce byte-for-byte the same final model
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import numpy as np
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan

ds = load_dataset("mnist", client_num_in_total=8, partition_method="homo")

def run():
    cfg = FedConfig(comm_round=3, epochs=1, batch_size=4, lr=0.05,
                    client_num_in_total=8, client_num_per_round=8,
                    pipeline_depth=2, buffer_size=5, staleness_alpha=0.5)
    api = FedAvgAPI(ds, cfg,
                    ClassificationTrainer(create_model("lr", output_dim=10)))
    api.train(chaos=FaultPlan(seed=7, straggler_rate=0.5, straggler_rounds=2))
    return api

a, b = run(), run()
for x, y in zip(jax.tree.leaves(a.global_variables),
                jax.tree.leaves(b.global_variables)):
    assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), "params differ"
assert a._buffer_host.committed_updates == b._buffer_host.committed_updates == 24
print(f"OK buffered rerun byte-identical: {a._buffer_host.commits} commits, "
      f"{a._buffer_host.committed_updates} updates")
EOF

echo "== graft-serve smoke (two tenants, one mesh: fedavg + buffered, both commit)"
python - <<'EOF'
# a sync-fedavg tenant and a partial-dispatch buffered tenant interleaved
# through the fair-share scheduler over the packed mnist store: both jobs
# must commit, the trace must carry per-tenant round spans and one
# job_committed event each, and the sync tenant must be byte-identical to
# running its job solo through the classic drive loop
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import numpy as np
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.serving import JobDescriptor, Scheduler
from fedml_tpu.telemetry.tracer import Tracer

ds = load_dataset("mnist", client_num_in_total=8, partition_method="homo")
sync_cfg = FedConfig(comm_round=2, epochs=1, batch_size=4, lr=0.05,
                     client_num_in_total=8, client_num_per_round=8)
buf_cfg = FedConfig(comm_round=2, epochs=1, batch_size=4, lr=0.03, seed=1,
                    client_num_in_total=8, client_num_per_round=8,
                    buffer_size=5, staleness_alpha=0.5)
tracer = Tracer()
sched = Scheduler(policy="fair_share", tracer=tracer)
sched.submit(JobDescriptor(name="sync", config=sync_cfg, dataset=ds))
sched.submit(JobDescriptor(name="buf", config=buf_cfg, dataset=ds,
                           chaos=FaultPlan(seed=7, straggler_rate=0.5,
                                           straggler_rounds=2),
                           partial_dispatch=True, weight=2.0))
sched.run()
assert all(j.done for j in sched.queue), [j.state for j in sched.queue]
committed = {e["job"] for e in tracer.find_events("job_committed")}
assert committed == {"sync", "buf"}, committed
jobs = tracer.job_summary()
assert set(jobs) == {"sync", "buf"} and all(
    p["round"]["count"] == 2 for p in jobs.values()), jobs

solo = FedAvgAPI(ds, sync_cfg,
                 ClassificationTrainer(create_model("lr", output_dim=10)))
solo.train()
for a, b in zip(jax.tree.leaves(sched.queue.get("sync").final_params()),
                jax.tree.leaves(jax.device_get(solo.global_variables))):
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), \
        "served sync tenant diverged from its solo run"
print(f"OK graft-serve: 2 tenants committed in {sched.ticks} ticks, "
      f"compile ledger={sched.compile_ledger}")
EOF

echo "== serving compile-budget self-test: a cache-blowing tenant must FAIL"
python - <<'EOF'
# synthetic ledger one request over the eager drive's pinned max_compiles:
# the per-tenant gate must FAIL that tenant (and only that tenant), proving
# the serving half of the compile budget is live
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import json
from fedml_tpu.core.config import FedConfig
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.serving import JobDescriptor, Scheduler

ds = load_dataset("mnist", client_num_in_total=2, partition_method="homo")
cfg = FedConfig(comm_round=1, epochs=1, batch_size=4,
                client_num_in_total=2, client_num_per_round=2)
sched = Scheduler()
sched.submit(JobDescriptor(name="polite", config=cfg, dataset=ds))
sched.submit(JobDescriptor(name="blower", config=cfg.replace(seed=1),
                           dataset=ds))
budgets = json.load(open("COMPILE_BUDGET.json"))
ceiling = budgets["eager"]["max_compiles"]
sched.compile_ledger["polite"]["requests"] = ceiling
sched.compile_ledger["blower"]["requests"] = ceiling + 1
ok, report = sched.check_compile_budgets(budgets)
print(report)
assert not ok, "per-tenant compile gate failed to trip"
lines = report.splitlines()
assert any(l.startswith("OK tenant=polite") for l in lines), report
assert any(l.startswith("FAIL tenant=blower") for l in lines), report
print("OK serving compile gate trips on one request over the eager ceiling")
EOF

echo "== graft-slo overload smoke: preemption + admission on one mesh slot"
python - <<'EOF'
# one mesh slot (max_resident=1), bounded queue (max_queued=2, reject):
# a latency-class arrival must preempt the running throughput tenant via
# checkpointed eviction, a third arrival must bounce as a schema'd
# job_rejected event, and the evicted-then-resumed tenant must finish
# byte-identical to its uninterrupted solo run
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import numpy as np
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.serving import JobDescriptor, Scheduler
from fedml_tpu.telemetry.tracer import Tracer

ds = load_dataset("mnist", client_num_in_total=8, partition_method="homo")
cfg = FedConfig(comm_round=3, epochs=1, batch_size=4, lr=0.05,
                client_num_in_total=8, client_num_per_round=8)
tracer = Tracer()
sched = Scheduler(policy="fair_share", tracer=tracer,
                  max_resident=1, admission="reject", max_queued=2)
sched.submit(JobDescriptor(name="tp", config=cfg, dataset=ds))
sched.tick()  # tp takes the slot
sched.submit(JobDescriptor(name="lat", config=cfg.replace(seed=1,
                                                          comm_round=1),
                           dataset=ds, slo="latency"))
bounced = sched.submit(JobDescriptor(name="extra",
                                     config=cfg.replace(seed=2),
                                     dataset=ds))
assert bounced is None and sched.rejections == 1
while sched.tick() is not None:
    pass
sched.close()
assert sched.queue.all_done() and sched.evictions == 1
kinds = [e["kind"] for e in tracer.find_events()
         if e["kind"] in ("job_evicted", "job_resumed", "job_rejected")]
assert kinds == ["job_rejected", "job_evicted", "job_resumed"], kinds
rej = tracer.find_events("job_rejected")[0]
assert rej["job"] == "extra" and rej["reason"] == "queue_full"

solo = FedAvgAPI(ds, cfg,
                 ClassificationTrainer(create_model("lr", output_dim=10)))
solo.train()
for a, b in zip(jax.tree.leaves(sched.queue.get("tp").final_params()),
                jax.tree.leaves(jax.device_get(solo.global_variables))):
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), \
        "evicted+resumed tenant diverged from its solo run"
print(f"OK graft-slo overload: 1 eviction, 1 rejection, resumed tenant "
      f"byte-identical to solo in {sched.ticks} ticks")
EOF

echo "== SLO deadline-gate self-test: a blown deadline must FAIL"
python - <<'EOF'
# deterministic injected clock (1s per reading) makes any completed job
# blow a 0.5s deadline: the per-tenant deadline-miss ceiling must trip,
# proving the SLO gate reads measured latency, not declared intent
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import itertools
from fedml_tpu.core.config import FedConfig
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.serving import JobDescriptor, Scheduler
from fedml_tpu.telemetry.tracer import Tracer

ds = load_dataset("mnist", client_num_in_total=2, partition_method="homo")
cfg = FedConfig(comm_round=1, epochs=1, batch_size=4,
                client_num_in_total=2, client_num_per_round=2)
clock = itertools.count()
tracer = Tracer(clock=lambda: float(next(clock)))
sched = Scheduler(tracer=tracer)
sched.submit(JobDescriptor(name="urgent", config=cfg, dataset=ds,
                           slo="latency", deadline_s=0.5))
sched.run()
assert sched.slo_ledger["urgent"]["misses"] == 1
assert len(tracer.find_events("deadline_miss")) == 1
ok, report = sched.check_slo(0)
print(report)
assert not ok, "deadline-miss ceiling failed to trip"
assert any(l.startswith("FAIL tenant=urgent") for l in report.splitlines())
print("OK SLO gate trips on a blown deadline (and reports it readably)")
EOF

echo "== perf-regression gate (ROADMAP item 5): TRACE rounds/s vs BENCH baseline"
rm -f /tmp/ci_gate_trace.jsonl
BENCH_PIPE_ROUNDS=10 BENCH_PIPE_REPS=2 BENCH_PIPE_DEPTHS=0 BENCH_PIPE_MODEL=lr \
  BENCH_PIPE_OUT='' BENCH_PIPE_TRACE=/tmp/ci_gate_trace.jsonl \
  python tools/bench_pipeline.py
python tools/trace_report.py /tmp/ci_gate_trace.jsonl --gate \
  | tee /tmp/ci_gate_out.txt

if grep -q 'perf-regression gate: PASS' /tmp/ci_gate_out.txt; then
  echo "== perf gate self-test: a 20x throttle must trip it (exit 1)"
  if python tools/trace_report.py /tmp/ci_gate_trace.jsonl --gate \
       --self-test-throttle 0.05 >/tmp/ci_gate_trip.txt 2>&1; then
    echo "perf gate FAILED TO TRIP on a 20x artificial throttle:"
    cat /tmp/ci_gate_trip.txt
    exit 1
  fi
  grep -q 'perf-regression gate: FAIL' /tmp/ci_gate_trip.txt
  echo "OK perf gate trips on artificial throttle"
else
  # gate SKIPped (baseline from an incomparable box) — the trip self-test
  # would skip identically, so there is nothing to prove here
  echo "perf gate self-test: skipped (gate did not run against this baseline)"
fi

echo "== out-of-core scale smoke (50k-client mmap store, RSS budget gate)"
# one bench_scale point over a sparse synthetic shard store: the drive loop
# must stay inside a fixed RSS budget that a whole-store materialization
# (~128MB of shards + copies on top of the ~250MB process floor) would blow
python tools/bench_scale.py --point --clients 50000 --rounds 3 \
  --rss_budget_mb 400 | tee /tmp/ci_scale_point.txt
python - <<'EOF'
import json
line = [l for l in open("/tmp/ci_scale_point.txt") if l.startswith("{")][-1]
p = json.loads(line)
assert p["clients"] == 50000 and p["rounds_per_sec"] > 0, p
assert not p["rss_budget_exceeded"], p
assert p["store_physical_mb"] < p["store_logical_mb"] / 10, p  # sparse store
print(f"OK scale point: rss={p['peak_rss_mb']}MB rps={p['rounds_per_sec']}")
EOF

echo "== 1M-client ledger scale smoke (mmap columns, RSS budget gate)"
# the same RSS budget must hold with a FULL-federation client-health ledger
# attached: per-round scatter writes touch O(cohort) mmap pages, so a
# million-client ledger costs pages, not gigabytes of resident columns
python tools/bench_scale.py --point --clients 1000000 --rounds 3 \
  --rss_budget_mb 400 --ledger --fast_sampling | tee /tmp/ci_scale_ledger.txt
python - <<'EOF'
import json
line = [l for l in open("/tmp/ci_scale_ledger.txt") if l.startswith("{")][-1]
p = json.loads(line)
assert not p["rss_budget_exceeded"], p
led = p["ledger"]
assert led["participating"] == 4 * 64, led  # warm + 3 rounds x CPR=64
assert led["physical_mb"] < led["logical_mb"], led  # sparse columns
print(f"OK 1M-client ledger point: rss={p['peak_rss_mb']}MB "
      f"ledger_physical={led['physical_mb']}MB")
EOF

echo "== scale RSS budget self-test: a 1MB budget must trip (exit 1)"
if python tools/bench_scale.py --point --clients 2000 --rounds 1 \
     --rss_budget_mb 1 >/tmp/ci_scale_trip.txt 2>&1; then
  echo "scale RSS budget FAILED TO TRIP on a 1MB budget:"
  cat /tmp/ci_scale_trip.txt
  exit 1
fi
grep -q '"rss_budget_exceeded": true' /tmp/ci_scale_trip.txt
echo "OK scale RSS budget trips"

echo "== graft-pfl smoke (--adapter_bank_dir: personalized drive + lift metric)"
# two personalized rounds over a fresh adapter bank: the eval boundary must
# report the accuracy lift of the personalized models over the global one,
# and two same-seed fresh-bank runs must write byte-identical shard files
# (the bank rides the deterministic record flush, so it cannot flap)
rm -rf /tmp/ci_pfl_bank_a /tmp/ci_pfl_bank_b
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 4 --comm_round 2 \
  --epochs 1 --batch_size 4 --lora_rank 4 --frequency_of_the_test 1 \
  --adapter_bank_dir /tmp/ci_pfl_bank_a
assert_summary "Personalization/Lift" -1.0 1.0
assert_summary "Test/Acc" 0.0 1.0
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 4 --comm_round 2 \
  --epochs 1 --batch_size 4 --lora_rank 4 --frequency_of_the_test 1 \
  --adapter_bank_dir /tmp/ci_pfl_bank_b
for f in /tmp/ci_pfl_bank_a/*; do
  cmp -s "$f" "/tmp/ci_pfl_bank_b/$(basename "$f")" \
    || { echo "bank shard $(basename "$f") differs across same-seed runs"; exit 1; }
done
echo "OK pfl smoke: lift reported, same-seed banks byte-identical"

echo "== graft-pfl resume smoke: a second run must gather the persisted rows"
# resume on bank A: open_or_create validates row count + adapter layout
# against the existing header, and the run trains FROM the persisted rows
# (a layout mismatch or a zeroed bank would be a silent personalization
# reset — open_or_create hard-fails the former; nonzero materialized rows
# before AND after proves the latter)
python - <<'EOF'
from fedml_tpu.models.adapter_bank import read_side_columns
pre = int(read_side_columns("/tmp/ci_pfl_bank_a")["mat"].sum())
assert pre > 0, "first pfl run materialized no bank rows"
open("/tmp/ci_pfl_mat_pre.txt", "w").write(str(pre))
EOF
python -m fedml_tpu.experiments.main_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 8 --client_num_per_round 4 --comm_round 2 \
  --epochs 1 --batch_size 4 --lora_rank 4 --frequency_of_the_test 1 \
  --adapter_bank_dir /tmp/ci_pfl_bank_a
assert_summary "Personalization/Lift" -1.0 1.0
python - <<'EOF'
from fedml_tpu.models.adapter_bank import read_side_columns
pre = int(open("/tmp/ci_pfl_mat_pre.txt").read())
post = int(read_side_columns("/tmp/ci_pfl_bank_a")["mat"].sum())
assert post >= pre, (pre, post)
print(f"OK pfl resume: {pre} rows persisted, {post} materialized after resume")
EOF

echo "== 1M-row adapter-bank scale smoke (mmap shards, RSS budget gate)"
# the bench_scale RSS budget must hold with a FULL-population adapter bank
# in the round: gather/scatter touch O(cohort) rows of the sparse shards,
# so a million personal adapter rows cost pages, not gigabytes
python tools/bench_pfl.py --point --clients 1000000 --rounds 2 \
  --rss_budget_mb 400 | tee /tmp/ci_pfl_point.txt
python - <<'EOF'
import json
line = [l for l in open("/tmp/ci_pfl_point.txt") if l.startswith("{")][-1]
p = json.loads(line)
assert not p["rss_budget_exceeded"], p
assert p["bank_physical_mb"] < p["bank_logical_mb"] / 10, p  # sparse shards
assert p["gather_rows_per_sec"] > 0 and p["scatter_rows_per_sec"] > 0, p
print(f"OK 1M-row bank point: rss={p['peak_rss_mb']}MB "
      f"bank_physical={p['bank_physical_mb']}MB "
      f"(logical {p['bank_logical_mb']}MB)")
EOF

echo "== pfl RSS budget self-test: a 1MB budget must trip (exit 1)"
if python tools/bench_pfl.py --point --clients 2000 --rounds 1 \
     --rss_budget_mb 1 >/tmp/ci_pfl_trip.txt 2>&1; then
  echo "pfl RSS budget FAILED TO TRIP on a 1MB budget:"
  cat /tmp/ci_pfl_trip.txt
  exit 1
fi
grep -q '"rss_budget_exceeded": true' /tmp/ci_pfl_trip.txt
echo "OK pfl RSS budget trips"

echo "== fedavg equivalence oracle: full-batch E=1 FedAvg == centralized"
python - <<'EOF'
# the reference CI's key trick (CI-script-fedavg.sh:44-50) as a direct check
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from fedml_tpu.algorithms.centralized import CentralizedTrainer
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model

ds = load_dataset("mnist", client_num_in_total=10, partition_method="homo")
# grad_clip must be off: clipping is per-client in FedAvg but global in
# centralized GD, which breaks exact gradient linearity when active
cfg = FedConfig(comm_round=3, epochs=1, batch_size=-1, lr=0.05, grad_clip=None,
                client_num_in_total=10, client_num_per_round=10)
trainer = ClassificationTrainer(create_model("lr", output_dim=10))
fed = FedAvgAPI(ds, cfg, trainer)
cen = CentralizedTrainer(ds, cfg, trainer)
cen.global_variables = fed.global_variables  # identical init (immutable pytrees)
for r in range(3):
    fed.train_one_round(r)
cen.train(3)
fa = fed.test_global(0)["Test/Acc"]; ca = cen.eval_global()["Test/Acc"]
assert abs(fa - ca) < 1e-3, (fa, ca)
print(f"OK equivalence: fedavg={fa:.4f} centralized={ca:.4f}")
EOF

echo "== fedavg over MQTT (mobile transport: broker + actor loops)"
python -m fedml_tpu.experiments.main_mqtt_fedavg $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 2 \
  --epochs 1 --batch_size 8
assert_summary "Test/Acc" 0.0 1.0

echo "== fedopt"
python -m fedml_tpu.experiments.main_fedopt $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 --epochs 1 --batch_size 4
assert_summary "Test/Acc" 0.0 1.0

echo "== fednova"
python -m fedml_tpu.experiments.main_fednova $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 --epochs 1 --batch_size 4
assert_summary "Test/Acc" 0.0 1.0

echo "== fedavg_robust (poisoned attacker + backdoor eval)"
python -m fedml_tpu.experiments.main_fedavg_robust $COMMON --dataset mnist --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 --epochs 1 --batch_size 4 \
  --attacker_num 1 --poison_frac 0.3
assert_summary "Test/Acc" 0.0 1.0
assert_summary "Backdoor/SuccessRate" 0.0 1.0

echo "== hierarchical"
python -m fedml_tpu.experiments.main_hierarchical $COMMON --dataset mnist --model lr \
  --client_num_in_total 4 --client_num_per_round 4 --comm_round 1 --epochs 1 \
  --batch_size 4 --group_num 2
assert_summary "Test/Acc" 0.0 1.0

echo "== decentralized (online regret)"
python -m fedml_tpu.experiments.main_decentralized --run_dir "$RUN_DIR" \
  --client_number 4 --iterations 20 --neighbor_num 2

echo "== fedgkt"
python -m fedml_tpu.experiments.main_fedgkt $COMMON --dataset cifar10 \
  --client_num_in_total 4 --client_num_per_round 4 --comm_round 1 \
  --epochs 1 --epochs_server 1 --batch_size 32 --partition_method homo \
  --server_blocks 1 1 1 --client_sample_cap 64
assert_summary "Test/Acc" 0.0 1.0

echo "== split_nn"
python -m fedml_tpu.experiments.main_split_nn $COMMON --dataset cifar10 \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 \
  --epochs 1 --batch_size 8 --partition_method homo
assert_summary "Test/Acc" 0.0 1.0

echo "== classical_vertical_fl"
python -m fedml_tpu.experiments.main_vfl --run_dir "$RUN_DIR" --dataset adult \
  --party_num 3 --epochs 2 --batch_size 32
assert_summary "Test/Acc" 0.0 1.0

echo "== turboaggregate (secure group-ring aggregation)"
python -m fedml_tpu.experiments.main_turboaggregate $COMMON --dataset mnist --model lr \
  --client_num_in_total 4 --client_num_per_round 4 --comm_round 1 \
  --epochs 1 --batch_size 4 --num_groups 2 --partition_method homo
assert_summary "Test/Acc" 0.0 1.0

echo "== fednas (tiny DARTS search, 1 round; reference CI-script-fednas.sh)"
python -m fedml_tpu.experiments.main_fednas $COMMON --dataset cifar10 --model lr \
  --client_num_in_total 2 --client_num_per_round 2 --comm_round 1 --epochs 1 \
  --batch_size 8 --init_channels 4 --layers 1 --steps 2 --multiplier 2
assert_summary "search_acc" 0.0 1.0
assert_summary_str "genotype" "Genotype(normal="

echo "== privacy (2-branch predavg ensemble + MI attack report)"
python -m fedml_tpu.experiments.main_privacy --run_dir "$RUN_DIR" --dataset mnist \
  --partition_method homo --client_num_in_total 8 --client_num_per_round 4 \
  --comm_round 1 --epochs 1 --batch_size 32 --lr 0.1 \
  --branch_num 2 --ensemble_method predavg
assert_summary "Ensemble/Acc" 0.0 1.0
assert_summary "MI/NN_attack_acc" 0.0 1.0

echo "== fedseg"
python -m fedml_tpu.experiments.main_fedseg $COMMON --comm_round 1 --epochs 1 \
  --batch_size 4 --image_size 24 --model fcn
assert_summary "Test/mIoU" 0.0 1.0

echo "== examples/baseline config twin (har_hetero: har_subject + HAR_CNN + adam)"
python -m fedml_tpu.experiments.fed_launch \
  --config fedml_tpu/experiments/configs/baseline/har_hetero.yaml \
  --override comm_round=1 epochs=1 run_dir="$RUN_DIR"
assert_summary "Test/Acc" 0.0 1.0

echo "ALL SMOKE TESTS PASSED"
