"""graft-codec: compressed update transport.

The contracts under test (fedml_tpu/codecs/, ISSUE 13):

- codec-off is STRUCTURAL: `--update_codec none` (and the default) arms
  nothing — per drive (eager, pipelined, buffered, tensor) the final
  params are bitwise identical to a build that never mentions the codec
  knob, and the aggregator state stays unwrapped.
- error-feedback accounting: ``decode(payload) + new_residual ==
  update + old_residual`` bitwise per leaf, for int8 and top-k, including
  a carried (non-zero) residual.
- static shapes: a codec-on drive compiles its round ONCE across 10
  rounds (top-k's k is a function of leaf shapes, never of the data).
- the residual is aggregator state: it rides checkpoints (resume is
  bitwise) and guard rollbacks (a retried round re-enters with the
  pre-round residual).
- the committed COMMS_BUDGET.json codec-on twins: top-k moves >=4x fewer
  collective bytes than the codec-off twin for both the tensor round and
  the buffered admit (the headline gate); the int8 twins are pinned too.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.codecs import make_codec
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.guard import RoundGuard
from fedml_tpu.serving.job import params_equal

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def ds16():
    return load_dataset("mnist", client_num_in_total=16,
                        partition_method="homo", seed=1)


def _api(ds, **kw):
    kw.setdefault("comm_round", 3)
    cfg = FedConfig(dataset="mnist", model="lr", batch_size=8, epochs=1,
                    lr=0.05, client_num_in_total=16, client_num_per_round=8,
                    seed=0, ci=1, frequency_of_the_test=10**9, **kw)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds.class_num))
    return FedAvgAPI(ds, cfg, trainer)


def _fetch(tree):
    return jax.device_get(tree)


# --------------------------------------------------------- codec-off identity

@pytest.mark.parametrize("extra", [
    {}, {"pipeline_depth": 2}, {"buffer_size": 8}, {"tensor_shards": 4},
], ids=["eager", "pipelined", "buffered", "tensor"])
def test_codec_off_is_bitwise_identical_per_drive(ds16, extra):
    # `--update_codec none` must trace and train the exact legacy program:
    # same drive, same seed, codec knob spelled vs never mentioned
    a = _api(ds16, **extra)
    b = _api(ds16, update_codec="none", **extra)
    assert b.codec is None
    assert not (isinstance(b.agg_state, dict)
                and set(b.agg_state) == {"agg", "codec"}), \
        "codec-off state must stay unwrapped"
    a.train()
    b.train()
    assert params_equal(_fetch(a.global_variables),
                        _fetch(b.global_variables))


@pytest.mark.parametrize("codec", ["int8", "topk"])
@pytest.mark.parametrize("extra", [
    {}, {"buffer_size": 8}, {"tensor_shards": 4},
], ids=["eager", "buffered", "tensor"])
def test_codec_on_drives_train_finite(ds16, codec, extra):
    api = _api(ds16, update_codec=codec, codec_k=32, **extra)
    hist = api.train()
    assert hist
    assert all(np.isfinite(l).all()
               for l in jax.tree.leaves(_fetch(api.global_variables)))


# ------------------------------------------------- error-feedback accounting

def _seeded_update(salt):
    k = jax.random.PRNGKey(7)
    return {"w": jax.random.normal(jax.random.fold_in(k, salt),
                                   (7, 5)) * 0.3,
            "b": jax.random.normal(jax.random.fold_in(k, salt + 100),
                                   (5,)) * 0.01}


@pytest.mark.parametrize("name,cfg", [("int8", {}), ("topk", {"codec_k": 9})])
def test_ef_residual_accounting_is_bitwise(name, cfg):
    # decode(payload) + new_residual == update + old_residual, leaf by
    # leaf in f32 — nothing is lost to the wire, only deferred; the carry
    # is seeded non-zero by a prior encode so the identity covers the
    # steady state, not just the first round
    codec = make_codec(name, cfg)
    upd = _seeded_update(0)
    resid = codec.init_state(upd)
    _, resid = codec.encode(_seeded_update(1), resid)
    payload, new_resid = codec.encode(upd, resid)
    dec = codec.decode(payload, upd)
    lhs = jax.tree.map(jnp.add, dec, new_resid)
    rhs = jax.tree.map(jnp.add, upd, resid)
    assert params_equal(_fetch(lhs), _fetch(rhs))


def test_topk_payload_shapes_are_static_in_k():
    codec = make_codec("topk", {"codec_k": 9})
    upd = _seeded_update(0)
    payload, _ = codec.encode(upd, codec.init_state(upd))
    assert payload["values"]["w"].shape == (9,)      # 35 entries, k=9
    assert payload["values"]["b"].shape == (5,)      # clamped to leaf size
    assert payload["idx"]["w"].dtype == jnp.int32


def test_make_codec_registry():
    assert make_codec("none", {}) is None
    assert make_codec("", None) is None
    assert make_codec(None) is None
    assert make_codec("int8", {"codec_bits": 4}).name == "int4"
    assert make_codec("topk", {"codec_k": 16}).name == "topk16"
    with pytest.raises(ValueError, match="unknown update codec"):
        make_codec("zstd")


# --------------------------------------------------- jit-signature stability

@pytest.mark.parametrize("codec", ["topk", "int8"])
def test_codec_round_compiles_once_across_10_rounds(ds16, codec):
    # the compile-once contract: payload shapes depend on leaf shapes and
    # the static k/bits, never on the data — 10 rounds, one signature
    api = _api(ds16, update_codec=codec, codec_k=32, comm_round=10)
    for r in range(10):
        api.train_one_round(r)
    jitted = getattr(api.round_fn, "jitted", api.round_fn)
    assert jitted._cache_size() == 1, \
        f"codec-on round retraced: {jitted._cache_size()} signatures"


# ------------------------------------------- state: checkpoints + rollbacks

def test_codec_state_survives_checkpoint_resume(ds16, tmp_path):
    a = _api(ds16, update_codec="int8")
    a.train_one_round(0)
    a.save_checkpoint(str(tmp_path), 1)
    b = _api(ds16, update_codec="int8")
    assert b.maybe_restore(str(tmp_path)) == 1
    assert params_equal(_fetch(a.agg_state), _fetch(b.agg_state)), \
        "codec residuals must round-trip the checkpoint bitwise"
    # and the restored residual drives on identically
    a.train_one_round(1)
    b.train_one_round(1)
    assert params_equal(_fetch(a.global_variables),
                        _fetch(b.global_variables))


def test_codec_residuals_roll_back_with_the_guard(ds16):
    # a guard-rejected round must not leak its residual update: the retry
    # re-enters with the bitwise pre-round {"agg", "codec"} state
    api = _api(ds16, update_codec="int8")
    orig = api.train_one_round
    entry_state = {}

    def flaky(round_idx, faults=None, rng_salt=0, tracer=None):
        entry_state[(round_idx, rng_salt)] = api.agg_state
        m = orig(round_idx, faults=faults, rng_salt=rng_salt, tracer=tracer)
        if round_idx == 1 and rng_salt == 0:
            m = dict(m)
            m["loss_sum"] = float("nan")  # simulate a diverged round
        return m

    api.train_one_round = flaky
    api.train(guard=RoundGuard(max_retries=2))
    assert (1, 1) in entry_state, "guard must have retried round 1"
    assert params_equal(_fetch(entry_state[(1, 1)]),
                        _fetch(entry_state[(1, 0)]))


# ------------------------------------------------- committed budget ratios

def test_comms_budget_topk_twins_shrink_wire_4x():
    # the headline gate, pinned from the COMMITTED budgets (the same
    # numbers `python -m fedml_tpu.analysis --comms` re-measures and
    # ci_smoke gates): top-k moves >=4x fewer collective bytes than the
    # codec-off twin on both codec-armed programs
    with open(os.path.join(ROOT, "COMMS_BUDGET.json")) as f:
        budgets = json.load(f)
    pairs = {
        "tensor.round[tformer,f32,fedavg,2x4]":
            "tensor.round[tformer,f32,fedavg,2x4,topk64]",
        "buffered.admit[lr,f32]": "buffered.admit[lr,f32,topk16]",
    }
    for off_name, on_name in pairs.items():
        off = budgets[off_name]["collective_bytes"]
        on = budgets[on_name]["collective_bytes"]
        assert off >= 4 * on, (
            f"{on_name}: {on} bytes vs {off} codec-off — "
            f"shrink {off / on:.2f}x < 4x")
    # int8 twins are pinned too (they land just under 4x — the scale
    # sidecars tip the quota; docs/PERF.md documents the honest numbers)
    for name in ("tensor.round[tformer,f32,fedavg,2x4,int8]",
                 "buffered.admit[lr,f32,int8]"):
        assert name in budgets


def test_job_descriptor_reports_per_tenant_codec(ds16):
    from fedml_tpu.serving.job import JobDescriptor

    cfg = FedConfig(model="lr", comm_round=1, update_codec="int8")
    assert JobDescriptor("t", cfg, ds16).codec == "int8"
    assert JobDescriptor("t", FedConfig(model="lr", comm_round=1),
                         ds16).codec == "none"
