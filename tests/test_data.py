"""Data pipeline tests: loader contract across the dataset matrix."""

import numpy as np
import pytest

from fedml_tpu.data.registry import available_datasets, load_dataset


@pytest.mark.parametrize("name,clients,class_num", [
    ("cifar10", 8, 10),
    ("cifar100", 8, 100),
    ("cinic10", 8, 10),
    ("fmnist", 8, 10),
    ("adult", 6, 2),
    ("purchase100", 6, 100),
    ("har", 6, 6),
    ("chmnist", 6, 8),
])
def test_global_loaders_contract(name, clients, class_num):
    ds = load_dataset(name, client_num_in_total=clients, seed=0)
    assert ds.client_num == clients
    assert ds.class_num == class_num
    assert ds.train.x.shape[0] == clients
    assert ds.train.total_samples > 0
    nine = ds.as_nine_tuple()
    assert nine[0] == clients and nine[8] == class_num
    # partition covers with no duplicates across clients
    assert sum(nine[5].values()) == ds.train_data_num


@pytest.mark.parametrize("name,class_num", [
    ("fed_cifar100", 100),
    ("shakespeare", 90),
    ("fed_shakespeare", 90),
    ("stackoverflow_nwp", 10004),
    ("stackoverflow_lr", 500),
])
def test_natural_split_loaders(name, class_num):
    ds = load_dataset(name, client_num_in_total=12, seed=0)
    assert ds.client_num == 12
    assert ds.class_num == class_num
    assert ds.train.total_samples > 0
    assert ds.test_global[0].shape[0] > 0


def test_shakespeare_shapes():
    ds = load_dataset("shakespeare", client_num_in_total=6, seed=0)
    assert ds.train.x.shape[2] == 80  # [C, n_max, 80] int windows
    assert ds.train.y.ndim == 2  # next-char label per window
    ds2 = load_dataset("fed_shakespeare", client_num_in_total=6, seed=0)
    assert ds2.train.y.shape[2] == 80  # per-position targets


def test_stackoverflow_lr_multilabel():
    ds = load_dataset("stackoverflow_lr", client_num_in_total=6, seed=0)
    assert ds.train.y.shape[-1] == 500  # multi-hot tags
    assert set(np.unique(ds.train.y)).issubset({0.0, 1.0})


def test_dataset_registry_is_wide():
    names = available_datasets()
    for required in ("mnist", "femnist", "cifar10", "cifar100", "cinic10",
                     "fed_cifar100", "shakespeare", "fed_shakespeare",
                     "stackoverflow_nwp", "stackoverflow_lr", "synthetic",
                     "adult", "purchase100", "texas100", "har", "chmnist", "fmnist"):
        assert required in names, required


def test_rnn_nwp_end_to_end():
    """Tiny LSTM trains on the fed_shakespeare surrogate through the full
    engine (NWP loss path, per-position targets)."""

    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import NWPTrainer
    from fedml_tpu.models.rnn import RNN_OriginalFedAvg

    ds = load_dataset("fed_shakespeare", client_num_in_total=4, seed=0)
    cfg = FedConfig(comm_round=2, batch_size=8, lr=0.5, epochs=1,
                    client_num_in_total=4, client_num_per_round=4)
    module = RNN_OriginalFedAvg(vocab_size=90, embedding_dim=8, hidden_size=32,
                                per_position=True)
    api = FedAvgAPI(ds, cfg, NWPTrainer(module, pad_id=-1))
    hist = api.train()
    assert np.isfinite(hist[-1]["Test/Loss"])
    assert hist[-1]["Test/Loss"] < hist[0]["Test/Loss"]


# ------------------------------------------------------- acquisition tooling


def test_acquire_dry_run_lists_reference_urls(capsys):
    from fedml_tpu.data.acquire import main

    assert main(["fetch", "femnist", "--dry_run"]) == 0
    out = capsys.readouterr().out
    assert "fed_emnist.tar.bz2" in out and "https://" in out


def test_acquire_verify_detects_corruption(tmp_path, capsys):
    import json

    from fedml_tpu.data import acquire

    # forge a "downloaded" file + manifest, then corrupt the file
    d = tmp_path / "data"
    (d / "MNIST" / "raw").mkdir(parents=True)
    f = d / "MNIST" / "raw" / "train-images-idx3-ubyte.gz"
    f.write_bytes(b"payload")
    manifest = {"MNIST/raw/train-images-idx3-ubyte.gz":
                {"sha256": acquire._sha256(str(f)), "bytes": 7}}
    (d / f"mnist.{acquire.MANIFEST}").write_text(json.dumps(manifest))

    assert acquire.verify("mnist", str(d)) == 0
    f.write_bytes(b"tampered")
    assert acquire.verify("mnist", str(d)) == 1
    out = capsys.readouterr().out
    assert "CORRUPT" in out
    f.unlink()
    assert acquire.verify("mnist", str(d)) == 1
    assert acquire.verify("nonexistent", str(d)) == 2  # no manifest


def test_acquire_stats_runs_on_surrogate(capsys):
    from fedml_tpu.data.acquire import main

    assert main(["stats", "mnist", "--clients", "4"]) == 0
    out = capsys.readouterr().out
    assert "clients: 4" in out and "class histogram" in out


def test_download_wrappers_exist_and_call_acquire():
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1] / "data"
    wrappers = list(root.glob("*/download_*.sh"))
    assert len(wrappers) >= 6
    for w in wrappers:
        text = w.read_text()
        assert "fedml_tpu.data.acquire fetch" in text


def test_acquire_fetch_end_to_end_with_file_urls(tmp_path, monkeypatch):
    """fetch downloads (file:// stands in for https under zero egress),
    records the sha256 manifest, unpacks tarballs, and verify passes —
    the full acquisition cycle without network."""
    import json
    import tarfile

    from fedml_tpu.data import acquire

    # build a tiny "remote" tarball
    src = tmp_path / "remote"
    src.mkdir()
    payload = src / "fed_emnist_train.h5"
    payload.write_bytes(b"h5-bytes")
    tarball = src / "fed_emnist.tar.bz2"
    with tarfile.open(tarball, "w:bz2") as tf:
        tf.add(payload, arcname="fed_emnist_train.h5")

    monkeypatch.setitem(
        acquire.CATALOG, "femnist",
        [("fed_emnist.tar.bz2", tarball.as_uri(), "tar")])
    data_dir = tmp_path / "data"
    assert acquire.fetch("femnist", str(data_dir)) == 0
    # artifact + unpacked member + manifest all present
    assert (data_dir / "fed_emnist.tar.bz2").exists()
    assert (data_dir / "fed_emnist_train.h5").read_bytes() == b"h5-bytes"
    mpath = data_dir / f"femnist.{acquire.MANIFEST}"
    manifest = json.loads(mpath.read_text())
    assert manifest["fed_emnist.tar.bz2"]["bytes"] == tarball.stat().st_size
    assert acquire.verify("femnist", str(data_dir)) == 0
    # re-fetch skips the completed download (no .part leftovers) and says
    # out loud that it trusted the existing copy (ADVICE r4 acquire.py:160)
    assert acquire.fetch("femnist", str(data_dir)) == 0
    assert not list(data_dir.glob("*.part"))


def test_acquire_fetch_trusts_existing_file_loudly(tmp_path, monkeypatch, capsys):
    from fedml_tpu.data import acquire

    src = tmp_path / "remote.bin"
    src.write_bytes(b"artifact")
    monkeypatch.setitem(acquire.CATALOG, "mnist",
                        [("mnist.bin", src.as_uri(), None)])
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    (data_dir / "mnist.bin").write_bytes(b"stale local copy")
    assert acquire.fetch("mnist", str(data_dir)) == 0
    assert "trusting the local copy" in capsys.readouterr().out
    # the manifest records the trusted file's hash, i.e. what is on disk
    assert (data_dir / "mnist.bin").read_bytes() == b"stale local copy"


def test_acquire_fetch_rejects_html_interstitial(tmp_path, monkeypatch):
    """A Drive virus-scan page (or any HTML error page) must never be
    recorded as the artifact (ADVICE r4 acquire.py:66): fetch retries with
    the confirm token and, still getting HTML, refuses."""
    from fedml_tpu.data import acquire

    page = tmp_path / "interstitial"
    page.write_bytes(b"<!DOCTYPE html><html>Download anyway? confirm=abc123</html>")
    monkeypatch.setitem(
        acquire.CATALOG, "shakespeare",
        [("shakespeare/train/data.json",
          "https://docs.google.com/uc?export=download&id=XYZ", None)])
    calls = []

    def fake_retrieve(url, dst):
        calls.append(url)
        import shutil
        shutil.copy(page, dst)

    monkeypatch.setattr(acquire.urllib.request, "urlretrieve", fake_retrieve)
    data_dir = tmp_path / "data"
    with pytest.raises(RuntimeError, match="HTML page"):
        acquire.fetch("shakespeare", str(data_dir))
    # the retry carried the confirm token parsed from the page
    assert len(calls) == 2 and "confirm=abc123" in calls[1]
    # nothing blessed: no artifact, no manifest, no .part leftovers
    assert not (data_dir / "shakespeare" / "train" / "data.json").exists()
    assert not list(data_dir.rglob("*.part"))
    assert not (data_dir / f"shakespeare.{acquire.MANIFEST}").exists()

    # a leftover interstitial saved by a pre-guard run is refused, not
    # trusted into the manifest
    import shutil
    dst = data_dir / "shakespeare" / "train" / "data.json"
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.copy(page, dst)
    with pytest.raises(RuntimeError, match="delete it"):
        acquire.fetch("shakespeare", str(data_dir))


_MODERN_INTERSTITIAL = b"""<!DOCTYPE html><html><body>
<form id="download-form"
      action="https://drive.usercontent.google.com/download" method="get">
  <input type="hidden" name="id" value="XYZ">
  <input type="hidden" name="export" value="download">
  <input type="hidden" name="confirm" value="t">
  <input type="hidden" name="uuid" value="abc-123">
  <input type="submit" value="Download anyway">
</form></body></html>"""


def test_gdrive_retry_url_parses_modern_form(tmp_path):
    """The modern virus-scan page is a GET form to
    drive.usercontent.google.com with hidden inputs — the retry must
    reconstruct that exact request, not just tack confirm= on the old URL."""
    from fedml_tpu.data.acquire import _gdrive_retry_url

    page = tmp_path / "page.html"
    page.write_bytes(_MODERN_INTERSTITIAL)
    retry = _gdrive_retry_url(
        str(page), "https://docs.google.com/uc?export=download&id=XYZ")
    assert retry.startswith("https://drive.usercontent.google.com/download?")
    assert "id=XYZ" in retry and "confirm=t" in retry and "uuid=abc-123" in retry
    # the submit button must not leak into the query string
    assert "Download" not in retry


def test_acquire_fetch_retries_through_modern_interstitial(tmp_path, monkeypatch):
    """First response is the usercontent form page; the reconstructed retry
    returns the real artifact — fetch must succeed and bless the real bytes."""
    from fedml_tpu.data import acquire

    monkeypatch.setitem(
        acquire.CATALOG, "shakespeare",
        [("shakespeare/train/data.json",
          "https://docs.google.com/uc?export=download&id=XYZ", None)])
    calls = []

    def fake_retrieve(url, dst):
        calls.append(url)
        with open(dst, "wb") as f:
            f.write(_MODERN_INTERSTITIAL if len(calls) == 1
                    else b'{"users": []}')

    monkeypatch.setattr(acquire.urllib.request, "urlretrieve", fake_retrieve)
    data_dir = tmp_path / "data"
    assert acquire.fetch("shakespeare", str(data_dir)) == 0
    assert len(calls) == 2
    assert calls[1].startswith("https://drive.usercontent.google.com/download?")
    got = (data_dir / "shakespeare" / "train" / "data.json").read_bytes()
    assert got == b'{"users": []}'
    assert (data_dir / f"shakespeare.{acquire.MANIFEST}").exists()
