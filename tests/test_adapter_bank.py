"""Packed mmap adapter bank (graft-pfl): O(cohort) personalization pins.

The load-bearing claims:
  - zero row = identity: a fresh (all-zero) bank changes NOTHING — one
    personalized round produces bitwise-identical GLOBAL params to the
    personalization-off run, eager and pipelined alike;
  - the personalized drive is deterministic: two same-seed chaos runs
    write byte-identical bank shard files and end at bitwise-identical
    params, and the pipelined drive matches eager bitwise (the prefetch
    read-after-write seam re-gathers post-flush rows);
  - resume is exact: close the bank mid-run, `open_or_create` it again,
    finish the run — params AND shard bytes match the uninterrupted run;
  - resume validates geometry: wrong row count or a different adapter
    layout (other rank) is rejected, never silently reinterpreted;
  - chaos dead rows pass through: a dropped or quarantined client's
    personal row is bitwise UNCHANGED on disk after the round;
  - cluster mode (`adapter_clusters K`) drives a K-row bank — cohort row
    ids come from EMA-loss buckets, so millions of clients share K rows;
  - `packed_leaves.pack_rows`/`unpack_rows` roundtrip exactly (the same
    byte layout `EvictionStore` spills, factored out by this graft).
"""

import os
import tempfile

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.adapter_bank import (
    cluster_rows,
    open_or_create,
    read_side_columns,
)
from fedml_tpu.models.lora import maybe_wrap_lora
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.telemetry.client_ledger import create_ledger
from fedml_tpu.utils import packed_leaves


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


def _api(ds, rounds=3, personalize=True, **cfg_kwargs):
    cfg_kwargs.setdefault("lora_rank", 4)
    cfg = FedConfig(comm_round=rounds, batch_size=8, epochs=1, lr=0.05,
                    client_num_in_total=ds.client_num,
                    client_num_per_round=ds.client_num,
                    seed=0, ci=1, frequency_of_the_test=10 ** 9,
                    personalize=personalize, **cfg_kwargs)
    trainer = maybe_wrap_lora(
        ClassificationTrainer(create_model("lr", output_dim=ds.class_num)),
        cfg)
    return FedAvgAPI(ds, cfg, trainer)


def _template(api):
    return jax.tree.map(lambda l: np.zeros(l.shape, l.dtype),
                        jax.device_get(api.global_variables["params"]))


def _bitwise_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _bank_file_bytes(root):
    return {fn: open(os.path.join(root, fn), "rb").read()
            for fn in sorted(os.listdir(root))}


def _drive(ds, bank_dir, rounds=3, chaos=None, ledger=None, **cfg_kwargs):
    """Fresh personalized api + bank at `bank_dir`, full drive; returns
    final params (bank closed — its state is all on disk)."""
    api = _api(ds, rounds=rounds, **cfg_kwargs)
    bank = open_or_create(bank_dir, ds.client_num, _template(api))
    try:
        api.train(chaos=chaos, ledger=ledger, bank=bank)
    finally:
        bank.close()
    return jax.device_get(api.global_variables)


# ------------------------------------------------------ zero row = identity

@pytest.mark.parametrize("cfg_kwargs", [
    pytest.param({}, id="eager"),
    pytest.param({"pipeline_depth": 2}, id="pipelined-depth2"),
])
def test_fresh_bank_round_matches_off_bitwise(ds8, tmp_path, cfg_kwargs):
    """An all-zero bank is the personalization identity: effective params
    are gv + 0, so one personalized round moves the GLOBAL model to
    bitwise the same place as the personalization-off program."""
    api_off = _api(ds8, rounds=1, personalize=False, **cfg_kwargs)
    api_off.train()
    params_on = _drive(ds8, str(tmp_path / "bank"), rounds=1, **cfg_kwargs)
    assert _bitwise_equal(params_on, jax.device_get(api_off.global_variables))


# ----------------------------------------------------------- determinism

_CHAOS = FaultPlan(seed=3, drop_rate=0.2, nan_rate=0.1)


def test_same_seed_chaos_runs_yield_byte_identical_shards(ds8, tmp_path):
    params = []
    dirs = [str(tmp_path / "bank_a"), str(tmp_path / "bank_b")]
    for d in dirs:
        params.append(_drive(ds8, d, rounds=4, chaos=_CHAOS))
    assert _bitwise_equal(*params)
    bytes_a, bytes_b = map(_bank_file_bytes, dirs)
    assert sorted(bytes_a) == sorted(bytes_b)
    for fn in bytes_a:
        assert bytes_a[fn] == bytes_b[fn], f"{fn} differs across runs"


def test_pipelined_personalized_matches_eager_bitwise(ds8, tmp_path):
    """The pipelined drive flushes records (scattering the round's rows)
    and RE-GATHERS prefetched personal rows before dispatch, so the
    depth-2 pipeline cannot train round t+1 on round t-1's adapters."""
    eager_dir = str(tmp_path / "bank_eager")
    pipe_dir = str(tmp_path / "bank_pipe")
    params_eager = _drive(ds8, eager_dir, rounds=4, chaos=_CHAOS)
    params_pipe = _drive(ds8, pipe_dir, rounds=4, chaos=_CHAOS,
                         pipeline_depth=2)
    assert _bitwise_equal(params_eager, params_pipe)
    bytes_e, bytes_p = map(_bank_file_bytes, (eager_dir, pipe_dir))
    for fn in bytes_e:
        assert bytes_e[fn] == bytes_p[fn], f"{fn} differs eager vs pipelined"


# ---------------------------------------------------------------- resume

def test_resume_continues_bitwise(ds8, tmp_path):
    """Rounds 0-1, close, open_or_create again, rounds 2-3 == one
    uninterrupted 4-round run — params and shard bytes both."""
    def manual(api, bank, rounds):
        for r in rounds:
            api.train_one_round(r)
            block = api._bank_block(r)
            if block is not None:
                bank.apply(jax.device_get(block))
        bank.flush()

    solo_dir = str(tmp_path / "bank_solo")
    api_solo = _api(ds8, rounds=4)
    bank_solo = open_or_create(solo_dir, ds8.client_num, _template(api_solo))
    api_solo.bank = bank_solo
    manual(api_solo, bank_solo, range(4))
    bank_solo.close()

    split_dir = str(tmp_path / "bank_split")
    api_split = _api(ds8, rounds=4)
    tmpl = _template(api_split)
    bank = open_or_create(split_dir, ds8.client_num, tmpl)
    api_split.bank = bank
    manual(api_split, bank, range(2))
    bank.close()
    bank = open_or_create(split_dir, ds8.client_num, tmpl)  # resume
    assert bank.rows_materialized > 0  # restored from the mat columns
    api_split.bank = bank
    manual(api_split, bank, range(2, 4))
    bank.close()

    assert _bitwise_equal(api_solo.global_variables,
                          api_split.global_variables)
    bytes_solo, bytes_split = map(_bank_file_bytes, (solo_dir, split_dir))
    for fn in bytes_solo:
        assert bytes_solo[fn] == bytes_split[fn], f"{fn} differs on resume"


def test_open_or_create_rejects_count_and_layout_mismatch(ds8, tmp_path):
    root = str(tmp_path / "bank")
    api = _api(ds8)
    bank = open_or_create(root, ds8.client_num, _template(api))
    bank.close()
    with pytest.raises(ValueError, match="holds 8 rows"):
        open_or_create(root, ds8.client_num + 1, _template(api))
    other = _api(ds8, lora_rank=2)  # different rank -> different row layout
    with pytest.raises(ValueError, match="different .* layout"):
        open_or_create(root, ds8.client_num, _template(other))


# ------------------------------------------------- chaos dead-row passthrough

def test_chaos_dead_rows_pass_through_unchanged(ds8, tmp_path):
    """Pre-seed every row with a sentinel, run ONE chaos round: exactly
    the healthy participants' rows move; a dropped or quarantined
    client's row is bitwise the sentinel still (its next gather must see
    the adapters it last trained, not a half-round)."""
    chaos = FaultPlan(seed=3, drop_rate=0.3, nan_rate=0.2)
    api = _api(ds8, rounds=1)
    tmpl = _template(api)
    bank = open_or_create(str(tmp_path / "bank"), ds8.client_num, tmpl)
    sentinel = jax.tree.map(
        lambda l: np.full((ds8.client_num,) + l.shape, 0.5, l.dtype), tmpl)
    bank.scatter(np.arange(ds8.client_num), sentinel)
    ledger = create_ledger(str(tmp_path / "led"), ds8.client_num)
    try:
        api.train(chaos=chaos, ledger=ledger, bank=bank)
        healthy = ((ledger.column("participation_count") > 0)
                   & (ledger.column("quarantine_count") == 0))
        assert 0 < healthy.sum() < ds8.client_num  # the plan actually bites
        rows = bank.gather(np.arange(ds8.client_num))
        leaves = [np.asarray(l) for l in jax.tree.leaves(rows)]
        for c in range(ds8.client_num):
            unchanged = all(np.array_equal(l[c], np.full_like(l[c], 0.5))
                            for l in leaves)
            assert unchanged == (not healthy[c]), (
                f"client {c}: healthy={bool(healthy[c])} but row "
                f"{'unchanged' if unchanged else 'moved'}")
    finally:
        ledger.close()
        bank.close()


# ------------------------------------------------------------- cluster mode

def test_cluster_mode_drives_k_row_bank(ds8, tmp_path):
    """adapter_clusters=K: the bank holds K rows total and every cohort
    maps to EMA-loss buckets — row ids never exceed K-1 no matter the
    client population."""
    k = 3
    api = _api(ds8, rounds=3, adapter_clusters=k)
    bank = open_or_create(str(tmp_path / "bank"), k, _template(api))
    try:
        api.train(bank=bank)
        assert bank.num_rows == k
        assert 0 < bank.rows_materialized <= k
    finally:
        bank.close()
    side = read_side_columns(str(tmp_path / "bank"))
    assert side["mat"].shape == (k,)
    # the static bucketer itself: edges span [0, 4] and clip beyond
    ema = np.array([0.0, 0.1, 1.5, 3.9, 100.0], np.float32)
    ids = cluster_rows(ema, k)
    assert ids.min() >= 0 and ids.max() == k - 1
    assert np.array_equal(cluster_rows(ema, k), ids)  # pure in its inputs


# -------------------------------------------------- packed_leaves roundtrip

def test_pack_unpack_rows_roundtrip_exact():
    """pack_rows -> unpack_rows is exact at mixed dtypes/shapes, and row c
    is byte-equal to what spill_leaves writes for client c's tree."""
    rng = np.random.RandomState(0)
    leaves = [rng.randn(4, 3, 2).astype(np.float32),
              rng.randint(-9, 9, size=(4, 5)).astype(np.int32),
              rng.randn(4, 2).astype(np.float64)]
    per_row = [[l[c] for l in leaves] for c in range(4)]
    entries, row_nbytes = packed_leaves.leaf_layout(per_row[0])
    buf = packed_leaves.pack_rows(leaves, entries, row_nbytes)
    assert buf.shape == (4, row_nbytes) and buf.dtype == np.uint8
    out = packed_leaves.unpack_rows(buf, entries)
    for a, b in zip(leaves, out):
        assert a.dtype == b.dtype and np.array_equal(a, b)
    # byte-parity with the spill writer, row by row
    with tempfile.TemporaryDirectory() as d:
        for c in range(4):
            p = os.path.join(d, f"row{c}.bin")
            packed_leaves.spill_leaves(p, per_row[c])
            assert open(p, "rb").read() == buf[c].tobytes()
