"""Fault-tolerant federated rounds (ISSUE 4): participation masking +
non-finite quarantine inside the jitted round programs, the seeded chaos
harness, the loss-spike rollback guard, and the shared retry policy.

The load-bearing claims, each asserted bitwise where the design promises
bitwise:
  - a masked vmap round equals aggregating the surviving cohort alone on the
    same per-client rng table (zero-insertion exactness);
  - a masked shard_map round equals the unmasked round with the dropped
    clients' weights zeroed, on identical geometry, for every aggregator;
  - 100% drop/quarantine degrades to a no-op on global variables AND
    aggregator state (FedOpt momentum included) — no NaN escape;
  - a FaultPlan is a pure function of (seed, round) — two runs share the
    schedule and the final metrics;
  - RetryPolicy backoff is exactly the capped-exponential full-jitter
    sequence, deterministic under injected clock/sleep/rng.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.aggregators import make_aggregator
from fedml_tpu.algorithms.engine import build_local_update, build_round_fn
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan, apply_faults, summarize
from fedml_tpu.robustness.guard import RoundGuard
from fedml_tpu.robustness.retry import RetryError, RetryPolicy, call_with_retry


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


def _all_finite(tree):
    return all(bool(jnp.all(jnp.isfinite(l))) for l in jax.tree.leaves(tree)
               if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact))


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


@pytest.fixture(scope="module")
def ds16():
    return load_dataset("mnist", client_num_in_total=16,
                        partition_method="homo", seed=1)


def _setup(ds, **cfg_kwargs):
    cfg = FedConfig(batch_size=8, epochs=1, lr=0.05,
                    client_num_in_total=ds.client_num,
                    client_num_per_round=ds.client_num, **cfg_kwargs)
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    gv = trainer.init(jax.random.PRNGKey(0), jnp.asarray(ds.train.x[:1, 0]))
    return cfg, trainer, gv


# ---------------------------------------------------------------- vmap engine

def test_vmap_masked_round_equals_surviving_cohort_bitwise(ds8):
    """Dropped rows (even carrying NaN garbage) contribute exact +0.0 terms,
    so the masked round is BITWISE the surviving cohort aggregated alone on
    the same per-client rng streams (split(rng, C)[survivors])."""
    cfg, trainer, gv = _setup(ds8)
    agg = make_aggregator("fedavg", cfg)
    state = agg.init_state(gv)
    round_fn = build_round_fn(trainer, cfg, agg)
    rng = jax.random.PRNGKey(7)

    x, y, counts = ds8.train.select(np.arange(8))
    surv = np.array([0, 2, 3, 6])
    part = np.zeros(8, bool)
    part[surv] = True
    x_bad = np.array(x, np.float32)
    x_bad[~part] = np.nan  # dropped clients' content must be irrelevant

    g_masked, s_masked, m = round_fn(
        gv, state, jnp.asarray(x_bad), jnp.asarray(y), jnp.asarray(counts),
        rng, jnp.asarray(part))
    assert float(m["participated_count"]) == len(surv)
    assert float(m["quarantined_count"]) == 0.0
    assert _all_finite(g_masked)

    # cohort-alone reference on the SAME rng table rows
    keys = jax.random.split(rng, 8)[surv]
    local = jax.jit(jax.vmap(build_local_update(trainer, cfg),
                             in_axes=(None, 0, 0, 0, 0)))
    res = local(gv, jnp.asarray(x[surv]), jnp.asarray(y[surv]),
                jnp.asarray(counts[surv]), keys)
    g_ref, s_ref = agg(gv, res, jnp.asarray(counts[surv], jnp.float32).astype(
        jnp.float32), rng, state)
    assert _bitwise_equal(g_masked, g_ref)
    assert _bitwise_equal(s_masked, s_ref)


def test_vmap_all_ones_mask_is_bitwise_legacy(ds8):
    cfg, trainer, gv = _setup(ds8)
    agg = make_aggregator("fedavg", cfg)
    round_fn = build_round_fn(trainer, cfg, agg)
    rng = jax.random.PRNGKey(5)
    x, y, counts = ds8.train.select(np.arange(8))
    args = (gv, agg.init_state(gv), jnp.asarray(x), jnp.asarray(y),
            jnp.asarray(counts), rng)
    g0, s0, m0 = round_fn(*args)
    g1, s1, m1 = round_fn(*args, jnp.ones(8, bool))
    assert _bitwise_equal(g0, g1)
    assert _bitwise_equal(s0, s1)
    # the masked specialization is a different XLA program, so metric SUM
    # reduction order may differ in the last ulp — equality is mathematical
    for k in m0:  # legacy metric keys unchanged; masked adds the two counts
        np.testing.assert_allclose(np.asarray(m0[k]), np.asarray(m1[k]),
                                   rtol=1e-6)
    assert float(m1["participated_count"]) == 8.0


def test_vmap_nan_clients_are_quarantined(ds8):
    """Participation all-true, but clients trained on NaN inputs produce
    non-finite variables — the aggregator must zero them out, count them,
    and keep the global finite."""
    cfg, trainer, gv = _setup(ds8)
    agg = make_aggregator("fedavg", cfg)
    round_fn = build_round_fn(trainer, cfg, agg)
    rng = jax.random.PRNGKey(9)
    x, y, counts = ds8.train.select(np.arange(8))
    poisoned = np.array([1, 4])
    x_bad = np.array(x, np.float32)
    x_bad[poisoned] = np.nan
    g, s, m = round_fn(gv, agg.init_state(gv), jnp.asarray(x_bad),
                       jnp.asarray(y), jnp.asarray(counts), rng,
                       jnp.ones(8, bool))
    assert float(m["quarantined_count"]) == len(poisoned)
    assert float(m["participated_count"]) == 8 - len(poisoned)
    assert _all_finite(g)


@pytest.mark.parametrize("agg_name", ["fedavg", "fedopt"])
def test_vmap_all_quarantined_round_is_noop(ds8, agg_name):
    """100% drop: global AND aggregator state pass through unchanged — the
    FedOpt server step on a pseudo-gradient of zeros must not fire."""
    cfg, trainer, gv = _setup(ds8, server_optimizer="adam", server_lr=0.01)
    agg = make_aggregator(agg_name, cfg)
    state = agg.init_state(gv)
    round_fn = build_round_fn(trainer, cfg, agg)
    x, y, counts = ds8.train.select(np.arange(8))
    g, s, m = round_fn(gv, state, jnp.asarray(x), jnp.asarray(y),
                       jnp.asarray(counts), jax.random.PRNGKey(1),
                       jnp.zeros(8, bool))
    assert _bitwise_equal(g, gv)
    assert _bitwise_equal(s, state)
    assert float(m["participated_count"]) == 0.0


# ------------------------------------------------------------ shard_map mesh

@pytest.mark.parametrize("agg_name", ["fedavg", "fedopt", "robust", "fednova"])
def test_sharded_masked_equals_zero_weight_cohort_bitwise(ds16, agg_name):
    """8-device mesh: the masked round (NaN garbage in dropped rows, true
    counts) is BITWISE the unmasked round on identical geometry with the
    dropped clients' counts zeroed and their rows cleaned — the psum partial
    sums see exactly the same terms."""
    from fedml_tpu.parallel import build_sharded_round_fn, make_mesh

    cfg, trainer, gv = _setup(ds16, server_optimizer="sgd", server_lr=1.0)
    agg = make_aggregator(agg_name, cfg)
    state = agg.init_state(gv)
    mesh = make_mesh((8,), ("clients",))
    round_fn = build_sharded_round_fn(trainer, cfg, agg, mesh)
    rng = jax.random.PRNGKey(11)

    x, y, counts = ds16.train.select(np.arange(16))
    part = np.arange(16) % 2 == 0  # drop the odd clients
    x_bad = np.array(x, np.float32)
    x_bad[~part] = np.nan

    g_m, s_m, m = round_fn(gv, state, jnp.asarray(x_bad), jnp.asarray(y),
                           jnp.asarray(counts), rng, jnp.asarray(part))
    assert float(m["participated_count"]) == part.sum()
    assert _all_finite(g_m)

    counts_zeroed = np.where(part, counts, 0).astype(counts.dtype)
    g_r, s_r, _ = round_fn(gv, state, jnp.asarray(x), jnp.asarray(y),
                           jnp.asarray(counts_zeroed), rng)
    assert _bitwise_equal(g_m, g_r)
    assert _bitwise_equal(s_m, s_r)


@pytest.mark.parametrize("agg_name", ["fedavg", "fedopt"])
def test_sharded_all_quarantined_round_is_noop(ds16, agg_name):
    from fedml_tpu.parallel import build_sharded_round_fn, make_mesh

    cfg, trainer, gv = _setup(ds16, server_optimizer="adam", server_lr=0.01)
    agg = make_aggregator(agg_name, cfg)
    state = agg.init_state(gv)
    mesh = make_mesh((8,), ("clients",))
    round_fn = build_sharded_round_fn(trainer, cfg, agg, mesh)
    x, y, counts = ds16.train.select(np.arange(16))
    x_bad = np.full_like(np.asarray(x, np.float32), np.nan)
    g, s, m = round_fn(gv, state, jnp.asarray(x_bad), jnp.asarray(y),
                       jnp.asarray(counts), jax.random.PRNGKey(2),
                       jnp.ones(16, bool))
    # every client trained on NaN -> all quarantined -> no-op, no NaN escape
    assert float(m["quarantined_count"]) == 16.0
    assert _bitwise_equal(g, gv)
    assert _bitwise_equal(s, state)


def test_hierarchical_masked_equals_zero_weight_cohort_bitwise(ds16):
    from fedml_tpu.parallel import (
        build_sharded_hierarchical_round_fn,
        make_mesh,
    )

    cfg, trainer, gv = _setup(ds16)
    mesh = make_mesh((2, 4), ("groups", "clients"))
    round_fn = build_sharded_hierarchical_round_fn(trainer, cfg, mesh,
                                                   group_comm_round=2)
    rng = jax.random.PRNGKey(13)
    x, y, counts = ds16.train.select(np.arange(16))
    x = np.asarray(x).reshape((2, 8) + x.shape[1:])
    y = np.asarray(y).reshape((2, 8) + y.shape[1:])
    counts = np.asarray(counts).reshape(2, 8)
    part = np.ones((2, 8), bool)
    part[0, 1] = part[1, 5] = part[1, 6] = False  # 13 participate
    x_bad = np.array(x, np.float32)
    x_bad[~part] = np.nan

    g_m, m = round_fn(gv, jnp.asarray(x_bad), jnp.asarray(y),
                      jnp.asarray(counts), rng, jnp.asarray(part))
    assert float(m["participated_count"]) == 13.0
    assert _all_finite(g_m)

    counts_zeroed = np.where(part, counts, 0).astype(counts.dtype)
    g_r, _ = round_fn(gv, jnp.asarray(x), jnp.asarray(y),
                      jnp.asarray(counts_zeroed), rng)
    assert _bitwise_equal(g_m, g_r)


def test_hierarchical_poisoned_client_quarantines_its_group(ds16):
    """Quarantine is GROUP-granular at the cloud step: one NaN client
    contaminates its group's running mean, so the whole group is dropped."""
    from fedml_tpu.parallel import (
        build_sharded_hierarchical_round_fn,
        make_mesh,
    )

    cfg, trainer, gv = _setup(ds16)
    mesh = make_mesh((2, 4), ("groups", "clients"))
    round_fn = build_sharded_hierarchical_round_fn(trainer, cfg, mesh,
                                                   group_comm_round=2)
    x, y, counts = ds16.train.select(np.arange(16))
    x = np.asarray(x, np.float32).reshape((2, 8) + x.shape[1:])
    y = np.asarray(y).reshape((2, 8) + y.shape[1:])
    counts = np.asarray(counts).reshape(2, 8)
    x[0, 3] = np.nan  # one poisoned client in group 0

    g, m = round_fn(gv, jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts),
                    jax.random.PRNGKey(3), jnp.ones((2, 8), bool))
    assert float(m["quarantined_count"]) == 8.0  # all of group 0
    assert float(m["participated_count"]) == 8.0  # all of group 1
    assert _all_finite(g)

    # every group poisoned -> no-op
    x[1, 0] = np.nan
    g2, m2 = round_fn(gv, jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts),
                      jax.random.PRNGKey(3), jnp.ones((2, 8), bool))
    assert _bitwise_equal(g2, gv)
    assert float(m2["participated_count"]) == 0.0


# -------------------------------------------------------------- chaos harness

def test_fault_plan_is_deterministic_and_disjoint():
    plan = FaultPlan(seed=3, drop_rate=0.3, nan_rate=0.2, corrupt_rate=0.1)
    for r in range(5):
        a, b = plan.events(r, 64), plan.events(r, 64)
        np.testing.assert_array_equal(a.participation, b.participation)
        np.testing.assert_array_equal(a.nan_mask, b.nan_mask)
        np.testing.assert_array_equal(a.corrupt_mask, b.corrupt_mask)
        # a dropped client cannot also be nan/corrupt, nor nan also corrupt
        assert not np.any(~a.participation & a.nan_mask)
        assert not np.any(~a.participation & a.corrupt_mask)
        assert not np.any(a.nan_mask & a.corrupt_mask)
        assert a.dropped == int((~a.participation).sum())
    # schedules differ across rounds (64 clients at 30% drop: certain)
    assert any(
        not np.array_equal(plan.events(0, 64).participation,
                           plan.events(r, 64).participation)
        for r in range(1, 5))


def test_fault_plan_overrides_and_apply():
    plan = FaultPlan(seed=0, drop_rate=0.0,
                     overrides={2: {"drop_rate": 1.0, "nan_rate": 0.0}})
    assert plan.events(1, 8).participation.all()
    assert not plan.events(2, 8).participation.any()

    ev = FaultPlan(seed=1, nan_rate=0.5).events(0, 16)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    out = apply_faults(ev, x)
    assert np.isnan(out[ev.nan_mask]).all()
    np.testing.assert_array_equal(out[~ev.nan_mask], x[~ev.nan_mask])
    s = summarize(ev)
    assert s["chaos_nan"] == int(ev.nan_mask.sum())


def test_chaos_training_is_deterministic_end_to_end(ds8):
    """Acceptance: two fixed-seed chaos runs produce the identical fault
    schedule, metrics, and final parameters (bitwise)."""
    def run():
        cfg = FedConfig(dataset="mnist", model="lr", comm_round=3,
                        batch_size=8, lr=0.05, client_num_in_total=8,
                        client_num_per_round=8, seed=0)
        trainer = ClassificationTrainer(
            create_model("lr", output_dim=ds8.class_num))
        api = FedAvgAPI(ds8, cfg, trainer)
        hist = api.train(chaos=FaultPlan(seed=4, drop_rate=0.3, nan_rate=0.2))
        return api.global_variables, hist

    g1, h1 = run()
    g2, h2 = run()
    assert _bitwise_equal(g1, g2)
    for r1, r2 in zip(h1, h2):
        for k in ("chaos_dropped", "chaos_nan", "participated_count",
                  "quarantined_count"):
            assert r1[k] == r2[k]
    assert _all_finite(g1)
    # the schedule actually dropped somebody somewhere in 3 rounds
    assert sum(r["chaos_dropped"] for r in h1) > 0


def test_chaos_with_fast_sampling_is_deterministic(ds16):
    """Satellite (ISSUE 9): the O(cohort) Feistel sampler composed with a
    seeded chaos plan stays end-to-end deterministic — two runs agree
    bitwise on the sampled cohorts, the participation masks, AND the final
    model. Recorded at the stage_fn seam both drive loops share."""
    def run():
        cfg = FedConfig(dataset="mnist", model="lr", comm_round=3,
                        batch_size=8, lr=0.05, client_num_in_total=16,
                        client_num_per_round=8, seed=0, fast_sampling=True)
        trainer = ClassificationTrainer(
            create_model("lr", output_dim=ds16.class_num))
        api = FedAvgAPI(ds16, cfg, trainer)
        staged = {}
        orig = api.stage_fn

        def recording(round_idx, **kw):
            cohort = orig(round_idx, **kw)
            staged[round_idx] = (
                np.asarray(cohort.client_idx).copy(),
                None if cohort.faults is None
                else np.asarray(cohort.faults.participation).copy())
            return cohort

        api.stage_fn = recording
        api.train(chaos=FaultPlan(seed=4, drop_rate=0.3, nan_rate=0.2))
        return api.global_variables, staged

    g1, s1 = run()
    g2, s2 = run()
    assert sorted(s1) == sorted(s2) == [0, 1, 2]
    for r in s1:
        idx1, mask1 = s1[r]
        idx2, mask2 = s2[r]
        np.testing.assert_array_equal(idx1, idx2)       # same cohort
        np.testing.assert_array_equal(mask1, mask2)     # same chaos mask
        assert len(idx1) == 8 and len(set(idx1.tolist())) == 8
    assert _bitwise_equal(g1, g2)
    assert _all_finite(g1)
    # the composed schedule actually exercised a drop somewhere
    assert any(not s1[r][1].all() for r in s1)


# ---------------------------------------------------------------- round guard

def test_round_guard_verdicts():
    guard = RoundGuard(spike_factor=4.0, window=8, min_history=3)
    for r, loss in enumerate([1.0, 0.9, 0.8]):
        assert guard.inspect(r, loss).ok
    assert not guard.inspect(3, float("nan")).ok
    assert not guard.inspect(3, 100.0).ok  # > 4x median(1.0, 0.9, 0.8)
    # the rejected spike must not have poisoned its own baseline
    assert not guard.inspect(4, 50.0).ok
    assert guard.inspect(5, 0.7).ok
    bad_tree = {"w": jnp.array([1.0, float("inf")])}
    assert not guard.inspect(6, 0.6, bad_tree).ok
    guard.reset()
    assert guard.inspect(0, 1000.0).ok  # no history -> no spike baseline


def test_guard_rolls_back_and_retries_with_fresh_rng(ds8):
    """API-level rollback: a poisoned round is rolled back through the
    Checkpointable snapshot and re-run with rng_salt=retries; the retried
    round starts from the bitwise pre-round state."""
    cfg = FedConfig(dataset="mnist", model="lr", comm_round=3, batch_size=8,
                    lr=0.05, client_num_in_total=8, client_num_per_round=8,
                    seed=0)
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds8.class_num))
    api = FedAvgAPI(ds8, cfg, trainer)
    orig = api.train_one_round
    calls = []
    entry_vars = {}

    def flaky(round_idx, faults=None, rng_salt=0, tracer=None):
        calls.append((round_idx, rng_salt))
        entry_vars[(round_idx, rng_salt)] = api.global_variables
        m = orig(round_idx, faults=faults, rng_salt=rng_salt, tracer=tracer)
        if round_idx == 1 and rng_salt == 0:
            m = dict(m)
            m["loss_sum"] = float("nan")  # simulate a diverged round
        return m

    api.train_one_round = flaky
    hist = api.train(guard=RoundGuard(max_retries=2))
    assert (1, 0) in calls and (1, 1) in calls  # retried exactly once
    assert (1, 2) not in calls
    # the retry started from the rolled-back (pre-round-1) state
    assert _bitwise_equal(entry_vars[(1, 1)], entry_vars[(1, 0)])
    assert len(hist) == 3
    assert any(r.get("guard_retries") == 1 for r in hist)
    assert _all_finite(api.global_variables)


# ----------------------------------------------------------------- retry loop

class _FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def sleep(self, d):
        self.sleeps.append(d)
        self.t += d

    def __call__(self):
        return self.t


class _FixedRng(random.Random):
    """random() always returns the same fraction — jitter becomes exact."""

    def __init__(self, frac):
        super().__init__(0)
        self._frac = frac

    def random(self):
        return self._frac


def test_retry_backoff_sequence_no_jitter():
    clock = _FakeClock()
    policy = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                         max_delay=0.5, jitter=False,
                         retryable=(ConnectionError,))
    attempts = []

    def fn():
        attempts.append(clock())
        if len(attempts) < 5:
            raise ConnectionError("nope")
        return "ok"

    assert call_with_retry(fn, policy=policy, sleep=clock.sleep,
                           clock=clock) == "ok"
    # capped exponential: 0.1, 0.2, 0.4, then capped at 0.5
    assert clock.sleeps == [0.1, 0.2, 0.4, 0.5]


def test_retry_full_jitter_uses_injected_rng():
    clock = _FakeClock()
    policy = RetryPolicy(max_attempts=3, base_delay=1.0, multiplier=2.0,
                         max_delay=10.0, jitter=True,
                         retryable=(ConnectionError,))

    def fn():
        raise ConnectionError("always")

    with pytest.raises(RetryError) as ei:
        call_with_retry(fn, policy=policy, sleep=clock.sleep, clock=clock,
                        rng=_FixedRng(0.5))
    # uniform(0, cap) with rng=0.5 -> half of 1.0, 2.0; no sleep after final
    assert clock.sleeps == [0.5, 1.0]
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, ConnectionError)


def test_retry_deadline_clamps_then_stops():
    clock = _FakeClock()
    policy = RetryPolicy(max_attempts=10, base_delay=4.0, multiplier=2.0,
                         max_delay=100.0, jitter=False, deadline=10.0,
                         retryable=(ConnectionError,))
    calls = []

    def fn():
        calls.append(clock())
        raise ConnectionError("down")

    with pytest.raises(RetryError) as ei:
        call_with_retry(fn, policy=policy, sleep=clock.sleep, clock=clock)
    # sleeps 4, then the 8s draw is CLAMPED to the 6s remaining budget (the
    # deadline buys a third attempt instead of being forfeited); at t=10 no
    # budget remains -> stop at attempt 3
    assert clock.sleeps == [4.0, 6.0]
    assert calls == [0.0, 4.0, 10.0]
    assert ei.value.attempts == 3


def test_retry_deadline_never_overshot_even_with_jitter():
    """Regression for the backoff-overshoot bug: whatever the jitter draws,
    total sleep never exceeds the deadline — on the injected clock the loop
    lands exactly on it, not past it."""
    clock = _FakeClock()
    policy = RetryPolicy(max_attempts=10, base_delay=8.0, multiplier=2.0,
                         max_delay=100.0, jitter=True, deadline=10.0,
                         retryable=(ConnectionError,))

    def fn():
        raise ConnectionError("down")

    with pytest.raises(RetryError) as ei:
        call_with_retry(fn, policy=policy, sleep=clock.sleep, clock=clock,
                        rng=_FixedRng(1.0))  # jitter always draws the cap
    # draws 8 (fits), then 16 clamped to the 2s remaining
    assert clock.sleeps == [8.0, 2.0]
    assert sum(clock.sleeps) == policy.deadline
    assert clock() == 10.0  # never slept past the deadline
    assert ei.value.attempts == 3


def test_retry_non_retryable_passes_through():
    def fn():
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        call_with_retry(fn, policy=RetryPolicy(retryable=(ConnectionError,)),
                        sleep=lambda d: None)


def test_retry_abort_short_circuits():
    clock = _FakeClock()

    with pytest.raises(RetryError) as ei:
        call_with_retry(lambda: "never", policy=RetryPolicy(),
                        sleep=clock.sleep, clock=clock, abort=lambda: True)
    assert ei.value.attempts == 0

    state = {"n": 0}

    def fn():
        state["n"] += 1
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        call_with_retry(fn,
                        policy=RetryPolicy(retryable=(ConnectionError,),
                                           jitter=False),
                        sleep=clock.sleep, clock=clock,
                        abort=lambda: state["n"] >= 1)
    assert state["n"] == 1  # aborted before the first backoff sleep


def test_retry_passes_args_and_returns_value():
    assert call_with_retry(lambda a, b=0: a + b, 2, b=3,
                           policy=RetryPolicy(max_attempts=1)) == 5


# ------------------------------------------------------------ download retry

def test_download_retries_flaky_fetcher_then_succeeds(tmp_path):
    from fedml_tpu.data.acquire import _download

    clock = _FakeClock()
    state = {"calls": 0}

    def flaky_fetcher(url, dst):
        state["calls"] += 1
        if state["calls"] < 3:
            raise ConnectionResetError("flaky network")
        with open(dst, "wb") as f:
            f.write(b"artifact-bytes")

    dst = str(tmp_path / "artifact.bin")
    _download("http://example.invalid/a.bin", dst, fetcher=flaky_fetcher,
              policy=RetryPolicy(max_attempts=4, base_delay=0.1,
                                 jitter=False, retryable=(OSError,)),
              sleep=clock.sleep)
    assert state["calls"] == 3
    assert clock.sleeps == [0.1, 0.2]
    with open(dst, "rb") as f:
        assert f.read() == b"artifact-bytes"


def test_download_gives_up_after_budget(tmp_path):
    from fedml_tpu.data.acquire import _download

    def always_down(url, dst):
        raise ConnectionResetError("still down")

    with pytest.raises(RetryError) as ei:
        _download("http://example.invalid/a.bin", str(tmp_path / "x"),
                  fetcher=always_down,
                  policy=RetryPolicy(max_attempts=3, base_delay=0.01,
                                     jitter=False, retryable=(OSError,)),
                  sleep=lambda d: None)
    assert ei.value.attempts == 3


def test_download_permanent_http_error_not_retried(tmp_path):
    import urllib.error

    from fedml_tpu.data.acquire import _download

    state = {"calls": 0}

    def gone(url, dst):
        state["calls"] += 1
        raise urllib.error.HTTPError(url, 404, "Not Found", {}, None)

    with pytest.raises(RuntimeError, match="HTTP 404"):
        _download("http://example.invalid/gone.bin", str(tmp_path / "x"),
                  fetcher=gone, sleep=lambda d: None)
    assert state["calls"] == 1
