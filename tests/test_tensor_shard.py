"""Tensor-parallel round tests on the forced 2x4 ('clients','tensor') mesh.

The contract under test (parallel/tensor.py): a round whose params and
aggregator state live tensor-sharded is BIT-IDENTICAL in f32 to the same
round built with REPLICATED_RULES — the gather at round entry and the
slice before the client psums are pure data movement, and slicing commutes
exactly with every elementwise aggregation rule. Plus: spec resolution
(divisibility demotion), per-device byte accounting, and the engine seam.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as PS

from fedml_tpu.algorithms.aggregators import make_aggregator
from fedml_tpu.algorithms.engine import build_round_fn
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer, NWPTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.parallel import TensorSharding, make_tensor_mesh
from fedml_tpu.parallel.tensor import (
    REPLICATED_RULES,
    build_tensor_round_fn,
    resolve_param_specs,
    rules_for_model,
)


@pytest.fixture(scope="module")
def mesh24():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_tensor_mesh(4)


@pytest.fixture(scope="module")
def ds16():
    return load_dataset("mnist", client_num_in_total=16,
                        partition_method="homo", seed=1)


def _lr_setup(ds16, agg_name):
    cfg = FedConfig(batch_size=8, epochs=2, lr=0.05, client_num_in_total=16,
                    client_num_per_round=16, server_optimizer="adam",
                    server_lr=0.01)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds16.class_num))
    agg = make_aggregator(agg_name, cfg)
    rng = jax.random.PRNGKey(0)
    gv = trainer.init(rng, jnp.asarray(ds16.train.x[:1, 0]))
    state = agg.init_state(gv)
    x, y, counts = ds16.train.select(np.arange(16))
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts))
    return cfg, trainer, agg, gv, state, data, rng


def _max_abs_delta(a, b):
    d = jax.tree.map(lambda u, v: float(jnp.max(jnp.abs(u - v))), a, b)
    return max(jax.tree.leaves(d), default=0.0)


@pytest.mark.parametrize("agg_name,masked", [
    ("fedavg", False), ("fedopt", False), ("robust", False),
    ("fednova", False), ("fedavg", True), ("robust", True),
])
def test_tensor_round_bit_identical_to_replicated(mesh24, ds16, agg_name,
                                                  masked):
    cfg, trainer, agg, gv, state, (x, y, counts), rng = _lr_setup(
        ds16, agg_name)
    part = (jnp.asarray(np.array([True] * 12 + [False] * 4))
            if masked else None)

    sh = TensorSharding.for_model(mesh24, "lr")
    sh_repl = TensorSharding(mesh24, tuple(REPLICATED_RULES))
    rf_sh = build_tensor_round_fn(trainer, cfg, agg, sh, donate_state=False)
    rf_re = build_tensor_round_fn(trainer, cfg, agg, sh_repl,
                                  donate_state=False)

    g1, s1, m1 = rf_sh(sh.place(gv), sh.place(state), x, y, counts, rng, part)
    g2, s2, m2 = rf_re(sh_repl.place(gv), sh_repl.place(state), x, y, counts,
                       rng, part)
    # fedavg/fedopt/fednova aggregate elementwise, so slicing commutes with
    # every reduction and the arms match BITWISE. Robust's clip norm spans
    # the whole tree; GSPMD may re-partition that reduction across the
    # tensor axis, reassociating the sum — one-ulp-scale slack only.
    tol = 1e-8 if agg_name == "robust" else 0.0
    assert _max_abs_delta(g1, g2) <= tol, "variables diverged"
    assert _max_abs_delta(s1, s2) <= tol, "aggregator state diverged"
    for k in m1:
        assert abs(float(m1[k]) - float(m2[k])) <= tol * 100
    # outputs really are tensor-sharded (donation-compatible placement)
    spec_leaves = [s.spec for s in jax.tree.leaves(
        jax.tree.map(lambda l: l.sharding, g1))]
    assert any("tensor" in str(s) for s in spec_leaves), \
        "no output leaf carries a tensor-axis sharding"


def test_tensor_round_matches_vmap_engine(mesh24, ds16):
    """Versus the single-chip engine only the client-psum reassociation
    applies — same tolerance as the 1-D sharded round."""
    cfg, trainer, agg, gv, state, (x, y, counts), rng = _lr_setup(
        ds16, "fedavg")
    sh = TensorSharding.for_model(mesh24, "lr")
    rf = build_tensor_round_fn(trainer, cfg, agg, sh, donate_state=False)
    vmap_rf = build_round_fn(trainer, cfg, agg)

    g1, _, m1 = rf(sh.place(gv), sh.place(state), x, y, counts, rng)
    g2, _, m2 = vmap_rf(gv, state, x, y, counts, rng)
    assert _max_abs_delta(g1, g2) < 1e-6
    for k in m1:
        assert abs(float(m1[k]) - float(m2[k])) < 1e-3


@pytest.mark.slow  # ~10s LSTM compile x2; the lr/cnn/tformer families pin
# the sharded==replicated identity in the fast suite
def test_rnn_family_round_bit_identical(mesh24):
    """The rnn rule table drives a real LSTM round: sharded == replicated."""
    cfg = FedConfig(model="rnn", batch_size=4, epochs=1, lr=0.1,
                    client_num_in_total=2, client_num_per_round=2)
    trainer = NWPTrainer(create_model("rnn", output_dim=90, vocab_size=90))
    agg = make_aggregator("fedavg", cfg)
    rng = jax.random.PRNGKey(3)
    gv = trainer.init(rng, jnp.zeros((2, 16), jnp.int32))
    state = agg.init_state(gv)
    nprng = np.random.RandomState(0)
    x = jnp.asarray(nprng.randint(1, 90, (2, 8, 16)), jnp.int32)
    y = jnp.asarray(nprng.randint(1, 90, (2, 8)), jnp.int32)  # last-pos logits
    counts = jnp.full((2,), 8, jnp.int32)

    sh = TensorSharding.for_model(mesh24, "rnn")
    sh_repl = TensorSharding(mesh24, tuple(REPLICATED_RULES))
    rf = build_tensor_round_fn(trainer, cfg, agg, sh, donate_state=False)
    rf_re = build_tensor_round_fn(trainer, cfg, agg, sh_repl,
                                  donate_state=False)
    g1, _, _ = rf(sh.place(gv), sh.place(state), x, y, counts, rng)
    g2, _, _ = rf_re(sh_repl.place(gv), sh_repl.place(state), x, y, counts,
                     rng)
    assert _max_abs_delta(g1, g2) == 0.0


def test_transformer_specs_shrink_per_device_bytes(mesh24):
    """The transformer rule table must shrink per-device param bytes by
    >= 1.9x at tensor=4 (the BENCH_SHARD acceptance floor) — checked from
    specs alone, no compile."""
    m = create_model("transformer_nwp", output_dim=10004)
    gv = jax.eval_shape(lambda: m.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 16), jnp.int32), train=False))
    sh = TensorSharding.for_model(mesh24, "transformer_nwp")
    repl, shard = sh.per_device_bytes(gv)
    assert repl / shard >= 1.9, f"shrink {repl / shard:.2f}x < 1.9x"


def test_divisibility_demotion_falls_back_to_replicated():
    tree = {"params": {"head": {"kernel": np.zeros((10, 7), np.float32)},
                       "body": {"kernel": np.zeros((8, 3), np.float32)}}}
    rules = [(r"kernel$", PS("tensor", None))]
    specs, demoted = resolve_param_specs(rules, tree, tensor_shards=4)
    # 10 % 4 != 0 -> demoted; 8 % 4 == 0 -> sharded
    assert demoted == ["params/head/kernel"]
    assert specs["params"]["head"]["kernel"] == PS()
    assert specs["params"]["body"]["kernel"] == PS("tensor", None)


def test_unmatched_param_raises():
    tree = {"params": {"mystery": np.zeros((4, 4), np.float32)}}
    with pytest.raises(ValueError, match="partition rule not found"):
        resolve_param_specs(rules_for_model("transformer_nwp"), tree, 4)


def test_engine_seam_routes_param_sharding(mesh24, ds16):
    """build_round_fn(param_sharding=...) must return the tensor round,
    with state donation keyed off cfg.extra['donate_params']."""
    cfg, trainer, agg, gv, state, (x, y, counts), rng = _lr_setup(
        ds16, "fedavg")
    sh = TensorSharding.for_model(mesh24, "lr")
    rf = build_round_fn(trainer, cfg, agg, param_sharding=sh)
    assert rf.sharding is sh and rf.donate_state is False

    cfg2 = cfg.replace(extra={"donate_params": True})
    rf2 = build_round_fn(trainer, cfg2, agg, param_sharding=sh)
    assert rf2.donate_state is True
    g, s, m = rf2(sh.place(gv), sh.place(state), x, y, counts, rng)
    assert np.isfinite(float(m["loss_sum"]))


def test_api_tensor_shards_trains_and_keeps_state_sharded(ds16):
    cfg = FedConfig(comm_round=3, batch_size=16, lr=0.1,
                    client_num_in_total=16, client_num_per_round=10,
                    tensor_shards=4)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds16.class_num))
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    api = FedAvgAPI(ds16, cfg, trainer)
    hist = api.train()
    assert hist[-1]["Test/Loss"] < hist[0]["Test/Loss"]
    kernel = api.global_variables["params"]["linear"]["kernel"]
    assert "tensor" in str(kernel.sharding.spec)


def test_tensor_shards_conflicts_raise(ds16):
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds16.class_num))
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    for bad in (dict(backend="shard_map"), dict(silo_threshold=8)):
        cfg = FedConfig(client_num_in_total=16, client_num_per_round=16,
                        tensor_shards=4, **bad)
        with pytest.raises(ValueError, match="tensor_shards"):
            FedAvgAPI(ds16, cfg, trainer)
