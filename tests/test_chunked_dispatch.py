"""Chunked donated-carry epoch dispatch vs the monolithic round, and the
FedProx stateless-opt fast-path regression.

The chunked runner (engine.build_chunked_round_runner) splits an E-epoch
local round into K host dispatches with the (variables, opt_state, steps)
carry donated between them. It must reproduce the monolithic
build_round_fn trajectory exactly — same rng stream, same epoch body.

The FedProx tests pin the ADVICE.md fix: plain SGD + fedprox_mu > 0 must
NOT take the stateless-opt fast path, because the proximal gradient
mu*(p - w_global) is nonzero on all-padding batches even though the masked
data loss gives exactly-zero grads.
"""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.aggregators import make_aggregator
from fedml_tpu.algorithms.engine import (
    build_chunked_round_runner,
    build_local_update,
    build_round_fn,
)
from fedml_tpu.algorithms.silo_grouped import build_silo_local_update
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.models.linear import DenseMLP

CLIENTS, N, BS, D, C = 3, 24, 8, 6, 4


def _setup(epochs, momentum=0.0, fedprox_mu=0.0, counts=None, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.rand(CLIENTS, N, D).astype(np.float32))
    y = jnp.asarray(rng.randint(0, C, size=(CLIENTS, N)).astype(np.int32))
    counts = jnp.asarray(counts if counts is not None
                         else [N, N - 5, N - 11], jnp.int32)
    cfg = FedConfig(batch_size=BS, epochs=epochs, lr=0.1,
                    client_optimizer="sgd", momentum=momentum,
                    fedprox_mu=fedprox_mu,
                    client_num_per_round=CLIENTS, shuffle=True)
    trainer = ClassificationTrainer(DenseMLP(output_dim=C, hidden=(8,)))
    gv = trainer.init(jax.random.PRNGKey(0), x[0, :1])
    agg = make_aggregator("fedavg", cfg)
    return cfg, trainer, gv, agg, x, y, counts


def _run_rounds(round_fn, gv, st, x, y, counts, key, n=2):
    m = None
    for r in range(n):
        gv, st, m = round_fn(gv, st, x, y, counts, jax.random.fold_in(key, r))
    return gv, st, m


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_chunked_round_matches_monolithic():
    # E=6, chunk=2 -> 3 equal dispatches; momentum exercises the donated
    # opt_state carry, ragged counts exercise the padding masks, shuffle
    # exercises the per-epoch rng stream
    cfg, trainer, gv, agg, x, y, counts = _setup(epochs=6, momentum=0.9)
    mono = build_round_fn(trainer, cfg, agg)
    chunked = build_chunked_round_runner(trainer, cfg, agg, epoch_chunk=2)

    key = jax.random.PRNGKey(3)
    gv_m, st_m, m_m = _run_rounds(mono, gv, agg.init_state(gv), x, y, counts, key)
    gv_c, st_c, m_c = _run_rounds(chunked, gv, agg.init_state(gv), x, y, counts, key)

    _assert_trees_equal(gv_m, gv_c)
    assert m_m.keys() == m_c.keys()
    for k in m_m:
        np.testing.assert_allclose(float(m_m[k]), float(m_c[k]), rtol=1e-6)


def test_chunked_round_remainder_chunk():
    # E=5, chunk=2 -> dispatches of 2+2+1: the remainder compiles a second
    # program; trajectory must still match the fused scan
    cfg, trainer, gv, agg, x, y, counts = _setup(epochs=5)
    mono = build_round_fn(trainer, cfg, agg)
    chunked = build_chunked_round_runner(trainer, cfg, agg, epoch_chunk=2)

    key = jax.random.PRNGKey(11)
    gv_m, _, _ = _run_rounds(mono, gv, agg.init_state(gv), x, y, counts, key, n=1)
    gv_c, _, _ = _run_rounds(chunked, gv, agg.init_state(gv), x, y, counts, key, n=1)
    _assert_trees_equal(gv_m, gv_c)


def test_chunked_round_single_chunk_degenerates_to_monolithic():
    cfg, trainer, gv, agg, x, y, counts = _setup(epochs=3)
    mono = build_round_fn(trainer, cfg, agg)
    chunked = build_chunked_round_runner(trainer, cfg, agg, epoch_chunk=3)
    key = jax.random.PRNGKey(5)
    gv_m, _, _ = _run_rounds(mono, gv, agg.init_state(gv), x, y, counts, key, n=1)
    gv_c, _, _ = _run_rounds(chunked, gv, agg.init_state(gv), x, y, counts, key, n=1)
    _assert_trees_equal(gv_m, gv_c)


# --- FedProx stateless-opt regression (ADVICE.md) ---------------------------


def _fedprox_padding_args(n_max):
    # one client, count=2 of n_max rows, bs=2: batch 0 has data, the rest are
    # all-padding. DenseMLP has no dropout and shuffle=False, so the step
    # rngs are inert and runs with different nb are comparable.
    rng = np.random.RandomState(7)
    x_full = jnp.asarray(rng.rand(8, D).astype(np.float32))
    y_full = jnp.asarray(rng.randint(0, C, size=(8,)).astype(np.int32))
    return x_full[:n_max], y_full[:n_max]


def test_fedprox_plain_sgd_takes_no_prox_only_steps_on_padding():
    cfg = FedConfig(batch_size=2, epochs=2, lr=0.2, client_optimizer="sgd",
                    fedprox_mu=0.5, client_num_per_round=1, shuffle=False)
    trainer = ClassificationTrainer(DenseMLP(output_dim=C, hidden=(8,)))
    x2, y2 = _fedprox_padding_args(2)   # exactly the valid rows
    x8, y8 = _fedprox_padding_args(8)   # + three all-padding batches
    gv = trainer.init(jax.random.PRNGKey(0), x2[:1])
    update = build_local_update(trainer, cfg)

    key = jax.random.PRNGKey(1)
    res_pad = jax.jit(update)(gv, x8, y8, jnp.int32(2), key)
    res_tight = jax.jit(update)(gv, x2, y2, jnp.int32(2), key)

    # padding batches must be complete no-ops: same params as the run that
    # never saw them, and no steps counted for them
    assert int(res_pad.num_steps) == int(res_tight.num_steps) == cfg.epochs
    _assert_trees_equal(res_pad.variables, res_tight.variables)


def test_fedprox_padding_regression_would_catch_prox_only_step():
    # sanity check on the probe itself: an all-padding batch under FedProx
    # has a NONZERO proximal gradient once params have left the global point
    # — i.e. the old `stateless_opt` criterion (without the fedprox_mu == 0
    # clause) really did take a step here, which is what the test above
    # guards. Simulate one unmasked prox-only step and confirm it moves.
    cfg = FedConfig(batch_size=2, epochs=1, lr=0.2, client_optimizer="sgd",
                    fedprox_mu=0.5, client_num_per_round=1, shuffle=False)
    trainer = ClassificationTrainer(DenseMLP(output_dim=C, hidden=(8,)))
    x2, y2 = _fedprox_padding_args(2)
    gv = trainer.init(jax.random.PRNGKey(0), x2[:1])
    update = build_local_update(trainer, cfg)
    moved = jax.jit(update)(gv, x2, y2, jnp.int32(2), jax.random.PRNGKey(1))
    prox_grads = jax.tree.map(lambda p, g: cfg.fedprox_mu * (p - g),
                              moved.variables["params"], gv["params"])
    assert max(float(jnp.abs(l).max())
               for l in jax.tree.leaves(prox_grads)) > 0.0


def test_fedprox_silo_grouped_criterion_matches_engine():
    # silo path must make the same call: FedProx + plain SGD on a silo whose
    # tail batches are padding matches a run without the padding rows
    cfg = FedConfig(batch_size=2, epochs=2, lr=0.2, client_optimizer="sgd",
                    fedprox_mu=0.5, client_num_per_round=1, shuffle=False)
    trainer = ClassificationTrainer(DenseMLP(output_dim=C, hidden=(8,)))
    x2, y2 = _fedprox_padding_args(2)
    x8, y8 = _fedprox_padding_args(8)
    gv = trainer.init(jax.random.PRNGKey(0), x2[:1])
    silo_update = build_silo_local_update(trainer, cfg)

    crngs = jax.random.split(jax.random.PRNGKey(1), 1)
    res_pad = jax.jit(silo_update)(gv, x8[None], y8[None],
                                   jnp.asarray([2], jnp.int32), crngs)
    res_tight = jax.jit(silo_update)(gv, x2[None], y2[None],
                                     jnp.asarray([2], jnp.int32), crngs)
    assert int(res_pad.num_steps[0]) == int(res_tight.num_steps[0]) == cfg.epochs
    _assert_trees_equal(res_pad.variables, res_tight.variables)
