"""End-to-end FedAvg slice + the reference CI equivalence oracles.

Oracle 1 (reference CI-script-fedavg.sh:44-50): full-batch, E=1 FedAvg over
all clients equals centralized full-batch GD to tight tolerance.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedml_tpu.algorithms.centralized import CentralizedTrainer
from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model


@pytest.fixture(scope="module")
def mnist10():
    return load_dataset("mnist", client_num_in_total=10, partition_method="homo", seed=0)


def make_api(ds, **cfg_kw):
    cfg = FedConfig(
        dataset="mnist", model="lr", client_num_in_total=ds.client_num,
        client_num_per_round=cfg_kw.pop("client_num_per_round", ds.client_num),
        **cfg_kw,
    )
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    return FedAvgAPI(ds, cfg, trainer)


def test_client_sampling_deterministic():
    a = client_sampling(3, 100, 10)
    b = client_sampling(3, 100, 10)
    np.testing.assert_array_equal(a, b)
    assert len(set(a.tolist())) == 10
    c = client_sampling(4, 100, 10)
    assert a.tolist() != c.tolist()


def test_fedavg_learns_mnist_lr(mnist10):
    api = make_api(mnist10, comm_round=5, batch_size=32, lr=0.1, client_num_per_round=5)
    hist = api.train()
    assert hist[-1]["Test/Acc"] > 0.5  # surrogate mnist is easily separable
    assert hist[-1]["Test/Loss"] < hist[0]["Test/Loss"]


def test_equivalence_oracle_fullbatch_fedavg_vs_centralized(mnist10):
    """Full batch, E=1, all clients, homo partition: 1 round of FedAvg =
    1 step of centralized GD (gradient linearity), to float tolerance."""
    # grad_clip must be off: clipping is per-client in FedAvg but global in
    # centralized GD, which breaks exact gradient linearity when active
    cfg = FedConfig(batch_size=-1, epochs=1, lr=0.05, comm_round=1, grad_clip=None,
                    client_num_in_total=10, client_num_per_round=10)
    trainer = ClassificationTrainer(create_model("lr", output_dim=10))
    fed = FedAvgAPI(mnist10, cfg, trainer)
    cen = CentralizedTrainer(mnist10, cfg, trainer)
    # identical init
    cen.global_variables = jax.tree.map(lambda x: x, fed.global_variables)

    for r in range(3):
        fed.train_one_round(r)
        cen.train(1)

    fed_acc = fed.test_global(0)
    cen_acc = cen.eval_global()
    assert abs(fed_acc["Test/Acc"] - cen_acc["Test/Acc"]) < 1e-3
    assert abs(fed_acc["Test/Loss"] - cen_acc["Test/Loss"]) < 1e-3
    # parameters themselves should agree tightly
    diff = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), fed.global_variables, cen.global_variables
    )
    assert max(jax.tree.leaves(diff)) < 1e-4


def test_padding_masks_do_not_leak(mnist10):
    """Clients with very different sizes: padded samples must not affect the
    result. Duplicate a dataset with extra padding and check identical output."""
    from fedml_tpu.data.packing import PackedClients
    ds = mnist10
    train2 = PackedClients(
        np.concatenate([ds.train.x, np.full_like(ds.train.x, 7.0)], axis=1),
        np.concatenate([ds.train.y, np.zeros_like(ds.train.y)], axis=1),
        ds.train.counts.copy(),
    )
    import dataclasses
    ds2 = dataclasses.replace(ds, train=train2)

    # full-batch mode: the single batch holds every valid sample, so the
    # padded tail must be exactly invisible regardless of n_max
    api1 = make_api(ds, comm_round=1, batch_size=-1, lr=0.1)
    api2 = make_api(ds2, comm_round=1, batch_size=-1, lr=0.1)
    api2.global_variables = jax.tree.map(lambda x: x, api1.global_variables)
    api1.train_one_round(0)
    api2.train_one_round(0)
    d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                     api1.global_variables, api2.global_variables)
    assert max(jax.tree.leaves(d)) < 1e-5


def test_multi_round_scan_equals_sequential_rounds(mnist10):
    """build_multi_round_fn with full participation == sequential
    build_round_fn calls with rng = fold_in(base, round_idx), exactly."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_multi_round_fn, build_round_fn

    cfg = FedConfig(batch_size=16, epochs=1, lr=0.1,
                    client_num_in_total=10, client_num_per_round=10)
    trainer = ClassificationTrainer(create_model("lr", output_dim=10))
    agg = make_aggregator("fedavg", cfg)
    base = jax.random.PRNGKey(5)
    gv = trainer.init(base, jnp.asarray(mnist10.train.x[:1, 0]))
    x, y, counts = mnist10.train.select(np.arange(10))
    x, y, counts = jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts)

    seq_fn = build_round_fn(trainer, cfg, agg)
    gv_seq = gv
    for r in range(3):
        gv_seq, _, _ = seq_fn(gv_seq, (), x, y, counts, jax.random.fold_in(base, r))

    multi = build_multi_round_fn(trainer, cfg, agg, 3)
    gv_scan, _, metrics = multi(gv, (), x, y, counts, base)
    d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), gv_seq, gv_scan)
    assert max(jax.tree.leaves(d)) < 1e-6
    assert metrics["total"].shape == (3,)  # per-round metric history


def test_multi_round_scan_sampling_subset(mnist10):
    """With k < C the scan path samples k distinct clients per round and
    still trains (loss falls)."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_multi_round_fn

    cfg = FedConfig(batch_size=16, epochs=1, lr=0.1,
                    client_num_in_total=10, client_num_per_round=4)
    trainer = ClassificationTrainer(create_model("lr", output_dim=10))
    agg = make_aggregator("fedavg", cfg)
    base = jax.random.PRNGKey(6)
    gv = trainer.init(base, jnp.asarray(mnist10.train.x[:1, 0]))
    x, y, counts = mnist10.train.select(np.arange(10))
    multi = build_multi_round_fn(trainer, cfg, agg, 8)
    gv2, _, metrics = multi(gv, (), jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts), base)
    losses = np.asarray(metrics["loss_sum"]) / np.maximum(np.asarray(metrics["total"]), 1.0)
    assert losses[-1] < losses[0]


@pytest.mark.skipif(
    "xla_backend_optimization_level=0" in os.environ.get("XLA_FLAGS", ""),
    reason="bit-identity holds only at default XLA codegen: the fast "
           "suite's opt-0 flag (tests/conftest.py) reassociates the "
           "weighted-mean reduction (~3e-8 drift); covered by --runslow / "
           "FEDML_TPU_RUN_SLOW=1 runs, which keep default codegen")
def test_assume_full_clients_bit_identical():
    """The assume_full_clients specialization must be a pure compile-time
    simplification: on data satisfying the contract (every count == n_max,
    n_max % batch == 0) the trajectories are BIT-identical to the general
    path — same shuffle permutations (argsort(u) == argsort(where(all,u,inf))),
    masks of literal ones, no-op-step selects statically resolved."""
    from fedml_tpu.algorithms.aggregators import make_aggregator
    from fedml_tpu.algorithms.engine import build_round_fn
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.models.registry import create_model

    rng = np.random.RandomState(5)
    C, n = 4, 24
    x = jnp.asarray(rng.rand(C, n, 12).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 3, size=(C, n)).astype(np.int32))
    counts = jnp.full((C,), n, jnp.int32)
    trainer = ClassificationTrainer(create_model("lr", output_dim=3))
    gv = trainer.init(jax.random.PRNGKey(0), x[0, :1])

    for opt_kw in ({"client_optimizer": "sgd"},  # stateless path (bench cfg)
                   {"client_optimizer": "sgd", "momentum": 0.9},
                   {"client_optimizer": "adam", "wd": 1e-3}):
        cfg = FedConfig(batch_size=8, epochs=2, lr=0.1,
                        client_num_per_round=C, **opt_kw)
        agg = make_aggregator("fedavg", cfg)
        key = jax.random.PRNGKey(3)
        g1, _, m1 = build_round_fn(trainer, cfg, agg)(
            gv, agg.init_state(gv), x, y, counts, key)
        cfg2 = cfg.replace(assume_full_clients=True)
        g2, _, m2 = build_round_fn(trainer, cfg2, agg)(
            gv, agg.init_state(gv), x, y, counts, key)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for k2 in m1:
            assert float(m1[k2]) == float(m2[k2])


def test_assume_full_clients_rejects_indivisible_batch():
    from fedml_tpu.algorithms.engine import build_local_update
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.models.registry import create_model

    trainer = ClassificationTrainer(create_model("lr", output_dim=3))
    cfg = FedConfig(batch_size=10, assume_full_clients=True)
    lu = build_local_update(trainer, cfg)
    x = jnp.zeros((24, 12)); y = jnp.zeros((24,), jnp.int32)
    with pytest.raises(ValueError, match="assume_full_clients"):
        lu({"params": {}}, x, y, jnp.int32(24), jax.random.PRNGKey(0))


def test_resident_eval_equals_chunked_eval(mnist10):
    """The one-dispatch resident federation eval (VERDICT r3 weak #4) must
    report exactly what the chunked streaming path reports — including a
    chunk-boundary-straddling federation (67 clients > one 64-chunk)."""
    ds = load_dataset("mnist", client_num_in_total=67, partition_method="homo",
                      seed=1)
    api_res = make_api(ds, comm_round=1, batch_size=32, lr=0.1,
                       client_num_per_round=5, resident_eval=True)
    api_chk = make_api(ds, comm_round=1, batch_size=32, lr=0.1,
                       client_num_per_round=5, resident_eval=False)
    api_chk.global_variables = api_res.global_variables
    m_res = api_res.local_test_on_all_clients(0)
    m_chk = api_chk.local_test_on_all_clients(0)
    assert m_res.keys() == m_chk.keys()
    for k in m_res:
        np.testing.assert_allclose(m_res[k], m_chk[k], rtol=1e-6, atol=1e-7)
    # the resident arrays were built once and reused on the second call
    first = api_res._resident_cache
    api_res.local_test_on_all_clients(0)
    assert api_res._resident_cache is first


def test_resident_eval_budget_fallback(mnist10):
    """Over-budget splits must fall back to chunked eval with a warning, not
    silently OOM the device."""
    api = make_api(mnist10, comm_round=1, batch_size=32, lr=0.1,
                   client_num_per_round=5, resident_eval=True,
                   resident_eval_budget=1)  # 1 byte: always over
    m = api.local_test_on_all_clients(0)
    assert api._resident_cache == {}  # remembered as over-budget
    assert "Test/Acc" in m and "Train/Acc" in m


# ------------------------------------------------- fast sampling (Feistel)

def test_fast_sampling_is_a_permutation_sample():
    from fedml_tpu.algorithms.fedavg import fast_client_sampling

    idx = fast_client_sampling(7, 1_000_003, 64)
    assert idx.shape == (64,)
    assert idx.dtype == np.int64
    assert len(set(idx.tolist())) == 64  # distinct
    assert idx.min() >= 0 and idx.max() < 1_000_003  # in range


def test_fast_sampling_deterministic_and_round_varying():
    from fedml_tpu.algorithms.fedavg import fast_client_sampling

    a = fast_client_sampling(3, 100, 10)
    b = fast_client_sampling(3, 100, 10)
    np.testing.assert_array_equal(a, b)
    c = fast_client_sampling(4, 100, 10)
    assert a.tolist() != c.tolist()


def test_fast_sampling_covers_whole_population():
    from fedml_tpu.algorithms.fedavg import fast_client_sampling

    idx = fast_client_sampling(11, 37, 37)
    assert sorted(idx.tolist()) == list(range(37))


def test_default_sampler_bit_compat_pin(mnist10):
    """fast_sampling defaults OFF: the staged cohort must keep coming from
    the original rng.choice sampler so existing trajectories replay."""
    api = make_api(mnist10, comm_round=1, client_num_per_round=4)
    assert api.cfg.fast_sampling is False
    expected = np.random.RandomState(5).choice(10, 4, replace=False)
    np.testing.assert_array_equal(client_sampling(5, 10, 4), expected)
