"""CNN-scale, long-horizon trajectory parity vs the LIVING reference.

The oracles in test_reference_parity.py match LR-sized models over <=10 steps.
This module closes the remaining altitude band: the full reference standalone
`FedAvgAPI.train()` (fedml_api/standalone/fedavg/fedavg_api.py:42-117) —
client sampling (np.random.seed(round_idx) choice, :86-94), minibatch local
SGD with E>1 via MyModelTrainer.train, the in-place `_aggregate` (:102-117),
and `_local_test_on_all_clients` (:119-183) — is driven END TO END for 24
rounds on the 1.66M-parameter `CNN_OriginalFedAvg` (model/cv/cnn.py:8) and
compared per round against `fedml_tpu.algorithms.fedavg.FedAvgAPI` on the
same surrogate federation with bit-ported initial weights.

Matched per round (documented, MEASURED tolerances):
  - global parameter relative L2 distance — both against a hard cap
    (CNN_TOL_REL) and against a Lyapunov CONTROL: the reference run again
    from a 1e-4-relative perturbed init. The federated CNN trajectory is
    chaotic (grad-clip normalization + nonconvex loss amplify an f32-epsilon
    ~20x per round early on), so the control measures the intrinsic noise
    floor; the rebuild must stay within 2x of it. Measured: ours <= 2.8e-3
    at round 23 vs control 6.5e-3 — the JAX rebuild tracks the reference
    BETTER than the reference tracks itself under a 1e-4 init wiggle.
  - Train/Acc + Test/Acc from the all-clients eval (count-based, so a
    mismatch means trajectories actually diverged, not just float noise);
    measured max disagreement 0.0042 = one test sample.
  - the sampled client indices each round (same MT19937 stream).

Reference DEFECT found while building this (pinned bit-exactly by
test_reference_standalone_chaining_defect): standalone FedAvgAPI's initial
`w_global = get_model_params()` (fedavg_api.py:43) returns the live
state_dict — references into the single shared model's tensors — so in
ROUND 0 each client trains from the previous client's result (sequential
pass-the-model training averaged over intermediate snapshots). Rounds >= 1
are unaffected: `_aggregate` allocates fresh tensors, breaking the alias.
The whole trajectory still diverges from intended FedAvg through the
round-0 starting point. The oracle de-aliases via a deepcopy shim to
recover the intended (distributed-path, FedAVGAggregator.py:58-87)
semantics, which is what the rebuild implements.

Real-data note: with the actual FEMNIST h5 files mounted (data/FederatedEMNIST),
the same two train loops are the reference's published 84.9@1500-rounds
config — `python -m fedml_tpu.experiments.main_fedavg --dataset femnist
--model cnn --client_num_in_total 3400 --client_num_per_round 10
--comm_round 1500` (see docs/PERF.md; "cnn" = CNN_DropOut for femnist,
matching reference main_fedavg.py:233-236).

Slow-marked: ~1,150 torch CNN training steps + a jitted JAX round. CPU-only.
"""

from __future__ import annotations

import copy
import sys
from types import SimpleNamespace

import numpy as np
import pytest

pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")

from _reference_oracle import setup_reference, torch_batches  # noqa: E402

setup_reference()
# the living-reference checkout is not shipped in every container;
# without it the oracle has nothing to run — skip at collect time
# instead of erroring the whole module
pytest.importorskip(
    "fedml_api",
    reason="reference FedML checkout (/root/reference) unavailable")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling  # noqa: E402
from fedml_tpu.core.config import FedConfig  # noqa: E402
from fedml_tpu.core.trainer import ClassificationTrainer  # noqa: E402
from fedml_tpu.data.packing import PackedClients  # noqa: E402
from fedml_tpu.data.registry import FederatedDataset  # noqa: E402
from fedml_tpu.models.cnn import CNN_OriginalFedAvg as JaxCNN  # noqa: E402

from fedml_api.model.cv.cnn import CNN_OriginalFedAvg as TorchCNN  # noqa: E402
from fedml_api.standalone.fedavg.my_model_trainer_classification import (  # noqa: E402
    MyModelTrainer,
)

# documented tolerances (f32 CPU, ~550 SGD steps through two 5x5 convs):
# torch and XLA reduce convolutions in different orders (~2e-5 relative
# grad-direction noise per step); the chaotic round map amplifies this to a
# measured 2.4e-4 after round 0 and a 2.8e-3 plateau by round 23 — always
# BELOW the 1e-4-perturbation control's 6.5e-3 (see module docstring)
CNN_TOL_REL = 6e-3
CTL_FACTOR = 2.0  # ours must stay within 2x the control's intrinsic drift
ACC_TOL = 0.02  # one borderline sample on the 240-sample eval = 0.0042

N_CLIENTS, PER_ROUND, ROUNDS = 12, 4, 24
EPOCHS, BS, LR = 2, 10, 0.06
TEST_PER_CLIENT = 20


def _make_federation(seed=0):
    """Seeded separable surrogate at MNIST scale: class prototypes + noise."""
    rng = np.random.RandomState(seed)
    protos = rng.randn(10, 28, 28).astype(np.float32)
    counts = rng.randint(40, 81, N_CLIENTS)
    train, test = [], []
    for c in counts:
        y = rng.randint(0, 10, c).astype(np.int64)
        x = protos[y] + 0.6 * rng.randn(c, 28, 28).astype(np.float32)
        train.append((x.astype(np.float32), y))
        yt = rng.randint(0, 10, TEST_PER_CLIENT).astype(np.int64)
        xt = protos[yt] + 0.6 * rng.randn(TEST_PER_CLIENT, 28, 28).astype(np.float32)
        test.append((xt.astype(np.float32), yt))
    return train, test, counts


_torch_batches = torch_batches  # shared scaffolding (tests/_reference_oracle.py)


def _torch_to_flax(sd):
    """Port a CNN_OriginalFedAvg state_dict to flax variables.

    Conv: [out, in, kh, kw] -> [kh, kw, in, out]. linear_1 crosses the
    NCHW-flatten (c,h,w) vs NHWC-flatten (h,w,c) boundary: reorder the 3136
    input columns before transposing.
    """
    def conv(w):
        return np.transpose(w.numpy(), (2, 3, 1, 0))

    l1 = sd["linear_1.weight"].numpy()  # [512, 64*7*7] in (c, h, w) order
    l1 = l1.reshape(512, 64, 7, 7).transpose(0, 2, 3, 1).reshape(512, 7 * 7 * 64)
    return {"params": {
        "conv2d_1": {"kernel": jnp.asarray(conv(sd["conv2d_1.weight"])),
                     "bias": jnp.asarray(sd["conv2d_1.bias"].numpy())},
        "conv2d_2": {"kernel": jnp.asarray(conv(sd["conv2d_2.weight"])),
                     "bias": jnp.asarray(sd["conv2d_2.bias"].numpy())},
        "linear_1": {"kernel": jnp.asarray(l1.T),
                     "bias": jnp.asarray(sd["linear_1.bias"].numpy())},
        "linear_2": {"kernel": jnp.asarray(sd["linear_2.weight"].numpy().T),
                     "bias": jnp.asarray(sd["linear_2.bias"].numpy())},
    }}


def _flax_to_vec(variables):
    """Flatten flax params into the torch state_dict layout's vector order."""
    p = variables["params"]
    parts = []
    for name in ("conv2d_1", "conv2d_2"):
        parts.append(np.transpose(np.asarray(p[name]["kernel"]), (3, 2, 0, 1)).ravel())
        parts.append(np.asarray(p[name]["bias"]).ravel())
    l1 = np.asarray(p["linear_1"]["kernel"]).T  # [512, 3136] in (h, w, c)
    l1 = l1.reshape(512, 7, 7, 64).transpose(0, 3, 1, 2).reshape(512, -1)
    parts += [l1.ravel(), np.asarray(p["linear_1"]["bias"]).ravel(),
              np.asarray(p["linear_2"]["kernel"]).T.ravel(),
              np.asarray(p["linear_2"]["bias"]).ravel()]
    return np.concatenate(parts)


def _torch_to_vec(sd):
    return np.concatenate([
        sd[k].numpy().ravel()
        for k in ("conv2d_1.weight", "conv2d_1.bias", "conv2d_2.weight",
                  "conv2d_2.bias", "linear_1.weight", "linear_1.bias",
                  "linear_2.weight", "linear_2.bias")
    ])


def _run_reference(train, test, counts, perturb=0.0):
    """Drive the reference FedAvgAPI.train() itself, recording the per-round
    aggregated params (via a set_model_params tap) and the wandb-logged
    Train/Acc / Test/Acc stream.

    ``perturb`` adds seeded gaussian noise of that relative scale to the
    initial weights — the Lyapunov CONTROL run: it measures how fast the
    reference's own trajectory amplifies an f32-epsilon difference, which is
    the intrinsic noise floor any cross-framework comparison must be judged
    against."""
    from fedml_api.standalone.fedavg.fedavg_api import FedAvgAPI as RefFedAvgAPI

    torch.manual_seed(0)
    model = TorchCNN(only_digits=True)
    if perturb:
        g = torch.Generator().manual_seed(99)
        with torch.no_grad():
            for p in model.parameters():
                p.add_(torch.randn(p.shape, generator=g) * perturb * p.abs().mean())
    init_sd = copy.deepcopy(model.state_dict())

    train_local = {i: _torch_batches(x, y, BS) for i, (x, y) in enumerate(train)}
    test_local = {i: _torch_batches(x, y, BS) for i, (x, y) in enumerate(test)}
    num_local = {i: int(c) for i, c in enumerate(counts)}
    dataset = [int(sum(counts)), N_CLIENTS * TEST_PER_CLIENT, None, None,
               num_local, train_local, test_local, 10]

    args = SimpleNamespace(
        client_num_in_total=N_CLIENTS, client_num_per_round=PER_ROUND,
        comm_round=ROUNDS, frequency_of_the_test=2, ci=0,
        client_optimizer="sgd", lr=LR, wd=0.0, epochs=EPOCHS,
        batch_size=BS, dataset="femnist-surrogate",
    )

    trainer = MyModelTrainer(model)

    # De-aliasing shim (reference DEFECT, pinned bit-exactly by
    # test_reference_standalone_chaining_defect below): the initial
    # w_global = get_model_params() returns the live state_dict —
    # references into the shared model's tensors — so in round 0 each
    # client's training mutates the w_global the next client starts from
    # (rounds >= 1 start from _aggregate's fresh tensors and are clean).
    # Deepcopying restores the INTENDED parallel FedAvg semantics (the
    # distributed path's, FedAVGAggregator.py:58-87), which is what the
    # rebuild implements — same policy as the decentralized oracle's
    # deepcopy of neighbors_weight_dict.
    orig_get = trainer.get_model_params
    trainer.get_model_params = lambda: copy.deepcopy(orig_get())

    metric_log = {}
    wandb_mod = sys.modules["wandb"]
    orig_log = wandb_mod.log

    def wlog(d, *a, **k):
        r = d.get("round")
        for key in ("Train/Acc", "Test/Acc", "Train/Loss", "Test/Loss"):
            if key in d:
                metric_log.setdefault(r, {})[key] = float(d[key])

    wandb_mod.log = wlog
    try:
        api = RefFedAvgAPI(dataset, torch.device("cpu"), args, trainer)
        # record each round's aggregated global weights (train() calls
        # _aggregate exactly once per round, fedavg_api.py:71)
        param_log = []
        orig_agg = api._aggregate

        def agg_tap(w_locals):
            w = orig_agg(w_locals)
            param_log.append(_torch_to_vec({k: v.clone() for k, v in w.items()}))
            return w

        api._aggregate = agg_tap
        api.train()
    finally:
        wandb_mod.log = orig_log
    return init_sd, param_log, metric_log


def _run_ours(init_sd, train, test, counts):
    n_max = int(max(counts))
    xs = np.zeros((N_CLIENTS, n_max, 28, 28, 1), np.float32)
    ys = np.zeros((N_CLIENTS, n_max), np.int32)
    for i, (x, y) in enumerate(train):
        xs[i, : len(x)] = x[..., None]
        ys[i, : len(y)] = y
    xt = np.stack([x[..., None] for x, _ in test])
    yt = np.stack([y for _, y in test]).astype(np.int32)
    ds = FederatedDataset(
        name="femnist-surrogate",
        train=PackedClients(xs, ys, np.asarray(counts, np.int32)),
        test=PackedClients(xt, yt,
                           np.full(N_CLIENTS, TEST_PER_CLIENT, np.int32)),
        train_global=(xs.reshape(-1, 28, 28, 1), ys.reshape(-1)),
        test_global=(xt.reshape(-1, 28, 28, 1), yt.reshape(-1)),
        class_num=10,
    )
    cfg = FedConfig(
        client_num_in_total=N_CLIENTS, client_num_per_round=PER_ROUND,
        comm_round=ROUNDS, frequency_of_the_test=2,
        client_optimizer="sgd", lr=LR, wd=0.0, epochs=EPOCHS, batch_size=BS,
        grad_clip=1.0, momentum=0.0, shuffle=False,
    )
    api = FedAvgAPI(ds, cfg, ClassificationTrainer(JaxCNN(output_dim=10)))
    api.global_variables = _torch_to_flax(init_sd)
    api.agg_state = api.aggregator.init_state(api.global_variables)

    param_log, metric_log = [], {}
    for r in range(ROUNDS):
        api.train_one_round(r)
        param_log.append(_flax_to_vec(api.global_variables))
        if r % cfg.frequency_of_the_test == 0 or r == ROUNDS - 1:
            metric_log[r] = api.local_test_on_all_clients(r)
    return param_log, metric_log


def test_cnn_long_horizon_fedavg_parity():
    train, test, counts = _make_federation(seed=0)

    # the sampling active-path precondition: per-round subsets actually vary
    samp = [tuple(client_sampling(r, N_CLIENTS, PER_ROUND)) for r in range(ROUNDS)]
    assert len(set(samp)) > 1

    init_sd, ref_params, ref_metrics = _run_reference(train, test, counts)
    _, ctl_params, _ = _run_reference(train, test, counts, perturb=1e-4)
    our_params, our_metrics = _run_ours(init_sd, train, test, counts)

    assert len(ref_params) == len(ctl_params) == len(our_params) == ROUNDS

    # (1) the same clients were sampled: reference np.random.seed(round_idx)
    # + choice == our RandomState(round_idx).choice (same MT19937 stream)
    for r in range(ROUNDS):
        np.random.seed(r)
        ref_idx = np.random.choice(range(N_CLIENTS), PER_ROUND, replace=False)
        np.testing.assert_array_equal(ref_idx, client_sampling(r, N_CLIENTS, PER_ROUND))

    # (2) global parameter trajectory: relative L2 per round, bounded by the
    # hard cap AND by the reference's own chaotic amplification of a 1e-4
    # init perturbation (the self-calibrating Lyapunov control)
    drifts = []
    for r in range(ROUNDS):
        ref_v, our_v = ref_params[r], our_params[r]
        rel = np.linalg.norm(ref_v - our_v) / np.linalg.norm(ref_v)
        ctl = np.linalg.norm(ref_v - ctl_params[r]) / np.linalg.norm(ref_v)
        drifts.append(rel)
        assert rel < CNN_TOL_REL, f"round {r}: param drift {rel:.2e} > {CNN_TOL_REL}"
        assert rel <= max(CTL_FACTOR * ctl, 1e-3), (
            f"round {r}: drift {rel:.2e} exceeds {CTL_FACTOR}x the intrinsic "
            f"noise floor {ctl:.2e}")
    # drift is smooth accumulation, not a jump (a semantic divergence shows
    # up as an order-of-magnitude step between consecutive rounds)
    for r in range(1, ROUNDS):
        assert drifts[r] < 10 * max(drifts[r - 1], 1e-6), (
            f"round {r}: drift jumped {drifts[r-1]:.2e} -> {drifts[r]:.2e}")

    # (3) eval trajectories: count-based accuracies from the all-clients eval
    eval_rounds = sorted(ref_metrics)
    assert eval_rounds == sorted(our_metrics) and len(eval_rounds) >= 12
    for r in eval_rounds:
        for key in ("Train/Acc", "Test/Acc"):
            d = abs(ref_metrics[r][key] - our_metrics[r][key])
            assert d <= ACC_TOL, (
                f"round {r} {key}: ref {ref_metrics[r][key]:.4f} vs "
                f"ours {our_metrics[r][key]:.4f}")

    # (4) the horizon is non-vacuous: training actually learned the task
    last = eval_rounds[-1]
    assert ref_metrics[last]["Test/Acc"] > 0.8
    assert our_metrics[last]["Test/Acc"] > 0.8
    # and the model moved far from init
    assert np.linalg.norm(ref_params[-1] - _torch_to_vec(init_sd)) > 1.0


def test_reference_standalone_chaining_defect():
    """Pin the reference defect the oracle works around: standalone
    FedAvgAPI's initial w_global (fedavg_api.py:43) aliases the live model
    tensors (get_model_params returns state_dict references,
    my_model_trainer_classification.py:11-12), so within ROUND 0 each client
    trains FROM THE PREVIOUS CLIENT'S RESULT. Round 0's output equals
    chained sequential training BIT-EXACTLY, and differs from the intended
    independent-clients FedAvg. (Rounds >= 1 are clean — _aggregate returns
    freshly allocated tensors — but every later round inherits round 0's
    wrong starting point.)

    The rebuild implements the intended semantics (clients start from
    w_global — the distributed path's FedAVGAggregator.py:58-87 behavior);
    this test documents why the oracle needs the deepcopy shim."""
    from fedml_api.standalone.fedavg.fedavg_api import FedAvgAPI as RefFedAvgAPI

    rng = np.random.RandomState(0)
    xs = [rng.randn(20, 28, 28).astype(np.float32) for _ in range(2)]
    ys = [rng.randint(0, 10, 20).astype(np.int64) for _ in range(2)]

    def batches(i):
        return _torch_batches(xs[i], ys[i], 20)

    args = SimpleNamespace(
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        frequency_of_the_test=10, ci=1, client_optimizer="sgd", lr=0.1,
        wd=0.0, epochs=1, batch_size=20, dataset="x")

    torch.manual_seed(0)
    model = TorchCNN(only_digits=True)
    init_sd = copy.deepcopy(model.state_dict())
    dataset = [40, 40, None, None, {0: 20, 1: 20},
               {0: batches(0), 1: batches(1)}, {0: batches(0), 1: batches(1)}, 10]
    api = RefFedAvgAPI(dataset, torch.device("cpu"), args, MyModelTrainer(model))
    api.train()
    api_vec = _torch_to_vec(model.state_dict())

    def train_from(sd, i):
        m = TorchCNN(only_digits=True)
        m.load_state_dict(copy.deepcopy(sd))
        MyModelTrainer(m).train(batches(i), torch.device("cpu"), args)
        return copy.deepcopy(m.state_dict())

    w0 = train_from(init_sd, 0)
    w1_indep = train_from(init_sd, 1)   # intended FedAvg
    w1_chain = train_from(w0, 1)        # what the aliasing actually computes
    indep = np.concatenate([
        (0.5 * w0[k] + 0.5 * w1_indep[k]).numpy().ravel() for k in w0])
    chain = np.concatenate([
        (0.5 * w0[k] + 0.5 * w1_chain[k]).numpy().ravel() for k in w0])

    np.testing.assert_array_equal(api_vec, chain)  # bit-exact: it chains
    assert np.abs(api_vec - indep).max() > 1e-4    # and is NOT the intended avg


if __name__ == "__main__":  # manual probe: print the trajectories
    train, test, counts = _make_federation(seed=0)
    init_sd, ref_params, ref_metrics = _run_reference(train, test, counts)
    _, ctl_params, ctl_metrics = _run_reference(train, test, counts, perturb=1e-4)
    our_params, our_metrics = _run_ours(init_sd, train, test, counts)
    for r in range(ROUNDS):
        rel = np.linalg.norm(ref_params[r] - our_params[r]) / np.linalg.norm(ref_params[r])
        ctl = np.linalg.norm(ref_params[r] - ctl_params[r]) / np.linalg.norm(ref_params[r])
        line = f"round {r:2d} drift {rel:.3e}  control {ctl:.3e}"
        if r in ref_metrics:
            line += (f"  Test/Acc ref {ref_metrics[r]['Test/Acc']:.4f}"
                     f" ctl {ctl_metrics[r]['Test/Acc']:.4f}"
                     f" ours {our_metrics[r]['Test/Acc']:.4f}")
        print(line)
