"""Registry-wide bf16 compute-dtype enforcement.

Every registered model must honor `create_model(name, dtype="bfloat16")`:
the traced forward jaxpr may contain NO f32 dot_general / conv_general_dilated
— a silently-f32 matmul runs the MXU at half rate and is exactly the class of
regression this test exists to catch (PERF.md: bf16 moved ResNet-56
7,641 -> 12,464 samples/s/chip). A new factory that drops the dtype knob
fails here, not in a bench three rounds later.

Deliberate exemption: we do not descend into pallas kernels (flash attention
accumulates in f32 *inside* the kernel by design — bf16 in/out, f32
accumulate is the numerically-correct flash formulation); the registry knob
controls what the kernel is *fed*, which the surrounding qkv/proj dots cover.
"""

import jax
import jax.numpy as jnp
import pytest

from fedml_tpu.models.registry import available_models, create_model

# model name -> (example input shape, input dtype, extra factory kwargs)
_EXAMPLES = {
    "lr": ((2, 32), jnp.float32, {}),
    "mlp": ((2, 32), jnp.float32, {}),
    "purchasemlp": ((2, 600), jnp.float32, {}),
    "texasmlp": ((2, 6169), jnp.float32, {}),
    "cnn_fedavg": ((2, 28, 28, 1), jnp.float32, {}),
    "cnn": ((2, 28, 28, 1), jnp.float32, {}),
    "cnn_cifar": ((2, 32, 32, 3), jnp.float32, {}),
    "har_cnn": ((2, 128, 9), jnp.float32, {}),
    "resnet20": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet32": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet44": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet56": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet56_s2d": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet110": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet18": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet34": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet50": ((2, 32, 32, 3), jnp.float32, {}),
    "resnet18_gn": ((2, 24, 24, 3), jnp.float32, {}),
    "mobilenet": ((2, 32, 32, 3), jnp.float32, {}),
    "mobilenet_v3": ((2, 32, 32, 3), jnp.float32, {"mode": "SMALL"}),
    "efficientnet": ((2, 32, 32, 3), jnp.float32,
                     {"variant": "efficientnet-b0"}),
    "vgg11": ((2, 32, 32, 3), jnp.float32, {}),
    "vgg16": ((2, 32, 32, 3), jnp.float32, {}),
    "deeplab": ((2, 32, 32, 3), jnp.float32, {}),
    "fcn": ((2, 16, 16, 3), jnp.float32, {}),
    "rnn": ((2, 16), jnp.int32, {"vocab_size": 90}),
    "rnn_stackoverflow": ((2, 12), jnp.int32, {}),
    "transformer_nwp": ((2, 16), jnp.int32, {}),
}

_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")


def _walk_eqns(jaxpr):
    """All eqns, recursing into scan/cond/pjit/... sub-jaxprs — but NOT into
    pallas kernels (see module docstring)."""
    for eqn in jaxpr.eqns:
        yield eqn
        if "pallas" in eqn.primitive.name:
            continue
        for v in eqn.params.values():
            for sub in jax.tree.leaves(v, is_leaf=lambda l: isinstance(
                    l, (jax.extend.core.Jaxpr, jax.extend.core.ClosedJaxpr))):
                if isinstance(sub, jax.extend.core.ClosedJaxpr):
                    yield from _walk_eqns(sub.jaxpr)
                elif isinstance(sub, jax.extend.core.Jaxpr):
                    yield from _walk_eqns(sub)


def _assert_no_f32_matmul(jaxpr, name):
    bad = []
    for eqn in _walk_eqns(jaxpr):
        if eqn.primitive.name in _MATMUL_PRIMS:
            dt = eqn.outvars[0].aval.dtype
            if dt != jnp.bfloat16:
                bad.append(f"{eqn.primitive.name} -> {dt}")
    assert not bad, (
        f"model {name!r} with dtype='bfloat16' still lowers f32 matmuls "
        f"(MXU half-rate): {bad[:8]}{' ...' if len(bad) > 8 else ''}")


def _forward_jaxpr(module, shape, in_dtype):
    rng = jax.random.PRNGKey(0)
    x = jax.ShapeDtypeStruct(shape, in_dtype)
    var_shapes = jax.eval_shape(
        lambda: module.init({"params": rng, "dropout": rng},
                            jnp.zeros(shape, in_dtype), train=False))
    variables = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes)
    return jax.make_jaxpr(
        lambda v, xx: module.apply(v, xx, train=False))(variables, x).jaxpr


def test_examples_cover_every_registered_model():
    # a new registration without an example here must fail loudly — the
    # whole point is that the NEXT model added can't dodge the dtype knob
    missing = set(available_models()) - set(_EXAMPLES)
    assert not missing, (
        f"models registered without a dtype-enforcement example: "
        f"{sorted(missing)} — add them to _EXAMPLES in {__file__}")


@pytest.mark.parametrize("name", sorted(_EXAMPLES))
def test_bf16_forward_has_no_f32_matmul(name):
    if name not in available_models():
        pytest.skip(f"{name} not registered")
    shape, in_dtype, kw = _EXAMPLES[name]
    module = create_model(name, output_dim=10, dtype="bfloat16", **kw)
    _assert_no_f32_matmul(_forward_jaxpr(module, shape, in_dtype), name)


def test_bf16_params_stay_f32():
    # mixed precision contract: compute bf16, parameters f32 (aggregation,
    # optimizer state, and checkpoints all stay full precision)
    m = create_model("resnet20", output_dim=10, dtype="bfloat16")
    rng = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(
        lambda: m.init({"params": rng}, jnp.zeros((1, 32, 32, 3)), train=False))
    for leaf in jax.tree.leaves(shapes["params"]):
        assert leaf.dtype == jnp.float32


def test_darts_supernet_bf16_mixed_op_path():
    # DARTSNetwork is built directly by FedNASAPI (not via the registry) —
    # enforce the mixed-op tensordot stays bf16 (f32 alphas must not promote)
    from fedml_tpu.models.darts import DARTSNetwork, init_alphas

    net = DARTSNetwork(output_dim=10, channels=4, layers=2,
                       dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    an, ar = init_alphas(rng)
    x = jnp.zeros((2, 16, 16, 3))
    var_shapes = jax.eval_shape(
        lambda: net.init({"params": rng}, x, an, ar, train=False))
    variables = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shapes)
    jaxpr = jax.make_jaxpr(
        lambda v, xx, a, b: net.apply(v, xx, a, b, train=False))(
        variables, jax.ShapeDtypeStruct(x.shape, x.dtype), an, ar).jaxpr
    _assert_no_f32_matmul(jaxpr, "darts")
