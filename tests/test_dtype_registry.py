"""Registry-wide bf16 compute-dtype enforcement — on the graft-lint analyzer.

Every registered model must honor `create_model(name, dtype="bfloat16")`:
the traced forward jaxpr may contain NO f32 dot_general / conv_general_dilated
— a silently-f32 matmul runs the MXU at half rate and is exactly the class of
regression this test exists to catch (PERF.md: bf16 moved ResNet-56
7,641 -> 12,464 samples/s/chip). A new factory that drops the dtype knob
fails here, not in a bench three rounds later.

The jaxpr walker, the example table, and the rule itself live in
fedml_tpu/analysis (shared with `python -m fedml_tpu.analysis`) — this file
is the per-model parametrization of that rule, so a failure names the model.
The pallas exemption (flash attention accumulates f32 inside the kernel by
design) is the walker's, not this file's.
"""

import jax
import jax.numpy as jnp
import pytest

from fedml_tpu.analysis.jaxpr_engine import check_dtype_policy
from fedml_tpu.analysis.targets import (
    MODEL_EXAMPLES,
    darts_jaxpr,
    model_jaxpr,
    models_missing_examples,
)
from fedml_tpu.models.registry import available_models, create_model


def test_examples_cover_every_registered_model():
    # a new registration without an example must fail loudly — the whole
    # point is that the NEXT model added can't dodge the dtype knob
    missing = models_missing_examples()
    assert not missing, (
        f"models registered without a dtype-enforcement example: "
        f"{missing} — add them to MODEL_EXAMPLES in "
        f"fedml_tpu/analysis/targets.py")


@pytest.mark.parametrize("name", sorted(MODEL_EXAMPLES))
def test_bf16_forward_has_no_f32_matmul(name):
    if name not in available_models():
        pytest.skip(f"{name} not registered")
    findings = check_dtype_policy(model_jaxpr(name), name,
                                  policy=jnp.bfloat16)
    assert not findings, (
        f"model {name!r} with dtype='bfloat16' still lowers f32 matmuls "
        f"(MXU half-rate): " + "; ".join(f.message for f in findings[:8]))


def test_bf16_params_stay_f32():
    # mixed precision contract: compute bf16, parameters f32 (aggregation,
    # optimizer state, and checkpoints all stay full precision)
    m = create_model("resnet20", output_dim=10, dtype="bfloat16")
    rng = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(
        lambda: m.init({"params": rng}, jnp.zeros((1, 32, 32, 3)), train=False))
    for leaf in jax.tree.leaves(shapes["params"]):
        assert leaf.dtype == jnp.float32


def test_darts_supernet_bf16_mixed_op_path():
    # DARTSNetwork is built directly by FedNASAPI (not via the registry) —
    # enforce the mixed-op tensordot stays bf16 (f32 alphas must not promote)
    findings = check_dtype_policy(darts_jaxpr(), "darts",
                                  policy=jnp.bfloat16)
    assert not findings, "; ".join(f.message for f in findings)
