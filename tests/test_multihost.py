"""Multi-process jax.distributed CPU tests (VERDICT r1 item 9; widened per
VERDICT r4 weak #5): the multihost control plane, cross-process sharded +
hierarchical rounds, cross-process ppermute gossip, and killed-process
failure detection — all without TPUs. Workers share one 8-device global
mesh (8 // nproc virtual CPU devices each)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_workers(nproc: int, mode: str = "train", extra: tuple = ()):
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env.pop("PYTHONSTARTUP", None)
    # the worker sets its own JAX_PLATFORMS/XLA_FLAGS before importing jax;
    # strip any inherited device-count forcing so 8/nproc-per-process sticks
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    return [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port), mode,
             *[str(a) for a in extra]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for pid in range(nproc)
    ]


def _communicate(procs, timeout):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out:\n" + "\n".join(outs))
    return outs


def test_init_timeout_default_and_error_wrapping(monkeypatch):
    """ISSUE 4 satellite: init_multihost passes initialization_timeout
    through to jax.distributed.initialize — defaulting to 300s when unset —
    and rewraps a startup failure into a RuntimeError naming the
    coordinator and process slot (the facts an operator needs)."""
    import jax

    from fedml_tpu.parallel import multihost

    calls = {}
    monkeypatch.setattr(multihost, "_distributed_initialized", lambda: False)

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None, initialization_timeout=None):
        calls.update(coordinator_address=coordinator_address,
                     num_processes=num_processes, process_id=process_id,
                     initialization_timeout=initialization_timeout)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    info = multihost.init_multihost("localhost:1234", 2, 0)
    assert calls["initialization_timeout"] == multihost.DEFAULT_INIT_TIMEOUT == 300
    assert info["process_count"] >= 1

    multihost.init_multihost("localhost:1234", 2, 0,
                             initialization_timeout=7)
    assert calls["initialization_timeout"] == 7

    def dead_peer_init(**kw):
        raise RuntimeError("barrier wait deadline exceeded")

    monkeypatch.setattr(jax.distributed, "initialize", dead_peer_init)
    with pytest.raises(RuntimeError) as ei:
        multihost.init_multihost("badhost:9999", 2, 1,
                                 initialization_timeout=5)
    msg = str(ei.value)
    assert "timed out" in msg and "badhost:9999" in msg
    assert "process_id=1" in msg and "num_processes=2" in msg
    assert isinstance(ei.value.__cause__, RuntimeError)


@pytest.mark.skipif(
    not os.environ.get("FEDML_TPU_TESTS_ON_TPU"),
    reason="this jaxlib's CPU backend rejects cross-process collectives "
           "(XlaRuntimeError: 'Multiprocess computations aren't implemented "
           "on the CPU backend' from multihost_utils.broadcast_one_to_all) "
           "— the full n-process round needs a real multihost backend; the "
           "control-plane and failure-detection halves still run here")
@pytest.mark.parametrize("nproc", [2, 4])
def test_distributed_round_n_processes(nproc):
    """Control plane + sharded FedAvg + two-level hierarchical mesh +
    ppermute gossip across nproc real processes. At nproc=4 each hierarchy
    group's in-group psum itself spans two processes (the 2x2 grid the
    verdict asked for)."""
    procs = _spawn_workers(nproc)
    outs = _communicate(procs, timeout=420)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid}" in out, out


def test_sharded_cohort_sampling_two_processes(tmp_path):
    """ISSUE 7 acceptance: 2 real processes over ONE shared mmap shard
    store derive the same seed-deterministic cohort with zero communication,
    and their per-host slices partition it exactly (assertions live in
    multihost_worker._cohort_exercise)."""
    import numpy as np

    from fedml_tpu.data.packed_store import write_packed_shards
    from fedml_tpu.data.packing import PackedClients

    rng = np.random.RandomState(0)
    clients, n_max, dim = 500, 4, 6
    packed = PackedClients(
        rng.rand(clients, n_max, dim).astype(np.float32),
        rng.randint(0, 3, size=(clients, n_max)).astype(np.int32),
        rng.randint(1, n_max + 1, size=clients).astype(np.int64))
    store_dir = str(tmp_path / "store")
    write_packed_shards(store_dir, packed, clients_per_shard=128)

    procs = _spawn_workers(2, mode="cohort", extra=(store_dir,))
    outs = _communicate(procs, timeout=300)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid}" in out, out


@pytest.mark.slow  # ~16s of subprocess spawn + heartbeat timeout; the
# failure-detection logic itself is unit-covered in multihost tests above
def test_dead_process_fails_cleanly():
    """Failure detection: when a silo never joins, the surviving processes
    must terminate with a clear startup-timeout error — bounded by
    init_multihost(initialization_timeout=10) — not hang (the reference's
    mpirun deployment hangs until the scheduler kills it)."""
    procs = _spawn_workers(2, mode="defect")
    outs = _communicate(procs, timeout=120)
    # worker 1 defects by design
    assert procs[1].returncode == 0 and "DEFECTOR" in outs[1]
    # worker 0 must FAIL (not hang, not succeed), with a timeout diagnostic
    assert procs[0].returncode != 0, outs[0]
    assert "MULTIHOST_OK" not in outs[0]
    assert ("timed out" in outs[0].lower() or "timeout" in outs[0].lower()
            or "deadline" in outs[0].lower()), outs[0]
