"""2-process jax.distributed CPU test (VERDICT r1 item 9): proves the
multihost control plane and a cross-process sharded round without TPUs.
Spawns two subprocesses with a local coordinator; each owns 4 virtual CPU
devices of one 8-device global mesh."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distributed_round():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env.pop("PYTHONSTARTUP", None)
    # the worker sets its own JAX_PLATFORMS/XLA_FLAGS before importing jax;
    # strip any inherited device-count forcing so 4-per-process sticks
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    procs = [
        subprocess.Popen([sys.executable, worker, str(pid), "2", str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out:\n" + "\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid}" in out, out
