"""FedSeg tests: losses, metrics, LR schedules, end-to-end segmentation FL."""


import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedseg import (
    confusion_matrix,
    evaluator_scores,
    make_lr_schedule,
    segmentation_ce,
    segmentation_focal,
    SegmentationTrainer,
)
from fedml_tpu.models.segmentation import SimpleFCN


def test_segmentation_ce_ignores_index():
    logits = jnp.zeros((1, 2, 2, 3))
    target = jnp.array([[[0, 255], [1, 2]]])
    per, m = segmentation_ce(logits, target)
    assert float(m.sum()) == 3.0  # the 255 pixel is masked out
    np.testing.assert_allclose(np.asarray(per[0, 0, 1]), 0.0, atol=1e-6)


def test_focal_loss_downweights_easy_pixels():
    easy = jnp.array([[[[10.0, 0.0, 0.0]]]])  # confident correct
    hard = jnp.array([[[[0.1, 0.0, 0.0]]]])
    target = jnp.zeros((1, 1, 1), jnp.int32)
    le, _ = segmentation_focal(easy, target)
    lh, _ = segmentation_focal(hard, target)
    ce_e, _ = segmentation_ce(easy, target)
    ce_h, _ = segmentation_ce(hard, target)
    # focal shrinks easy-pixel loss far more than hard-pixel loss
    assert float(le.sum()) / max(float(ce_e.sum()), 1e-9) < float(lh.sum()) / float(ce_h.sum())


def test_confusion_matrix_and_scores():
    pred = jnp.array([[0, 1], [1, 1]])
    target = jnp.array([[0, 1], [255, 0]])
    cm = confusion_matrix(pred, target, 2)
    np.testing.assert_array_equal(np.asarray(cm), [[1, 1], [0, 1]])
    s = evaluator_scores(cm)
    assert abs(s["Acc"] - 2 / 3) < 1e-6
    assert 0 <= s["mIoU"] <= 1
    assert 0 <= s["FWIoU"] <= 1


def test_perfect_prediction_scores_one():
    t = jnp.array([[0, 1, 2]])
    cm = confusion_matrix(t, t, 3)
    s = evaluator_scores(cm)
    assert abs(s["Acc"] - 1.0) < 1e-9
    assert abs(s["mIoU"] - 1.0) < 1e-9


@pytest.mark.parametrize("mode", ["cos", "poly", "step"])
def test_lr_schedules(mode):
    sched = make_lr_schedule(mode, 0.1, num_epochs=10, iters_per_epoch=5,
                             lr_step=3, warmup_epochs=1)
    lrs = [float(sched(t)) for t in range(50)]
    assert lrs[0] < lrs[5]  # warmup ramps
    assert lrs[-1] <= lrs[6] + 1e-9  # decays after warmup
    assert all(l >= 0 for l in lrs)


@pytest.mark.slow  # ~28s segmentation drive; ci_smoke's fedseg CLI step runs
# the same end-to-end path on every push
def test_fedseg_end_to_end():
    """Tiny FCN learns a synthetic segmentation task through FedAvgAPI with
    SegmentationTrainer (per-pixel labels + ignore_index)."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset

    rng = np.random.RandomState(0)
    C, n, h, w = 4, 24, 16, 16
    # low-frequency task (so it survives the encoder's 4x downsampling):
    # a 4x4 sign field upsampled to 16x16; segment = sign > 0
    seed_field = rng.normal(size=(C, n, 4, 4)).astype(np.float32)
    field = np.kron(seed_field, np.ones((1, 1, 4, 4), np.float32))
    x = (field + 0.1 * rng.normal(size=(C, n, h, w)).astype(np.float32))[..., None]
    y = (field > 0).astype(np.int32)
    ignore = rng.rand(C, n, h, w) < 0.05
    y[ignore] = 255
    counts = np.full(C, n, np.int32)
    packed = PackedClients(x, y, counts)
    flat_x = x.reshape(-1, h, w, 1)
    flat_y = y.reshape(-1, h, w)
    ds = FederatedDataset(name="synthseg", train=packed, test=packed,
                          train_global=(flat_x, flat_y),
                          test_global=(flat_x[:32], flat_y[:32]), class_num=2)
    # lr scaled by the batch size: the trainer reproduces the reference's
    # batch_average loss scale (mean-CE / n), under which the old 0.1 is
    # effectively 0.1/8
    cfg = FedConfig(comm_round=8, batch_size=8, lr=0.8, epochs=5, momentum=0.9,
                    client_num_in_total=C, client_num_per_round=C, ci=1,
                    frequency_of_the_test=7)
    api = FedAvgAPI(ds, cfg, SegmentationTrainer(SimpleFCN(output_dim=2, width=8)))
    hist = api.train()
    assert hist[-1]["Test/Acc"] > 0.75  # pixel accuracy on the easy task
    assert hist[-1]["Test/Loss"] < hist[0]["Test/Loss"]


def test_fedseg_api_evaluate_metrics():
    """FedSegAPI.evaluate (the fused confusion-matrix eval path) runs and
    returns sane segmentation metrics — direct unit coverage for cm_batches,
    which a past refactor broke while only the CLI smoke exercised it."""
    from fedml_tpu.algorithms.fedseg import FedSegAPI, SegmentationTrainer
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset

    rng = np.random.RandomState(2)
    C, n, h, w = 2, 8, 16, 16
    x = rng.rand(C, n, h, w, 1).astype(np.float32)
    y = rng.randint(0, 2, size=(C, n, h, w)).astype(np.int32)
    y[0, 0, :2, :2] = 255
    packed = PackedClients(x, y, np.full(C, n, np.int32))
    ds = FederatedDataset(name="synthseg", train=packed, test=packed,
                          train_global=(x.reshape(-1, h, w, 1), y.reshape(-1, h, w)),
                          test_global=(x.reshape(-1, h, w, 1)[:8], y.reshape(-1, h, w)[:8]),
                          class_num=2)
    cfg = FedConfig(comm_round=1, batch_size=4, lr=0.1, epochs=1,
                    client_num_in_total=C, client_num_per_round=C)
    api = FedSegAPI(ds, cfg, SegmentationTrainer(SimpleFCN(output_dim=2, width=4)))
    api.train_one_round(0)
    keeper = api.evaluate()  # reference-parity EvaluationMetricsKeeper
    for v in (keeper.accuracy, keeper.accuracy_class, keeper.mIoU,
              keeper.FWIoU, keeper.loss):
        assert np.isfinite(v), vars(keeper)
    assert 0.0 <= keeper.mIoU <= 1.0


def test_fedseg_checkpoint_resume_exact(tmp_path):
    """A FedSeg run interrupted mid-run resumes exactly (model + aggregator
    state + eval history) — previously FedSegAPI only SAVED checkpoints and
    restarted from round 0 on rerun."""
    import jax

    from fedml_tpu.algorithms.fedseg import FedSegAPI, SegmentationTrainer
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset

    rng = np.random.RandomState(5)
    C, n, h, w = 2, 8, 16, 16
    x = rng.rand(C, n, h, w, 1).astype(np.float32)
    y = (x[..., 0] > 0.5).astype(np.int32)
    packed = PackedClients(x, y, np.full(C, n, np.int32))
    ds = FederatedDataset(name="synthseg", train=packed, test=packed,
                          train_global=(x.reshape(-1, h, w, 1), y.reshape(-1, h, w)),
                          test_global=(x.reshape(-1, h, w, 1)[:8], y.reshape(-1, h, w)[:8]),
                          class_num=2)
    cfg = FedConfig(comm_round=3, batch_size=4, lr=0.1, epochs=1,
                    client_num_in_total=C, client_num_per_round=C, seed=0)

    def fresh():
        return FedSegAPI(ds, cfg, SegmentationTrainer(SimpleFCN(output_dim=2, width=4)))

    straight = fresh()
    straight.train()

    ck = str(tmp_path / "ck")
    first = fresh()
    for r in range(2):
        m = first._inner.train_one_round(r)
        first.history.append({"round": r, **{k: float(v) for k, v in m.items()}})
    first._inner.history = first.history
    first._inner.save_checkpoint(ck, 2)

    resumed = fresh()
    resumed.train(ckpt_dir=ck)
    for a, b in zip(jax.tree.leaves(straight.global_variables),
                    jax.tree.leaves(resumed.global_variables)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert len(resumed.history) == 3


def test_fedseg_default_model_honors_config_dtype():
    """FedSegAPI's default DeepLab build must respect config.dtype (the r5
    silent-f32 lesson: an absent knob means f32 regardless of BENCH_DTYPE)."""
    from fedml_tpu.algorithms.fedseg import FedSegAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.registry import load_dataset

    ds = load_dataset("pascal_voc", client_num_in_total=2, image_size=16)
    cfg = FedConfig(batch_size=2, epochs=1, lr=0.01, comm_round=1,
                    client_num_in_total=2, client_num_per_round=2,
                    dtype="bfloat16")
    api = FedSegAPI(ds, cfg)
    assert api.trainer.module.dtype == jnp.bfloat16
    cfg32 = cfg.replace(dtype="float32")
    assert FedSegAPI(ds, cfg32).trainer.module.dtype is None
