"""FedNAS / DARTS tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models.darts import (
    DARTSNetwork,
    PRIMITIVES,
    init_alphas,
    parse_genotype,
)


def test_darts_network_forward_shapes():
    # layers=3 keeps compile cheap while still exercising BOTH cell types:
    # reductions land at cells (layers//3, 2*layers//3) = (1, 2), cell 0 is
    # a normal cell (same placement as layers=4, one normal cell fewer)
    net = DARTSNetwork(output_dim=10, channels=4, layers=3)
    rng = jax.random.PRNGKey(0)
    an, ar = init_alphas(rng)
    assert an.shape == (14, len(PRIMITIVES))
    x = jnp.zeros((2, 16, 16, 3))
    v = net.init({"params": rng}, x, an, ar, train=False)
    out = net.apply(v, x, an, ar, train=False)
    assert out.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(out)))


def test_alphas_change_output():
    net = DARTSNetwork(output_dim=10, channels=4, layers=4)
    rng = jax.random.PRNGKey(0)
    an, ar = init_alphas(rng)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 16, 3))
    v = net.init({"params": rng}, x, an, ar, train=False)
    o1 = net.apply(v, x, an, ar, train=False)
    o2 = net.apply(v, x, an + 1.0 * jax.random.normal(rng, an.shape), ar, train=False)
    assert float(jnp.max(jnp.abs(o1 - o2))) > 1e-6


def test_parse_genotype_structure():
    rng = jax.random.PRNGKey(1)
    an, ar = init_alphas(rng)
    g = parse_genotype(an, ar)
    assert len(g.normal) == 8  # 2 edges per node x 4 nodes
    assert len(g.reduce) == 8
    for op, j in g.normal:
        assert op in PRIMITIVES and op != "none"
    assert list(g.normal_concat) == [2, 3, 4, 5]
    # concentrated alphas pick the expected op
    an2 = np.asarray(an).copy()
    an2[:, :] = -10.0
    an2[:, PRIMITIVES.index("sep_conv_3x3")] = 10.0
    g2 = parse_genotype(jnp.asarray(an2), ar)
    assert all(op == "sep_conv_3x3" for op, _ in g2.normal)


@pytest.mark.slow
def test_unrolled_arch_gradient_differs_from_first_order():
    """The exact unrolled arch gradient (differentiating through the inner
    weight step) carries a second-order term the first-order approximation
    lacks. Raw gradients are compared — after Adam's first step both would
    collapse to sign(g), which is why the step outputs can coincide."""
    import optax

    net = DARTSNetwork(output_dim=4, channels=4, layers=2)
    rng = jax.random.PRNGKey(0)
    an, ar = init_alphas(rng)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8, 3))
    y = jnp.zeros((4,), jnp.int32)
    params = net.init({"params": rng}, x, an, ar, train=False)["params"]
    lr = 0.05

    def ce(p, alphas):
        logits = net.apply({"params": p}, x, alphas[0], alphas[1], train=True)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    g_first = jax.jit(jax.grad(lambda a: ce(params, a)))((an, ar))

    def unrolled_val(a):
        g = jax.grad(ce)(params, a)
        w2 = jax.tree.map(lambda p_, g_: p_ - lr * g_, params, g)
        return ce(w2, a)

    g_unrolled = jax.jit(jax.grad(unrolled_val))((an, ar))
    for gf, gu in zip(jax.tree.leaves(g_first), jax.tree.leaves(g_unrolled)):
        assert np.all(np.isfinite(np.asarray(gu)))
    diff = max(
        float(jnp.max(jnp.abs(gu - gf)))
        for gf, gu in zip(jax.tree.leaves(g_first), jax.tree.leaves(g_unrolled))
    )
    assert diff > 1e-8


@pytest.mark.slow
@pytest.mark.parametrize("unrolled", [False])
def test_fednas_search_round(unrolled):
    from fedml_tpu.algorithms.fednas import FedNASAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset

    rng = np.random.RandomState(0)
    C, n = 2, 16
    x = rng.rand(C, n, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(C, n)).astype(np.int32)
    packed = PackedClients(x, y, np.full(C, n, np.int32))
    ds = FederatedDataset(name="tiny", train=packed, test=packed,
                          train_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          test_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          class_num=4)
    cfg = FedConfig(comm_round=2, epochs=2, batch_size=4, lr=0.05,
                    client_num_in_total=C, client_num_per_round=C)
    api = FedNASAPI(ds, cfg, channels=4, layers=2, unrolled=unrolled)
    a0 = jax.tree.map(lambda a: np.asarray(a).copy(), api.global_state.alphas)
    rec = api.train_one_round(0)
    assert np.isfinite(rec["search_loss"])
    # faithful local search: every real train-half sample is visited exactly
    # once per local epoch (reference local_search sweeps the whole
    # train_queue, FedNASTrainer.py:84-128), including a ragged client
    counts = np.full(C, n)
    assert rec["search_samples"] == cfg.epochs * sum(c // 2 for c in counts)
    api.train_one_round(1)
    # alphas moved (architecture search is actually happening)
    a1 = api.global_state.alphas
    assert float(jnp.max(jnp.abs(a1[0] - a0[0]))) > 1e-6
    assert len(api.genotype_history) == 2
    acc = api.evaluate()["Test/Acc"]
    assert 0.0 <= acc <= 1.0


@pytest.mark.slow
def test_fednas_sweep_counts_ragged_clients():
    """Full-sweep accounting with unequal client sizes: search_samples must be
    sum over clients of epochs * (count_i // 2), proving padded batches are
    masked out and every real sample is swept."""
    from fedml_tpu.algorithms.fednas import FedNASAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset

    rng = np.random.RandomState(0)
    C, n_max = 2, 20
    counts = np.array([20, 9], np.int32)
    x = rng.rand(C, n_max, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(C, n_max)).astype(np.int32)
    packed = PackedClients(x, y, counts)
    ds = FederatedDataset(name="tiny", train=packed, test=packed,
                          train_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          test_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          class_num=4)
    cfg = FedConfig(comm_round=1, epochs=3, batch_size=4, lr=0.05,
                    client_num_in_total=C, client_num_per_round=C)
    api = FedNASAPI(ds, cfg, channels=4, layers=2)
    rec = api.train_one_round(0)
    assert rec["search_samples"] == cfg.epochs * sum(int(c) // 2 for c in counts)


@pytest.mark.slow  # heaviest DARTS compile in the module (~80s); the val-half
# gating logic is also covered by the search_samples assert above
def test_fednas_arch_step_skipped_without_val_half():
    """A count==1 client has no validation half; its 'val' batch would be
    padding. The arch step must be a no-op there (ADVICE r2): a federation of
    only count==1 clients leaves alphas exactly at their init."""
    from fedml_tpu.algorithms.fednas import FedNASAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset

    rng = np.random.RandomState(1)
    C, n_max = 2, 8
    counts = np.array([1, 1], np.int32)
    x = rng.rand(C, n_max, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(C, n_max)).astype(np.int32)
    packed = PackedClients(x, y, counts)
    ds = FederatedDataset(name="tiny", train=packed, test=packed,
                          train_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          test_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          class_num=4)
    cfg = FedConfig(comm_round=1, epochs=2, batch_size=4, lr=0.05,
                    client_num_in_total=C, client_num_per_round=C)
    api = FedNASAPI(ds, cfg, channels=4, layers=2)
    a0 = tuple(np.asarray(a) for a in api.global_state.alphas)
    p0 = jax.tree.leaves(api.global_state.params)[0].copy()
    api.train_one_round(0)
    a1 = api.global_state.alphas
    np.testing.assert_array_equal(a0[0], np.asarray(a1[0]))
    np.testing.assert_array_equal(a0[1], np.asarray(a1[1]))
    # ...while the weight step still trains on the single real sample
    p1 = jax.tree.leaves(api.global_state.params)[0]
    assert float(jnp.max(jnp.abs(p1 - p0))) > 0.0


@pytest.mark.slow
def test_gdas_search_improves_and_parses_genotype():
    """GDAS variant (reference model_search_gdas.py): gumbel-softmax hard
    sampling over the DARTS space — search loss improves on toy data and the
    final alphas parse to a genotype."""
    from fedml_tpu.algorithms.fednas import FedNASAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset
    from fedml_tpu.models.darts import Genotype

    rng = np.random.RandomState(3)
    C, n = 2, 12
    x = rng.rand(C, n, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(C, n)).astype(np.int32)
    packed = PackedClients(x, y, np.full(C, n, np.int32))
    ds = FederatedDataset(name="tiny", train=packed, test=packed,
                          train_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          test_global=(x.reshape(-1, 8, 8, 3), y.reshape(-1)),
                          class_num=4)
    cfg = FedConfig(comm_round=2, epochs=2, batch_size=6, lr=0.1,
                    client_num_in_total=C, client_num_per_round=C)
    api = FedNASAPI(ds, cfg, channels=4, layers=2, gdas=True, tau=5.0)
    a0 = np.asarray(api.global_state.alphas[0]).copy()
    r0 = api.train_one_round(0)
    r1 = api.train_one_round(1)
    assert np.isfinite(r0["search_loss"]) and np.isfinite(r1["search_loss"])
    # alphas moved through the straight-through estimator
    assert float(jnp.max(jnp.abs(np.asarray(api.global_state.alphas[0]) - a0))) > 1e-7
    assert isinstance(api.genotype_history[-1], Genotype)
    assert r1["search_loss"] < r0["search_loss"] * 1.5  # trains, not diverging


@pytest.mark.slow
def test_fednas_checkpoint_resume_exact(tmp_path):
    """A FedNAS search interrupted mid-run and resumed produces exactly the
    same weights, alphas, optimizer states, and genotype history as an
    uninterrupted run (VERDICT r3 #7 — the reference only logs genotypes,
    FedNASAggregator.py:173, and cannot resume)."""
    from fedml_tpu.algorithms.fednas import FedNASAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()  # three identical round_fn compiles -> one
    ds = load_dataset("cifar10", client_num_in_total=3, partition_method="homo",
                      seed=0)
    cfg = FedConfig(client_num_in_total=3, client_num_per_round=2, comm_round=2,
                    batch_size=8, lr=0.025, momentum=0.9, wd=3e-4, epochs=1,
                    seed=0)

    def fresh():
        return FedNASAPI(ds, cfg, channels=4, layers=2)

    straight = fresh()
    straight.train()

    ck = str(tmp_path / "ck")
    first = fresh()
    rec0 = first.train_one_round(0)  # exactly once — it mutates global_state
    first.history.append({"round": 0, "search_loss": rec0["search_loss"],
                          "search_acc": rec0["search_acc"]})
    first.save_checkpoint(ck, 1)

    resumed = fresh()
    resumed.train(ckpt_dir=ck)

    for a, b in zip(jax.tree.leaves(tuple(straight.global_state)),
                    jax.tree.leaves(tuple(resumed.global_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert len(resumed.history) == 2
    # genotypes JSON-normalize (namedtuples round-trip to nested lists)
    import json as _json

    assert (_json.dumps(resumed.genotype_history[-1])
            == _json.dumps(straight.genotype_history[-1]))
    assert len(resumed.genotype_history) == len(straight.genotype_history)


@pytest.mark.slow
def test_main_fednas_cli(tmp_path):
    """CLI-level coverage for main_fednas (VERDICT r3 weak #5: argparse
    wiring rots precisely when untested) — tiny DARTS, 1 round, genotype
    recorded in the wandb summary like reference FedNASAggregator.py:173."""
    import json

    from fedml_tpu.experiments.main_fednas import main

    hist = main([
        "--dataset", "cifar10", "--model", "lr", "--client_num_in_total", "2",
        "--client_num_per_round", "2", "--comm_round", "1", "--epochs", "1",
        "--batch_size", "8", "--init_channels", "4", "--layers", "1",
        "--steps", "2", "--multiplier", "2", "--run_dir", str(tmp_path / "run"),
    ])
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert 0.0 <= summary["search_acc"] <= 1.0
    assert summary["genotype"].startswith("Genotype(normal=")
    assert len(hist) == 1
