"""Reference-parity oracle — runs the ACTUAL reference implementation.

Every numerics-parity claim elsewhere in the repo rests on code citations;
this module converts them into *measured trajectory matches* by importing the
living reference from /root/reference (torch CPU) and running it against the
JAX engine on identical tiny data with identical initial weights:

  (a) local trainer  — reference MyModelTrainer.train
      (fedml_api/standalone/fedavg/my_model_trainer_classification.py:17-53:
      CE loss, SGD(lr) or Adam(lr, wd, amsgrad=True), unconditional
      clip_grad_norm 1.0) vs engine.build_local_update, multi-epoch minibatch
      trajectories with matched batch order (cfg.shuffle=False ≙ a fixed-order
      DataLoader).
  (b) FedAvg round   — reference standalone FedAvgAPI._aggregate
      (fedavg_api.py:102-117) over per-client reference training vs one
      engine round_fn.
  (c) FedOpt server  — reference FedOptAggregator.aggregate
      (fedml_api/distributed/fedopt/FedOptAggregator.py:94-123: pseudo-grad
      w_global - w_avg into a persistent torch server optimizer) vs
      FedOptAggregator over 3 rounds (exercises optimizer-state carryover).
  (d) FedNova        — reference FedNova optimizer + Client.train norm-grads
      (standalone/fednova/fednova.py:79-153, client.py:41-109) +
      FedNovaTrainer.aggregate (fednova_trainer.py:104-125) vs
      FedNovaAggregator, with heterogeneous per-client local work (different
      sample counts AND different local epochs -> different tau_i).

Intended deviations (documented, none material here):
  - The engine's padded batches reproduce DataLoader(drop_last=False)'s short
    final batch via masked-mean CE — same loss, same grads.
  - optax.clip_by_global_norm has no +1e-6 in the denominator
    (torch clip_grad_norm_ does) — relative difference ~1e-6, absorbed by tol.
  - The reference LogisticRegression applies sigmoid before CE (lr.py:13, a
    known quirk); the test's flax twin replicates the sigmoid so the
    comparison runs through the reference model class unmodified.

Slow-marked: imports torch + many tiny training runs.
"""

from __future__ import annotations

import copy
import sys
from types import SimpleNamespace

import numpy as np
import pytest

pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")

from _reference_oracle import setup_reference, torch_batches  # noqa: E402

setup_reference()
# the living-reference checkout is not shipped in every container;
# without it the oracle has nothing to run — skip at collect time
# instead of erroring the whole module
pytest.importorskip(
    "fedml_api",
    reason="reference FedML checkout (/root/reference) unavailable")

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from fedml_tpu.algorithms.aggregators import (  # noqa: E402
    FedAvgAggregator,
    FedNovaAggregator,
    FedOptAggregator,
)
from fedml_tpu.algorithms.engine import build_local_update, build_round_fn  # noqa: E402
from fedml_tpu.core.config import FedConfig  # noqa: E402
from fedml_tpu.core.trainer import ClassificationTrainer  # noqa: E402

from fedml_api.model.linear.lr import LogisticRegression as TorchLR  # noqa: E402
from fedml_api.standalone.fedavg.my_model_trainer_classification import (  # noqa: E402
    MyModelTrainer,
)

D, C = 8, 5  # feature dim, classes


class SigmoidLR(nn.Module):
    """Flax twin of reference linear/lr.py:4-14 (sigmoid before the loss)."""

    output_dim: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.sigmoid(nn.Dense(self.output_dim, name="linear")(x))


def _make_data(n, seed):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(D, C)
    # scale 4x so the global grad norm exceeds the 1.0 clip bound through the
    # sigmoid (test_grad_clip_is_active_in_parity_regime asserts this) — the
    # clip numerics are then genuinely part of the compared trajectories
    x = (12.0 * rng.randn(n, D)).astype(np.float32)
    y = (x @ w_true + 0.5 * rng.randn(n, C)).argmax(-1).astype(np.int32)
    return x, y


def _init_weights(seed):
    rng = np.random.RandomState(seed)
    w = (rng.randn(C, D) * 0.03).astype(np.float32)  # torch layout [out, in]
    b = (rng.randn(C) * 0.1).astype(np.float32)
    return w, b


def _torch_model(w, b):
    m = TorchLR(D, C)
    with torch.no_grad():
        m.linear.weight.copy_(torch.from_numpy(w))
        m.linear.bias.copy_(torch.from_numpy(b))
    return m


def _jax_variables(w, b):
    return {"params": {"linear": {"kernel": jnp.asarray(w.T), "bias": jnp.asarray(b)}}}


_torch_batches = torch_batches  # shared scaffolding (tests/_reference_oracle.py)


def _ref_params_np(model):
    sd = model.state_dict()
    return {k: v.detach().numpy().copy() for k, v in sd.items()}


def _assert_match(ref_sd, variables, atol=5e-5, rtol=5e-4):
    p = variables["params"]["linear"]
    np.testing.assert_allclose(
        ref_sd["linear.weight"], np.asarray(p["kernel"]).T, atol=atol, rtol=rtol
    )
    np.testing.assert_allclose(
        ref_sd["linear.bias"], np.asarray(p["bias"]), atol=atol, rtol=rtol
    )


def _pad(x, y, n_max):
    nx = np.zeros((n_max,) + x.shape[1:], x.dtype)
    ny = np.zeros((n_max,) + y.shape[1:], y.dtype)
    nx[: len(x)], ny[: len(y)] = x, y
    return nx, ny


# ---------------------------------------------------------------------------
# (a) local trainer: SGD+clip and Adam(amsgrad, wd) minibatch trajectories
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "opt_name,lr,wd", [("sgd", 0.3, 0.0), ("adam", 0.05, 0.01)]
)
def test_local_trainer_parity(opt_name, lr, wd):
    n, bs = 22, 8  # 3 batches/epoch, short final batch (drop_last=False path)
    x, y = _make_data(n, seed=1)
    w0, b0 = _init_weights(seed=2)

    cfg = FedConfig(
        client_optimizer=opt_name, lr=lr, wd=wd, batch_size=bs,
        grad_clip=1.0, momentum=0.0, shuffle=False,
    )
    trainer = ClassificationTrainer(SigmoidLR(C))

    # lr=0.3 steps with clip ACTIVE at the start (verified below) so the
    # clip numerics themselves are part of the trajectory being compared
    for epochs in (1, 4, 10):
        model = _torch_model(w0, b0)
        ref_trainer = MyModelTrainer(model)
        args = SimpleNamespace(client_optimizer=opt_name, lr=lr, wd=wd, epochs=epochs)
        ref_trainer.train(_torch_batches(x, y, bs), torch.device("cpu"), args)
        ref_sd = _ref_params_np(model)

        local = build_local_update(trainer, cfg.replace(epochs=epochs))
        res = local(
            _jax_variables(w0, b0), jnp.asarray(x), jnp.asarray(y),
            jnp.int32(n), jax.random.PRNGKey(0),
        )
        assert int(res.num_steps) == epochs * 3
        _assert_match(ref_sd, res.variables)

    # sanity: the run actually moved the weights (a vacuous match would pass)
    assert np.abs(ref_sd["linear.weight"] - w0).max() > 1e-3


def test_grad_clip_is_active_in_parity_regime():
    """The SGD parity case must exercise the clip path, not just plain SGD."""
    n, bs = 22, 8
    x, y = _make_data(n, seed=1)
    w0, b0 = _init_weights(seed=2)
    model = _torch_model(w0, b0)
    bx, by = _torch_batches(x, y, bs)[0]
    loss = torch.nn.CrossEntropyLoss()(model(bx), by)
    loss.backward()
    total_norm = torch.sqrt(
        sum((p.grad**2).sum() for p in model.parameters())
    ).item()
    assert total_norm > 1.0  # clip at 1.0 triggers on the first step


# ---------------------------------------------------------------------------
# (b) one FedAvg round: per-client reference training + _aggregate
# ---------------------------------------------------------------------------


def test_fedavg_round_parity():
    from fedml_api.standalone.fedavg.fedavg_api import FedAvgAPI

    counts = [6, 10, 7, 9]
    n_max = max(counts)
    datas = [_make_data(c, seed=10 + i) for i, c in enumerate(counts)]
    w0, b0 = _init_weights(seed=3)
    epochs, bs, lr = 2, 4, 0.2

    # reference: train each client from the same global weights, then
    # sample-weighted average (fedavg_api.py:102-117; pass deep copies since
    # _aggregate mutates w_locals[0] in place — a known reference defect)
    w_locals = []
    for (x, y), cnt in zip(datas, counts):
        model = _torch_model(w0, b0)
        ref_trainer = MyModelTrainer(model)
        args = SimpleNamespace(client_optimizer="sgd", lr=lr, wd=0.0, epochs=epochs)
        ref_trainer.train(_torch_batches(x, y, bs), torch.device("cpu"), args)
        w_locals.append((cnt, copy.deepcopy(model.state_dict())))
    ref_avg = {k: v.numpy() for k, v in FedAvgAPI._aggregate(None, w_locals).items()}

    cfg = FedConfig(
        client_optimizer="sgd", lr=lr, batch_size=bs, epochs=epochs,
        grad_clip=1.0, shuffle=False,
    )
    trainer = ClassificationTrainer(SigmoidLR(C))
    agg = FedAvgAggregator(cfg)
    round_fn = build_round_fn(trainer, cfg, agg)
    xs = np.stack([_pad(x, y, n_max)[0] for x, y in datas])
    ys = np.stack([_pad(x, y, n_max)[1] for x, y in datas])
    gv = _jax_variables(w0, b0)
    new_global, _, _ = round_fn(
        gv, agg.init_state(gv), jnp.asarray(xs), jnp.asarray(ys),
        jnp.asarray(counts, jnp.int32), jax.random.PRNGKey(0),
    )
    _assert_match(ref_avg, new_global)


# ---------------------------------------------------------------------------
# (c) FedOpt server optimizer over 3 rounds (state persists across rounds)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("server_opt,server_lr",
                         [("adam", 0.03), ("sgd", 0.7), ("adagrad", 0.1)])
def test_fedopt_server_parity(server_opt, server_lr):
    from fedml_api.distributed.fedopt.FedOptAggregator import (
        FedOptAggregator as RefFedOptAggregator,
    )

    counts = [6, 10, 7, 9]
    n_max = max(counts)
    datas = [_make_data(c, seed=20 + i) for i, c in enumerate(counts)]
    w0, b0 = _init_weights(seed=4)
    epochs, bs, lr, rounds = 1, 4, 0.2, 3

    # reference aggregator without its heavy constructor (it wants live
    # dataloaders + wandb); aggregate() itself only touches these fields
    global_model = _torch_model(w0, b0)
    ref = RefFedOptAggregator.__new__(RefFedOptAggregator)
    ref.trainer = MyModelTrainer(global_model)
    ref.args = SimpleNamespace(
        server_optimizer=server_opt, server_lr=server_lr, is_mobile=0
    )
    ref.worker_num = len(counts)
    ref.model_dict, ref.sample_num_dict = {}, {}
    ref.opt = ref._instantiate_opt()

    for _ in range(rounds):
        w_global = copy.deepcopy(ref.trainer.get_model_params())
        for i, ((x, y), cnt) in enumerate(zip(datas, counts)):
            local_model = TorchLR(D, C)
            local_model.load_state_dict(copy.deepcopy(w_global))
            args = SimpleNamespace(client_optimizer="sgd", lr=lr, wd=0.0, epochs=epochs)
            MyModelTrainer(local_model).train(
                _torch_batches(x, y, bs), torch.device("cpu"), args
            )
            ref.model_dict[i] = copy.deepcopy(local_model.state_dict())
            ref.sample_num_dict[i] = cnt
        ref.aggregate()
    ref_sd = _ref_params_np(global_model)

    cfg = FedConfig(
        client_optimizer="sgd", lr=lr, batch_size=bs, epochs=epochs,
        grad_clip=1.0, shuffle=False,
        server_optimizer=server_opt, server_lr=server_lr, server_momentum=0.0,
    )
    trainer = ClassificationTrainer(SigmoidLR(C))
    agg = FedOptAggregator(cfg)
    round_fn = build_round_fn(trainer, cfg, agg)
    xs = np.stack([_pad(x, y, n_max)[0] for x, y in datas])
    ys = np.stack([_pad(x, y, n_max)[1] for x, y in datas])
    gv = _jax_variables(w0, b0)
    st = agg.init_state(gv)
    for _ in range(rounds):
        gv, st, _ = round_fn(
            gv, st, jnp.asarray(xs), jnp.asarray(ys),
            jnp.asarray(counts, jnp.int32), jax.random.PRNGKey(0),
        )
    _assert_match(ref_sd, gv, atol=1e-4, rtol=1e-3)


def test_reference_yogi_is_not_instantiable():
    """Pin a reference limitation: "FedYogi" rides OptRepo reflection over
    torch.optim.Optimizer subclasses (optrepo.py:7-64), and torch ships no
    Yogi — name2cls("yogi") raises KeyError, so the reference cannot actually
    run its advertised FedYogi with stock torch. The rebuild's
    server_optimizer="yogi" (optax.yogi) therefore EXCEEDS the reference and
    has no living oracle to match against; its sgd/adam/adagrad siblings are
    trajectory-matched above."""
    from fedml_api.distributed.fedopt.optrepo import OptRepo

    with pytest.raises(KeyError):
        OptRepo.name2cls("yogi")
    # sanity: the rebuild's yogi path runs
    from fedml_tpu.algorithms.aggregators import FedOptAggregator

    cfg = FedConfig(server_optimizer="yogi", server_lr=0.01)
    agg = FedOptAggregator(cfg)
    gv = _jax_variables(*_init_weights(seed=9))
    st = agg.init_state(gv)
    stacked = jax.tree.map(lambda l: jnp.stack([l, 1.1 * l]), gv)
    result = SimpleNamespace(variables=stacked)
    new_gv, _ = agg(gv, result, jnp.asarray([1.0, 1.0]), None, st)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(new_gv))


# ---------------------------------------------------------------------------
# (d) FedNova with heterogeneous tau_i (counts AND local epochs differ)
# ---------------------------------------------------------------------------


def test_fednova_parity():
    from fedml_api.standalone.fednova.client import Client as RefNovaClient
    from fedml_api.standalone.fednova.fednova_trainer import FedNovaTrainer

    counts = [6, 10, 7, 9]
    n_max = max(counts)
    datas = [_make_data(c, seed=30 + i) for i, c in enumerate(counts)]
    w0, b0 = _init_weights(seed=5)
    bs, lr, epochs = 4, 0.2, 2
    total = sum(counts)

    norm_grads, tau_effs = [], []
    for i, ((x, y), cnt) in enumerate(zip(datas, counts)):
        args = SimpleNamespace(
            lr=lr, gmf=0.0, mu=0.0, momentum=0.0, dampening=0.0,
            wd=0.0, nesterov=False, epochs=epochs, dataset="synthetic",
        )
        client = RefNovaClient(
            i, _torch_batches(x, y, bs), None, cnt, args, torch.device("cpu")
        )
        net = _torch_model(w0, b0)
        _, grad, t_eff = client.train(
            net=net, ratio=torch.tensor([cnt / total], dtype=torch.float32)
        )
        norm_grads.append({k: v.clone() for k, v in grad.items()})
        tau_effs.append(float(t_eff))

    ref_tr = FedNovaTrainer.__new__(FedNovaTrainer)
    ref_tr.args = SimpleNamespace(gmf=0.0, lr=lr)
    ref_tr.global_momentum_buffer = {}
    init = _torch_model(w0, b0).state_dict()
    ref_sd = {
        k: v.numpy().copy()
        for k, v in ref_tr.aggregate(init, norm_grads, tau_effs).items()
    }

    # engine: tau heterogeneity arises from counts (6 samples -> 2 steps/epoch,
    # 10 -> 3) exactly as the reference's per-DataLoader batch counts
    cfg = FedConfig(
        client_optimizer="sgd", lr=lr, batch_size=bs, epochs=epochs,
        grad_clip=None, shuffle=False,
    )
    trainer = ClassificationTrainer(SigmoidLR(C))
    agg = FedNovaAggregator(cfg)
    round_fn = build_round_fn(trainer, cfg, agg)
    xs = np.stack([_pad(x, y, n_max)[0] for x, y in datas])
    ys = np.stack([_pad(x, y, n_max)[1] for x, y in datas])
    gv = _jax_variables(w0, b0)
    new_global, _, _ = round_fn(
        gv, agg.init_state(gv), jnp.asarray(xs), jnp.asarray(ys),
        jnp.asarray(counts, jnp.int32), jax.random.PRNGKey(0),
    )
    # reference tau_i = epochs * ceil(count/bs): [4, 6, 4, 6]
    _assert_match(ref_sd, new_global)


def test_lda_partitioner_exact_parity():
    """(e) The LDA partitioner consumes the SAME numpy rng call sequence as
    the reference (shuffle class indices -> dirichlet -> quota-zeroing ->
    cumsum cuts -> final per-client shuffle), so with an identical seed the
    net_dataidx_map must be IDENTICAL, index for index."""
    from fedml_core.non_iid_partition.noniid_partition import (
        non_iid_partition_with_dirichlet_distribution as ref_lda,
    )

    from fedml_tpu.core.partition import (
        non_iid_partition_with_dirichlet_distribution as our_lda,
    )

    y = np.random.RandomState(123).randint(0, 6, size=400)
    np.random.seed(42)
    ref_map = ref_lda(y, client_num=7, classes=6, alpha=0.5)
    our_map = our_lda(y, client_num=7, classes=6, alpha=0.5,
                      rng=np.random.RandomState(42))
    assert set(ref_map) == set(our_map)
    for k in ref_map:
        np.testing.assert_array_equal(np.asarray(ref_map[k]),
                                      np.asarray(our_map[k]),
                                      err_msg=f"client {k} differs")


def test_robust_clip_parity():
    """(f) Norm-diff clipping vs the reference RobustAggregator
    (fedml_core/robustness/robust_aggregation.py:38-49): same deltas in,
    same clipped weights out (stddev=0 isolates the deterministic clip).

    NB two latent defects in the reference (worked around, not replicated):
    vectorize_weight torch.cat's UNFLATTENED params (crashes for any model
    mixing 2D weights with 1D biases), and load_model_weight_diff calls
    .state_dict() on what its one call site passes as a plain state dict
    (FedAvgRobustAggregator.py:180-182). The test drives the reference lines
    with a single-tensor model via a shim exposing both surfaces; the rebuild
    clips the full pytree correctly (norm over ALL weight leaves)."""
    from fedml_core.robustness.robust_aggregation import (
        RobustAggregator as RefRobust,
    )

    from fedml_tpu.algorithms.aggregators import RobustAggregator
    from fedml_tpu.algorithms.engine import LocalResult

    rng = np.random.RandomState(0)
    gw = rng.normal(size=(D, C)).astype(np.float32)
    # two clients: one small delta (unclipped), one huge (clipped)
    deltas = [0.1 * rng.normal(size=(D, C)).astype(np.float32),
              50.0 * rng.normal(size=(D, C)).astype(np.float32)]
    bound = 2.0

    class _SdShim(dict):
        def state_dict(self):
            return self

    ref_agg = RefRobust(SimpleNamespace(defense_type="norm_diff_clipping",
                                        norm_bound=bound, stddev=0.0))
    g_sd = {"w": torch.tensor(gw)}
    ref_clipped = []
    for dlt in deltas:
        local_sd = _SdShim(w=torch.tensor(gw + dlt))
        out = ref_agg.norm_diff_clipping(local_sd, g_sd)
        ref_clipped.append(out["w"].numpy())
    ref_avg_w = np.mean(ref_clipped, axis=0)

    cfg = FedConfig(norm_bound=bound, stddev=0.0)
    agg = RobustAggregator(cfg)
    gv = {"params": {"dense": {"kernel": jnp.asarray(gw)}}}
    stacked = {"params": {"dense": {
        "kernel": jnp.stack([jnp.asarray(gw + d) for d in deltas]),
    }}}
    result = LocalResult(variables=stacked,
                         num_steps=jnp.ones(2, jnp.int32),
                         metrics={})
    avg, _ = agg(gv, result, jnp.ones(2, jnp.float32),
                 jax.random.PRNGKey(0), ())
    np.testing.assert_allclose(np.asarray(avg["params"]["dense"]["kernel"]),
                               ref_avg_w, rtol=1e-5, atol=1e-6)


def test_symmetric_topology_exact_parity():
    """(g) Decentralized mixing matrices vs the living reference
    (symmetric_topology_manager.py:21-52): Watts-Strogatz at rewire p=0 is a
    deterministic ring lattice, so the row-stochastic mixing matrix must
    match EXACTLY for several (n, neighbor_num) shapes."""
    pytest.importorskip("networkx")  # the reference's dependency
    from fedml_core.distributed.topology.symmetric_topology_manager import (
        SymmetricTopologyManager as RefSym,
    )

    from fedml_tpu.core.topology import SymmetricTopologyManager

    for n, k in [(6, 2), (8, 4), (10, 2), (9, 4)]:
        ref = RefSym(n, neighbor_num=k)
        ref.generate_topology()
        ours = SymmetricTopologyManager(n, neighbor_num=k)
        ours.generate_topology()
        np.testing.assert_allclose(
            np.asarray(ours.topology), np.asarray(ref.topology),
            rtol=0, atol=1e-7, err_msg=f"(n={n}, k={k})")
        for node in range(n):
            assert (ours.get_in_neighbor_idx_list(node)
                    == ref.get_in_neighbor_idx_list(node)), (n, k, node)


def test_homo_and_p_hetero_partition_exact_parity():
    """(h) homo + the fork's p-hetero split vs the living reference
    (data_preprocessing/utils.py:9-58): identical numpy rng sequences, so
    identical maps index for index."""
    from fedml_api.data_preprocessing.utils import (
        homo_partition as ref_homo,
        p_hetero_partition as ref_ph,
    )

    from fedml_tpu.core.partition import homo_partition, p_hetero_partition

    np.random.seed(7)
    ref_h = ref_homo(103, 5)
    our_h = homo_partition(103, 5, rng=np.random.RandomState(7))
    for k in ref_h:
        np.testing.assert_array_equal(ref_h[k], our_h[k])

    y = np.random.RandomState(9).randint(0, 4, size=240)
    np.random.seed(21)
    ref_map = ref_ph(8, y, 0.6)          # 8 clients, 4 classes -> 2 per group
    our_map = p_hetero_partition(8, y, 0.6, rng=np.random.RandomState(21))
    assert set(ref_map) == set(our_map)
    for k in ref_map:
        np.testing.assert_array_equal(np.asarray(ref_map[k]),
                                      np.asarray(our_map[k]),
                                      err_msg=f"client {k} differs")


def test_segmentation_loss_parity():
    """(i) FedSeg training losses vs the living reference SegmentationLosses
    (fedseg/utils.py:71-110), including its quirks: size_average'd CE divided
    AGAIN by batch size (batch_average), and focal applied to the batch-mean
    CE scalar rather than per pixel."""
    from fedml_api.distributed.fedseg.utils import SegmentationLosses

    from fedml_tpu.algorithms.fedseg import (
        reference_focal_scalar,
        segmentation_ce,
    )

    rng = np.random.RandomState(0)
    n, c, h, w = 3, 5, 6, 6
    logits = rng.normal(size=(n, c, h, w)).astype(np.float32)
    target = rng.randint(0, c, size=(n, h, w)).astype(np.int64)
    target[0, :2, :2] = 255  # ignore region

    losses = SegmentationLosses(ignore_index=255)
    ref_ce = float(losses.CrossEntropyLoss(torch.tensor(logits), torch.tensor(target)))
    ref_focal = float(losses.FocalLoss(torch.tensor(logits), torch.tensor(target)))

    jl = jnp.asarray(np.transpose(logits, (0, 2, 3, 1)))  # NHWC
    jt = jnp.asarray(target.astype(np.int32))
    per, m = segmentation_ce(jl, jt, ignore_index=255)
    mean_ce = float((per * m).sum() / m.sum())
    ours_ce = mean_ce / n
    ours_focal = float(reference_focal_scalar(jnp.float32(mean_ce))) / n
    np.testing.assert_allclose(ours_ce, ref_ce, rtol=1e-5)
    np.testing.assert_allclose(ours_focal, ref_focal, rtol=1e-5)


def test_gkt_kl_loss_parity():
    """(j) FedGKT's distillation loss vs the living reference KL_Loss
    (fedgkt/utils.py:75-94): T^2 * batchmean KL with the +1e-7 regularizer."""
    from fedml_api.distributed.fedgkt.utils import KL_Loss

    from fedml_tpu.algorithms.fedgkt import kd_kl_loss

    rng = np.random.RandomState(1)
    student = rng.normal(size=(6, 10)).astype(np.float32)
    teacher = rng.normal(size=(6, 10)).astype(np.float32) * 2
    for T in (1.0, 3.0):
        ref = float(KL_Loss(T)(torch.tensor(student), torch.tensor(teacher)))
        ours = float(jnp.mean(kd_kl_loss(jnp.asarray(student),
                                         jnp.asarray(teacher), T)))
        np.testing.assert_allclose(ours, ref, rtol=2e-5, atol=1e-6)


class _FixedOut(torch.nn.Module):
    """Torch stub returning precomputed outputs — drives the reference
    trainers' test() with known tensors."""

    def __init__(self, out):
        super().__init__()
        self.out = out

    def forward(self, x):
        return self.out


def test_nwp_eval_metrics_parity():
    """(k) NWP masked eval vs the living reference trainer
    (my_model_trainer_nwp.py:54-81): identical correct/total, and the
    reported-loss contract (meanCE-over-non-pad x batch_size)."""
    from fedml_api.standalone.fedavg.my_model_trainer_nwp import (
        MyModelTrainer as RefNWP,
    )

    from fedml_tpu.core.trainer import NWPTrainer

    rng = np.random.RandomState(0)
    B, T, V = 6, 10, 12
    logits = rng.normal(size=(B, T, V)).astype(np.float32)
    y = rng.randint(0, V, size=(B, T)).astype(np.int64)
    y[:, 7:] = 0  # pad tail (ignore_index 0)

    ref = RefNWP(_FixedOut(torch.tensor(np.transpose(logits, (0, 2, 1)))))
    loader = [(torch.zeros(B, T), torch.tensor(y))]
    ref_m = ref.test(loader, torch.device("cpu"), None)

    class _JaxFixed(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return jnp.asarray(logits)

    tr = NWPTrainer(_JaxFixed(), pad_id=0)
    ours = tr.eval_fn({"params": {}},
                      {"x": jnp.zeros((B, T)), "y": jnp.asarray(y.astype(np.int32)),
                       "mask": jnp.ones(B)})
    assert float(ours["test_correct"]) == ref_m["test_correct"]
    assert float(ours["test_total"]) == ref_m["test_total"]
    np.testing.assert_allclose(float(ours["test_loss"]), ref_m["test_loss"],
                               rtol=1e-5)


def test_tag_prediction_eval_metrics_parity():
    """(l) Multi-label tag eval vs the living reference trainer
    (my_model_trainer_tag_prediction.py:56-96): exact-match correct,
    macro precision/recall sums, sum-BCE x batch_size loss."""
    from fedml_api.standalone.fedavg.my_model_trainer_tag_prediction import (
        MyModelTrainer as RefTag,
    )

    from fedml_tpu.core.trainer import TagPredictionTrainer

    rng = np.random.RandomState(1)
    B, L = 8, 9
    probs = rng.rand(B, L).astype(np.float32) * 0.98 + 0.01
    y = (rng.rand(B, L) < 0.3).astype(np.float32)
    y[0] = (probs[0] > 0.5)  # guarantee one exact match

    ref = RefTag(_FixedOut(torch.tensor(probs)))
    loader = [(torch.zeros(B, 4), torch.tensor(y))]
    ref_m = ref.test(loader, torch.device("cpu"), None)

    logits = np.log(probs / (1 - probs))  # sigmoid^-1 so our model sees probs

    class _JaxFixed(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return jnp.asarray(logits)

    tr = TagPredictionTrainer(_JaxFixed())
    ours = tr.eval_fn({"params": {}},
                      {"x": jnp.zeros((B, 4)), "y": jnp.asarray(y),
                       "mask": jnp.ones(B)})
    assert float(ours["test_correct"]) == ref_m["test_correct"]
    assert float(ours["test_total"]) == ref_m["test_total"]
    np.testing.assert_allclose(float(ours["test_precision"]),
                               ref_m["test_precision"], rtol=1e-4)
    np.testing.assert_allclose(float(ours["test_recall"]),
                               ref_m["test_recall"], rtol=1e-4)
    np.testing.assert_allclose(float(ours["test_loss"]), ref_m["test_loss"],
                               rtol=1e-4)


class _SigmoidLinearTwin(nn.Module):
    """Flax twin of the reference decentralized clients' model
    (Linear + Sigmoid; BCELoss on probabilities)."""

    @nn.compact
    def __call__(self, x, train=False):
        return jax.nn.sigmoid(nn.Dense(1, name="lin")(x))


class _BCEStreamTrainer:
    module = _SigmoidLinearTwin()

    def loss_fn(self, variables, batch, rng, train=True):
        p = self.module.apply(variables, batch["x"])[:, 0]
        y = batch["y"]
        eps = 1e-12
        l = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps)).mean()
        return l, ({}, {"loss": l})


def test_decentralized_dsgd_trajectory_parity():
    """(m) Decentralized DSGD vs the living reference ClientDSGD
    (client_dsgd.py:54-102): grads at z_t, x_{t+1/2} = x_t - lr*grad, gossip
    mix with the symmetric topology row, z_{t+1} = x_{t+1} — trajectories of
    every node match over 5 streaming iterations on identical data + init.

    NB a latent reference defect surfaced here (worked around, not
    replicated): send_local_gradient_to_neighbor hands out REFERENCES to
    model_x (client_dsgd.py:78-86), and update_local_parameters then mutates
    each model_x in place sequentially — so client i>0 mixes with neighbors'
    ALREADY-MIXED weights (order-dependent Gauss-Seidel, not the synchronous
    DSGD the papers define). The test snapshots neighbor weights at send time
    so the reference computes the intended synchronous update, which the
    jitted gossip step then matches."""
    from fedml_api.standalone.decentralized.client_dsgd import ClientDSGD
    from fedml_api.standalone.decentralized.topology_manager import (
        TopologyManager as RefTopo,
    )

    from fedml_tpu.algorithms.decentralized import build_gossip_step
    from fedml_tpu.core.topology import SymmetricTopologyManager

    rng = np.random.RandomState(0)
    n, d, iters = 4, 6, 5
    streams = [[{"x": rng.normal(size=(d,)).astype(np.float64),
                 "y": float(rng.randint(0, 2))} for _ in range(iters)]
               for _ in range(n)]
    w0 = [rng.normal(size=(1, d)).astype(np.float32) * 0.3 for _ in range(n)]
    b0 = [rng.normal(size=(1,)).astype(np.float32) * 0.1 for _ in range(n)]
    lr = 0.2

    # ---- reference actors -------------------------------------------------
    ref_topo = RefTopo(n, b_symmetric=True, undirected_neighbor_num=2)
    ref_topo.generate_topology()

    def make_model(i):
        m = torch.nn.Sequential(torch.nn.Linear(d, 1), torch.nn.Sigmoid())
        with torch.no_grad():
            m[0].weight.copy_(torch.tensor(w0[i]))
            m[0].bias.copy_(torch.tensor(b0[i]))
        return m

    clients = [ClientDSGD(make_model(i), make_model(i), i, streams[i],
                          ref_topo, iters, lr, batch_size=1, weight_decay=0.0,
                          latency=0.0, b_symmetric=True) for i in range(n)]
    for t in range(iters):
        for c in clients:
            c.train(t)
        for c in clients:
            c.send_local_gradient_to_neighbor(clients)
        for c in clients:  # snapshot: undo the reference's aliasing defect
            c.neighbors_weight_dict = {k: copy.deepcopy(v)
                                       for k, v in c.neighbors_weight_dict.items()}
        for c in clients:
            c.update_local_parameters()
    ref_w = np.stack([c.model[0].weight.detach().numpy() for c in clients])
    ref_b = np.stack([c.model[0].bias.detach().numpy() for c in clients])

    # ---- jitted gossip step ----------------------------------------------
    topo = SymmetricTopologyManager(n, neighbor_num=2)
    topo.generate_topology()
    W = jnp.asarray(np.stack([topo.get_in_neighbor_weights(i)
                              for i in range(n)]).astype(np.float32))
    cfg = FedConfig(lr=lr)
    step = build_gossip_step(_BCEStreamTrainer(), cfg)
    stack = lambda arrs: jnp.asarray(np.stack(arrs))
    params = {"params": {"lin": {"kernel": stack([w.T for w in w0]),
                                 "bias": stack(b0)}}}
    x_params = params["params"]
    z_vars = params
    omega = jnp.ones(n)
    key = jax.random.PRNGKey(0)
    for t in range(iters):
        batch = {"x": stack([streams[i][t]["x"].astype(np.float32)[None]
                             for i in range(n)]),
                 "y": jnp.asarray([[streams[i][t]["y"]] for i in range(n)],
                                  jnp.float32)}
        x_params, omega, z_vars, _ = step(x_params, omega, z_vars, batch, W,
                                          jax.random.fold_in(key, t))
    ours_w = np.asarray(z_vars["params"]["lin"]["kernel"]).transpose(0, 2, 1)
    ours_b = np.asarray(z_vars["params"]["lin"]["bias"])
    np.testing.assert_allclose(ours_w, ref_w, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(ours_b, ref_b, rtol=1e-4, atol=1e-6)


def test_decentralized_pushsum_trajectory_parity():
    """(n) Push-sum over a DIRECTED (row-stochastic, non-doubly-stochastic)
    mixing matrix vs the living reference ClientPushsum
    (client_pushsum.py:57-130): grads at z, x-update, W^T-weighted mixing
    with omega mass tracking, z = x/omega. Same aliasing snapshot as the
    DSGD oracle; the matrix is injected directly into both sides so the test
    does not depend on rng-identical asymmetric graph generation."""
    from fedml_api.standalone.decentralized.client_pushsum import ClientPushsum

    from fedml_tpu.algorithms.decentralized import build_gossip_step

    rng = np.random.RandomState(3)
    n, d, iters = 4, 5, 5
    # hand-built directed row-stochastic W (columns NOT stochastic)
    adj = np.array([[1, 1, 0, 1],
                    [0, 1, 1, 0],
                    [1, 1, 1, 0],
                    [0, 0, 1, 1]], np.float32)
    W = adj / adj.sum(axis=1, keepdims=True)

    class _StubTopo:
        def get_asymmetric_neighbor_list(self, i):
            return W[i]

        def get_symmetric_neighbor_list(self, i):  # pragma: no cover
            return W[i]

    streams = [[{"x": rng.normal(size=(d,)).astype(np.float64),
                 "y": float(rng.randint(0, 2))} for _ in range(iters)]
               for _ in range(n)]
    w0 = [rng.normal(size=(1, d)).astype(np.float32) * 0.3 for _ in range(n)]
    b0 = [rng.normal(size=(1,)).astype(np.float32) * 0.1 for _ in range(n)]
    lr = 0.2

    def make_model(i):
        m = torch.nn.Sequential(torch.nn.Linear(d, 1), torch.nn.Sigmoid())
        with torch.no_grad():
            m[0].weight.copy_(torch.tensor(w0[i]))
            m[0].bias.copy_(torch.tensor(b0[i]))
        return m

    clients = [ClientPushsum(make_model(i), make_model(i), i, streams[i],
                             _StubTopo(), iters, lr, 1, 0.0, 0.0,
                             b_symmetric=False, time_varying=False)
               for i in range(n)]
    for t in range(iters):
        for c in clients:
            c.train(t)
        for c in clients:
            c.send_local_gradient_to_neighbor(clients)
        for c in clients:  # snapshot (same aliasing defect as ClientDSGD)
            c.neighbors_weight_dict = {k: copy.deepcopy(v)
                                       for k, v in c.neighbors_weight_dict.items()}
        for c in clients:
            c.update_local_parameters()
    ref_w = np.stack([c.model[0].weight.detach().numpy() for c in clients])
    ref_omega = np.array([c.omega for c in clients], np.float32)

    step = build_gossip_step(_BCEStreamTrainer(), FedConfig(lr=lr), push_sum=True)
    stack = lambda arrs: jnp.asarray(np.stack(arrs))
    params = {"params": {"lin": {"kernel": stack([w.T for w in w0]),
                                 "bias": stack(b0)}}}
    x_params, z_vars, omega = params["params"], params, jnp.ones(n)
    key = jax.random.PRNGKey(0)
    for t in range(iters):
        batch = {"x": stack([streams[i][t]["x"].astype(np.float32)[None]
                             for i in range(n)]),
                 "y": jnp.asarray([[streams[i][t]["y"]] for i in range(n)],
                                  jnp.float32)}
        x_params, omega, z_vars, _ = step(x_params, omega, z_vars, batch,
                                          jnp.asarray(W), jax.random.fold_in(key, t))
    np.testing.assert_allclose(np.asarray(omega), ref_omega, rtol=1e-5)
    ours_w = np.asarray(z_vars["params"]["lin"]["kernel"]).transpose(0, 2, 1)
    np.testing.assert_allclose(ours_w, ref_w, rtol=1e-4, atol=1e-6)
    ref_b = np.stack([c.model[0].bias.detach().numpy() for c in clients])
    ours_b = np.asarray(z_vars["params"]["lin"]["bias"])
    np.testing.assert_allclose(ours_b, ref_b, rtol=1e-4, atol=1e-6)


def test_neural_vfl_trajectory_parity():
    """(o) Neural vertical FL vs the living reference guest/host party stack
    (party_models.py:12-118 + finance/vfl_models_standalone.py:6-75):
    LocalModel (Linear+LeakyReLU) -> DenseModel logit components, guest sums,
    BCE-with-logits common gradient, per-sub-model SGD(momentum .9, wd .01) —
    all parties' weights match over 4 joint steps."""
    from fedml_api.model.finance.vfl_models_standalone import (
        DenseModel as RefDense,
        LocalModel as RefLocal,
    )
    from fedml_api.standalone.classical_vertical_fl.party_models import (
        VFLGuestModel,
        VFLHostModel,
    )
    from fedml_api.standalone.classical_vertical_fl.vfl import (
        VerticalMultiplePartyLogisticRegressionFederatedLearning as RefVFL,
    )

    from fedml_tpu.algorithms.vfl import build_neural_vfl_step

    rng = np.random.RandomState(0)
    B, dims, H, lr, steps = 12, [3, 4], 5, 0.05, 4
    Xa = rng.normal(size=(B, dims[0])).astype(np.float32)
    Xb = rng.normal(size=(B, dims[1])).astype(np.float32)
    y = rng.randint(0, 2, size=(B, 1)).astype(np.float32)
    inits = []
    for d in dims:
        inits.append({
            "local_w": rng.normal(0, 0.4, (d, H)).astype(np.float32),
            "local_b": rng.normal(0, 0.1, (H,)).astype(np.float32),
            "dense_w": rng.normal(0, 0.3, (H, 1)).astype(np.float32),
            "dense_b": rng.normal(0, 0.1, (1,)).astype(np.float32),
        })

    # ---- reference actors -------------------------------------------------
    def port(torch_linear, w, b=None):
        with torch.no_grad():
            torch_linear.weight.copy_(torch.tensor(w.T))
            if b is not None:
                torch_linear.bias.copy_(torch.tensor(b))

    guest_local = RefLocal(dims[0], H, lr)
    port(guest_local.classifier[0], inits[0]["local_w"], inits[0]["local_b"])
    guest = VFLGuestModel(guest_local)
    guest_dense = RefDense(H, 1, learning_rate=lr, bias=True)
    port(guest_dense.classifier[0], inits[0]["dense_w"], inits[0]["dense_b"])
    guest.set_dense_model(guest_dense)

    host_local = RefLocal(dims[1], H, lr)
    port(host_local.classifier[0], inits[1]["local_w"], inits[1]["local_b"])
    host = VFLHostModel(host_local)
    host_dense = RefDense(H, 1, learning_rate=lr, bias=False)
    port(host_dense.classifier[0], inits[1]["dense_w"])
    host.set_dense_model(host_dense)

    fed = RefVFL(guest)
    fed.add_party(id="host", party_model=host)
    for t in range(steps):
        fed.fit(Xa, y, {"host": Xb}, global_step=t)
    ref_lw = [guest_local.classifier[0].weight.detach().numpy().T,
              host_local.classifier[0].weight.detach().numpy().T]
    ref_dw = [guest_dense.classifier[0].weight.detach().numpy().T,
              host_dense.classifier[0].weight.detach().numpy().T]

    # ---- jitted joint step ------------------------------------------------
    step, _, opt = build_neural_vfl_step(lr=lr, momentum=0.9, wd=0.01)
    params = []
    for k, init in enumerate(inits):
        p = {"local_w": jnp.asarray(init["local_w"]),
             "local_b": jnp.asarray(init["local_b"]),
             "dense_w": jnp.asarray(init["dense_w"])}
        if k == 0:
            p["dense_b"] = jnp.asarray(init["dense_b"])
        params.append(p)
    params = tuple(params)
    opt_state = opt.init(params)
    xs = (jnp.asarray(Xa), jnp.asarray(Xb))
    for t in range(steps):
        params, opt_state, loss = step(params, opt_state, xs,
                                       jnp.asarray(y[:, 0]))
    for k in range(2):
        np.testing.assert_allclose(np.asarray(params[k]["local_w"]), ref_lw[k],
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"party {k} local_w")
        np.testing.assert_allclose(np.asarray(params[k]["dense_w"]), ref_dw[k],
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"party {k} dense_w")


def test_reference_hierarchical_fl_is_broken():
    """Pin why hierarchical FL has no living-reference trajectory oracle:
    the fork's standalone/hierarchical_fl imports
    fedml_api.standalone.fedavg.fedavg_trainer (trainer.py:6, group.py:4),
    which does not exist — the reference implementation cannot even be
    imported (SURVEY §2.3 'Broken in this fork'). The rebuild's
    hierarchical path is instead validated by the reference CI's own
    equivalence oracle (hierarchical == flat FedAvg when global x group
    rounds are fixed, CI-script-fedavg.sh:52-62) in
    tests/test_algorithms.py::test_hierarchical_oracle_equals_flat_fedavg."""
    with pytest.raises(ModuleNotFoundError, match="fedavg_trainer"):
        import fedml_api.standalone.hierarchical_fl.trainer  # noqa: F401
