"""Mobile MQTT transport tests: the dependency-free MQTT 3.1.1 codec,
in-process broker, and the reference topic scheme carrying a model pytree
as a JSON Message (reference mqtt_comm_manager.py:14-125 + the is_mobile
list encoding)."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.comm import Message, MiniBroker, MqttClient, MqttCommManager


def test_mqtt_pubsub_roundtrip():
    broker = MiniBroker()
    try:
        got = []
        done = threading.Event()
        sub = MqttClient(broker.host, broker.port, "sub")
        sub.subscribe("t/1", lambda t, p: (got.append((t, p)), done.set()))
        pub = MqttClient(broker.host, broker.port, "pub")
        pub.publish("t/1", b"hello mqtt")
        assert done.wait(10)
        assert got == [("t/1", b"hello mqtt")]
        sub.disconnect()
        pub.disconnect()
    finally:
        broker.close()


def test_mqtt_comm_manager_model_exchange():
    """Server broadcasts a model pytree to a client over the reference topic
    scheme; the client replies; both decode bit-exactly."""
    broker = MiniBroker()
    try:
        server = MqttCommManager(broker.host, broker.port, client_id=0, client_num=2)
        client1 = MqttCommManager(broker.host, broker.port, client_id=1)

        tree = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                "b": jnp.ones((3,), jnp.float32)}
        received = {}
        c_done, s_done = threading.Event(), threading.Event()

        def on_client(msg_type, msg):
            received["client"] = Message.decode_model_params(msg.get("model"), tree)
            c_done.set()

        def on_server(msg_type, msg):
            received["server_sender"] = msg.get_sender_id()
            s_done.set()

        client1.add_observer(on_client)
        server.add_observer(on_server)

        m = Message(msg_type=2, sender_id=0, receiver_id=1)
        m.add_model_params("model", tree)
        server.send_message(m)
        assert c_done.wait(10)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(received["client"][k]),
                                          np.asarray(tree[k]))

        reply = Message(msg_type=3, sender_id=1, receiver_id=0)
        reply.add("train_acc", 0.9)
        client1.send_message(reply)
        assert s_done.wait(10)
        assert received["server_sender"] == 1

        server.stop()
        client1.stop()
    finally:
        broker.close()


def test_fedavg_over_mqtt_end_to_end():
    """Full FedAvg (2 workers x 3 rounds) rides real MQTT frames through the
    in-process broker — the reference mobile deployment path
    (FedAvgServerManager.py:74-127 + FedAvgClientManager.py:127-167 with
    is_mobile list-encoded payloads). Loss must decrease."""
    from fedml_tpu.comm import run_mqtt_fedavg
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    ds = load_dataset("mnist", client_num_in_total=2, partition_method="homo",
                      seed=0)
    cfg = FedConfig(
        dataset="mnist", model="lr", client_num_in_total=2,
        client_num_per_round=2, comm_round=3, batch_size=32, lr=0.1,
    )
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    final_vars, history = run_mqtt_fedavg(ds, trainer, cfg, timeout=120.0)

    assert len(history) == 3
    assert history[-1]["test_loss"] < history[0]["test_loss"]
    assert history[-1]["test_acc"] > 0.3
    # the aggregated model came back over the wire as nested JSON lists
    assert all(np.asarray(l).dtype == np.float32
               for l in __import__("jax").tree.leaves(final_vars))


def test_mqtt_multiple_subscribers_fanout():
    broker = MiniBroker()
    try:
        hits = []
        evs = [threading.Event() for _ in range(2)]
        subs = []
        for i in range(2):
            c = MqttClient(broker.host, broker.port, f"s{i}")
            c.subscribe("fan", lambda t, p, i=i: (hits.append(i), evs[i].set()))
            subs.append(c)
        pub = MqttClient(broker.host, broker.port, "p")
        pub.publish("fan", b"x")
        assert all(e.wait(10) for e in evs)
        assert sorted(hits) == [0, 1]
        for c in subs + [pub]:
            c.disconnect()
    finally:
        broker.close()


def test_mqtt_survives_client_killed_mid_exchange():
    """QoS-0 semantics under failure (VERDICT r2 weak #6): a subscriber whose
    socket dies abruptly (no DISCONNECT) must not take down the broker or
    starve the surviving subscribers — the broker drops the dead connection
    and keeps delivering."""
    broker = MiniBroker()
    try:
        got = []
        ev = threading.Event()
        survivor = MqttClient(broker.host, broker.port, "alive")
        survivor.subscribe("st", lambda t, p: (got.append(p), ev.set()))
        victim = MqttClient(broker.host, broker.port, "dead")
        victim.subscribe("st", lambda t, p: None)
        # kill the victim's socket without the MQTT goodbye
        victim._stop.set()
        victim._sock.close()

        pub = MqttClient(broker.host, broker.port, "p")
        for i in range(3):  # several publishes so the broker hits the corpse
            pub.publish("st", b"payload-%d" % i)
        assert ev.wait(10), "survivor never received a publish"
        # broker still functional end to end after the dead-socket sends
        ev2 = threading.Event()
        survivor.subscribe("st2", lambda t, p: ev2.set())
        pub.publish("st2", b"again")
        assert ev2.wait(10)
        for c in (survivor, pub):
            c.disconnect()
    finally:
        broker.close()


def test_mqtt_client_reconnects_and_resubscribes():
    """paho-parity reconnect semantics: when the TCP connection drops out
    from under a live client, it reconnects with backoff, re-subscribes its
    topics, and keeps receiving (QoS-0: in-flight messages may be lost)."""
    broker = MiniBroker()
    try:
        got = []
        ev1, ev2 = threading.Event(), threading.Event()
        sub = MqttClient(broker.host, broker.port, "r",
                         reconnect_backoff=0.05)
        sub.subscribe("rt", lambda t, p: (got.append(p), (ev1 if len(got) == 1
                                                          else ev2).set()))
        pub = MqttClient(broker.host, broker.port, "p")
        pub.publish("rt", b"before")
        assert ev1.wait(10)

        # sever the subscriber's TCP connection out from under it
        sub._sock.shutdown(2)
        # give the receive loop time to notice + reconnect + resubscribe
        deadline = time.time() + 10
        while time.time() < deadline:
            pub.publish("rt", b"after")
            if ev2.wait(0.25):
                break
        assert ev2.wait(1), "client never recovered after the drop"
        assert got[-1] == b"after"
        for c in (sub, pub):
            c.disconnect()
    finally:
        broker.close()


@pytest.mark.slow  # ~60-120s: one jit compile + a real 3-round outage drill;
# tier-1 keeps the fast halves (client reconnect+resubscribe above, the
# idempotent resent-sync below) inside the suite's wall-clock budget
def test_fedavg_survives_broker_kill_and_restart_mid_exchange():
    """ISSUE 4 satellite: kill the broker mid-round and restart it on the
    same port. The clients' retry-policy reconnect + resubscribe
    (robustness.retry) and the server's round-stamped resend loop must
    complete every round — frames lost in the outage are re-sent, duplicate
    syncs retrain deterministically (rng derives from the stamped round
    index), and stale replies are dropped."""
    import jax

    from fedml_tpu.algorithms.engine import build_local_update
    from fedml_tpu.comm.mqtt_fedavg import (
        MqttFedAvgClientManager,
        MqttFedAvgServerManager,
    )
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    ds = load_dataset("mnist", client_num_in_total=2, partition_method="homo",
                      seed=0)
    cfg = FedConfig(dataset="mnist", model="lr", client_num_in_total=2,
                    client_num_per_round=2, comm_round=3, batch_size=32,
                    lr=0.1)
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    gv = trainer.init(jax.random.PRNGKey(cfg.seed),
                      jnp.asarray(ds.train.x[0][:1]))

    broker = MiniBroker()
    host, port = broker.host, broker.port
    server = clients = None
    try:
        server = MqttFedAvgServerManager(
            host, port, 2, jax.device_get(gv), cfg, trainer=trainer,
            test_global=ds.test_global, resend_interval=0.5)
        shared = jax.jit(build_local_update(trainer, cfg))
        # warm the jit cache before the exchange starts: otherwise the first
        # sync compiles for ~30s inside the callback thread while the resend
        # loop floods duplicate (idempotent, but slow) syncs
        jax.block_until_ready(shared(
            gv, jnp.asarray(ds.train.x[0]), jnp.asarray(ds.train.y[0]),
            jnp.int32(ds.train.counts[0]), jax.random.PRNGKey(0)))
        clients = [
            MqttFedAvgClientManager(host, port, k, ds, trainer, cfg, gv,
                                    local_update=shared)
            for k in (1, 2)
        ]
        server.send_init_msg()
        # let round 0 complete so the kill lands mid-exchange of a later round
        deadline = time.time() + 120
        while len(server.history) < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert len(server.history) >= 1, "round 0 never finished"

        # kill: close the listener and shutdown every established connection
        # (shutdown, not close — a close with the serve thread blocked in
        # recv leaves the kernel socket holding the port), then restart on
        # the SAME port once the teardown lands
        old = broker
        old.close()
        for s in list(old._send_locks):
            try:
                s.shutdown(2)
            except OSError:
                pass
        for _ in range(200):
            try:
                broker = MiniBroker(host, port)
                break
            except OSError:
                time.sleep(0.05)
        else:
            pytest.fail("could not rebind the broker port")

        assert server.done.wait(120), (
            f"run wedged after broker restart: history={server.history}")
        assert len(server.history) == cfg.comm_round
        assert all(np.isfinite(r["test_loss"]) for r in server.history)
        assert server.history[-1]["test_acc"] > 0.3
    finally:
        if clients:
            for c in clients:
                c.stop()
        if server:
            server.stop()
        broker.close()


def test_mqtt_fedavg_client_resent_sync_is_idempotent():
    """A duplicated/resent sync for the same round must produce a bitwise
    identical reply (rng derives from the stamped round index, not a local
    message counter) and must not advance the client's round counter twice."""
    import jax

    from fedml_tpu.comm.mqtt_fedavg import MqttFedAvgClientManager, MyMessage
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    ds = load_dataset("mnist", client_num_in_total=2, partition_method="homo",
                      seed=0)
    cfg = FedConfig(dataset="mnist", model="lr", client_num_in_total=2,
                    client_num_per_round=1, comm_round=5, batch_size=32,
                    lr=0.1)
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    gv = trainer.init(jax.random.PRNGKey(cfg.seed),
                      jnp.asarray(ds.train.x[0][:1]))

    broker = MiniBroker()
    try:
        client = MqttFedAvgClientManager(broker.host, broker.port, 1, ds,
                                         trainer, cfg, gv)
        sent = []
        client.comm.send_message = lambda m: sent.append(m)

        sync = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, 1)
        sync.add_model_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                              jax.device_get(gv))
        sync.add(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, "0")
        sync.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, "2")

        client._train_and_reply(sync)
        client._train_and_reply(sync)  # the resend
        assert len(sent) == 2
        assert sent[0].to_json() == sent[1].to_json()  # bitwise on the wire
        assert sent[0].get(MyMessage.MSG_ARG_KEY_ROUND_IDX) == "2"
        assert client.rounds_trained == 3  # ridx + 1, not += per message
        client.stop()
    finally:
        broker.close()


# ---------------------------------------------------------------------------
# Wire-format interoperability with the LIVING reference (VERDICT r3 #5):
# messages produced by the actual reference Message.to_json +
# transform_tensor_to_list drive our client loop through the broker, and our
# replies parse with the reference decoder — both directions asserted.
#
# Scope cap (VERDICT r4 weak #6): this proves CODEC-level interop. Loop-level
# interop — driving the reference's MqttCommManager actor against our broker —
# is untestable in this image because paho-mqtt is not installed; the claim
# stops exactly at the wire format. See docs/REFERENCE_DEFECTS.md §caps.
# ---------------------------------------------------------------------------


def test_reference_wire_format_interop_both_directions():
    import pytest

    torch = pytest.importorskip("torch")
    import jax
    from _reference_oracle import setup_reference

    setup_reference()
    # the living-reference checkout (/root/reference) is not shipped in
    # every container; without it this interop oracle has nothing to
    # compare against — same gate as the reference_parity modules
    pytest.importorskip(
        "fedml_core.distributed.communication.message",
        reason="reference FedML checkout (/root/reference) unavailable")
    from fedml_core.distributed.communication.message import Message as RefMessage
    from fedml_api.distributed.fedavg.utils import (
        transform_list_to_tensor,
        transform_tensor_to_list,
    )

    from fedml_tpu.algorithms.engine import build_local_update
    from fedml_tpu.comm.message import _named_leaves
    from fedml_tpu.comm.mqtt_fedavg import MqttFedAvgClientManager
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    ds = load_dataset("mnist", client_num_in_total=2, partition_method="homo",
                      seed=0)
    cfg = FedConfig(dataset="mnist", model="lr", client_num_in_total=2,
                    client_num_per_round=1, comm_round=1, batch_size=32,
                    lr=0.1, shuffle=False)
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    gv = trainer.init(jax.random.PRNGKey(cfg.seed),
                      jnp.asarray(ds.train.x[0][:1]))

    broker = MiniBroker()
    try:
        client = MqttFedAvgClientManager(broker.host, broker.port, 1, ds,
                                         trainer, cfg, gv)
        got: list[bytes] = []
        ev = threading.Event()
        tap = MqttClient(broker.host, broker.port, "tap")
        tap.subscribe("fedml1", lambda t, p: (got.append(p), ev.set()))
        time.sleep(0.2)

        # ---- direction 1: REFERENCE-encoded init message -> our client.
        # The reference mobile server encodes the state dict with
        # transform_tensor_to_list and ships Message.to_json
        # (FedAvgServerManager is_mobile path + message.py:60-74).
        named = {name: torch.from_numpy(np.asarray(leaf).copy())
                 for name, leaf in _named_leaves(gv)}
        payload = transform_tensor_to_list(named)
        ref_msg = RefMessage(type=1, sender_id=0, receiver_id=1)
        ref_msg.add_params("model_params", payload)
        ref_msg.add_params("client_idx", "0")
        wire = ref_msg.to_json().encode()

        pub = MqttClient(broker.host, broker.port, "refserver")
        pub.publish("fedml0_1", wire)

        assert ev.wait(60), "client never replied to the reference message"

        # ---- direction 2: our client's trained reply parses with the
        # REFERENCE decoder (init_from_json_string + transform_list_to_tensor)
        reply = RefMessage()
        reply.init_from_json_string(got[-1].decode())
        assert reply.get_type() == 3  # MSG_TYPE_C2S_SEND_MODEL_TO_SERVER
        assert reply.get_sender_id() == 1
        assert reply.get("num_samples") == int(ds.train.counts[0])
        decoded = transform_list_to_tensor(dict(reply.get("model_params")))

        # the decoded tensors equal the jitted local update our client ran
        rng = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0 * 1000 + 1)
        # jitted like the client's own update so the comparison is exact up
        # to the JSON float round-trip
        expect = jax.jit(build_local_update(trainer, cfg))(
            gv, jnp.asarray(ds.train.x[0]), jnp.asarray(ds.train.y[0]),
            jnp.int32(ds.train.counts[0]), rng)
        for name, leaf in _named_leaves(expect.variables):
            np.testing.assert_allclose(decoded[name].numpy(),
                                       np.asarray(leaf), atol=1e-6)

        tap.disconnect()
        pub.disconnect()
        client.stop()
    finally:
        broker.close()
