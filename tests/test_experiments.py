"""Experiment CLI mains, metrics logging, checkpoint/resume tests."""

import json

import numpy as np
import pytest


def test_main_fedavg_cli(tmp_path):
    from fedml_tpu.experiments.main_fedavg import main

    hist = main([
        "--dataset", "mnist", "--model", "lr", "--partition_method", "homo",
        "--client_num_in_total", "6", "--client_num_per_round", "4",
        "--comm_round", "3", "--batch_size", "32", "--lr", "0.1",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 3
    # wandb-compatible summary written (the reference CI assert source)
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "Test/Acc" in summary and summary["Test/Acc"] > 0.5


def test_main_fedopt_cli(tmp_path):
    from fedml_tpu.experiments.main_fedopt import main

    hist = main([
        "--dataset", "mnist", "--model", "lr", "--partition_method", "homo",
        "--client_num_in_total", "6", "--client_num_per_round", "6",
        "--comm_round", "2", "--batch_size", "32", "--lr", "0.1",
        "--server_optimizer", "adam", "--server_lr", "0.01",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 2


def test_main_decentralized_cli(tmp_path):
    from fedml_tpu.experiments.main_decentralized import main

    losses = main(["--client_number", "6", "--iterations", "20",
                   "--run_dir", str(tmp_path / "run")])
    assert len(losses) == 20
    assert np.isfinite(losses[-1])


def test_main_base_cli():
    from fedml_tpu.experiments.main_base import main

    out = main(["--client_num", "4", "--comm_round", "2"])
    assert out == [0.0 + 1 + 2 + 3, 1.0 + 2 + 3 + 4]


@pytest.mark.slow
def test_main_split_nn_cli(tmp_path):
    from fedml_tpu.experiments.main_split_nn import main

    hist = main([
        "--dataset", "cifar10", "--partition_method", "homo",
        "--client_num_in_total", "2", "--client_num_per_round", "2",
        "--comm_round", "1", "--epochs", "1", "--batch_size", "64",
        "--lr", "0.05", "--split_width", "8",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 1
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert 0.0 <= summary["Test/Acc"] <= 1.0


def test_main_vfl_cli(tmp_path):
    from fedml_tpu.experiments.main_vfl import main

    out = main(["--dataset", "adult", "--party_num", "3", "--epochs", "2",
                "--batch_size", "64", "--run_dir", str(tmp_path / "run")])
    assert 0.0 <= out["Test/Acc"] <= 1.0
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "Train/Acc" in summary


def test_main_turboaggregate_cli(tmp_path):
    from fedml_tpu.experiments.main_turboaggregate import main

    hist = main([
        "--dataset", "mnist", "--model", "lr", "--partition_method", "homo",
        "--client_num_in_total", "4", "--client_num_per_round", "4",
        "--comm_round", "2", "--epochs", "1", "--batch_size", "32",
        "--lr", "0.1", "--num_groups", "2",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 2
    # secure group-ring aggregation still trains: accuracy well above chance
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert summary["Test/Acc"] > 0.5


def test_main_fedseg_cli(tmp_path):
    from fedml_tpu.experiments.main_fedseg import main

    hist = main([
        "--comm_round", "1", "--epochs", "1", "--batch_size", "4",
        "--image_size", "16", "--model", "fcn", "--lr", "0.05",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 1
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    for key in ("Test/mIoU", "Test/FWIoU", "Test/accuracy"):
        assert key in summary, summary.keys()


@pytest.mark.slow
def test_main_fedgkt_cli(tmp_path):
    from fedml_tpu.experiments.main_fedgkt import main

    hist = main([
        "--dataset", "cifar10", "--partition_method", "homo",
        "--client_num_in_total", "4", "--client_num_per_round", "4",
        "--comm_round", "1", "--epochs", "1", "--epochs_server", "1",
        "--batch_size", "32", "--lr", "0.05", "--server_blocks", "1", "1", "1",
        "--client_sample_cap", "64", "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 1
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert 0.0 <= summary["Test/Acc"] <= 1.0


def test_checkpoint_resume_exact(tmp_path):
    """A run interrupted at round 2 of 4 and resumed produces exactly the
    same global model as an uninterrupted run (SURVEY §5: the reference's
    FedAvg cannot do this at all)."""
    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    ds = load_dataset("mnist", client_num_in_total=6, partition_method="homo", seed=0)
    cfg = FedConfig(comm_round=4, batch_size=32, lr=0.1,
                    client_num_in_total=6, client_num_per_round=4)

    def fresh_api():
        return FedAvgAPI(ds, cfg, ClassificationTrainer(create_model("lr", output_dim=10)))

    straight = fresh_api()
    straight.train()

    ck = str(tmp_path / "ck")
    first = fresh_api()
    for r in range(2):
        first.train_one_round(r)
    first.save_checkpoint(ck, 2)

    resumed = fresh_api()
    resumed.train(ckpt_dir=ck)
    d = jax.tree.map(lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))),
                     straight.global_variables, resumed.global_variables)
    assert max(jax.tree.leaves(d)) < 1e-6


def test_metrics_logger_files(tmp_path):
    from fedml_tpu.utils.logging import MetricsLogger

    lg = MetricsLogger(run_dir=str(tmp_path), config={"lr": 0.1})
    lg.log({"Test/Acc": 0.5}, step=0)
    lg.log({"Test/Acc": 0.8}, step=1)
    summary = json.loads((tmp_path / "wandb-summary.json").read_text())
    assert summary["Test/Acc"] == 0.8  # latest wins (wandb summary semantics)
    lines = (tmp_path / "history.jsonl").read_text().strip().split("\n")
    assert len(lines) == 2
    assert json.loads((tmp_path / "config.json").read_text())["lr"] == 0.1


def test_fed_launch_yaml(tmp_path):
    """YAML launcher (reference fed_launch analog) dispatches to the right
    main with config args + CLI overrides."""
    cfg = tmp_path / "exp.yaml"
    cfg.write_text(
        "algorithm: fedavg\n"
        "args:\n"
        "  dataset: mnist\n"
        "  model: lr\n"
        "  partition_method: homo\n"
        "  client_num_in_total: 4\n"
        "  client_num_per_round: 4\n"
        "  comm_round: 3\n"
        "  batch_size: 32\n"
        "  lr: '0.1'\n"
        f"  run_dir: {tmp_path / 'run'}\n"
    )
    from fedml_tpu.experiments.fed_launch import main

    hist = main(["--config", str(cfg), "--override", "comm_round=2"])
    assert len(hist) == 2  # override won
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert summary["Test/Acc"] > 0.5


def test_raw_mnist_loader(tmp_path):
    """LEAF-json raw_MNIST (reference raw_MNIST/data_loader.py:9-50)."""
    import json as _json

    for split, n in (("train", 6), ("test", 2)):
        d = tmp_path / split
        d.mkdir()
        rng = np.random.RandomState(0 if split == "train" else 1)
        data = {
            "users": ["f_0001", "f_0002"],
            "user_data": {
                u: {"x": rng.rand(n, 784).tolist(),
                    "y": rng.randint(0, 10, n).tolist()}
                for u in ("f_0001", "f_0002")
            },
        }
        (d / "all_data.json").write_text(_json.dumps(data))
    from fedml_tpu.data.registry import load_dataset

    ds = load_dataset("raw_mnist", data_dir=str(tmp_path))
    assert ds.train.num_clients == 2
    assert ds.train_global[0].shape == (12, 28, 28, 1)
    assert ds.test_global[0].shape == (4, 28, 28, 1)


def test_main_hierarchical_cli(tmp_path):
    """CLI-level coverage (VERDICT r3 weak #5 — previously only ci_smoke)."""
    from fedml_tpu.experiments.main_hierarchical import main

    hist = main([
        "--dataset", "mnist", "--model", "lr", "--partition_method", "homo",
        "--client_num_in_total", "4", "--client_num_per_round", "4",
        "--comm_round", "2", "--batch_size", "16", "--lr", "0.1",
        "--group_num", "2", "--group_comm_round", "2",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 2
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "Test/Acc" in summary and 0.0 <= summary["Test/Acc"] <= 1.0


def test_main_mqtt_fedavg_cli(tmp_path):
    """FedAvg over the in-process broker from its CLI (weak #5)."""
    from fedml_tpu.experiments.main_mqtt_fedavg import main

    hist = main([
        "--dataset", "mnist", "--model", "lr", "--partition_method", "homo",
        "--client_num_in_total", "2", "--client_num_per_round", "2",
        "--comm_round", "2", "--batch_size", "16", "--lr", "0.1",
        "--run_dir", str(tmp_path / "run"),
    ])
    assert len(hist) == 2
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "test_acc" in summary or "Test/Acc" in summary
