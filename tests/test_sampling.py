"""Feistel sampler seam: the in-graph jnp permutation
(algorithms/sampling.py, uint64 emulated on uint32 half-lanes) must be
BITWISE equal to the host `fast_client_sampling` — the superstep's in-graph
cohorts are only valid because these two never disagree on a single id.
Domains are adversarial: N=1, powers of four (the Feistel geometry's
natural sizes), powers of four +- 1 (cycle-walking armed), and ~1M.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import fast_client_sampling
from fedml_tpu.algorithms.sampling import (
    feistel_cohort_in_graph,
    feistel_geometry,
    feistel_keys_block,
    feistel_round_keys,
    split_keys,
)


def _in_graph(round_idx, n, num):
    keys = split_keys(feistel_round_keys(round_idx))
    return np.asarray(feistel_cohort_in_graph(jnp.asarray(keys), n, num))


@pytest.mark.parametrize("n", [1, 3, 4, 5, 16, 17, 63, 64, 65, 1024])
@pytest.mark.parametrize("round_idx", [0, 1, 7, 12345])
def test_in_graph_matches_host_bitwise(n, round_idx):
    num = min(max(n // 2, 1), n)
    host = fast_client_sampling(round_idx, n, num)
    if n == num:  # host arange fast path — the superstep drive mirrors it
        assert np.array_equal(host, np.arange(n))
        return
    got = _in_graph(round_idx, n, num)
    np.testing.assert_array_equal(got, host)
    assert got.size == len(set(got.tolist()))  # without replacement
    assert (got >= 0).all() and (got < n).all()


@pytest.mark.parametrize("n", [2 ** 20 - 1, 1_000_003])
def test_in_graph_matches_host_at_scale(n):
    host = fast_client_sampling(11, n, 64)
    np.testing.assert_array_equal(_in_graph(11, n, 64), host)


def test_fold_in_derived_round_seeds():
    """The superstep's key schedule is per-ROUND (RandomState(round_idx)),
    independent of how the drive derives its data rng — sweep a block of
    consecutive rounds as feistel_keys_block stages them."""
    n, num, r0, k = 1024, 32, 40, 8
    keys = jnp.asarray(feistel_keys_block(r0, k))
    for j in range(k):
        host = fast_client_sampling(r0 + j, n, num)
        got = np.asarray(feistel_cohort_in_graph(keys[j], n, num))
        np.testing.assert_array_equal(got, host)


def test_keys_block_shape_and_split_roundtrip():
    blk = feistel_keys_block(3, 5)
    assert blk.shape == (5, 4, 2) and blk.dtype == np.uint32
    raw = feistel_round_keys(3)
    hi_lo = split_keys(raw)
    back = (hi_lo[:, 0].astype(np.uint64) << np.uint64(32)) | \
        hi_lo[:, 1].astype(np.uint64)
    np.testing.assert_array_equal(back, raw)


def test_geometry_matches_host_derivation():
    for n in (1, 2, 4, 5, 64, 65, 1 << 20):
        half_bits, mask = feistel_geometry(n)
        assert half_bits == max(1, (max(n - 1, 1).bit_length() + 1) // 2)
        assert mask == (1 << half_bits) - 1


def test_rejects_domains_past_uint32_half_lanes():
    with pytest.raises(ValueError, match="2\\*\\*31"):
        feistel_cohort_in_graph(jnp.zeros((4, 2), jnp.uint32), 2 ** 31 + 1, 8)


def test_jit_stable_under_vmapped_keys():
    """One compiled program serves every round: keys are the only traced
    input, so a jit over the key schedule must not retrace per round."""
    n, num = 257, 16
    fn = jax.jit(lambda kk: feistel_cohort_in_graph(kk, n, num))
    for r in (0, 5, 99):
        host = fast_client_sampling(r, n, num)
        got = np.asarray(fn(jnp.asarray(split_keys(feistel_round_keys(r)))))
        np.testing.assert_array_equal(got, host)
    assert fn._cache_size() == 1
