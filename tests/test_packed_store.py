"""Out-of-core data plane (ISSUE 7 tentpole): the mmap-packed shard store
must be an invisible swap for in-RAM PackedClients — bit-identical
select() for any seeded cohort, bit-identical FedAvg trajectories (eager,
pipelined, under chaos), checkpoint resume across a store close/reopen —
while touching only the sampled rows (O(cohort) staging, the scale claim
tools/bench_scale.py measures).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax

from fedml_tpu import telemetry
from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.packed_store import (DEFAULT_CLIENTS_PER_SHARD,
                                         MmapPackedStore,
                                         create_synthetic_store, materialize,
                                         write_packed_shards)
from fedml_tpu.data.packing import PackedClients
from fedml_tpu.data.registry import FederatedDataset, load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


def _cfg(comm_round, **kw):
    kw.setdefault("client_num_per_round", 8)
    return FedConfig(dataset="mnist", model="lr", comm_round=comm_round,
                     batch_size=8, lr=0.05, client_num_in_total=8,
                     seed=0, **kw)


def _api(ds, cfg):
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    return FedAvgAPI(ds, cfg, trainer)


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


def _strip_times(history):
    return [{k: v for k, v in r.items() if k != "round_time"}
            for r in history]


def _random_packed(clients=37, n_max=5, shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(clients, n_max, *shape).astype(np.float32)
    y = rng.randint(0, 7, size=(clients, n_max)).astype(np.int32)
    counts = rng.randint(1, n_max + 1, size=clients).astype(np.int64)
    return PackedClients(x, y, counts)


def _store_ds(ds, tmp_path, name="mnist_store", clients_per_shard=3):
    """ds with its train set rewritten through the shard store (tiny
    clients_per_shard forces multi-shard gathers)."""
    d = str(tmp_path / name)
    write_packed_shards(d, ds.train, clients_per_shard=clients_per_shard)
    return dataclasses.replace(ds, train=MmapPackedStore(d)), d


# ---------------------------------------------------------- select() parity

def test_store_select_bit_identical_for_seeded_cohorts(tmp_path):
    packed = _random_packed()
    d = str(tmp_path / "store")
    write_packed_shards(d, packed, clients_per_shard=8, chunk_clients=5)
    store = MmapPackedStore(d)

    assert store.num_clients == packed.num_clients
    assert store.n_max == packed.n_max
    assert store.total_samples == packed.total_samples
    assert np.array_equal(np.asarray(store.counts), packed.counts)

    for round_idx in range(12):
        idx = client_sampling(round_idx, packed.num_clients, 9)
        sx, sy, sc = store.select(idx)
        px, py, pc = packed.select(idx)
        assert sx.dtype == px.dtype and np.array_equal(sx, px)
        assert sy.dtype == py.dtype and np.array_equal(sy, py)
        assert np.array_equal(sc, pc)
    # facade reads used by the drive loop / registry
    assert np.array_equal(np.asarray(store.x[:1, 0]), packed.x[:1, 0])
    assert np.array_equal(np.asarray(store.y[11]), packed.y[11])
    store.close()


def test_store_header_and_multi_shard_layout(tmp_path):
    packed = _random_packed(clients=10)
    d = str(tmp_path / "store")
    write_packed_shards(d, packed, clients_per_shard=4)
    header = json.load(open(os.path.join(d, "store.json")))
    assert header["num_clients"] == 10
    assert header["shard_rows"] == [4, 4, 2]   # roll-over at 4 clients
    assert os.path.exists(os.path.join(d, "shard_00002.x"))
    store = MmapPackedStore(d)
    # a cohort spanning all three shards gathers correctly
    idx = np.array([9, 0, 5, 3, 8])
    sx, _, _ = store.select(idx)
    assert np.array_equal(sx, packed.x[idx])
    store.close()


def test_materialize_is_the_blessed_full_read(tmp_path):
    packed = _random_packed(clients=6)
    d = str(tmp_path / "store")
    write_packed_shards(d, packed, clients_per_shard=4)
    store = MmapPackedStore(d)
    full = materialize(store)
    assert np.array_equal(full.x, packed.x)
    assert np.array_equal(full.y, packed.y)
    # the byte budget refuses silly whole-store pulls
    with pytest.raises(ValueError):
        materialize(store, budget=16)
    store.close()


def test_synthetic_store_is_sparse_and_zero_filled(tmp_path):
    d = str(tmp_path / "synth")
    create_synthetic_store(d, 5000, n_max=4, sample_shape=(8,),
                           clients_per_shard=2048)
    store = MmapPackedStore(d)
    x, y, counts = store.select(np.array([0, 4999, 2048]))
    assert not x.any() and not y.any()          # holes read as zeros
    assert (counts == 4).all()
    logical = sum(os.stat(os.path.join(d, f)).st_size for f in os.listdir(d))
    physical = sum(os.stat(os.path.join(d, f)).st_blocks * 512
                   for f in os.listdir(d))
    assert physical < logical / 10              # sparse on disk
    store.close()


def test_closed_store_refuses_reads(tmp_path):
    packed = _random_packed(clients=4)
    d = str(tmp_path / "store")
    write_packed_shards(d, packed)
    store = MmapPackedStore(d)
    store.close()
    with pytest.raises(ValueError):
        store.select(np.array([0]))


# ------------------------------------------------------ drive-loop identity

def test_fedavg_from_store_bit_identical_to_in_ram(ds8, tmp_path):
    ram = _api(ds8, _cfg(5))
    ram.train()
    store_ds, _ = _store_ds(ds8, tmp_path)
    stored = _api(store_ds, _cfg(5))
    stored.train()
    assert _bitwise_equal(stored.global_variables, ram.global_variables)
    assert _bitwise_equal(stored.agg_state, ram.agg_state)
    assert _strip_times(stored.history) == _strip_times(ram.history)
    store_ds.train.close()


def test_fedavg_from_store_pipelined_chaos_bit_identical(ds8, tmp_path):
    """The prefetcher's staging thread gathers from the mmap store under a
    fault schedule; trajectory must still match the in-RAM eager loop."""
    plan = lambda: FaultPlan(seed=3, drop_rate=0.25, nan_rate=0.25)
    ram = _api(ds8, _cfg(5))
    ram.train(chaos=plan())
    store_ds, _ = _store_ds(ds8, tmp_path)
    stored = _api(store_ds, _cfg(5, pipeline_depth=2))
    stored.train(chaos=plan())
    assert _bitwise_equal(stored.global_variables, ram.global_variables)
    assert _strip_times(stored.history) == _strip_times(ram.history)
    store_ds.train.close()


def test_checkpoint_resume_across_store_close_reopen(ds8, tmp_path):
    """Interrupt at round 3, CLOSE the store (process death), reopen the
    same shard directory in a fresh store + API: final state matches a
    straight in-RAM run."""
    straight = _api(ds8, _cfg(6))
    straight.train()

    ck = str(tmp_path / "ckpt")
    store_ds, store_dir = _store_ds(ds8, tmp_path)
    first = _api(store_ds, _cfg(3))
    first.train(ckpt_dir=ck, ckpt_every=100)
    store_ds.train.close()

    reopened = dataclasses.replace(ds8, train=MmapPackedStore(store_dir))
    resumed = _api(reopened, _cfg(6))
    hist = resumed.train(ckpt_dir=ck, ckpt_every=100)
    assert _bitwise_equal(resumed.global_variables, straight.global_variables)
    assert _bitwise_equal(resumed.agg_state, straight.agg_state)
    assert len(hist) == 6
    reopened.train.close()


# ------------------------------------------------------------- observability

def test_store_gauges_flow_through_telemetry_seam(tmp_path):
    packed = _random_packed(clients=12)
    d = str(tmp_path / "store")
    write_packed_shards(d, packed, clients_per_shard=4)
    store = MmapPackedStore(d, cache_budget=1 << 20)
    t = telemetry.Tracer()
    telemetry.install(t)
    try:
        store.select(np.array([0, 5, 9]))
        store.select(np.array([0, 5, 9]))   # second pass hits the row cache
    finally:
        telemetry.uninstall(t)
    by_name = {}
    for g in t.gauges:
        by_name.setdefault(g["name"], []).append(g)
    assert by_name["store_decode_miss"][0]["count"] == 3
    assert by_name["store_decode_hit"][-1]["count"] == 3
    assert by_name["store_resident_bytes"][-1]["bytes"] > 0
    assert all(g["store"] == "mmap"
               for gs in by_name.values() for g in gs)
    # ... and the summary table surfaces them
    table = t.summary_table()
    assert "store_decode_hit" in table and "store_resident_bytes" in table
    store.close()


def test_default_shard_size_sane():
    # the header/bench contract: a shard never holds zero clients
    assert DEFAULT_CLIENTS_PER_SHARD >= 1
