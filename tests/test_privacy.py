"""Privacy package tests: branch ensembles, MI attacks, adversarial eval."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.ensemble import AdaptiveCNN, build_hetero_archs
from fedml_tpu.models.registry import create_model
from fedml_tpu.privacy.branch_fedavg import BranchFedAvgAPI


@pytest.fixture(scope="module")
def mnist8():
    return load_dataset("mnist", client_num_in_total=8, partition_method="homo", seed=0)


@pytest.fixture(scope="module")
def mnist8_img():
    return load_dataset("mnist", client_num_in_total=8, partition_method="homo",
                        seed=0, flatten=False)


def test_adaptive_cnn_variants_forward():
    x = jnp.zeros((2, 28, 28, 1))
    specs = build_hetero_archs(4)
    # 3 variants keep CI cheap but must include b=3's (48, 48) conv1 — the
    # only spec whose internal-conv loop stacks more than one layer
    for spec in (specs[0], specs[1], specs[3]):
        m = AdaptiveCNN(output_dim=10, arch=spec)
        v = m.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}, x)
        out = m.apply(v, x, train=False)
        assert out.shape == (2, 10)
    # hetero archs actually differ
    descs = {s.describe() for s in build_hetero_archs(6)}
    assert len(descs) > 1


@pytest.mark.parametrize("method", ["predavg", "predvote", "predweight"])
def test_branch_fedavg_ensembles(mnist8, method):
    cfg = FedConfig(comm_round=3, batch_size=32, lr=0.1,
                    client_num_in_total=8, client_num_per_round=8)
    trainers = [ClassificationTrainer(create_model("lr", output_dim=10)) for _ in range(2)]
    api = BranchFedAvgAPI(mnist8, cfg, trainers, ensemble_method=method)
    hist = api.train()
    assert hist[-1]["Ensemble/Acc"] > 0.5
    assert hist[-1]["Branch0/Acc"] > 0.4 and hist[-1]["Branch1/Acc"] > 0.4


def test_blockavg_shares_blocks(mnist8):
    cfg = FedConfig(comm_round=2, batch_size=32, lr=0.1,
                    client_num_in_total=8, client_num_per_round=8)
    trainers = [ClassificationTrainer(create_model("lr", output_dim=10)) for _ in range(2)]
    api = BranchFedAvgAPI(mnist8, cfg, trainers, ensemble_method="predavg",
                          shared_blocks=("linear",))
    api.train()
    a = api.branches[0]["params"]["linear"]["kernel"]
    b = api.branches[1]["params"]["linear"]["kernel"]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


@pytest.mark.slow
def test_hetero_ensemble_branches(mnist8):
    import dataclasses
    ds = mnist8
    # hetero AdaptiveCNN branches need image input
    ds_img = load_dataset("mnist", client_num_in_total=4, partition_method="homo",
                          seed=0, flatten=False)
    from fedml_tpu.data.packing import PackedClients
    n_cap = 48
    ds_img = dataclasses.replace(
        ds_img,
        train=PackedClients(ds_img.train.x[:, :n_cap], ds_img.train.y[:, :n_cap],
                            np.minimum(ds_img.train.counts, n_cap)),
        test_global=(ds_img.test_global[0][:200], ds_img.test_global[1][:200]),
    )
    cfg = FedConfig(comm_round=1, batch_size=16, lr=0.05,
                    client_num_in_total=4, client_num_per_round=4)
    specs = build_hetero_archs(2)
    trainers = [ClassificationTrainer(AdaptiveCNN(output_dim=10, arch=s)) for s in specs]
    api = BranchFedAvgAPI(ds_img, cfg, trainers, ensemble_method="predavg")
    hist = api.train()
    assert "Ensemble/Acc" in hist[-1]


# ------------------------------------------------------------------ attacks

def _overfit_target(seed=0):
    """A deliberately-overfit LR target: memorizes its tiny train split."""
    import optax

    rng = np.random.RandomState(seed)
    xm = rng.normal(size=(40, 16)).astype(np.float32)
    ym = rng.randint(0, 2, size=40).astype(np.int32)
    xn = rng.normal(size=(40, 16)).astype(np.float32)
    yn = rng.randint(0, 2, size=40).astype(np.int32)
    trainer = ClassificationTrainer(create_model("lr", output_dim=2))
    v = trainer.init(jax.random.PRNGKey(0), jnp.asarray(xm[:1]))
    opt = optax.sgd(0.5)
    st = opt.init(v["params"])

    @jax.jit
    def step(p, st):
        def loss(p):
            logits, _ = trainer.apply({"params": p}, jnp.asarray(xm), train=False)
            return optax.softmax_cross_entropy_with_integer_labels(logits, jnp.asarray(ym)).mean()

        g = jax.grad(loss)(p)
        u, st = opt.update(g, st, p)
        return optax.apply_updates(p, u), st

    p = v["params"]
    for _ in range(300):
        p, st = step(p, st)
    return trainer, {"params": p}, (xm, ym), (xn, yn)


def test_loss_attack_detects_overfit_membership():
    from fedml_tpu.privacy.mi_attack import loss_attack, make_per_sample_loss

    trainer, variables, member, nonmember = _overfit_target()
    f = make_per_sample_loss(trainer, variables)
    res = loss_attack(f, (jnp.asarray(member[0]), jnp.asarray(member[1])),
                      (jnp.asarray(nonmember[0]), jnp.asarray(nonmember[1])))
    assert res["advantage"] > 0.3  # memorized members have much lower loss


def test_nn_attack_runs_and_beats_chance():
    from fedml_tpu.privacy.mi_attack import NNAttack

    trainer, variables, member, nonmember = _overfit_target()

    def predict(x):
        logits, _ = trainer.apply(variables, x, train=False)
        return logits

    atk = NNAttack(epochs=20).fit(predict, jnp.asarray(member[0]), jnp.asarray(nonmember[0]))
    res = atk.score(predict, jnp.asarray(member[0]), jnp.asarray(nonmember[0]))
    assert res["attack_acc"] > 0.6


def test_gradient_norm_attack():
    from fedml_tpu.privacy.mi_attack import gradient_norm_attack, make_per_sample_grad_norm

    trainer, variables, member, nonmember = _overfit_target()
    f = make_per_sample_grad_norm(trainer, variables)
    res = gradient_norm_attack(f, (jnp.asarray(member[0]), jnp.asarray(member[1])),
                               (jnp.asarray(nonmember[0]), jnp.asarray(nonmember[1])))
    assert res["advantage"] > 0.3


def test_pgd_attack_reduces_accuracy():
    from fedml_tpu.privacy.adv_attack import robust_accuracy

    ds = load_dataset("mnist", client_num_in_total=4, partition_method="homo", seed=0)
    trainer = ClassificationTrainer(create_model("lr", output_dim=10))
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    cfg = FedConfig(comm_round=3, batch_size=32, lr=0.1,
                    client_num_in_total=4, client_num_per_round=4,
                    frequency_of_the_test=3)
    api = FedAvgAPI(ds, cfg, trainer)
    api.train()

    def predict(x):
        logits, _ = trainer.apply(api.global_variables, x, train=False)
        return logits

    x = jnp.asarray(ds.test_global[0][:128])
    y = jnp.asarray(ds.test_global[1][:128])
    accs = robust_accuracy(predict, x, y, [0.0, 0.5], attack="pgd", steps=5)
    assert accs[0.0] > 0.8
    assert accs[0.5] < accs[0.0]  # attack hurts


# ------------------------------------------------- multi-model / blockensemble


@pytest.mark.slow
def test_joint_local_update_trains_two_models(mnist8_img):
    """TwoModelTrainer semantics: both paths improve on the client's data and
    the feature-matching term pulls block features together."""
    from fedml_tpu.privacy.multi_model import TwoModelTrainer, _forward_with_features

    cfg = FedConfig(comm_round=1, epochs=2, batch_size=16, lr=0.1,
                    client_num_in_total=8, client_num_per_round=8)
    module = AdaptiveCNN(output_dim=10)
    rng = jax.random.PRNGKey(0)
    x, y, counts = mnist8_img.train.select(np.array([0]))
    n_cap = 48  # keep the joint compile cheap on CI boxes
    x0, y0 = jnp.asarray(x[0][:n_cap]), jnp.asarray(y[0][:n_cap])
    c0 = jnp.minimum(jnp.asarray(counts[0]), n_cap)
    paths = tuple(
        module.init({"params": jax.random.fold_in(rng, b), "dropout": rng},
                    x0[:1], train=False)
        for b in range(2)
    )
    tm = TwoModelTrainer(module, cfg, feat_lmda=0.0)
    new_paths, m = tm.train(paths, x0, y0, c0, rng)
    assert float(m["total"]) == cfg.epochs * int(c0)
    # both models moved
    for old, new in zip(paths, new_paths):
        diff = max(float(jnp.max(jnp.abs(a - b)))
                   for a, b in zip(jax.tree.leaves(old), jax.tree.leaves(new)))
        assert diff > 1e-6
    # feature matching reduces inter-model feature distance vs no matching
    tm_reg = TwoModelTrainer(module, cfg, feat_lmda=10.0)
    reg_paths, _ = tm_reg.train(paths, x0, y0, c0, rng)

    def feat_dist(ps):
        _, fa = _forward_with_features(module, ps[0], x0[:16], None, train=False)
        _, fb = _forward_with_features(module, ps[1], x0[:16], None, train=False)
        return sum(float(jnp.mean(jnp.square(a - b))) for a, b in zip(fa, fb))

    assert feat_dist(reg_paths) < feat_dist(new_paths)


@pytest.mark.slow
def test_blockensemble_round_updates_only_drawn_blocks(mnist8_img):
    """Reference average_updated_branch_params: a (branch, block) pair not
    drawn this round keeps its previous params; drawn ones change."""
    from fedml_tpu.privacy.blockensemble import BLOCKS, BlockEnsembleAPI, block_of

    import dataclasses

    from fedml_tpu.data.packing import PackedClients

    n_cap = 48  # keep the joint compile cheap on CI boxes
    ds = dataclasses.replace(
        mnist8_img,
        train=PackedClients(mnist8_img.train.x[:, :n_cap],
                            mnist8_img.train.y[:, :n_cap],
                            np.minimum(mnist8_img.train.counts, n_cap)),
        test_global=(mnist8_img.test_global[0][:200],
                     mnist8_img.test_global[1][:200]),
    )
    cfg = FedConfig(comm_round=1, epochs=1, batch_size=16, lr=0.1,
                    client_num_in_total=8, client_num_per_round=4)
    api = BlockEnsembleAPI(ds, cfg, branch_num=3, num_paths=2)
    before = [jax.tree.map(lambda l: np.asarray(l).copy(), b)
              for b in api.branches]
    _, pick = api.prepare_paths(0)
    api.train_one_round(0)
    for b in range(3):
        for name in api.branches[b]["params"]:
            blk = block_of(name)
            drawn = b in set(int(v) for v in pick[blk])
            changed = any(
                float(np.max(np.abs(np.asarray(l1) - np.asarray(l2)))) > 1e-7
                for l1, l2 in zip(jax.tree.leaves(before[b]["params"][name]),
                                  jax.tree.leaves(api.branches[b]["params"][name]))
            )
            assert changed == drawn, (b, name, drawn)
    ev = api.evaluate()
    assert 0.0 <= ev["Ensemble/Acc"] <= 1.0 and "Branch2/Acc" in ev


@pytest.mark.slow
def test_main_privacy_cli_blockensemble(tmp_path):
    import json

    from fedml_tpu.experiments.main_privacy import main

    _hist, final = main([
        "--dataset", "mnist", "--partition_method", "homo",
        "--client_num_in_total", "16", "--client_num_per_round", "4",
        "--comm_round", "1", "--epochs", "1", "--batch_size", "32",
        "--lr", "0.1", "--branch_num", "3", "--ensemble_method",
        "blockensemble", "--run_dir", str(tmp_path / "run"),
    ])
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "Ensemble/Acc" in summary
    assert "Branch0/Acc" in summary
    assert "MI/NN_attack_acc" in summary  # MI-attack report present


@pytest.mark.slow
def test_main_privacy_cli_predweight(tmp_path):
    import json

    from fedml_tpu.experiments.main_privacy import main

    _hist, final = main([
        "--dataset", "mnist", "--partition_method", "homo",
        "--comm_round", "1", "--epochs", "1", "--batch_size", "32",
        "--lr", "0.1", "--branch_num", "2", "--ensemble_method", "predweight",
        "--no_mi_attack", "--client_num_in_total", "16",
        "--client_num_per_round", "4", "--run_dir", str(tmp_path / "run"),
    ])
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "Ensemble/Acc" in summary and "Branch1/Acc" in summary


def test_gradient_vector_attack_beats_chance():
    """Two-branch gradient-vector classifier (reference Gradient_attack.py)
    separates an overfit model's members from non-members."""
    from fedml_tpu.privacy.mi_attack import (
        GradientVectorAttack,
        make_penultimate_grad_fn,
    )

    trainer, variables, member, nonmember = _overfit_target()

    def predict(x):
        logits, _ = trainer.apply(variables, x, train=False)
        return logits

    pg = make_penultimate_grad_fn(trainer, variables)
    m = (jnp.asarray(member[0]), jnp.asarray(member[1]))
    n = (jnp.asarray(nonmember[0]), jnp.asarray(nonmember[1]))
    atk = GradientVectorAttack(epochs=25).fit(predict, pg, m, n)
    res = atk.score(predict, pg, m, n)
    assert res["attack_acc"] > 0.6
    assert res["advantage"] > 0.0


def test_mix_gradient_attack_runs():
    """Mix-gradient variant (reference MixGradient_attack.py): target-model
    predictions mixed with a (different) local model's penultimate grads."""
    from fedml_tpu.privacy.mi_attack import (
        MixGradientAttack,
        make_penultimate_grad_fn,
    )

    trainer, variables, member, nonmember = _overfit_target()
    # a second, fresh "local" model supplies the gradients
    fresh = trainer.init(jax.random.PRNGKey(9), jnp.asarray(member[0][:1]))

    def target_predict(x):
        logits, _ = trainer.apply(variables, x, train=False)
        return logits

    local_pg = make_penultimate_grad_fn(trainer, fresh)
    m = (jnp.asarray(member[0]), jnp.asarray(member[1]))
    n = (jnp.asarray(nonmember[0]), jnp.asarray(nonmember[1]))
    atk = MixGradientAttack(epochs=15).fit(target_predict, local_pg, m, n)
    res = atk.score(target_predict, local_pg, m, n)
    assert 0.0 <= res["attack_acc"] <= 1.0
    assert np.isfinite(res["advantage"])


def test_penultimate_grad_matches_autodiff():
    """Closed-form (softmax - onehot) @ W^T equals jax.grad wrt the head
    input on a model whose head input is the raw feature vector (LR)."""
    from fedml_tpu.privacy.mi_attack import make_penultimate_grad_fn

    trainer, variables, member, _ = _overfit_target()
    x = jnp.asarray(member[0][:8])
    y = jnp.asarray(member[1][:8])
    pg = make_penultimate_grad_fn(trainer, variables)
    got = pg(x, y)

    def per_sample(xi, yi):
        def loss(inp):
            logits, _ = trainer.apply(variables, inp[None], train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yi[None]).sum()
        return jax.grad(loss)(xi)

    import optax
    want = jax.vmap(per_sample)(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
