"""Unit tests for core contracts: partitioners, packing, pytree ops."""

import jax
import numpy as np
import jax.numpy as jnp

from fedml_tpu.core.partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
    p_hetero_partition,
    record_net_data_stats,
)
from fedml_tpu.data.packing import pack_client_data, pack_eval_batches
from fedml_tpu.utils.pytree import tree_weighted_mean, tree_global_norm, tree_where


def test_homo_partition_covers_all():
    m = homo_partition(103, 7, np.random.RandomState(0))
    all_idx = np.concatenate([m[i] for i in range(7)])
    assert sorted(all_idx.tolist()) == list(range(103))


def test_lda_partition_properties():
    y = np.random.RandomState(0).randint(0, 10, size=2000)
    m = non_iid_partition_with_dirichlet_distribution(y, 8, 10, alpha=0.5, rng=np.random.RandomState(1))
    all_idx = np.concatenate([m[i] for i in range(8)])
    assert sorted(all_idx.tolist()) == list(range(2000))  # exact cover, no dup
    assert min(len(m[i]) for i in range(8)) >= 10  # min-samples guarantee
    # non-IID: class histograms should differ across clients
    stats = record_net_data_stats(y, m)
    h0 = [stats[0].get(c, 0) for c in range(10)]
    h1 = [stats[1].get(c, 0) for c in range(10)]
    assert h0 != h1


def test_p_hetero_partition_covers_all():
    y = np.random.RandomState(0).randint(0, 10, size=1000)
    m = p_hetero_partition(10, y, alpha=0.8, rng=np.random.RandomState(1))
    all_idx = np.concatenate([m[i] for i in range(10)])
    assert sorted(all_idx.tolist()) == list(range(1000))
    # dense class dominates: client of group k holds mostly class k
    stats = record_net_data_stats(y, m)
    for k in range(10):
        hist = stats[k]
        assert max(hist, key=hist.get) == k


def test_pack_client_data_shapes_and_counts():
    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    y = np.arange(20, dtype=np.int32)
    m = {0: np.array([0, 1, 2]), 1: np.array([3, 4, 5, 6, 7, 8, 9])}
    packed = pack_client_data(x, y, m)
    assert packed.x.shape == (2, 7, 2)
    assert packed.counts.tolist() == [3, 7]
    assert packed.total_samples == 10
    np.testing.assert_array_equal(packed.y[0, :3], [0, 1, 2])
    assert packed.y[0, 3:].sum() == 0  # padding


def test_pack_eval_batches_mask():
    x = np.ones((10, 3), np.float32)
    y = np.zeros((10,), np.int32)
    bx, by, bm = pack_eval_batches(x, y, 4)
    assert bx.shape == (3, 4, 3)
    assert bm.sum() == 10


def test_tree_weighted_mean_matches_manual():
    stacked = {"a": jnp.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])}
    w = jnp.array([1.0, 1.0, 2.0])
    out = tree_weighted_mean(stacked, w)
    np.testing.assert_allclose(out["a"], (1 * np.array([1, 2.0]) + 1 * np.array([3, 4.0]) + 2 * np.array([5, 6.0])) / 4)


def test_tree_weighted_mean_flat_equals_per_leaf():
    """The one-matvec aggregation (aggregators.tree_weighted_mean_flat, the
    r5 latency probe) must equal the per-leaf weighted mean on a mixed-rank
    tree, including rank-1 leaves and non-f32 dtypes."""
    from fedml_tpu.algorithms.aggregators import tree_weighted_mean_flat

    rng = np.random.RandomState(3)
    stacked = {
        "k": jnp.asarray(rng.rand(6, 4, 3).astype(np.float32)),
        "b": jnp.asarray(rng.rand(6, 5).astype(np.float32)),
        "s": jnp.asarray(rng.rand(6).astype(np.float32)),
        "h": jnp.asarray(rng.rand(6, 2).astype(np.float16)),
    }
    w = jnp.asarray(rng.randint(1, 9, 6).astype(np.float32))
    want = tree_weighted_mean(stacked, w)
    got = tree_weighted_mean_flat(stacked, w)
    for k in stacked:
        assert got[k].dtype == stacked[k].dtype
        np.testing.assert_allclose(np.asarray(got[k], np.float32),
                                   np.asarray(want[k], np.float32),
                                   rtol=2e-3 if k == "h" else 1e-6,
                                   atol=1e-6)


def test_tree_weighted_mean_flat_budget_guard():
    """The [C, P] f32 staging copy must be refused — at trace time, with an
    actionable message — when it exceeds the byte budget; the jitted round
    aborts before any device allocation instead of OOMing opaquely."""
    import pytest

    from fedml_tpu.algorithms.aggregators import tree_weighted_mean_flat

    stacked = {"a": jnp.ones((4, 8, 8), jnp.float32)}  # stages 4*64*4 = 1 KiB
    w = jnp.ones(4)
    # over budget: raises, names the shape and the escape hatches
    with pytest.raises(ValueError, match=r"flat_agg.*\[4, 64\].*flat_agg_budget"):
        tree_weighted_mean_flat(stacked, w, byte_budget=1000)
    with pytest.raises(ValueError, match="flat_agg"):
        jax.jit(tree_weighted_mean_flat, static_argnums=2)(stacked, w, 1000)
    # at budget: runs
    out = tree_weighted_mean_flat(stacked, w, byte_budget=1024)
    np.testing.assert_allclose(out["a"], np.ones((8, 8)), rtol=1e-6)


def test_tree_where_selects():
    a = {"x": jnp.ones(3)}
    b = {"x": jnp.zeros(3)}
    np.testing.assert_array_equal(tree_where(jnp.bool_(True), a, b)["x"], np.ones(3))
    np.testing.assert_array_equal(tree_where(jnp.bool_(False), a, b)["x"], np.zeros(3))


def test_tree_global_norm():
    t = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    assert abs(float(tree_global_norm(t)) - 5.0) < 1e-6


def test_native_packing_matches_numpy():
    """The C++ pack_rows kernel produces byte-identical output to the numpy
    fallback (and actually loads in this environment)."""
    from fedml_tpu import native

    assert native.native_available(), "g++ is in the image; native must build"
    rng = np.random.RandomState(0)
    x = rng.normal(size=(50, 3, 4)).astype(np.float32)
    idx_lists = [rng.choice(50, rng.randint(1, 12), replace=False).astype(np.int64)
                 for _ in range(7)]
    n_max = 12
    out = native.pack_rows(x, idx_lists, n_max)
    ref = np.zeros((7, n_max, 3, 4), np.float32)
    for i, idx in enumerate(idx_lists):
        ref[i, : len(idx)] = x[idx]
    np.testing.assert_array_equal(out, ref)


def test_pack_client_data_native_and_fallback_agree():
    from fedml_tpu.data.packing import pack_client_data

    rng = np.random.RandomState(1)
    x = rng.normal(size=(30, 5)).astype(np.float32)
    y = rng.randint(0, 3, size=30).astype(np.int32)
    m = {0: np.arange(10), 1: np.arange(10, 30)}
    packed = pack_client_data(x, y, m)
    assert packed.x.shape == (2, 20, 5)
    np.testing.assert_array_equal(packed.x[0, :10], x[:10])
    assert packed.x[0, 10:].sum() == 0
    np.testing.assert_array_equal(packed.y[1], y[10:30])
