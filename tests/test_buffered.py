"""Staleness-aware buffered aggregation (ISSUE 9 tentpole): the FedBuff
drive loop, its admit/commit programs, the seeded straggler plan, and the
sharded twin.

The pins that matter, each bitwise where the design promises bitwise:
  - the DEGENERATE config (buffer_size = cohort, staleness_alpha = 0, no
    stragglers) reproduces the synchronous loop's final params AND
    aggregator state bit-exactly, for fedavg and fedopt-with-momentum,
    eager and depth-2 pipelined;
  - two same-seed runs with stragglers on and a guard rollback mid-run are
    byte-identical (params and FedOpt momenta) — the whole async schedule
    is a pure function of the seed;
  - the straggler plan draws from a SEPARATE rng stream, so arming it
    changes no drop/NaN mask byte;
  - the sharded admit/commit twin matches the vmap programs (exact buffer
    rows; commit within the float-reassociation bar of test_parallel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.aggregators import (
    build_buffer_admit,
    build_buffer_commit,
    make_aggregator,
    make_staleness_discount,
)
from fedml_tpu.algorithms.buffered import build_client_step_fn, init_buffer
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.parallel import build_sharded_buffer_fns, make_mesh
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.robustness.guard import GuardVerdict
from fedml_tpu.telemetry.tracer import Tracer


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


def _all_finite(tree):
    return all(bool(jnp.all(jnp.isfinite(l))) for l in jax.tree.leaves(tree)
               if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact))


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


@pytest.fixture(scope="module")
def ds16():
    return load_dataset("mnist", client_num_in_total=16,
                        partition_method="homo", seed=1)


def _train(ds, aggregator_name="fedavg", chaos=None, guard=None,
           tracer=None, **cfg_kwargs):
    cfg_kwargs.setdefault("client_num_per_round", ds.client_num)
    cfg = FedConfig(dataset="mnist", model="lr", batch_size=8, lr=0.05,
                    client_num_in_total=ds.client_num, seed=0, **cfg_kwargs)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds.class_num))
    api = FedAvgAPI(ds, cfg, trainer, aggregator_name=aggregator_name)
    api.train(chaos=chaos, guard=guard, tracer=tracer)
    return api


# ----------------------------------------------------- straggler chaos plan

def test_straggler_latencies_deterministic_and_bounded():
    plan = FaultPlan(seed=7, straggler_rate=0.5, straggler_rounds=3)
    for r in range(4):
        l1 = plan.latencies(r, 32)
        l2 = plan.latencies(r, 32)
        np.testing.assert_array_equal(l1, l2)       # pure in (seed, round)
        assert l1.dtype == np.int32
        assert l1.min() >= 0 and l1.max() <= 3
    # the schedule varies by round and actually straggles somebody
    all_lat = np.stack([plan.latencies(r, 32) for r in range(4)])
    assert (all_lat > 0).any()
    assert not (all_lat == all_lat[0]).all()
    # degenerate plan: nobody straggles, no rng consumed
    off = FaultPlan(seed=7)
    assert off.latencies(0, 32).tolist() == [0] * 32


def test_straggler_stream_leaves_drop_nan_draws_byte_stable():
    """Arming the straggler plan must not move a single byte of the
    existing fault schedule — latencies draw from a separate rng stream."""
    base = FaultPlan(seed=5, drop_rate=0.25, nan_rate=0.2, corrupt_rate=0.1)
    armed = FaultPlan(seed=5, drop_rate=0.25, nan_rate=0.2, corrupt_rate=0.1,
                      straggler_rate=0.5, straggler_rounds=4)
    for r in range(4):
        e0, e1 = base.events(r, 32), armed.events(r, 32)
        np.testing.assert_array_equal(e0.participation, e1.participation)
        np.testing.assert_array_equal(e0.nan_mask, e1.nan_mask)
        np.testing.assert_array_equal(e0.corrupt_mask, e1.corrupt_mask)


# ------------------------------------------------- degenerate bit-identity

def test_degenerate_buffered_is_bitwise_the_sync_loop(ds8):
    """buffer_size = cohort + alpha = 0 + no stragglers: every round admits
    its whole cohort in slot order and commits once with zero staleness —
    bit-identical params AND aggregator state to the synchronous fedavg
    loop, eager and depth-2 pipelined."""
    sync = _train(ds8, "fedavg", comm_round=3)
    for depth in (0, 2):
        buffered = _train(ds8, "fedavg", buffer_size=8, staleness_alpha=0.0,
                          pipeline_depth=depth, comm_round=3)
        assert _bitwise_equal(sync.global_variables,
                              buffered.global_variables), depth
        assert _bitwise_equal(sync.agg_state, buffered.agg_state), depth
        assert all(r["buffer_commits"] == 1 for r in buffered.history
                   if "buffer_commits" in r)


def test_degenerate_fedopt_tracks_sync_momenta_in_fast_suite(ds8):
    """fedopt-with-momentum in the fast suite's opt-0 codegen: XLA
    duplicates the momentum subexpression into the params output and
    contracts the copies differently per program context, so the fused sync
    round and the standalone commit drift by ~1 ULP — the suite pins a tight
    allclose here; the exact bitwise pin runs at default codegen
    (test_degenerate_fedopt_bitwise_at_default_codegen)."""
    kw = dict(comm_round=3, server_optimizer="sgd", server_lr=1.0,
              server_momentum=0.9)
    sync = _train(ds8, "fedopt", **kw)
    for depth in (0, 2):
        buffered = _train(ds8, "fedopt", buffer_size=8, staleness_alpha=0.0,
                          pipeline_depth=depth, **kw)
        for a, b in zip(jax.tree.leaves(sync.global_variables),
                        jax.tree.leaves(buffered.global_variables)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=0)
        for a, b in zip(jax.tree.leaves(sync.agg_state),
                        jax.tree.leaves(buffered.agg_state)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=0)


@pytest.mark.slow  # ~14s default-codegen subprocess recompile; the same
# degenerate identity runs at opt-0 in the two fast-suite tests above
def test_degenerate_fedopt_bitwise_at_default_codegen():
    """The ISSUE-9 acceptance pin, verbatim: degenerate buffered config
    bit-identical to the sync fedavg AND fedopt loops (params AND momenta,
    eager and depth-2 pipelined). Runs buffered_degenerate_probe.py in a
    subprocess with the fast suite's --xla_backend_optimization_level=0
    stripped — default codegen contracts FMA chains consistently across
    programs, where the identity holds exactly."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "").replace(
        "--xla_backend_optimization_level=0", "").strip()
    env["JAX_PLATFORMS"] = "cpu"
    probe = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "buffered_degenerate_probe.py")
    proc = subprocess.run([sys.executable, probe], env=env, timeout=540,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "BITWISE OK" in proc.stdout


# --------------------------------------- async schedule: seeded determinism

class _RejectOnce:
    max_retries = 2

    def __init__(self, bad_round=2):
        self.bad_round = bad_round
        self.fired = False

    def inspect(self, round_idx, loss, global_variables=None):
        if round_idx == self.bad_round and not self.fired:
            self.fired = True
            return GuardVerdict(False, "forced test rejection")
        return GuardVerdict(True, "")


def _straggler_run(ds, depth):
    tracer = Tracer()
    api = _train(
        ds, "fedopt", comm_round=5, client_num_per_round=8, buffer_size=5,
        staleness_alpha=0.5, pipeline_depth=depth,
        server_optimizer="sgd", server_lr=1.0, server_momentum=0.9,
        chaos=FaultPlan(seed=3, drop_rate=0.1, straggler_rate=0.4,
                        straggler_rounds=3),
        guard=_RejectOnce(bad_round=2), tracer=tracer)
    return api, tracer


def test_straggler_runs_reproduce_bitwise_with_guard_rollback(ds16):
    """The acceptance pin: same seed, stragglers on, a guard rollback
    mid-run — two runs byte-identical on final params AND FedOpt momenta,
    and the depth-2 pipelined run byte-identical to the eager run."""
    api1, t1 = _straggler_run(ds16, depth=2)
    api2, t2 = _straggler_run(ds16, depth=2)
    api3, _ = _straggler_run(ds16, depth=0)
    assert _bitwise_equal(api1.global_variables, api2.global_variables)
    assert _bitwise_equal(api1.agg_state, api2.agg_state)
    assert _bitwise_equal(api1.global_variables, api3.global_variables)
    assert _bitwise_equal(api1.agg_state, api3.agg_state)
    assert _all_finite(api1.global_variables)

    # the run actually exercised the async machinery
    rollback, = t1.find_events("guard_rollback")
    assert rollback["round"] == 2
    commits = t1.find_events("buffer_committed")
    assert commits and any(e["staleness_max"] > 0 for e in commits)
    admitted = t1.find_events("update_admitted")
    assert any(e["round"] > e["birth"] for e in admitted)  # a late arrival
    assert sum(r.get("staleness_sum", 0.0) for r in api1.history) > 0
    # both runs committed the identical number of updates, and their commit
    # LEDGERS agree byte-for-byte too (the ledger keeps the rolled-back
    # round's commits — that's what a ledger is for — so it can only
    # overcount the surviving total, never disagree between the runs)
    assert (api1._buffer_host.committed_updates
            == api2._buffer_host.committed_updates)
    sizes2 = [e["size"] for e in t2.find_events("buffer_committed")]
    assert [e["size"] for e in commits] == sizes2
    assert sum(sizes2) >= api1._buffer_host.committed_updates


def test_oversized_buffer_drains_through_partial_flush(ds8):
    """K larger than every update the run produces: no commit fires during
    the dispatch rounds, then the drain flushes the partial buffer once
    through the participation-masked commit path."""
    api = _train(ds8, comm_round=3, buffer_size=64)
    host = api._buffer_host
    assert host.commits == 1
    assert host.committed_updates == 3 * 8
    assert _all_finite(api.global_variables)
    drain = api.history[-1]
    assert drain["round"] == 3 and drain["buffer_commits"] == 1
    # the model moved: the masked partial commit actually landed
    init = ClassificationTrainer(
        create_model("lr", output_dim=ds8.class_num))
    assert not _bitwise_equal(
        api.global_variables,
        init.init(jax.random.PRNGKey(0),
                  jnp.asarray(ds8.train.x[:1, 0])))


def test_buffered_rejects_sharded_drive_configs(ds8):
    cfg = FedConfig(dataset="mnist", model="lr", batch_size=8,
                    client_num_in_total=8, client_num_per_round=8,
                    buffer_size=4, backend="shard_map", mesh_shape=(8,))
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds8.class_num))
    with pytest.raises(ValueError, match="buffer_size"):
        FedAvgAPI(ds8, cfg, trainer)


# ------------------------------------------------------------- sharded twin

def test_sharded_buffer_twin_matches_vmap_programs(ds8):
    """8 admits + 1 commit on the 8-virtual-device mesh: the sharded twin
    lands the exact same buffer rows (admit is a masked copy — bitwise),
    and its commit matches the vmap commit within the float-reassociation
    bar build_sharded_round_fn is held to (1e-6)."""
    cfg = FedConfig(dataset="mnist", model="lr", batch_size=8, lr=0.05,
                    client_num_in_total=8, client_num_per_round=8,
                    server_optimizer="sgd", server_lr=1.0,
                    server_momentum=0.9)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds8.class_num))
    gv = trainer.init(jax.random.PRNGKey(0),
                      jnp.asarray(ds8.train.x[:1, 0]))
    agg = make_aggregator("fedopt", cfg)
    state = agg.init_state(gv)
    x, y, counts = ds8.train.select(np.arange(8))
    x, y, counts = jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts)
    result = build_client_step_fn(trainer, cfg)(
        gv, x, y, counts, jax.random.PRNGKey(11))
    discount = make_staleness_discount(0.5)
    rng = jax.random.PRNGKey(7)

    admit = build_buffer_admit()
    commit = build_buffer_commit(agg, discount)
    buf = init_buffer(result, 8)
    for slot in range(8):
        buf = admit(buf, result.variables, result.num_steps, result.metrics,
                    counts, np.int32(slot), np.int32(slot % 3))
    gv_v, state_v, m_v = commit(gv, state, buf, np.int32(4), rng)

    mesh = make_mesh((8,), ("clients",))
    admit_s, commit_s = build_sharded_buffer_fns(agg, discount, mesh)
    buf_s = {k: v for k, v in init_buffer(result, 8).items() if k != "fill"}
    fill = jnp.zeros((), jnp.int32)
    for slot in range(8):
        buf_s = admit_s(buf_s, fill, result.variables, result.num_steps,
                        result.metrics, counts, jnp.int32(slot),
                        jnp.int32(slot % 3))
        fill = fill + 1
    # admit is a masked row copy (+0.0 psum terms): rows match BITWISE
    for key in ("steps", "weights", "birth"):
        np.testing.assert_array_equal(np.asarray(buf[key]),
                                      np.asarray(buf_s[key]))
    assert _bitwise_equal(buf["vars"], buf_s["vars"])
    assert _bitwise_equal(buf["metrics"], buf_s["metrics"])

    gv_s, state_s, m_s = commit_s(gv, state, buf_s, fill, jnp.int32(4), rng)
    for a, b in zip(jax.tree.leaves(gv_v), jax.tree.leaves(gv_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(jax.tree.leaves(state_v), jax.tree.leaves(state_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    m_v, m_s = jax.device_get((m_v, m_s))
    for key in ("participated_count", "quarantined_count",
                "staleness_sum", "staleness_max"):
        assert m_s[key] == pytest.approx(float(m_v[key]), abs=1e-4), key
    assert float(m_s["staleness_sum"]) > 0  # births 0..2, committed at 4
