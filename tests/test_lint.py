"""graft-lint: every rule fires on its deliberately-bad fixture, and the
repo itself is clean.

The repo-clean assertions are the teeth: they pin the satellite fixes
(tools/ sync idioms, bf16 metric sums) so a regression reintroducing any
of them fails tier-1, not a TPU bench. The per-model dtype sweep lives in
test_dtype_registry.py (same analyzer, parametrized per model)."""

import warnings

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as PS

from fedml_tpu.analysis import (
    check_dead_cast,
    check_donation,
    check_dtype_policy,
    check_host_sync,
    check_partition_coverage,
    check_retrace,
    lint_source,
)
from fedml_tpu.analysis.core import Finding, Report
from fedml_tpu.analysis.partition import match_partition_rules


# ---------------------------------------------------------------- jaxpr rules

def test_dtype_policy_fires_on_f32_dot_under_bf16_policy():
    jaxpr = jax.make_jaxpr(
        lambda a, b: a @ b)(jnp.zeros((2, 3)), jnp.zeros((3, 4))).jaxpr
    findings = check_dtype_policy(jaxpr, "fixture", policy=jnp.bfloat16)
    assert findings and findings[0].rule == "dtype-policy"
    assert "dot_general" in findings[0].message


def test_dtype_policy_recurses_into_scan():
    def f(w, xs):
        def body(c, x):
            return c, x @ w
        return jax.lax.scan(body, 0.0, xs)

    jaxpr = jax.make_jaxpr(f)(jnp.zeros((3, 4)), jnp.zeros((5, 2, 3))).jaxpr
    assert check_dtype_policy(jaxpr, "fixture", policy=jnp.bfloat16)


def test_dtype_policy_clean_on_bf16_dot_and_int_dot():
    bf = jnp.bfloat16
    jaxpr = jax.make_jaxpr(
        lambda a, b: a @ b)(jnp.zeros((2, 3), bf), jnp.zeros((3, 4), bf)).jaxpr
    assert not check_dtype_policy(jaxpr, "fixture", policy=bf)
    # integer matmuls (turboaggregate field arithmetic) never fire
    jaxpr = jax.make_jaxpr(lambda a, b: a @ b)(
        jnp.zeros((2, 3), jnp.int32), jnp.zeros((3, 4), jnp.int32)).jaxpr
    assert not check_dtype_policy(jaxpr, "fixture", policy=bf)


def test_host_sync_fires_on_pure_callback():
    def f(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    jaxpr = jax.make_jaxpr(f)(jnp.zeros((2,))).jaxpr
    findings = check_host_sync(jaxpr, "fixture")
    assert findings and findings[0].rule == "host-sync"


def test_host_sync_fires_on_debug_callback_inside_scan():
    def f(xs):
        def body(c, x):
            jax.debug.callback(lambda v: None, x)
            return c, x
        return jax.lax.scan(body, 0.0, xs)

    jaxpr = jax.make_jaxpr(f)(jnp.zeros((3,))).jaxpr
    assert check_host_sync(jaxpr, "fixture")


def test_dead_cast_fires_on_f32_bf16_f32_roundtrip():
    def f(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    jaxpr = jax.make_jaxpr(f)(jnp.zeros((4,))).jaxpr
    findings = check_dead_cast(jaxpr, "fixture")
    assert findings and findings[0].rule == "dead-cast"
    assert "float32->bfloat16->float32" in findings[0].message


def test_dead_cast_spares_multi_use_intermediate():
    # the bf16 value is ALSO consumed (e.g. stored) — casting back is not dead
    def f(x):
        h = x.astype(jnp.bfloat16)
        return h.astype(jnp.float32) + 1.0, h * 2

    jaxpr = jax.make_jaxpr(f)(jnp.zeros((4,))).jaxpr
    assert not check_dead_cast(jaxpr, "fixture")


def test_donation_fires_on_dtype_mismatched_donation():
    # donated f32 buffer can never alias the bf16 output -> donation dropped
    bad = jax.jit(lambda x: x.astype(jnp.bfloat16), donate_argnums=(0,))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        findings = check_donation(bad, (jnp.zeros((8,)),), "fixture",
                                  argnums=(0,))
    assert findings and findings[0].rule == "donation"


def test_donation_clean_on_real_donation():
    good = jax.jit(lambda x: x + 1.0, donate_argnums=(0,))
    assert not check_donation(good, (jnp.zeros((8,)),), "fixture",
                              argnums=(0,))


def test_retrace_fires_on_weak_type_flapping():
    # alternating python-scalar / array args flips the weak-type signature
    # -> one compile per call, the classic silent-retrace bug
    f = jax.jit(lambda x, s: x * s)

    def make_args(i):
        return (jnp.zeros((4,)), 1.0 if i % 2 == 0 else jnp.float32(1.0))

    findings = check_retrace(f, make_args, "fixture", rounds=3)
    assert findings and findings[0].rule == "retrace"


def test_retrace_clean_on_stable_signature():
    f = jax.jit(lambda x, s: x * s)

    def make_args(i):
        return (jnp.zeros((4,)), jnp.float32(i))

    assert not check_retrace(f, make_args, "fixture", rounds=3)


# ------------------------------------------------------------------ AST rules

def _findings(src):
    return lint_source(src, "fixture.py")


def test_ast_host_transfer_fires_in_jit_decorated_fn():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x) + np.asarray(x).sum()\n")
    rules = [f.rule for f in _findings(src)]
    assert rules.count("host-transfer") == 2


def test_ast_host_transfer_fires_via_call_graph():
    # helper is only traced because a traced fn calls it
    src = (
        "import jax\n"
        "def helper(v):\n"
        "    return v.item()\n"
        "def f(x):\n"
        "    return helper(x)\n"
        "out = jax.jit(f)\n")
    assert any(f.rule == "host-transfer" for f in _findings(src))


def test_ast_host_transfer_fires_on_scanned_fn():
    src = (
        "import jax\n"
        "def body(c, x):\n"
        "    x.block_until_ready()\n"
        "    return c, x\n"
        "def run(xs):\n"
        "    return jax.lax.scan(body, 0.0, xs)\n")
    assert any(f.rule == "host-transfer" for f in _findings(src))


def test_ast_traced_loop_fires():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(xs):\n"
        "    t = 0\n"
        "    for x in xs:\n"
        "        t = t + x\n"
        "    return t\n")
    assert any(f.rule == "traced-loop" for f in _findings(src))


def test_ast_sync_idiom_fires_anywhere():
    src = (
        "import numpy as np\n"
        "def timeit(out):\n"
        "    return float(np.asarray(out).ravel()[0])\n")
    assert any(f.rule == "sync-idiom" for f in _findings(src))


def test_ast_suppression_comment_silences_rule():
    src = (
        "import numpy as np\n"
        "def timeit(out):\n"
        "    return float(np.asarray(out).ravel()[0])"
        "  # graft-lint: disable=sync-idiom -- intended one-shot timing sync\n")
    assert not _findings(src)


def test_ast_bare_suppression_fires():
    # the suppression still works, but the missing reason is its own finding
    src = (
        "import numpy as np\n"
        "def timeit(out):\n"
        "    return float(np.asarray(out).ravel()[0])"
        "  # graft-lint: disable=sync-idiom\n")
    findings = _findings(src)
    assert [f.rule for f in findings] == ["bare-suppression"]
    assert "sync-idiom" in findings[0].message


def test_ast_reasoned_suppression_is_not_bare():
    src = "x = 1  # graft-lint: disable=traced-loop -- static unroll\n"
    assert not _findings(src)


def test_suppression_reason_never_swallowed_into_rule_name():
    # the regex must not parse 'traced-loop -- reason' as one rule name —
    # that would silently disable the suppression itself
    from fedml_tpu.analysis.core import suppressed_rules, suppression_reason
    line = "x  # graft-lint: disable=traced-loop,sync-idiom -- both intended"
    assert suppressed_rules(line) == {"traced-loop", "sync-idiom"}
    assert suppression_reason(line) == "both intended"
    assert suppressed_rules("x  # graft-lint: disable=sync-idiom") == {
        "sync-idiom"}
    assert suppression_reason("x  # graft-lint: disable=sync-idiom") is None


def test_ast_untraced_code_is_not_flagged():
    src = (
        "import numpy as np\n"
        "def pure_host(x):\n"
        "    return float(x) + np.asarray(x).sum()\n")
    assert not [f for f in _findings(src) if f.rule == "host-transfer"]


# ----------------------------------------------------------- unschema-event

def _event_findings(src):
    return [f for f in lint_source(src, "fixture.py")
            if f.rule == "unschema-event"]


def test_unschema_event_fires_on_seam_emit_with_unknown_kind():
    src = (
        "from fedml_tpu import telemetry\n"
        "def f():\n"
        "    telemetry.emit('totally_made_up_kind', x=1)\n")
    findings = _event_findings(src)
    assert findings and "totally_made_up_kind" in findings[0].message


def test_unschema_event_fires_on_tracer_event_and_kind_kwarg():
    src = (
        "def f(tracer):\n"
        "    tracer.event('bogus_event', round=0)\n"
        "    tracer.event(kind='also_bogus', round=0)\n"
        "def g(self):\n"
        "    self.tracer.event('nested_bogus', round=0)\n")
    assert len(_event_findings(src)) == 3


def test_unschema_event_clean_on_registered_kinds():
    src = (
        "from fedml_tpu import telemetry\n"
        "def f(tracer):\n"
        "    telemetry.emit('chaos_inject', round=0, dropped=0, nan=0,\n"
        "                   corrupt=0)\n"
        "    tracer.event('round_committed', round=0)\n")
    assert not _event_findings(src)


def test_unschema_event_skips_non_literal_kind():
    # the seam's own forward (tracer.event(kind, ...)) passes a variable —
    # a static spelling check must not flag dataflow it cannot see
    src = (
        "def forward(tracer, kind, fields):\n"
        "    tracer.event(kind, **fields)\n")
    assert not _event_findings(src)


def test_unschema_event_suppression_works():
    src = (
        "def f(tracer):\n"
        "    # graft-lint: disable=unschema-event -- kind registered "
        "downstream\n"
        "    tracer.event('future_kind', round=0)\n")
    assert not _event_findings(src)


def test_unschema_event_ignores_unrelated_event_and_emit_names():
    # a bare event() function call (no attribute) is not a tracer surface
    src = (
        "def event(name):\n"
        "    return name\n"
        "def f():\n"
        "    return event('not_telemetry')\n")
    assert not _event_findings(src)


# -------------------------------------------- blocking-fetch-in-drive-loop

def _drive_findings(src):
    # the rule is path-scoped to algorithms/ driver modules
    return [f for f in lint_source(src, "fedml_tpu/algorithms/fixture.py")
            if f.rule == "blocking-fetch-in-drive-loop"]


def test_drive_loop_fetch_fires_on_per_item_float():
    # one blocking transfer per metric key — the eager-loop bug this PR fixes
    src = (
        "def train(self):\n"
        "    for r in range(n):\n"
        "        m = self.round_fn(gv)\n"
        "        rec = {k: float(v) for k, v in m.items()}\n")
    findings = _drive_findings(src)
    assert findings and "per-item float" in findings[0].message


def test_drive_loop_fetch_fires_on_jnp_scalar_in_round_loop():
    src = (
        "import jax.numpy as jnp\n"
        "def train(self):\n"
        "    for r in range(n):\n"
        "        loss = float(jnp.sum(v))\n")
    assert _drive_findings(src)


def test_drive_loop_fetch_blessed_device_get_is_clean():
    # the fixed idiom: one bulk device_get, host-side floats afterwards
    src = (
        "import jax\n"
        "def train(self):\n"
        "    for r in range(n):\n"
        "        m = self.round_fn(gv)\n"
        "        rec = {k: float(v) for k, v in jax.device_get(m).items()}\n")
    assert not _drive_findings(src)


def test_drive_loop_fetch_shape_math_is_clean():
    src = (
        "import numpy as np\n"
        "def sizes(tree):\n"
        "    return [int(np.prod(l.shape[1:])) for l in tree]\n")
    assert not _drive_findings(src)


def test_drive_loop_fetch_scoped_to_algorithms_path():
    src = (
        "import jax.numpy as jnp\n"
        "def train(self):\n"
        "    for r in range(n):\n"
        "        loss = float(jnp.sum(v))\n")
    assert not [f for f in lint_source(src, "fedml_tpu/tools/fixture.py")
                if f.rule == "blocking-fetch-in-drive-loop"]


def test_drive_loop_fetch_suppression_works():
    src = (
        "def train(self):\n"
        "    for r in range(n):\n"
        "        # graft-lint: disable=blocking-fetch-in-drive-loop -- field arithmetic on host ints\n"
        "        rec = {k: float(v) for k, v in m.items()}\n")
    assert not _drive_findings(src)


# ---------------------------------------------- naked-timer-in-drive-loop

def _timer_findings(src, path="fedml_tpu/algorithms/fixture.py"):
    return [f for f in lint_source(src, path)
            if f.rule == "naked-timer-in-drive-loop"]


def test_naked_timer_fires_on_time_time_pair_in_round_loop():
    # the r01–r05 footgun: wall-clock around an async dispatch measures
    # dispatch latency, not compute
    src = (
        "import time\n"
        "def train(self):\n"
        "    for r in range(n):\n"
        "        t0 = time.time()\n"
        "        m = self.round_fn(gv)\n"
        "        rec['round_time'] = time.time() - t0\n")
    findings = _timer_findings(src)
    assert len(findings) == 2
    assert all(f.rule == "naked-timer-in-drive-loop" for f in findings)


def test_naked_timer_fires_on_perf_counter_in_while_loop():
    src = (
        "import time\n"
        "def train(self):\n"
        "    while r < n:\n"
        "        t0 = time.perf_counter()\n"
        "        self.round_fn(gv)\n")
    assert _timer_findings(src)


def test_naked_timer_blessed_by_block_until_ready():
    # bracketing the timed region with a device sync makes the pair honest
    src = (
        "import time\n"
        "import jax\n"
        "def train(self):\n"
        "    for r in range(n):\n"
        "        t0 = time.perf_counter()\n"
        "        m = self.round_fn(gv)\n"
        "        jax.block_until_ready(m)\n"
        "        dt = time.perf_counter() - t0\n")
    assert not _timer_findings(src)


def test_naked_timer_blessed_by_telemetry_span():
    src = (
        "import time\n"
        "def train(self, tracer):\n"
        "    for r in range(n):\n"
        "        with tracer.span('dispatch', r):\n"
        "            m = self.round_fn(gv)\n"
        "        log_wall_clock(time.time())\n")
    assert not _timer_findings(src)


def test_naked_timer_clean_outside_loops():
    src = (
        "import time\n"
        "def train(self):\n"
        "    t0 = time.time()\n"
        "    run()\n")
    assert not _timer_findings(src)


def test_naked_timer_scoped_to_algorithms_path():
    src = (
        "import time\n"
        "def bench(self):\n"
        "    for r in range(n):\n"
        "        t0 = time.time()\n")
    assert not _timer_findings(src, path="fedml_tpu/tools/fixture.py")


def test_naked_timer_suppression_works():
    src = (
        "import time\n"
        "def train(self):\n"
        "    for r in range(n):\n"
        "        # graft-lint: disable=naked-timer-in-drive-loop -- coarse ETA print only\n"
        "        t0 = time.time()\n")
    assert not _timer_findings(src)


# ----------------------------------------------------- full-store-materialize

def _store_findings(src, path="fedml_tpu/algorithms/fixture.py"):
    return [f for f in lint_source(src, path)
            if f.rule == "full-store-materialize"]


def test_full_store_fires_on_np_asarray_of_store_x():
    src = (
        "import numpy as np\n"
        "def stage(store):\n"
        "    return np.asarray(store.x)\n")
    assert _store_findings(src)


def test_full_store_fires_on_full_slice_even_with_bounded_rest():
    # .x[:, :cap] bounds the SAMPLE axis but still reads every client row
    src = (
        "def cap_pack(ds, cap):\n"
        "    return ds.train.x[:, :cap]\n")
    f = _store_findings(src)
    assert f and ".x[:]" in f[0].message


def test_full_store_fires_on_jnp_stack_and_bare_slice():
    src = (
        "import jax.numpy as jnp\n"
        "def stage(store):\n"
        "    a = jnp.stack([store.x[:]])\n")
    # one finding per line even though both triggers match the same read
    assert len(_store_findings(src)) == 1


def test_full_store_bounded_reads_are_clean():
    src = (
        "import numpy as np\n"
        "def stage(store, idx):\n"
        "    probe = np.asarray(store.x[:1, 0])\n"
        "    cohort = store.x[idx]\n"
        "    one = store.x[3]\n"
        "    head = store.x[:64]\n"
        "    return np.asarray(cohort), one, head, probe\n")
    assert not _store_findings(src)


def test_full_store_blessed_inside_materialize_and_its_callees():
    src = (
        "import numpy as np\n"
        "def _gather_all(store):\n"
        "    return np.asarray(store.x)\n"
        "def materialize(store):\n"
        "    return _gather_all(store)\n")
    assert not _store_findings(src)


def test_full_store_helper_outside_blessed_closure_still_fires():
    src = (
        "import numpy as np\n"
        "def sneaky(store):\n"
        "    return np.asarray(store.x)\n"
        "def materialize(store):\n"
        "    return store.select(range(store.num_clients))\n")
    assert _store_findings(src)


def test_full_store_fires_outside_algorithms_paths_too():
    src = (
        "import numpy as np\n"
        "def stage(store):\n"
        "    return np.asarray(store.x)\n")
    assert _store_findings(src, path="tools/fixture.py")


def test_full_store_suppression_works():
    src = (
        "import numpy as np\n"
        "def stage(store):\n"
        "    # graft-lint: disable=full-store-materialize -- eager tiny fixture set\n"
        "    return np.asarray(store.x)\n")
    assert not _store_findings(src)


# ----------------------------------------- compile layer: retrace-risk (AST)

def _retrace_findings(src):
    return [f for f in _findings(src) if f.rule == "retrace-risk"]


def test_retrace_risk_fires_on_scalar_literal_into_jitted_call():
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x):\n"
        "    return f(x, 0.1)\n")
    findings = _retrace_findings(src)
    assert findings and "weak-typed" in findings[0].message


def test_retrace_risk_fires_on_float_cast_argument():
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x, s):\n"
        "    return f(x, float(s))\n")
    assert _retrace_findings(src)


def test_retrace_risk_fires_on_shape_varying_slice():
    # x[:n] changes shape per call -> one compile per distinct n
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x, n):\n"
        "    return f(x[:n])\n")
    findings = _retrace_findings(src)
    assert findings and "shape-varying" in findings[0].message


def test_retrace_risk_clean_on_strongly_typed_scalar():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "f = jax.jit(g)\n"
        "def run(x, s):\n"
        "    return f(x, jnp.float32(s))\n")
    assert not _retrace_findings(src)


def test_retrace_risk_suppression_works():
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x):\n"
        "    # graft-lint: disable=retrace-risk -- two geometries by construction\n"
        "    return f(x, 0.1)\n")
    assert not _retrace_findings(src)


# ------------------------------------- compile layer: use-after-donate (AST)

def _donate_findings(src):
    return [f for f in _findings(src) if f.rule == "use-after-donate"]


def test_use_after_donate_fires_on_read_of_donated_jit_arg():
    src = (
        "import jax\n"
        "f = jax.jit(g, donate_argnums=(0,))\n"
        "def run(x):\n"
        "    y = f(x)\n"
        "    return x + y\n")
    findings = _donate_findings(src)
    assert findings and "donated" in findings[0].message


def test_use_after_donate_fires_through_build_factory():
    # build_round_fn(donate_data=True) donates (x, y, counts) — argnums 2-4
    src = (
        "def run(trainer, cfg, agg, gv, st, x, y, counts, rng):\n"
        "    step = build_round_fn(trainer, cfg, agg, donate_data=True)\n"
        "    gv, st, m = step(gv, st, x, y, counts, rng)\n"
        "    return x.sum()\n")
    assert _donate_findings(src)


def test_use_after_donate_rebinding_is_blessed():
    # x = f(x) is the canonical donation idiom: the dead name is re-bound
    src = (
        "import jax\n"
        "f = jax.jit(g, donate_argnums=(0,))\n"
        "def run(x):\n"
        "    x = f(x)\n"
        "    return x\n")
    assert not _donate_findings(src)


def test_use_after_donate_no_donation_no_finding():
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x):\n"
        "    y = f(x)\n"
        "    return x + y\n")
    assert not _donate_findings(src)


def test_use_after_donate_suppression_works():
    src = (
        "import jax\n"
        "f = jax.jit(g, donate_argnums=(0,))\n"
        "def run(x):\n"
        "    y = f(x)\n"
        "    # graft-lint: disable=use-after-donate -- donation elided on CPU fixture\n"
        "    return x + y\n")
    assert not _donate_findings(src)


# -------------------------------------- compile layer: rng-key-reuse (AST)

def _rng_findings(src):
    return [f for f in _findings(src) if f.rule == "rng-key-reuse"]


def test_rng_key_reuse_fires_on_second_consumption():
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x, y):\n"
        "    rng = jax.random.PRNGKey(0)\n"
        "    a = f(x, rng)\n"
        "    b = f(y, rng)\n"
        "    return a + b\n")
    findings = _rng_findings(src)
    assert findings and "second" in findings[0].message


def test_rng_key_reuse_fires_on_loop_replay():
    # same key every iteration -> identical "randomness" each round
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(xs):\n"
        "    rng = jax.random.PRNGKey(0)\n"
        "    for x in xs:\n"
        "        out = f(x, rng)\n")
    findings = _rng_findings(src)
    assert findings and "loop" in findings[0].message


def test_rng_key_reuse_fold_in_derivation_is_blessed():
    # the repo idiom: derive a fresh per-iteration key inside the call
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(xs):\n"
        "    rng = jax.random.PRNGKey(0)\n"
        "    for i, x in enumerate(xs):\n"
        "        out = f(x, jax.random.fold_in(rng, i))\n")
    assert not _rng_findings(src)


def test_rng_key_reuse_feistel_block_rebind_is_fresh():
    # the superstep drive idiom (algorithms/fedavg.py): each dispatch
    # derives its key block from the host-side feistel schedule
    # (algorithms/sampling.py) — a per-iteration rebind is a FRESH key
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(xs, rng_block):\n"
        "    for j, x in enumerate(xs):\n"
        "        rng_block = feistel_keys_block(j, 2)\n"
        "        out = f(x, rng_block)\n")
    assert not _rng_findings(src)


def test_rng_key_reuse_fires_on_feistel_block_replay():
    # the derived block is itself a key: feeding the SAME block to two
    # dispatches replays identical in-graph cohort sampling
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x, y):\n"
        "    rng_block = split_keys(feistel_round_keys(3))\n"
        "    a = f(x, rng_block)\n"
        "    b = f(y, rng_block)\n"
        "    return a + b\n")
    findings = _rng_findings(src)
    assert findings and "second" in findings[0].message


def test_rng_key_reuse_suppression_works():
    src = (
        "import jax\n"
        "f = jax.jit(g)\n"
        "def run(x, y):\n"
        "    rng = jax.random.PRNGKey(0)\n"
        "    a = f(x, rng)\n"
        "    # graft-lint: disable=rng-key-reuse -- twins must see the identical key\n"
        "    b = f(y, rng)\n"
        "    return a + b\n")
    assert not _rng_findings(src)


# ------------------------------------ compile layer: lock-discipline (AST)

def _lock_findings(src):
    return [f for f in _findings(src) if f.rule == "lock-discipline"]


def test_lock_discipline_fires_on_unguarded_read_of_guarded_attr():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._staged = {}\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._staged[k] = v\n"
        "    def peek(self, k):\n"
        "        return self._staged.get(k)\n")
    findings = _lock_findings(src)
    assert findings and "_staged" in findings[0].message


def test_lock_discipline_clean_when_every_touch_is_bracketed():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._staged = {}\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._staged[k] = v\n"
        "    def peek(self, k):\n"
        "        with self._lock:\n"
        "            return self._staged.get(k)\n")
    assert not _lock_findings(src)


def test_lock_discipline_lock_held_caller_propagates():
    # _peek_locked is only ever called under the lock -> its unguarded
    # touch of self._staged is fine (call-graph propagation)
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._staged = {}\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._staged[k] = v\n"
        "            self._peek_locked(k)\n"
        "    def _peek_locked(self, k):\n"
        "        return self._staged.get(k)\n")
    assert not _lock_findings(src)


def test_lock_discipline_suppression_works():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._staged = {}\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._staged[k] = v\n"
        "    def peek(self, k):\n"
        "        # graft-lint: disable=lock-discipline -- read-only probe, GIL-atomic\n"
        "        return self._staged.get(k)\n")
    assert not _lock_findings(src)


# ------------------------------------------------------------ partition rules

def test_partition_coverage_fires_on_unmatched_leaf():
    tree = {"params": {"odd_name": jax.ShapeDtypeStruct((3, 4), jnp.float32)}}
    findings = check_partition_coverage(tree, "fixture")
    assert findings and findings[0].rule == "partition-coverage"


def test_match_partition_rules_total_on_standard_names():
    tree = {"params": {"dense": {"kernel": jax.ShapeDtypeStruct((3, 4), jnp.float32),
                                 "bias": jax.ShapeDtypeStruct((4,), jnp.float32)},
                       "step": jax.ShapeDtypeStruct((), jnp.int32)}}
    from fedml_tpu.analysis import DEFAULT_PARTITION_RULES
    specs = match_partition_rules(DEFAULT_PARTITION_RULES, tree)
    assert specs["params"]["dense"]["kernel"] == PS(None, "model")
    assert specs["params"]["step"] == PS()  # scalars auto-replicate
    with pytest.raises(ValueError, match="partition rule not found"):
        match_partition_rules(
            [], {"params": {"kernel": jax.ShapeDtypeStruct((3, 4), jnp.float32)}})


# -------------------------------------------------------- unregistered-codec

def _codec_findings(src, path="fedml_tpu/algorithms/fixture.py"):
    return [f for f in lint_source(src, path)
            if f.rule == "unregistered-codec"]


def test_unregistered_codec_fires_on_direct_int8_ctor():
    src = ("from fedml_tpu.codecs import Int8Codec\n"
           "def build(agg):\n"
           "    return Int8Codec(bits=4)\n")
    fs = _codec_findings(src)
    assert len(fs) == 1
    assert "make_codec" in fs[0].message


def test_unregistered_codec_fires_on_dotted_topk_ctor():
    src = ("from fedml_tpu import codecs\n"
           "def build():\n"
           "    return codecs.topk.TopKCodec(k=3)\n")
    fs = _codec_findings(src, "fedml_tpu/parallel/fixture.py")
    assert len(fs) == 1
    assert "TopKCodec" in fs[0].message


def test_unregistered_codec_make_codec_and_wrapper_are_clean():
    src = ("from fedml_tpu.codecs import make_codec\n"
           "from fedml_tpu.codecs.transport import CodecAggregator\n"
           "def build(cfg, agg):\n"
           "    codec = make_codec(cfg.update_codec, cfg)\n"
           "    return CodecAggregator(codec, agg, slots=8)\n")
    assert _codec_findings(src, "fedml_tpu/serving/fixture.py") == []


def test_unregistered_codec_scoped_to_data_plane_paths():
    src = ("from fedml_tpu.codecs import Int8Codec\n"
           "c = Int8Codec(bits=8)\n")
    # codecs/ itself and out-of-scope trees (analysis, tools, tests) are
    # where direct construction is legitimate
    for path in ("fedml_tpu/codecs/int8.py", "fedml_tpu/analysis/comms.py",
                 "tools/bench_codec.py"):
        assert _codec_findings(src, path) == []
    assert _codec_findings(src, "fedml_tpu/algorithms/fixture.py")


def test_unregistered_codec_suppression_works():
    src = ("from fedml_tpu.codecs import TopKCodec\n"
           "def build():\n"
           "    # graft-lint: disable=unregistered-codec -- fixture codec "
           "with a fixed k, never budget-pinned\n"
           "    return TopKCodec(k=2)\n")
    assert _codec_findings(src) == []


# ------------------------------------- personal-state-in-federated-tree

def _personal_findings(src, path="fedml_tpu/algorithms/fixture.py"):
    return [f for f in lint_source(src, path)
            if f.rule == "personal-state-in-federated-tree"]


def test_personal_state_fires_on_psum_of_personal_rows():
    src = (
        "import jax\n"
        "def agg(new_personal):\n"
        "    return jax.lax.psum(new_personal, 'clients')\n")
    fs = _personal_findings(src)
    assert len(fs) == 1
    assert "new_personal" in fs[0].message


def test_personal_state_fires_on_codec_and_checkpoint_surfaces():
    src = (
        "def ship(codec, personal_rows, ckpt_dir, staged):\n"
        "    wire, residual = codec.encode(personal_rows, residual)\n"
        "    save_checkpoint(ckpt_dir, 0, state=staged.personal)\n")
    fs = _personal_findings(src)
    assert len(fs) == 2
    assert any("encode" in f.message for f in fs)
    assert any("save_checkpoint" in f.message for f in fs)


def test_personal_state_fires_on_attribute_chain_into_aggregate():
    src = (
        "def round(agg, self):\n"
        "    return agg.aggregate(self._last_personal)\n")
    assert _personal_findings(src)


def test_personal_state_clean_on_non_surface_and_non_personal():
    # personal rows through jnp/tree math, and global trees through psum,
    # are both fine — only the cross product trips
    src = (
        "import jax, jax.numpy as jnp\n"
        "def ok(new_personal, new_global):\n"
        "    a = jax.tree.map(jnp.add, new_personal, new_personal)\n"
        "    b = jax.lax.psum(new_global, 'clients')\n"
        "    return a, b\n")
    assert not _personal_findings(src)


def test_personal_state_blessed_inside_adapter_bank():
    src = (
        "def flush(self, personal_rows):\n"
        "    return self.codec.encode(personal_rows, None)\n")
    assert not _personal_findings(src, "fedml_tpu/models/adapter_bank.py")
    assert _personal_findings(src, "fedml_tpu/serving/fixture.py")


def test_personal_state_suppression_works():
    src = (
        "import jax\n"
        "def agg(new_personal):\n"
        "    # graft-lint: disable=personal-state-in-federated-tree -- "
        "zero-row identity proof fixture\n"
        "    return jax.lax.psum(new_personal, 'clients')\n")
    assert not _personal_findings(src)


# ----------------------------------------------------------------- repo clean

def test_every_registered_model_has_an_example():
    from fedml_tpu.analysis.targets import models_missing_examples
    assert models_missing_examples() == []


@pytest.mark.slow
def test_repo_is_clean_full():
    import os
    from fedml_tpu.analysis.targets import run_all
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = run_all(root, include_models=True)
    assert report.ok, "\n" + report.summary()


@pytest.mark.slow  # ~27s; ci_smoke's first step runs the identical gate
# (python -m fedml_tpu.analysis --fast) on every push, so tier-1 keeps only
# the per-rule unit tests above
def test_repo_is_clean_fast():
    # engine/silo/darts jaxprs + donation + retrace + partition coverage +
    # the AST sweep over fedml_tpu/ and tools/ (pins the satellite fixes);
    # the 29-model dtype sweep runs per-model in test_dtype_registry.py
    import os
    from fedml_tpu.analysis.targets import run_all
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = run_all(root, include_models=False)
    assert report.ok, "\n" + report.summary()


def test_report_json_roundtrip(tmp_path):
    import json
    r = Report()
    r.extend([Finding("dead-cast", "t", "msg")])
    r.mark("t")
    p = tmp_path / "LINT.json"
    r.write_json(str(p))
    d = json.loads(p.read_text())
    assert d["ok"] is False and d["num_findings"] == 1
    assert d["findings"][0]["rule"] == "dead-cast"


# ------------------------------- unconstrained-intermediate (tensor.step)

def _matmul_chain_jaxpr(constrained: bool):
    """Two chained matmuls, optionally pinning the intermediate — the
    minimal shape of an activation-sharded step body."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("clients", "tensor"))

    def f(a, w1, w2):
        h = a @ w1
        if constrained:
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, PS(None, "tensor")))
        return h @ w2

    return jax.make_jaxpr(f)(jnp.zeros((2, 8)), jnp.zeros((8, 8)),
                             jnp.zeros((8, 4))).jaxpr


def test_unconstrained_intermediate_fires_on_bare_matmuls():
    from fedml_tpu.analysis import check_unconstrained_intermediate

    findings = check_unconstrained_intermediate(
        _matmul_chain_jaxpr(constrained=False), "fixture",
        tensor_axis_size=4)
    assert findings and findings[0].rule == "unconstrained-intermediate"
    assert "0 sharding constraints" in findings[0].message


def test_unconstrained_intermediate_clean_with_constraint():
    from fedml_tpu.analysis import check_unconstrained_intermediate

    assert not check_unconstrained_intermediate(
        _matmul_chain_jaxpr(constrained=True), "fixture",
        tensor_axis_size=4)


def test_unconstrained_intermediate_structurally_off_at_one_shard():
    # a 1-shard tensor axis is trivially replicated — no constraint needed,
    # no finding (the shards=1 bit-identity contract)
    from fedml_tpu.analysis import check_unconstrained_intermediate

    assert not check_unconstrained_intermediate(
        _matmul_chain_jaxpr(constrained=False), "fixture",
        tensor_axis_size=1)


def test_unconstrained_intermediate_repo_step_is_clean():
    # the real tensor.step program (transformer, activation rule table on)
    # carries its constraints; the fixture arm with the table off fires —
    # pinning that the finding watches the REAL seam, not a toy
    from fedml_tpu.analysis import check_unconstrained_intermediate
    from fedml_tpu.analysis.targets import tensor_step_jaxpr

    jaxpr, t_sz = tensor_step_jaxpr()
    assert not check_unconstrained_intermediate(
        jaxpr, "tensor.step", tensor_axis_size=t_sz)
    dark, t_sz = tensor_step_jaxpr(constrained=False)
    assert check_unconstrained_intermediate(
        dark, "tensor.step", tensor_axis_size=t_sz)


# ------------------------------------- tensor-rule coverage (runtime tables)

def test_tensor_rule_coverage_repo_tables_clean():
    from fedml_tpu.analysis.targets import check_tensor_rule_coverage

    assert check_tensor_rule_coverage() == []


def test_tensor_rule_coverage_unmatched_param_trips():
    from fedml_tpu.analysis.targets import check_tensor_rule_coverage

    # a table that only knows biases leaves every kernel/embedding unmatched
    findings = check_tensor_rule_coverage(
        rule_tables={"transformer": [(r"(bias|scale)$", PS())]},
        family_models={"transformer": ("transformer_nwp",)})
    assert findings, "kernels without a rule must trip the lint"
    assert any("matches no PartitionSpec rule" in f.message for f in findings)


def test_tensor_rule_coverage_dead_rule_trips():
    from fedml_tpu.analysis.targets import check_tensor_rule_coverage
    from fedml_tpu.parallel.tensor import TRANSFORMER_PARTITION_RULES

    rules = [(r"no_such_layer_ever/kernel$", PS(None, "tensor"))]
    rules += list(TRANSFORMER_PARTITION_RULES)
    findings = check_tensor_rule_coverage(
        rule_tables={"transformer": rules},
        family_models={"transformer": ("transformer_nwp",)})
    assert len(findings) == 1
    assert "dead rule" in findings[0].message
