"""Silo-grouped conv execution path (VERDICT r4 next #1).

The measured 1.55x grouped-conv lever (docs/cross_silo_ladder.json) ships as
an execution path: GroupableConv lowers vmapped narrow convs to one
feature_group_count=S conv, and the grad-outside-vmap silo engine
(algorithms/silo_grouped.py) trains with it. These tests pin the two claims
that make the path safe to use:
  1. GroupableConv is numerically an nn.Conv drop-in (unbatched AND under
     every vmap pattern the framework uses), with an identical param tree.
  2. Full training trajectories (multi-round, aggregation included) match
     the standard vmap engine to tight tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.aggregators import make_aggregator
from fedml_tpu.algorithms.engine import build_multi_round_fn, build_round_fn
from fedml_tpu.algorithms.silo_grouped import (
    build_silo_multi_round_fn,
    build_silo_round_fn,
)
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.models.resnet import Bottleneck, ResNetCifar


def _models(threshold=8):
    kw = dict(block=Bottleneck, layers=(1, 1, 1), widths=(4, 8, 16), output_dim=10)
    return ResNetCifar(**kw), ResNetCifar(silo_threshold=threshold, **kw)


def _data(s=3, n=8, hw=8, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.rand(s, n, hw, hw, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, size=(s, n)).astype(np.int32))
    counts = jnp.full((s,), n, jnp.int32)
    return x, y, counts


def test_groupable_conv_is_nn_conv_drop_in():
    """Same param tree structure + same numerics, unbatched and under the
    eval-style vmap (weights unbatched) and the silo-style vmap (weights
    batched — where the grouped lowering actually fires)."""
    plain, silo = _models()
    x, _, _ = _data()
    v_plain = plain.init(jax.random.PRNGKey(0), x[0, :1], train=False)
    v_silo = silo.init(jax.random.PRNGKey(0), x[0, :1], train=False)
    # identical tree: same paths, same shapes, same init values
    assert jax.tree_util.tree_structure(v_plain) == jax.tree_util.tree_structure(v_silo)
    for a, b in zip(jax.tree.leaves(v_plain), jax.tree.leaves(v_silo)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # unbatched forward
    np.testing.assert_allclose(
        np.asarray(silo.apply(v_plain, x[0], train=False)),
        np.asarray(plain.apply(v_plain, x[0], train=False)), rtol=1e-5, atol=1e-6)

    # eval-style vmap: variables broadcast, data batched (fallback rule path)
    f_plain = jax.vmap(lambda xi: plain.apply(v_plain, xi, train=False))
    f_silo = jax.vmap(lambda xi: silo.apply(v_plain, xi, train=False))
    np.testing.assert_allclose(np.asarray(f_silo(x)), np.asarray(f_plain(x)),
                               rtol=1e-5, atol=1e-6)

    # silo-style vmap: per-silo variables AND data batched (grouped lowering)
    stacked = jax.tree.map(lambda l: jnp.stack([l, l * 1.5, l * 0.5]), v_plain)
    g_plain = jax.vmap(lambda v, xi: plain.apply(v, xi, train=False))
    g_silo = jax.vmap(lambda v, xi: silo.apply(v, xi, train=False))
    np.testing.assert_allclose(np.asarray(g_silo(stacked, x)),
                               np.asarray(g_plain(stacked, x)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("full", [True, False], ids=["full", "ragged"])
def test_silo_round_matches_engine_trajectory(full):
    """3 rounds of silo-grouped FedAvg == 3 rounds of the vmap engine
    (weights, BN stats, metrics), tight tolerance. Covers SGD+clip (the
    cross-silo bench config's optimizer chain) and the ragged path's
    per-silo no-op-step machinery."""
    plain, silo = _models()
    x, y, counts = _data()
    if not full:
        counts = jnp.asarray([8, 5, 3], jnp.int32)
    cfg = FedConfig(batch_size=4, epochs=2, lr=0.1, client_optimizer="sgd",
                    client_num_per_round=3, assume_full_clients=full)
    agg = make_aggregator("fedavg", cfg)
    tr_plain, tr_silo = ClassificationTrainer(plain), ClassificationTrainer(silo)
    gv = tr_plain.init(jax.random.PRNGKey(0), x[0, :1])
    st = agg.init_state(gv)

    rf_plain = build_round_fn(tr_plain, cfg, agg)
    rf_silo = build_silo_round_fn(tr_silo, cfg, agg)

    gv_p, st_p = gv, st
    gv_s, st_s = gv, st
    key = jax.random.PRNGKey(7)
    for r in range(3):
        rng = jax.random.fold_in(key, r)
        gv_p, st_p, m_p = rf_plain(gv_p, st_p, x, y, counts, rng)
        gv_s, st_s, m_s = rf_silo(gv_s, st_s, x, y, counts, rng)
        for k in m_p:
            np.testing.assert_allclose(np.asarray(m_s[k]), np.asarray(m_p[k]),
                                       rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(gv_p), jax.tree.leaves(gv_s)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # ~11s; the plain-SGD engine-match twin above pins the
# same silo==engine trajectory in the fast suite
def test_silo_momentum_optimizer_exact_per_silo():
    """vmapped optimizer = exact per-silo semantics for stateful chains
    (momentum + weight decay): trajectories still match the engine."""
    plain, silo = _models()
    x, y, counts = _data()
    cfg = FedConfig(batch_size=4, epochs=1, lr=0.05, client_optimizer="sgd",
                    momentum=0.9, wd=1e-4, client_num_per_round=3,
                    assume_full_clients=True)
    agg = make_aggregator("fedavg", cfg)
    tr_plain, tr_silo = ClassificationTrainer(plain), ClassificationTrainer(silo)
    gv = tr_plain.init(jax.random.PRNGKey(1), x[0, :1])
    st = agg.init_state(gv)
    rng = jax.random.PRNGKey(3)
    gv_p, _, _ = build_round_fn(tr_plain, cfg, agg)(gv, st, x, y, counts, rng)
    gv_s, _, _ = build_silo_round_fn(tr_silo, cfg, agg)(gv, st, x, y, counts, rng)
    for a, b in zip(jax.tree.leaves(gv_p), jax.tree.leaves(gv_s)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # ~10s epochs=2 compile; the LocalResult num_steps
# contract is structural, not codegen-sensitive — nightly coverage suffices
def test_silo_round_with_fednova_aggregator():
    """The silo path's LocalResult contract (stacked variables + per-silo
    num_steps) must satisfy non-FedAvg aggregators too — FedNova consumes
    num_steps for tau normalization. RAGGED counts on purpose: with uniform
    tau FedNova collapses algebraically to FedAvg and a wrong-but-uniform
    num_steps would pass unnoticed; differing per-silo step counts make the
    tau normalization load-bearing."""
    plain, silo = _models()
    x, y, counts = _data()
    counts = jnp.asarray([8, 5, 3], jnp.int32)  # 2 / 2 / 1 real batches
    cfg = FedConfig(batch_size=4, epochs=2, lr=0.1, client_optimizer="sgd",
                    client_num_per_round=3, assume_full_clients=False)
    agg = make_aggregator("fednova", cfg)
    tr_plain, tr_silo = ClassificationTrainer(plain), ClassificationTrainer(silo)
    gv = tr_plain.init(jax.random.PRNGKey(2), x[0, :1])
    st = agg.init_state(gv)
    rng = jax.random.PRNGKey(5)
    gv_p, _, _ = build_round_fn(tr_plain, cfg, agg)(gv, st, x, y, counts, rng)
    gv_s, _, _ = build_silo_round_fn(tr_silo, cfg, agg)(gv, st, x, y, counts, rng)
    for a, b in zip(jax.tree.leaves(gv_p), jax.tree.leaves(gv_s)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # ~9s K=4 scan compile x2; the single-round engine-match
# tests above keep the silo numerics pinned in the fast suite
def test_silo_multi_round_matches_engine_multi_round():
    """The scan-amortized silo path (what bench.py runs) matches the
    engine's multi-round scan, including in-graph client sampling."""
    plain, silo = _models()
    x, y, counts = _data(s=4)
    cfg = FedConfig(batch_size=4, epochs=1, lr=0.1, client_optimizer="sgd",
                    client_num_per_round=2, assume_full_clients=True)
    agg = make_aggregator("fedavg", cfg)
    tr_plain, tr_silo = ClassificationTrainer(plain), ClassificationTrainer(silo)
    gv = tr_plain.init(jax.random.PRNGKey(0), x[0, :1])
    st = agg.init_state(gv)
    key = jax.random.PRNGKey(11)
    gv_p, _, m_p = build_multi_round_fn(tr_plain, cfg, agg, 4)(gv, st, x, y, counts, key)
    gv_s, _, m_s = build_silo_multi_round_fn(tr_silo, cfg, agg, 4)(gv, st, x, y, counts, key)
    for k in m_p:
        np.testing.assert_allclose(np.asarray(m_s[k]), np.asarray(m_p[k]),
                                   rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(gv_p), jax.tree.leaves(gv_s)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)
