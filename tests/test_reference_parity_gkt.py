"""FedGKT full-round oracle vs the LIVING reference.

Drives reference fedml_api/distributed/fedgkt/GKTClientTrainer.py:49-126
(client CE+KD minibatch training, feature/logit export) and
GKTServerTrainer.py:234-291 (train_large_model_on_the_server: one Adam/SGD
step per (client, batch) feature chunk with the PERSISTENT server optimizer)
for TWO full rounds against fedml_tpu.algorithms.fedgkt.FedGKTAPI with
bit-ported tiny twin models. Matched:

  - round-0 client params after epochs_client epochs (CE only),
  - exported per-sample features and client logits,
  - server params after the round-0 server phase (KL + alpha*CE loss,
    Adam(amsgrad, wd=1e-4) / SGD(momentum .9, nesterov, wd)),
  - round-1 client params (CE + alpha*KD against server logits) and
    round-1 server params — which exercises the server optimizer state
    CARRYOVER across rounds (fresh Adam state would visibly diverge).

Intended deviation (fedgkt.py module docstring): the reference captures
next-round KD targets DURING the last server epoch (pre-step, training
mode, GKTServerTrainer.py:271-284), so each batch's logits come from a
different mid-epoch model; the rebuild recomputes all logits from the final
server params in eval mode. The oracle verifies our logits equal the
reference's final-params eval recomputation, then INJECTS those shared
targets into the reference clients for round 1 so the remaining comparisons
isolate the training algebra.

Full batches (batch_size=-1) keep the rebuild's in-graph shuffle
permutation-invariant so order-insensitive losses compare exactly.

Slow-marked: torch training runs + two jitted GKT phases.
"""

from __future__ import annotations

import copy
from types import SimpleNamespace

import numpy as np
import pytest

pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")

from _reference_oracle import setup_reference, torch_batches  # noqa: E402

setup_reference()
# the living-reference checkout is not shipped in every container;
# without it the oracle has nothing to run — skip at collect time
# instead of erroring the whole module
pytest.importorskip(
    "fedml_api",
    reason="reference FedML checkout (/root/reference) unavailable")

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from fedml_tpu.algorithms.fedgkt import FedGKTAPI  # noqa: E402
from fedml_tpu.core.config import FedConfig  # noqa: E402
from fedml_tpu.data.packing import PackedClients  # noqa: E402
from fedml_tpu.data.registry import FederatedDataset  # noqa: E402

from fedml_api.distributed.fedgkt import utils as gkt_utils  # noqa: E402
from fedml_api.distributed.fedgkt.GKTClientTrainer import GKTClientTrainer  # noqa: E402
from fedml_api.distributed.fedgkt.GKTServerTrainer import GKTServerTrainer  # noqa: E402


def _accuracy_shim(output, target, topk=(1,)):
    """The reference's metrics-only accuracy helper (utils.py:56-72) calls
    .view on a non-contiguous tensor, which modern torch rejects; reshape
    keeps identical values. Training math is untouched. Applied per-test via
    monkeypatch so other tests see the real reference function."""
    maxk = max(topk)
    batch_size = target.size(0)
    _, pred = output.topk(maxk, dim=1, largest=True, sorted=True)
    pred = pred.t()
    correct = pred.eq(target.view(1, -1).expand_as(pred))
    return [correct[:k].reshape(-1).float().sum(0).mul_(100.0 / batch_size)
            for k in topk]

C = 5          # classes
N_CLIENTS = 2
N = 12         # samples per client (full batch)
HW = 8


class TorchGKTClient(tnn.Module):
    def __init__(self):
        super().__init__()
        self.conv = tnn.Conv2d(1, 4, 3, padding=1)
        self.fc = tnn.Linear(4 * HW * HW, C)

    def forward(self, x):
        f = F.relu(self.conv(x))          # [b, 4, 8, 8]
        return self.fc(f.flatten(1)), f


class TorchGKTServer(tnn.Module):
    def __init__(self):
        super().__init__()
        self.conv = tnn.Conv2d(4, 8, 3, padding=1)
        self.fc = tnn.Linear(8, C)

    def forward(self, f):
        h = F.relu(self.conv(f))          # [b, 8, 8, 8]
        return self.fc(h.mean(dim=(2, 3)))


class FlaxGKTClient(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        f = nn.relu(nn.Conv(4, (3, 3), padding=1, name="conv")(x))
        logits = nn.Dense(C, name="fc")(f.reshape(f.shape[0], -1))
        return logits, f                   # f: [b, 8, 8, 4] NHWC


class FlaxGKTServer(nn.Module):
    @nn.compact
    def __call__(self, f, train: bool = False):
        h = nn.relu(nn.Conv(8, (3, 3), padding=1, name="conv")(f))
        return nn.Dense(C, name="fc")(h.mean(axis=(1, 2)))


def _port_client(sd):
    fc = sd["fc.weight"].numpy()  # [C, 4*8*8] in (c, h, w) flatten order
    fc = fc.reshape(C, 4, HW, HW).transpose(0, 2, 3, 1).reshape(C, -1)
    return {"params": {
        "conv": {"kernel": jnp.asarray(np.transpose(sd["conv.weight"].numpy(), (2, 3, 1, 0))),
                 "bias": jnp.asarray(sd["conv.bias"].numpy())},
        "fc": {"kernel": jnp.asarray(fc.T), "bias": jnp.asarray(sd["fc.bias"].numpy())},
    }}


def _port_server(sd):
    return {"params": {
        "conv": {"kernel": jnp.asarray(np.transpose(sd["conv.weight"].numpy(), (2, 3, 1, 0))),
                 "bias": jnp.asarray(sd["conv.bias"].numpy())},
        "fc": {"kernel": jnp.asarray(sd["fc.weight"].numpy().T),
               "bias": jnp.asarray(sd["fc.bias"].numpy())},
    }}


def _client_vec(variables):
    p = variables["params"]
    fc = np.asarray(p["fc"]["kernel"]).T.reshape(C, HW, HW, 4)
    fc = fc.transpose(0, 3, 1, 2).reshape(C, -1)
    return np.concatenate([
        np.transpose(np.asarray(p["conv"]["kernel"]), (3, 2, 0, 1)).ravel(),
        np.asarray(p["conv"]["bias"]).ravel(),
        fc.ravel(), np.asarray(p["fc"]["bias"]).ravel()])


def _server_vec(variables):
    p = variables["params"]
    return np.concatenate([
        np.transpose(np.asarray(p["conv"]["kernel"]), (3, 2, 0, 1)).ravel(),
        np.asarray(p["conv"]["bias"]).ravel(),
        np.asarray(p["fc"]["kernel"]).T.ravel(),
        np.asarray(p["fc"]["bias"]).ravel()])


def _torch_vec(model):
    return np.concatenate([
        p.detach().numpy().ravel()
        for p in (model.conv.weight, model.conv.bias, model.fc.weight, model.fc.bias)])


def _rel(a, b):
    return np.linalg.norm(a - b) / max(np.linalg.norm(a), 1e-12)


@pytest.mark.parametrize("optimizer", ["Adam", "SGD"])
def test_fedgkt_two_round_parity(optimizer, monkeypatch):
    monkeypatch.setattr(gkt_utils, "accuracy", _accuracy_shim)
    lr, wd, alpha, temp, epochs_client = 0.01, 5e-4, 1.0, 3.0, 2
    rng = np.random.RandomState(0)
    xs = rng.randn(N_CLIENTS, N, HW, HW, 1).astype(np.float32)
    ys = rng.randint(0, C, (N_CLIENTS, N)).astype(np.int64)

    args = SimpleNamespace(
        optimizer=optimizer, lr=lr, wd=wd, temperature=temp, alpha=alpha,
        epochs_client=epochs_client, whether_training_on_client=1,
        whether_distill_on_the_server=1, no_bn_wd=0, multi_gpu_server=0,
        sweep=0, batch_size=N)

    class _LoaderList(list):
        """Fixed-order batch list with the .dataset attribute the reference's
        progress logging dereferences (GKTClientTrainer.py:87)."""

        def __init__(self, batches, n):
            super().__init__(batches)
            self.dataset = range(n)

    def gkt_batches(i):
        # shared fixed-order batching + the reference's NCHW layout
        return _LoaderList(torch_batches(xs[i].transpose(0, 3, 1, 2), ys[i], N), N)

    # ---------------- reference side
    torch.manual_seed(0)
    t_clients = [TorchGKTClient() for _ in range(N_CLIENTS)]
    t_server = TorchGKTServer()
    client_init = [copy.deepcopy(m.state_dict()) for m in t_clients]
    server_init = copy.deepcopy(t_server.state_dict())

    train_dict = {i: gkt_batches(i) for i in range(N_CLIENTS)}
    test_dict = {i: gkt_batches(i) for i in range(N_CLIENTS)}
    ref_clients = [
        GKTClientTrainer(i, train_dict, test_dict, N, torch.device("cpu"),
                         t_clients[i], args)
        for i in range(N_CLIENTS)]
    ref_server = GKTServerTrainer(N_CLIENTS, torch.device("cpu"), t_server, args)

    # round 0: clients train (CE only), export; server trains one epoch
    ref_feats, ref_logits = [], []
    for i, tr in enumerate(ref_clients):
        out = tr.train()
        ref_feats.append(out[0][0])    # batch 0 features [N, 4, 8, 8]
        ref_logits.append(out[1][0])
        ref_server.add_local_trained_result(i, *out)
    ref_server.train_large_model_on_the_server()
    ref_client_r0 = [_torch_vec(m) for m in t_clients]
    ref_server_r0 = _torch_vec(t_server)

    # final-params eval logits — the shared KD-target convention (see module
    # docstring); injected into the reference clients for round 1
    t_server.eval()
    shared_logits = []
    with torch.no_grad():
        for i in range(N_CLIENTS):
            f = torch.from_numpy(ref_server.client_extracted_feauture_dict[i][0])
            shared_logits.append(t_server(f).numpy())
    t_server.train()
    for i, tr in enumerate(ref_clients):
        tr.update_large_model_logits({0: shared_logits[i]})

    # round 1: clients train WITH KD, server trains again (carried optimizer)
    for i, tr in enumerate(ref_clients):
        out = tr.train()
        ref_server.add_local_trained_result(i, *out)
    ref_server.train_large_model_on_the_server()
    ref_client_r1 = [_torch_vec(m) for m in t_clients]
    ref_server_r1 = _torch_vec(t_server)

    # ---------------- rebuild side
    ds = FederatedDataset(
        name="gkt-oracle",
        train=PackedClients(xs, ys.astype(np.int32), np.full(N_CLIENTS, N, np.int32)),
        test=None,
        train_global=(xs.reshape(-1, HW, HW, 1), ys.reshape(-1).astype(np.int32)),
        test_global=(xs.reshape(-1, HW, HW, 1), ys.reshape(-1).astype(np.int32)),
        class_num=C)
    cfg = FedConfig(client_optimizer=optimizer.lower(), lr=lr, wd=wd,
                    epochs=epochs_client, batch_size=-1, comm_round=2, seed=0)
    api = FedGKTAPI(ds, cfg, FlaxGKTClient(), FlaxGKTServer(), alpha=alpha,
                    temperature=temp, server_epochs=1)
    ported = [_port_client(sd) for sd in client_init]
    api.client_vars = jax.tree.map(lambda *ls: jnp.stack(ls), *ported)
    api.client_opt_states = jax.vmap(api.c_opt.init)(api.client_vars["params"])
    api.server_vars = _port_server(server_init)
    api.server_opt_state = api.s_opt.init(api.server_vars["params"])

    x = jnp.asarray(ds.train.x)
    y = jnp.asarray(ds.train.y)
    counts = jnp.asarray(ds.train.counts)
    mask = jnp.ones((N_CLIENTS, N), jnp.float32)
    key = jax.random.PRNGKey(0)
    sl = jnp.zeros((N_CLIENTS, N, C))

    sl = api.train_one_round(0, x, y, counts, mask, sl, key)

    # round-0 comparisons
    for i in range(N_CLIENTS):
        ours = _client_vec(jax.tree.map(lambda l: l[i], api.client_vars))
        assert _rel(ref_client_r0[i], ours) < 1e-4, f"client {i} r0"
    assert _rel(ref_server_r0, _server_vec(api.server_vars)) < 1e-4, "server r0"
    # exported features/logits: recompute ours from the post-round client
    for i in range(N_CLIENTS):
        cv = jax.tree.map(lambda l: l[i], api.client_vars)
        logits_i, feats_i = FlaxGKTClient().apply(cv, x[i], train=False)
        np.testing.assert_allclose(
            np.transpose(np.asarray(feats_i), (0, 3, 1, 2)), ref_feats[i],
            atol=5e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(logits_i), ref_logits[i],
                                   atol=5e-5, rtol=1e-4)
    # our KD targets == the reference's final-params eval recomputation
    for i in range(N_CLIENTS):
        np.testing.assert_allclose(np.asarray(sl[i]), shared_logits[i],
                                   atol=5e-5, rtol=1e-4)

    sl = api.train_one_round(1, x, y, counts, mask, sl, key)

    # round-1 comparisons (KD path + server optimizer carryover)
    for i in range(N_CLIENTS):
        ours = _client_vec(jax.tree.map(lambda l: l[i], api.client_vars))
        assert _rel(ref_client_r1[i], ours) < 5e-4, f"client {i} r1"
    assert _rel(ref_server_r1, _server_vec(api.server_vars)) < 5e-4, "server r1"

    # non-vacuity: training moved both models
    assert _rel(ref_server_r0, ref_server_r1) > 1e-4
    for i in range(N_CLIENTS):
        assert np.abs(ref_client_r1[i] - _client_vec(ported[i])).max() > 1e-3
