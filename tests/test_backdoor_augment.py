"""Backdoor eval + jit-native augmentation tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.backdoor import (
    apply_trigger,
    backdoor_metrics,
    poison_client_data,
)
from fedml_tpu.data.augment import cifar_train_augment, cutout, random_crop, random_flip


def test_trigger_and_poison():
    rng = np.random.RandomState(0)
    x = rng.rand(20, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 10, 20).astype(np.int32)
    xt = apply_trigger(x, size=2)
    assert np.all(xt[:, -2:, -2:, :] == xt.max())
    np.testing.assert_array_equal(xt[:, :6, :6], x[:, :6, :6])  # rest untouched

    xp, yp = poison_client_data(x, y, count=20, target_label=7, poison_frac=0.5,
                                rng=np.random.RandomState(1))
    assert (yp == 7).sum() >= 10
    assert not np.array_equal(xp, x)


def test_backdoor_metrics_on_backdoored_model():
    """A 'model' that fires the target class whenever the trigger is present
    must score ~1.0 backdoor success; a clean model ~chance."""
    x = np.random.RandomState(0).rand(50, 8, 8, 1).astype(np.float32) * 0.5
    y = np.random.RandomState(1).randint(0, 4, 50).astype(np.int32)

    def backdoored(xb):
        has_trigger = (xb[:, -3:, -3:, :] > 0.49).all(axis=(1, 2, 3))
        logits = jnp.zeros((xb.shape[0], 4)).at[:, 2].set(
            jnp.where(has_trigger, 10.0, -10.0))
        return logits

    m = backdoor_metrics(backdoored, x, y, target_label=2)
    assert m["Backdoor/SuccessRate"] > 0.99


def test_augment_shapes_and_determinism():
    rng = jax.random.PRNGKey(0)
    x = jnp.asarray(np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32))
    for fn in (random_flip, lambda r, a: random_crop(r, a, 4),
               lambda r, a: cutout(r, a, 16), cifar_train_augment):
        out = fn(rng, x)
        assert out.shape == x.shape
        out2 = fn(rng, x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))  # same key -> same aug


def test_cutout_zeroes_patch():
    rng = jax.random.PRNGKey(3)
    x = jnp.ones((2, 32, 32, 3))
    out = np.asarray(cutout(rng, x, 16))
    assert out.min() == 0.0 and out.max() == 1.0
    zeros = (out[0, :, :, 0] == 0).sum()
    assert 8 * 8 <= zeros <= 16 * 16  # clipped square at the border


@pytest.mark.slow
def test_augmented_trainer_end_to_end():
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    ds = load_dataset("cifar10", client_num_in_total=4, partition_method="homo", seed=0)
    cfg = FedConfig(comm_round=2, batch_size=32, lr=0.05, momentum=0.9,
                    client_num_in_total=4, client_num_per_round=4, ci=1,
                    frequency_of_the_test=2)
    trainer = ClassificationTrainer(create_model("cnn_cifar", output_dim=10),
                                    augment_fn=cifar_train_augment)
    api = FedAvgAPI(ds, cfg, trainer)
    hist = api.train()
    assert np.isfinite(hist[-1]["Test/Loss"])


def test_main_fedavg_robust_backdoor_eval(tmp_path):
    """Robust main poisons attacker clients and reports MainTask/Acc +
    Backdoor/SuccessRate in wandb-summary.json (reference poisoned-task
    eval, FedAvgRobustAggregator.py:14-112)."""
    import json

    from fedml_tpu.experiments.main_fedavg_robust import main

    main([
        "--dataset", "mnist", "--model", "lr", "--partition_method", "homo",
        "--client_num_in_total", "4", "--client_num_per_round", "4",
        "--comm_round", "2", "--epochs", "1", "--batch_size", "32",
        "--lr", "0.1", "--attacker_num", "1", "--poison_frac", "0.5",
        "--target_label", "3", "--run_dir", str(tmp_path / "run"),
    ])
    summary = json.loads((tmp_path / "run" / "wandb-summary.json").read_text())
    assert "MainTask/Acc" in summary and "Backdoor/SuccessRate" in summary
    assert summary["MainTask/Acc"] > 0.5
    assert 0.0 <= summary["Backdoor/SuccessRate"] <= 1.0
