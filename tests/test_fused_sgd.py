"""Fused local-SGD pallas kernel vs the engine path.

With dropout disabled and shuffling off, one fused round must reproduce the
vmap-engine round trajectory exactly (f32): same forward (conv/pool/dense),
same CE gradient, same first-max pool routing, same optax-style global-norm
clip, same SGD update, same weighted aggregation. Runs the kernel in pallas
interpret mode on the CPU test mesh.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.aggregators import make_aggregator
from fedml_tpu.algorithms.engine import build_round_fn
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.ops.fused_sgd import (
    FusedEpochSpec,
    build_fused_round_fn,
    build_fused_multi_round_fn,
)


class _CNNNoDrop(nn.Module):
    """CNN_DropOut (models/cnn.py) with dropout removed — parameter tree is
    identical (Dropout has no params), so fused-kernel outputs are comparable
    leaf for leaf."""

    output_dim: int = 5

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", name="conv2d_1")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", name="conv2d_2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, name="linear_1")(x))
        return nn.Dense(self.output_dim, name="linear_2")(x).astype(jnp.float32)


CLIENTS, N, BS, H, C = 3, 40, 20, 12, 5


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.rand(CLIENTS, N, H, H, 1).astype(np.float32))
    y = jnp.asarray(rng.randint(0, C, size=(CLIENTS, N)).astype(np.int32))
    counts = jnp.full((CLIENTS,), N, jnp.int32)
    cfg = FedConfig(batch_size=BS, epochs=1, lr=0.1, client_optimizer="sgd",
                    client_num_per_round=CLIENTS, shuffle=False)
    trainer = ClassificationTrainer(_CNNNoDrop(output_dim=C))
    gv = trainer.init(jax.random.PRNGKey(0), x[0, :1])
    agg = make_aggregator("fedavg", cfg)
    spec = FusedEpochSpec(height=H, width=H, n_classes=C, samples=N, batch=BS,
                          lr=0.1, grad_clip=1.0, drop1=0.0, drop2=0.0,
                          compute_dtype=jnp.float32)
    return cfg, trainer, gv, agg, spec, x, y, counts


def test_fused_round_matches_engine():
    cfg, trainer, gv, agg, spec, x, y, counts = _setup()
    engine_round = build_round_fn(trainer, cfg, agg)
    fused_round = build_fused_round_fn(spec, agg, shuffle=False, interpret=True)

    key = jax.random.PRNGKey(7)
    gv_e, st_e, m_e = gv, agg.init_state(gv), None
    gv_f, st_f, m_f = gv, agg.init_state(gv), None
    for r in range(3):
        k = jax.random.fold_in(key, r)
        gv_e, st_e, m_e = engine_round(gv_e, st_e, x, y, counts, k)
        gv_f, st_f, m_f = fused_round(gv_f, st_f, x, y, counts, k)

    for le, lf in zip(jax.tree.leaves(gv_e), jax.tree.leaves(gv_f)):
        np.testing.assert_allclose(np.asarray(le), np.asarray(lf),
                                   rtol=2e-5, atol=1e-5)
    assert m_e.keys() == m_f.keys()
    for k2 in m_e:
        np.testing.assert_allclose(float(m_e[k2]), float(m_f[k2]),
                                   rtol=1e-4, atol=1e-4)


def test_fused_multi_round_scan_matches_single_rounds():
    cfg, trainer, gv, agg, spec, x, y, counts = _setup(1)
    fused_round = build_fused_round_fn(spec, agg, shuffle=False, interpret=True)
    multi = build_fused_multi_round_fn(spec, agg, 3, shuffle=False,
                                       interpret=True)
    key = jax.random.PRNGKey(3)
    gv_s, st_s = gv, agg.init_state(gv)
    for r in range(3):
        gv_s, st_s, _ = fused_round(gv_s, st_s, x, y, counts,
                                    jax.random.fold_in(key, r))
    gv_m, _, metrics = multi(gv, agg.init_state(gv), x, y, counts, key)
    for ls, lm in zip(jax.tree.leaves(gv_s), jax.tree.leaves(gv_m)):
        np.testing.assert_allclose(np.asarray(ls), np.asarray(lm),
                                   rtol=1e-6, atol=1e-7)
    assert all(v.shape[0] == 3 for v in metrics.values())


def test_fused_training_decreases_loss_with_dropout_and_shuffle():
    """Dropout + shuffle draw different streams than the engine (documented);
    check the trajectory trains rather than matches bitwise."""
    cfg, trainer, gv, agg, _, x, y, counts = _setup(2)
    spec = FusedEpochSpec(height=H, width=H, n_classes=C, samples=N, batch=BS,
                          lr=0.1, grad_clip=1.0, drop1=0.25, drop2=0.5,
                          compute_dtype=jnp.float32)
    fused_round = build_fused_round_fn(spec, agg, shuffle=True, interpret=True)
    key = jax.random.PRNGKey(11)
    st = agg.init_state(gv)
    losses = []
    gvr = gv
    for r in range(8):
        gvr, st, m = fused_round(gvr, st, x, y, counts,
                                 jax.random.fold_in(key, r))
        losses.append(float(m["loss_sum"]) / float(m["total"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
