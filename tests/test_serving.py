"""graft-serve (ISSUE 12 tentpole): the multi-tenant job scheduler over one
device mesh. graft-slo (ISSUE 19) adds the overload pins at the bottom:
evict/resume bitwise parity vs solo (sync AND buffered), deterministic
SLO preemption, admission-policy rejection semantics, guard-rollback
chaos composition, and warm-start resume through the compile cache.

The pins that matter:
  - a two-tenant scheduler run is byte-identical across reruns (schedule
    AND final params) — dispatch is seeded by submission order + tick
    count, nothing else;
  - each tenant's final params are bitwise-equal to running its job SOLO
    through the classic `FedAvgAPI.train` drive — interleaving tenants
    perturbs no tenant's stream;
  - deficit-weighted fair share bounds per-tenant dispatch skew at the
    weight ratio, deterministically;
  - the shared prefetcher scopes staged buffers by job id — one tenant's
    invalidate can never evict another tenant's staged cohorts (the PR 12
    isolation regression);
  - partial-cohort dispatch degenerates to full dispatch bit-exactly when
    nobody straggles, and stages only freed capacity when clients do;
  - tenant N+1 with the same model config warm-starts from the persistent
    compile cache (cache_hits > 0 in its scheduler ledger), and a tenant
    exceeding its drive's pinned max_compiles ceiling FAILs the budget
    gate.
"""

import jax
import numpy as np
import pytest
from jax.experimental.compilation_cache import compilation_cache as cc

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.prefetch import CohortPrefetcher
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.serving import JobDescriptor, JobQueue, Scheduler
from fedml_tpu.serving.job import params_equal
from fedml_tpu.telemetry.tracer import Tracer
from fedml_tpu.utils.cache import enable_compile_cache


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


@pytest.fixture(scope="module")
def ds16():
    return load_dataset("mnist", client_num_in_total=16,
                        partition_method="homo", seed=1)


def _cfg(ds, **kw):
    kw.setdefault("client_num_per_round", ds.client_num)
    kw.setdefault("comm_round", 3)
    kw.setdefault("seed", 0)
    kw.setdefault("lr", 0.05)
    return FedConfig(dataset="mnist", model="lr", batch_size=8,
                     client_num_in_total=ds.client_num, **kw)


def _desc(name, ds, weight=1.0, chaos=None, partial=False, slo="throughput",
          deadline_s=None, guard=None, **cfg_kw):
    return JobDescriptor(name=name, config=_cfg(ds, **cfg_kw), dataset=ds,
                         weight=weight, chaos=chaos, partial_dispatch=partial,
                         slo=slo, deadline_s=deadline_s, guard=guard)


def _solo(ds, cfg, chaos=None, guard=None):
    api = FedAvgAPI(ds, cfg, ClassificationTrainer(
        create_model("lr", output_dim=ds.class_num)))
    api.train(chaos=chaos, guard=guard)
    return api


def _run_two_tenants(ds, policy="round_robin"):
    tracer = Tracer()
    sched = Scheduler(policy=policy, tracer=tracer)
    sched.submit(_desc("tenant-a", ds, seed=0))
    sched.submit(_desc("tenant-b", ds, seed=1, lr=0.03, buffer_size=5,
                       staleness_alpha=0.5))
    order = []
    while True:
        name = sched.tick()
        if name is None:
            break
        order.append(name)
    sched.close()
    return sched, tracer, order


# ------------------------------------------------ determinism + solo parity

def test_two_tenant_run_byte_identical_across_reruns(ds8):
    s1, t1, order1 = _run_two_tenants(ds8)
    s2, _, order2 = _run_two_tenants(ds8)
    assert order1 == order2
    for name in ("tenant-a", "tenant-b"):
        assert params_equal(s1.queue.get(name).final_params(),
                            s2.queue.get(name).final_params()), name
    # both tenants committed, each with a job_committed ledger event
    committed = {e["job"]: e for e in t1.find_events("job_committed")}
    assert set(committed) == {"tenant-a", "tenant-b"}
    assert all(e["rounds"] == 3 for e in committed.values())
    # every tenant's round spans carry its job label
    jobs = t1.job_summary()
    assert set(jobs) == {"tenant-a", "tenant-b"}
    assert all(phases["round"]["count"] == 3 for phases in jobs.values())


def test_tenant_final_params_bitwise_equal_solo_run(ds8):
    """The acceptance pin: interleaved tenants train the SAME bytes as
    solo runs — for the sync tenant and the buffered tenant both."""
    sched, _, _ = _run_two_tenants(ds8)
    solo_a = _solo(ds8, _cfg(ds8, seed=0))
    solo_b = _solo(ds8, _cfg(ds8, seed=1, lr=0.03, buffer_size=5,
                             staleness_alpha=0.5))
    assert params_equal(sched.queue.get("tenant-a").final_params(),
                        jax.device_get(solo_a.global_variables))
    assert params_equal(sched.queue.get("tenant-b").final_params(),
                        jax.device_get(solo_b.global_variables))
    # histories line up round for round (buffered adds its drain record)
    assert len(sched.queue.get("tenant-a").history) == len(solo_a.history)
    assert len(sched.queue.get("tenant-b").history) == len(solo_b.history)


# ------------------------------------------------------- fair-share policy

@pytest.mark.slow  # ~10s many-tenant drive; the fair-share policy's
# correctness is pinned by the cheaper scheduler tests in this module
def test_fair_share_bounds_dispatch_skew(ds8):
    """Weight 2:1 -> the heavy tenant gets 2 of every 3 ticks while both
    are active, off by at most one in any prefix (deficit round-robin's
    bounded-lag property), and the schedule reproduces exactly."""
    def run():
        sched = Scheduler(policy="fair_share", tracer=Tracer())
        sched.submit(_desc("heavy", ds8, weight=2.0, seed=0, comm_round=8))
        sched.submit(_desc("light", ds8, weight=1.0, seed=1, comm_round=4))
        order = []
        while True:
            name = sched.tick()
            if name is None:
                break
            order.append(name)
        sched.close()
        return order

    order = run()
    assert order == run()  # bit-reproducible schedule
    # while both tenants are active (light's 4 rounds = first 12 ticks at
    # a 2:1 split), every prefix stays within one dispatch of the ratio
    both_active = order[:order.index("light") + order.count("light")]
    for i in range(1, 12 + 1):
        heavy = order[:i].count("heavy")
        assert abs(heavy - 2 * i / 3) <= 1.0, (i, order)
    assert order.count("heavy") == 8 and order.count("light") == 4
    del both_active


def test_scheduler_validation(ds8):
    with pytest.raises(ValueError, match="policy"):
        Scheduler(policy="lottery")
    q = JobQueue()
    q.submit(_desc("dup", ds8).build())
    with pytest.raises(ValueError, match="duplicate"):
        q.submit(_desc("dup", ds8).build())


# ------------------------------------------- prefetcher per-job isolation

def test_prefetcher_scopes_staged_buffers_by_job():
    """The PR 12 isolation regression: invalidate(job=A) must drop only
    A's in-flight stagings; B's staged cohorts stay warm. The legacy
    argless invalidate() still drops everything (single-job drives)."""
    staged_calls = []

    def stage(round_idx, job):
        staged_calls.append((job, round_idx))
        return (job, round_idx)

    with CohortPrefetcher(stage, depth=4) as pf:
        assert pf.prefetch(0, job="A") and pf.prefetch(1, job="A")
        assert pf.prefetch(0, job="B") and pf.prefetch(1, job="B")
        pf.invalidate(job="A")
        # B's rounds are still staged: consuming them is NOT a miss
        assert pf.get(0, job="B") == ("B", 0)
        assert pf.get(1, job="B") == ("B", 1)
        assert pf.misses == 0
        # A's were dropped: consuming re-stages on demand
        assert pf.get(0, job="A") == ("A", 0)
        assert pf.misses == 1
        # legacy drop-all still works
        pf.prefetch(5, job="A")
        pf.prefetch(5, job="B")
        pf.invalidate()
        assert pf.get(5, job="B") == ("B", 5)
        assert pf.misses == 2


def test_interleaved_pipelined_jobs_stay_isolated(ds8):
    """Two interleaved jobs with prefetch enabled: per-job staging keys
    mean each tenant still consumes ITS round-r cohort, so both stay
    bitwise-equal to their solo runs, and the first tenant's completion
    (which invalidates its job scope) cannot disturb the second."""
    tracer = Tracer()
    sched = Scheduler(policy="round_robin", tracer=tracer, prefetch_depth=4)
    sched.submit(_desc("pipe-a", ds8, seed=0, pipeline_depth=2, comm_round=2))
    sched.submit(_desc("pipe-b", ds8, seed=1, pipeline_depth=2, comm_round=5,
                       lr=0.02))
    sched.run()
    solo_a = _solo(ds8, _cfg(ds8, seed=0, pipeline_depth=2, comm_round=2))
    solo_b = _solo(ds8, _cfg(ds8, seed=1, pipeline_depth=2, comm_round=5,
                             lr=0.02))
    assert params_equal(sched.queue.get("pipe-a").final_params(),
                        jax.device_get(solo_a.global_variables))
    assert params_equal(sched.queue.get("pipe-b").final_params(),
                        jax.device_get(solo_b.global_variables))


# ----------------------------------------------- partial-cohort dispatch

def test_partial_dispatch_degenerates_to_full_without_stragglers(ds16):
    """No stragglers -> every arrival lands the round it was dispatched,
    capacity is always the full cohort, and partial mode is bit-identical
    to classic full-cohort dispatch."""
    def run(partial):
        sched = Scheduler(tracer=Tracer())
        sched.submit(_desc("t", ds16, seed=0, comm_round=4, buffer_size=5,
                           staleness_alpha=0.5, client_num_per_round=8,
                           partial=partial))
        sched.run()
        return sched.queue.get("t")

    assert params_equal(run(False).final_params(), run(True).final_params())


def test_partial_dispatch_stages_only_freed_capacity(ds16):
    """With stragglers holding updates in flight, partial mode stages
    narrower replacement cohorts (width < cohort) instead of re-running
    the full cohort every dispatch round — and still converges finitely."""
    plan = FaultPlan(seed=3, straggler_rate=0.5, straggler_rounds=3)

    def run(partial):
        tracer = Tracer()
        sched = Scheduler(tracer=tracer)
        sched.submit(_desc("t", ds16, seed=0, comm_round=5, buffer_size=5,
                           staleness_alpha=0.5, client_num_per_round=8,
                           chaos=plan, partial=partial))
        sched.run()
        return sched.queue.get("t"), tracer

    job_p, tr_p = run(True)
    job_f, _ = run(False)
    widths = [s["width"] for s in tr_p.find_spans("stage") if "width" in s]
    assert widths and all(w < 8 for w in widths)  # replacement cohorts only
    # partial mode dispatched strictly fewer client-steps overall
    assert (job_p.runner.host.committed_updates
            < job_f.runner.host.committed_updates)
    assert all(bool(np.isfinite(np.asarray(l)).all())
               for l in jax.tree.leaves(job_p.final_params()))


# ------------------------------------------------ compile budget + warm start

@pytest.fixture
def restore_jax_cache_config():
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cc.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", old_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
    cc.reset_cache()


def test_second_tenant_warm_starts_from_compile_cache(
        tmp_path, ds8, restore_jax_cache_config):
    """Tenant N+1 with the same model config must not pay cold compiles:
    its jit wrappers are its own, but XLA serves them from the persistent
    cache — the scheduler's per-tenant ledger shows cache hits for the
    second tenant."""
    assert enable_compile_cache(min_compile_secs=0.0,
                                cache_dir=str(tmp_path / "jcache"))
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(_desc("first", ds8, seed=0, comm_round=2))
    sched.submit(_desc("second", ds8, seed=1, comm_round=2))
    sched.run()
    ledger = sched.compile_ledger
    assert ledger["first"]["requests"] > 0
    assert ledger["second"]["requests"] > 0
    assert ledger["second"]["cache_hits"] > 0  # warm start
    ok, report = sched.check_compile_budgets()
    assert "tenant=first" in report and "tenant=second" in report


def test_compile_budget_gate_trips_on_cache_blower(ds8):
    """A tenant whose compile requests exceed its drive's pinned ceiling
    FAILs the gate; a tenant within budget passes; a drive without a
    pinned ceiling is a SKIP, never a FAIL."""
    sched = Scheduler(tracer=Tracer())
    sched.submit(_desc("polite", ds8, seed=0, comm_round=1))
    sched.submit(_desc("blower", ds8, seed=1, comm_round=1))
    sched.submit(_desc("unpinned", ds8, seed=2, comm_round=1,
                       buffer_size=5))
    # synthetic ledger: the gate reads the ledger, not the trace
    sched.compile_ledger["polite"] = {"requests": 3, "cache_hits": 3,
                                      "cache_misses": 0}
    sched.compile_ledger["blower"] = {"requests": 99, "cache_hits": 0,
                                      "cache_misses": 99}
    sched.compile_ledger["unpinned"] = {"requests": 7, "cache_hits": 0,
                                        "cache_misses": 7}
    budgets = {"eager": {"max_compiles": 4}, "buffered": {}}
    ok, report = sched.check_compile_budgets(budgets)
    sched.close()
    assert not ok
    lines = report.splitlines()
    assert any(ln.startswith("OK tenant=polite") for ln in lines)
    assert any(ln.startswith("FAIL tenant=blower") for ln in lines)
    assert any(ln.startswith("SKIP tenant=unpinned") for ln in lines)
    # within-ceiling world: the same queue passes
    sched.compile_ledger["blower"]["requests"] = 4
    ok2, _ = sched.check_compile_budgets(budgets)
    assert ok2


# ------------------------------------------- graft-slo: evict / resume

def _drain(sched):
    order = []
    while True:
        name = sched.tick()
        if name is None:
            break
        order.append(name)
    return order


def test_evict_resume_sync_tenant_bitwise_parity(ds8):
    """The tentpole pin, sync half: a tenant evicted mid-run and resumed
    trains byte-identical final params (and the same history) as its
    uninterrupted solo run."""
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(_desc("t", ds8, seed=0, comm_round=2))
    sched.tick()  # round 0 done; evict at the step boundary
    job = sched.queue.get("t")
    assert job.evict(tracer, reason="test")
    assert job.state == "evicted" and not job.resident
    assert not job.evict(tracer)  # nothing resident: idempotent no-op
    assert job.resume(tracer) and job.resident
    _drain(sched)
    sched.close()
    solo = _solo(ds8, _cfg(ds8, seed=0, comm_round=2))
    assert params_equal(job.final_params(),
                        jax.device_get(solo.global_variables))
    assert ([r["round"] for r in job.history]
            == [r["round"] for r in solo.history])
    marks = [(e["kind"], e["job"], e["round"]) for e in tracer.find_events()
             if e["kind"] in ("job_evicted", "job_resumed")]
    assert marks == [("job_evicted", "t", 1), ("job_resumed", "t", 1)]


def test_evict_resume_buffered_straggler_tenant_bitwise_parity(
        ds16, tmp_path):
    """The tentpole pin, buffered half: eviction snapshots the device
    buffer + birth tags + pending straggler arrivals (spilled through the
    mmap EvictionStore here), and the resumed tenant is byte-identical to
    its solo buffered run."""
    from fedml_tpu.serving import EvictionStore

    plan = FaultPlan(seed=3, straggler_rate=0.5, straggler_rounds=3)
    store = EvictionStore(str(tmp_path / "spill"))
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(_desc("b", ds16, seed=0, comm_round=4, buffer_size=5,
                       staleness_alpha=0.5, client_num_per_round=8,
                       chaos=plan))
    sched.tick()
    sched.tick()  # straggler updates now in flight across the eviction
    job = sched.queue.get("b")
    assert job.runner.host.arrivals or job.runner.host.pending
    assert job.evict(tracer, store=store)
    assert "b" in store and not job.resident
    assert job.resume(tracer)
    _drain(sched)
    sched.close()
    cfg = _cfg(ds16, seed=0, comm_round=4, buffer_size=5,
               staleness_alpha=0.5, client_num_per_round=8)
    solo = _solo(ds16, cfg, chaos=plan)
    assert params_equal(job.final_params(),
                        jax.device_get(solo.global_variables))
    assert len(job.history) == len(solo.history)


def test_evict_resume_personalized_tenant_bank_parity(ds8, tmp_path):
    """graft-pfl × graft-slo: the adapter bank is HOST state the tenant
    shares across evict/resume — eviction flushes its dirty rows AFTER the
    record flush scattered the pending `_bank` block, so the resumed
    tenant gathers exactly the rows its evicted self trained. Final params
    AND every bank shard byte must match the uninterrupted solo run."""
    from fedml_tpu.models.adapter_bank import open_or_create
    from fedml_tpu.models.lora import maybe_wrap_lora

    import os

    def mk(tag):
        cfg = _cfg(ds8, comm_round=4, client_num_per_round=4,
                   lora_rank=4, personalize=True)
        api = FedAvgAPI(ds8, cfg, maybe_wrap_lora(ClassificationTrainer(
            create_model("lr", output_dim=ds8.class_num)), cfg))
        template = jax.tree.map(lambda l: np.zeros(l.shape, l.dtype),
                                jax.device_get(api.global_variables["params"]))
        root = str(tmp_path / tag)
        return cfg, api, root, open_or_create(root, ds8.client_num, template)

    _, api_solo, solo_root, bank_solo = mk("solo")
    api_solo.train(bank=bank_solo)
    bank_solo.close()

    cfg, _, job_root, bank_job = mk("served")
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(JobDescriptor(name="p", config=cfg, dataset=ds8,
                               bank=bank_job))
    sched.tick()
    sched.tick()  # two rounds in: the bank holds trained rows at eviction
    job = sched.queue.get("p")
    assert job.evict(tracer, reason="test") and not job.resident
    assert job.resume(tracer)
    _drain(sched)
    sched.close()
    bank_job.close()

    assert params_equal(job.final_params(),
                        jax.device_get(api_solo.global_variables))
    for fn in sorted(os.listdir(solo_root)):
        a = open(os.path.join(solo_root, fn), "rb").read()
        b = open(os.path.join(job_root, fn), "rb").read()
        assert a == b, f"bank shard {fn} differs served vs solo"


def test_scheduler_close_evicts_in_flight_jobs(ds8):
    """Satellite 3: close() must not abandon device buffers — an
    interrupted run's resident tenants are evicted (snapshot + free), and
    the parked job can resume and finish afterwards."""
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(_desc("t", ds8, seed=0, comm_round=2))
    sched.tick()
    sched.close()
    job = sched.queue.get("t")
    assert job.state == "evicted" and not job.resident
    evs = tracer.find_events("job_evicted")
    assert len(evs) == 1 and evs[0]["reason"] == "close"
    assert job.resume(tracer)
    while not job.step(tracer):
        pass
    assert job.done
    assert all(bool(np.isfinite(np.asarray(l)).all())
               for l in jax.tree.leaves(job.final_params()))


# ----------------------------------------- graft-slo: SLO-tier preemption

@pytest.mark.slow  # ci_smoke pins the per-commit preemption + parity
# smoke on one mesh slot; the double-run replay rides the nightly
def test_latency_tenant_preempts_and_replays_deterministically(ds8):
    """SLO classes on the scheduler: a latency-bound arrival preempts the
    resident throughput-bound tenant (checkpointed eviction, max_resident
    slot bound), runs to completion first, and the whole overload
    schedule — dispatch order, eviction decisions, event ledger — replays
    bit-identically; both tenants stay byte-equal to solo."""
    def run():
        tracer = Tracer()
        sched = Scheduler(policy="fair_share", tracer=tracer,
                          max_resident=1, seed=7)
        sched.submit(_desc("tp", ds8, seed=0, comm_round=4))
        order = [sched.tick(), sched.tick()]
        sched.submit(_desc("lat", ds8, seed=1, comm_round=2, slo="latency"))
        order += _drain(sched)
        sched.close()
        evs = [(e["kind"], e.get("job"), e.get("round"), e.get("rounds"),
                e.get("reason"))
               for e in tracer.find_events()
               if e["kind"] in ("job_evicted", "job_resumed",
                                "job_committed")]
        return sched, order, evs

    s1, order1, evs1 = run()
    s2, order2, evs2 = run()
    assert order1 == order2 and evs1 == evs2  # bit-identical replay
    # the latency tenant takes the mesh the moment it arrives...
    assert order1 == ["tp", "tp", "lat", "lat", "tp", "tp"]
    kinds = [e[0] for e in evs1]
    assert kinds == ["job_evicted", "job_committed", "job_resumed",
                     "job_committed"]
    assert evs1[0][1] == "tp" and evs1[0][4] == "preempted"
    assert s1.evictions == 1
    # ...and nobody's bytes moved: both tenants equal their solo runs
    solo_tp = _solo(ds8, _cfg(ds8, seed=0, comm_round=4))
    solo_lat = _solo(ds8, _cfg(ds8, seed=1, comm_round=2))
    assert params_equal(s1.queue.get("tp").final_params(),
                        jax.device_get(solo_tp.global_variables))
    assert params_equal(s1.queue.get("lat").final_params(),
                        jax.device_get(solo_lat.global_variables))


# ------------------------------------- graft-slo: admission + backpressure

def test_admission_reject_bounces_past_queue_bound(ds8):
    tracer = Tracer()
    sched = Scheduler(tracer=tracer, admission="reject", max_queued=1,
                      max_resident=1)
    assert sched.submit(_desc("a", ds8, seed=0, comm_round=1)) is not None
    assert sched.submit(_desc("b", ds8, seed=1, comm_round=1)) is None
    evs = tracer.find_events("job_rejected")
    assert len(evs) == 1 and evs[0]["job"] == "b"
    assert evs[0]["reason"] == "queue_full"
    assert sched.rejections == 1
    _drain(sched)
    sched.close()
    assert sched.queue.get("a").done
    with pytest.raises(KeyError):
        sched.queue.get("b")  # never entered the queue


def test_admission_shed_sacrifices_queued_throughput_for_latency(ds8):
    tracer = Tracer()
    sched = Scheduler(tracer=tracer, admission="shed", max_queued=1,
                      max_resident=1)
    sched.submit(_desc("tp", ds8, seed=0, comm_round=1))
    # a latency arrival sheds the youngest never-dispatched throughput job
    assert sched.submit(
        _desc("lat", ds8, seed=1, comm_round=1, slo="latency")) is not None
    assert sched.queue.get("tp").state == "cancelled"
    shed = [e for e in tracer.find_events("job_rejected")
            if e["reason"] == "shed"]
    assert len(shed) == 1 and shed[0]["job"] == "tp"
    # no throughput victim left: the next latency arrival bounces
    assert sched.submit(
        _desc("lat2", ds8, seed=2, comm_round=1, slo="latency")) is None
    _drain(sched)
    sched.close()
    assert sched.queue.get("lat").done and sched.queue.all_done()


def test_cancel_removes_queued_job_with_deficit_cleanup(ds8):
    sched = Scheduler(tracer=Tracer(), policy="fair_share", max_resident=1)
    sched.submit(_desc("a", ds8, seed=0, comm_round=2))
    sched.submit(_desc("c", ds8, seed=1, comm_round=2))
    assert sched.cancel("c")
    assert not sched.cancel("c")  # already terminal
    assert sched.queue.get("c").state == "cancelled"
    order = _drain(sched)
    sched.close()
    assert order == ["a", "a"]  # the cancelled job never runs
    assert sched.queue.all_done()


def test_slo_validation():
    with pytest.raises(ValueError, match="admission"):
        Scheduler(admission="coinflip")
    with pytest.raises(ValueError, match="max_resident"):
        Scheduler(max_resident=0)
    with pytest.raises(ValueError, match="slo"):
        JobDescriptor(name="x", config=FedConfig(dataset="d", model="lr"),
                      dataset=None, slo="gold")


# ------------------------------- graft-slo: deadline ledger + chaos + warm

def test_deadline_miss_ledger_and_slo_gate(ds8):
    """Deadline misses are measured telemetry (injected deterministic
    clock), counted per tenant in the ledger, and gated by check_slo the
    way compile budgets are."""
    ticks = iter(range(10 ** 9))
    tracer = Tracer(clock=lambda: float(next(ticks)))
    sched = Scheduler(tracer=tracer, policy="fair_share")
    sched.submit(_desc("d", ds8, seed=0, comm_round=1, slo="latency",
                       deadline_s=0.5))
    sched.submit(_desc("free", ds8, seed=1, comm_round=1))
    sched.run()
    assert sched.slo_ledger["d"]["misses"] == 1
    evs = tracer.find_events("deadline_miss")
    assert len(evs) == 1 and evs[0]["job"] == "d"
    assert evs[0]["latency_s"] > evs[0]["deadline_s"]
    ok, report = sched.check_slo(0)
    assert not ok
    lines = report.splitlines()
    assert any(ln.startswith("FAIL tenant=d") for ln in lines)
    assert any(ln.startswith("SKIP tenant=free") for ln in lines)
    ok2, _ = sched.check_slo(5)
    assert ok2
    # queue_depth / evicted-gauge telemetry rode the same run
    assert tracer.gauge_summary()["queue_depth"]["count"] > 0


class _TripGuard:
    """Rejects its first inspection (forcing one rollback+retry), then
    behaves like an always-accepting guard with a loss window — the same
    decision sequence whether driven solo or served."""

    max_retries = 2

    def __init__(self):
        from collections import deque

        self._losses = deque(maxlen=8)
        self._tripped = False

    def inspect(self, round_idx, loss, global_variables=None):
        from fedml_tpu.robustness.guard import GuardVerdict

        if not self._tripped:
            self._tripped = True
            return GuardVerdict(False, "forced trip")
        self._losses.append(float(loss))
        return GuardVerdict(True, "")

    def reset(self):
        self._losses.clear()


def test_eviction_composes_with_guard_rollback_chaos(ds16):
    """Chaos composition: a buffered straggler tenant whose guard forced a
    rollback is evicted right after the rollback round and resumed — the
    guard's loss window rides the snapshot, and the final params still
    match the solo chaos+guard run bit-for-bit."""
    plan = FaultPlan(seed=5, straggler_rate=0.4, straggler_rounds=2)
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(_desc("g", ds16, seed=0, comm_round=3, buffer_size=4,
                       staleness_alpha=0.5, client_num_per_round=8,
                       chaos=plan, guard=_TripGuard()))
    sched.tick()
    sched.tick()
    assert tracer.find_events("guard_rollback")  # the trip fired
    job = sched.queue.get("g")
    assert job.evict(tracer)
    assert job.resume(tracer)
    _drain(sched)
    sched.close()
    cfg = _cfg(ds16, seed=0, comm_round=3, buffer_size=4,
               staleness_alpha=0.5, client_num_per_round=8)
    solo = _solo(ds16, cfg, chaos=plan, guard=_TripGuard())
    assert params_equal(job.final_params(),
                        jax.device_get(solo.global_variables))


def test_warm_start_resume_hits_compile_cache(
        tmp_path, ds8, restore_jax_cache_config):
    """Warm-start pools: a resumed tenant's rebuild re-traces but never
    recompiles — the persistent cache serves every program (cache_hits
    grows, cache_misses does not), and a same-signature submission is
    flagged as a warm start."""
    from fedml_tpu import telemetry

    assert enable_compile_cache(min_compile_secs=0.0,
                                cache_dir=str(tmp_path / "jcache"))
    tracer = Tracer()
    sched = Scheduler(tracer=tracer, max_resident=1)
    sched.submit(_desc("t", ds8, seed=0, comm_round=2))
    telemetry.install(tracer)
    try:
        sched.tick()  # cold build: misses land here
        job = sched.queue.get("t")
        pre = dict(sched.compile_ledger["t"])
        sched._evict(job)
        assert job.state == "evicted"
        _drain(sched)  # resume + remaining rounds
    finally:
        telemetry.uninstall(tracer)
    sched.close()
    post = sched.compile_ledger["t"]
    assert job.done
    assert post["cache_hits"] > pre["cache_hits"]  # rebuild served warm
    assert post["cache_misses"] == pre["cache_misses"]  # no new compiles
    assert job.warm_start is False  # first of its signature
    j2 = sched.submit(_desc("t2", ds8, seed=1, comm_round=1))
    assert j2 is not None and j2.warm_start  # same program shape: pooled
    sched.cancel("t2")


def test_serving_budget_entry_matches_enumeration():
    """COMPILE_BUDGET.json's serving entry pins the union of the eager and
    buffered program sets — regenerate with the analysis CLI if this
    drifts."""
    from fedml_tpu.analysis.targets import enumerate_drive_programs
    from fedml_tpu.serving.scheduler import load_compile_budgets

    budgets = load_compile_budgets()
    entry = budgets["serving"]
    programs = enumerate_drive_programs("serving")
    assert entry["programs"] == programs
    assert entry["static_total"] == sum(programs.values())
