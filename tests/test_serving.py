"""graft-serve (ISSUE 12 tentpole): the multi-tenant job scheduler over one
device mesh.

The pins that matter:
  - a two-tenant scheduler run is byte-identical across reruns (schedule
    AND final params) — dispatch is seeded by submission order + tick
    count, nothing else;
  - each tenant's final params are bitwise-equal to running its job SOLO
    through the classic `FedAvgAPI.train` drive — interleaving tenants
    perturbs no tenant's stream;
  - deficit-weighted fair share bounds per-tenant dispatch skew at the
    weight ratio, deterministically;
  - the shared prefetcher scopes staged buffers by job id — one tenant's
    invalidate can never evict another tenant's staged cohorts (the PR 12
    isolation regression);
  - partial-cohort dispatch degenerates to full dispatch bit-exactly when
    nobody straggles, and stages only freed capacity when clients do;
  - tenant N+1 with the same model config warm-starts from the persistent
    compile cache (cache_hits > 0 in its scheduler ledger), and a tenant
    exceeding its drive's pinned max_compiles ceiling FAILs the budget
    gate.
"""

import jax
import numpy as np
import pytest
from jax.experimental.compilation_cache import compilation_cache as cc

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.prefetch import CohortPrefetcher
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.serving import JobDescriptor, JobQueue, Scheduler
from fedml_tpu.serving.job import params_equal
from fedml_tpu.telemetry.tracer import Tracer
from fedml_tpu.utils.cache import enable_compile_cache


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


@pytest.fixture(scope="module")
def ds16():
    return load_dataset("mnist", client_num_in_total=16,
                        partition_method="homo", seed=1)


def _cfg(ds, **kw):
    kw.setdefault("client_num_per_round", ds.client_num)
    kw.setdefault("comm_round", 3)
    kw.setdefault("seed", 0)
    kw.setdefault("lr", 0.05)
    return FedConfig(dataset="mnist", model="lr", batch_size=8,
                     client_num_in_total=ds.client_num, **kw)


def _desc(name, ds, weight=1.0, chaos=None, partial=False, **cfg_kw):
    return JobDescriptor(name=name, config=_cfg(ds, **cfg_kw), dataset=ds,
                         weight=weight, chaos=chaos, partial_dispatch=partial)


def _solo(ds, cfg):
    api = FedAvgAPI(ds, cfg, ClassificationTrainer(
        create_model("lr", output_dim=ds.class_num)))
    api.train()
    return api


def _run_two_tenants(ds, policy="round_robin"):
    tracer = Tracer()
    sched = Scheduler(policy=policy, tracer=tracer)
    sched.submit(_desc("tenant-a", ds, seed=0))
    sched.submit(_desc("tenant-b", ds, seed=1, lr=0.03, buffer_size=5,
                       staleness_alpha=0.5))
    order = []
    while True:
        name = sched.tick()
        if name is None:
            break
        order.append(name)
    sched.close()
    return sched, tracer, order


# ------------------------------------------------ determinism + solo parity

def test_two_tenant_run_byte_identical_across_reruns(ds8):
    s1, t1, order1 = _run_two_tenants(ds8)
    s2, _, order2 = _run_two_tenants(ds8)
    assert order1 == order2
    for name in ("tenant-a", "tenant-b"):
        assert params_equal(s1.queue.get(name).final_params(),
                            s2.queue.get(name).final_params()), name
    # both tenants committed, each with a job_committed ledger event
    committed = {e["job"]: e for e in t1.find_events("job_committed")}
    assert set(committed) == {"tenant-a", "tenant-b"}
    assert all(e["rounds"] == 3 for e in committed.values())
    # every tenant's round spans carry its job label
    jobs = t1.job_summary()
    assert set(jobs) == {"tenant-a", "tenant-b"}
    assert all(phases["round"]["count"] == 3 for phases in jobs.values())


def test_tenant_final_params_bitwise_equal_solo_run(ds8):
    """The acceptance pin: interleaved tenants train the SAME bytes as
    solo runs — for the sync tenant and the buffered tenant both."""
    sched, _, _ = _run_two_tenants(ds8)
    solo_a = _solo(ds8, _cfg(ds8, seed=0))
    solo_b = _solo(ds8, _cfg(ds8, seed=1, lr=0.03, buffer_size=5,
                             staleness_alpha=0.5))
    assert params_equal(sched.queue.get("tenant-a").final_params(),
                        jax.device_get(solo_a.global_variables))
    assert params_equal(sched.queue.get("tenant-b").final_params(),
                        jax.device_get(solo_b.global_variables))
    # histories line up round for round (buffered adds its drain record)
    assert len(sched.queue.get("tenant-a").history) == len(solo_a.history)
    assert len(sched.queue.get("tenant-b").history) == len(solo_b.history)


# ------------------------------------------------------- fair-share policy

@pytest.mark.slow  # ~10s many-tenant drive; the fair-share policy's
# correctness is pinned by the cheaper scheduler tests in this module
def test_fair_share_bounds_dispatch_skew(ds8):
    """Weight 2:1 -> the heavy tenant gets 2 of every 3 ticks while both
    are active, off by at most one in any prefix (deficit round-robin's
    bounded-lag property), and the schedule reproduces exactly."""
    def run():
        sched = Scheduler(policy="fair_share", tracer=Tracer())
        sched.submit(_desc("heavy", ds8, weight=2.0, seed=0, comm_round=8))
        sched.submit(_desc("light", ds8, weight=1.0, seed=1, comm_round=4))
        order = []
        while True:
            name = sched.tick()
            if name is None:
                break
            order.append(name)
        sched.close()
        return order

    order = run()
    assert order == run()  # bit-reproducible schedule
    # while both tenants are active (light's 4 rounds = first 12 ticks at
    # a 2:1 split), every prefix stays within one dispatch of the ratio
    both_active = order[:order.index("light") + order.count("light")]
    for i in range(1, 12 + 1):
        heavy = order[:i].count("heavy")
        assert abs(heavy - 2 * i / 3) <= 1.0, (i, order)
    assert order.count("heavy") == 8 and order.count("light") == 4
    del both_active


def test_scheduler_validation(ds8):
    with pytest.raises(ValueError, match="policy"):
        Scheduler(policy="lottery")
    q = JobQueue()
    q.submit(_desc("dup", ds8).build())
    with pytest.raises(ValueError, match="duplicate"):
        q.submit(_desc("dup", ds8).build())


# ------------------------------------------- prefetcher per-job isolation

def test_prefetcher_scopes_staged_buffers_by_job():
    """The PR 12 isolation regression: invalidate(job=A) must drop only
    A's in-flight stagings; B's staged cohorts stay warm. The legacy
    argless invalidate() still drops everything (single-job drives)."""
    staged_calls = []

    def stage(round_idx, job):
        staged_calls.append((job, round_idx))
        return (job, round_idx)

    with CohortPrefetcher(stage, depth=4) as pf:
        assert pf.prefetch(0, job="A") and pf.prefetch(1, job="A")
        assert pf.prefetch(0, job="B") and pf.prefetch(1, job="B")
        pf.invalidate(job="A")
        # B's rounds are still staged: consuming them is NOT a miss
        assert pf.get(0, job="B") == ("B", 0)
        assert pf.get(1, job="B") == ("B", 1)
        assert pf.misses == 0
        # A's were dropped: consuming re-stages on demand
        assert pf.get(0, job="A") == ("A", 0)
        assert pf.misses == 1
        # legacy drop-all still works
        pf.prefetch(5, job="A")
        pf.prefetch(5, job="B")
        pf.invalidate()
        assert pf.get(5, job="B") == ("B", 5)
        assert pf.misses == 2


def test_interleaved_pipelined_jobs_stay_isolated(ds8):
    """Two interleaved jobs with prefetch enabled: per-job staging keys
    mean each tenant still consumes ITS round-r cohort, so both stay
    bitwise-equal to their solo runs, and the first tenant's completion
    (which invalidates its job scope) cannot disturb the second."""
    tracer = Tracer()
    sched = Scheduler(policy="round_robin", tracer=tracer, prefetch_depth=4)
    sched.submit(_desc("pipe-a", ds8, seed=0, pipeline_depth=2, comm_round=2))
    sched.submit(_desc("pipe-b", ds8, seed=1, pipeline_depth=2, comm_round=5,
                       lr=0.02))
    sched.run()
    solo_a = _solo(ds8, _cfg(ds8, seed=0, pipeline_depth=2, comm_round=2))
    solo_b = _solo(ds8, _cfg(ds8, seed=1, pipeline_depth=2, comm_round=5,
                             lr=0.02))
    assert params_equal(sched.queue.get("pipe-a").final_params(),
                        jax.device_get(solo_a.global_variables))
    assert params_equal(sched.queue.get("pipe-b").final_params(),
                        jax.device_get(solo_b.global_variables))


# ----------------------------------------------- partial-cohort dispatch

def test_partial_dispatch_degenerates_to_full_without_stragglers(ds16):
    """No stragglers -> every arrival lands the round it was dispatched,
    capacity is always the full cohort, and partial mode is bit-identical
    to classic full-cohort dispatch."""
    def run(partial):
        sched = Scheduler(tracer=Tracer())
        sched.submit(_desc("t", ds16, seed=0, comm_round=4, buffer_size=5,
                           staleness_alpha=0.5, client_num_per_round=8,
                           partial=partial))
        sched.run()
        return sched.queue.get("t")

    assert params_equal(run(False).final_params(), run(True).final_params())


def test_partial_dispatch_stages_only_freed_capacity(ds16):
    """With stragglers holding updates in flight, partial mode stages
    narrower replacement cohorts (width < cohort) instead of re-running
    the full cohort every dispatch round — and still converges finitely."""
    plan = FaultPlan(seed=3, straggler_rate=0.5, straggler_rounds=3)

    def run(partial):
        tracer = Tracer()
        sched = Scheduler(tracer=tracer)
        sched.submit(_desc("t", ds16, seed=0, comm_round=5, buffer_size=5,
                           staleness_alpha=0.5, client_num_per_round=8,
                           chaos=plan, partial=partial))
        sched.run()
        return sched.queue.get("t"), tracer

    job_p, tr_p = run(True)
    job_f, _ = run(False)
    widths = [s["width"] for s in tr_p.find_spans("stage") if "width" in s]
    assert widths and all(w < 8 for w in widths)  # replacement cohorts only
    # partial mode dispatched strictly fewer client-steps overall
    assert (job_p.runner.host.committed_updates
            < job_f.runner.host.committed_updates)
    assert all(bool(np.isfinite(np.asarray(l)).all())
               for l in jax.tree.leaves(job_p.final_params()))


# ------------------------------------------------ compile budget + warm start

@pytest.fixture
def restore_jax_cache_config():
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cc.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", old_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
    cc.reset_cache()


def test_second_tenant_warm_starts_from_compile_cache(
        tmp_path, ds8, restore_jax_cache_config):
    """Tenant N+1 with the same model config must not pay cold compiles:
    its jit wrappers are its own, but XLA serves them from the persistent
    cache — the scheduler's per-tenant ledger shows cache hits for the
    second tenant."""
    assert enable_compile_cache(min_compile_secs=0.0,
                                cache_dir=str(tmp_path / "jcache"))
    tracer = Tracer()
    sched = Scheduler(tracer=tracer)
    sched.submit(_desc("first", ds8, seed=0, comm_round=2))
    sched.submit(_desc("second", ds8, seed=1, comm_round=2))
    sched.run()
    ledger = sched.compile_ledger
    assert ledger["first"]["requests"] > 0
    assert ledger["second"]["requests"] > 0
    assert ledger["second"]["cache_hits"] > 0  # warm start
    ok, report = sched.check_compile_budgets()
    assert "tenant=first" in report and "tenant=second" in report


def test_compile_budget_gate_trips_on_cache_blower(ds8):
    """A tenant whose compile requests exceed its drive's pinned ceiling
    FAILs the gate; a tenant within budget passes; a drive without a
    pinned ceiling is a SKIP, never a FAIL."""
    sched = Scheduler(tracer=Tracer())
    sched.submit(_desc("polite", ds8, seed=0, comm_round=1))
    sched.submit(_desc("blower", ds8, seed=1, comm_round=1))
    sched.submit(_desc("unpinned", ds8, seed=2, comm_round=1,
                       buffer_size=5))
    # synthetic ledger: the gate reads the ledger, not the trace
    sched.compile_ledger["polite"] = {"requests": 3, "cache_hits": 3,
                                      "cache_misses": 0}
    sched.compile_ledger["blower"] = {"requests": 99, "cache_hits": 0,
                                      "cache_misses": 99}
    sched.compile_ledger["unpinned"] = {"requests": 7, "cache_hits": 0,
                                        "cache_misses": 7}
    budgets = {"eager": {"max_compiles": 4}, "buffered": {}}
    ok, report = sched.check_compile_budgets(budgets)
    sched.close()
    assert not ok
    lines = report.splitlines()
    assert any(ln.startswith("OK tenant=polite") for ln in lines)
    assert any(ln.startswith("FAIL tenant=blower") for ln in lines)
    assert any(ln.startswith("SKIP tenant=unpinned") for ln in lines)
    # within-ceiling world: the same queue passes
    sched.compile_ledger["blower"]["requests"] = 4
    ok2, _ = sched.check_compile_budgets(budgets)
    assert ok2


def test_serving_budget_entry_matches_enumeration():
    """COMPILE_BUDGET.json's serving entry pins the union of the eager and
    buffered program sets — regenerate with the analysis CLI if this
    drifts."""
    from fedml_tpu.analysis.targets import enumerate_drive_programs
    from fedml_tpu.serving.scheduler import load_compile_budgets

    budgets = load_compile_budgets()
    entry = budgets["serving"]
    programs = enumerate_drive_programs("serving")
    assert entry["programs"] == programs
    assert entry["static_total"] == sum(programs.values())
