"""Subprocess probe for the ISSUE-9 degenerate bit-identity acceptance pin.

Run OUTSIDE the fast suite's --xla_backend_optimization_level=0 hack: at
opt-0, XLA CPU duplicates the optax momentum subexpression into the params
output and contracts the two copies differently between the fused synchronous
round program and the standalone commit program — a 1-ULP params drift with
bitwise-equal momenta. Default codegen contracts both the same way, and the
degenerate buffered config (buffer_size = cohort, staleness_alpha = 0, no
stragglers) is then bit-identical to the synchronous loop for fedavg AND
fedopt-with-momentum, eager and depth-2 pipelined.

tests/test_buffered.py::test_degenerate_fedopt_bitwise_at_default_codegen
runs this file in a subprocess with the opt-0 flag stripped and asserts the
BITWISE OK line. Exit code 0 = all comparisons bitwise-equal.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def _run(ds, aggregator_name, **kw):
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import ClassificationTrainer
    from fedml_tpu.models.registry import create_model

    cfg = FedConfig(dataset="mnist", model="lr", batch_size=8, lr=0.05,
                    client_num_in_total=8, client_num_per_round=8, seed=0,
                    comm_round=3, server_optimizer="sgd", server_lr=1.0,
                    server_momentum=0.9, **kw)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds.class_num))
    api = FedAvgAPI(ds, cfg, trainer, aggregator_name=aggregator_name)
    api.train()
    return api


def _bitwise(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def main() -> int:
    from fedml_tpu.data.registry import load_dataset

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(repo, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    ds = load_dataset("mnist", client_num_in_total=8,
                      partition_method="homo", seed=0)
    for agg in ("fedavg", "fedopt"):
        sync = _run(ds, agg)
        for depth in (0, 2):
            buf = _run(ds, agg, buffer_size=8, staleness_alpha=0.0,
                       pipeline_depth=depth)
            if not _bitwise(sync.global_variables, buf.global_variables):
                print(f"FAIL params {agg} depth={depth}")
                return 1
            if not _bitwise(sync.agg_state, buf.agg_state):
                print(f"FAIL agg_state {agg} depth={depth}")
                return 1
    print("BITWISE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
