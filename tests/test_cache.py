"""Persistent compile cache wiring (ISSUE 5 satellite): enable_compile_cache
points jax at a cache dir by default, a second lowering of the same program
hits the on-disk cache instead of recompiling, and the env opt-out works.
"""

import os

import jax
import jax.numpy as jnp
import pytest
from jax.experimental.compilation_cache import compilation_cache as cc

from fedml_tpu.utils.cache import enable_compile_cache


@pytest.fixture
def restore_jax_cache_config():
    """The suite-wide conftest points jax at the repo .jax_cache — put it
    back however this test leaves it. The persistent cache object is
    process-wide and latches the dir it was first used with, so a config
    change only takes effect after reset_cache()."""
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cc.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", old_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
    cc.reset_cache()


def _cache_files(d):
    return {f for f in os.listdir(d) if not f.startswith(".")}


def test_second_lowering_hits_cache_dir(tmp_path, restore_jax_cache_config):
    d = str(tmp_path / "jcache")
    assert enable_compile_cache(min_compile_secs=0.0, cache_dir=d)
    assert jax.config.jax_compilation_cache_dir == d

    @jax.jit
    def f(x):
        return jnp.tanh(x) @ x.T

    x = jnp.ones((16, 16))
    f(x).block_until_ready()
    first = _cache_files(d)
    assert first, "compile produced no persistent cache entries"

    jax.clear_caches()              # force a re-lowering of the same program
    f(x).block_until_ready()
    assert _cache_files(d) == first  # served from disk: no new entries


def test_env_opt_out(tmp_path, restore_jax_cache_config, monkeypatch):
    monkeypatch.setenv("FEDML_TPU_NO_COMPILE_CACHE", "1")
    before = jax.config.jax_compilation_cache_dir
    assert not enable_compile_cache(cache_dir=str(tmp_path / "nope"))
    assert jax.config.jax_compilation_cache_dir == before


def test_env_dir_override(tmp_path, restore_jax_cache_config, monkeypatch):
    d = str(tmp_path / "envdir")
    monkeypatch.setenv("FEDML_TPU_COMPILE_CACHE_DIR", d)
    assert enable_compile_cache(min_compile_secs=0.0)
    assert jax.config.jax_compilation_cache_dir == d


def test_default_is_repo_local(restore_jax_cache_config, monkeypatch):
    monkeypatch.delenv("FEDML_TPU_COMPILE_CACHE_DIR", raising=False)
    monkeypatch.delenv("FEDML_TPU_NO_COMPILE_CACHE", raising=False)
    assert enable_compile_cache()
    assert jax.config.jax_compilation_cache_dir.endswith(".jax_cache")
