"""TurboAggregate field-op oracles — BIT-EXACT vs the living reference.

Drives reference fedml_api/distributed/turboaggregate/mpc_function.py
(modular_inv:4, gen_Lagrange_coeffs:38, BGW_encoding:61, gen_BGW_lambda_s:78,
BGW_decoding:91, LCC_encoding:112, LCC_encoding_w_Random:138) against
fedml_tpu.algorithms.turboaggregate's vectorized limb-matmul rebuild. Integer
field arithmetic admits EQUALITY assertions, not closeness:

  - modular_inv: reference iterative extended-Euclid vs our Fermat
    square-and-multiply — same residue for every unit mod a prime.
  - gen_Lagrange_coeffs: per-element loops vs vectorized — equal matrices.
  - BGW/LCC encodings: np.random.seed(s) drives the reference's global
    np.random while RandomState(s) drives ours — the SAME MT19937 stream, so
    even the random masking polynomials match share-for-share.

Reference context: these functions are dead code in the reference (nothing
outside mpc_function.py calls them — verified by grep); the rebuild wires
the same math into a working SecureAggregator. One genuine reference defect
is pinned: LCC_decoding's beta grid uses n_beta=K (mpc_function.py:197)
while LCC_encoding placed the data chunks on the first K of K+T points
starting at -floor((K+T)/2) — the grids only coincide when
floor((K+T)/2) == floor(K/2), so reference encode->decode round-trips
corrupt data for e.g. (K=2, T=2) while ours is self-consistent for all.

Slow-marked (imports torch-era reference modules).
"""

from __future__ import annotations

import numpy as np
import pytest

pytestmark = pytest.mark.slow

pytest.importorskip("torch")

from _reference_oracle import setup_reference  # noqa: E402

setup_reference()
# the living-reference checkout is not shipped in every container;
# without it the oracle has nothing to run — skip at collect time
# instead of erroring the whole module
pytest.importorskip(
    "fedml_api",
    reason="reference FedML checkout (/root/reference) unavailable")

from fedml_api.distributed.turboaggregate import mpc_function as ref  # noqa: E402

from fedml_tpu.algorithms import turboaggregate as ta  # noqa: E402

P_BIG = ta.DEFAULT_PRIME  # 2^31 - 1
P_SMALL = 97


@pytest.mark.parametrize("p", [P_SMALL, P_BIG])
def test_modular_inv_exact(p):
    rng = np.random.RandomState(0)
    vals = np.concatenate([[1, 2, p - 1], rng.randint(1, p, 50)])
    for a in vals:
        got = int(ta.modular_inv(np.int64(a), p))
        want = int(ref.modular_inv(int(a), p))
        assert got == want, (a, got, want)
        assert (got * int(a)) % p == 1


@pytest.mark.parametrize("p", [P_SMALL, P_BIG])
def test_gen_lagrange_coeffs_exact(p):
    rng = np.random.RandomState(1)
    for na, nb in [(1, 3), (4, 4), (5, 8)]:
        # distinct beta points (reference skips o == cur_beta by VALUE);
        # rejection-sample — choice(replace=False) would materialize a
        # p-element permutation for the 2^31-1 field
        beta = rng.randint(0, p, nb).astype(np.int64)
        while len(np.unique(beta)) < nb:
            beta = rng.randint(0, p, nb).astype(np.int64)
        alpha = rng.randint(0, p, na).astype(np.int64)
        want = ref.gen_Lagrange_coeffs(alpha, beta, p)
        got = ta.gen_lagrange_coeffs(alpha, beta, p)
        np.testing.assert_array_equal(got, np.asarray(want, np.int64))
    # is_K1 path: only the first alpha row
    want = ref.gen_Lagrange_coeffs(alpha, beta, p, is_K1=1)
    np.testing.assert_array_equal(
        ta.gen_lagrange_coeffs(alpha[:1], beta, p), np.asarray(want, np.int64))


@pytest.mark.parametrize("p", [P_SMALL, P_BIG])
def test_bgw_encoding_exact(p):
    N, T, m, d, seed = 7, 2, 4, 6, 3
    rng = np.random.RandomState(seed + 1)
    X = rng.randint(0, p, (m, d)).astype(np.int64)

    np.random.seed(seed)  # reference draws masks from global np.random
    want = ref.BGW_encoding(X, N, T, p)
    got = ta.bgw_encoding(X, N, T, p, rng=np.random.RandomState(seed))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("p", [P_SMALL, P_BIG])
def test_bgw_decoding_exact_and_roundtrip(p):
    N, T, m, d, seed = 7, 2, 4, 6, 4
    rng = np.random.RandomState(seed)
    X = rng.randint(0, p, (m, d)).astype(np.int64)
    shares = ta.bgw_encoding(X, N, T, p, rng=rng)

    # any T+1 shares reconstruct; pick a non-contiguous subset
    worker_idx = [0, 3, 6]
    f_eval = shares[worker_idx].reshape(len(worker_idx), -1)
    want = ref.BGW_decoding(f_eval, worker_idx, p)
    got = ta.bgw_decoding(f_eval, worker_idx, p)
    np.testing.assert_array_equal(got.reshape(1, -1), want)
    np.testing.assert_array_equal(got.reshape(m, d) % p, X % p)


@pytest.mark.parametrize("p", [P_SMALL, P_BIG])
def test_lcc_encoding_exact(p):
    N, K, T, m, d, seed = 8, 2, 2, 6, 5, 5
    rng = np.random.RandomState(seed + 1)
    X = rng.randint(0, p, (m, d)).astype(np.int64)

    np.random.seed(seed)
    want = ref.LCC_encoding(X, N, K, T, p)
    got = ta.lcc_encoding(X, N, K, T, p, rng=np.random.RandomState(seed))
    np.testing.assert_array_equal(got, want)

    # the explicit-randomness variant must agree with the seeded one:
    # recreate the mask stream LCC_encoding drew (K..K+T, encoding order)
    np.random.seed(seed)
    R_stream = np.stack([np.random.randint(p, size=(m // K, d)) for _ in range(T)])
    want2 = ref.LCC_encoding_w_Random(X, R_stream, N, K, T, p)
    np.testing.assert_array_equal(want2, want)


def test_lcc_decoding_roundtrip_ours_vs_reference_defect():
    """Our decoder round-trips the encoder for every (K, T); the reference's
    decode beta grid (n_beta=K, mpc_function.py:197) only matches its own
    encoder's data placement when floor((K+T)/2) == floor(K/2)."""
    p = P_BIG
    m, d, N = 8, 3, 9
    rng = np.random.RandomState(6)
    for K, T in [(2, 0), (2, 1), (2, 2), (4, 2)]:
        X = rng.randint(0, p, (m, d)).astype(np.int64)
        shares = ta.lcc_encoding(X, N, K, T, p, rng=np.random.RandomState(7))
        # decode from an arbitrary K+T-share subset; eval points are the
        # encoder's alpha grid entries for those workers
        worker_idx = list(range(K + T))
        alpha = np.mod(np.arange(-(N // 2), -(N // 2) + N, dtype=np.int64), p)
        dec = ta.lcc_decoding(shares[worker_idx], alpha[worker_idx], K, T, p)
        np.testing.assert_array_equal(dec.reshape(m, d), X,
                                      err_msg=f"ours failed K={K} T={T}")

        # the reference's own round-trip, same shares
        ref_dec = ref.LCC_decoding(
            shares[worker_idx].reshape(K + T, -1), 1, N, K, T, worker_idx, p)
        consistent = (K + T) // 2 == K // 2
        matches = np.array_equal(ref_dec.reshape(m, d), X)
        assert matches == consistent, (
            f"reference LCC round-trip K={K} T={T}: expected "
            f"{'success' if consistent else 'corruption'}, got match={matches}")


def test_secure_weighted_sum_uses_exact_field_ops():
    """End-to-end: the SecureAggregator's masked sum over quantized pytrees
    equals the plain weighted sum (the field ops above are what make this
    hold bit-for-bit at the int level)."""
    import jax.numpy as jnp

    trees = [{"w": jnp.asarray(np.random.RandomState(i).randn(4, 3), jnp.float32)}
             for i in range(5)]
    weights = np.asarray([1, 2, 3, 2, 1], np.float64)
    agg = ta.SecureAggregator(num_clients=5, threshold=2, seed=0)
    got = agg.secure_weighted_sum(trees, weights)  # weighted AVERAGE
    want = sum(w * np.asarray(t["w"]) for w, t in zip(weights, trees)) / weights.sum()
    # atol bounded by the 8-bit fixed-point weight resolution (see
    # secure_weighted_sum_grouped), same bound as test_split_vfl_secure
    np.testing.assert_allclose(np.asarray(got["w"]), want, atol=2e-2)
