"""graft-matrix: the declarative feature-matrix spec (core/spec.py) and its
analysis engine (analysis/matrix_engine.py).

Covers the spec<->FedConfig.validate round-trip, the illegal-combination
proof (every table entry raises with its exact reason), a cheap abstract
trace of legal points through the real builders, the spec<->budget-file
coverage gate (pass + trip), the axis-drift AST rule on fixtures and on
the repo itself, and the byte-stability of the --update-budgets path.

The full pairwise-cover trace (29 programs, ~12s) runs in ci_smoke.sh's
--matrix step; here only vmap-family points are traced so the module adds
seconds, not minutes, to tier-1."""

import itertools
import json
import os

import pytest

from fedml_tpu.analysis.matrix_engine import (
    check_budget_coverage,
    check_illegal_pairs,
    enumerate_matrix,
    lint_axis_drift,
    lint_axis_drift_source,
    pairwise_cover,
    point_family,
    trace_point,
)
from fedml_tpu.core.spec import (
    ASSEMBLERS,
    AXES,
    AXIS_KWARGS,
    CONSTRAINTS,
    DRIVE_SPECS,
    EXCLUSIONS,
    AssemblerSpec,
    axis_levels,
    drive_program_names,
    first_violation,
    is_legal,
    point_config,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _full(**levels):
    """A complete axis assignment: table defaults overlaid with `levels`."""
    out = {name: axis.default for name, axis in AXES.items()}
    out.update(levels)
    return out


# ---------------------------------------------- spec <-> validate round-trip

def test_every_axis_level_is_reachable_in_some_legal_point():
    legal, total = enumerate_matrix()
    assert total == len(list(itertools.product(
        *(a.levels for a in AXES.values()))))
    assert 0 < len(legal) < total
    seen = {name: set() for name in AXES}
    for point in legal:
        for name, level in point.items():
            seen[name].add(level)
    for name, axis in AXES.items():
        assert seen[name] == set(axis.levels), (
            f"axis {name}: level(s) {set(axis.levels) - seen[name]} appear "
            f"in NO legal point — the exclusion tables made them dead")


def test_legal_points_round_trip_through_fedconfig_validate():
    # spec -> config -> spec: a legal assignment builds a FedConfig,
    # validate() accepts it with the non-config overlay, and axis_levels
    # projects the config back onto the same config-axis levels
    legal, _ = enumerate_matrix()
    overlay_axes = {n for n, a in AXES.items() if a.overrides is None}
    for point in legal[:: max(1, len(legal) // 50)]:  # ~50-point sample
        cfg = point_config(point)
        overlay = {n: point[n] for n in overlay_axes}
        cfg.validate(**overlay)
        projected = axis_levels(cfg)
        for name in AXES:
            if name in overlay_axes:
                continue
            assert projected[name] == point[name], (name, point)


def test_illegal_point_is_rejected_by_fedconfig_validate():
    point = _full(codec="int8", silo="on")
    assert not is_legal(point)
    reason = first_violation(point).reason
    with pytest.raises(ValueError) as e:
        point_config(point).validate(
            **{n: point[n] for n, a in AXES.items() if a.overrides is None})
    assert str(e.value) == reason


# ----------------------------------------------- illegal-combination proof

def test_every_illegal_table_entry_raises_with_its_reason():
    findings, checked = check_illegal_pairs()
    assert not findings, "\n".join(f.message for f in findings)
    # every pairwise exclusion level-pair plus every constraint clause
    # combination must have been probed
    floor = sum(len(e.levels_a) * len(e.levels_b) for e in EXCLUSIONS)
    assert checked >= floor, (checked, floor)
    assert CONSTRAINTS, "spec lost its n-ary constraint table"


def test_shadowed_constraint_raises_the_first_matching_reason():
    # codec x tensor=shard_step x robust violates BOTH the pairwise
    # shard_step exclusion and the ternary robust-codec constraint; table
    # order says the pairwise entry fires — the contract check_illegal_pairs
    # enforces for every combination
    point = _full(codec="int8", tensor="shard_step", aggregator="robust")
    hit = first_violation(point)
    assert hit in EXCLUSIONS, "expected the pairwise exclusion to shadow"
    with pytest.raises(ValueError, match="shard_step"):
        point_config(point).validate(aggregator="robust")


# ---------------------------------------------------- legal-cover tracing

def test_pairwise_cover_hits_every_legal_pair():
    legal, _ = enumerate_matrix()
    cover = pairwise_cover(legal)
    assert 0 < len(cover) < len(legal)

    def pairs(point):
        names = sorted(point)
        return {((a, point[a]), (b, point[b]))
                for a, b in itertools.combinations(names, 2)}

    want = set().union(*(pairs(p) for p in legal))
    have = set().union(*(pairs(p) for p in cover))
    assert want == have, f"{len(want - have)} legal pair(s) uncovered"


def test_trace_smoke_vmap_families():
    # the cheap slice of what ci_smoke's full --matrix run proves: the
    # default point, a codec-wrapped point, and a superstep point all
    # build abstractly through the real assemblers
    trace_point(_full())
    trace_point(_full(codec="topk", chaos="on"))
    trace_point(_full(superstep="on", lora="on"))


def test_trace_point_rejects_illegal_points_at_config_time():
    with pytest.raises(ValueError, match="silo"):
        trace_point(_full(codec="int8", silo="on"))


# ------------------------------------------------- budget coverage gate

def test_budget_coverage_gate_passes_on_the_committed_files():
    findings = check_budget_coverage(ROOT)
    assert not findings, "\n".join(f.message for f in findings)


def test_budget_coverage_trips_on_removed_pin():
    budgets = json.load(open(os.path.join(ROOT, "COMPILE_BUDGET.json")))
    pin = "sharded.round[lr,f32,fedavg,8,topk64]"
    assert pin in budgets["sharded"]["programs"]
    del budgets["sharded"]["programs"][pin]
    findings = check_budget_coverage(ROOT, compile_budgets=budgets,
                                     check_live_comms=False)
    assert any(f.rule == "matrix-coverage" and pin in f.message
               and "not budget-gated" in f.message for f in findings), \
        [f.message for f in findings]


def test_budget_coverage_trips_on_stale_pin_and_count_drift():
    budgets = json.load(open(os.path.join(ROOT, "COMPILE_BUDGET.json")))
    budgets["eager"]["programs"]["engine.round[lr,f32,ghost]"] = 1
    budgets["eager"]["programs"]["engine.eval[lr,f32]"] += 1
    findings = check_budget_coverage(ROOT, compile_budgets=budgets,
                                     check_live_comms=False)
    msgs = [f.message for f in findings]
    assert any("stale budget pin `engine.round[lr,f32,ghost]`" in m
               for m in msgs), msgs
    assert any("engine.eval[lr,f32]" in m and "pins" in m
               for m in msgs), msgs


def test_budget_coverage_trips_on_comms_drift_both_directions():
    comms = {name: {} for name in
             __import__("fedml_tpu.core.spec",
                        fromlist=["COMMS_PROGRAM_NAMES"]).COMMS_PROGRAM_NAMES}
    dropped = sorted(comms)[0]
    del comms[dropped]
    comms["tensor.round[lr,f32,ghost,2x4]"] = {}
    findings = check_budget_coverage(ROOT, comms_budgets=comms,
                                     check_live_comms=False)
    msgs = [f.message for f in findings if f.target == "comms:budget"]
    assert any(dropped in m and "no entry" in m for m in msgs), msgs
    assert any("ghost" in m and "stale pin or undeclared" in m
               for m in msgs), msgs


# ----------------------------------------------------- axis-drift rule

_DRIFT_SPECS = (
    AssemblerSpec("pkg/mod.py", "build_x_round_fn",
                  ("donate_data", "collect_stats")),
)


def test_axis_drift_clean_fixture():
    src = ("def build_x_round_fn(trainer, cfg, *, donate_data=True,\n"
           "                     collect_stats=False):\n"
           "    pass\n")
    assert lint_axis_drift_source(src, "pkg/mod.py",
                                  assemblers=_DRIFT_SPECS) == []


def test_axis_drift_flags_dropped_kwarg():
    src = "def build_x_round_fn(trainer, cfg, *, donate_data=True):\n    pass\n"
    findings = lint_axis_drift_source(src, "pkg/mod.py",
                                      assemblers=_DRIFT_SPECS)
    assert len(findings) == 1 and findings[0].rule == "axis-drift"
    assert "no longer carries feature-axis kwarg `collect_stats`" \
        in findings[0].message


def test_axis_drift_flags_undeclared_kwarg():
    src = ("def build_x_round_fn(trainer, cfg, *, donate_data=True,\n"
           "                     collect_stats=False, codec=None):\n"
           "    pass\n")
    findings = lint_axis_drift_source(src, "pkg/mod.py",
                                      assemblers=_DRIFT_SPECS)
    assert len(findings) == 1
    assert "grew feature-axis kwarg `codec`" in findings[0].message
    assert "codec" in AXIS_KWARGS  # the rule only polices spec'd axis kwargs


def test_axis_drift_ignores_non_axis_kwargs_and_missing_fn():
    src = "def build_x_round_fn(trainer, cfg, *, donate_data=True,\n" \
          "                     collect_stats=False, verbose=False):\n" \
          "    pass\n"
    assert lint_axis_drift_source(src, "pkg/mod.py",
                                  assemblers=_DRIFT_SPECS) == []
    findings = lint_axis_drift_source("x = 1\n", "pkg/mod.py",
                                      assemblers=_DRIFT_SPECS)
    assert len(findings) == 1 and "does not define" in findings[0].message


def test_axis_drift_respects_suppression_with_reason():
    src = ("# graft-lint: disable=axis-drift -- fixture: deliberate drop\n"
           "def build_x_round_fn(trainer, cfg, *, donate_data=True):\n"
           "    pass\n")
    assert lint_axis_drift_source(src, "pkg/mod.py",
                                  assemblers=_DRIFT_SPECS) == []


def test_axis_drift_repo_is_clean():
    # the pin: every ASSEMBLERS signature matches its declaration, so any
    # future kwarg add/drop must come with a table update (or suppression)
    findings = lint_axis_drift(ROOT)
    assert not findings, "\n".join(str(f) for f in findings)


def test_assemblers_table_names_real_modules_and_axis_kwargs():
    for spec in ASSEMBLERS:
        assert os.path.exists(os.path.join(ROOT, spec.module)), spec.module
        assert set(spec.axis_kwargs) <= AXIS_KWARGS, spec


# ------------------------------------------- --update-budgets byte stability

def test_update_budgets_round_trips_byte_stable_from_the_spec():
    # the spec-declared program surface regenerates COMPILE_BUDGET.json
    # byte-for-byte: same entries, same counts, same key order, preserved
    # max_compiles ceilings — proof the committed file IS the spec's view
    from fedml_tpu.analysis.compile_engine import load_budgets, make_budgets

    committed = open(os.path.join(ROOT, "COMPILE_BUDGET.json")).read()
    measured = {d: drive_program_names(d) for d in DRIVE_SPECS}
    regenerated = make_budgets(measured, existing=load_budgets(ROOT))
    assert json.dumps(regenerated, indent=2) + "\n" == committed


def test_spec_families_cover_every_drive_program():
    # every budget-pinned program name parses and maps onto a family the
    # matrix engine knows how to trace
    from fedml_tpu.core.spec import parse_program_name

    eval_prefixes = ("engine.eval", "engine.client_eval",
                     "engine.federation_eval", "engine.chunked")
    for drive in DRIVE_SPECS:
        for name in drive_program_names(drive):
            assert parse_program_name(name), name
            fam = name.rsplit("[", 1)[0]
            assert fam.count(".") == 1 or name.startswith("engine.chunked"), \
                name


def test_point_family_mirrors_fedavg_dispatch_order():
    # fused wins over superstep wins over buffer wins over the parallel
    # backends — the same if/elif ladder FedAvgAPI uses
    assert point_family(_full(fused="on", superstep="on")) == "fused"
    assert point_family(_full(superstep="on", buffer="on")) == "superstep"
    assert point_family(_full(buffer="on", backend="shard_map")) == "buffered"
    assert point_family(_full(backend="shard_map")) == "sharded"
    assert point_family(_full(tensor="shards")) == "tensor_round"
    assert point_family(_full(tensor="shard_step")) == "tensor_step"
    assert point_family(_full(silo="on")) == "silo"
    assert point_family(_full()) == "engine"
