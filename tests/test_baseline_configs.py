"""examples/baseline config matrix (VERDICT r4 missing #2).

Every reference examples/baseline/*.sh has a named YAML twin under
experiments/configs/baseline/. These tests keep the matrix honest: each
twin must exist, parse, and resolve to a loadable dataset + constructible
model; representatives of each new model/dataset family train a round.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

CONFIG_DIR = os.path.join(
    os.path.dirname(__file__), "..", "fedml_tpu", "experiments", "configs", "baseline")

# the reference's script inventory, name-for-name
REFERENCE_BASELINES = [
    "adult_heter", "adult_homo", "chmnist_heter", "chmnist_homo",
    "cifar10_cnn", "cifar10_heter_res20", "cifar10_homo_res20",
    "cifar10_vgg11", "emnist", "femnist", "fmnist", "har_class_heter",
    "har_class_homo", "har_hetero", "har_homo", "mnist", "purchase_heter",
    "purchase_homo", "texas_heter", "texas_homo",
]


def _load(name):
    from fedml_tpu.experiments.fed_launch import _load_yaml

    return _load_yaml(os.path.join(CONFIG_DIR, f"{name}.yaml"))


def test_every_reference_baseline_has_a_twin():
    for name in REFERENCE_BASELINES:
        assert os.path.exists(os.path.join(CONFIG_DIR, f"{name}.yaml")), name


@pytest.mark.parametrize("name", REFERENCE_BASELINES)
def test_baseline_config_resolves(name):
    """Parse + resolve: dataset loads (surrogate), model constructs at the
    dataset's class_num, config round-trips through FedConfig."""
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.registry import create_model

    conf = _load(name)
    assert conf["algorithm"] == "fedavg"
    args = conf["args"]
    cfg = FedConfig.from_dict(args)
    assert cfg.comm_round >= 10
    load_kw = {}
    if args["dataset"] == "mnist":  # flatten by model, as setup_run does
        load_kw["flatten"] = args["model"] in ("lr", "mlp")
    ds = load_dataset(args["dataset"],
                      client_num_in_total=args["client_num_in_total"],
                      partition_method=args["partition_method"],
                      partition_alpha=args.get("partition_alpha", 0.5),
                      **load_kw)
    assert ds.client_num == args["client_num_in_total"]
    model_name = args["model"]
    if model_name == "cnn":  # dataset-contextual, as in the reference
        model_name = {"har": "har_cnn", "har_subject": "har_cnn",
                      "cifar10": "cnn_cifar"}.get(args["dataset"], "cnn")
    module = create_model(model_name, output_dim=ds.class_num)
    v = module.init({"params": jax.random.PRNGKey(0),
                     "dropout": jax.random.PRNGKey(1)},
                    jnp.asarray(ds.train.x[:1, 0]), train=False)
    assert jax.tree.leaves(v)


@pytest.mark.parametrize("name", [
    # har_hetero (~76s) and texas_heter (~53s) are the two heaviest tests
    # in tier-1 — nightly + the ci_smoke har_hetero step cover them;
    # purchase_homo keeps one end-to-end fed_launch round in the fast suite
    pytest.param("har_hetero", marks=pytest.mark.slow),
    "purchase_homo",
    pytest.param("texas_heter", marks=pytest.mark.slow),
])
def test_new_baseline_families_train_a_round(name):
    """The families this matrix introduced (har_subject partition,
    purchasemlp/texasmlp) run one fed_launch round end to end."""
    from fedml_tpu.experiments.fed_launch import main

    hist = main(["--config", os.path.join(CONFIG_DIR, f"{name}.yaml"),
                 "--override", "comm_round=1", "--override", "epochs=1"])
    assert np.isfinite(hist[-1]["Test/Loss"])


def test_har_subject_partition_groups_by_subject():
    """p-hetero over SUBJECT labels: with alpha=1 every client's windows
    come from (a slice of) one subject group — the reference subject
    loader's dense case (subject_dataloader.py:275-310)."""
    from fedml_tpu.data.registry import load_dataset

    ds = load_dataset("har_subject", client_num_in_total=21,
                      partition_method="p-hetero", partition_alpha=1.0, seed=3)
    assert ds.client_num == 21
    counts = np.asarray(ds.train.counts)
    assert counts.sum() > 0
