"""Asynchronous round pipeline (ISSUE 5 tentpole): the pipelined drive loop
must be BIT-identical to the eager loop at any depth — plain runs, chaos
runs, guard rollbacks, and checkpoint resume — because staging is a pure
function of round_idx and the round rng stream is untouched. Plus the
prefetcher's contract with streaming stores: only sampled clients decode.
"""

import numpy as np
import pytest

import jax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.prefetch import CohortPrefetcher
from fedml_tpu.data.registry import FederatedDataset, load_dataset
from fedml_tpu.data.streaming import StreamingPackedClients
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.robustness.guard import GuardVerdict


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


def _cfg(comm_round, **kw):
    kw.setdefault("client_num_per_round", 8)
    return FedConfig(dataset="mnist", model="lr", comm_round=comm_round,
                     batch_size=8, lr=0.05, client_num_in_total=8,
                     seed=0, **kw)


def _api(ds, cfg, aggregator_name="fedavg"):
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    return FedAvgAPI(ds, cfg, trainer, aggregator_name=aggregator_name)


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


def _strip_times(history):
    return [{k: v for k, v in r.items() if k != "round_time"}
            for r in history]


# ------------------------------------------------------------- bit identity

@pytest.mark.parametrize("depth", [1, 2, 4])
@pytest.mark.parametrize("agg_name,cfg_extra", [
    ("fedavg", {}),
    ("fedopt", {"server_optimizer": "adam", "server_lr": 0.01}),
])
def test_pipelined_bit_identical_to_eager(ds8, depth, agg_name, cfg_extra):
    eager = _api(ds8, _cfg(5, **cfg_extra), agg_name)
    eager.train()
    piped = _api(ds8, _cfg(5, pipeline_depth=depth, **cfg_extra), agg_name)
    piped.train()
    assert _bitwise_equal(piped.global_variables, eager.global_variables)
    assert _bitwise_equal(piped.agg_state, eager.agg_state)
    assert _strip_times(piped.history) == _strip_times(eager.history)


def test_pipelined_chaos_bit_identical(ds8):
    """FaultPlan.events is pure in (seed, round_idx), so the staging thread
    reproduces the eager loop's fault schedule byte-for-byte."""
    plan = lambda: FaultPlan(seed=3, drop_rate=0.25, nan_rate=0.25)
    eager = _api(ds8, _cfg(5))
    eager.train(chaos=plan())
    piped = _api(ds8, _cfg(5, pipeline_depth=2))
    piped.train(chaos=plan())
    assert _bitwise_equal(piped.global_variables, eager.global_variables)
    assert _strip_times(piped.history) == _strip_times(eager.history)


class _RejectOnce:
    """Deterministic guard: rejects exactly one round once, accepts after."""

    max_retries = 2

    def __init__(self, bad_round=2):
        self.bad_round = bad_round
        self.fired = False

    def inspect(self, round_idx, loss, global_variables=None):
        if round_idx == self.bad_round and not self.fired:
            self.fired = True
            return GuardVerdict(False, "forced test rejection")
        return GuardVerdict(True, "")


def test_guard_rollback_drops_stale_cohorts(ds8):
    """A rejected round invalidates every in-flight prefetch: the retried
    round re-stages from scratch (round 2 staged twice) and the driver's
    round_idx assertion proves no stale cohort was consumed. Outcome stays
    bit-identical to the eager loop under the same guard."""
    eager = _api(ds8, _cfg(5))
    eager.train(guard=_RejectOnce(bad_round=2))
    piped = _api(ds8, _cfg(5, pipeline_depth=2))
    piped.train(guard=_RejectOnce(bad_round=2))

    pf = piped._last_prefetcher
    assert pf.staged_rounds.count(2) == 2      # invalidated, then re-staged
    assert pf.consumed_rounds.count(2) == 2    # consumed once per attempt
    assert [r["round"] for r in piped.history] == list(range(5))
    assert piped.history[2].get("guard_retries") == 1
    assert _bitwise_equal(piped.global_variables, eager.global_variables)
    assert _strip_times(piped.history) == _strip_times(eager.history)


@pytest.mark.slow  # ~11s (12-round eager + depth-4 piped twins); the
# pipelined==eager bit-identity is pinned by the faster tests above
def test_pipelined_flush_bounds_pending_backlog(ds8):
    """BENCH_r06 depth-scaling regression pin: without sync points (no
    guard, rare eval), deferred records must still flush once the backlog
    reaches ~2x the pipeline depth — unbounded record debt competed with
    the staging thread for the host CPU at depth 4. The threshold flush
    rides rounds long done on device, so the trajectory stays bit-identical
    to the eager loop."""
    depth = 4
    eager = _api(ds8, _cfg(12, frequency_of_the_test=100))
    eager.train()
    piped = _api(ds8, _cfg(12, pipeline_depth=depth,
                           frequency_of_the_test=100))
    piped.train()
    assert piped._last_records.max_pending <= max(4, 2 * depth)
    assert len(piped.history) == 12
    assert _bitwise_equal(piped.global_variables, eager.global_variables)
    assert _strip_times(piped.history) == _strip_times(eager.history)


def test_pipelined_checkpoint_resume_bit_identical(ds8, tmp_path):
    """Interrupt at round 3, resume with a NEW pipelined API: final state
    matches the straight pipelined run AND the straight eager run."""
    straight = _api(ds8, _cfg(6))
    straight.train()

    d = str(tmp_path / "ckpt_pipe")
    first = _api(ds8, _cfg(3, pipeline_depth=2))
    first.train(ckpt_dir=d, ckpt_every=100)
    resumed = _api(ds8, _cfg(6, pipeline_depth=2))
    hist = resumed.train(ckpt_dir=d, ckpt_every=100)

    assert _bitwise_equal(resumed.global_variables, straight.global_variables)
    assert _bitwise_equal(resumed.agg_state, straight.agg_state)
    assert len(hist) == 6


# ------------------------------------------------- streaming store contract

def _counting_streaming_ds(clients=8, per_client=6, dim=12, class_num=2):
    """StreamingPackedClients over synthetic 'files' (decode_fn is pure in
    the path string — no disk), with a decode-call log."""
    decoded: list[int] = []

    def dec(path):
        k, i = (int(s) for s in path.split("_")[1:])
        decoded.append(k)
        rs = np.random.RandomState(k * 1000 + i)
        return rs.rand(dim).astype(np.float32)

    files = [[f"f_{k}_{i}" for i in range(per_client)]
             for k in range(clients)]
    labels = [np.arange(per_client) % class_num for _ in range(clients)]
    row_bytes = per_client * dim * 4
    st = StreamingPackedClients(files, labels, dec,
                                byte_budget=4 * row_bytes)
    rs = np.random.RandomState(99)
    gx = rs.rand(16, dim).astype(np.float32)
    gy = (np.arange(16) % class_num).astype(np.int32)
    ds = FederatedDataset(name="synth-stream", train=st, test=None,
                          train_global=(gx, gy), test_global=(gx, gy),
                          class_num=class_num, meta={"streaming": True})
    return ds, decoded


def test_prefetch_decodes_only_sampled_clients():
    """The staging thread must touch exactly the sampled cohorts' rows —
    ci=1 confines eval to client 0 — and the LRU byte budget holds even
    with the prefetcher running ahead."""
    ds, decoded = _counting_streaming_ds()
    cfg = _cfg(4, client_num_per_round=3, pipeline_depth=2, ci=1,
               frequency_of_the_test=100)
    api = _api(ds, cfg)
    api.train()

    sampled = set()
    for r in range(cfg.comm_round):
        sampled.update(client_sampling(r, ds.client_num,
                                       cfg.client_num_per_round).tolist())
    assert set(decoded) <= sampled | {0}   # client 0: example input + ci eval
    assert ds.train.resident_bytes <= ds.train.byte_budget

    eager_ds, _ = _counting_streaming_ds()
    eager = _api(eager_ds, _cfg(4, client_num_per_round=3, ci=1,
                                frequency_of_the_test=100))
    eager.train()
    assert _bitwise_equal(api.global_variables, eager.global_variables)


# ------------------------------------------------------- prefetcher surface

def test_prefetcher_miss_restages_and_counts():
    staged = []

    def stage(r):
        staged.append(r)
        return type("C", (), {"round_idx": r})()

    with CohortPrefetcher(stage, depth=2) as pf:
        assert pf.prefetch(0)
        assert pf.prefetch(1)
        assert not pf.prefetch(2)         # at depth: dropped
        assert pf.get(0).round_idx == 0
        assert pf.get(5).round_idx == 5   # never staged -> on-demand miss
        assert pf.misses == 1
        pf.prefetch(6)
        pf.invalidate()                   # forgets 6 (run or not)
        assert pf.get(6).round_idx == 6   # -> miss, fresh staging
        assert pf.misses == 2
    assert 2 not in staged
    assert staged.count(6) in (1, 2)      # 2 iff the job beat the cancel
