"""Long-context / sequence-parallel tests on the 8-device virtual CPU mesh:
ring attention and Ulysses all-to-all must match the dense reference, and
the pallas flash kernel (interpret mode off-TPU) must match forward and
backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from fedml_tpu.ops.attention import attention_reference, flash_attention
from fedml_tpu.parallel.sequence import ring_attention, ulysses_attention


def _qkv(b=2, t=64, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("sp",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v = _qkv()
    mesh = _mesh()
    ref = attention_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    q, k, v = _qkv(h=8)  # H must divide over the 8-way axis
    mesh = _mesh()
    ref = attention_reference(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_kernel_matches_reference(causal):
    q, k, v = _qkv(t=64)
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal, 16, 16, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_backward_matches_reference():
    q, k, v = _qkv(t=32, h=2)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, 16, 16, True).sum()

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_ring_attention_rejects_indivisible_seq():
    q, k, v = _qkv(t=60)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, _mesh(), causal=False)


def test_transformer_lm_forward_and_fedavg_round():
    """TransformerLM (flash-attention core) trains one FedAvg round through
    the NWP trainer on packed token windows."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.core.trainer import NWPTrainer
    from fedml_tpu.data.packing import PackedClients
    from fedml_tpu.data.registry import FederatedDataset
    from fedml_tpu.models.registry import create_model

    m = create_model("transformer_nwp", output_dim=50, vocab_size=50,
                     d_model=32, heads=2, num_layers=1, max_len=64)
    x = jnp.zeros((2, 16), jnp.int32)
    v = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = m.apply(v, x, train=False)
    assert out.shape == (2, 16, 50)

    rng = np.random.RandomState(0)
    C, n, T = 4, 12, 16
    xs = rng.randint(1, 50, (C, n, T)).astype(np.int32)
    ys = np.concatenate([xs[:, :, 1:], rng.randint(1, 50, (C, n, 1))], -1).astype(np.int32)
    packed = PackedClients(xs, ys, np.full(C, n, np.int32))
    ds = FederatedDataset(name="toy_nwp", train=packed, test=packed,
                          train_global=(xs.reshape(-1, T), ys.reshape(-1, T)),
                          test_global=(xs.reshape(-1, T), ys.reshape(-1, T)),
                          class_num=50)
    cfg = FedConfig(comm_round=2, epochs=1, batch_size=6, lr=0.05,
                    client_num_in_total=C, client_num_per_round=C,
                    frequency_of_the_test=2)
    api = FedAvgAPI(ds, cfg, NWPTrainer(m, pad_id=0))
    hist = api.train()
    assert np.isfinite(hist[-1]["Test/Loss"])


def test_ring_attention_gradients_match_reference():
    """Ring attention must be trainable: grads through the shard_map ring
    (scan + ppermute) equal grads through the dense reference."""
    q, k, v = _qkv(t=32, h=2)
    mesh = _mesh()

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_dense_reference(causal):
    """The blocked backward (dq via K-sweep, dk/dv via Q-sweep, p recomputed
    from the saved logsumexp) must match autodiff through the dense reference
    — multi-block shapes so the accumulator sweeps actually accumulate."""
    from fedml_tpu.ops.attention import attention_reference, flash_attention

    rng = np.random.RandomState(0 if causal else 1)
    b, t, h, d = 2, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    cot = jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 128, 128) * cot)

    def loss_dense(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal) * cot)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b2, name in zip(gf, gd, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_backward_trains_through_transformer_block():
    """End-to-end: gradients flow through the kernel inside a jitted train
    step and reduce the loss (the long-context training path is real)."""
    from fedml_tpu.ops.attention import flash_attention

    rng = jax.random.PRNGKey(0)
    b, t, h, d = 2, 128, 2, 32
    w = jax.random.normal(rng, (h * d, h * d)) * 0.05
    x = jax.random.normal(jax.random.fold_in(rng, 1), (b, t, h * d))
    target = jax.random.normal(jax.random.fold_in(rng, 2), (b, t, h * d))

    @jax.jit
    def loss_fn(w):
        qkv = (x @ w).reshape(b, t, h, d)
        o = flash_attention(qkv, qkv, qkv, True, 128, 128)
        return jnp.mean((o.reshape(b, t, h * d) - target) ** 2)

    g = jax.grad(loss_fn)
    l0 = float(loss_fn(w))
    for _ in range(10):
        w = w - 0.5 * g(w)
    assert float(loss_fn(w)) < l0
