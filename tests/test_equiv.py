"""graft-equiv (analysis/equiv_engine.py): the canonicalizer's PASS/FAIL
fixtures, the EQUIV_PAIRS contract plumbing, and bitwise spot-checks that
core/builder.build_round_program and the preserved legacy hand assembly
don't just trace to the same canonical jaxpr but COMPUTE the same values
on the four drive-loop families."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.analysis.equiv_engine import (canonicalize, equal,
                                             first_divergence,
                                             legacy_round_programs)
from fedml_tpu.core.builder import build_round_program


def _canon(fn, *args):
    return canonicalize(jax.make_jaxpr(fn)(*args))


def _sds(shape=(), dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# ------------------------------------------------------------ canonicalizer


def test_swapped_primitive_fails_with_primitive_pair():
    ca = _canon(lambda a, b: a + b, _sds((3,)), _sds((3,)))
    cb = _canon(lambda a, b: a - b, _sds((3,)), _sds((3,)))
    assert not equal(ca, cb)
    div = first_divergence(ca, cb)
    assert div and "add" in div and "sub" in div and "eqn[" in div


def test_perturbed_literal_fails():
    ca = _canon(lambda x: x + 1.0, _sds((3,)))
    cb = _canon(lambda x: x + 1.5, _sds((3,)))
    assert not equal(ca, cb)
    div = first_divergence(ca, cb)
    assert div and "eqn[" in div


def test_reordered_tree_keys_pass():
    # dict pytrees flatten key-sorted; insertion order is a trace accident
    def f(tree):
        return tree["a"] * tree["b"]

    ca = _canon(f, {"a": _sds((2,)), "b": _sds((2,))})
    cb = _canon(f, {"b": _sds((2,)), "a": _sds((2,))})
    assert equal(ca, cb)
    assert first_divergence(ca, cb) is None


def test_extra_dead_eqn_passes():
    def live(x):
        return x * 2.0

    def with_dead(x):
        _ = jnp.sin(x)          # traced, unused — DCE'd by canonicalization
        return x * 2.0

    ca, cb = _canon(live, _sds((4,))), _canon(with_dead, _sds((4,)))
    assert equal(ca, cb)


def test_sharding_constraint_is_erased():
    # placement hints are not computation: constraining over a mesh must
    # canonicalize away (what makes the tensor-shards-1 contract provable)
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("clients",))

    def plain(x):
        return x + 1.0

    def hinted(x):
        x = jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, P()))
        return x + 1.0

    assert equal(_canon(plain, _sds((4,))), _canon(hinted, _sds((4,))))


def test_different_aggregator_fails_with_eqn_diff():
    # a REAL divergence (fedavg vs robust trimmed aggregation) must be
    # caught and reported at equation level, operands labeled by origin
    a = build_round_program({})[0]
    b = build_round_program({"aggregator": "robust"})[0]
    ca = _canon(a.fn, *a.args)
    cb = _canon(b.fn, *b.args)
    assert not equal(ca, cb)
    div = first_divergence(ca, cb)
    assert div is not None
    assert "eqn[" in div or "signature" in div


# ------------------------------------- builder vs legacy: bitwise spot-check


def _concretize(aval):
    """Deterministic concrete value for one abstract leaf: positive ints
    (counts/fills stay nonzero), small varied floats, all-True bools (every
    client participates — the masked and unmasked programs agree there)."""
    if not isinstance(aval, jax.ShapeDtypeStruct):
        return aval                       # already concrete (the rng key)
    n = max(1, int(np.prod(aval.shape)))
    flat = np.arange(n, dtype=np.float64)
    if jnp.issubdtype(aval.dtype, jnp.bool_):
        return jnp.ones(aval.shape, dtype=bool)
    if jnp.issubdtype(aval.dtype, jnp.integer):
        return jnp.asarray((flat % 3 + 1).reshape(aval.shape),
                           dtype=aval.dtype)
    return jnp.asarray(((flat % 7 + 1) / 7.0).reshape(aval.shape),
                       dtype=aval.dtype)


def _bitwise_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        eq = (np.array_equal(x, y, equal_nan=True)
              if x.dtype.kind == "f" else np.array_equal(x, y))
        if not eq:
            return False
    return True


@pytest.mark.parametrize("levels", [
    {},                             # engine vmap round
    {"backend": "shard_map"},       # 1-D sharded round
    {"tensor": "shards"},           # tensor-sharded round
    {"buffer": "on"},               # buffered client_step / admit / commit
], ids=["engine", "sharded", "tensor", "buffered"])
def test_builder_and_legacy_compute_bitwise_identical(levels):
    built = build_round_program(levels)
    legacy = legacy_round_programs(levels)
    assert len(built) == len(legacy)
    for bp, lp in zip(built, legacy):
        b_args = jax.tree.map(_concretize, bp.args)
        l_args = jax.tree.map(_concretize, lp.args)
        out_b = bp.fn(*b_args)
        out_l = lp.fn(*l_args)
        assert _bitwise_equal(out_b, out_l), (
            f"{bp.name} vs {lp.name}: outputs diverge bitwise")


# --------------------------------------------------- contract-trip plumbing


def test_mutated_equiv_pair_trips_with_readable_diff(monkeypatch):
    # the CI self-test's seam: perturb ONE contract (lora rank 0 -> 2) and
    # the engine must FAIL that contract with an eqn-level divergence while
    # the others keep proving
    import fedml_tpu.core.spec as spec
    from fedml_tpu.analysis.equiv_engine import run_equiv

    mutated = tuple(
        spec.EquivPair(p.name, spec.EquivSide(p.lhs.kind, p.lhs.levels,
                                              (("lora_rank", 2),)),
                       p.rhs, p.doc)
        if p.name == "lora-rank-0" else p
        for p in spec.EQUIV_PAIRS)
    monkeypatch.setattr(spec, "EQUIV_PAIRS", mutated)
    report, payload = run_equiv(".", fast=True, targets=["lora-rank-0"])
    assert not report.ok
    [row] = [r for r in payload["pairs"] if r["name"] == "lora-rank-0"]
    assert row["ok"] is False
    msg = report.findings[0].message
    assert "divergence" in msg and ("eqn[" in msg or "signature" in msg)


def test_equiv_pairs_all_prove(monkeypatch):
    # the unmutated contracts hold (the full sweep runs in ci_smoke; this
    # is the fast in-suite gate)
    from fedml_tpu.analysis.equiv_engine import run_equiv

    report, payload = run_equiv(".", fast=True)
    assert report.ok, report.summary()
    assert all(r["ok"] for r in payload["pairs"] + payload["cover"])
