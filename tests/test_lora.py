"""Federated LoRA + the activation-sharded client step (ISSUE 14).

The contracts under test, each at the strength the design promises:

  - frozen base: the engine differentiates ``variables["params"]`` only, so
    the ``lora_base`` collection is BITWISE invariant across a whole drive —
    a structural property, not a masking trick (models/lora.py);
  - structurally off: ``lora_rank=0`` returns the very trainer object, and
    a 1-shard tensor axis disables the activation-constraint scope, so both
    knobs trace the exact legacy programs (bit-identity);
  - checkpoints are adapters-only; resume and guard rollback re-attach the
    deterministic base and land bitwise where the design says bitwise;
  - the GSPMD ``shard_step`` round carries an ALLCLOSE contract versus the
    vmap engine (the partitioner reassociates float contractions — the
    documented trade for the per-device memory win), pinned here at 1e-6;
  - the win itself: XLA ``memory_analysis`` per-device peak of the
    activation-sharded transformer step is >=2x smaller than its
    replicated twin at 4 shards (COMMS_BUDGET.json pins <=0.5x in CI);
  - the wire: committed COMMS budgets show >=50x adapter-only param-byte
    shrink at rank 8, and lora+topk strictly below either alone.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as PS

from fedml_tpu.algorithms.aggregators import make_aggregator
from fedml_tpu.algorithms.engine import build_local_update, build_round_fn
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer, NWPTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.lora import (
    LORA_COLLECTION,
    LoRATrainer,
    maybe_wrap_lora,
    strip_lora_base,
)
from fedml_tpu.models.registry import create_model
from fedml_tpu.parallel import TensorSharding, make_tensor_mesh
from fedml_tpu.parallel.tensor import (
    REPLICATED_RULES,
    build_tensor_step_fn,
    build_tensor_step_round_fn,
)
from fedml_tpu.robustness.guard import GuardVerdict
from fedml_tpu.utils.checkpoint import all_checkpoint_steps

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


def _max_abs_delta(a, b):
    d = jax.tree.map(lambda u, v: float(jnp.max(jnp.abs(u - v))), a, b)
    return max(jax.tree.leaves(d), default=0.0)


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


def _cfg(**kw):
    kw.setdefault("dataset", "mnist")
    kw.setdefault("model", "lr")
    kw.setdefault("batch_size", 8)
    kw.setdefault("lr", 0.05)
    kw.setdefault("client_num_in_total", 8)
    kw.setdefault("client_num_per_round", 8)
    kw.setdefault("seed", 0)
    return FedConfig(**kw)


def _lora_api(ds, cfg):
    trainer = maybe_wrap_lora(
        ClassificationTrainer(create_model("lr", output_dim=ds.class_num)),
        cfg)
    return FedAvgAPI(ds, cfg, trainer)


# -------------------------------------------------------- adapter structure

def test_lora_wrap_starts_bit_identical_to_unwrapped():
    """B initializes to zeros, so base + (A @ B) * scale == base and the
    wrapped model's first forward matches the unwrapped one bitwise."""
    inner = ClassificationTrainer(create_model("lr", output_dim=10))
    wrapped = LoRATrainer(inner, rank=4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 784), jnp.float32)
    gv_in = inner.init(jax.random.PRNGKey(0), x[:1])
    gv_wr = wrapped.init(jax.random.PRNGKey(0), x[:1])
    assert LORA_COLLECTION in gv_wr
    assert _bitwise_equal(gv_wr[LORA_COLLECTION], gv_in["params"])
    assert _bitwise_equal(inner.apply(gv_in, x), wrapped.apply(gv_wr, x))


def test_lora_rank_zero_is_structurally_off():
    """rank 0 must return the SAME trainer object — no wrapper, no new
    collections, the exact legacy trace."""
    trainer = ClassificationTrainer(create_model("lr", output_dim=10))
    assert maybe_wrap_lora(trainer, _cfg(lora_rank=0)) is trainer
    assert maybe_wrap_lora(trainer, _cfg()) is trainer
    # and double-wrapping is refused too
    wrapped = maybe_wrap_lora(trainer, _cfg(lora_rank=4))
    assert maybe_wrap_lora(wrapped, _cfg(lora_rank=4)) is wrapped


def test_lm_head_kernel_gets_no_adapter():
    """DEFAULT_TARGETS excludes the [d_model, vocab] head (peft's
    "all-linear" convention) — the one adapter that would dwarf every block
    adapter combined and cap the adapter-only wire shrink."""
    trainer = LoRATrainer(
        NWPTrainer(create_model("transformer_nwp", output_dim=200)), rank=4)
    gv = jax.eval_shape(lambda: trainer.init(jax.random.PRNGKey(0),
                                             jnp.zeros((2, 8), jnp.int32)))
    assert "lm_head" in gv[LORA_COLLECTION]
    assert "lm_head" not in gv["params"]
    assert gv["params"]  # block kernels did match


# ----------------------------------------------------- frozen-base invariance

def test_frozen_base_bitwise_invariant_across_rounds(ds8):
    api = _lora_api(ds8, _cfg(comm_round=3, lora_rank=4))
    base0 = jax.device_get(api.global_variables[LORA_COLLECTION])
    adapters0 = jax.device_get(api.global_variables["params"])
    hist = api.train()
    assert _bitwise_equal(api.global_variables[LORA_COLLECTION], base0)
    assert not _bitwise_equal(api.global_variables["params"], adapters0)
    assert hist[-1]["Test/Loss"] < hist[0]["Test/Loss"]


# ------------------------------------------- checkpoint resume + guard rollback

def test_adapter_only_checkpoint_resume_is_bitwise(ds8, tmp_path):
    """ckpt-at-2 -> NEW api -> resume -> finish == straight 4-round run,
    bitwise on params, base AND aggregator state; the on-disk tree holds
    adapters only (the base is a pure function of cfg.seed, re-derived by
    the fresh api at construction)."""
    straight = _lora_api(ds8, _cfg(comm_round=4, lora_rank=4))
    straight.train()

    d = str(tmp_path / "ckpt")
    first = _lora_api(ds8, _cfg(comm_round=2, lora_rank=4))
    first.train(ckpt_dir=d, ckpt_every=100)
    assert all_checkpoint_steps(d) == [2]
    # what went to disk is what _ckpt_tree hands save_checkpoint:
    # adapters-only variables, never the base
    saved = first._ckpt_tree()["variables"]
    assert LORA_COLLECTION not in saved
    paths = [jax.tree_util.keystr(p) for p, _ in
             jax.tree_util.tree_flatten_with_path(saved)[0]]
    assert any("lora_A" in p for p in paths)

    resumed = _lora_api(ds8, _cfg(comm_round=4, lora_rank=4))
    resumed.train(ckpt_dir=d, ckpt_every=100)
    assert _bitwise_equal(resumed.global_variables,
                          straight.global_variables)
    assert _bitwise_equal(resumed.agg_state, straight.agg_state)


class _RejectOnce:
    max_retries = 2

    def __init__(self, bad_round=1):
        self.bad_round = bad_round
        self.fired = False

    def inspect(self, round_idx, loss, global_variables=None):
        if round_idx == self.bad_round and not self.fired:
            self.fired = True
            return GuardVerdict(False, "forced test rejection")
        return GuardVerdict(True, "")


def test_guard_rollback_restores_adapters_bitwise(ds8):
    """Rollback restores the in-memory snapshot (adapters + agg state) and
    re-attaches the live base: two same-seed guarded runs are byte-identical
    end to end, and the base never moves."""
    runs = []
    for _ in range(2):
        api = _lora_api(ds8, _cfg(comm_round=3, lora_rank=4))
        base0 = jax.device_get(api.global_variables[LORA_COLLECTION])
        api.train(guard=_RejectOnce(bad_round=1))
        assert _bitwise_equal(api.global_variables[LORA_COLLECTION], base0)
        runs.append(api)
    assert runs[0].history[1]["guard_retries"] == 1  # the rollback fired
    assert _bitwise_equal(runs[0].global_variables,
                          runs[1].global_variables)
    assert _bitwise_equal(runs[0].agg_state, runs[1].agg_state)


# ------------------------------------------------- codec + buffered composition

def test_lora_topk_codec_e2e_on_buffered_drive(ds8):
    """The full stack in one drive: LoRA adapters through the FedBuff
    admit/commit loop with the top-k codec on the wire. Base frozen, loss
    finite and improving — the codec residual tree is adapters-shaped."""
    api = _lora_api(ds8, _cfg(comm_round=3, lora_rank=4, buffer_size=8,
                              update_codec="topk", codec_k=16))
    base0 = jax.device_get(api.global_variables[LORA_COLLECTION])
    hist = api.train()
    assert _bitwise_equal(api.global_variables[LORA_COLLECTION], base0)
    assert np.isfinite(hist[-1]["Test/Loss"])
    assert hist[-1]["Test/Loss"] < hist[0]["Test/Loss"]
    # the codec really was on the wire, and the buffer rows it compressed
    # are the WIRE tree: adapters only, no base (engine strips inside vmap)
    assert api.codec is not None and api.codec.name.startswith("topk")
    rows = api._buffer["vars"]
    assert LORA_COLLECTION not in rows
    assert jax.tree.structure(rows) == jax.tree.structure(
        strip_lora_base(api.global_variables))


# ------------------------------------------------ shard_step (GSPMD) contracts

@pytest.fixture(scope="module")
def mesh24():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_tensor_mesh(4)


def _round_setup(ds8, lora_rank=0):
    cfg = _cfg(epochs=1, tensor_shards=4, shard_step=True,
               lora_rank=lora_rank)
    trainer = maybe_wrap_lora(
        ClassificationTrainer(create_model("lr", output_dim=ds8.class_num)),
        cfg)
    agg = make_aggregator("fedavg", cfg)
    rng = jax.random.PRNGKey(0)
    gv = trainer.init(rng, jnp.asarray(ds8.train.x[:1, 0]))
    state = agg.init_state(gv)
    x, y, counts = ds8.train.select(np.arange(8))
    # ONE minibatch step per client: sequential SGD compounds the
    # partitioner's per-step reassociation error multiplicatively, so the
    # tolerance pin holds the single-step error, not the compounded tail
    data = (jnp.asarray(x[:, :8]), jnp.asarray(y[:, :8]),
            jnp.full((8,), 8, jnp.int32))
    return cfg, trainer, agg, gv, state, data, rng


@pytest.mark.parametrize("lora_rank", [0, 4])
def test_shard_step_round_allclose_to_vmap_engine(mesh24, ds8, lora_rank):
    """The documented trade: GSPMD reassociates float contractions, so the
    activation-sharded round matches the vmap engine within 1e-6 (not
    bitwise). Composes with LoRA — the frozen base stays bitwise."""
    cfg, trainer, agg, gv, state, (x, y, counts), rng = _round_setup(
        ds8, lora_rank)
    sh = TensorSharding.for_model(mesh24, "lr")
    rf = build_tensor_step_round_fn(trainer, cfg, agg, sh,
                                    donate_state=False)
    vmap_rf = build_round_fn(trainer, cfg, agg)

    g1, s1, m1 = rf(sh.place(gv), sh.place(state), x, y, counts, rng)
    g2, s2, m2 = vmap_rf(gv, state, x, y, counts, rng)
    assert _max_abs_delta(g1, g2) < 1e-6
    assert _max_abs_delta(s1, s2) < 1e-6
    for k in m1:
        assert abs(float(m1[k]) - float(m2[k])) < 1e-3
    if lora_rank:
        assert _bitwise_equal(g1[LORA_COLLECTION], gv[LORA_COLLECTION])


def test_tensor_shards_one_is_bit_identical(ds8):
    """At tensor_shards=1 the constraint scope is structurally off and the
    step program IS the plain jitted vmap step — bitwise, on a 1x1 mesh so
    no partitioner touches the arithmetic."""
    cfg = _cfg(epochs=1, tensor_shards=1)
    trainer = ClassificationTrainer(
        create_model("lr", output_dim=ds8.class_num))
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                ("clients", "tensor"))
    sh = TensorSharding.for_model(mesh, "lr")
    gv = trainer.init(jax.random.PRNGKey(0), jnp.asarray(ds8.train.x[:1, 0]))
    x, y, counts = ds8.train.select(np.arange(8))
    x, y, counts = jnp.asarray(x), jnp.asarray(y), jnp.asarray(counts)
    rng = jax.random.PRNGKey(7)

    step_fn = build_tensor_step_fn(trainer, cfg, sh)
    local_update = build_local_update(trainer, cfg)

    def plain(gv, x, y, counts, rng):
        crngs = jax.random.split(rng, x.shape[0])
        return jax.vmap(local_update, in_axes=(None, 0, 0, 0, 0))(
            gv, x, y, counts, crngs)

    r_sh = step_fn(gv, x, y, counts, rng)
    r_pl = jax.jit(plain)(gv, x, y, counts, rng)
    assert _bitwise_equal(r_sh.variables, r_pl.variables)
    assert _bitwise_equal(r_sh.metrics, r_pl.metrics)


def test_batched_rank_constraint_spec_raises_at_trace():
    """Constraint specs are written at the rank the MODEL sees; the client
    vmap prepends its batch dim automatically. A spec written at the
    batched rank over-ranks the actual intermediate and must fail loudly at
    trace time, not silently mis-shard (parallel/activations.py)."""
    mesh = make_tensor_mesh(4)
    cfg = FedConfig(model="transformer_nwp", batch_size=2, epochs=1,
                    tensor_shards=4)
    trainer = NWPTrainer(create_model("transformer_nwp", output_dim=200))
    sh = TensorSharding.for_model(mesh, "transformer_nwp")
    gv = jax.eval_shape(lambda: trainer.init(jax.random.PRNGKey(0),
                                             jnp.zeros((2, 16), jnp.int32)))
    SDS = jax.ShapeDtypeStruct
    args = (gv, SDS((2, 4, 16), jnp.int32), SDS((2, 4, 16), jnp.int32),
            SDS((2,), jnp.int32), SDS((2,), jnp.uint32))
    bad_rules = {"attn_qkv": PS(None, None, None, "tensor")}  # batched rank
    step_bad = build_tensor_step_fn(trainer, cfg, sh,
                                    activation_rules=bad_rules)
    with pytest.raises(ValueError, match="rank at least"):
        step_bad.lower(*args)


# --------------------------------------------------- the per-device memory win

def test_step_peak_memory_shrinks_at_four_shards():
    """XLA's own memory_analysis: per-device peak (temp + args + out) of the
    activation-sharded transformer step is >=2x below the replicated twin at
    4 shards. COMMS_BUDGET.json pins the tighter <=0.5x ratio at the full
    NWP vocab in CI; this is the suite-local floor at a fast vocab."""
    mesh = make_tensor_mesh(4)
    cfg = FedConfig(model="transformer_nwp", batch_size=2, epochs=1,
                    dtype="float32", tensor_shards=4)
    trainer = NWPTrainer(create_model("transformer_nwp", output_dim=2000))
    gv = jax.eval_shape(lambda: trainer.init(jax.random.PRNGKey(0),
                                             jnp.zeros((2, 16), jnp.int32)))
    SDS = jax.ShapeDtypeStruct
    tail = (SDS((2, 4, 16), jnp.int32), SDS((2, 4, 16), jnp.int32),
            SDS((2,), jnp.int32), SDS((2,), jnp.uint32))

    def peak(step_fn):
        ma = step_fn.lower(gv, *tail).compile().memory_analysis()
        return (ma.temp_size_in_bytes + ma.argument_size_in_bytes
                + ma.output_size_in_bytes)

    sharded = peak(build_tensor_step_fn(
        trainer, cfg, TensorSharding.for_model(mesh, "transformer_nwp")))
    replicated = peak(build_tensor_step_fn(
        trainer, cfg, TensorSharding(mesh, tuple(REPLICATED_RULES)),
        activation_rules=None))
    assert replicated / sharded >= 2.0, \
        f"peak shrink {replicated / sharded:.2f}x < 2x " \
        f"(sharded {sharded}B, replicated {replicated}B)"


# -------------------------------------------------------- committed wire pins

def test_committed_budgets_pin_lora_wire_shrink():
    """The >=50x rank-8 adapter-only param-byte shrink and the
    lora+topk-strictly-smaller stacking, read from the committed
    COMMS_BUDGET.json (run_comms re-measures and gates both in CI)."""
    with open(os.path.join(_REPO, "COMMS_BUDGET.json")) as f:
        budgets = json.load(f)
    full = budgets["tensor.round[tformer,f32,fedavg,2x4]"]
    lora = budgets["tensor.round[tformer,f32,fedavg,2x4,lora8]"]
    topk = budgets["tensor.round[tformer,f32,fedavg,2x4,topk64]"]
    stack = budgets["tensor.round[tformer,f32,fedavg,2x4,lora8,topk64]"]
    assert full["param_bytes"] / lora["param_bytes"] >= 50.0
    assert stack["collective_bytes"] < lora["collective_bytes"]
    assert stack["collective_bytes"] < topk["collective_bytes"]
    step = budgets["tensor.step[tformer,f32,2x4]"]
    repl = budgets["tensor.step[tformer,f32,2x4,replicated]"]
    # both step twins pin ZERO user collectives (GSPMD resharding is
    # bounded by the peak budget, not counted here)
    assert step["collective_count"] == repl["collective_count"] == 0
    assert step["peak_bytes"] <= 0.5 * repl["peak_bytes"]
