"""Multi-round fused dispatch (the superstep, --rounds_per_dispatch K):
K federated rounds per jitted program must be BIT-identical to K eager
rounds — params, aggregator state (fedopt momenta, codec residuals), ledger
stats rows and history — under chaos masks and compressed transport, with
K-fold fewer `dispatch` spans, structurally off at K=1, and degrading to
the eager loop (guard rollback replay, streaming stores) without losing the
trajectory.
"""

import json

import numpy as np
import pytest

import jax

from fedml_tpu import telemetry
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.robustness.guard import GuardVerdict
from fedml_tpu.telemetry.client_ledger import COLUMNS, open_or_create


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


def _cfg(comm_round=9, **kw):
    kw.setdefault("client_num_per_round", 8)
    # frequency_of_the_test=1 would make every round an eval boundary and
    # clamp every chunk to K=1 — push eval to the final round only
    kw.setdefault("frequency_of_the_test", 100)
    return FedConfig(dataset="mnist", model="lr", comm_round=comm_round,
                     batch_size=8, lr=0.05, client_num_in_total=8,
                     seed=0, **kw)


def _api(ds, cfg, aggregator_name="fedavg"):
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    return FedAvgAPI(ds, cfg, trainer, aggregator_name=aggregator_name)


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


def _strip_times(history):
    return [{k: v for k, v in r.items() if k != "round_time"}
            for r in history]


def _span_count(trace_path, name):
    n = 0
    with open(trace_path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "span" and rec.get("name") == name:
                n += 1
    return n


# ------------------------------------------------------------- bit identity

# the acceptance matrix is (fedavg, fedopt) x (plain, chaos); the diagonal
# runs in tier-1, the off-diagonal pair rides the slow lane — same code
# paths, kept for completeness
@pytest.mark.parametrize("agg_name,cfg_extra,chaos_on", [
    ("fedavg", {}, False),
    ("fedopt", {"server_optimizer": "adam", "server_lr": 0.01}, True),
    pytest.param("fedavg", {}, True, marks=pytest.mark.slow),
    pytest.param("fedopt", {"server_optimizer": "adam", "server_lr": 0.01},
                 False, marks=pytest.mark.slow),
])
def test_superstep_bit_identical_to_eager(ds8, agg_name, cfg_extra, chaos_on):
    """K=8 fused == 8 eager rounds bitwise: params, momenta, history."""
    plan = lambda: (FaultPlan(seed=7, drop_rate=0.3, nan_rate=0.4)
                    if chaos_on else None)
    eager = _api(ds8, _cfg(9, **cfg_extra), agg_name)
    eager.train(chaos=plan())
    fused = _api(ds8, _cfg(9, rounds_per_dispatch=8, **cfg_extra), agg_name)
    fused.train(chaos=plan())
    assert _bitwise_equal(fused.global_variables, eager.global_variables)
    assert _bitwise_equal(fused.agg_state, eager.agg_state)
    assert _strip_times(fused.history) == _strip_times(eager.history)


def test_superstep_codec_residual_rides_carry(ds8):
    """--update_codec int8: the codec residual is part of agg_state and must
    thread through the scan carry bit-exactly (momenta-style)."""
    eager = _api(ds8, _cfg(9, update_codec="int8"))
    eager.train(chaos=FaultPlan(seed=7, drop_rate=0.3, nan_rate=0.4))
    fused = _api(ds8, _cfg(9, update_codec="int8", rounds_per_dispatch=8))
    fused.train(chaos=FaultPlan(seed=7, drop_rate=0.3, nan_rate=0.4))
    assert _bitwise_equal(fused.global_variables, eager.global_variables)
    assert _bitwise_equal(fused.agg_state, eager.agg_state)
    assert _strip_times(fused.history) == _strip_times(eager.history)


@pytest.mark.slow
def test_superstep_lora_composes(ds8):
    """--lora_rank: adapters-only aggregation + per-round base re-attach
    inside the scan."""
    eager = _api(ds8, _cfg(9, lora_rank=4))
    eager.train()
    fused = _api(ds8, _cfg(9, lora_rank=4, rounds_per_dispatch=4))
    fused.train()
    assert _bitwise_equal(fused.global_variables, eager.global_variables)
    assert _strip_times(fused.history) == _strip_times(eager.history)


def test_superstep_in_graph_feistel_sampling(ds8):
    """--fast_sampling with a sub-total cohort: the in-graph Feistel twin
    must reproduce the host sampler's cohorts bitwise end to end."""
    eager = _api(ds8, _cfg(9, client_num_per_round=4, fast_sampling=True))
    eager.train(chaos=FaultPlan(seed=3, drop_rate=0.25, corrupt_rate=0.25))
    fused = _api(ds8, _cfg(9, client_num_per_round=4, fast_sampling=True,
                           rounds_per_dispatch=8))
    fused.train(chaos=FaultPlan(seed=3, drop_rate=0.25, corrupt_rate=0.25))
    assert _bitwise_equal(fused.global_variables, eager.global_variables)
    assert _strip_times(fused.history) == _strip_times(eager.history)


@pytest.mark.slow
def test_superstep_ledger_rows_identical(ds8, tmp_path):
    """Per-cohort ledger stats rows ride the [K]-stacked scan outputs and
    scatter-write identically to K eager flushes."""
    def run(k):
        ledger = open_or_create(str(tmp_path / f"ledger_k{k}"), 8)
        api = _api(ds8, _cfg(9, rounds_per_dispatch=k))
        api.train(chaos=FaultPlan(seed=7, drop_rate=0.3, nan_rate=0.4),
                  ledger=ledger)
        ledger.flush()
        return ledger
    l1, l8 = run(1), run(8)
    for name, _, _ in COLUMNS:
        np.testing.assert_array_equal(l1.column(name), l8.column(name),
                                      err_msg=name)


# ------------------------------------------------- dispatch-count contract

def test_superstep_dispatch_count_drops_k_fold(ds8, tmp_path):
    """The headline: `dispatch` span count per round <= 1/K * eager + O(1),
    proven from TRACE.jsonl."""
    def run(k, name):
        trace = str(tmp_path / f"{name}.jsonl")
        tracer = telemetry.Tracer(jsonl_path=trace)
        api = _api(ds8, _cfg(8, rounds_per_dispatch=k))
        api.train(tracer=tracer)
        tracer.close()
        return _span_count(trace, "dispatch")
    eager_n = run(1, "eager")
    fused_n = run(4, "fused")
    assert eager_n == 8
    # 8 rounds at K=4: round 0 is the r%freq==0 eval boundary (eager),
    # rounds 1-4 one chunk, 5-7 a clamped chunk ending at the final-eval
    # round -> 3 dispatches, <= 8/4 + O(1)
    assert fused_n <= eager_n // 4 + 2
    # superstep_committed events cover the fused chunks
    events = []
    with open(str(tmp_path / "fused.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "event" and rec.get("kind") == "superstep_committed":
                events.append(rec)
    assert sum(e["rounds"] for e in events) == 7  # all but the eager round 0
    assert all(e["k"] == 4 for e in events)


def test_superstep_k1_structurally_off(ds8, monkeypatch):
    """rounds_per_dispatch=1 must never build a superstep program — the
    eager branch IS the K=1 path."""
    import fedml_tpu.algorithms.engine as engine

    def boom(*a, **kw):
        raise AssertionError("superstep program built at K=1")

    monkeypatch.setattr(engine, "build_superstep_fn", boom)
    api = _api(ds8, _cfg(3, rounds_per_dispatch=1))
    api.train()
    assert len(api.history) == 3
    assert api._superstep_cache == {}


def test_superstep_rejects_incompatible_modes(ds8):
    with pytest.raises(ValueError, match="superstep"):
        _api(ds8, _cfg(3, rounds_per_dispatch=4, pipeline_depth=2))
    with pytest.raises(ValueError, match="superstep"):
        _api(ds8, _cfg(3, rounds_per_dispatch=4, buffer_size=2))


# ------------------------------------------------------- graceful degrade

class _RejectOnce:
    """Deterministic guard: rejects exactly one round once, accepts after."""

    max_retries = 2

    def __init__(self, bad_round=3):
        self.bad_round = bad_round
        self.fired = False

    def inspect(self, round_idx, loss, global_variables=None):
        if round_idx == self.bad_round and not self.fired:
            self.fired = True
            return GuardVerdict(False, "forced test rejection")
        return GuardVerdict(True, "")


@pytest.mark.slow  # ~11s (fused + eager replay compiles); the rollback
# contract is also exercised by ci_smoke's superstep byte-equality step
def test_superstep_guard_rollback_replays_chunk_eagerly(ds8):
    """A rejection inside a chunk rolls the WHOLE chunk back (params AND
    guard state) and replays it at K=1 — localizing the bad round with the
    eager loop's exact salted-rng retry, so the trajectory matches pure
    eager under the same guard."""
    eager = _api(ds8, _cfg(9))
    eager.train(guard=_RejectOnce(bad_round=3))
    fused = _api(ds8, _cfg(9, rounds_per_dispatch=8))
    fused.train(guard=_RejectOnce(bad_round=3))
    assert fused.history[3].get("guard_retries") == 1
    assert [r["round"] for r in fused.history] == list(range(9))
    assert _bitwise_equal(fused.global_variables, eager.global_variables)
    assert _bitwise_equal(fused.agg_state, eager.agg_state)
    assert _strip_times(fused.history) == _strip_times(eager.history)


@pytest.mark.slow
def test_superstep_checkpoint_cadence_clamps_k(ds8, tmp_path):
    """ckpt_every=3 with K=8: chunks clamp so checkpoint rounds land
    chunk-final; an interrupt + resume matches the straight eager run."""
    straight = _api(ds8, _cfg(9))
    straight.train()

    d = str(tmp_path / "ckpt_superstep")
    first = _api(ds8, _cfg(6, rounds_per_dispatch=8))
    first.train(ckpt_dir=d, ckpt_every=3)
    resumed = _api(ds8, _cfg(9, rounds_per_dispatch=8))
    hist = resumed.train(ckpt_dir=d, ckpt_every=3)

    assert _bitwise_equal(resumed.global_variables, straight.global_variables)
    assert _bitwise_equal(resumed.agg_state, straight.agg_state)
    assert len(hist) == 9


@pytest.mark.slow
def test_superstep_streaming_store_falls_back_eager(ds8, monkeypatch):
    """No device-resident train store -> the drive degrades to the eager
    loop wholesale, same trajectory."""
    eager = _api(ds8, _cfg(5))
    eager.train()
    fused = _api(ds8, _cfg(5, rounds_per_dispatch=4))
    monkeypatch.setattr(fused, "_resident_train_arrays", lambda: None)
    fused.train()
    assert fused._superstep_cache == {}
    assert _bitwise_equal(fused.global_variables, eager.global_variables)
    assert _strip_times(fused.history) == _strip_times(eager.history)
