"""Client health ledger (ISSUE 11): mmap column semantics and the ledger
on/off bit-identity pin.

The load-bearing claims:
  - attaching a ClientLedger to ANY drive loop (eager, pipelined, buffered
    with stragglers, tensor-sharded) changes no traced program and no rng
    stream — final params are BITWISE identical with the ledger on or off;
  - the ledger itself is deterministic: two same-seed chaos runs produce
    byte-identical shard files and identical folded reports (the flagged
    set is stable, so a CI gate on it cannot flap);
  - ledger counters cross-check the chaos plan exactly — drop_count totals
    equal the plan's dispatch-time drops, quarantine totals its surviving
    NaN injections;
  - EMAs seed from the first HEALTHY observation and quarantined rounds
    never touch them;
  - scatter writes land in the right shard at any clients_per_shard, and
    apply() trims mesh-padded stats rows.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.trainer import ClassificationTrainer
from fedml_tpu.data.registry import load_dataset
from fedml_tpu.models.registry import create_model
from fedml_tpu.robustness.chaos import FaultPlan
from fedml_tpu.telemetry.client_ledger import (
    COLUMNS,
    ClientLedger,
    create_ledger,
    open_or_create,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import client_report  # noqa: E402  (tools/client_report.py)


def _bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


@pytest.fixture(scope="module")
def ds8():
    return load_dataset("mnist", client_num_in_total=8,
                        partition_method="homo", seed=0)


def _drive(ds, ledger, chaos=None, rounds=4, **cfg_kwargs):
    """Run a fresh FedAvgAPI drive loop; returns the final params tree."""
    cfg = FedConfig(comm_round=rounds, batch_size=8, epochs=1, lr=0.05,
                    client_num_in_total=ds.client_num,
                    client_num_per_round=ds.client_num,
                    seed=0, ci=1, frequency_of_the_test=10 ** 9,
                    **cfg_kwargs)
    trainer = ClassificationTrainer(create_model("lr", output_dim=ds.class_num))
    api = FedAvgAPI(ds, cfg, trainer)
    api.train(chaos=chaos, ledger=ledger)
    return api.global_variables


_CHAOS = FaultPlan(seed=3, drop_rate=0.2, nan_rate=0.1)

# every drive the repo ships, each with the seeded chaos plan that
# exercises its ledger path (the buffered drive adds stragglers so the
# staleness column fills too)
DRIVES = [
    pytest.param({}, _CHAOS, id="eager"),
    pytest.param({"pipeline_depth": 2}, _CHAOS, id="pipelined-depth2"),
    pytest.param({"buffer_size": 3},
                 FaultPlan(seed=3, drop_rate=0.2, nan_rate=0.1,
                           straggler_rate=0.4, straggler_rounds=2),
                 id="buffered-stragglers"),
    # ~13s: the tensor drive compiles twice (ledger on + off); the other
    # three drives pin the same pure-observation contract in the fast suite
    pytest.param({"tensor_shards": 4}, _CHAOS, id="tensor-sharded",
                 marks=pytest.mark.slow),
]


# ------------------------------------------------- ledger on/off bit identity

@pytest.mark.parametrize("cfg_kwargs,chaos", DRIVES)
def test_ledger_on_off_params_bitwise(ds8, tmp_path, cfg_kwargs, chaos):
    """Attaching the ledger is pure observation: the round programs always
    return the stats rows (collect_stats=True), so whether a ledger
    scatter-writes them host-side cannot move a single bit of the model."""
    params_off = _drive(ds8, None, chaos=chaos, **cfg_kwargs)
    ledger = create_ledger(str(tmp_path / "led"), ds8.client_num)
    try:
        params_on = _drive(ds8, ledger, chaos=chaos, **cfg_kwargs)
        assert _bitwise_equal(params_off, params_on)

        # dispatch-time accounting must reproduce the chaos plan exactly:
        # the plan is pure in (seed, round), so totals are closed-form
        part = ledger.column("participation_count")
        drop = ledger.column("drop_count")
        quar = ledger.column("quarantine_count")
        events = [chaos.events(r, ds8.client_num) for r in range(4)]
        assert int(drop.sum()) == sum(e.dropped for e in events)
        assert int(part.sum()) == sum(
            int(e.participation.sum()) for e in events)
        assert int(quar.sum()) == sum(
            int((e.participation & e.nan_mask).sum()) for e in events)
        assert int(ledger.column("last_seen_round").max()) <= 3
    finally:
        ledger.close()


def test_buffered_straggler_staleness_lands_in_ledger(ds8, tmp_path):
    """The buffered drive's commit-time staleness blocks attribute rounds of
    lateness to the clients that straggled — the plan says who."""
    chaos = FaultPlan(seed=3, drop_rate=0.2, nan_rate=0.1,
                      straggler_rate=0.4, straggler_rounds=2)
    ledger = create_ledger(str(tmp_path / "led"), ds8.client_num)
    try:
        _drive(ds8, ledger, chaos=chaos, buffer_size=3)
        # the seeded plan must actually produce stragglers for this test to
        # mean anything; latencies() is pure so this is a stable property
        planned = sum(int(chaos.latencies(r, ds8.client_num).sum())
                      for r in range(4))
        assert planned > 0
        stale = ledger.column("staleness_sum")
        assert int(stale.sum()) > 0
        # staleness only ever accrues to clients that were dispatched
        assert not np.any((stale > 0)
                          & (ledger.column("participation_count") == 0))
    finally:
        ledger.close()


def _ledger_file_bytes(root: str) -> dict:
    return {fn: open(os.path.join(root, fn), "rb").read()
            for fn in sorted(os.listdir(root))}


def test_same_seed_chaos_runs_yield_byte_identical_shards(ds8, tmp_path):
    """Two same-seed buffered chaos runs write byte-identical ledger files
    and fold to the identical report — the flagged set cannot flap."""
    chaos = FaultPlan(seed=3, drop_rate=0.2, nan_rate=0.1,
                      straggler_rate=0.4, straggler_rounds=2)
    reports = []
    dirs = []
    for tag in ("a", "b"):
        root = str(tmp_path / f"led_{tag}")
        ledger = create_ledger(root, ds8.client_num)
        try:
            _drive(ds8, ledger, chaos=chaos, buffer_size=3)
            reports.append(client_report.fold_ledger(
                ledger, z_threshold=1.0, recidivist_min=1))
        finally:
            ledger.close()
        dirs.append(root)
    bytes_a, bytes_b = map(_ledger_file_bytes, dirs)
    assert sorted(bytes_a) == sorted(bytes_b)
    for fn in bytes_a:
        assert bytes_a[fn] == bytes_b[fn], f"{fn} differs across runs"
    # identical flagged sets (json round-trip = exact structural equality)
    assert json.dumps(reports[0], sort_keys=True) == \
        json.dumps(reports[1], sort_keys=True)


# ------------------------------------------------------- column unit semantics

def test_create_layout_shards_and_fills(tmp_path):
    root = str(tmp_path / "led")
    ledger = create_ledger(root, 10, clients_per_shard=4)
    assert ledger.shard_rows == [4, 4, 2]
    for shard, rows in enumerate(ledger.shard_rows):
        for column, dtype, _ in COLUMNS:
            path = os.path.join(root, f"ledger_{shard:05d}.{column}")
            assert os.path.getsize(path) == rows * np.dtype(dtype).itemsize
    # fills: -1 for "never seen", zero everywhere else
    assert np.all(ledger.column("last_seen_round") == -1)
    for column in ("participation_count", "drop_count", "quarantine_count",
                   "staleness_sum", "ema_update_norm", "ema_loss"):
        assert np.all(ledger.column(column) == 0)
    ledger.close()


def test_update_counters_and_ema_seeding(tmp_path):
    led = create_ledger(str(tmp_path / "led"), 10, clients_per_shard=4)
    # round 0: client 1 healthy, client 5 quarantined (NaN), client 9 dropped
    led.update(0, client_idx=[1, 5, 9],
               participated=[True, True, False],
               update_norm=[1.0, 2.0, 3.0],
               finite=[True, False, True],
               loss_sum=[2.0, 4.0, 6.0], total=[2.0, 2.0, 2.0])
    assert led.column("participation_count")[[1, 5, 9]].tolist() == [1, 1, 0]
    assert led.column("drop_count")[[1, 5, 9]].tolist() == [0, 0, 1]
    assert led.column("quarantine_count")[[1, 5, 9]].tolist() == [0, 1, 0]
    assert led.column("last_seen_round")[[1, 5, 9]].tolist() == [0, 0, -1]
    # EMA seeded from the first healthy observation only: the quarantined
    # and dropped clients' EMAs stay untouched at 0
    assert led.column("ema_update_norm")[[1, 5, 9]].tolist() == [1.0, 0.0, 0.0]
    assert led.column("ema_loss")[[1, 5, 9]].tolist() == [1.0, 0.0, 0.0]

    # round 3: both healthy. client 1 decays (seen before: 1 healthy obs);
    # client 5's only prior round was quarantined, so it SEEDS fresh now
    led.update(3, client_idx=[1, 5], participated=[True, True],
               update_norm=[3.0, 4.0], finite=[True, True],
               loss_sum=[4.0, 8.0], total=[2.0, 2.0])
    norm = led.column("ema_update_norm")
    loss = led.column("ema_loss")
    assert norm[1] == pytest.approx(0.9 * 1.0 + 0.1 * 3.0)
    assert norm[5] == pytest.approx(4.0)
    assert loss[1] == pytest.approx(0.9 * 1.0 + 0.1 * 2.0)
    assert loss[5] == pytest.approx(4.0)
    assert led.column("last_seen_round")[[1, 5]].tolist() == [3, 3]
    led.close()


def test_multi_shard_scatter_roundtrip(tmp_path):
    """One cohort spanning all shards: every row lands in the right shard
    and column() reassembles the global order."""
    led = create_ledger(str(tmp_path / "led"), 10, clients_per_shard=4)
    idx = [0, 3, 4, 7, 8, 9]  # shards 0, 0, 1, 1, 2, 2
    led.update(5, client_idx=idx,
               participated=[True] * 6,
               update_norm=[float(i) for i in idx],
               finite=[True] * 6,
               loss_sum=[0.0] * 6, total=[1.0] * 6)
    part = led.column("participation_count")
    assert part[idx].tolist() == [1] * 6
    assert int(part.sum()) == 6
    norm = led.column("ema_update_norm")
    assert norm[idx].tolist() == [float(i) for i in idx]
    led.add_staleness([3, 8], [2, 5])
    stale = led.column("staleness_sum")
    assert stale[[3, 8]].tolist() == [2, 5]
    assert int(stale.sum()) == 7
    with pytest.raises(IndexError):
        led.update(0, client_idx=[10], participated=[True],
                   update_norm=[0.0], finite=[True],
                   loss_sum=[0.0], total=[1.0])
    led.close()


def test_apply_trims_mesh_padding_and_rejects_unknown_blocks(tmp_path):
    led = create_ledger(str(tmp_path / "led"), 8)
    # stats vectors padded to 4 rows for a 2-row cohort (mesh padding):
    # apply() must drop the synthetic tail
    led.apply({"round": 2, "client_idx": np.array([6, 1]),
               "participated": np.array([True, True, False, False]),
               "stats": {"update_norm": np.array([1.0, 2.0, 99.0, 99.0]),
                         "finite": np.array([True, True, False, False]),
                         "loss_sum": np.array([2.0, 2.0, 9.0, 9.0]),
                         "total": np.array([2.0, 1.0, 1.0, 1.0])}})
    assert int(led.column("participation_count").sum()) == 2
    assert led.column("ema_update_norm")[[6, 1]].tolist() == [1.0, 2.0]
    led.apply({"round": 3, "client_idx": np.array([6]),
               "staleness": np.array([4, 9, 9])})  # padded staleness too
    assert int(led.column("staleness_sum").sum()) == 4
    with pytest.raises(ValueError, match="unknown ledger block"):
        led.apply({"round": 0, "client_idx": np.array([0])})
    led.close()


def test_open_or_create_resumes_and_rejects_mismatch(tmp_path):
    root = str(tmp_path / "led")
    led = open_or_create(root, 10, clients_per_shard=4)
    led.update(0, client_idx=[2], participated=[True], update_norm=[5.0],
               finite=[True], loss_sum=[1.0], total=[1.0])
    led.close()
    reopened = open_or_create(root, 10)
    assert reopened.shard_rows == [4, 4, 2]  # header wins over the default
    assert int(reopened.column("participation_count")[2]) == 1
    assert float(reopened.column("ema_update_norm")[2]) == 5.0
    reopened.close()
    with pytest.raises(ValueError, match="holds 10 clients"):
        open_or_create(root, 11)


# ------------------------------------------------------------- fleet report

def _report_ledger(tmp_path, n=20):
    """Hand-built ledger: client 3 a quarantine recidivist, client 7 an
    update-norm outlier, clients 15..19 never sampled."""
    led = create_ledger(str(tmp_path / "report_led"), n, clients_per_shard=8)
    for r in range(4):
        idx = np.arange(15)
        healthy = np.ones(15, bool)
        healthy[3] = r >= 3  # quarantined rounds 0-2, healthy round 3
        norm = np.full(15, 1.0)
        norm[7] = 50.0  # persistent outlier
        led.update(r, client_idx=idx, participated=np.ones(15, bool),
                   update_norm=norm, finite=healthy,
                   loss_sum=np.full(15, 2.0), total=np.full(15, 2.0))
    return led


def test_fold_ledger_flags_recidivists_and_outliers(tmp_path):
    led = _report_ledger(tmp_path)
    try:
        report = client_report.fold_ledger(led, z_threshold=3.0,
                                           recidivist_min=2)
    finally:
        led.close()
    assert report["num_clients"] == 20
    assert report["participating"] == 15
    assert report["coverage"] == pytest.approx(0.75)
    assert report["rounds_seen"] == 4
    assert report["quarantine_total"] == 3
    assert report["drop_total"] == 0
    assert report["recidivists"] == [{"client": 3, "quarantine_count": 3}]
    assert [o["client"] for o in report["outliers"]] == [7]
    flagged = {(f["client"], f["reason"]) for f in report["flagged"]}
    assert flagged == {(3, "quarantine_recidivist"), (7, "update_norm_outlier")}
    assert report["flagged_fraction"] == pytest.approx(2 / 15, abs=1e-6)
    # sync drives: zero staleness means everything in the first bin
    assert report["staleness_hist"]["counts"][0] == 15
    assert sum(report["staleness_hist"]["counts"]) == 15


def test_coverage_counts_sampled_not_just_alive(tmp_path):
    """A client the chaos plan dropped every round was still SAMPLED — only
    clients the cohort draw never touched count against coverage."""
    led = create_ledger(str(tmp_path / "cov_led"), 4)
    led.update(0, client_idx=[0, 1], participated=[True, False],
               update_norm=[1.0, 0.0], finite=[True, True],
               loss_sum=[1.0, 0.0], total=[1.0, 1.0])
    try:
        report = client_report.fold_ledger(led)
    finally:
        led.close()
    assert report["participating"] == 1   # client 0 only
    assert report["sampled"] == 2         # the dropped client 1 counts
    assert report["coverage"] == pytest.approx(0.5)


def test_report_gate_pass_and_trip(tmp_path, capsys):
    led = _report_ledger(tmp_path)
    led.close()
    root = str(tmp_path / "report_led")
    out = str(tmp_path / "report.json")
    # lenient thresholds: gate passes, artifact written
    rc = client_report.main([root, "--gate", "--coverage_floor", "0.5",
                             "--flagged_ceiling", "0.5", "--out", out])
    assert rc == 0
    assert "client-health gate: PASS" in capsys.readouterr().out
    with open(out) as f:
        assert json.load(f)["participating"] == 15
    # a zero flagged ceiling must trip on the recidivist + outlier
    rc = client_report.main([root, "--gate", "--flagged_ceiling", "0"])
    assert rc == 1
    assert "client-health gate: FAIL" in capsys.readouterr().out
    # an unreachable coverage floor trips too
    rc = client_report.main([root, "--gate", "--coverage_floor", "0.9"])
    assert rc == 1
