"""HLO-layer lint: parser, every new rule on its deliberately-bad fixture,
the repo's parallel programs clean, and the COMMS_BUDGET.json gate.

The jax fixtures lower tiny shard_map programs on the 8-virtual-device
mesh from conftest.py with ``compile=False`` — pre-optimization collective
counts/bytes are independent of backend optimization flags, so these
assertions hold under the fast suite's ``--xla_backend_optimization_level=0``
as well as the CI smoke environment. Peak-memory (compile-dependent)
checks live only in the slow full run and the CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.analysis.hlo_engine import (
    analyze_program,
    check_collective_in_loop,
    collective_inventory,
    parse_hlo_text,
    shape_bytes,
)
from fedml_tpu.utils.jax_compat import shard_map

N = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("i",))


def _sharded1d(body, n_in=1):
    mesh = _mesh()
    specs = tuple(P("i") for _ in range(n_in))
    return jax.jit(shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=P("i")))


_S = jax.ShapeDtypeStruct((N, 16), jnp.float32)


# --------------------------------------------------------------------- parser

def test_shape_bytes():
    assert shape_bytes("f32[8,16]") == 8 * 16 * 4
    assert shape_bytes("bf16[4]{0}") == 8
    assert shape_bytes("pred[]") == 1
    # tuple shapes sum their leaves
    assert shape_bytes("(s32[], f32[2,2], u8[3])") == 4 + 16 + 3


_SYNTH = """\
HloModule synth, entry_computation_layout={(f32[8])->f32[]}

adder {
  a = f32[] parameter(0)
  b = f32[] parameter(1)
  ROOT r = f32[] add(a, b)
}

body {
  p = (s32[], f32[], f32[8]) parameter(0)
  i = s32[] get-tuple-element(p), index=0
  one = s32[] constant(1)
  inext = s32[] add(i, one)
  acc = f32[] get-tuple-element(p), index=1
  w = f32[8] get-tuple-element(p), index=2
  zero = f32[] constant(0)
  s = f32[] reduce(w, zero), dimensions={0}, to_apply=adder
  ar = f32[] all-reduce(s), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=adder
  accn = f32[] add(acc, ar)
  ROOT t = (s32[], f32[], f32[8]) tuple(inext, accn, w)
}

cond {
  p2 = (s32[], f32[], f32[8]) parameter(0)
  i2 = s32[] get-tuple-element(p2), index=0
  n = s32[] constant(4)
  ROOT lt = pred[] compare(i2, n), direction=LT
}

ENTRY main {
  arg = f32[8] parameter(0)
  c0 = s32[] constant(0)
  f0 = f32[] constant(0)
  init = (s32[], f32[], f32[8]) tuple(c0, f0, arg)
  loop = (s32[], f32[], f32[8]) while(init), condition=cond, body=body
  ROOT out = f32[] get-tuple-element(loop), index=1
}
"""


def test_parse_hlo_module_structure():
    m = parse_hlo_text(_SYNTH)
    assert set(m.computations) == {"adder", "body", "cond", "main"}
    assert m.entry == "main"
    body = m.computations["body"]
    assert body.root == "t"
    ar = body.instructions["ar"]
    assert ar.opcode == "all-reduce" and ar.operands == ["s"]
    assert ar.bytes == 4
    # tuple shape + operand list with nested brackets both survive
    t = body.instructions["t"]
    assert t.opcode == "tuple" and t.operands == ["inext", "accn", "w"]
    assert t.is_root


def test_collective_inventory_synthetic():
    inv = collective_inventory(parse_hlo_text(_SYNTH))
    assert len(inv) == 1
    (c,) = inv
    assert c["op"] == "all-reduce" and c["computation"] == "body"
    assert c["bytes"] == 4 and c["channel_id"] == 1
    assert c["replica_groups"] == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_collective_in_loop_fires_on_synthetic_while():
    # `w` is a pass-through carry element, so `ar` recomputes the same
    # reduction every iteration — the finding, found without any jax
    findings = check_collective_in_loop(parse_hlo_text(_SYNTH), "synth")
    assert [f.rule for f in findings] == ["collective-in-loop"]
    assert "ar" in findings[0].message and "body" in findings[0].message


def test_collective_in_loop_clean_when_carry_varies():
    # same module but the loop rotates `w` through the collective's result:
    # not pass-through, so nothing is invariant
    varied = _SYNTH.replace(
        "ROOT t = (s32[], f32[], f32[8]) tuple(inext, accn, w)",
        "wb = f32[8] broadcast(ar), dimensions={}\n"
        "  ROOT t = (s32[], f32[], f32[8]) tuple(inext, accn, wb)")
    assert not check_collective_in_loop(parse_hlo_text(varied), "synth")


# -------------------------------------------------- rules on lowered fixtures

def test_collective_in_loop_fires_on_shard_map_scan():
    def body(x, w):
        def step(c, _):
            tot = jax.lax.psum(jnp.sum(w), "i")  # loop-invariant psum
            return c + jnp.sum(x) / tot, None
        c, _ = jax.lax.scan(step, jnp.sum(x) * 0.0, None, length=4)
        return x * 0 + c

    fn = _sharded1d(body, n_in=2)
    _, findings = analyze_program(fn, (_S, _S), "fix", num_devices=N,
                                  compile=False)
    assert [f.rule for f in findings] == ["collective-in-loop"]


def test_collective_in_loop_clean_when_hoisted():
    def body(x, w):
        tot = jax.lax.psum(jnp.sum(w), "i")  # hoisted: once per call

        def step(c, _):
            return c + jnp.sum(x) / tot, None
        c, _ = jax.lax.scan(step, jnp.sum(x) * 0.0, None, length=4)
        return x * 0 + c

    fn = _sharded1d(body, n_in=2)
    _, findings = analyze_program(fn, (_S, _S), "fix", num_devices=N,
                                  compile=False)
    assert not findings


def test_accidental_replication_fires_on_param_gather():
    def body(x):
        full = jax.lax.all_gather(x, "i")  # rematerializes the full array
        return x + jnp.sum(full, axis=0)

    fn = _sharded1d(body)
    _, findings = analyze_program(
        fn, (_S,), "fix", num_devices=N,
        params_bytes=N * 16 * 4, compile=False)
    assert [f.rule for f in findings] == ["accidental-replication"]
    assert "all-gather" in findings[0].message


def test_ppermute_coverage_fires_on_truncated_ring():
    def body(x):
        perm = [(i, i + 1) for i in range(N - 1)]  # missing the wraparound
        return jax.lax.ppermute(x, "i", perm)

    fn = _sharded1d(body)
    _, findings = analyze_program(fn, (_S,), "fix", num_devices=N,
                                  compile=False)
    assert [f.rule for f in findings] == ["ppermute-coverage"]
    assert "ZEROS" in findings[0].message


def test_ppermute_coverage_clean_on_full_ring():
    def body(x):
        perm = [(i, (i + 1) % N) for i in range(N)]
        return jax.lax.ppermute(x, "i", perm)

    fn = _sharded1d(body)
    _, findings = analyze_program(fn, (_S,), "fix", num_devices=N,
                                  compile=False)
    assert not findings


def test_unweighted_psum_mean_fires():
    def body(x):
        return x * 0 + jax.lax.psum(jnp.sum(x), "i") / N

    fn = _sharded1d(body)
    _, findings = analyze_program(fn, (_S,), "fix", num_devices=N,
                                  compile=False)
    assert [f.rule for f in findings] == ["unweighted-psum-mean"]


def test_unweighted_psum_mean_clean_on_weighted_mean():
    # weighted mean: the denominator is itself a psum, not the axis size
    def body(x, w):
        num = jax.lax.psum(jnp.sum(x * w), "i")
        den = jax.lax.psum(jnp.sum(w), "i")
        return x * 0 + num / den

    fn = _sharded1d(body, n_in=2)
    _, findings = analyze_program(fn, (_S, _S), "fix", num_devices=N,
                                  compile=False)
    assert not findings


def test_axis_name_mismatch_reported_as_finding():
    def body(x):
        return x * 0 + jax.lax.psum(jnp.sum(x), "dz")  # unbound axis

    fn = _sharded1d(body)
    comms, findings = analyze_program(fn, (_S,), "fix", num_devices=N,
                                      compile=False)
    assert comms is None
    assert [f.rule for f in findings] == ["axis-name-mismatch"]
    assert "dz" in findings[0].message


# ------------------------------------------------------- real round programs

def test_gossip_inventory_counts_and_bytes():
    from fedml_tpu.analysis.comms import PROGRAMS

    builder, ndev = PROGRAMS["gossip.mix[ring8]"]
    fn, args, _ = builder()
    comms, findings = analyze_program(fn, args, "gossip", num_devices=ndev,
                                      compile=False)
    assert not findings
    # ring W has 3 nonzero shifts (0, +1, -1); the identity shift moves no
    # bytes, so each of the 2 pytree leaves pays exactly 2 ppermutes
    assert comms.per_op == {"collective-permute": 4}
    # per-device shard bytes: (1,16,4) f32 = 256 and (1,4) f32 = 16
    assert comms.collective_bytes == 2 * (256 + 16)


def test_psum_aggregation_halves_all_gather_bytes():
    # the claim in fedml_tpu/parallel/sharded.py: psum-aggregation moves at
    # most HALF the collective bytes of all-gathering the client stacks
    from fedml_tpu.analysis.comms import PROGRAMS

    builder, ndev = PROGRAMS["sharded.round[lr,f32,fedavg]"]
    fn, args, params_bytes = builder()
    comms, findings = analyze_program(
        fn, args, "sharded", num_devices=ndev,
        params_bytes=params_bytes, compile=False)
    assert not findings
    assert comms.per_op.get("all-reduce", 0) > 0
    # an all_gather of per-device partial trees lands ndev * params_bytes
    # on every device; the psum path must stay under half of that
    gather_bytes = ndev * params_bytes
    assert comms.collective_bytes <= gather_bytes / 2, (
        f"psum path moves {comms.collective_bytes}B vs all_gather "
        f"{gather_bytes}B — the sharded.py comment is now a lie")


@pytest.mark.slow  # ~22s full-surface lowering; ci_smoke's --comms step
# lowers the same programs AND gates the budgets on every push
def test_all_parallel_programs_lower_clean():
    # every shard_map round lowers on the virtual mesh with zero HLO-rule
    # findings (budget gate excluded — that needs compiled memory numbers)
    from fedml_tpu.analysis.comms import EXTRA_PROGRAMS, PROGRAMS

    for name, (builder, ndev) in PROGRAMS.items():
        if name in EXTRA_PROGRAMS:
            continue
        # builders optionally append federated-tree bytes (the param_bytes
        # pin) — same [:3] slice run_comms takes
        fn, args, params_bytes = builder()[:3]
        comms, findings = analyze_program(
            fn, args, name, num_devices=ndev,
            params_bytes=params_bytes, compile=False,
            expect_resharding=name.startswith("tensor.step"))
        assert comms is not None and not findings, (
            name + ":\n" + "\n".join(str(f) for f in findings))
        if name.startswith("tensor.step"):
            # the client-step programs are pure compute by contract — all
            # cross-client traffic lives in the round program around them
            assert comms.collective_count == 0, (
                f"{name}: the step program grew collectives "
                f"({comms.per_op}) — cross-client traffic belongs to the "
                f"round program")
        else:
            assert comms.collective_count > 0, (
                f"{name}: a parallel round with no collectives means the "
                f"program is not actually sharded")


# ---------------------------------------------------------------- budget gate

def test_budget_gate_trips_on_tightened_entry():
    from fedml_tpu.analysis.comms import PROGRAMS, check_budgets

    builder, ndev = PROGRAMS["gossip.mix[ring8]"]
    fn, args, _ = builder()
    comms, _ = analyze_program(fn, args, "gossip.mix[ring8]",
                               num_devices=ndev, compile=False)
    programs = {"gossip.mix[ring8]": comms}

    # exact budget: clean
    ok_budget = {"gossip.mix[ring8]": {
        "collective_count": comms.collective_count,
        "collective_bytes": comms.collective_bytes}}
    assert not check_budgets(programs, ok_budget)

    # tighten collective_count by one: the gate trips with a readable diff
    tight = {"gossip.mix[ring8]": {
        "collective_count": comms.collective_count - 1,
        "collective_bytes": comms.collective_bytes}}
    findings = check_budgets(programs, tight)
    assert [f.rule for f in findings] == ["comms-budget"]
    msg = findings[0].message
    assert "collective_count" in msg
    assert str(comms.collective_count) in msg            # measured
    assert str(comms.collective_count - 1) in msg        # ceiling
    assert "+1" in msg                                   # overshoot


def test_budget_missing_entry_is_a_finding():
    from fedml_tpu.analysis.comms import check_budgets
    from fedml_tpu.analysis.hlo_engine import ProgramComms

    pc = ProgramComms(target="new.round", collective_count=1,
                      collective_bytes=4, per_op={"all-reduce": 1},
                      per_op_bytes={"all-reduce": 4}, collectives=[])
    findings = check_budgets({"new.round": pc}, {})
    assert [f.rule for f in findings] == ["comms-budget"]
    assert "--update-budgets" in findings[0].message


def test_budget_file_covers_every_program():
    import os

    from fedml_tpu.analysis.comms import PROGRAMS, load_budgets

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    budgets = load_budgets(root)
    missing = sorted(set(PROGRAMS) - set(budgets))
    assert not missing, (
        f"programs without a COMMS_BUDGET.json entry: {missing} — run "
        f"`python -m fedml_tpu.analysis --comms --update-budgets`")
    for name, entry in budgets.items():
        assert {"collective_count", "collective_bytes"} <= set(entry), name


@pytest.mark.slow
def test_comms_full_repo_clean(tmp_path):
    # the whole CLI path: lower + compile all 10 programs, memory analysis,
    # budget gate against the checked-in COMMS_BUDGET.json (valid under
    # --runslow where conftest leaves XLA optimization at its default, the
    # same environment the budgets were measured in)
    import os

    from fedml_tpu.analysis.comms import run_comms

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report, comms = run_comms(root)
    assert report.ok, "\n" + report.summary()
    assert len(comms["programs"]) == 10
    for pc in comms["programs"].values():
        assert pc["peak_bytes"] is not None
